"""The Tendermint BFT consensus state machine.

Reference parity: internal/consensus/state.go (2400 LoC). One
receive-routine thread owns all round state (state.go:757 receiveRoutine);
peer messages, internal messages and timeouts arrive on a queue; every
message is WAL-logged before processing; the node's own votes are
WAL-synced before broadcast (the double-sign-safety invariant).

Step flow (types/round_state.go:20-28):
  NewHeight → NewRound → Propose → Prevote → PrevoteWait → Precommit →
  PrecommitWait → Commit → (NewHeight...)

The message handlers mirror state.go's enterX functions with their exact
guard conditions; vote accumulation uses types.VoteSet (per-vote verify)
and the finalize path applies blocks through state.BlockExecutor, whose
LastCommit verification runs on the device batch engine.
"""

from __future__ import annotations

import os
import queue
import threading
import time as _time
from collections import deque
from dataclasses import dataclass
from typing import Callable, Deque, Dict, List, Optional

from ..libs.service import BaseService
from ..observability import trace as _trace
from ..types import (
    BlockID,
    Commit,
    Timestamp,
    ValidatorSet,
    Vote,
    VoteSet,
)
from ..types.block import Block
from ..types.part_set import Part, PartSet
from ..types.proposal import Proposal
from ..types.vote import PRECOMMIT_TYPE, PREVOTE_TYPE
from ..types.vote_set import ErrVoteConflictingVotes, ErrVoteNonDeterministicSignature
from ..state import State
from ..state.execution import BlockExecutor
from .ticker import TimeoutInfo, TimeoutTicker
from .types import (
    STEP_COMMIT,
    STEP_NEW_HEIGHT,
    STEP_NEW_ROUND,
    STEP_PRECOMMIT,
    STEP_PRECOMMIT_WAIT,
    STEP_PREVOTE,
    STEP_PREVOTE_WAIT,
    STEP_PROPOSE,
    HeightVoteSet,
    RoundState,
)
from .wal import WAL, WALMessage


def _ts_from_float(t: float) -> Timestamp:
    sec = int(t)
    return Timestamp(seconds=sec, nanos=int((t - sec) * 1e9))


def _ts_le(a: Timestamp, b: Timestamp) -> bool:
    return (a.seconds, a.nanos) <= (b.seconds, b.nanos)


@dataclass
class ProposalMessage:
    proposal: Proposal


@dataclass
class BlockPartMessage:
    height: int
    round: int
    part: Part


@dataclass
class VoteMessage:
    vote: Vote
    # flow correlation id (ISSUE 10): captured from the tracer's inbound-
    # flow register at enqueue time so the causal chain survives the
    # receive-queue hop (the vote is verified on a later event/thread
    # than the delivery that carried it)
    flow: Optional[int] = None


@dataclass
class VoteVerdictMessage:
    """A vote ingress verdict re-entering the pump (ISSUE 15). The
    original VoteMessage was WAL-logged before dispatch; verdicts are
    NOT (``_wal_write_msg`` skips unknown kinds), so a replayed WAL
    re-verifies the vote through the sequential path instead of trusting
    a stale device verdict. ``valid`` is None iff ``error`` is set — the
    poisoned-window shape, re-driven through the per-vote fallback."""

    pend: object  # vote_ingress.PendingVote
    valid: Optional[bool] = None
    error: Optional[BaseException] = None


@dataclass
class HeightTimeline:
    """Per-height consensus latency attribution (ISSUE 10): the timestamps
    of every phase transition one height passes through, read off the
    state machine's own clock (`self._now` — the simnet virtual clock when
    injected, so simulated timelines are deterministic). The per-phase
    breakdown is the 2302.00418 instrument: where a height's latency
    actually went — waiting for the proposal, gathering 2/3 prevotes,
    gathering 2/3 precommits, fetching/committing the block, or verifying
    and applying it."""

    height: int
    t_new_height: float
    t_proposal: Optional[float] = None       # valid proposal accepted
    t_prevote_23: Optional[float] = None     # 2/3 prevotes observed
    t_precommit_23: Optional[float] = None   # 2/3 precommits observed
    t_commit: Optional[float] = None         # entered STEP_COMMIT
    t_verify_dispatch: Optional[float] = None  # block validate/verify begins
    t_applied: Optional[float] = None        # ABCI apply finished
    rounds: int = 0                          # rounds consumed (>= 1)

    # (phase, start attr, end attr) — consecutive transition deltas
    _PHASES = (
        ("propose", "t_new_height", "t_proposal"),
        ("prevote", "t_proposal", "t_prevote_23"),
        ("precommit", "t_prevote_23", "t_precommit_23"),
        ("commit", "t_precommit_23", "t_commit"),
        ("apply", "t_verify_dispatch", "t_applied"),
    )

    def phases(self) -> Dict[str, float]:
        """Phase durations in seconds, only for transitions that happened
        (a height entered via WAL replay or catch-up can skip phases)."""
        out: Dict[str, float] = {}
        for name, a, b in self._PHASES:
            ta, tb = getattr(self, a), getattr(self, b)
            if ta is not None and tb is not None and tb >= ta:
                out[name] = tb - ta
        return out

    def to_dict(self) -> dict:
        d = {
            "height": self.height,
            "rounds": self.rounds,
            "t_new_height": self.t_new_height,
            "t_proposal": self.t_proposal,
            "t_prevote_23": self.t_prevote_23,
            "t_precommit_23": self.t_precommit_23,
            "t_commit": self.t_commit,
            "t_verify_dispatch": self.t_verify_dispatch,
            "t_applied": self.t_applied,
            "phases": self.phases(),
        }
        if self.t_applied is not None:
            d["total_s"] = self.t_applied - self.t_new_height
        return d


class ConsensusState(BaseService):
    """state.go:81-200 State."""

    def __init__(
        self,
        config,  # ConsensusConfig
        state: State,
        block_exec: BlockExecutor,
        block_store,
        mempool=None,
        evpool=None,
        event_bus=None,
        wal: Optional[WAL] = None,
        priv_validator=None,
        metrics=None,  # libs.metrics.ConsensusMetrics (None = no-op)
        clock=None,  # injectable time source (simnet); None = wall clock
        tracer=None,  # per-node SpanTracer (simnet); None = global TRACER
    ):
        super().__init__("ConsensusState")
        self._cfg = config
        # Flight recorder (ISSUE 10): spans/flows go to the injected
        # per-node tracer under simnet (virtual-clock timebase, one pid
        # per node in the merged trace) and to the process tracer on a
        # real node.
        self._tracer = tracer if tracer is not None else _trace.TRACER
        # last-K completed HeightTimeline records (RPC /height_timeline,
        # SimReport ring, flight-recorder dumps)
        ring = int(os.environ.get("TM_TPU_TIMELINE_RING", "32") or 32)
        self.height_timelines: Deque[HeightTimeline] = deque(
            maxlen=max(ring, 1)
        )
        self._timeline: Optional[HeightTimeline] = None
        # All reads of "now" inside the state machine (round start times,
        # commit times, vote timestamps) go through self._now so a virtual
        # clock can drive the whole machine deterministically.
        self._clock = clock
        self._now: Callable[[], float] = clock.time if clock is not None else _time.time
        self._block_exec = block_exec
        self._block_store = block_store
        self._mempool = mempool
        self._evpool = evpool
        self._event_bus = event_bus
        self._wal = wal
        self._metrics = metrics
        self._priv_validator = priv_validator
        self._priv_validator_pub_key = (
            priv_validator.get_pub_key() if priv_validator else None
        )

        self.rs = RoundState()
        self._state = state  # committed chain state

        self._queue: "queue.Queue" = queue.Queue(maxsize=1000)
        self._internal_queue: "queue.Queue" = queue.Queue(maxsize=1000)
        # Wakes the receive routine when either queue gains a message —
        # a blocking wait instead of a poll (same pattern as the ops
        # pipeline worker). on_enqueue is the external-driver (simnet)
        # hook: called after every enqueue so a scheduler can pump
        # process_pending() instead of running the thread.
        self._msg_ready = threading.Event()
        self.on_enqueue: Optional[Callable[[], None]] = None
        # Committed-height watchers block here rather than sleep-polling
        # (kills ~50 wakeups/s/node that wait_for_height used to cost).
        self._commit_cond = threading.Condition()
        self._ticker = TimeoutTicker(self._tock, clock=clock)
        self._thread: Optional[threading.Thread] = None
        self._done_first_block = threading.Event()
        self._height_events: List[Callable] = []  # hooks per committed height

        # byzantine-test overrides (common_test.go decideProposal/doPrevote)
        self.decide_proposal_override: Optional[Callable] = None
        self.do_prevote_override: Optional[Callable] = None

        # Broadcast seam: the consensus reactor registers here to gossip the
        # node's own proposals/parts/votes (reactor.go's peer routines read
        # these off the internal message flow).
        self.broadcast_hooks: List[Callable] = []
        # Called with every vote successfully added to the height vote sets
        # (any source) — the reactor broadcasts HasVote off this
        # (reactor.go:1031 broadcastHasVoteMessage).
        self.vote_added_hooks: List[Callable] = []

        # Live-vote ingress (ISSUE 15): attach_vote_ingress() wires the
        # device-batched verify lane; None = every vote rides the
        # sequential host path, byte-identically to pre-ISSUE-15.
        self._vote_ingress = None

        self._update_to_state(state)

    # ------------------------------------------------------------------
    # lifecycle

    def on_start(self) -> None:
        self._start_common()
        self._thread = threading.Thread(target=self._receive_routine, daemon=True)
        self._thread.start()
        # start the height's round 0 after commit-timeout from start_time
        self._schedule_round_0()

    def start_stepped(self) -> None:
        """on_start without the receive thread: WAL replay + round-0
        scheduling only. For an external event-driven driver (the simnet
        scheduler) that pumps process_pending() off the on_enqueue hook —
        the whole state machine then runs single-threaded and
        deterministically."""
        self._start_common()
        self._schedule_round_0()

    def _start_common(self) -> None:
        self._reconstruct_last_commit()
        if self._wal is not None:
            self._wal.start()
            self._replay_wal()

    def on_stop(self) -> None:
        self._ticker.stop()
        self._close_vote_ingress()
        self._queue.put(("quit", None))
        self._msg_ready.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
        if self._wal is not None:
            self._wal.stop()

    def stop_stepped(self) -> None:
        """Tear down a start_stepped() node (ticker + WAL; no thread)."""
        self._quit.set()
        self._ticker.stop()
        self._close_vote_ingress()
        if self._wal is not None:
            self._wal.stop()

    def _close_vote_ingress(self) -> None:
        ing = self._vote_ingress
        if ing is not None:
            self._vote_ingress = None
            try:
                ing.close(timeout=2.0)
            except Exception:  # noqa: BLE001 — teardown must not raise
                pass

    # ------------------------------------------------------------------
    # external inputs

    def _wake(self) -> None:
        self._msg_ready.set()
        hook = self.on_enqueue
        if hook is not None:
            try:
                hook()
            except Exception:  # noqa: BLE001 — a driver bug must not break enqueue
                pass

    def set_proposal(self, proposal: Proposal, peer_id: str = "") -> None:
        self._queue.put((ProposalMessage(proposal), peer_id))
        self._wake()

    def add_block_part(self, height: int, round_: int, part: Part, peer_id: str = "") -> None:
        self._queue.put((BlockPartMessage(height, round_, part), peer_id))
        self._wake()

    def add_vote_msg(self, vote: Vote, peer_id: str = "") -> None:
        msg = VoteMessage(vote)
        tr = self._tracer
        if tr.enabled and tr.flow is not None:
            msg.flow = tr.flow  # the delivery's flow rides with the vote
        self._queue.put((msg, peer_id))
        self._wake()

    # ------------------------------------------------------------------
    # live-vote ingress (ISSUE 15)

    def attach_vote_ingress(self, verifier=None, stepped: bool = False,
                            max_batch=None, window_ms=None, metrics=None):
        """Wire the device-batched vote-verify lane: peer votes for the
        current height run HeightVoteSet.check_vote on the pump, then
        window through consensus/vote_ingress.py; verdicts re-enter the
        queue and apply in submission order. Attach AFTER start — WAL
        replay must ride the sequential path."""
        from . import vote_ingress as _vi

        ing = _vi.VoteIngress(
            self._on_vote_verdicts, verifier=verifier, stepped=stepped,
            max_batch=max_batch, window_ms=window_ms, metrics=metrics,
        )
        self._vote_ingress = ing
        return ing

    @property
    def vote_ingress(self):
        return self._vote_ingress

    def _on_vote_verdicts(self, batch, verdicts, error) -> None:
        """VoteIngress apply callback — may run on the pipeline resolver
        thread, so it ONLY enqueues (the deadlock rule from
        mempool/ingress.py). A full queue drops the verdict instead of
        blocking the resolver: re-gossip re-delivers the vote, so a drop
        costs latency, never correctness."""
        ing = self._vote_ingress
        for i, pend in enumerate(batch):
            msg = VoteVerdictMessage(
                pend,
                None if error is not None else bool(verdicts[i]),
                error,
            )
            try:
                self._queue.put_nowait((msg, pend.peer_id))
            except queue.Full:
                if ing is not None:
                    ing.apply_drops += 1
        self._wake()

    def _ingress_submit(self, vote: Vote, peer_id: str,
                        flow: Optional[int]) -> bool:
        """Host stage of the batched vote path. Returns True when the
        vote was consumed (queued for device verify, answered from the
        memo, or rejected by a host-stage check with the same outcome
        the sequential path produces); False routes it to the sequential
        path (wrong height shape, non-ed25519 key)."""
        rs = self.rs
        if vote.height != rs.height:
            return False  # catchup / future-height shapes stay sync
        ing = self._vote_ingress
        tr = self._tracer
        fid = None
        if tr.enabled:
            fid = flow if flow is not None else tr.flow
            span = tr.span(
                "consensus.verify_dispatch", flow=fid,
                flow_phase="t" if fid is not None else None,
                height=vote.height, round=vote.round, type=vote.type,
            )
        else:
            span = None
        try:
            if span is not None:
                with span:
                    chk = rs.votes.check_vote(vote, peer_id)
            else:
                chk = rs.votes.check_vote(vote, peer_id)
        except ErrVoteNonDeterministicSignature:
            return True  # sequential outcome: swallowed, returns False
        except ErrVoteConflictingVotes as e:
            self._record_conflicting_votes(vote, e)
            return True
        if chk is None:
            return True  # exact duplicate / invalid type: a no-op add
        pub = chk.pub_key
        if pub.type() != "ed25519":
            return False  # host lane for exotic keys
        from . import vote_ingress as _vi

        pend = _vi.PendingVote(
            vote, peer_id, pub.bytes(),
            vote.sign_bytes(self._state.chain_id),
            flow=fid, t_enq=_time.perf_counter(),
        )
        ing.submit(pend, rs.validators)
        return True

    def _apply_vote_verdict_msg(self, msg: VoteVerdictMessage,
                                peer_id: str) -> None:
        pend = msg.pend
        vote = pend.vote
        if msg.error is not None:
            # poisoned window (DispatchError): exactly these votes
            # re-drive through the full sequential per-vote path
            self._try_add_vote(vote, peer_id, flow=pend.flow)
            return
        tr = self._tracer
        if tr.enabled:
            fid = pend.flow
            with tr.span("consensus.verify_apply", flow=fid,
                         flow_phase="f" if fid is not None else None,
                         height=vote.height, round=vote.round,
                         type=vote.type, valid=bool(msg.valid)):
                self._try_add_vote_impl(vote, peer_id, verdict=msg.valid)
        else:
            self._try_add_vote_impl(vote, peer_id, verdict=msg.valid)

    def _send_internal(self, msg) -> None:
        self._internal_queue.put((msg, ""))
        self._wake()
        for hook in self.broadcast_hooks:
            try:
                hook(msg)
            except Exception:  # noqa: BLE001 — gossip must not break consensus
                pass

    def wait_for_height(self, height: int, timeout: float = 30.0) -> None:
        """Block until the committed chain reaches `height` — on a
        condition signalled per commit, not a sleep-poll."""
        # injected-clock reads (not _time.time()): under simnet the
        # deadline must advance with VIRTUAL time or a replay would hang
        # on machine speed (tmlint simnet-determinism). Condition.wait's
        # timeout is REAL time though, so a monotonic deadline backstops
        # the loop — a wedged virtual clock (remaining frozen at
        # `timeout` forever) must still surface as TimeoutError instead
        # of re-waiting indefinitely.
        deadline = self._now() + timeout
        real_deadline = _time.monotonic() + timeout
        with self._commit_cond:
            while self._state.last_block_height < height:
                remaining = deadline - self._now()
                real_remaining = real_deadline - _time.monotonic()
                if remaining <= 0 or real_remaining <= 0:
                    raise TimeoutError(
                        f"height {height} not reached; at {self._state.last_block_height}"
                    )
                self._commit_cond.wait(min(remaining, real_remaining))

    @property
    def committed_state(self) -> State:
        return self._state

    # ------------------------------------------------------------------
    # the receive routine (state.go:757-850)

    def _pop_msg(self):
        """Next queued (msg, peer_id), internal queue first (own
        proposal/votes take priority, state.go:772), or None."""
        try:
            return self._internal_queue.get_nowait()
        except queue.Empty:
            pass
        try:
            return self._queue.get_nowait()
        except queue.Empty:
            return None

    def _dispatch(self, msg, peer_id: str) -> None:
        """WAL-log then handle one message — shared by the receive thread
        and the stepped (simnet) driver."""
        if isinstance(msg, TimeoutInfo):
            self._wal_write(WALMessage(timeout=(
                int(msg.duration * 1000), msg.height, msg.round, msg.step)))
            self._handle_timeout(msg)
        else:
            self._wal_write_msg(msg, peer_id)
            try:
                self._handle_msg(msg, peer_id)
            except Exception:  # noqa: BLE001 — a bad peer message must not kill consensus
                import traceback

                traceback.print_exc()

    def process_pending(self, max_msgs: Optional[int] = None) -> int:
        """Drain queued messages synchronously; returns how many were
        processed. The stepped-mode pump: an external scheduler calls this
        off the on_enqueue hook instead of running _receive_routine."""
        n = 0
        while max_msgs is None or n < max_msgs:
            if self._quit.is_set():
                break
            item = self._pop_msg()
            if item is None:
                # Stepped-mode vote-ingress flush point (ISSUE 15): the
                # queue draining IS the deterministic window boundary —
                # flush_pending() host-verifies every open window in
                # submission order and enqueues the verdicts, which the
                # next loop iterations apply before anything else can
                # arrive. Replay-exact: flush timing is a pure function
                # of message arrival order.
                ing = self._vote_ingress
                if (ing is not None and ing.stepped
                        and ing.flush_pending()):
                    continue
                break
            msg, peer_id = item
            if msg == "quit":
                break
            self._dispatch(msg, peer_id)
            n += 1
        return n

    def _receive_routine(self) -> None:
        while not self._quit.is_set():
            item = self._pop_msg()
            if item is None:
                # blocking wait, woken by _wake() on any enqueue; the
                # timeout only bounds the _quit re-check
                if not self._msg_ready.wait(timeout=0.2):
                    continue
                self._msg_ready.clear()
                continue
            msg, peer_id = item
            if msg == "quit":
                return
            self._dispatch(msg, peer_id)

    def _wal_write(self, rec: WALMessage) -> None:
        if self._wal is not None:
            self._wal.write(rec)

    def _wal_write_msg(self, msg, peer_id: str) -> None:
        if self._wal is None:
            return
        if isinstance(msg, ProposalMessage):
            rec = WALMessage(msg_kind="proposal", msg_payload=msg.proposal.encode(), peer_id=peer_id)
        elif isinstance(msg, BlockPartMessage):
            from ..wire.proto import ProtoWriter

            w = ProtoWriter()
            w.write_varint(1, msg.height)
            w.write_varint(2, msg.round)
            w.write_message(3, msg.part.encode(), always=True)
            rec = WALMessage(msg_kind="block_part", msg_payload=w.bytes(), peer_id=peer_id)
        elif isinstance(msg, VoteMessage):
            rec = WALMessage(msg_kind="vote", msg_payload=msg.vote.encode(), peer_id=peer_id)
        else:
            return
        if peer_id == "":
            self._wal.write_sync(rec)  # own messages are synced (state.go:780)
        else:
            self._wal.write(rec)

    def _handle_msg(self, msg, peer_id: str) -> None:
        """state.go:849-920."""
        if isinstance(msg, ProposalMessage):
            self._set_proposal(msg.proposal)
        elif isinstance(msg, BlockPartMessage):
            added = self._add_proposal_block_part(msg, peer_id)
            if added and self.rs.proposal_block_parts is not None and \
                    self.rs.proposal_block_parts.is_complete():
                pass  # handled inside _add_proposal_block_part
        elif isinstance(msg, VoteMessage):
            if (
                self._vote_ingress is not None
                and peer_id != ""  # own votes stay sync (WAL-synced)
                and self._ingress_submit(msg.vote, peer_id, msg.flow)
            ):
                return
            self._try_add_vote(msg.vote, peer_id, flow=msg.flow)
        elif isinstance(msg, VoteVerdictMessage):
            self._apply_vote_verdict_msg(msg, peer_id)
        else:
            raise ValueError(f"unknown msg type {type(msg)}")

    def _tock(self, ti: TimeoutInfo) -> None:
        """Ticker callback → queue (state.go timeoutRoutine → tockChan)."""
        self._queue.put((ti, ""))
        self._wake()

    def _handle_timeout(self, ti: TimeoutInfo) -> None:
        """state.go:923-1005."""
        rs = self.rs
        if ti.height != rs.height or ti.round < rs.round or (
            ti.round == rs.round and ti.step < rs.step
        ):
            return  # stale
        if ti.step == STEP_NEW_HEIGHT:
            self._enter_new_round(ti.height, 0)
        elif ti.step == STEP_NEW_ROUND:
            self._enter_propose(ti.height, 0)
        elif ti.step == STEP_PROPOSE:
            if self._event_bus:
                self._event_bus.publish_timeout_propose(rs.round_state_event())
            self._enter_prevote(ti.height, ti.round)
        elif ti.step == STEP_PREVOTE_WAIT:
            if self._event_bus:
                self._event_bus.publish_timeout_wait(rs.round_state_event())
            self._enter_precommit(ti.height, ti.round)
        elif ti.step == STEP_PRECOMMIT_WAIT:
            if self._event_bus:
                self._event_bus.publish_timeout_wait(rs.round_state_event())
            self._enter_precommit(ti.height, ti.round)
            self._enter_new_round(ti.height, ti.round + 1)
        else:
            raise ValueError(f"invalid timeout step {ti.step}")

    # ------------------------------------------------------------------
    # state transitions

    def _update_to_state(self, state: State) -> None:
        """state.go:624-722 updateToState."""
        rs = self.rs
        if rs.commit_round > -1 and 0 < rs.height and rs.height != state.last_block_height:
            raise RuntimeError(
                f"updateToState() expected state height of {rs.height} but found {state.last_block_height}"
            )
        validators = state.validators
        if state.last_block_height == 0:
            last_precommits = None
        else:
            if rs.votes is not None and rs.commit_round > -1:
                precommits = rs.votes.precommits(rs.commit_round)
                if precommits is None or not precommits.has_two_thirds_majority():
                    last_precommits = None
                else:
                    last_precommits = precommits
            else:
                last_precommits = None

        height = state.last_block_height + 1
        if height == 1:
            height = state.initial_height

        rs.height = height
        rs.round = 0
        rs.step = STEP_NEW_HEIGHT
        if rs.commit_time:
            rs.start_time = rs.commit_time + self._cfg.commit_timeout()
        else:
            rs.start_time = self._now() + self._cfg.commit_timeout()
        rs.validators = validators
        rs.proposal = None
        rs.proposal_block = None
        rs.proposal_block_parts = None
        rs.locked_round = -1
        rs.locked_block = None
        rs.locked_block_parts = None
        rs.valid_round = -1
        rs.valid_block = None
        rs.valid_block_parts = None
        rs.votes = HeightVoteSet(state.chain_id, height, validators)
        rs.commit_round = -1
        rs.last_commit = last_precommits
        rs.last_validators = state.last_validators
        rs.triggered_timeout_precommit = False
        self._state = state
        # flight recorder: a fresh timeline per height (an unfinished one
        # — catch-up, WAL replay — is simply superseded)
        self._timeline = HeightTimeline(height=height,
                                        t_new_height=self._now())

    # ------------------------------------------------------------------
    # per-height latency attribution (ISSUE 10)

    def _tl_mark(self, attr: str) -> None:
        """Stamp a phase transition once, on the current height's
        timeline; later re-entries (higher rounds re-reaching 2/3) keep
        the FIRST observation — the latency the height actually paid."""
        tl = self._timeline
        if tl is not None and tl.height == self.rs.height and \
                getattr(tl, attr) is None:
            setattr(tl, attr, self._now())

    def _tl_finish(self, tl: HeightTimeline) -> None:
        """Height committed+applied: retire the timeline into the ring and
        feed the phase histograms."""
        self.height_timelines.append(tl)
        self._timeline = None
        m = self._metrics
        if m is not None:
            try:
                for name, dur in tl.phases().items():
                    m.phase_seconds.observe(dur, phase=name)
            except Exception:  # noqa: BLE001 — metrics must never break commit
                pass

    def height_timeline(self, height: Optional[int] = None
                        ) -> Optional[HeightTimeline]:
        """The retained timeline for `height` (latest when None)."""
        ring = list(self.height_timelines)  # snapshot: RPC thread reads
        if not ring:
            return None
        if height is None:
            return ring[-1]
        for tl in ring:
            if tl.height == height:
                return tl
        return None

    def _schedule_round_0(self) -> None:
        sleep = max(self.rs.start_time - self._now(), 0.0)
        self._ticker.schedule_timeout(
            TimeoutInfo(sleep, self.rs.height, 0, STEP_NEW_HEIGHT)
        )

    def _new_step_event(self) -> None:
        if self._event_bus is not None:
            self._event_bus.publish_new_round_step(self.rs.round_state_event())

    def _enter_new_round(self, height: int, round_: int) -> None:
        """state.go:1008-1088."""
        rs = self.rs
        if rs.height != height or round_ < rs.round or (
            rs.round == round_ and rs.step != STEP_NEW_HEIGHT
        ):
            return
        validators = rs.validators
        if rs.round < round_:
            validators = validators.copy()
            validators.increment_proposer_priority(round_ - rs.round)
        rs.round = round_
        rs.step = STEP_NEW_ROUND
        rs.validators = validators
        if self._metrics is not None:
            self._metrics.rounds.set(round_)
        if round_ != 0:
            rs.proposal = None
            rs.proposal_block = None
            rs.proposal_block_parts = None
        rs.votes.set_round(round_ + 1)  # track next round's votes
        rs.triggered_timeout_precommit = False
        if self._event_bus is not None:
            self._event_bus.publish_new_round(rs.round_state_event())
        wait_for_txs = (
            self._cfg.create_empty_blocks_interval_ms > 0
            and not self._cfg.create_empty_blocks
            and round_ == 0
        )
        if wait_for_txs:
            self._ticker.schedule_timeout(
                TimeoutInfo(
                    self._cfg.create_empty_blocks_interval_ms / 1000.0,
                    height, round_, STEP_NEW_ROUND,
                )
            )
            return
        self._enter_propose(height, round_)

    def _is_proposer(self) -> bool:
        if self._priv_validator_pub_key is None:
            return False
        proposer = self.rs.validators.get_proposer()
        return proposer is not None and proposer.address == self._priv_validator_pub_key.address()

    def _enter_propose(self, height: int, round_: int) -> None:
        """state.go:1090-1159."""
        rs = self.rs
        if rs.height != height or round_ < rs.round or (
            rs.round == round_ and rs.step >= STEP_PROPOSE
        ):
            return
        rs.round = round_
        rs.step = STEP_PROPOSE
        self._new_step_event()
        self._ticker.schedule_timeout(
            TimeoutInfo(self._cfg.propose_timeout(round_), height, round_, STEP_PROPOSE)
        )
        if self._priv_validator is not None and self._is_proposer():
            if self.decide_proposal_override is not None:
                self.decide_proposal_override(self, height, round_)
            else:
                self._decide_proposal(height, round_)
        # if the proposal is already complete (e.g. we are the proposer or
        # received parts earlier), advance
        if self._is_proposal_complete():
            self._enter_prevote(height, round_)

    def _decide_proposal(self, height: int, round_: int) -> None:
        """state.go:1161-1226 defaultDecideProposal."""
        rs = self.rs
        if rs.valid_block is not None:
            block, block_parts = rs.valid_block, rs.valid_block_parts
        else:
            commit = None
            if height == self._state.initial_height:
                commit = Commit(height=0, round=0, block_id=BlockID(), signatures=[])
            elif rs.last_commit is not None and rs.last_commit.has_two_thirds_majority():
                commit = rs.last_commit.make_commit()
            else:
                return  # no commit for the previous block: cannot propose
            proposer_addr = self._priv_validator_pub_key.address()
            block, block_parts = self._block_exec.create_proposal_block(
                height, self._state, commit, proposer_addr
            )
        block_id = BlockID(hash=block.hash(), part_set_header=block_parts.header())
        proposal = Proposal(
            height=height,
            round=round_,
            pol_round=rs.valid_round,
            block_id=block_id,
            timestamp=_ts_from_float(self._now()),
        )
        try:
            proposal = self._priv_validator.sign_proposal(self._state.chain_id, proposal)
        except ValueError:
            return
        self._send_internal(ProposalMessage(proposal))
        for i in range(block_parts.total()):
            self._send_internal(BlockPartMessage(height, round_, block_parts.get_part(i)))

    def _is_proposal_complete(self) -> bool:
        """state.go:1228-1243."""
        rs = self.rs
        if rs.proposal is None or rs.proposal_block is None:
            return False
        if rs.proposal.pol_round < 0:
            return True
        prevotes = rs.votes.prevotes(rs.proposal.pol_round)
        return prevotes is not None and prevotes.has_two_thirds_majority()

    def _enter_prevote(self, height: int, round_: int) -> None:
        """state.go:1268-1296."""
        rs = self.rs
        if rs.height != height or round_ < rs.round or (
            rs.round == round_ and rs.step >= STEP_PREVOTE
        ):
            return
        rs.round = round_
        rs.step = STEP_PREVOTE
        self._new_step_event()
        if self.do_prevote_override is not None:
            self.do_prevote_override(self, height, round_)
        else:
            self._do_prevote(height, round_)

    def _do_prevote(self, height: int, round_: int) -> None:
        """state.go:1298-1336 defaultDoPrevote."""
        rs = self.rs
        if rs.locked_block is not None:
            self._sign_add_vote(PREVOTE_TYPE, rs.locked_block.hash(),
                                rs.locked_block_parts.header())
            return
        if rs.proposal_block is None:
            self._sign_add_vote(PREVOTE_TYPE, b"", None)
            return
        try:
            self._block_exec.validate_block(self._state, rs.proposal_block)
        except ValueError:
            self._sign_add_vote(PREVOTE_TYPE, b"", None)
            return
        self._sign_add_vote(
            PREVOTE_TYPE, rs.proposal_block.hash(), rs.proposal_block_parts.header()
        )

    def _enter_prevote_wait(self, height: int, round_: int) -> None:
        """state.go:1338-1362."""
        rs = self.rs
        if rs.height != height or round_ < rs.round or (
            rs.round == round_ and rs.step >= STEP_PREVOTE_WAIT
        ):
            return
        prevotes = rs.votes.prevotes(round_)
        if prevotes is None or not prevotes.has_two_thirds_any():
            raise RuntimeError("enter_prevote_wait without +2/3 prevotes")
        self._tl_mark("t_prevote_23")
        rs.round = round_
        rs.step = STEP_PREVOTE_WAIT
        self._new_step_event()
        self._ticker.schedule_timeout(
            TimeoutInfo(self._cfg.prevote_timeout(round_), height, round_, STEP_PREVOTE_WAIT)
        )

    def _enter_precommit(self, height: int, round_: int) -> None:
        """state.go:1364-1462."""
        rs = self.rs
        if rs.height != height or round_ < rs.round or (
            rs.round == round_ and rs.step >= STEP_PRECOMMIT
        ):
            return
        rs.round = round_
        rs.step = STEP_PRECOMMIT
        self._tl_mark("t_prevote_23")  # entered on polka or prevote-wait
        self._new_step_event()         # timeout — 2/3 prevotes either way
        prevotes = rs.votes.prevotes(round_)
        block_id, ok = (prevotes.two_thirds_majority() if prevotes else (BlockID(), False))
        if not ok:
            # no polka: precommit nil
            self._sign_add_vote(PRECOMMIT_TYPE, b"", None)
            return
        if self._event_bus is not None:
            self._event_bus.publish_polka(rs.round_state_event())
        pol_round, _ = rs.votes.pol_info()
        if pol_round < round_:
            raise RuntimeError(f"POLRound {pol_round} < {round_}")
        if block_id.is_zero():
            # +2/3 prevoted nil: unlock and precommit nil
            if rs.locked_block is not None:
                rs.locked_round = -1
                rs.locked_block = None
                rs.locked_block_parts = None
                if self._event_bus is not None:
                    self._event_bus.publish_relock(rs.round_state_event())
            self._sign_add_vote(PRECOMMIT_TYPE, b"", None)
            return
        if rs.locked_block is not None and rs.locked_block.hash() == block_id.hash:
            # relock
            rs.locked_round = round_
            if self._event_bus is not None:
                self._event_bus.publish_relock(rs.round_state_event())
            self._sign_add_vote(PRECOMMIT_TYPE, block_id.hash, block_id.part_set_header)
            return
        if rs.proposal_block is not None and rs.proposal_block.hash() == block_id.hash:
            try:
                self._block_exec.validate_block(self._state, rs.proposal_block)
            except ValueError as e:
                raise RuntimeError(f"+2/3 prevoted an invalid block: {e}") from e
            rs.locked_round = round_
            rs.locked_block = rs.proposal_block
            rs.locked_block_parts = rs.proposal_block_parts
            if self._event_bus is not None:
                self._event_bus.publish_lock(rs.round_state_event())
            self._sign_add_vote(PRECOMMIT_TYPE, block_id.hash, block_id.part_set_header)
            return
        # +2/3 prevotes for a block we don't have: unlock, fetch, precommit nil
        rs.locked_round = -1
        rs.locked_block = None
        rs.locked_block_parts = None
        if rs.proposal_block_parts is None or not rs.proposal_block_parts.has_header(
            block_id.part_set_header
        ):
            rs.proposal_block = None
            rs.proposal_block_parts = PartSet.new_from_header(block_id.part_set_header)
        self._sign_add_vote(PRECOMMIT_TYPE, b"", None)

    def _enter_precommit_wait(self, height: int, round_: int) -> None:
        """state.go:1464-1491."""
        rs = self.rs
        if rs.height != height or round_ < rs.round or (
            rs.round == round_ and rs.triggered_timeout_precommit
        ):
            return
        precommits = rs.votes.precommits(round_)
        if precommits is None or not precommits.has_two_thirds_any():
            raise RuntimeError("enter_precommit_wait without +2/3 precommits")
        self._tl_mark("t_precommit_23")
        rs.triggered_timeout_precommit = True
        self._new_step_event()
        self._ticker.schedule_timeout(
            TimeoutInfo(self._cfg.precommit_timeout(round_), height, round_, STEP_PRECOMMIT_WAIT)
        )

    def _enter_commit(self, height: int, commit_round: int) -> None:
        """state.go:1518-1579."""
        rs = self.rs
        if rs.height != height or rs.step >= STEP_COMMIT:
            return
        rs.round = rs.round  # unchanged by commit
        rs.step = STEP_COMMIT
        rs.commit_round = commit_round
        rs.commit_time = self._now()
        self._tl_mark("t_precommit_23")  # 2/3 precommits proved just above
        self._tl_mark("t_commit")
        self._new_step_event()
        precommits = rs.votes.precommits(commit_round)
        block_id, ok = precommits.two_thirds_majority()
        if not ok:
            raise RuntimeError("RunActionCommit without +2/3 precommits")
        if rs.locked_block is not None and rs.locked_block_parts.has_header(
            block_id.part_set_header
        ):
            rs.proposal_block = rs.locked_block
            rs.proposal_block_parts = rs.locked_block_parts
        elif rs.proposal_block is None or not rs.proposal_block_parts.has_header(
            block_id.part_set_header
        ):
            rs.proposal_block = None
            rs.proposal_block_parts = PartSet.new_from_header(block_id.part_set_header)
        self._try_finalize_commit(height)

    def _try_finalize_commit(self, height: int) -> None:
        """state.go:1581-1607."""
        rs = self.rs
        if rs.height != height:
            raise RuntimeError("try_finalize_commit at wrong height")
        precommits = rs.votes.precommits(rs.commit_round)
        block_id, ok = precommits.two_thirds_majority()
        if not ok or block_id.is_zero():
            return
        if rs.proposal_block is None or rs.proposal_block.hash() != block_id.hash:
            return  # don't have the block yet
        self._finalize_commit(height)

    def _finalize_commit(self, height: int) -> None:
        """state.go:1609-1700."""
        rs = self.rs
        if rs.height != height or rs.step != STEP_COMMIT:
            return
        precommits = rs.votes.precommits(rs.commit_round)
        block_id, ok = precommits.two_thirds_majority()
        block, block_parts = rs.proposal_block, rs.proposal_block_parts
        if not ok or not block_parts.has_header(block_id.part_set_header):
            raise RuntimeError("finalize_commit preconditions violated")
        if block.hash() != block_id.hash:
            raise RuntimeError("cannot finalize: proposal block does not hash to commit hash")
        # the verify/apply leg begins here: block validation (LastCommit
        # signatures ride the device batch engine) then ABCI apply
        self._tl_mark("t_verify_dispatch")
        self._block_exec.validate_block(self._state, block)

        # Save to block store before applying (state.go:1640-1652)
        if self._block_store.height() < block.header.height:
            seen_commit = precommits.make_commit()
            self._block_store.save_block(block, block_parts, seen_commit)

        if self._wal is not None:
            self._wal.write_sync(WALMessage(end_height=height))

        self._record_metrics(block, block_parts)

        state_copy = self._state.copy()
        new_state = self._block_exec.apply_block(state_copy, block_id, block)

        tl = self._timeline
        if tl is not None and tl.height == height:
            tl.rounds = rs.round + 1
            tl.t_applied = self._now()
            self._tl_finish(tl)

        # NewHeight: updateToState + schedule round 0
        self._update_to_state(new_state)
        self._done_first_block.set()
        with self._commit_cond:
            self._commit_cond.notify_all()
        for hook in self._height_events:
            try:
                hook(height)
            except Exception:  # noqa: BLE001 — observer hooks must not break commit
                pass
        self._schedule_round_0()

    def _record_metrics(self, block, block_parts) -> None:
        """state.go:1702-1757 recordMetrics — called with the pre-apply
        state still current, so last_block_time and last_validators refer
        to the previous height (what the interval and the missing-set
        accounting need)."""
        m = self._metrics
        if m is None:
            return
        try:
            hdr = block.header
            m.height.set(hdr.height)
            n_txs = len(block.data.txs)
            m.num_txs.set(n_txs)
            m.total_txs.inc(n_txs)
            # the part set already carries the wire size — no re-encode
            m.block_size_bytes.set(block_parts.byte_size())
            vals = self._state.validators
            m.validators.set(vals.size())
            m.validators_power.set(vals.total_voting_power())
            m.byzantine_validators.set(len(block.evidence))
            # the block's LastCommit is over the previous height's set
            last_vals = self._state.last_validators
            if block.last_commit is not None and last_vals is not None and \
                    last_vals.size() == len(block.last_commit.signatures):
                missing = 0
                missing_power = 0
                for i, cs in enumerate(block.last_commit.signatures):
                    if cs.is_absent():
                        missing += 1
                        missing_power += last_vals.validators[i].voting_power
                m.missing_validators.set(missing)
                m.missing_validators_power.set(missing_power)
            last_t = self._state.last_block_time
            if self._state.last_block_height > 0 and last_t is not None:
                dt = (hdr.time.seconds - last_t.seconds) + (
                    hdr.time.nanos - last_t.nanos
                ) / 1e9
                if dt >= 0:
                    m.block_interval_seconds.observe(dt)
        except Exception:  # noqa: BLE001 — metrics must never break commit
            pass

    # ------------------------------------------------------------------
    # proposals / parts / votes

    def _set_proposal(self, proposal: Proposal) -> None:
        """state.go:1753-1804 defaultSetProposal."""
        rs = self.rs
        if rs.proposal is not None:
            return
        if proposal.height != rs.height or proposal.round != rs.round:
            return
        if proposal.pol_round < -1 or (
            proposal.pol_round >= 0 and proposal.pol_round >= proposal.round
        ):
            raise ValueError("error invalid proposal POL round")
        proposer = rs.validators.get_proposer()
        if not proposer.pub_key.verify_signature(
            proposal.sign_bytes(self._state.chain_id), proposal.signature
        ):
            raise ValueError("error invalid proposal signature")
        rs.proposal = proposal
        self._tl_mark("t_proposal")
        if rs.proposal_block_parts is None:
            rs.proposal_block_parts = PartSet.new_from_header(
                proposal.block_id.part_set_header
            )

    def _add_proposal_block_part(self, msg: BlockPartMessage, peer_id: str) -> bool:
        """state.go:1806-1895."""
        rs = self.rs
        if msg.height != rs.height:
            return False
        if rs.proposal_block_parts is None:
            return False
        added = rs.proposal_block_parts.add_part(msg.part)
        if added and rs.proposal_block_parts.is_complete():
            data = rs.proposal_block_parts.assemble()
            rs.proposal_block = Block.decode(data)
            if self._event_bus is not None:
                self._event_bus.publish_complete_proposal(rs.round_state_event())
            prevotes = rs.votes.prevotes(rs.round)
            block_id, has_23 = (
                prevotes.two_thirds_majority() if prevotes else (BlockID(), False)
            )
            if has_23 and not block_id.is_zero() and rs.valid_round < rs.round:
                if rs.proposal_block.hash() == block_id.hash:
                    rs.valid_round = rs.round
                    rs.valid_block = rs.proposal_block
                    rs.valid_block_parts = rs.proposal_block_parts
            if rs.step <= STEP_PROPOSE and self._is_proposal_complete():
                self._enter_prevote(rs.height, rs.round)
            elif rs.step == STEP_COMMIT:
                self._try_finalize_commit(rs.height)
        return added

    def _try_add_vote(self, vote: Vote, peer_id: str,
                      flow: Optional[int] = None) -> bool:
        """state.go:1959-2005, span-wrapped: the vote's signature verify +
        set accounting is the consensus-side terminus of a gossiped vote's
        causal chain — the flow id captured at enqueue time (or parked on
        the tracer by a synchronous delivery driver) FINISHES here, so the
        merged trace links gossip send → deliver → verify dispatch."""
        tr = self._tracer
        if tr.enabled:
            fid = flow if flow is not None else tr.flow
            with tr.span("consensus.verify_dispatch", flow=fid,
                         flow_phase="f" if fid is not None else None,
                         height=vote.height, round=vote.round,
                         type=vote.type):
                return self._try_add_vote_impl(vote, peer_id)
        return self._try_add_vote_impl(vote, peer_id)

    def _try_add_vote_impl(self, vote: Vote, peer_id: str,
                           verdict: Optional[bool] = None) -> bool:
        try:
            return self._add_vote(vote, peer_id, verdict=verdict)
        except ErrVoteNonDeterministicSignature:
            return False
        except ErrVoteConflictingVotes as e:
            self._record_conflicting_votes(vote, e)
            return False

    def _record_conflicting_votes(self, vote: Vote,
                                  e: ErrVoteConflictingVotes) -> bool:
        """The ErrVoteConflictingVotes arm of state.go:1959-2005 —
        evidence: our own double-sign would be fatal; peers' recorded.
        Shared by the sequential path and the ingress host/apply stages."""
        if (
            self._priv_validator_pub_key is not None
            and vote.validator_address == self._priv_validator_pub_key.address()
        ):
            return False
        if self._evpool is not None:
            from ..types.evidence import DuplicateVoteEvidence

            try:
                ev = DuplicateVoteEvidence.new(
                    e.vote_a, e.vote_b, self._state.last_block_time,
                    self._state.validators,
                )
                self._evpool.add_evidence(ev)
            except ValueError:
                pass
        return False

    def _add_vote(self, vote: Vote, peer_id: str,
                  verdict: Optional[bool] = None) -> bool:
        """state.go:2007-2180. `verdict` is the device signature verdict
        from the ingress lane (ISSUE 15): None = sequential host verify;
        a bool routes through HeightVoteSet.apply_vote_verdict, which
        re-runs the host checks and applies. A verdict that arrives after
        the height moved on falls into the catchup/stale branches below —
        those always re-verify sequentially, never trusting a verdict
        produced against a different height's vote sets."""
        rs = self.rs
        # A precommit for the previous height (catchup for commit-timeout)
        if vote.height + 1 == rs.height and vote.type == PRECOMMIT_TYPE:
            if rs.step != STEP_NEW_HEIGHT or rs.last_commit is None:
                return False
            added = rs.last_commit.add_vote(vote)
            if added:
                if self._event_bus is not None:
                    self._event_bus.publish_vote(vote)
                for hook in self.vote_added_hooks:
                    try:
                        hook(vote)
                    except Exception:  # noqa: BLE001
                        pass
            return added
        if vote.height != rs.height:
            return False

        if verdict is None:
            added = rs.votes.add_vote(vote, peer_id)
        else:
            added = rs.votes.apply_vote_verdict(vote, peer_id, verdict)
        if not added:
            return False
        if self._event_bus is not None:
            self._event_bus.publish_vote(vote)
        for hook in self.vote_added_hooks:
            try:
                hook(vote)
            except Exception:  # noqa: BLE001 — gossip hooks must not break consensus
                pass

        if vote.type == PREVOTE_TYPE:
            prevotes = rs.votes.prevotes(vote.round)
            # valid-block tracking (state.go:2085-2130)
            block_id, ok = prevotes.two_thirds_majority()
            if ok and not block_id.is_zero() and rs.valid_round < vote.round and vote.round == rs.round:
                if rs.proposal_block is not None and rs.proposal_block.hash() == block_id.hash:
                    rs.valid_round = vote.round
                    rs.valid_block = rs.proposal_block
                    rs.valid_block_parts = rs.proposal_block_parts
                else:
                    rs.proposal_block = None
                    if rs.proposal_block_parts is None or not rs.proposal_block_parts.has_header(
                        block_id.part_set_header
                    ):
                        rs.proposal_block_parts = PartSet.new_from_header(
                            block_id.part_set_header
                        )
                if self._event_bus is not None:
                    self._event_bus.publish_valid_block(rs.round_state_event())
            # step transitions (state.go:2132-2160)
            if rs.round < vote.round and prevotes.has_two_thirds_any():
                self._enter_new_round(rs.height, vote.round)
            elif rs.round == vote.round and rs.step >= STEP_PREVOTE:
                block_id2, ok2 = prevotes.two_thirds_majority()
                if ok2 and (self._is_proposal_complete() or block_id2.is_zero()):
                    self._enter_precommit(rs.height, vote.round)
                elif prevotes.has_two_thirds_any():
                    self._enter_prevote_wait(rs.height, vote.round)
            elif rs.proposal is not None and rs.proposal.pol_round >= 0 and rs.proposal.pol_round == vote.round:
                if self._is_proposal_complete():
                    self._enter_prevote(rs.height, rs.round)
        elif vote.type == PRECOMMIT_TYPE:
            precommits = rs.votes.precommits(vote.round)
            block_id, ok = precommits.two_thirds_majority()
            if ok:
                self._enter_new_round(rs.height, vote.round)
                self._enter_precommit(rs.height, vote.round)
                if not block_id.is_zero():
                    self._enter_commit(rs.height, vote.round)
                    if self._cfg.skip_timeout_commit and precommits.has_all():
                        self._enter_new_round(rs.height, 0)
                else:
                    self._enter_precommit_wait(rs.height, vote.round)
            elif rs.round <= vote.round and precommits.has_two_thirds_any():
                self._enter_new_round(rs.height, vote.round)
                self._enter_precommit_wait(rs.height, vote.round)
        return added

    def _sign_vote(self, vote_type: int, hash_: bytes, header) -> Optional[Vote]:
        """state.go:2182-2230 signVote."""
        if self._priv_validator is None or self._priv_validator_pub_key is None:
            return None
        addr = self._priv_validator_pub_key.address()
        idx, val = self.rs.validators.get_by_address(addr)
        if val is None:
            return None  # not a validator
        block_id = BlockID(hash=hash_, part_set_header=header) if hash_ else BlockID()
        vote = Vote(
            type=vote_type,
            height=self.rs.height,
            round=self.rs.round,
            block_id=block_id,
            timestamp=self._vote_time(),
            validator_address=addr,
            validator_index=idx,
        )
        try:
            # The signer returns the signed vote — possibly with the
            # last-signed timestamp restored on a same-HRS re-sign
            # (privval file.go:339-341), so the signature always verifies.
            return self._priv_validator.sign_vote(self._state.chain_id, vote)
        except ValueError:
            return None

    def _vote_time(self) -> Timestamp:
        """state.go voteTime: max(now, lastBlockTime + 1ns-ish)."""
        now = _ts_from_float(self._now())
        lbt = self._state.last_block_time
        min_time = Timestamp(seconds=lbt.seconds, nanos=lbt.nanos + 1)
        if min_time.nanos >= 10**9:
            min_time = Timestamp(seconds=min_time.seconds + 1, nanos=min_time.nanos - 10**9)
        if _ts_le(now, min_time):
            return min_time
        return now

    def _sign_add_vote(self, vote_type: int, hash_: bytes, header) -> Optional[Vote]:
        vote = self._sign_vote(vote_type, hash_, header)
        if vote is not None:
            self._send_internal(VoteMessage(vote))
        return vote

    # ------------------------------------------------------------------
    # WAL replay (replay.go:96-160 catchupReplay)

    def catch_up_to_state(self, state: State) -> None:
        """node.go:323-343 switchToConsensus: adopt a state advanced by
        statesync/blocksync BEFORE the state machine starts (safe while
        commit_round == -1), and rebuild LastCommit from the stored seen
        commit so proposing can resume."""
        if self._thread is not None and self._thread.is_alive():
            raise RuntimeError("cannot catch up a running consensus state")
        self._update_to_state(state)
        self._reconstruct_last_commit()

    def _reconstruct_last_commit(self) -> None:
        """state.go:518-543 reconstructLastCommit: after a restart the
        in-memory precommit VoteSet for the last committed height is gone;
        rebuild it from the block store's seen commit so the proposer can
        assemble the next block's LastCommit (without this a restarted
        validator can never propose again)."""
        state = self._state
        if state.last_block_height == 0 or self.rs.last_commit is not None:
            return
        seen = self._block_store.load_seen_commit()
        if seen is None or seen.height != state.last_block_height:
            return
        vs = VoteSet(
            state.chain_id, seen.height, seen.round, PRECOMMIT_TYPE, state.last_validators
        )
        for idx, cs in enumerate(seen.signatures):
            if cs.is_absent():
                continue
            try:
                vs.add_vote(
                    Vote(
                        type=PRECOMMIT_TYPE,
                        height=seen.height,
                        round=seen.round,
                        block_id=cs.block_id(seen.block_id),
                        timestamp=cs.timestamp,
                        validator_address=cs.validator_address,
                        validator_index=idx,
                        signature=cs.signature,
                    )
                )
            except ValueError:
                continue  # e.g. nil-vote sigs; majority check below decides
        if vs.has_two_thirds_majority():
            self.rs.last_commit = vs

    def _replay_wal(self) -> None:
        if self._wal is None:
            return
        tail = self._wal.search_for_end_height(self._state.last_block_height)
        if tail is None:
            return
        for rec in tail:
            if rec.end_height is not None:
                continue
            if rec.timeout is not None:
                continue  # timeouts are rescheduled naturally
            try:
                if rec.msg_kind == "proposal":
                    self._set_proposal(Proposal.decode(rec.msg_payload))
                elif rec.msg_kind == "block_part":
                    from ..wire.proto import decode_message, field_bytes, field_int

                    f = decode_message(rec.msg_payload)
                    self._add_proposal_block_part(
                        BlockPartMessage(
                            height=field_int(f, 1),
                            round=field_int(f, 2),
                            part=Part.decode(field_bytes(f, 3)),
                        ),
                        rec.peer_id,
                    )
                elif rec.msg_kind == "vote":
                    self._try_add_vote(Vote.decode(rec.msg_payload), rec.peer_id)
            except (ValueError, RuntimeError):
                continue
