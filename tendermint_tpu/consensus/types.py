"""Consensus internal types: round steps, RoundState, HeightVoteSet.

Reference parity: internal/consensus/types/{round_state.go,
height_vote_set.go}.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from ..types import BlockID, Commit, Timestamp, ValidatorSet, Vote, VoteSet
from ..types.block import Block
from ..types.part_set import PartSet
from ..types.proposal import Proposal
from ..types.vote import PRECOMMIT_TYPE, PREVOTE_TYPE, is_vote_type_valid

# RoundStepType (round_state.go:20-28)
STEP_NEW_HEIGHT = 1
STEP_NEW_ROUND = 2
STEP_PROPOSE = 3
STEP_PREVOTE = 4
STEP_PREVOTE_WAIT = 5
STEP_PRECOMMIT = 6
STEP_PRECOMMIT_WAIT = 7
STEP_COMMIT = 8

STEP_NAMES = {
    STEP_NEW_HEIGHT: "NewHeight",
    STEP_NEW_ROUND: "NewRound",
    STEP_PROPOSE: "Propose",
    STEP_PREVOTE: "Prevote",
    STEP_PREVOTE_WAIT: "PrevoteWait",
    STEP_PRECOMMIT: "Precommit",
    STEP_PRECOMMIT_WAIT: "PrecommitWait",
    STEP_COMMIT: "Commit",
}


class ErrGotVoteFromUnwantedRound(ValueError):
    pass


class HeightVoteSet:
    """height_vote_set.go:40-200: all vote sets for a height, rounds
    0..round, plus up to 2 catchup rounds per peer."""

    def __init__(self, chain_id: str, height: int, val_set: ValidatorSet):
        self._chain_id = chain_id
        self._mtx = threading.RLock()
        self.reset(height, val_set)

    def reset(self, height: int, val_set: ValidatorSet) -> None:
        with self._mtx:
            self._height = height
            self._val_set = val_set
            self._round_vote_sets: Dict[int, Tuple[VoteSet, VoteSet]] = {}
            self._peer_catchup_rounds: Dict[str, List[int]] = {}
            self._add_round(0)
            self._round = 0

    def height(self) -> int:
        return self._height

    def round(self) -> int:
        return self._round

    def set_round(self, round_: int) -> None:
        with self._mtx:
            new_round = self._round - 1
            if self._round != 0 and round_ < new_round:
                raise ValueError("set_round() must increment round")
            for r in range(max(new_round, 0), round_ + 1):
                if r not in self._round_vote_sets:
                    self._add_round(r)
            self._round = round_

    def _add_round(self, round_: int) -> None:
        if round_ in self._round_vote_sets:
            raise ValueError("add_round() for an existing round")
        prevotes = VoteSet(self._chain_id, self._height, round_, PREVOTE_TYPE, self._val_set)
        precommits = VoteSet(self._chain_id, self._height, round_, PRECOMMIT_TYPE, self._val_set)
        self._round_vote_sets[round_] = (prevotes, precommits)

    def add_vote(self, vote: Vote, peer_id: str) -> bool:
        """height_vote_set.go:116-136. Returns added; raises on invalid."""
        with self._mtx:
            if not is_vote_type_valid(vote.type):
                return False
            vs = self._get_vote_set(vote.round, vote.type)
            if vs is None:
                rndz = self._peer_catchup_rounds.get(peer_id, [])
                if len(rndz) < 2:
                    self._add_round(vote.round)
                    vs = self._get_vote_set(vote.round, vote.type)
                    self._peer_catchup_rounds[peer_id] = rndz + [vote.round]
                else:
                    raise ErrGotVoteFromUnwantedRound(
                        "peer has sent a vote that does not match our round for more than one round"
                    )
            return vs.add_vote(vote)

    def check_vote(self, vote: Vote, peer_id: str):
        """Host stage of add_vote (ISSUE 15): the same routing —
        type-validity, catchup-round registration with its 2-round peer
        budget — followed by VoteSet.check_vote. Returns the CheckedVote
        (or None for a type-invalid vote / exact duplicate: both shapes
        where sequential add_vote returns False without raising). The
        catchup round is registered at CHECK time so the verdict always
        has a VoteSet to land in."""
        with self._mtx:
            if not is_vote_type_valid(vote.type):
                return None
            vs = self._get_vote_set(vote.round, vote.type)
            if vs is None:
                rndz = self._peer_catchup_rounds.get(peer_id, [])
                if len(rndz) < 2:
                    self._add_round(vote.round)
                    vs = self._get_vote_set(vote.round, vote.type)
                    self._peer_catchup_rounds[peer_id] = rndz + [vote.round]
                else:
                    raise ErrGotVoteFromUnwantedRound(
                        "peer has sent a vote that does not match our round for more than one round"
                    )
            return vs.check_vote(vote)

    def apply_vote_verdict(self, vote: Vote, peer_id: str, valid: bool) -> bool:
        """Verdict-application stage of add_vote (ISSUE 15). The round's
        VoteSet was registered by check_vote; if it has since vanished
        (height advanced resets this object — callers guard on height)
        fall back to the full sequential add path, which re-verifies."""
        with self._mtx:
            if not is_vote_type_valid(vote.type):
                return False
            vs = self._get_vote_set(vote.round, vote.type)
            if vs is None:
                return self.add_vote(vote, peer_id)
            return vs.apply_vote_verdict(vote, valid)

    def prevotes(self, round_: int) -> Optional[VoteSet]:
        with self._mtx:
            return self._get_vote_set(round_, PREVOTE_TYPE)

    def precommits(self, round_: int) -> Optional[VoteSet]:
        with self._mtx:
            return self._get_vote_set(round_, PRECOMMIT_TYPE)

    def pol_info(self) -> Tuple[int, BlockID]:
        """height_vote_set.go:152-163: last round with a prevote maj23."""
        with self._mtx:
            for r in range(self._round, -1, -1):
                rvs = self._get_vote_set(r, PREVOTE_TYPE)
                if rvs is not None:
                    block_id, ok = rvs.two_thirds_majority()
                    if ok:
                        return r, block_id
            return -1, BlockID()

    def _get_vote_set(self, round_: int, vote_type: int) -> Optional[VoteSet]:
        rvs = self._round_vote_sets.get(round_)
        if rvs is None:
            return None
        return rvs[0] if vote_type == PREVOTE_TYPE else rvs[1]

    def set_peer_maj23(self, round_: int, vote_type: int, peer_id: str, block_id: BlockID) -> None:
        """height_vote_set.go:184-202."""
        with self._mtx:
            if not is_vote_type_valid(vote_type):
                raise ValueError(f"SetPeerMaj23: invalid vote type {vote_type}")
            vs = self._get_vote_set(round_, vote_type)
            if vs is None:
                return
            vs.set_peer_maj23(peer_id, block_id)


@dataclass
class RoundState:
    """round_state.go:30-80 — the full consensus round state."""

    height: int = 0
    round: int = 0
    step: int = STEP_NEW_HEIGHT
    start_time: float = 0.0
    commit_time: float = 0.0

    validators: Optional[ValidatorSet] = None
    proposal: Optional[Proposal] = None
    proposal_block: Optional[Block] = None
    proposal_block_parts: Optional[PartSet] = None
    locked_round: int = -1
    locked_block: Optional[Block] = None
    locked_block_parts: Optional[PartSet] = None
    valid_round: int = -1
    valid_block: Optional[Block] = None
    valid_block_parts: Optional[PartSet] = None
    votes: Optional[HeightVoteSet] = None
    commit_round: int = -1
    last_commit: Optional[VoteSet] = None
    last_validators: Optional[ValidatorSet] = None
    triggered_timeout_precommit: bool = False

    def round_state_event(self) -> dict:
        return {
            "height": self.height,
            "round": self.round,
            "step": STEP_NAMES.get(self.step, str(self.step)),
        }
