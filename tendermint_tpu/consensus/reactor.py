"""Consensus reactor — gossips consensus messages over p2p channels with
per-peer targeted gossip.

Reference parity: internal/consensus/reactor.go — channels State (0x20),
Data (0x21), Vote (0x22), VoteSetBits (0x23) with the reference's channel
priorities (reactor.go:32-73). Each peer's round state and vote bit arrays
are tracked in a PeerState (peer_state.py ~ peer_state.go), and the gossip
loop sends each peer only what it is missing — the Python analog of the
reference's three per-peer goroutines (gossipDataRoutine reactor.go:503,
gossipVotesRoutine :715, queryMaj23Routine :797), folded into one loop
over all peers.

Wire (internal/consensus/msgs.go oneofs, field numbers ours):
  State ch:  1 NewRoundStep{1 height, 2 round, 3 step, 4 secs_since_start,
                            5 last_commit_round}
           | 2 NewValidBlock{1 height, 2 round, 3 part_set_header,
                             4 parts_bits, 5 is_commit}
           | 3 HasVote{1 height, 2 round, 3 type, 4 index}
           | 4 VoteSetMaj23{1 height, 2 round, 3 type, 4 block_id}
           | 5 HasVoteBits{1 height, 2 round, 3 type, 4 bits}
             (ISSUE 15 traffic diet: one bit-array summary per
             (height, round, type) per gossip sweep replaces the
             per-vote HasVote broadcast — the PR-6 O(N²·V)
             state-channel hotspot; field 3 remains understood inbound
             for mixed-version peers)
  Data ch:   1 Proposal | 2 BlockPart{1 height, 2 round, 3 part}
           | 3 ProposalPOL{1 height, 2 pol_round, 3 bits}
  Vote ch:   1 Vote
  VSB ch:    1 VoteSetBits{1 height, 2 round, 3 type, 4 block_id, 5 bits}
"""

from __future__ import annotations

import queue as _q
import threading
import time as _t
from typing import Dict, Optional

from ..libs.bits import BitArray
from ..p2p.conn.mconnection import ChannelDescriptor
from ..p2p.router import Router
from ..types import BlockID
from ..types.part_set import Part
from ..types.proposal import Proposal
from ..types.vote import PRECOMMIT_TYPE, PREVOTE_TYPE, Vote
from ..wire.proto import (
    ProtoWriter,
    decode_message,
    field_bytes,
    field_int,
    to_signed32,
    to_signed64,
)
from .peer_state import PeerState
from .state import BlockPartMessage, ConsensusState, ProposalMessage, VoteMessage
from .types import (
    STEP_COMMIT,
    STEP_NEW_HEIGHT,
    STEP_PRECOMMIT_WAIT,
    STEP_PREVOTE_WAIT,
    STEP_PROPOSE,
)

STATE_CHANNEL = 0x20
DATA_CHANNEL = 0x21
VOTE_CHANNEL = 0x22
VOTE_SET_BITS_CHANNEL = 0x23

STATE_DESC = ChannelDescriptor(id=STATE_CHANNEL, priority=8, send_queue_capacity=64)
DATA_DESC = ChannelDescriptor(id=DATA_CHANNEL, priority=12, send_queue_capacity=64)
VOTE_DESC = ChannelDescriptor(id=VOTE_CHANNEL, priority=10, send_queue_capacity=64)
VOTE_SET_BITS_DESC = ChannelDescriptor(id=VOTE_SET_BITS_CHANNEL, priority=5)

ALL_DESCS = [STATE_DESC, DATA_DESC, VOTE_DESC, VOTE_SET_BITS_DESC]


def _encode_block_part(height: int, round_: int, part: Part) -> bytes:
    w = ProtoWriter()
    w.write_varint(1, height)
    w.write_varint(2, round_)
    w.write_message(3, part.encode(), always=True)
    return w.bytes()


def _wrap(field: int, inner: bytes) -> bytes:
    w = ProtoWriter()
    w.write_message(field, inner, always=True)
    return w.bytes()


class ConsensusReactor:
    """reactor.go:100-300 with per-peer targeted gossip."""

    GOSSIP_INTERVAL = 0.05
    QUERY_MAJ23_INTERVAL = 2.0

    def __init__(self, cs: ConsensusState, router: Router, block_store=None, rng=None):
        self._cs = cs
        self._router = router
        # randomness for per-peer gossip picks (PeerState); injectable so
        # simnet's seeded PRNG makes whole-cluster runs replayable
        self._rng = rng
        self._block_store = (
            block_store if block_store is not None else getattr(cs, "_block_store", None)
        )
        self._data_ch = router.open_channel(DATA_DESC)
        self._vote_ch = router.open_channel(VOTE_DESC)
        self._state_ch = router.open_channel(STATE_DESC)
        self._vsb_ch = router.open_channel(VOTE_SET_BITS_DESC)
        self._stopped = threading.Event()
        self._threads = []
        self._peers: Dict[str, PeerState] = {}
        self._peers_mtx = threading.Lock()
        # Incremental gossip: a sweep visits only peers marked DIRTY by a
        # RoundState delta on our side (new step/vote/proposal/part) or a
        # PeerRoundState delta on theirs (inbound NRS/NVB/VoteSetBits),
        # instead of scanning every peer each tick — at 100+ peers the
        # all-peers scan dominated gossip_once even when nothing was
        # sendable. A sweep that makes progress re-marks the peer (more
        # may remain, e.g. catchup parts served one per sweep), and the
        # query-maj23 cadence stays a FULL sweep, so a mark lost to a
        # dropped message costs at most one 2s interval — the same
        # recovery bound the one-shot NRS/NVB re-advertisement already
        # leans on. Insertion-ordered dict: deterministic sweep order
        # under simnet's seeded driver.
        self._dirty: Dict[str, None] = {}
        self._dirty_mtx = threading.Lock()
        self._last_nrs = None  # last broadcast (height, round, step, lcr)
        self._last_nvb = None  # last broadcast NewValidBlock key
        # HasVote traffic diet (ISSUE 15): votes added between sweeps
        # accumulate their (height, round, type) keys here; gossip_once
        # drains the dict and broadcasts ONE HasVoteBits summary per key
        # instead of one HasVote per vote. Insertion-ordered for simnet
        # determinism.
        self._pending_has_vote: Dict[tuple, None] = {}
        self._pending_hv_mtx = threading.Lock()
        self._handlers = {
            DATA_CHANNEL: self._handle_data,
            VOTE_CHANNEL: self._handle_vote,
            STATE_CHANNEL: self._handle_state,
            VOTE_SET_BITS_CHANNEL: self._handle_vsb,
        }
        cs.broadcast_hooks.append(self._broadcast_own)
        cs.vote_added_hooks.append(self._broadcast_has_vote)

    def start(self) -> None:
        for ch in (self._data_ch, self._vote_ch, self._state_ch, self._vsb_ch):
            t = threading.Thread(target=self._process, args=(ch,), daemon=True)
            t.start()
            self._threads.append(t)
        for target in (self._peer_update_routine, self._gossip_routine):
            t = threading.Thread(target=target, daemon=True)
            t.start()
            self._threads.append(t)

    def stop(self) -> None:
        self._stopped.set()

    # -- peer lifecycle ---------------------------------------------------

    def _peer_update_routine(self) -> None:
        updates = self._router.subscribe_peer_updates()
        while not self._stopped.is_set():
            try:
                upd = updates.get(timeout=0.5)
            except _q.Empty:
                continue
            if upd.status == "up":
                self.add_peer(upd.node_id)
            elif upd.status == "down":
                self.remove_peer(upd.node_id)

    def add_peer(self, peer_id: str) -> None:
        """Register a peer for gossip and advertise our round state
        (reactor.go AddPeer). Also the simnet seam: a deterministic driver
        calls this directly instead of running _peer_update_routine."""
        with self._peers_mtx:
            if peer_id not in self._peers:
                self._peers[peer_id] = PeerState(peer_id, rng=self._rng)
        self._mark_dirty(peer_id)
        # network send OUTSIDE the peers lock — a full send queue
        # blocks up to the mconn timeout and every inbound handler
        # takes this lock per message
        self._send_new_round_step(peer_id)

    def remove_peer(self, peer_id: str) -> None:
        """Forget a peer's round state (reactor.go RemovePeer); a
        reconnect starts from a fresh PeerState."""
        with self._peers_mtx:
            self._peers.pop(peer_id, None)
        with self._dirty_mtx:
            self._dirty.pop(peer_id, None)

    # -- dirty-peer bookkeeping ------------------------------------------

    def _mark_dirty(self, peer_id: str) -> None:
        with self._dirty_mtx:
            self._dirty[peer_id] = None

    def _mark_all_dirty(self) -> None:
        with self._peers_mtx:
            ids = list(self._peers)
        with self._dirty_mtx:
            for pid in ids:
                self._dirty[pid] = None

    def _peer_list(self):
        with self._peers_mtx:
            return list(self._peers.values())

    def _get_peer(self, peer_id: str) -> PeerState:
        with self._peers_mtx:
            ps = self._peers.get(peer_id)
            if ps is None:
                ps = self._peers[peer_id] = PeerState(peer_id, rng=self._rng)
            return ps

    # -- NewRoundStep / HasVote broadcasting ------------------------------

    def _nrs_payload(self) -> tuple:
        rs = self._cs.rs
        lcr = rs.last_commit.round if rs.last_commit is not None else -1
        return rs.height, rs.round, rs.step, lcr, rs.start_time

    def _encode_nrs(self, h, r, s, lcr, start_time) -> bytes:
        w = ProtoWriter()
        w.write_varint(1, h)
        w.write_varint(2, r)
        w.write_varint(3, s)
        # seconds-since-start on the STATE MACHINE's clock — under a
        # virtual clock (simnet) wall time would leak nondeterministic
        # bytes into the wire message
        w.write_varint(4, max(int(self._cs._now() - start_time), 0))
        w.write_varint(5, lcr)
        return _wrap(1, w.bytes())

    def _send_new_round_step(self, peer_id: str) -> None:
        h, r, s, lcr, st = self._nrs_payload()
        self._state_ch.send(peer_id, self._encode_nrs(h, r, s, lcr, st))

    def _maybe_broadcast_new_round_step(self) -> None:
        h, r, s, lcr, st = self._nrs_payload()
        key = (h, r, s, lcr)
        if key != self._last_nrs:
            self._last_nrs = key
            self._state_ch.broadcast(self._encode_nrs(h, r, s, lcr, st))
            # our RoundState moved: any peer may now be missing something
            self._mark_all_dirty()

    def _maybe_broadcast_new_valid_block(self) -> None:
        """reactor.go broadcastNewValidBlockMessage (sent from enterCommit
        and on valid-block update): advertises our part-set header + which
        parts we hold, so peers — including ones ahead of us — know they
        can serve us the remaining parts."""
        rs = self._cs.rs
        parts = rs.proposal_block_parts
        in_commit = rs.step >= STEP_COMMIT
        is_valid = rs.valid_block_parts is parts and rs.valid_round >= 0
        if parts is None or not (in_commit or is_valid):
            return
        bits = parts.bit_array()
        key = (rs.height, rs.round, parts.header(), tuple(bits.get_true_indices()))
        if key == self._last_nvb:
            return
        self._last_nvb = key
        w = ProtoWriter()
        w.write_varint(1, rs.height)
        w.write_varint(2, rs.round)
        w.write_message(3, parts.header().encode(), always=True)
        w.write_message(4, bits.encode(), always=True)
        w.write_varint(5, 1 if in_commit else 0)
        self._state_ch.broadcast(_wrap(2, w.bytes()))
        self._mark_all_dirty()  # new valid-block/parts state to serve

    def _broadcast_has_vote(self, vote: Vote) -> None:
        """reactor.go:1031 broadcastHasVoteMessage — coalesced (ISSUE 15):
        instead of broadcasting one HasVote per added vote (O(N²·V) on the
        state channel at cluster scale), record the vote's (height, round,
        type) key; the next gossip_once sweep broadcasts ONE HasVoteBits
        bit-array summary per recorded key."""
        with self._pending_hv_mtx:
            self._pending_has_vote[(vote.height, vote.round, vote.type)] = None
        # a vote entered OUR state: peers at (or below) its height may be
        # missing it. The height read is deliberately lock-free — a stale
        # read only means a spurious mark (harmless) or a missed one
        # (healed by the next full sweep). One dirty-lock acquisition for
        # the whole batch: this runs once per vote added, the hot path.
        h = vote.height
        with self._peers_mtx:
            peers = list(self._peers.values())
        marks = [ps.peer_id for ps in peers if ps.prs.height <= h]
        if marks:
            with self._dirty_mtx:
                for pid in marks:
                    self._dirty[pid] = None

    def _flush_has_vote(self) -> None:
        """Drain the pending HasVote keys and broadcast one HasVoteBits
        summary per (height, round, type) — our VoteSet's CURRENT bit
        array, so a summary sent once covers every vote added since the
        last sweep (and any the per-key coalescing folded together).
        Deterministic under simnet: the pending dict is insertion-ordered
        and drained atomically at the sweep boundary."""
        with self._pending_hv_mtx:
            if not self._pending_has_vote:
                return
            pending = list(self._pending_has_vote)
            self._pending_has_vote.clear()
        rs = self._cs.rs
        for h, r, t in pending:
            bits = None
            if h == rs.height and rs.votes is not None:
                vs = (rs.votes.prevotes(r) if t == PREVOTE_TYPE
                      else rs.votes.precommits(r))
                if vs is not None:
                    bits = vs.bit_array()
            elif (
                h + 1 == rs.height
                and rs.last_commit is not None
                and t == PRECOMMIT_TYPE
                and r == rs.last_commit.round
            ):
                bits = rs.last_commit.bit_array()
            if bits is None:
                # height moved on mid-sweep: the NewRoundStep broadcast +
                # catchup gossip already cover what peers need
                continue
            w = ProtoWriter()
            w.write_varint(1, h)
            w.write_varint(2, r)
            w.write_varint(3, t)
            w.write_message(4, bits.encode(), always=True)
            self._state_ch.broadcast(_wrap(5, w.bytes()))

    # -- gossip loop (the per-peer goroutines, folded) --------------------

    def gossip_once(self, query_maj23: bool = False) -> None:
        """One gossip sweep — one iteration of the reference's per-peer
        goroutines. The threaded path loops this; a deterministic driver
        (simnet) calls it on its own schedule.

        A plain tick sweeps only DIRTY peers (see _mark_dirty): with no
        state deltas since the last tick the sweep is O(1), which is what
        lets a 100+-node cluster tick 20x/s without the O(peers) scan.
        The query-maj23 cadence (every ~2s) remains a FULL sweep over all
        peers — the safety net that also re-sends the one-shot
        advertisements below."""
        if query_maj23:
            # periodic refresh of the one-shot advertisements: on a lossy
            # link a dropped NewRoundStep/NewValidBlock would otherwise
            # never be re-sent (the last-key guards suppress it) and a
            # laggard's catchup wedges forever — found by simnet's drop
            # fault; the reference leans on TCP for this
            self._last_nrs = None
            self._last_nvb = None
        self._maybe_broadcast_new_round_step()
        self._maybe_broadcast_new_valid_block()
        self._flush_has_vote()
        if query_maj23:
            with self._dirty_mtx:
                self._dirty.clear()
            peers = self._peer_list()
        else:
            # drain the dirty set BEFORE sweeping: concurrent marks during
            # the sweep land in the next tick instead of being lost
            with self._dirty_mtx:
                if not self._dirty:
                    return
                dirty = self._dirty
                self._dirty = {}
            with self._peers_mtx:
                peers = [self._peers[p] for p in dirty if p in self._peers]
        for ps in peers:
            sent = self._gossip_data(ps)
            sent = self._gossip_votes(ps) or sent
            if query_maj23:
                self._query_maj23(ps)
            if sent:
                # progress made ⇒ more may remain (catchup serves one
                # part/vote per sweep): keep the peer hot
                self._mark_dirty(ps.peer_id)

    def _gossip_routine(self) -> None:
        last_maj23 = 0.0
        while not self._stopped.is_set():
            _t.sleep(self.GOSSIP_INTERVAL)
            try:
                query_maj23 = _t.time() - last_maj23 >= self.QUERY_MAJ23_INTERVAL
                if query_maj23:
                    last_maj23 = _t.time()
                self.gossip_once(query_maj23)
            except Exception:  # noqa: BLE001 — gossip must never die
                continue

    def _gossip_data(self, ps: PeerState) -> bool:
        """reactor.go:503 gossipDataRoutine (one iteration). Returns True
        when something was sent (the dirty-sweep progress signal)."""
        rs = self._cs.rs
        prs = ps.snapshot()
        sent = False
        if prs.height == rs.height:
            # proposal first, then missing parts
            if rs.proposal is not None and not prs.proposal:
                w = ProtoWriter()
                w.write_message(1, rs.proposal.encode(), always=True)
                if self._data_ch.send(ps.peer_id, w.bytes()):
                    sent = True
                    ps.apply_proposal(rs.proposal)
                    if rs.proposal.pol_round >= 0 and rs.votes is not None:
                        pol = rs.votes.prevotes(rs.proposal.pol_round)
                        if pol is not None:
                            pw = ProtoWriter()
                            pw.write_varint(1, rs.height)
                            pw.write_varint(2, rs.proposal.pol_round)
                            pw.write_message(3, pol.bit_array().encode(), always=True)
                            self._data_ch.send(ps.peer_id, _wrap(3, pw.bytes()))
            parts = rs.proposal_block_parts
            if (
                parts is not None
                and prs.proposal_block_parts is not None
                and prs.proposal_block_part_set_header == parts.header()
            ):
                missing = parts.bit_array().sub(prs.proposal_block_parts)
                idxs = missing.get_true_indices()
                if idxs:
                    idx = idxs[0]
                    p = parts.get_part(idx)
                    if p is not None:
                        msg = _wrap(2, _encode_block_part(rs.height, rs.round, p))
                        if self._data_ch.send(ps.peer_id, msg):
                            sent = True
                            # bookkeeping is keyed to the PEER's round
                            # (reactor.go:545 SetHasProposalBlockPart(prs...))
                            # — with rs.round a round-lagged peer's bit
                            # would never set and the part resend forever
                            ps.set_has_proposal_block_part(prs.height, prs.round, idx)
            return sent
        # catchup: peer is behind — serve committed block parts from the
        # store (reactor.go:556 gossipDataForCatchup)
        bs = self._block_store
        if (
            bs is not None
            and 0 < prs.height < rs.height
            and bs.base() <= prs.height <= bs.height()
        ):
            meta = bs.load_block_meta(prs.height)
            if meta is None:
                return sent
            psh = meta.block_id.part_set_header
            # Only serve parts once the peer advertises the matching part
            # set header (via its NewValidBlock after entering commit) —
            # before that its consensus state would drop them
            # (reactor.go:556 gossipDataForCatchup checks exactly this).
            if (
                prs.proposal_block_part_set_header != psh
                or prs.proposal_block_parts is None
            ):
                return sent
            have = BitArray(max(psh.total, 1))
            for i in range(psh.total):
                have.set_index(i, True)
            missing = have.sub(prs.proposal_block_parts)
            idxs = missing.get_true_indices()
            if not idxs:
                return sent
            idx = idxs[0]
            part = bs.load_block_part(prs.height, idx)
            if part is None:
                return sent
            msg = _wrap(2, _encode_block_part(prs.height, prs.round, part))
            if self._data_ch.send(ps.peer_id, msg):
                ps.set_has_proposal_block_part(prs.height, prs.round, idx)
                sent = True
        return sent

    def _send_vote(self, ps: PeerState, vote: Optional[Vote]) -> bool:
        if vote is None:
            return False
        w = ProtoWriter()
        w.write_message(1, vote.encode(), always=True)
        if self._vote_ch.send(ps.peer_id, w.bytes()):
            ps.set_has_vote(vote.height, vote.round, vote.type, vote.validator_index)
            return True
        return False

    def _gossip_votes(self, ps: PeerState) -> bool:
        """reactor.go:715 gossipVotesRoutine (one iteration): send ONE vote
        this peer is missing, chosen in the reference's priority order.
        Returns True when a vote was sent (the dirty-sweep progress
        signal)."""
        rs = self._cs.rs
        prs = ps.snapshot()
        hvs = rs.votes
        if prs.height == rs.height and hvs is not None:
            # gossipVotesForHeight (reactor.go:616-713)
            if prs.step == STEP_NEW_HEIGHT and rs.last_commit is not None:
                if self._send_vote(ps, ps.pick_vote_to_send(rs.last_commit)):
                    return True
            if (
                prs.step <= STEP_PROPOSE
                and 0 <= prs.round <= rs.round
                and prs.proposal_pol_round >= 0
            ):
                if self._send_vote(
                    ps, ps.pick_vote_to_send(hvs.prevotes(prs.proposal_pol_round))
                ):
                    return True
            if prs.step <= STEP_PREVOTE_WAIT and 0 <= prs.round <= rs.round:
                if self._send_vote(ps, ps.pick_vote_to_send(hvs.prevotes(prs.round))):
                    return True
            if prs.step <= STEP_PRECOMMIT_WAIT and 0 <= prs.round <= rs.round:
                if self._send_vote(ps, ps.pick_vote_to_send(hvs.precommits(prs.round))):
                    return True
            if 0 <= prs.round <= rs.round:
                if self._send_vote(ps, ps.pick_vote_to_send(hvs.prevotes(prs.round))):
                    return True
            if prs.proposal_pol_round >= 0:
                return self._send_vote(
                    ps, ps.pick_vote_to_send(hvs.prevotes(prs.proposal_pol_round))
                )
            return False
        # peer is exactly one height behind: our last commit's precommits
        # are its current height's votes (reactor.go:741-748)
        if prs.height != 0 and rs.height == prs.height + 1 and rs.last_commit is not None:
            if self._send_vote(ps, ps.pick_vote_to_send(rs.last_commit)):
                return True
        # peer is further behind: reconstruct precommits from the stored
        # commit at its height (reactor.go:750-777)
        bs = self._block_store
        if (
            bs is not None
            and prs.height != 0
            and rs.height >= prs.height + 2
            and bs.base() <= prs.height <= bs.height()
        ):
            commit = bs.load_block_commit(prs.height)
            if commit is not None:
                vote = ps.pick_commit_vote_to_send(commit)
                if vote is not None and self._send_vote(ps, vote):
                    ps.set_has_catchup_commit_vote(prs.height, commit.round, vote.validator_index)
                    return True
        return False

    def _query_maj23(self, ps: PeerState) -> None:
        """reactor.go:797 queryMaj23Routine (one iteration)."""
        rs = self._cs.rs
        prs = ps.snapshot()
        hvs = rs.votes
        if hvs is None or prs.height != rs.height:
            return
        probes = [
            (rs.round, PREVOTE_TYPE, hvs.prevotes(rs.round)),
            (rs.round, PRECOMMIT_TYPE, hvs.precommits(rs.round)),
        ]
        if prs.proposal_pol_round >= 0:
            probes.append(
                (prs.proposal_pol_round, PREVOTE_TYPE, hvs.prevotes(prs.proposal_pol_round))
            )
        for round_, type_, vs in probes:
            if vs is None:
                continue
            block_id, ok = vs.two_thirds_majority()
            if not ok:
                continue
            w = ProtoWriter()
            w.write_varint(1, rs.height)
            w.write_varint(2, round_)
            w.write_varint(3, type_)
            w.write_message(4, block_id.encode(), always=True)
            self._state_ch.send(ps.peer_id, _wrap(4, w.bytes()))

    # -- outbound (own messages) -----------------------------------------

    def _broadcast_own(self, msg) -> None:
        if isinstance(msg, ProposalMessage):
            w = ProtoWriter()
            w.write_message(1, msg.proposal.encode(), always=True)
            self._data_ch.broadcast(w.bytes())
            self._mark_all_dirty()
        elif isinstance(msg, BlockPartMessage):
            w = ProtoWriter()
            w.write_message(2, _encode_block_part(msg.height, msg.round, msg.part), always=True)
            self._data_ch.broadcast(w.bytes())
            self._mark_all_dirty()
        elif isinstance(msg, VoteMessage):
            # own votes reach add_vote like any other — the vote_added
            # hook (_broadcast_has_vote) does the targeted dirty marking
            w = ProtoWriter()
            w.write_message(1, msg.vote.encode(), always=True)
            self._vote_ch.broadcast(w.bytes())

    # -- inbound --------------------------------------------------------

    def handle_envelope(self, env) -> bool:
        """Dispatch one inbound envelope to its channel handler; bad peer
        messages are swallowed (the router would ban). Shared by the
        threaded _process loops and the simnet's synchronous delivery."""
        handler = self._handlers.get(env.channel_id)
        if handler is None:
            return False
        try:
            handler(env)
        except (ValueError, KeyError):
            return False  # bad peer message: ignore (router would ban)
        return True

    def _process(self, ch) -> None:
        while not self._stopped.is_set():
            try:
                env = ch.receive(timeout=0.5)
            except _q.Empty:
                continue
            self.handle_envelope(env)

    def _handle_data(self, env) -> None:
        """reactor.go:1087 handleDataMessage."""
        f = decode_message(env.message)
        ps = self._get_peer(env.from_id)
        if 1 in f:
            proposal = Proposal.decode(field_bytes(f, 1))
            ps.apply_proposal(proposal)
            self._cs.set_proposal(proposal, peer_id=env.from_id)
            self._mark_all_dirty()  # we may now relay the proposal
        elif 2 in f:
            bp = decode_message(field_bytes(f, 2))
            height = to_signed64(field_int(bp, 1))
            round_ = to_signed32(field_int(bp, 2))
            part = Part.decode(field_bytes(bp, 3))
            ps.set_has_proposal_block_part(height, round_, part.index)
            self._cs.add_block_part(height, round_, part, peer_id=env.from_id)
            self._mark_all_dirty()  # a part we hold is a part we can serve
        elif 3 in f:
            pol = decode_message(field_bytes(f, 3))
            ps.apply_proposal_pol(
                to_signed64(field_int(pol, 1)),
                to_signed32(field_int(pol, 2)),
                BitArray.decode(field_bytes(pol, 3)),
            )
            self._mark_dirty(env.from_id)  # POL prevotes became sendable

    def _handle_vote(self, env) -> None:
        f = decode_message(env.message)
        if 1 in f:
            vote = Vote.decode(field_bytes(f, 1))
            ps = self._get_peer(env.from_id)
            ps.ensure_vote_bit_arrays(
                vote.height,
                len(self._cs.rs.validators.validators)
                if self._cs.rs.validators is not None
                else 0,
            )
            ps.set_has_vote(vote.height, vote.round, vote.type, vote.validator_index)
            self._cs.add_vote_msg(vote, peer_id=env.from_id)

    def _handle_state(self, env) -> None:
        """reactor.go:1261 handleStateMessage: NewRoundStep / HasVote /
        VoteSetMaj23 bookkeeping."""
        f = decode_message(env.message)
        ps = self._get_peer(env.from_id)
        if 1 in f:  # NewRoundStep
            r = decode_message(field_bytes(f, 1))
            ps.apply_new_round_step(
                to_signed64(field_int(r, 1)),
                to_signed32(field_int(r, 2)),
                field_int(r, 3),
                to_signed32(field_int(r, 5)),
            )
            # the peer moved: it may need votes for its new round, or
            # catchup data if it announced a lagging height
            self._mark_dirty(env.from_id)
        elif 2 in f:  # NewValidBlock
            r = decode_message(field_bytes(f, 2))
            from ..types.block import PartSetHeader

            ps.apply_new_valid_block(
                to_signed64(field_int(r, 1)),
                to_signed32(field_int(r, 2)),
                PartSetHeader.decode(field_bytes(r, 3)),
                BitArray.decode(field_bytes(r, 4)),
                bool(field_int(r, 5)),
            )
            self._mark_dirty(env.from_id)  # it can now accept block parts
        elif 3 in f:  # HasVote
            r = decode_message(field_bytes(f, 3))
            rs = self._cs.rs
            if rs.validators is not None:
                ps.ensure_vote_bit_arrays(
                    to_signed64(field_int(r, 1)), len(rs.validators.validators)
                )
            ps.apply_has_vote(
                to_signed64(field_int(r, 1)),
                to_signed32(field_int(r, 2)),
                field_int(r, 3),
                field_int(r, 4),
            )
        elif 5 in f:  # HasVoteBits (ISSUE 15 coalesced HasVote summary)
            r = decode_message(field_bytes(f, 5))
            height = to_signed64(field_int(r, 1))
            rs = self._cs.rs
            if rs.validators is not None:
                ps.ensure_vote_bit_arrays(
                    height, len(rs.validators.validators)
                )
            ps.apply_has_vote_bits(
                height,
                to_signed32(field_int(r, 2)),
                field_int(r, 3),
                BitArray.decode(field_bytes(r, 4)),
            )
        elif 4 in f:  # VoteSetMaj23 -> record + respond with VoteSetBits
            r = decode_message(field_bytes(f, 4))
            height = to_signed64(field_int(r, 1))
            round_ = to_signed32(field_int(r, 2))
            type_ = field_int(r, 3)
            block_id = BlockID.decode(field_bytes(r, 4))
            rs = self._cs.rs
            if rs.height != height or rs.votes is None:
                return
            try:
                rs.votes.set_peer_maj23(round_, type_, env.from_id, block_id)
            except ValueError:
                return
            vs = (
                rs.votes.prevotes(round_)
                if type_ == PREVOTE_TYPE
                else rs.votes.precommits(round_)
            )
            if vs is None:
                return
            bits = vs.bit_array_by_block_id(block_id)
            if bits is None:
                bits = BitArray(len(vs.votes))
            w = ProtoWriter()
            w.write_varint(1, height)
            w.write_varint(2, round_)
            w.write_varint(3, type_)
            w.write_message(4, block_id.encode(), always=True)
            w.write_message(5, bits.encode(), always=True)
            self._vsb_ch.send(env.from_id, _wrap(1, w.bytes()))

    def _handle_vsb(self, env) -> None:
        """reactor.go:1374 handleVoteSetBitsMessage."""
        f = decode_message(env.message)
        if 1 not in f:
            return
        r = decode_message(field_bytes(f, 1))
        height = to_signed64(field_int(r, 1))
        round_ = to_signed32(field_int(r, 2))
        type_ = field_int(r, 3)
        block_id = BlockID.decode(field_bytes(r, 4))
        bits = BitArray.decode(field_bytes(r, 5))
        ps = self._get_peer(env.from_id)
        rs = self._cs.rs
        our_votes = None
        if rs.height == height and rs.votes is not None:
            vs = (
                rs.votes.prevotes(round_)
                if type_ == PREVOTE_TYPE
                else rs.votes.precommits(round_)
            )
            if vs is not None:
                our_votes = vs.bit_array_by_block_id(block_id)
        ps.apply_vote_set_bits(height, round_, type_, bits, our_votes)
        self._mark_dirty(env.from_id)  # its bit gaps are sendable work
