"""Consensus reactor — gossips consensus messages over p2p channels.

Reference parity: internal/consensus/reactor.go — channels State (0x20),
Data (0x21), Vote (0x22), VoteSetBits (0x23) with the reference's channel
priorities (reactor.go:32-73). The node's own proposals/parts/votes flow
out through the ConsensusState broadcast seam; incoming envelopes are
decoded and fed into the state machine's queues.

Round-1 scope note: this reactor broadcasts and relays within a connected
mesh (NewRoundStep/HasVote bookkeeping and the per-peer catchup gossip
routines of reactor.go:503-797 land with blocksync integration).
"""

from __future__ import annotations

import threading
from typing import Optional

from ..p2p.conn.mconnection import ChannelDescriptor
from ..p2p.router import Router
from ..types.part_set import Part
from ..types.proposal import Proposal
from ..types.vote import Vote
from ..wire.proto import ProtoWriter, decode_message, field_bytes, field_int
from .state import BlockPartMessage, ConsensusState, ProposalMessage, VoteMessage

STATE_CHANNEL = 0x20
DATA_CHANNEL = 0x21
VOTE_CHANNEL = 0x22
VOTE_SET_BITS_CHANNEL = 0x23

STATE_DESC = ChannelDescriptor(id=STATE_CHANNEL, priority=8, send_queue_capacity=64)
DATA_DESC = ChannelDescriptor(id=DATA_CHANNEL, priority=12, send_queue_capacity=64)
VOTE_DESC = ChannelDescriptor(id=VOTE_CHANNEL, priority=10, send_queue_capacity=64)
VOTE_SET_BITS_DESC = ChannelDescriptor(id=VOTE_SET_BITS_CHANNEL, priority=5)

ALL_DESCS = [STATE_DESC, DATA_DESC, VOTE_DESC, VOTE_SET_BITS_DESC]


def _encode_block_part(height: int, round_: int, part: Part) -> bytes:
    w = ProtoWriter()
    w.write_varint(1, height)
    w.write_varint(2, round_)
    w.write_message(3, part.encode(), always=True)
    return w.bytes()


class ConsensusReactor:
    """reactor.go:100-300 (mesh-broadcast variant)."""

    def __init__(self, cs: ConsensusState, router: Router):
        self._cs = cs
        self._router = router
        self._data_ch = router.open_channel(DATA_DESC)
        self._vote_ch = router.open_channel(VOTE_DESC)
        self._state_ch = router.open_channel(STATE_DESC)
        self._vsb_ch = router.open_channel(VOTE_SET_BITS_DESC)
        self._stopped = threading.Event()
        self._threads = []
        cs.broadcast_hooks.append(self._broadcast_own)

    def start(self) -> None:
        for ch, handler in (
            (self._data_ch, self._handle_data),
            (self._vote_ch, self._handle_vote),
            (self._state_ch, self._handle_state),
        ):
            t = threading.Thread(target=self._process, args=(ch, handler), daemon=True)
            t.start()
            self._threads.append(t)
        t = threading.Thread(target=self._gossip_routine, daemon=True)
        t.start()
        self._threads.append(t)

    def stop(self) -> None:
        self._stopped.set()

    # -- catchup gossip (reactor.go:503 gossipDataRoutine + :715
    # gossipVotesRoutine, mesh-rebroadcast variant): periodically re-send
    # the current round's proposal/parts/votes and the last commit's
    # precommits so peers that missed messages (disconnect, late join,
    # round skew) converge; receivers dedup, so this is idempotent. --------

    GOSSIP_INTERVAL = 0.3

    def _gossip_routine(self) -> None:
        import time as _t

        while not self._stopped.is_set():
            _t.sleep(self.GOSSIP_INTERVAL)
            try:
                self._gossip_once()
            except Exception:  # noqa: BLE001 — gossip must never die
                continue

    def _gossip_once(self) -> None:
        rs = self._cs.rs
        if rs.proposal is not None:
            w = ProtoWriter()
            w.write_message(1, rs.proposal.encode(), always=True)
            self._data_ch.broadcast(w.bytes())
        parts = rs.proposal_block_parts
        if parts is not None:
            for i in range(parts.total()):
                p = parts.get_part(i)
                if p is not None:
                    w = ProtoWriter()
                    w.write_message(
                        2, _encode_block_part(rs.height, rs.round, p), always=True
                    )
                    self._data_ch.broadcast(w.bytes())
        votes = []
        hvs = rs.votes
        if hvs is not None:
            for r in {max(rs.round - 1, 0), rs.round}:
                for vs in (hvs.prevotes(r), hvs.precommits(r)):
                    if vs is not None:
                        votes.extend(v for v in vs.votes if v is not None)
        if rs.last_commit is not None:
            votes.extend(v for v in rs.last_commit.votes if v is not None)
        for v in votes:
            w = ProtoWriter()
            w.write_message(1, v.encode(), always=True)
            self._vote_ch.broadcast(w.bytes())

    # -- outbound -------------------------------------------------------

    def _broadcast_own(self, msg) -> None:
        if isinstance(msg, ProposalMessage):
            w = ProtoWriter()
            w.write_message(1, msg.proposal.encode(), always=True)
            self._data_ch.broadcast(w.bytes())
        elif isinstance(msg, BlockPartMessage):
            w = ProtoWriter()
            w.write_message(2, _encode_block_part(msg.height, msg.round, msg.part), always=True)
            self._data_ch.broadcast(w.bytes())
        elif isinstance(msg, VoteMessage):
            w = ProtoWriter()
            w.write_message(1, msg.vote.encode(), always=True)
            self._vote_ch.broadcast(w.bytes())

    # -- inbound --------------------------------------------------------

    def _process(self, ch, handler) -> None:
        import queue as _q

        while not self._stopped.is_set():
            try:
                env = ch.receive(timeout=0.5)
            except _q.Empty:
                continue
            try:
                handler(env)
            except (ValueError, KeyError):
                continue  # bad peer message: ignore (router would ban)

    def _handle_data(self, env) -> None:
        """reactor.go:1261+ channel processors (Data)."""
        f = decode_message(env.message)
        if 1 in f:
            proposal = Proposal.decode(field_bytes(f, 1))
            self._cs.set_proposal(proposal, peer_id=env.from_id)
        elif 2 in f:
            bp = decode_message(field_bytes(f, 2))
            self._cs.add_block_part(
                field_int(bp, 1),
                field_int(bp, 2),
                Part.decode(field_bytes(bp, 3)),
                peer_id=env.from_id,
            )

    def _handle_vote(self, env) -> None:
        f = decode_message(env.message)
        if 1 in f:
            vote = Vote.decode(field_bytes(f, 1))
            self._cs.add_vote_msg(vote, peer_id=env.from_id)

    def _handle_state(self, env) -> None:
        pass  # NewRoundStep/HasVote bookkeeping (catchup gossip, later round)
