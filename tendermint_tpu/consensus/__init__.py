"""tendermint_tpu.consensus — the BFT consensus engine (reference
internal/consensus/, L7)."""

from .state import (  # noqa: F401
    BlockPartMessage,
    ConsensusState,
    ProposalMessage,
    VoteMessage,
)
from .ticker import TimeoutInfo, TimeoutTicker  # noqa: F401
from .types import HeightVoteSet, RoundState  # noqa: F401
from .wal import WAL, WALMessage  # noqa: F401
