"""Live-vote ingress — device-batched VoteSet.add_vote (ISSUE 15).

The paper's named hot path is types.VoteSet.AddVote → per-vote signature
verification. Commits, light headers, tx ingress and replay ranges all
ride the device pipeline already; live gossiped prevotes/precommits were
the last one-at-a-time host path. This module micro-windows them:

  ConsensusState host stage (HeightVoteSet.check_vote — index/address/
  step checks, duplicate + non-deterministic-signature detection, the
  ErrVoteConflictingVotes machinery all run BEFORE dispatch)
      │ CheckedVote
      ▼
  VoteIngress.submit(PendingVote)
      ├─ signature-memo consult (PR-6 _SigMemo): a re-gossiped vote
      │  whose (pub, msg, sig) verdict is memoized never re-dispatches
      ├─ in-window duplicate drop: the same signature already queued in
      │  an open window is dropped (sequential processing would apply
      │  the first copy and return False for the second — no observable
      │  difference, one device lane saved)
      └─ window keyed by (height, valset epoch): flushed as ONE
         EntryBlock (val_idx + epoch_key attached when the epoch cache
         is warm) into the SHARED AsyncBatchVerifier at CONSENSUS
         priority — same-round votes from many peers cross-coalesce in
         the pipeline's coalescer (mesh lanes when TM_TPU_MESH)
      ▼
  apply callback (ENQUEUE-ONLY: puts verdict messages on the consensus
  state's own queue) → the consensus pump applies verdicts in
  deterministic submission order via VoteSet.apply_vote_verdict.

Fallbacks — a window verifies on the HOST (via crypto.ed25519.
verify_zip215_fast, which the simnet _SigMemo wraps) when it is smaller
than types.validation.BATCH_VERIFY_THRESHOLD, when no engine is
attached, or in stepped mode. A DispatchError poisons ONLY its own
window: those votes are handed back with the error and the consensus
state re-drives each one through the sequential per-vote path.

Stepped/simnet mode: no threads. Votes accumulate until the consensus
pump drains its queue; ConsensusState.process_pending then calls
flush_pending(), which host-verifies every open window in submission
order and applies inline — flush points are a pure function of message
arrival, so cluster runs stay replay-exact.

Threading (threaded mode): the shared ingress fabric's one scheduler
flushes the lane. Verifier done-callbacks run on the pipeline resolver
thread and ONLY enqueue — the apply callback must never take consensus
locks (ConsensusState's is queue.put + wake, both lock-free from the
resolver's perspective).

Since ISSUE 17 the windowing machinery lives in ops/ingress.py (the
one ingress fabric): this module keeps the vote-shaped host stage —
memo consult, (height, epoch) window keys, val_idx attachment — as a
LaneSpec plus callbacks. Knobs: TM_TPU_INGRESS_VOTES_BATCH (default
128 sigs) and TM_TPU_INGRESS_VOTES_WINDOW_MS (default 2 ms); legacy
TM_TPU_VOTE_BATCH / TM_TPU_VOTE_WINDOW_MS still honored with a
DeprecationWarning.
"""

from __future__ import annotations

import weakref
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..crypto import ed25519 as _ed
from ..observability import trace as _trace
from ..types.validation import BATCH_VERIFY_THRESHOLD

DEFAULT_BATCH = 128
DEFAULT_WINDOW_MS = 2.0

# apply callback: (batch, verdicts, error) — verdicts is None iff error
# is set. MUST be enqueue-only (runs on resolver/flusher threads).
ApplyFn = Callable[[List["PendingVote"], Optional[Sequence[bool]],
                    Optional[BaseException]], None]


class PendingVote:
    """One checked, not-yet-verified vote riding a window."""

    __slots__ = ("vote", "peer_id", "flow", "pub", "msg", "t_enq")

    def __init__(self, vote, peer_id: str, pub: bytes, msg: bytes,
                 flow: Optional[int] = None, t_enq: float = 0.0):
        self.vote = vote
        self.peer_id = peer_id
        self.flow = flow
        self.pub = pub        # 32-byte ed25519 key (valset row bytes)
        self.msg = msg        # sign bytes under the chain id
        self.t_enq = t_enq


def memo_verdict(pub: bytes, msg: bytes, sig: bytes) -> Optional[bool]:
    """Consult the PR-6 signature memo WITHOUT computing: when simnet's
    _SigMemo wraps crypto.ed25519.verify_zip215_fast, its cache dict is
    duck-typed here. Read-only — a miss never populates (the device or
    host verify that follows will, through the memo's own __call__)."""
    cache = getattr(_ed.verify_zip215_fast, "cache", None)
    if cache is None:
        return None
    v = cache.get((pub, msg, sig))
    return None if v is None else bool(v)


# live accumulators for /status aggregation (rpc/core.py)
_ACTIVE: "weakref.WeakSet[VoteIngress]" = weakref.WeakSet()


def vote_ingress_stats() -> dict:
    """Aggregate snapshot over every live vote accumulator in the
    process — the /status `vote_ingress` section."""
    accs = list(_ACTIVE)
    if not accs:
        return {"enabled": False}
    out: Dict[str, float] = {
        "enabled": True, "queue_depth": 0, "batches": 0, "sigs": 0,
        "memo_hits": 0, "window_dups": 0, "sync_fallbacks": 0,
        "preemptions": 0, "dispatch_errors": 0,
    }
    waits = []
    for a in accs:
        s = a.stats()
        for k in ("queue_depth", "batches", "sigs", "memo_hits",
                  "window_dups", "sync_fallbacks", "preemptions",
                  "dispatch_errors"):
            out[k] += s[k]
        if s["batch_wait_ms_avg"]:
            waits.append(s["batch_wait_ms_avg"])
    out["batch_wait_ms_avg"] = sum(waits) / len(waits) if waits else 0.0
    return out


class VoteIngress:
    """Window/size-batched live-vote signature verification — a `votes`
    lane on the shared ingress fabric (ops/ingress.py).

    submit(pend, val_set) queues one host-checked vote. Windows are
    keyed by (height, valset epoch) and flush as ONE EntryBlock to the
    shared verifier at PRIORITY_CONSENSUS after the lane's batch target
    or window elapses. Verdicts come back through the apply callback in
    window submission order; the callback only enqueues (see module
    docstring). The lane carries the consensus hot path's 5 ms p99
    budget: when adaptive, the deadline-aware flush fires early enough
    that submit + expected device service still fit it.

    stepped=True builds a threadless lane for simnet: nothing flushes
    until flush_pending() — called by the consensus pump when its queue
    drains — host-verifies and applies inline."""

    def __init__(self, apply_fn: ApplyFn, verifier=None,
                 max_batch: Optional[int] = None,
                 window_ms: Optional[float] = None,
                 stepped: bool = False, metrics=None):
        from ..ops import ingress as _fabric

        cfg = _fabric.resolve_lane_config(
            "votes", batch=max_batch, window_ms=window_ms,
            legacy_batch="TM_TPU_VOTE_BATCH",
            legacy_window="TM_TPU_VOTE_WINDOW_MS",
        )
        self._apply = apply_fn
        self.metrics = metrics
        self.memo_hits = 0
        self.apply_drops = 0    # consensus/state.py bumps this directly
        self._epoch_keys: Dict[Tuple, Optional[bytes]] = {}
        self._lane = _fabric.shared_engine().register(_fabric.LaneSpec(
            name="votes",
            priority=_fabric.PRIORITY_CONSENSUS,
            batch=cfg.batch,
            window_ms=cfg.window_ms,
            budget_ms=cfg.budget_ms,
            adaptive=cfg.adaptive,
            stepped=bool(stepped),
            full_by_window=True,     # size trigger per (height, epoch)
            device_threshold=BATCH_VERIFY_THRESHOLD,
            submit_error_to_host=True,  # host path is always available
            closed_msg="vote ingress is closed",
            verifier=verifier,
            entries_fn=lambda p: (p.pub, p.msg, p.vote.signature),
            attach_fn=self._attach,
            flow_fn=lambda p: p.flow,
            trace_fn=self._trace,
            host_fn=self._host_check,
            deliver=self._deliver,
            observer=self,
        ))
        _ACTIVE.add(self)

    @property
    def stepped(self) -> bool:
        return self._lane.spec.stepped

    # -- lane callbacks ---------------------------------------------------

    def _deliver(self, items, verdicts, err) -> None:
        """Hand one window's verdicts (or its error) to the apply
        callback — enqueue-only by contract, so the fabric may call this
        straight from the pipeline resolver thread."""
        self._apply([it.item for it in items], verdicts, err)

    def _host_check(self, batch: List[PendingVote]) -> List[bool]:
        """The sync fallback: verify on the host — through
        crypto.ed25519.verify_zip215_fast so simnet's _SigMemo memoizes
        the verdicts."""
        return [
            bool(_ed.verify_zip215_fast(p.pub, p.msg, p.vote.signature))
            for p in batch
        ]

    @staticmethod
    def _attach(block, key: Tuple, batch: List[PendingVote]) -> None:
        """Warm-epoch windows carry val_idx + epoch_key so kernels
        gather A on device (key[1] is the epoch key iff warm)."""
        ek = key[1] if isinstance(key[1], bytes) else None
        if ek is not None:
            block.val_idx = np.array(
                [p.vote.validator_index for p in batch], dtype=np.int32
            )
            block.epoch_key = ek

    @staticmethod
    def _trace(batch: List[PendingVote], flow: int) -> None:
        if _trace.TRACER.enabled:
            _trace.TRACER.flow_point(
                "vote_ingress.flush", flow, "t",
                n=len(batch), height=batch[0].vote.height,
            )

    # -- legacy metric mirror (fabric observer) ---------------------------

    def _metrics(self):
        if self.metrics is None:
            from ..libs import metrics as _m

            self.metrics = _m.vote_ingress_metrics()
        return self.metrics

    def flush(self, n: int, wait_ms: float) -> None:
        try:
            m = self._metrics()
            m.batches.inc()
            m.batch_sigs.inc(n)
            m.batch_wait_ms.observe(wait_ms)
        except Exception:  # noqa: BLE001 — observability never fatal
            pass

    def sync_fallback(self) -> None:
        try:
            self._metrics().sync_fallbacks.inc()
        except Exception:  # noqa: BLE001
            pass

    def dispatch_error(self) -> None:
        try:
            self._metrics().dispatch_errors.inc()
        except Exception:  # noqa: BLE001
            pass

    # -- submission -------------------------------------------------------

    def submit(self, pend: PendingVote, val_set) -> None:
        """Queue one host-checked vote. The verdict reaches the apply
        callback later (possibly immediately, on a memo hit)."""
        if self._lane._closed:
            raise RuntimeError("vote ingress is closed")
        vote = pend.vote
        sig = vote.signature
        hit = memo_verdict(pend.pub, pend.msg, sig)
        if hit is not None:
            self.memo_hits += 1
            try:
                self._metrics().memo_hits.inc()
            except Exception:  # noqa: BLE001 — observability never fatal
                pass
            self._apply([pend], [hit], None)
            return
        dkey = (vote.height, vote.round, vote.type,
                vote.validator_index, sig)
        self._lane.submit(pend, key=self._window_key(vote.height, val_set),
                          dedup_key=dkey, t_enq=pend.t_enq or None)

    def _window_key(self, height: int, val_set) -> Tuple:
        """(height, epoch key) when the epoch cache knows this valset
        (warm windows attach val_idx so kernels gather A on device);
        (height, id(valset)) cold — still coalesces same-valset votes,
        never fuses rows from different tables."""
        vkey = (height, id(val_set))
        ek = self._epoch_keys.get(vkey)
        if ek is None and vkey not in self._epoch_keys:
            try:
                from ..ops import epoch_cache as _epoch

                ek = _epoch.note_valset(val_set)
            except Exception:  # noqa: BLE001 — cache is an optimization
                ek = None
            self._epoch_keys[vkey] = ek
            if len(self._epoch_keys) > 64:
                self._epoch_keys.clear()
                self._epoch_keys[vkey] = ek
        return (height, ek) if ek is not None else vkey

    def flush_now(self) -> None:
        self._lane.flush_now()

    def flush_pending(self) -> bool:
        """Stepped-mode flush point (ConsensusState.process_pending when
        its queue drains): host-verify every open window in submission
        order and apply inline. Returns True when anything flushed —
        the pump then re-drains its queue for the verdict messages."""
        return self._lane.flush_pending()

    # -- lifecycle / introspection ----------------------------------------

    def stats(self) -> dict:
        s = self._lane.stats()
        return {
            "queue_depth": s["queue_depth"],
            "batches": s["batches"],
            "sigs": s["sigs"],
            "memo_hits": self.memo_hits,
            "window_dups": s["window_dups"],
            "sync_fallbacks": s["sync_fallbacks"],
            "batch_wait_ms_avg": s["batch_wait_ms_avg"],
            "preemptions": s["preemptions"],
            "dispatch_errors": s["dispatch_errors"],
            "apply_drops": self.apply_drops,
            "max_batch": s["max_batch"],
            "window_ms": s["window_ms"],
            "stepped": s["stepped"],
        }

    def close(self, timeout: float = 10.0) -> None:
        self._lane.close(timeout=timeout)
