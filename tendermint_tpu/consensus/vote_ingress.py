"""Live-vote ingress — device-batched VoteSet.add_vote (ISSUE 15).

The paper's named hot path is types.VoteSet.AddVote → per-vote signature
verification. Commits, light headers, tx ingress and replay ranges all
ride the device pipeline already; live gossiped prevotes/precommits were
the last one-at-a-time host path. This module micro-windows them:

  ConsensusState host stage (HeightVoteSet.check_vote — index/address/
  step checks, duplicate + non-deterministic-signature detection, the
  ErrVoteConflictingVotes machinery all run BEFORE dispatch)
      │ CheckedVote
      ▼
  VoteIngress.submit(PendingVote)
      ├─ signature-memo consult (PR-6 _SigMemo): a re-gossiped vote
      │  whose (pub, msg, sig) verdict is memoized never re-dispatches
      ├─ in-window duplicate drop: the same signature already queued in
      │  an open window is dropped (sequential processing would apply
      │  the first copy and return False for the second — no observable
      │  difference, one device lane saved)
      └─ window keyed by (height, valset epoch): flushed as ONE
         EntryBlock (val_idx + epoch_key attached when the epoch cache
         is warm) into the SHARED AsyncBatchVerifier at CONSENSUS
         priority — same-round votes from many peers cross-coalesce in
         the pipeline's coalescer (mesh lanes when TM_TPU_MESH)
      ▼
  apply callback (ENQUEUE-ONLY: puts verdict messages on the consensus
  state's own queue) → the consensus pump applies verdicts in
  deterministic submission order via VoteSet.apply_vote_verdict.

Fallbacks — a window verifies on the HOST (via crypto.ed25519.
verify_zip215_fast, which the simnet _SigMemo wraps) when it is smaller
than types.validation.BATCH_VERIFY_THRESHOLD, when no engine is
attached, or in stepped mode. A DispatchError poisons ONLY its own
window: those votes are handed back with the error and the consensus
state re-drives each one through the sequential per-vote path.

Stepped/simnet mode: no threads. Votes accumulate until the consensus
pump drains its queue; ConsensusState.process_pending then calls
flush_pending(), which host-verifies every open window in submission
order and applies inline — flush points are a pure function of message
arrival, so cluster runs stay replay-exact.

Threading (threaded mode): one flusher thread per accumulator. Verifier
done-callbacks run on the pipeline resolver thread and ONLY enqueue —
the apply callback must never take consensus locks (ConsensusState's is
queue.put + wake, both lock-free from the resolver's perspective).

Knobs: TM_TPU_VOTE_BATCH (default 128 sigs) and TM_TPU_VOTE_WINDOW_MS
(default 2 ms).
"""

from __future__ import annotations

import os
import threading
import time
import weakref
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..crypto import ed25519 as _ed
from ..observability import trace as _trace
from ..types.validation import BATCH_VERIFY_THRESHOLD

DEFAULT_BATCH = 128
DEFAULT_WINDOW_MS = 2.0

# apply callback: (batch, verdicts, error) — verdicts is None iff error
# is set. MUST be enqueue-only (runs on resolver/flusher threads).
ApplyFn = Callable[[List["PendingVote"], Optional[Sequence[bool]],
                    Optional[BaseException]], None]


class PendingVote:
    """One checked, not-yet-verified vote riding a window."""

    __slots__ = ("vote", "peer_id", "flow", "pub", "msg", "t_enq")

    def __init__(self, vote, peer_id: str, pub: bytes, msg: bytes,
                 flow: Optional[int] = None, t_enq: float = 0.0):
        self.vote = vote
        self.peer_id = peer_id
        self.flow = flow
        self.pub = pub        # 32-byte ed25519 key (valset row bytes)
        self.msg = msg        # sign bytes under the chain id
        self.t_enq = t_enq


def memo_verdict(pub: bytes, msg: bytes, sig: bytes) -> Optional[bool]:
    """Consult the PR-6 signature memo WITHOUT computing: when simnet's
    _SigMemo wraps crypto.ed25519.verify_zip215_fast, its cache dict is
    duck-typed here. Read-only — a miss never populates (the device or
    host verify that follows will, through the memo's own __call__)."""
    cache = getattr(_ed.verify_zip215_fast, "cache", None)
    if cache is None:
        return None
    v = cache.get((pub, msg, sig))
    return None if v is None else bool(v)


# live accumulators for /status aggregation (rpc/core.py)
_ACTIVE: "weakref.WeakSet[VoteIngress]" = weakref.WeakSet()


def vote_ingress_stats() -> dict:
    """Aggregate snapshot over every live vote accumulator in the
    process — the /status `vote_ingress` section."""
    accs = list(_ACTIVE)
    if not accs:
        return {"enabled": False}
    out: Dict[str, float] = {
        "enabled": True, "queue_depth": 0, "batches": 0, "sigs": 0,
        "memo_hits": 0, "window_dups": 0, "sync_fallbacks": 0,
        "preemptions": 0, "dispatch_errors": 0,
    }
    waits = []
    for a in accs:
        s = a.stats()
        for k in ("queue_depth", "batches", "sigs", "memo_hits",
                  "window_dups", "sync_fallbacks", "preemptions",
                  "dispatch_errors"):
            out[k] += s[k]
        if s["batch_wait_ms_avg"]:
            waits.append(s["batch_wait_ms_avg"])
    out["batch_wait_ms_avg"] = sum(waits) / len(waits) if waits else 0.0
    return out


class VoteIngress:
    """Window/size-batched live-vote signature verification.

    submit(pend, val_set) queues one host-checked vote. Windows are
    keyed by (height, valset epoch) and flush as ONE EntryBlock to the
    shared verifier at PRIORITY_CONSENSUS after `max_batch` votes or
    `window_ms` past the oldest entry. Verdicts come back through the
    apply callback in window submission order; the callback only
    enqueues (see module docstring).

    stepped=True builds a threadless accumulator for simnet: nothing
    flushes until flush_pending() — called by the consensus pump when
    its queue drains — host-verifies and applies inline."""

    def __init__(self, apply_fn: ApplyFn, verifier=None,
                 max_batch: Optional[int] = None,
                 window_ms: Optional[float] = None,
                 stepped: bool = False, metrics=None):
        if max_batch is None:
            max_batch = int(os.environ.get("TM_TPU_VOTE_BATCH",
                                           DEFAULT_BATCH))
        if window_ms is None:
            window_ms = float(os.environ.get("TM_TPU_VOTE_WINDOW_MS",
                                             DEFAULT_WINDOW_MS))
        self._apply = apply_fn
        self._max = max(int(max_batch), 1)
        self._window_s = max(float(window_ms), 0.0) / 1000.0
        self._stepped = bool(stepped)
        self._v = verifier
        self._v_hooked = False
        self.metrics = metrics
        self._mtx = threading.Lock()
        # (height, epoch-or-cold key) → [PendingVote]; insertion-ordered
        # so stepped flushes replay in submission order
        self._windows: Dict[Tuple, List[PendingVote]] = {}
        self._inwindow: set = set()   # (h, r, type, idx, sig) dedup keys
        self._epoch_keys: Dict[Tuple, Optional[bytes]] = {}
        self._depth = 0
        self._t_first = 0.0
        self._wake = threading.Event()
        self._full = threading.Event()
        self._inflight = 0
        self._stopped = threading.Event()
        # counters (read via stats(); the metrics set mirrors them)
        self.batches = 0
        self.sigs = 0
        self.memo_hits = 0
        self.window_dups = 0
        self.sync_fallbacks = 0
        self.preempted = 0
        self.dispatch_errors = 0
        self.apply_drops = 0
        self._wait_ms_sum = 0.0
        self._thread: Optional[threading.Thread] = None
        if not self._stepped:
            self._thread = threading.Thread(
                target=self._flusher, daemon=True, name="vote-ingress-flush"
            )
            self._thread.start()
        _ACTIVE.add(self)

    @property
    def stepped(self) -> bool:
        return self._stepped

    # -- wiring ----------------------------------------------------------

    def _metrics(self):
        if self.metrics is None:
            from ..libs import metrics as _m

            self.metrics = _m.vote_ingress_metrics()
        return self.metrics

    def _ensure_verifier(self):
        if self._v is None:
            from ..ops import pipeline as _pl

            self._v = _pl.shared_verifier()
        if not self._v_hooked:
            self._v_hooked = True
            hook = getattr(self._v, "add_preempt_hook", None)
            if hook is not None:
                hook(self._note_preempt)
        return self._v

    def _note_preempt(self, n: int) -> None:
        self.preempted += n

    # -- submission ------------------------------------------------------

    def submit(self, pend: PendingVote, val_set) -> None:
        """Queue one host-checked vote. The verdict reaches the apply
        callback later (possibly immediately, on a memo hit)."""
        if self._stopped.is_set():
            raise RuntimeError("vote ingress is closed")
        vote = pend.vote
        sig = vote.signature
        hit = memo_verdict(pend.pub, pend.msg, sig)
        if hit is not None:
            self.memo_hits += 1
            try:
                self._metrics().memo_hits.inc()
            except Exception:  # noqa: BLE001 — observability never fatal
                pass
            self._apply([pend], [hit], None)
            return
        dkey = (vote.height, vote.round, vote.type,
                vote.validator_index, sig)
        full = False
        with self._mtx:
            if dkey in self._inwindow:
                self.window_dups += 1
                return
            wkey = self._window_key(vote.height, val_set)
            win = self._windows.get(wkey)
            if win is None:
                win = self._windows[wkey] = []
            if not self._depth:
                self._t_first = pend.t_enq or time.perf_counter()
            win.append(pend)
            self._inwindow.add(dkey)
            self._depth += 1
            if not self._stepped:
                full = (len(win) >= self._max or self._window_s <= 0.0)
        if full:
            self._full.set()
        if not self._stepped:
            self._wake.set()

    def _window_key(self, height: int, val_set) -> Tuple:
        """(height, epoch key) when the epoch cache knows this valset
        (warm windows attach val_idx so kernels gather A on device);
        (height, id(valset)) cold — still coalesces same-valset votes,
        never fuses rows from different tables."""
        vkey = (height, id(val_set))
        ek = self._epoch_keys.get(vkey)
        if ek is None and vkey not in self._epoch_keys:
            try:
                from ..ops import epoch_cache as _epoch

                ek = _epoch.note_valset(val_set)
            except Exception:  # noqa: BLE001 — cache is an optimization
                ek = None
            self._epoch_keys[vkey] = ek
            if len(self._epoch_keys) > 64:
                self._epoch_keys.clear()
                self._epoch_keys[vkey] = ek
        return (height, ek) if ek is not None else vkey

    def flush_now(self) -> None:
        if self._stepped:
            self.flush_pending()
        else:
            self._full.set()
            self._wake.set()

    # -- flusher (threaded mode) -----------------------------------------

    def _flusher(self) -> None:
        while True:
            with self._mtx:
                have = self._depth > 0
                t_first = self._t_first
            if not have:
                if self._stopped.is_set():
                    break
                self._wake.wait(0.05)
                self._wake.clear()
                continue
            if self._window_s > 0.0 and not self._stopped.is_set():
                remaining = t_first + self._window_s - time.perf_counter()
                if remaining > 0 and not self._full.is_set():
                    self._full.wait(remaining)
            self._full.clear()
            for key, batch in self._take_windows():
                self._flush_window(key, batch)

    def _take_windows(self) -> List[Tuple[Tuple, List[PendingVote]]]:
        with self._mtx:
            taken = list(self._windows.items())
            self._windows = {}
            self._inwindow.clear()
            self._depth = 0
            self._t_first = 0.0
        return taken

    def _note_flush(self, batch: List[PendingVote]) -> None:
        now = time.perf_counter()
        wait_ms = max(
            (now - min((p.t_enq or now) for p in batch)) * 1e3, 0.0
        )
        self.batches += 1
        self.sigs += len(batch)
        self._wait_ms_sum += wait_ms
        try:
            m = self._metrics()
            m.batches.inc()
            m.batch_sigs.inc(len(batch))
            m.batch_wait_ms.observe(wait_ms)
        except Exception:  # noqa: BLE001 — observability never fatal
            pass

    def _flush_window(self, key: Tuple, batch: List[PendingVote]) -> None:
        self._note_flush(batch)
        # sub-threshold windows stay on the host — unless the bench
        # force-device discipline is on (TM_TPU_FORCE_DEVICE, same as
        # types.validation): the per-vote baseline column must pay the
        # relay cost per launch, never quietly route to host crypto
        force = os.environ.get("TM_TPU_FORCE_DEVICE", "0") == "1"
        if self._stepped or (len(batch) < BATCH_VERIFY_THRESHOLD
                             and not force):
            self._host_verify(batch)
            return
        try:
            from ..ops import pipeline as _pl
            from ..ops.entry_block import EntryBlock

            block = EntryBlock.from_entries(
                [(p.pub, p.msg, p.vote.signature) for p in batch]
            )
            ek = key[1] if isinstance(key[1], bytes) else None
            if ek is not None:
                block.val_idx = np.array(
                    [p.vote.validator_index for p in batch], dtype=np.int32
                )
                block.epoch_key = ek
            flow = next((p.flow for p in batch if p.flow is not None), None)
            if flow is not None and _trace.TRACER.enabled:
                _trace.TRACER.flow_point(
                    "vote_ingress.flush", flow, "t",
                    n=len(batch), height=batch[0].vote.height,
                )
            with self._mtx:
                self._inflight += 1
            fut = self._ensure_verifier().submit(
                block, flow=flow, priority=_pl.PRIORITY_CONSENSUS
            )
        except Exception:  # noqa: BLE001 — engine absent or closed:
            # the host path is always available, so a window that could
            # not even be SUBMITTED verifies synchronously instead of
            # failing (only post-submit DispatchErrors poison a window)
            with self._mtx:
                self._inflight = max(self._inflight - 1, 0)
            self._host_verify(batch)
            return
        # done-callback runs on the pipeline resolver: the apply
        # callback is enqueue-only by contract, so calling it here is
        # safe and keeps verdict→apply latency at one queue hop
        fut.add_done_callback(
            lambda f, b=batch: self._on_device_done(b, f)
        )

    def _on_device_done(self, batch: List[PendingVote], fut) -> None:
        with self._mtx:
            self._inflight = max(self._inflight - 1, 0)
        err = fut.exception()
        if err is not None:
            # poisoned window: exactly these votes fall back; later
            # windows keep flowing
            self._deliver_error(batch, err)
            return
        try:
            verdicts = [bool(v) for v in np.asarray(fut.result())]
            self._apply(batch, verdicts, None)
        except Exception as e:  # noqa: BLE001
            self._deliver_error(batch, e)

    def _deliver_error(self, batch: List[PendingVote],
                       err: BaseException) -> None:
        self.dispatch_errors += 1
        try:
            self._metrics().dispatch_errors.inc()
        except Exception:  # noqa: BLE001
            pass
        self._apply(batch, None, err)

    def _host_verify(self, batch: List[PendingVote]) -> None:
        """The sync fallback: verify on the host — through
        crypto.ed25519.verify_zip215_fast so simnet's _SigMemo memoizes
        the verdicts — and apply."""
        self.sync_fallbacks += 1
        try:
            self._metrics().sync_fallbacks.inc()
        except Exception:  # noqa: BLE001
            pass
        verdicts = [
            bool(_ed.verify_zip215_fast(p.pub, p.msg, p.vote.signature))
            for p in batch
        ]
        self._apply(batch, verdicts, None)

    # -- stepped mode -----------------------------------------------------

    def flush_pending(self) -> bool:
        """Stepped-mode flush point (ConsensusState.process_pending when
        its queue drains): host-verify every open window in submission
        order and apply inline. Returns True when anything flushed —
        the pump then re-drains its queue for the verdict messages."""
        taken = self._take_windows()
        if not taken:
            return False
        for _key, batch in taken:
            self._note_flush(batch)
            self._host_verify(batch)
        return True

    # -- lifecycle / introspection ----------------------------------------

    def stats(self) -> dict:
        with self._mtx:
            depth = self._depth
        return {
            "queue_depth": depth,
            "batches": self.batches,
            "sigs": self.sigs,
            "memo_hits": self.memo_hits,
            "window_dups": self.window_dups,
            "sync_fallbacks": self.sync_fallbacks,
            "batch_wait_ms_avg": (
                self._wait_ms_sum / self.batches if self.batches else 0.0
            ),
            "preemptions": self.preempted,
            "dispatch_errors": self.dispatch_errors,
            "apply_drops": self.apply_drops,
            "max_batch": self._max,
            "window_ms": self._window_s * 1e3,
            "stepped": self._stepped,
        }

    def close(self, timeout: float = 10.0) -> None:
        self._stopped.set()
        self._wake.set()
        self._full.set()
        if self._thread is not None:
            self._thread.join(timeout=timeout)
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            with self._mtx:
                if self._inflight == 0:
                    break
            time.sleep(0.005)
