"""Timeout ticker — the consensus timer.

Reference parity: internal/consensus/ticker.go — one active timeout at a
time, scheduled timeouts for earlier (height, round, step) are ignored,
newer ones replace the pending timer (timeoutRoutine:80-130). Fired
timeouts are delivered through a callback into the receive loop's queue.

The timer source is injectable: by default timeouts ride a
threading.Timer (wall clock); a simulation clock (simnet.clock.SimClock)
can be passed instead, in which case timeouts fire at *virtual* time from
the simulator's single-threaded event loop — the seam that makes a whole
cluster deterministically replayable.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Callable, Optional


@dataclass(frozen=True)
class TimeoutInfo:
    duration: float  # seconds
    height: int
    round: int
    step: int

    def hrs(self):
        return (self.height, self.round, self.step)


class TimeoutTicker:
    """ticker.go:17-60.

    `clock`, when given, must provide `call_later(delay, fn)` returning a
    handle with `.cancel()` (duck-compatible with threading.Timer). All
    wall-clock knowledge of the consensus timer lives behind it.
    """

    def __init__(self, on_timeout: Callable[[TimeoutInfo], None], clock=None):
        self._on_timeout = on_timeout
        self._clock = clock
        self._mtx = threading.Lock()
        self._timer = None  # threading.Timer or clock timer handle
        self._pending: Optional[TimeoutInfo] = None
        self._stopped = False

    def schedule_timeout(self, ti: TimeoutInfo) -> None:
        """timeoutRoutine: ignore stale, replace pending with newer."""
        with self._mtx:
            if self._stopped:
                return
            if self._pending is not None and ti.hrs() < self._pending.hrs():
                return  # stale relative to what's already scheduled
            if self._timer is not None:
                self._timer.cancel()
            self._pending = ti
            if self._clock is not None:
                self._timer = self._clock.call_later(
                    ti.duration, lambda ti=ti: self._fire(ti)
                )
            else:
                self._timer = threading.Timer(ti.duration, self._fire, args=(ti,))
                self._timer.daemon = True
                self._timer.start()

    def _fire(self, ti: TimeoutInfo) -> None:
        with self._mtx:
            if self._stopped or self._pending is not ti:
                return
            self._pending = None
            self._timer = None
        self._on_timeout(ti)

    def stop(self) -> None:
        with self._mtx:
            self._stopped = True
            if self._timer is not None:
                self._timer.cancel()
                self._timer = None
            self._pending = None
