"""Consensus write-ahead log.

Reference parity: internal/consensus/wal.go — every message and timeout is
written before processing (state.go:757+); the node's own votes/proposals
use write_sync (wal.go:196). Framing is CRC32C + length + proto-ish body
(wal.go encodeFrame), max message 1MB (wal.go:25); a decode error on
replay truncates (crash-tolerant tail).

Message envelope (self-defined wire, node-local on-disk format):
  1 time(Timestamp)  2 end_height(varint)  3 msg_info{1 kind(varint),
  2 payload(bytes), 3 peer_id(string)}  4 timeout{1 duration_ms, 2 height,
  3 round, 4 step}
"""

from __future__ import annotations

import os
import struct
import threading
import zlib
from dataclasses import dataclass
from typing import BinaryIO, Iterator, Optional, Tuple

from ..libs import autofile
from ..wire.proto import ProtoWriter, decode_message, field_bytes, field_int, to_signed64

MAX_MSG_SIZE = 1 << 20  # 1MB (wal.go:25)


@dataclass
class WALMessage:
    """Decoded WAL record."""

    end_height: Optional[int] = None
    msg_kind: Optional[str] = None  # "proposal" | "block_part" | "vote" | "event_rs"
    msg_payload: bytes = b""
    peer_id: str = ""
    timeout: Optional[Tuple[int, int, int, int]] = None  # (dur_ms, h, r, step)


_KINDS = {"event_rs": 1, "proposal": 2, "block_part": 3, "vote": 4}
_KINDS_BY_NUM = {v: k for k, v in _KINDS.items()}


def _encode_record(msg: WALMessage) -> bytes:
    w = ProtoWriter()
    if msg.end_height is not None:
        w.write_varint(2, msg.end_height, always=True)
    elif msg.timeout is not None:
        t = ProtoWriter()
        t.write_varint(1, msg.timeout[0])
        t.write_varint(2, msg.timeout[1])
        t.write_varint(3, msg.timeout[2])
        t.write_varint(4, msg.timeout[3])
        w.write_message(4, t.bytes(), always=True)
    else:
        m = ProtoWriter()
        m.write_varint(1, _KINDS[msg.msg_kind])
        m.write_bytes(2, msg.msg_payload)
        m.write_string(3, msg.peer_id)
        w.write_message(3, m.bytes(), always=True)
    return w.bytes()


def _decode_record(data: bytes) -> WALMessage:
    f = decode_message(data)
    if 2 in f:
        return WALMessage(end_height=to_signed64(field_int(f, 2)))
    if 4 in f:
        t = decode_message(field_bytes(f, 4))
        return WALMessage(
            timeout=(field_int(t, 1), field_int(t, 2), field_int(t, 3), field_int(t, 4))
        )
    m = decode_message(field_bytes(f, 3))
    return WALMessage(
        msg_kind=_KINDS_BY_NUM[field_int(m, 1)],
        msg_payload=field_bytes(m, 2),
        peer_id=field_bytes(m, 3).decode(),
    )


class WAL:
    """wal.go:58-220 BaseWAL on an autofile Group (size-rotated chunks,
    internal/libs/autofile/group.go parity via libs.autofile)."""

    def __init__(
        self,
        path: str,
        head_size_limit: int = autofile.DEFAULT_HEAD_SIZE_LIMIT,
        total_size_limit: int = autofile.DEFAULT_TOTAL_SIZE_LIMIT,
    ):
        self._path = path
        self._group = autofile.Group(
            path, head_size_limit=head_size_limit, total_size_limit=total_size_limit
        )
        self._mtx = threading.Lock()
        self._started = False

    def start(self) -> None:
        self._repair_torn_tail()
        exists = any(
            os.path.getsize(p) > 0 for p in self._group.files_oldest_first()
        )
        self._group.open()
        self._started = True
        if not exists:
            self.write(WALMessage(end_height=0))  # wal.go OnStart:118-124

    def _repair_torn_tail(self) -> None:
        """Truncate a crash-torn partial frame at the end of the head file
        BEFORE appending: without this, post-restart records land after
        the garbage and become invisible to replay (frame decoding stops
        at the first bad CRC), silently breaking the write_sync recovery
        invariant."""
        if not os.path.exists(self._path):
            return
        good_end = 0
        with open(self._path, "rb") as fh:
            while True:
                head = fh.read(8)
                if len(head) < 8:
                    break
                crc, length = struct.unpack(">II", head)
                if length > MAX_MSG_SIZE:
                    break
                body = fh.read(length)
                if len(body) < length or (zlib.crc32(body) & 0xFFFFFFFF) != crc:
                    break
                good_end = fh.tell()
        if good_end < os.path.getsize(self._path):
            with open(self._path, "r+b") as fh:
                fh.truncate(good_end)

    def stop(self) -> None:
        with self._mtx:
            if self._started:
                self._group.close()
                self._started = False

    # -- writes ---------------------------------------------------------

    def write(self, msg: WALMessage) -> None:
        body = _encode_record(msg)
        if len(body) > MAX_MSG_SIZE:
            raise ValueError(f"msg is too big: {len(body)} bytes, max: {MAX_MSG_SIZE}")
        crc = zlib.crc32(body) & 0xFFFFFFFF
        frame = struct.pack(">II", crc, len(body)) + body
        with self._mtx:
            if self._started:
                self._group.write(frame)
                self._group.maybe_rotate()

    def write_sync(self, msg: WALMessage) -> None:
        """wal.go:196-210: fsync before the process acts on its own
        proposal/vote — the crash-recovery invariant."""
        self.write(msg)
        try:
            self.flush_and_sync()
        except (OSError, ValueError):
            pass  # closed during shutdown

    def flush_and_sync(self) -> None:
        with self._mtx:
            if self._started:
                self._group.flush_and_sync()

    # -- reads ----------------------------------------------------------

    @staticmethod
    def _iter_file(path: str) -> Iterator[WALMessage]:
        with open(path, "rb") as fh:
            while True:
                head = fh.read(8)
                if len(head) < 8:
                    return
                crc, length = struct.unpack(">II", head)
                if length > MAX_MSG_SIZE:
                    return
                body = fh.read(length)
                if len(body) < length:
                    return
                if (zlib.crc32(body) & 0xFFFFFFFF) != crc:
                    return
                try:
                    yield _decode_record(body)
                except (ValueError, KeyError):
                    return

    def iter_messages(self) -> Iterator[WALMessage]:
        """Decode across the group, oldest chunk -> head; stop at
        corruption (only the head can carry a crash-torn tail)."""
        for path in self._group.files_oldest_first():
            yield from self._iter_file(path)

    def search_for_end_height(self, height: int) -> Optional[list]:
        """wal.go:226-280 SearchForEndHeight, newest-chunk-first: walk the
        chunks backwards, decoding each file AT MOST ONCE (newer chunks'
        decoded records are kept — they are part of the replay tail), so
        startup replay cost is bounded by the tail, not O(chunks^2) over
        the whole rotated group."""
        files = self._group.files_oldest_first()
        newer_msgs: list = []  # records of files newer than the current one
        for start in range(len(files) - 1, -1, -1):
            msgs = list(self._iter_file(files[start]))
            last = -1
            for i, msg in enumerate(msgs):
                if msg.end_height == height:
                    last = i  # replay from the LAST marker for the height
            if last >= 0:
                return msgs[last + 1 :] + newer_msgs
            newer_msgs = msgs + newer_msgs
        return None
