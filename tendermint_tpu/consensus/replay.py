"""Handshake / replay — reconcile app state with chain state on boot.

Reference parity: internal/consensus/replay.go — Handshaker (:203):
ABCI Info → compare heights → InitChain for fresh chains → ReplayBlocks
(:283) re-applies blocks from the store to the app until both are at the
store height. The WAL catchup half lives in ConsensusState._replay_wal.
"""

from __future__ import annotations

from dataclasses import replace
from typing import List, Optional

from ..abci import types as abci
from ..crypto.encoding import pubkey_from_proto
from ..state import State
from ..state.execution import (
    BlockExecutor,
    exec_block_on_proxy_app,
    update_state,
)
from ..state.store import StateStore
from ..types import BlockID, Validator, ValidatorSet
from ..types.genesis import GenesisDoc
from ..types.params import ConsensusParams
from ..wire import canonical as _canon


class HandshakeError(RuntimeError):
    pass


class Handshaker:
    """replay.go:203-281."""

    def __init__(
        self,
        state_store: StateStore,
        state: State,
        block_store,
        gen_doc: GenesisDoc,
        event_bus=None,
    ):
        self._state_store = state_store
        self._state = state
        self._block_store = block_store
        self._gen_doc = gen_doc
        self._event_bus = event_bus
        self.n_blocks_replayed = 0

    def handshake(self, proxy_app) -> State:
        """replay.go:240-281: returns the post-handshake state."""
        res = proxy_app.info(abci.RequestInfo(version="tendermint-tpu"))
        app_height = res.last_block_height
        app_hash = res.last_block_app_hash
        if app_height < 0:
            raise HandshakeError(f"got a negative last block height ({app_height})")
        state = self.replay_blocks(self._state, proxy_app, app_height, app_hash)
        return state

    def replay_blocks(
        self, state: State, proxy_app, app_height: int, app_hash: bytes
    ) -> State:
        """replay.go:283-430 (the height-case analysis)."""
        store_height = self._block_store.height()
        state_height = state.last_block_height

        # 1. fresh chain: InitChain
        if app_height == 0 and state_height == 0:
            validators = [
                abci.ValidatorUpdate(
                    pub_key=_pubkey_proto(v.pub_key), power=v.voting_power
                )
                for v in (state.validators.validators if state.validators else [])
            ]
            params = state.consensus_params
            req = abci.RequestInitChain(
                time=self._gen_doc.genesis_time,
                chain_id=self._gen_doc.chain_id,
                consensus_params=params.encode(),
                validators=validators,
                app_state_bytes=(
                    __import__("json").dumps(self._gen_doc.app_state).encode()
                    if self._gen_doc.app_state is not None
                    else b""
                ),
                initial_height=self._gen_doc.initial_height,
            )
            ic = proxy_app.init_chain(req)
            # apply InitChain response (replay.go:300-340)
            if state_height == 0:
                app_hash = ic.app_hash or app_hash
                if ic.validators:
                    vals = [
                        Validator.new(pubkey_from_proto(v.pub_key), v.power)
                        for v in ic.validators
                    ]
                    state = replace_state_validators(state, ValidatorSet.new(vals))
                elif not self._gen_doc.validators:
                    raise HandshakeError(
                        "validator set is nil in genesis and still empty after InitChain"
                    )
                if ic.consensus_params is not None:
                    state = replace(
                        state,
                        consensus_params=ConsensusParams.decode(ic.consensus_params),
                    )
                state = replace(state, app_hash=app_hash)
                self._state_store.save(state)

        if store_height == 0:
            return state

        # sanity (replay.go:341-360)
        if store_height < app_height:
            raise HandshakeError(
                f"app block height ({app_height}) is higher than store ({store_height})"
            )
        if store_height < state_height:
            raise HandshakeError(
                f"state height ({state_height}) is higher than store ({store_height})"
            )

        if store_height == state_height:
            # tendermint is in sync; maybe replay a few blocks to the app
            return self._replay_to_app(state, proxy_app, app_height, store_height)

        if store_height == state_height + 1:
            # saved the block but crashed before applying it
            state = self._apply_stored_block(state, proxy_app, store_height, app_height)
            return state

        raise HandshakeError(
            f"uncovered case: store {store_height}, state {state_height}, app {app_height}"
        )

    def _replay_to_app(
        self, state: State, proxy_app, app_height: int, store_height: int
    ) -> State:
        """Replay finalized blocks the app hasn't seen (replay.go:430-500)."""
        for height in range(app_height + 1, store_height + 1):
            block = self._block_store.load_block(height)
            if block is None:
                raise HandshakeError(f"missing block at height {height} for replay")
            exec_block_on_proxy_app(
                proxy_app, block, self._state_store, state.initial_height
            )
            proxy_app.commit()
            self.n_blocks_replayed += 1
        return state

    def _apply_stored_block(
        self, state: State, proxy_app, store_height: int, app_height: int
    ) -> State:
        """store is one ahead of state: re-apply via a full BlockExecutor."""
        # first catch the app up to state height
        state = self._replay_to_app(state, proxy_app, app_height, state.last_block_height)
        block = self._block_store.load_block(store_height)
        meta = self._block_store.load_block_meta(store_height)
        ex = BlockExecutor(self._state_store, proxy_app, block_store=self._block_store)
        state = ex.apply_block(state, meta.block_id, block)
        self.n_blocks_replayed += 1
        return state


def replace_state_validators(state: State, vals: ValidatorSet) -> State:
    return replace(
        state,
        validators=vals,
        next_validators=vals.copy_increment_proposer_priority(1),
        last_validators=ValidatorSet(),
    )


def _pubkey_proto(pk) -> bytes:
    from ..crypto.encoding import pubkey_to_proto

    return pubkey_to_proto(pk)
