"""WAL replay console — re-drive the consensus WAL through the state
machine against COPIES of the node's stores.

Reference parity: internal/consensus/replay_file.go:38-90 (RunReplayFile /
State.ReplayFile) and the playback manager (:120-199): records decode from
the WAL file and feed the real consensus State's handlers one at a time;
`back N` rebuilds the State from the restart point and re-applies
count - N records (replayReset — "back is not supported in the state
machine design, so we restart and replay up to"). Unlike the reference,
the stores are snapshotted into MemDBs first, so a console session can
never corrupt the node's data directory (blocks re-applied during replay
commit to the copies).
"""

from __future__ import annotations

from typing import List, Optional

from ..db import DB, MemDB
from ..types.part_set import Part
from ..types.proposal import Proposal
from ..types.vote import Vote


def _copy_db(src: DB) -> MemDB:
    dst = MemDB()
    for k, v in src.iterator(None, None):
        dst.set(k, v)
    return dst


class Playback:
    """replay_file.go:120 playback: a consensus State fed straight from
    decoded WAL records, with reset-and-replay for `back`."""

    def __init__(self, config, app=None):
        self._config = config
        self._app = app
        self._records: List = []
        self.warnings: List[str] = []  # per-record corrupt/malformed notes
        self._load_stores()
        self._build_cs()
        self._read_wal()
        self.count = 0  # records applied so far

    # -- construction -------------------------------------------------------

    def _load_stores(self) -> None:
        from ..db import backend as db_backend
        from ..state.store import StateStore
        from ..store import BlockStore
        from ..types.genesis import GenesisDoc

        cfg = self._config
        home = cfg.base.home

        def _db(name: str):
            if cfg.base.db_backend in ("memdb", "mem") or not home:
                return MemDB()
            return db_backend(cfg.base.db_backend, cfg.base.db_path(name))

        # snapshot: replay APPLIES blocks (ABCI + store writes); the
        # console must never touch the node's real data directory
        self._block_db = _copy_db(_db("blockstore"))
        self._state_db = _copy_db(_db("state"))
        self._genesis = GenesisDoc.from_file(cfg.base.genesis_path())
        self._genesis.validate_and_complete()
        self.block_store = BlockStore(self._block_db)
        self.state_store = StateStore(self._state_db)

    def _build_cs(self) -> None:
        """Mirror make_node's consensus wiring (node/__init__.py) on the
        snapshotted stores, minus p2p/rpc/privval — and with wal=None:
        a replay session must not append to the WAL it is reading
        (ReplayFile refuses when cs.wal is open)."""
        from ..abci.client import LocalClient, SocketClient
        from ..abci.kvstore import KVStoreApplication
        from ..consensus.replay import Handshaker
        from ..consensus.state import ConsensusState
        from ..eventbus import EventBus
        from ..evidence import Pool as EvidencePool
        from ..mempool import TxMempool
        from ..state import make_genesis_state
        from ..state.execution import BlockExecutor

        cfg = self._config
        state = self.state_store.load()
        if state is None:
            state = make_genesis_state(self._genesis)
            self.state_store.save(state)

        if self._app is not None:
            conn = LocalClient(self._app)
        elif cfg.base.proxy_app in ("kvstore", "persistent_kvstore"):
            conn = LocalClient(KVStoreApplication())
        else:
            conn = SocketClient(cfg.base.proxy_app)
        event_bus = EventBus()
        handshaker = Handshaker(
            self.state_store, state, self.block_store, self._genesis, event_bus
        )
        state = handshaker.handshake(conn)
        mempool = TxMempool(conn, cfg.mempool, height=state.last_block_height)
        evpool = EvidencePool(
            MemDB(), state_store=self.state_store, block_store=self.block_store
        )
        evpool.set_state(state)
        block_exec = BlockExecutor(
            self.state_store, conn, mempool=mempool, evpool=evpool,
            block_store=self.block_store, event_bus=event_bus,
        )
        self.cs = ConsensusState(
            cfg.consensus, state, block_exec, self.block_store,
            mempool=mempool, evpool=evpool, event_bus=event_bus, wal=None,
        )

    def _read_wal(self) -> None:
        from .wal import WAL

        cfg = self._config
        wal = WAL(cfg.consensus.wal_path(cfg.base.home))
        self._records = list(wal.iter_messages())

    # -- stepping -----------------------------------------------------------

    def remaining(self) -> int:
        return len(self._records) - self.count

    def _warn_record(self, index: int, kind: str, err: Exception) -> None:
        """Corrupt or unexpectedly-failing WAL records are surfaced once
        per record (never silently dropped): stderr line + self.warnings
        for the console session to inspect."""
        import sys

        msg = f"wal record #{index} ({kind}): {type(err).__name__}: {err}"
        self.warnings.append(msg)
        print(f"replay: {msg}", file=sys.stderr)

    def step(self, n: int = 1) -> int:
        """Apply the next n records through the state machine handlers
        (readReplayMessage, replay.go:41: msgInfo -> handleMsg paths,
        timeouts -> handleTimeout, EndHeight -> marker). Returns how many
        were applied.

        Error handling is deliberately narrow: records addressed to an
        ALREADY-COMMITTED height (rec height < the state's height) are the
        expected stale/duplicate case when replaying a full WAL over a
        caught-up state and are skipped silently, as are stale-step votes
        (ErrVoteUnexpectedStep). Anything else — a record that fails to
        decode, or a current-height record the handlers reject — is a
        corrupt/malformed WAL entry and gets a per-record warning instead
        of a silent skip."""
        from ..types.vote_set import ErrVoteUnexpectedStep
        from ..wire.proto import decode_message, field_bytes, field_int
        from .state import BlockPartMessage, TimeoutInfo

        applied = 0
        while applied < n and self.count < len(self._records):
            rec = self._records[self.count]
            self.count += 1
            applied += 1
            if rec.end_height is not None:
                continue  # height marker; state advances via commits
            kind = "timeout" if rec.timeout is not None else (rec.msg_kind or "?")
            # decode phase: a payload that does not parse is corrupt, full
            # stop — there is no stale interpretation of it
            try:
                call = None
                if rec.timeout is not None:
                    d, h, r, st = rec.timeout
                    rec_height = h
                    ti = TimeoutInfo(
                        duration=d / 1000.0, height=h, round=r, step=st
                    )
                    call = lambda: self.cs._handle_timeout(ti)  # noqa: E731
                elif rec.msg_kind == "proposal":
                    p = Proposal.decode(rec.msg_payload)
                    rec_height = p.height
                    call = lambda: self.cs._set_proposal(p)  # noqa: E731
                elif rec.msg_kind == "block_part":
                    f = decode_message(rec.msg_payload)
                    bp = BlockPartMessage(
                        height=field_int(f, 1),
                        round=field_int(f, 2),
                        part=Part.decode(field_bytes(f, 3)),
                    )
                    rec_height = bp.height
                    call = lambda: self.cs._add_proposal_block_part(  # noqa: E731
                        bp, rec.peer_id
                    )
                elif rec.msg_kind == "vote":
                    v = Vote.decode(rec.msg_payload)
                    rec_height = v.height
                    call = lambda: self.cs._try_add_vote(v, rec.peer_id)  # noqa: E731
                else:
                    continue  # unknown kinds are ignored as before
            except Exception as e:  # noqa: BLE001 - decode = corrupt record
                self._warn_record(self.count - 1, kind, e)
                continue
            try:
                call()
            except ErrVoteUnexpectedStep:
                continue  # stale-step vote: expected during catch-up replay
            except (ValueError, RuntimeError, KeyError) as e:
                if rec_height < self.cs.rs.height:
                    # stale/duplicate record for an already-committed
                    # height: the expected case replaying a full WAL over
                    # a caught-up state
                    continue
                self._warn_record(self.count - 1, kind, e)
        return applied

    def reset_back(self, back: int) -> None:
        """replayReset: rebuild the State from the restart point and
        re-apply count - back records."""
        target = max(self.count - back, 0)
        self._load_stores()
        self._build_cs()
        self.count = 0
        self.step(target)
        # step() counts every record it consumed; make the position exact
        self.count = target

    # -- round state (the `rs` console command) ------------------------------

    def round_state(self, field: Optional[str] = None) -> str:
        from .types import STEP_NAMES

        rs = self.cs.rs
        if field in (None, "", "short"):
            return f"{rs.height}/{rs.round}/{STEP_NAMES.get(rs.step, rs.step)}"
        if field in (
            "validators", "proposal", "proposal_block", "locked_round",
            "locked_block", "votes", "valid_round", "valid_block",
            "commit_round", "last_commit",
        ):
            return str(getattr(rs, field))
        return f"unknown option {field}"
