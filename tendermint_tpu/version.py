"""Version constants.

Reference parity: version/version.go:13,22-27 — the wire protocol versions
must match so artifacts (blocks, handshakes) are interoperable in shape.
"""

TM_CORE_SEM_VER = "0.35.0-tpu"
TM_VERSION = TM_CORE_SEM_VER
ABCI_SEM_VER = "0.17.0"
ABCI_VERSION = ABCI_SEM_VER

# Protocol versions (uint64 on the wire).
P2P_PROTOCOL = 8
BLOCK_PROTOCOL = 11
