"""The ABCI Application interface.

Reference parity: abci/types/application.go:11-31 — the 12-method contract
a replicated application implements, plus BaseApplication defaults
(application.go:36-92) so apps override only what they need.
"""

from __future__ import annotations

import abc

from . import types as abci


class Application(abc.ABC):
    """abci/types/application.go:11-31."""

    # Info/Query connection
    def info(self, req: abci.RequestInfo) -> abci.ResponseInfo:
        return abci.ResponseInfo()

    def query(self, req: abci.RequestQuery) -> abci.ResponseQuery:
        return abci.ResponseQuery(code=abci.CODE_TYPE_OK)

    # Mempool connection
    def check_tx(self, req: abci.RequestCheckTx) -> abci.ResponseCheckTx:
        return abci.ResponseCheckTx(code=abci.CODE_TYPE_OK)

    # Consensus connection
    def init_chain(self, req: abci.RequestInitChain) -> abci.ResponseInitChain:
        return abci.ResponseInitChain()

    def begin_block(self, req: abci.RequestBeginBlock) -> abci.ResponseBeginBlock:
        return abci.ResponseBeginBlock()

    def deliver_tx(self, req: abci.RequestDeliverTx) -> abci.ResponseDeliverTx:
        return abci.ResponseDeliverTx(code=abci.CODE_TYPE_OK)

    def end_block(self, req: abci.RequestEndBlock) -> abci.ResponseEndBlock:
        return abci.ResponseEndBlock()

    def commit(self) -> abci.ResponseCommit:
        return abci.ResponseCommit()

    # State sync connection
    def list_snapshots(self) -> abci.ResponseListSnapshots:
        return abci.ResponseListSnapshots()

    def offer_snapshot(self, req: abci.RequestOfferSnapshot) -> abci.ResponseOfferSnapshot:
        return abci.ResponseOfferSnapshot()

    def load_snapshot_chunk(
        self, req: abci.RequestLoadSnapshotChunk
    ) -> abci.ResponseLoadSnapshotChunk:
        return abci.ResponseLoadSnapshotChunk()

    def apply_snapshot_chunk(
        self, req: abci.RequestApplySnapshotChunk
    ) -> abci.ResponseApplySnapshotChunk:
        return abci.ResponseApplySnapshotChunk()


class BaseApplication(Application):
    """Concrete no-op application (abci/types/application.go:36-92)."""
