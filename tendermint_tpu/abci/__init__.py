"""tendermint_tpu.abci — the application boundary (reference abci/, L5)."""

from . import types  # noqa: F401
from .application import Application, BaseApplication  # noqa: F401
from .client import LocalClient, SocketClient, new_client  # noqa: F401
from .kvstore import KVStoreApplication, PersistentKVStoreApplication  # noqa: F401
from .server import ABCIServer  # noqa: F401
