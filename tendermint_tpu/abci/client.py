"""ABCI clients: local (in-process) and socket.

Reference parity: abci/client/ — local_client.go:29 (mutex-serialized
in-process calls), socket_client.go:54 (async pipelined request/response
over a length-delimited stream). The socket client here pipelines via a
writer thread + reader thread with a response futures queue, mirroring
the reference's sendRequestRoutine/recvResponseRoutine.
"""

from __future__ import annotations

import queue
import socket
import threading
from concurrent.futures import Future
from typing import Callable, Optional, Tuple

from . import types as abci
from .application import Application


class ClientError(RuntimeError):
    pass


class LocalClient:
    """abci/client/local_client.go — direct calls under one mutex."""

    def __init__(self, app: Application):
        self._app = app
        self._mtx = threading.Lock()

    def echo(self, msg: str) -> str:
        return msg

    def flush(self) -> None:
        return None

    def info(self, req: abci.RequestInfo) -> abci.ResponseInfo:
        with self._mtx:
            return self._app.info(req)

    def query(self, req: abci.RequestQuery) -> abci.ResponseQuery:
        with self._mtx:
            return self._app.query(req)

    def check_tx(self, req: abci.RequestCheckTx) -> abci.ResponseCheckTx:
        with self._mtx:
            return self._app.check_tx(req)

    def init_chain(self, req: abci.RequestInitChain) -> abci.ResponseInitChain:
        with self._mtx:
            return self._app.init_chain(req)

    def begin_block(self, req: abci.RequestBeginBlock) -> abci.ResponseBeginBlock:
        with self._mtx:
            return self._app.begin_block(req)

    def deliver_tx(self, req: abci.RequestDeliverTx) -> abci.ResponseDeliverTx:
        with self._mtx:
            return self._app.deliver_tx(req)

    def deliver_tx_async(self, req: abci.RequestDeliverTx) -> Future:
        fut: Future = Future()
        fut.set_result(self.deliver_tx(req))
        return fut

    def check_tx_async(self, req: abci.RequestCheckTx) -> Future:
        fut: Future = Future()
        fut.set_result(self.check_tx(req))
        return fut

    def end_block(self, req: abci.RequestEndBlock) -> abci.ResponseEndBlock:
        with self._mtx:
            return self._app.end_block(req)

    def commit(self) -> abci.ResponseCommit:
        with self._mtx:
            return self._app.commit()

    def list_snapshots(self) -> abci.ResponseListSnapshots:
        with self._mtx:
            return self._app.list_snapshots()

    def offer_snapshot(self, req: abci.RequestOfferSnapshot) -> abci.ResponseOfferSnapshot:
        with self._mtx:
            return self._app.offer_snapshot(req)

    def load_snapshot_chunk(self, req) -> abci.ResponseLoadSnapshotChunk:
        with self._mtx:
            return self._app.load_snapshot_chunk(req)

    def apply_snapshot_chunk(self, req) -> abci.ResponseApplySnapshotChunk:
        with self._mtx:
            return self._app.apply_snapshot_chunk(req)

    def close(self) -> None:
        pass


class SocketClient:
    """abci/client/socket_client.go — pipelined over TCP or unix socket."""

    def __init__(self, address: str):
        self._address = address
        self._sock = _dial(address)
        self._pending: "queue.Queue[Tuple[str, Future]]" = queue.Queue()
        self._wbuf_lock = threading.Lock()
        self._closed = False
        self._reader = threading.Thread(target=self._recv_routine, daemon=True)
        self._reader.start()

    # -- plumbing -------------------------------------------------------

    def _send(self, kind: str, req) -> Future:
        payload = abci.enc_request_payload(kind, req)
        framed = abci.write_message(abci.encode_request(kind, payload))
        fut: Future = Future()
        with self._wbuf_lock:
            self._pending.put((kind, fut))
            self._sock.sendall(framed)
        return fut

    def _call(self, kind: str, req):
        fut = self._send(kind, req)
        # flush after each sync call, like socket_client.go's *Sync methods
        flush_fut = self._send("flush", None)
        res = fut.result(timeout=30)
        flush_fut.result(timeout=30)
        return res

    def _recv_routine(self) -> None:
        buf = b""
        try:
            while not self._closed:
                chunk = self._sock.recv(65536)
                if not chunk:
                    break
                buf += chunk
                while True:
                    try:
                        msg, consumed = abci.read_message(buf)
                    except ValueError:
                        break
                    buf = buf[consumed:]
                    try:
                        kind, payload = abci.decode_response(msg)
                    except ValueError as e:
                        # protocol error (unknown oneof): route through the
                        # OSError path so pending futures fail instead of
                        # blocking their full timeout on a dead recv thread
                        raise OSError(f"ABCI protocol error: {e}") from e
                    want_kind, fut = self._pending.get_nowait()
                    if kind == "exception":
                        fut.set_exception(
                            ClientError(abci.dec_response_payload(kind, payload))
                        )
                    elif kind != want_kind:
                        fut.set_exception(
                            ClientError(f"unexpected response {kind}, want {want_kind}")
                        )
                    else:
                        fut.set_result(abci.dec_response_payload(kind, payload))
        except (OSError, queue.Empty):
            pass
        # fail whatever is left
        while True:
            try:
                _, fut = self._pending.get_nowait()
                if not fut.done():
                    fut.set_exception(ClientError("connection closed"))
            except queue.Empty:
                break

    # -- API ------------------------------------------------------------

    def echo(self, msg: str) -> str:
        return self._call("echo", msg)

    def flush(self) -> None:
        self._send("flush", None).result(timeout=30)

    def info(self, req) -> abci.ResponseInfo:
        return self._call("info", req)

    def query(self, req) -> abci.ResponseQuery:
        return self._call("query", req)

    def check_tx(self, req) -> abci.ResponseCheckTx:
        return self._call("check_tx", req)

    def check_tx_async(self, req) -> Future:
        return self._send("check_tx", req)

    def init_chain(self, req) -> abci.ResponseInitChain:
        return self._call("init_chain", req)

    def begin_block(self, req) -> abci.ResponseBeginBlock:
        return self._call("begin_block", req)

    def deliver_tx(self, req) -> abci.ResponseDeliverTx:
        return self._call("deliver_tx", req)

    def deliver_tx_async(self, req) -> Future:
        """Pipelined deliver (execution.go:294 execBlockOnProxyApp pattern)."""
        return self._send("deliver_tx", req)

    def end_block(self, req) -> abci.ResponseEndBlock:
        return self._call("end_block", req)

    def commit(self) -> abci.ResponseCommit:
        return self._call("commit", None)

    def list_snapshots(self) -> abci.ResponseListSnapshots:
        return self._call("list_snapshots", None)

    def offer_snapshot(self, req) -> abci.ResponseOfferSnapshot:
        return self._call("offer_snapshot", req)

    def load_snapshot_chunk(self, req) -> abci.ResponseLoadSnapshotChunk:
        return self._call("load_snapshot_chunk", req)

    def apply_snapshot_chunk(self, req) -> abci.ResponseApplySnapshotChunk:
        return self._call("apply_snapshot_chunk", req)

    def close(self) -> None:
        self._closed = True
        try:
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        self._sock.close()


def _dial(address: str) -> socket.socket:
    """tcp://host:port or unix:///path (abci/client/client.go address form)."""
    if address.startswith("unix://"):
        s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        s.connect(address[len("unix://") :])
        return s
    if address.startswith("tcp://"):
        address = address[len("tcp://") :]
    host, _, port = address.rpartition(":")
    s = socket.create_connection((host or "127.0.0.1", int(port)))
    s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    return s


def new_client(address: str, transport: str, app: Optional[Application] = None):
    """abci/client/creators.go: "socket" dials; local wraps in-process."""
    if transport == "local":
        if app is None:
            raise ValueError("local transport needs an app")
        return LocalClient(app)
    if transport == "socket":
        return SocketClient(address)
    raise ValueError(f"unknown ABCI transport {transport!r}")
