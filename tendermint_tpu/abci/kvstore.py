"""Example kvstore application.

Reference parity: abci/example/kvstore/ — txs are "key=value" (or the raw
tx as both key and value), state is a KV map with an app hash over the tx
count; the persistent variant handles validator updates via txs of the
form "val:<base64 pubkey>!<power>" (persistent_kvstore.go).
"""

from __future__ import annotations

import base64
import struct
from typing import Dict, List, Optional

from ..crypto import ed25519
from ..crypto.encoding import pubkey_to_proto, pubkey_from_proto
from ..db import DB, MemDB
from . import types as abci
from .application import BaseApplication

VALIDATOR_TX_PREFIX = b"val:"
PROTOCOL_VERSION = 1


class KVStoreApplication(BaseApplication):
    """abci/example/kvstore/kvstore.go (+ state-sync snapshot support,
    abci/example/kvstore's snapshots extension)."""

    SNAPSHOT_CHUNK_SIZE = 65536

    def __init__(
        self,
        db: Optional[DB] = None,
        snapshot_interval: int = 0,
        snapshot_keep: int = 3,
    ):
        self._db = db or MemDB()
        self._height = 0
        self._app_hash = b""
        self._size = 0
        self._snapshot_interval = snapshot_interval
        self._snapshot_keep = max(snapshot_keep, 1)
        self._snapshots: dict = {}  # height -> (chunks: List[bytes], hash)
        self._restore_buf: list = []
        self._restoring: Optional[abci.Snapshot] = None
        self._restore()

    # -- state persistence ---------------------------------------------

    def _restore(self) -> None:
        raw = self._db.get(b"__state__")
        if raw is not None:
            self._height, self._size = struct.unpack(">qq", raw[:16])

    def _persist(self) -> None:
        self._db.set(b"__state__", struct.pack(">qq", self._height, self._size))

    # -- ABCI -----------------------------------------------------------

    def info(self, req: abci.RequestInfo) -> abci.ResponseInfo:
        return abci.ResponseInfo(
            data=f"{{\"size\":{self._size}}}",
            version="kvstore-tpu-0.1",
            app_version=PROTOCOL_VERSION,
            last_block_height=self._height,
            last_block_app_hash=self._compute_app_hash(),
        )

    def _compute_app_hash(self) -> bytes:
        if self._height == 0:
            return b""
        return struct.pack(">q", self._size).ljust(8, b"\x00")

    def check_tx(self, req: abci.RequestCheckTx) -> abci.ResponseCheckTx:
        if not req.tx:
            return abci.ResponseCheckTx(code=1, log="empty tx")
        return abci.ResponseCheckTx(code=abci.CODE_TYPE_OK, gas_wanted=1)

    def deliver_tx(self, req: abci.RequestDeliverTx) -> abci.ResponseDeliverTx:
        key, _, value = req.tx.partition(b"=")
        if not value:
            key = value = req.tx
        self._db.set(b"kv:" + key, value)
        self._size += 1
        events = [
            abci.Event(
                type="app",
                attributes=[
                    abci.EventAttribute(key="creator", value="Cosmoshi Netowoko", index=True),
                    abci.EventAttribute(key="key", value=key.decode("utf-8", "replace"), index=True),
                ],
            )
        ]
        return abci.ResponseDeliverTx(code=abci.CODE_TYPE_OK, events=events)

    def end_block(self, req: abci.RequestEndBlock) -> abci.ResponseEndBlock:
        self._height = req.height
        return abci.ResponseEndBlock()

    def commit(self) -> abci.ResponseCommit:
        self._persist()
        if self._snapshot_interval and self._height > 0 and (
            self._height % self._snapshot_interval == 0
        ):
            self._take_snapshot()
        return abci.ResponseCommit(data=self._compute_app_hash())

    # -- state-sync snapshots -------------------------------------------

    def _take_snapshot(self) -> None:
        import hashlib
        import json as _json

        items = {
            k[len(b"kv:"):].decode("latin1"): v.decode("latin1")
            for k, v in self._db.iterator(b"kv:", b"kv;")
        }
        blob = _json.dumps(
            {"height": self._height, "size": self._size, "items": items},
            sort_keys=True,
        ).encode()
        chunks = [
            blob[i : i + self.SNAPSHOT_CHUNK_SIZE]
            for i in range(0, max(len(blob), 1), self.SNAPSHOT_CHUNK_SIZE)
        ] or [b""]
        self._snapshots[self._height] = (chunks, hashlib.sha256(blob).digest())
        # bounded retention (kvstore keeps only the newest few)
        for h in sorted(self._snapshots)[: -self._snapshot_keep]:
            del self._snapshots[h]

    def list_snapshots(self) -> abci.ResponseListSnapshots:
        return abci.ResponseListSnapshots(
            snapshots=[
                abci.Snapshot(
                    height=h, format=1, chunks=len(chunks), hash=digest, metadata=b""
                )
                for h, (chunks, digest) in sorted(self._snapshots.items())
            ]
        )

    def load_snapshot_chunk(self, req) -> abci.ResponseLoadSnapshotChunk:
        entry = self._snapshots.get(req.height)
        if entry is None or req.format != 1 or req.chunk >= len(entry[0]):
            return abci.ResponseLoadSnapshotChunk(chunk=b"")
        return abci.ResponseLoadSnapshotChunk(chunk=entry[0][req.chunk])

    def offer_snapshot(self, req) -> abci.ResponseOfferSnapshot:
        if req.snapshot is None or req.snapshot.format != 1:
            return abci.ResponseOfferSnapshot(result=abci.OFFER_SNAPSHOT_REJECT_FORMAT)
        self._restoring = req.snapshot
        self._restore_buf = []
        return abci.ResponseOfferSnapshot(result=abci.OFFER_SNAPSHOT_ACCEPT)

    def apply_snapshot_chunk(self, req) -> abci.ResponseApplySnapshotChunk:
        import hashlib
        import json as _json

        if self._restoring is None:
            return abci.ResponseApplySnapshotChunk(
                result=abci.APPLY_SNAPSHOT_CHUNK_ABORT
            )
        self._restore_buf.append(req.chunk)
        if len(self._restore_buf) < self._restoring.chunks:
            return abci.ResponseApplySnapshotChunk(
                result=abci.APPLY_SNAPSHOT_CHUNK_ACCEPT
            )
        blob = b"".join(self._restore_buf)
        if hashlib.sha256(blob).digest() != self._restoring.hash:
            self._restoring = None
            return abci.ResponseApplySnapshotChunk(
                result=abci.APPLY_SNAPSHOT_CHUNK_REJECT_SNAPSHOT
            )
        obj = _json.loads(blob)
        for k, v in obj["items"].items():
            self._db.set(b"kv:" + k.encode("latin1"), v.encode("latin1"))
        self._height = obj["height"]
        self._size = obj["size"]
        self._persist()
        self._restoring = None
        return abci.ResponseApplySnapshotChunk(result=abci.APPLY_SNAPSHOT_CHUNK_ACCEPT)

    def query(self, req: abci.RequestQuery) -> abci.ResponseQuery:
        if req.path == "/key" or req.path == "":
            v = self._db.get(b"kv:" + req.data)
            return abci.ResponseQuery(
                code=abci.CODE_TYPE_OK,
                key=req.data,
                value=v or b"",
                log="exists" if v is not None else "does not exist",
                height=self._height,
            )
        return abci.ResponseQuery(code=1, log=f"unexpected path {req.path}")


class PersistentKVStoreApplication(KVStoreApplication):
    """abci/example/kvstore/persistent_kvstore.go — adds validator-set
    updates driven by "val:<base64 pub>!<power>" transactions."""

    def __init__(self, db: Optional[DB] = None):
        super().__init__(db)
        self._val_updates: Dict[bytes, abci.ValidatorUpdate] = {}

    def init_chain(self, req: abci.RequestInitChain) -> abci.ResponseInitChain:
        for v in req.validators:
            self._store_validator(v)
        return abci.ResponseInitChain()

    def begin_block(self, req: abci.RequestBeginBlock) -> abci.ResponseBeginBlock:
        self._val_updates = {}
        return abci.ResponseBeginBlock()

    def deliver_tx(self, req: abci.RequestDeliverTx) -> abci.ResponseDeliverTx:
        if req.tx.startswith(VALIDATOR_TX_PREFIX):
            return self._exec_validator_tx(req.tx)
        return super().deliver_tx(req)

    def end_block(self, req: abci.RequestEndBlock) -> abci.ResponseEndBlock:
        super().end_block(req)
        return abci.ResponseEndBlock(validator_updates=list(self._val_updates.values()))

    def _exec_validator_tx(self, tx: bytes) -> abci.ResponseDeliverTx:
        body = tx[len(VALIDATOR_TX_PREFIX) :]
        pub_b64, _, power_s = body.partition(b"!")
        # optional trailing "!nonce" so logically identical updates (a
        # validator leaving and later rejoining at the same power) remain
        # distinct tx bytes for the mempool's seen-tx cache
        power_s, _, _nonce = power_s.partition(b"!")
        try:
            pub_raw = base64.b64decode(pub_b64)
            power = int(power_s)
        except Exception:
            return abci.ResponseDeliverTx(code=1, log="invalid validator tx")
        pk = ed25519.PubKey(pub_raw)
        update = abci.ValidatorUpdate(pub_key=pubkey_to_proto(pk), power=power)
        self._val_updates[pub_raw] = update
        self._store_validator(update)
        return abci.ResponseDeliverTx(code=abci.CODE_TYPE_OK)

    def _store_validator(self, v: abci.ValidatorUpdate) -> None:
        pk = pubkey_from_proto(v.pub_key)
        key = b"validator:" + pk.bytes()
        if v.power == 0:
            self._db.delete(key)
        else:
            self._db.set(key, struct.pack(">q", v.power))

    def validators(self) -> List[abci.ValidatorUpdate]:
        out = []
        for k, raw in self._db.iterator(b"validator:", b"validator;"):
            pk = ed25519.PubKey(k[len(b"validator:") :])
            out.append(
                abci.ValidatorUpdate(
                    pub_key=pubkey_to_proto(pk), power=struct.unpack(">q", raw)[0]
                )
            )
        return out


def make_validator_tx(
    pub_key_bytes: bytes, power: int, nonce: Optional[int] = None
) -> bytes:
    tx = VALIDATOR_TX_PREFIX + base64.b64encode(pub_key_bytes) + b"!" + str(power).encode()
    if nonce is not None:
        tx += b"!" + str(nonce).encode()
    return tx
