"""ABCI over gRPC.

Reference parity: abci/client/grpc_client.go:46 + abci/server/grpc_server.go
— the `tendermint.abci.ABCIApplication` service with one unary RPC per
request kind. Built on grpcio's generic handler API with this framework's
hand-rolled proto payload codecs (abci/types.py enc/dec_*_payload) — no
protoc-generated stubs, same bytes on the wire.
"""

from __future__ import annotations

import threading
from typing import Optional

from . import types as abci

SERVICE = "tendermint.abci.ABCIApplication"

# (snake kind used by the payload codecs, CamelCase gRPC method name)
_METHODS = [
    ("echo", "Echo"),
    ("flush", "Flush"),
    ("info", "Info"),
    ("init_chain", "InitChain"),
    ("query", "Query"),
    ("check_tx", "CheckTx"),
    ("begin_block", "BeginBlock"),
    ("deliver_tx", "DeliverTx"),
    ("end_block", "EndBlock"),
    ("commit", "Commit"),
    ("list_snapshots", "ListSnapshots"),
    ("offer_snapshot", "OfferSnapshot"),
    ("load_snapshot_chunk", "LoadSnapshotChunk"),
    ("apply_snapshot_chunk", "ApplySnapshotChunk"),
]
_KIND_BY_METHOD = {m: k for k, m in _METHODS}


def _require_grpc():
    try:
        import grpc
    except ImportError as e:  # pragma: no cover — grpcio is in the image
        raise RuntimeError("grpcio is not available") from e
    return grpc


def _identity(b: bytes) -> bytes:
    return b


class GRPCServer:
    """abci/server/grpc_server.go: serve an Application over gRPC."""

    def __init__(self, app: abci.Application, address: str = "127.0.0.1:0"):
        grpc = _require_grpc()
        from concurrent.futures import ThreadPoolExecutor

        from .client import LocalClient

        self._local = LocalClient(app)
        self._server = grpc.server(ThreadPoolExecutor(max_workers=8))

        local = self._local

        class _Handler(grpc.GenericRpcHandler):
            def service(self, handler_call_details):
                path = handler_call_details.method  # /Service/Method
                try:
                    service, method = path.lstrip("/").split("/", 1)
                except ValueError:
                    return None
                if service != SERVICE or method not in _KIND_BY_METHOD:
                    return None
                kind = _KIND_BY_METHOD[method]

                def unary(request: bytes, context) -> bytes:
                    if kind == "echo":
                        msg = abci.dec_request_payload("echo", request)
                        return abci.enc_response_payload("echo", local.echo(msg))
                    if kind == "flush":
                        local.flush()
                        return abci.enc_response_payload("flush", None)
                    if kind == "commit":
                        return abci.enc_response_payload("commit", local.commit())
                    if kind == "list_snapshots":
                        return abci.enc_response_payload(
                            "list_snapshots", local.list_snapshots()
                        )
                    req = abci.dec_request_payload(kind, request)
                    resp = getattr(local, kind)(req)
                    return abci.enc_response_payload(kind, resp)

                return grpc.unary_unary_rpc_method_handler(
                    unary,
                    request_deserializer=_identity,
                    response_serializer=_identity,
                )

        self._server.add_generic_rpc_handlers((_Handler(),))
        host, _, port = address.rpartition(":")
        self._port = self._server.add_insecure_port(f"{host or '127.0.0.1'}:{port}")

    @property
    def address(self) -> str:
        return f"127.0.0.1:{self._port}"

    def start(self) -> None:
        self._server.start()

    def stop(self) -> None:
        self._server.stop(grace=1)


class GRPCClient:
    """abci/client/grpc_client.go: the Application interface over gRPC;
    drop-in for LocalClient/SocketClient in the proxy multiplexer."""

    def __init__(self, address: str):
        grpc = _require_grpc()
        for prefix in ("grpc://", "tcp://"):
            if address.startswith(prefix):
                address = address[len(prefix):]
        self._channel = grpc.insecure_channel(address)
        self._mtx = threading.Lock()
        self._calls = {
            kind: self._channel.unary_unary(
                f"/{SERVICE}/{method}",
                request_serializer=_identity,
                response_deserializer=_identity,
            )
            for kind, method in _METHODS
        }

    def _call(self, kind: str, req) -> object:
        raw = abci.enc_request_payload(kind, req)
        out = self._calls[kind](raw, timeout=30)
        return abci.dec_response_payload(kind, out)

    def echo(self, msg: str) -> str:
        return self._call("echo", msg)

    def flush(self) -> None:
        self._calls["flush"](b"", timeout=30)

    def info(self, req: abci.RequestInfo) -> abci.ResponseInfo:
        return self._call("info", req)

    def query(self, req: abci.RequestQuery) -> abci.ResponseQuery:
        return self._call("query", req)

    def check_tx(self, req: abci.RequestCheckTx) -> abci.ResponseCheckTx:
        return self._call("check_tx", req)

    def check_tx_async(self, req: abci.RequestCheckTx):
        from concurrent.futures import Future

        fut: Future = Future()
        try:
            fut.set_result(self.check_tx(req))
        except Exception as e:  # noqa: BLE001
            fut.set_exception(e)
        return fut

    def init_chain(self, req: abci.RequestInitChain) -> abci.ResponseInitChain:
        return self._call("init_chain", req)

    def begin_block(self, req: abci.RequestBeginBlock) -> abci.ResponseBeginBlock:
        return self._call("begin_block", req)

    def deliver_tx(self, req: abci.RequestDeliverTx) -> abci.ResponseDeliverTx:
        return self._call("deliver_tx", req)

    def deliver_tx_async(self, req: abci.RequestDeliverTx):
        from concurrent.futures import Future

        fut: Future = Future()
        try:
            fut.set_result(self.deliver_tx(req))
        except Exception as e:  # noqa: BLE001
            fut.set_exception(e)
        return fut

    def end_block(self, req: abci.RequestEndBlock) -> abci.ResponseEndBlock:
        return self._call("end_block", req)

    def commit(self) -> abci.ResponseCommit:
        raw = self._calls["commit"](b"", timeout=30)
        return abci.dec_response_payload("commit", raw)

    def list_snapshots(self) -> abci.ResponseListSnapshots:
        raw = self._calls["list_snapshots"](b"", timeout=30)
        return abci.dec_response_payload("list_snapshots", raw)

    def offer_snapshot(self, req) -> abci.ResponseOfferSnapshot:
        return self._call("offer_snapshot", req)

    def load_snapshot_chunk(self, req) -> abci.ResponseLoadSnapshotChunk:
        return self._call("load_snapshot_chunk", req)

    def apply_snapshot_chunk(self, req) -> abci.ResponseApplySnapshotChunk:
        return self._call("apply_snapshot_chunk", req)

    def close(self) -> None:
        self._channel.close()
