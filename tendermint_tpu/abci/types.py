"""ABCI message types + wire codec.

Reference parity: abci/types/types.pb.go (tendermint.abci package).
Request/Response are proto oneofs; the socket transport frames each
message with a uvarint length prefix (abci/types/messages.go
WriteMessage/ReadMessage).

Field-surface contract (VERDICT r3 missing-item 6): only the fields the
framework and example apps touch are modeled as dataclasses; everything
round-trips through the deterministic proto codec in wire/proto.py.
Concretely:
- Unknown fields INSIDE a message are ignored on decode — standard
  proto3 semantics, identical to what the reference's generated codec
  does — and are therefore NOT re-emitted on re-encode. ABCI messages
  are never round-tripped through this codec on behalf of a third
  party (each side encodes its own structs), so no wire data is lost.
- Unknown Request/Response ONEOF kinds (an ABCI method this framework
  does not implement) are rejected loudly (ValueError) instead of being
  silently dropped — see decode_request/decode_response.
- The modeled surface covers every field the v0.35 framework reads or
  writes on each message (consensus, mempool, query, snapshot
  connections), cross-checked against abci/types/types.pb.go usage in
  the reference's node/consensus/mempool/statesync packages.
"""

from __future__ import annotations

from dataclasses import dataclass, field as dfield
from typing import List, Optional, Tuple

from ..wire.canonical import Timestamp, encode_timestamp
from ..wire.proto import (
    ProtoWriter,
    decode_message,
    field_bytes,
    field_int,
    field_repeated_bytes,
    marshal_delimited,
    to_signed32,
    to_signed64,
    unmarshal_delimited,
)

CODE_TYPE_OK = 0

# CheckTxType enum
CHECK_TX_TYPE_NEW = 0
CHECK_TX_TYPE_RECHECK = 1

# ResponseOfferSnapshot.Result / ResponseApplySnapshotChunk.Result enums
OFFER_SNAPSHOT_UNKNOWN = 0
OFFER_SNAPSHOT_ACCEPT = 1
OFFER_SNAPSHOT_ABORT = 2
OFFER_SNAPSHOT_REJECT = 3
OFFER_SNAPSHOT_REJECT_FORMAT = 4
OFFER_SNAPSHOT_REJECT_SENDER = 5

APPLY_SNAPSHOT_CHUNK_UNKNOWN = 0
APPLY_SNAPSHOT_CHUNK_ACCEPT = 1
APPLY_SNAPSHOT_CHUNK_ABORT = 2
APPLY_SNAPSHOT_CHUNK_RETRY = 3
APPLY_SNAPSHOT_CHUNK_RETRY_SNAPSHOT = 4
APPLY_SNAPSHOT_CHUNK_REJECT_SNAPSHOT = 5

# EvidenceType enum
EVIDENCE_TYPE_UNKNOWN = 0
EVIDENCE_TYPE_DUPLICATE_VOTE = 1
EVIDENCE_TYPE_LIGHT_CLIENT_ATTACK = 2


def _decode_ts(raw: bytes) -> Timestamp:
    f = decode_message(raw)
    return Timestamp(
        seconds=to_signed64(field_int(f, 1)), nanos=to_signed32(field_int(f, 2))
    )


@dataclass
class EventAttribute:
    key: str = ""
    value: str = ""
    index: bool = False

    def encode(self) -> bytes:
        w = ProtoWriter()
        w.write_string(1, self.key)
        w.write_string(2, self.value)
        w.write_varint(3, 1 if self.index else 0)
        return w.bytes()

    @classmethod
    def decode(cls, data: bytes) -> "EventAttribute":
        f = decode_message(data)
        return cls(
            key=field_bytes(f, 1).decode("utf-8", "replace"),
            value=field_bytes(f, 2).decode("utf-8", "replace"),
            index=bool(field_int(f, 3)),
        )


@dataclass
class Event:
    type: str = ""
    attributes: List[EventAttribute] = dfield(default_factory=list)

    def encode(self) -> bytes:
        w = ProtoWriter()
        w.write_string(1, self.type)
        for a in self.attributes:
            w.write_message(2, a.encode(), always=True)
        return w.bytes()

    @classmethod
    def decode(cls, data: bytes) -> "Event":
        f = decode_message(data)
        return cls(
            type=field_bytes(f, 1).decode("utf-8", "replace"),
            attributes=[EventAttribute.decode(raw) for raw in field_repeated_bytes(f, 2)],
        )


@dataclass
class ValidatorUpdate:
    """abci.ValidatorUpdate: pub_key (tendermint.crypto.PublicKey) + power."""

    pub_key: bytes  # encoded PublicKey message
    power: int = 0

    def encode(self) -> bytes:
        w = ProtoWriter()
        w.write_message(1, self.pub_key, always=True)
        w.write_varint(2, self.power)
        return w.bytes()

    @classmethod
    def decode(cls, data: bytes) -> "ValidatorUpdate":
        f = decode_message(data)
        return cls(pub_key=field_bytes(f, 1), power=to_signed64(field_int(f, 2)))


@dataclass
class ABCIValidator:
    """abci.Validator: address + power (no pubkey)."""

    address: bytes = b""
    power: int = 0

    def encode(self) -> bytes:
        w = ProtoWriter()
        w.write_bytes(1, self.address)
        w.write_varint(3, self.power)
        return w.bytes()

    @classmethod
    def decode(cls, data: bytes) -> "ABCIValidator":
        f = decode_message(data)
        return cls(address=field_bytes(f, 1), power=to_signed64(field_int(f, 3)))


@dataclass
class VoteInfo:
    validator: ABCIValidator = dfield(default_factory=ABCIValidator)
    signed_last_block: bool = False

    def encode(self) -> bytes:
        w = ProtoWriter()
        w.write_message(1, self.validator.encode(), always=True)
        w.write_varint(2, 1 if self.signed_last_block else 0)
        return w.bytes()

    @classmethod
    def decode(cls, data: bytes) -> "VoteInfo":
        f = decode_message(data)
        return cls(
            validator=ABCIValidator.decode(field_bytes(f, 1)),
            signed_last_block=bool(field_int(f, 2)),
        )


@dataclass
class LastCommitInfo:
    round: int = 0
    votes: List[VoteInfo] = dfield(default_factory=list)

    def encode(self) -> bytes:
        w = ProtoWriter()
        w.write_varint(1, self.round)
        for v in self.votes:
            w.write_message(2, v.encode(), always=True)
        return w.bytes()

    @classmethod
    def decode(cls, data: bytes) -> "LastCommitInfo":
        f = decode_message(data)
        return cls(
            round=to_signed32(field_int(f, 1)),
            votes=[VoteInfo.decode(raw) for raw in field_repeated_bytes(f, 2)],
        )


@dataclass
class ABCIEvidence:
    """abci.Evidence (misbehavior report to the app)."""

    type: int = EVIDENCE_TYPE_UNKNOWN
    validator: ABCIValidator = dfield(default_factory=ABCIValidator)
    height: int = 0
    time: Timestamp = dfield(default_factory=Timestamp.zero)
    total_voting_power: int = 0

    def encode(self) -> bytes:
        w = ProtoWriter()
        w.write_varint(1, self.type)
        w.write_message(2, self.validator.encode(), always=True)
        w.write_varint(3, self.height)
        w.write_message(4, encode_timestamp(self.time), always=True)
        w.write_varint(5, self.total_voting_power)
        return w.bytes()

    @classmethod
    def decode(cls, data: bytes) -> "ABCIEvidence":
        f = decode_message(data)
        return cls(
            type=field_int(f, 1),
            validator=ABCIValidator.decode(field_bytes(f, 2)),
            height=to_signed64(field_int(f, 3)),
            time=_decode_ts(field_bytes(f, 4)),
            total_voting_power=to_signed64(field_int(f, 5)),
        )


@dataclass
class Snapshot:
    height: int = 0
    format: int = 0
    chunks: int = 0
    hash: bytes = b""
    metadata: bytes = b""

    def encode(self) -> bytes:
        w = ProtoWriter()
        w.write_varint(1, self.height)
        w.write_varint(2, self.format)
        w.write_varint(3, self.chunks)
        w.write_bytes(4, self.hash)
        w.write_bytes(5, self.metadata)
        return w.bytes()

    @classmethod
    def decode(cls, data: bytes) -> "Snapshot":
        f = decode_message(data)
        return cls(
            height=field_int(f, 1),
            format=field_int(f, 2),
            chunks=field_int(f, 3),
            hash=field_bytes(f, 4),
            metadata=field_bytes(f, 5),
        )


# --------------------------------------------------------------------------
# Requests


@dataclass
class RequestInfo:
    version: str = ""
    block_version: int = 0
    p2p_version: int = 0
    abci_version: str = ""


@dataclass
class RequestInitChain:
    time: Timestamp = dfield(default_factory=Timestamp.zero)
    chain_id: str = ""
    consensus_params: Optional[bytes] = None  # encoded ConsensusParams
    validators: List[ValidatorUpdate] = dfield(default_factory=list)
    app_state_bytes: bytes = b""
    initial_height: int = 0


@dataclass
class RequestQuery:
    data: bytes = b""
    path: str = ""
    height: int = 0
    prove: bool = False


@dataclass
class RequestBeginBlock:
    hash: bytes = b""
    header: bytes = b""  # encoded types.Header
    last_commit_info: LastCommitInfo = dfield(default_factory=LastCommitInfo)
    byzantine_validators: List[ABCIEvidence] = dfield(default_factory=list)


@dataclass
class RequestCheckTx:
    tx: bytes = b""
    type: int = CHECK_TX_TYPE_NEW


@dataclass
class RequestDeliverTx:
    tx: bytes = b""


@dataclass
class RequestEndBlock:
    height: int = 0


@dataclass
class RequestOfferSnapshot:
    snapshot: Optional[Snapshot] = None
    app_hash: bytes = b""


@dataclass
class RequestLoadSnapshotChunk:
    height: int = 0
    format: int = 0
    chunk: int = 0


@dataclass
class RequestApplySnapshotChunk:
    index: int = 0
    chunk: bytes = b""
    sender: str = ""


# --------------------------------------------------------------------------
# Responses


@dataclass
class ResponseInfo:
    data: str = ""
    version: str = ""
    app_version: int = 0
    last_block_height: int = 0
    last_block_app_hash: bytes = b""


@dataclass
class ResponseInitChain:
    consensus_params: Optional[bytes] = None
    validators: List[ValidatorUpdate] = dfield(default_factory=list)
    app_hash: bytes = b""


@dataclass
class ResponseQuery:
    code: int = 0
    log: str = ""
    info: str = ""
    index: int = 0
    key: bytes = b""
    value: bytes = b""
    proof_ops: Optional[bytes] = None  # encoded crypto.ProofOps
    height: int = 0
    codespace: str = ""

    def is_ok(self) -> bool:
        return self.code == CODE_TYPE_OK


@dataclass
class ResponseBeginBlock:
    events: List[Event] = dfield(default_factory=list)


@dataclass
class ResponseCheckTx:
    code: int = 0
    data: bytes = b""
    log: str = ""
    info: str = ""
    gas_wanted: int = 0
    gas_used: int = 0
    events: List[Event] = dfield(default_factory=list)
    codespace: str = ""
    sender: str = ""
    priority: int = 0
    mempool_error: str = ""

    def is_ok(self) -> bool:
        return self.code == CODE_TYPE_OK


@dataclass
class ResponseDeliverTx:
    code: int = 0
    data: bytes = b""
    log: str = ""
    info: str = ""
    gas_wanted: int = 0
    gas_used: int = 0
    events: List[Event] = dfield(default_factory=list)
    codespace: str = ""

    def is_ok(self) -> bool:
        return self.code == CODE_TYPE_OK


@dataclass
class ResponseEndBlock:
    validator_updates: List[ValidatorUpdate] = dfield(default_factory=list)
    consensus_param_updates: Optional[bytes] = None
    events: List[Event] = dfield(default_factory=list)


@dataclass
class ResponseCommit:
    data: bytes = b""
    retain_height: int = 0


@dataclass
class ResponseListSnapshots:
    snapshots: List[Snapshot] = dfield(default_factory=list)


@dataclass
class ResponseOfferSnapshot:
    result: int = OFFER_SNAPSHOT_UNKNOWN


@dataclass
class ResponseLoadSnapshotChunk:
    chunk: bytes = b""


@dataclass
class ResponseApplySnapshotChunk:
    result: int = APPLY_SNAPSHOT_CHUNK_UNKNOWN
    refetch_chunks: List[int] = dfield(default_factory=list)
    reject_senders: List[str] = dfield(default_factory=list)


# --------------------------------------------------------------------------
# Request/Response oneof wire codec (for the socket transport)

_REQ_FIELDS = {
    "echo": 1, "flush": 2, "info": 3, "init_chain": 4, "query": 5,
    "begin_block": 6, "check_tx": 7, "deliver_tx": 8, "end_block": 9,
    "commit": 10, "list_snapshots": 11, "offer_snapshot": 12,
    "load_snapshot_chunk": 13, "apply_snapshot_chunk": 14,
}
_REQ_BY_NUM = {v: k for k, v in _REQ_FIELDS.items()}

_RESP_FIELDS = {
    "exception": 1, "echo": 2, "flush": 3, "info": 4, "init_chain": 5,
    "query": 6, "begin_block": 7, "check_tx": 8, "deliver_tx": 9,
    "end_block": 10, "commit": 11, "list_snapshots": 12,
    "offer_snapshot": 13, "load_snapshot_chunk": 14,
    "apply_snapshot_chunk": 15,
}
_RESP_BY_NUM = {v: k for k, v in _RESP_FIELDS.items()}


def encode_request(kind: str, payload: bytes) -> bytes:
    w = ProtoWriter()
    w.write_message(_REQ_FIELDS[kind], payload, always=True)
    return w.bytes()


def decode_request(data: bytes) -> Tuple[str, bytes]:
    f = decode_message(data)
    unknown = []
    for num, vals in f.items():
        kind = _REQ_BY_NUM.get(num)
        if kind is not None:
            return kind, vals[-1][1]
        unknown.append(num)
    if unknown:
        # a request carrying ONLY methods this framework does not
        # implement must fail LOUDLY, not be silently dropped (a foreign
        # app would otherwise get no reply and hang its connection);
        # unknown fields NEXT TO a known oneof are skipped (proto3)
        raise ValueError(f"unknown ABCI request oneof field(s) {unknown}")
    raise ValueError("empty ABCI request")


def encode_response(kind: str, payload: bytes) -> bytes:
    w = ProtoWriter()
    w.write_message(_RESP_FIELDS[kind], payload, always=True)
    return w.bytes()


def decode_response(data: bytes) -> Tuple[str, bytes]:
    f = decode_message(data)
    unknown = []
    for num, vals in f.items():
        kind = _RESP_BY_NUM.get(num)
        if kind is not None:
            return kind, vals[-1][1]
        unknown.append(num)
    if unknown:
        raise ValueError(f"unknown ABCI response oneof field(s) {unknown}")
    raise ValueError("empty ABCI response")


def write_message(msg: bytes) -> bytes:
    """Length-delimited framing (abci/types/messages.go WriteMessage)."""
    return marshal_delimited(msg)


def read_message(buf: bytes) -> Tuple[bytes, int]:
    return unmarshal_delimited(buf)


# -- payload codecs (request) ----------------------------------------------


def enc_request_payload(kind: str, req) -> bytes:
    w = ProtoWriter()
    if kind == "echo":
        w.write_string(1, req)
    elif kind in ("flush", "commit", "list_snapshots"):
        pass
    elif kind == "info":
        w.write_string(1, req.version)
        w.write_varint(2, req.block_version)
        w.write_varint(3, req.p2p_version)
        w.write_string(4, req.abci_version)
    elif kind == "init_chain":
        w.write_message(1, encode_timestamp(req.time), always=True)
        w.write_string(2, req.chain_id)
        w.write_message(3, req.consensus_params)
        for v in req.validators:
            w.write_message(4, v.encode(), always=True)
        w.write_bytes(5, req.app_state_bytes)
        w.write_varint(6, req.initial_height)
    elif kind == "query":
        w.write_bytes(1, req.data)
        w.write_string(2, req.path)
        w.write_varint(3, req.height)
        w.write_varint(4, 1 if req.prove else 0)
    elif kind == "begin_block":
        w.write_bytes(1, req.hash)
        w.write_message(2, req.header, always=True)
        w.write_message(3, req.last_commit_info.encode(), always=True)
        for e in req.byzantine_validators:
            w.write_message(4, e.encode(), always=True)
    elif kind == "check_tx":
        w.write_bytes(1, req.tx)
        w.write_varint(2, req.type)
    elif kind == "deliver_tx":
        w.write_bytes(1, req.tx)
    elif kind == "end_block":
        w.write_varint(1, req.height)
    elif kind == "offer_snapshot":
        if req.snapshot is not None:
            w.write_message(1, req.snapshot.encode(), always=True)
        w.write_bytes(2, req.app_hash)
    elif kind == "load_snapshot_chunk":
        w.write_varint(1, req.height)
        w.write_varint(2, req.format)
        w.write_varint(3, req.chunk)
    elif kind == "apply_snapshot_chunk":
        w.write_varint(1, req.index)
        w.write_bytes(2, req.chunk)
        w.write_string(3, req.sender)
    else:
        raise ValueError(f"unknown request kind {kind}")
    return w.bytes()


def dec_request_payload(kind: str, data: bytes):
    f = decode_message(data)
    if kind == "echo":
        return field_bytes(f, 1).decode("utf-8", "replace")
    if kind in ("flush", "commit", "list_snapshots"):
        return None
    if kind == "info":
        return RequestInfo(
            version=field_bytes(f, 1).decode(),
            block_version=field_int(f, 2),
            p2p_version=field_int(f, 3),
            abci_version=field_bytes(f, 4).decode(),
        )
    if kind == "init_chain":
        return RequestInitChain(
            time=_decode_ts(field_bytes(f, 1)),
            chain_id=field_bytes(f, 2).decode(),
            consensus_params=field_bytes(f, 3) if 3 in f else None,
            validators=[ValidatorUpdate.decode(raw) for raw in field_repeated_bytes(f, 4)],
            app_state_bytes=field_bytes(f, 5),
            initial_height=to_signed64(field_int(f, 6)),
        )
    if kind == "query":
        return RequestQuery(
            data=field_bytes(f, 1),
            path=field_bytes(f, 2).decode(),
            height=to_signed64(field_int(f, 3)),
            prove=bool(field_int(f, 4)),
        )
    if kind == "begin_block":
        return RequestBeginBlock(
            hash=field_bytes(f, 1),
            header=field_bytes(f, 2),
            last_commit_info=LastCommitInfo.decode(field_bytes(f, 3)),
            byzantine_validators=[ABCIEvidence.decode(raw) for raw in field_repeated_bytes(f, 4)],
        )
    if kind == "check_tx":
        return RequestCheckTx(tx=field_bytes(f, 1), type=field_int(f, 2))
    if kind == "deliver_tx":
        return RequestDeliverTx(tx=field_bytes(f, 1))
    if kind == "end_block":
        return RequestEndBlock(height=to_signed64(field_int(f, 1)))
    if kind == "offer_snapshot":
        return RequestOfferSnapshot(
            snapshot=Snapshot.decode(field_bytes(f, 1)) if 1 in f else None,
            app_hash=field_bytes(f, 2),
        )
    if kind == "load_snapshot_chunk":
        return RequestLoadSnapshotChunk(
            height=field_int(f, 1), format=field_int(f, 2), chunk=field_int(f, 3)
        )
    if kind == "apply_snapshot_chunk":
        return RequestApplySnapshotChunk(
            index=field_int(f, 1),
            chunk=field_bytes(f, 2),
            sender=field_bytes(f, 3).decode(),
        )
    raise ValueError(f"unknown request kind {kind}")


# -- payload codecs (response) ---------------------------------------------


def enc_response_payload(kind: str, resp) -> bytes:
    w = ProtoWriter()
    if kind == "exception":
        w.write_string(1, resp)
    elif kind == "echo":
        w.write_string(1, resp)
    elif kind == "flush":
        pass
    elif kind == "info":
        w.write_string(1, resp.data)
        w.write_string(2, resp.version)
        w.write_varint(3, resp.app_version)
        w.write_varint(4, resp.last_block_height)
        w.write_bytes(5, resp.last_block_app_hash)
    elif kind == "init_chain":
        w.write_message(1, resp.consensus_params)
        for v in resp.validators:
            w.write_message(2, v.encode(), always=True)
        w.write_bytes(3, resp.app_hash)
    elif kind == "query":
        w.write_varint(1, resp.code)
        w.write_string(3, resp.log)
        w.write_string(4, resp.info)
        w.write_varint(5, resp.index)
        w.write_bytes(6, resp.key)
        w.write_bytes(7, resp.value)
        w.write_message(8, resp.proof_ops)
        w.write_varint(9, resp.height)
        w.write_string(10, resp.codespace)
    elif kind == "begin_block":
        for e in resp.events:
            w.write_message(1, e.encode(), always=True)
    elif kind in ("check_tx", "deliver_tx"):
        w.write_varint(1, resp.code)
        w.write_bytes(2, resp.data)
        w.write_string(3, resp.log)
        w.write_string(4, resp.info)
        w.write_varint(5, resp.gas_wanted)
        w.write_varint(6, resp.gas_used)
        for e in resp.events:
            w.write_message(7, e.encode(), always=True)
        w.write_string(8, resp.codespace)
        if kind == "check_tx":
            w.write_string(9, resp.sender)
            w.write_varint(10, resp.priority)
            w.write_string(11, resp.mempool_error)
    elif kind == "end_block":
        for v in resp.validator_updates:
            w.write_message(1, v.encode(), always=True)
        w.write_message(2, resp.consensus_param_updates)
        for e in resp.events:
            w.write_message(3, e.encode(), always=True)
    elif kind == "commit":
        w.write_bytes(2, resp.data)
        w.write_varint(3, resp.retain_height)
    elif kind == "list_snapshots":
        for s in resp.snapshots:
            w.write_message(1, s.encode(), always=True)
    elif kind == "offer_snapshot":
        w.write_varint(1, resp.result)
    elif kind == "load_snapshot_chunk":
        w.write_bytes(1, resp.chunk)
    elif kind == "apply_snapshot_chunk":
        w.write_varint(1, resp.result)
        for c in resp.refetch_chunks:
            w.write_varint(2, c, always=True)
        for s in resp.reject_senders:
            w.write_string(3, s, always=True)
    else:
        raise ValueError(f"unknown response kind {kind}")
    return w.bytes()


def dec_response_payload(kind: str, data: bytes):
    f = decode_message(data)
    if kind == "exception":
        return field_bytes(f, 1).decode("utf-8", "replace")
    if kind == "echo":
        return field_bytes(f, 1).decode("utf-8", "replace")
    if kind == "flush":
        return None
    if kind == "info":
        return ResponseInfo(
            data=field_bytes(f, 1).decode(),
            version=field_bytes(f, 2).decode(),
            app_version=field_int(f, 3),
            last_block_height=to_signed64(field_int(f, 4)),
            last_block_app_hash=field_bytes(f, 5),
        )
    if kind == "init_chain":
        return ResponseInitChain(
            consensus_params=field_bytes(f, 1) if 1 in f else None,
            validators=[ValidatorUpdate.decode(raw) for raw in field_repeated_bytes(f, 2)],
            app_hash=field_bytes(f, 3),
        )
    if kind == "query":
        return ResponseQuery(
            code=field_int(f, 1),
            log=field_bytes(f, 3).decode(),
            info=field_bytes(f, 4).decode(),
            index=to_signed64(field_int(f, 5)),
            key=field_bytes(f, 6),
            value=field_bytes(f, 7),
            proof_ops=field_bytes(f, 8) if 8 in f else None,
            height=to_signed64(field_int(f, 9)),
            codespace=field_bytes(f, 10).decode(),
        )
    if kind == "begin_block":
        return ResponseBeginBlock(events=[Event.decode(raw) for raw in field_repeated_bytes(f, 1)])
    if kind in ("check_tx", "deliver_tx"):
        cls = ResponseCheckTx if kind == "check_tx" else ResponseDeliverTx
        resp = cls(
            code=field_int(f, 1),
            data=field_bytes(f, 2),
            log=field_bytes(f, 3).decode(),
            info=field_bytes(f, 4).decode(),
            gas_wanted=to_signed64(field_int(f, 5)),
            gas_used=to_signed64(field_int(f, 6)),
            events=[Event.decode(raw) for raw in field_repeated_bytes(f, 7)],
            codespace=field_bytes(f, 8).decode(),
        )
        if kind == "check_tx":
            resp.sender = field_bytes(f, 9).decode()
            resp.priority = to_signed64(field_int(f, 10))
            resp.mempool_error = field_bytes(f, 11).decode()
        return resp
    if kind == "end_block":
        return ResponseEndBlock(
            validator_updates=[ValidatorUpdate.decode(raw) for raw in field_repeated_bytes(f, 1)],
            consensus_param_updates=field_bytes(f, 2) if 2 in f else None,
            events=[Event.decode(raw) for raw in field_repeated_bytes(f, 3)],
        )
    if kind == "commit":
        return ResponseCommit(
            data=field_bytes(f, 2), retain_height=to_signed64(field_int(f, 3))
        )
    if kind == "list_snapshots":
        return ResponseListSnapshots(
            snapshots=[Snapshot.decode(raw) for raw in field_repeated_bytes(f, 1)]
        )
    if kind == "offer_snapshot":
        return ResponseOfferSnapshot(result=field_int(f, 1))
    if kind == "load_snapshot_chunk":
        return ResponseLoadSnapshotChunk(chunk=field_bytes(f, 1))
    if kind == "apply_snapshot_chunk":
        return ResponseApplySnapshotChunk(
            result=field_int(f, 1),
            refetch_chunks=[v for _, v in f.get(2, [])],
            reject_senders=[raw.decode() for raw in field_repeated_bytes(f, 3)],
        )
    raise ValueError(f"unknown response kind {kind}")
