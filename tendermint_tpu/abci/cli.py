"""abci-cli — drive an ABCI application from the command line.

Reference parity: abci/cmd/abci-cli/abci-cli.go — echo / info / deliver_tx
/ check_tx / commit / query / version against a running socket server,
`batch` (commands from stdin) and `console` (interactive), plus `kvstore`
(serve the demo app). Run as `python -m tendermint_tpu.abci.cli`.

Argument convention matches the reference (abci-cli.go stringOrHexToBytes):
values are strings in double quotes or hex with an 0x prefix.
"""

from __future__ import annotations

import argparse
import shlex
import sys

from . import types as abci


def string_or_hex_to_bytes(s: str) -> bytes:
    """abci-cli.go:658 stringOrHexToBytes."""
    if s.lower().startswith("0x"):
        return bytes.fromhex(s[2:])
    if s.startswith('"') and s.endswith('"') and len(s) >= 2:
        return s[1:-1].encode()
    raise ValueError(
        f"invalid string arg: \"{s}\"; must be in the format 0xXXXX or \"string\""
    )


def _connect(address: str):
    from .client import SocketClient

    return SocketClient(address)


def _print_response(name: str, **fields) -> None:
    print(f"-> {name}")
    for k, v in fields.items():
        if v in (None, b"", "", 0) and k != "code":
            continue
        if isinstance(v, bytes):
            print(f"-> {k}: 0x{v.hex().upper()}")
            try:
                print(f"-> {k}.string: {v.decode()}")
            except UnicodeDecodeError:
                pass
        else:
            print(f"-> {k}: {v}")


def cmd_echo(cli, args: list) -> int:
    msg = args[0] if args else ""
    _print_response("echo", message=cli.echo(msg))
    return 0


def cmd_info(cli, args: list) -> int:
    res = cli.info(abci.RequestInfo())
    _print_response(
        "info",
        data=res.data,
        version=res.version,
        app_version=res.app_version,
        last_block_height=res.last_block_height,
        last_block_app_hash=res.last_block_app_hash,
    )
    return 0


def cmd_deliver_tx(cli, args: list) -> int:
    if not args:
        print("want the tx", file=sys.stderr)
        return 1
    res = cli.deliver_tx(abci.RequestDeliverTx(tx=string_or_hex_to_bytes(args[0])))
    _print_response("deliver_tx", code=res.code, data=res.data, log=res.log)
    return 0 if res.code == 0 else 1


def cmd_check_tx(cli, args: list) -> int:
    if not args:
        print("want the tx", file=sys.stderr)
        return 1
    res = cli.check_tx(
        abci.RequestCheckTx(tx=string_or_hex_to_bytes(args[0]), type=abci.CHECK_TX_TYPE_NEW)
    )
    _print_response("check_tx", code=res.code, data=res.data, log=res.log)
    return 0 if res.code == 0 else 1


def cmd_commit(cli, args: list) -> int:
    res = cli.commit()
    _print_response("commit", data=res.data)
    return 0


def cmd_query(cli, args: list) -> int:
    if not args:
        print("want the query", file=sys.stderr)
        return 1
    res = cli.query(abci.RequestQuery(data=string_or_hex_to_bytes(args[0]), path=""))
    _print_response(
        "query", code=res.code, key=res.key, value=res.value, height=res.height
    )
    return 0 if res.code == 0 else 1


def cmd_version(cli, args: list) -> int:
    from ..version import ABCI_VERSION

    print(ABCI_VERSION)
    return 0


COMMANDS = {
    "echo": cmd_echo,
    "info": cmd_info,
    "deliver_tx": cmd_deliver_tx,
    "check_tx": cmd_check_tx,
    "commit": cmd_commit,
    "query": cmd_query,
    "version": cmd_version,
}


def run_line(cli, line: str) -> int:
    """One batch/console line: `<command> [args...]` (abci-cli.go:283)."""
    try:
        parts = shlex.split(line, posix=False)
        if not parts:
            return 0
        cmd, args = parts[0], parts[1:]
        fn = COMMANDS.get(cmd)
        if fn is None:
            print(f"unknown command: {cmd}", file=sys.stderr)
            return 1
        print(f"> {line}")
        return fn(cli, args)
    except ValueError as e:
        # bad quoting or bad args must not kill the batch/console session
        print(f"-> error: {e}", file=sys.stderr)
        return 1


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="abci-cli")
    p.add_argument("--address", default="127.0.0.1:26658")
    sub = p.add_subparsers(dest="command")
    for name in COMMANDS:
        sp = sub.add_parser(name)
        sp.add_argument("args", nargs="*")
    sub.add_parser("batch")
    sub.add_parser("console")
    sp = sub.add_parser("kvstore")
    sp.add_argument("--persist", default="")
    args = p.parse_args(argv)

    if not args.command:
        p.print_help()
        return 1

    if args.command == "version":
        # local, like the reference: no server needed
        return cmd_version(None, args.args)

    if args.command == "kvstore":
        from .kvstore import KVStoreApplication, PersistentKVStoreApplication
        from .server import ABCIServer

        app = (
            PersistentKVStoreApplication(args.persist)
            if args.persist
            else KVStoreApplication()
        )
        srv = ABCIServer(args.address, app)
        srv.start()
        print(f"kvstore serving on {args.address}")
        try:
            import time

            while True:
                time.sleep(1)
        except KeyboardInterrupt:
            srv.stop()
        return 0

    cli = _connect(args.address)
    try:
        if args.command == "batch":
            rc = 0
            for line in sys.stdin:
                rc |= run_line(cli, line.strip())
            return rc
        if args.command == "console":
            while True:
                try:
                    line = input("> ")
                except EOFError:
                    return 0
                run_line(cli, line.strip())
        try:
            return COMMANDS[args.command](cli, args.args)
        except ValueError as e:
            print(f"-> error: {e}", file=sys.stderr)
            return 1
    finally:
        cli.close()


if __name__ == "__main__":
    sys.exit(main())
