"""ABCI socket server — serve an Application over TCP or unix socket.

Reference parity: abci/server/socket_server.go. One connection = one
request stream processed in order (the app mutex serializes across
connections, matching the reference's global app lock).
"""

from __future__ import annotations

import socket
import socketserver
import threading
from typing import Optional, Tuple

from . import types as abci
from .application import Application


class ABCIServer:
    def __init__(self, address: str, app: Application):
        self._app = app
        self._app_mtx = threading.Lock()
        self._address = address
        self._threads = []
        self._listener: Optional[socket.socket] = None
        self._closed = False

    @property
    def address(self) -> str:
        return self._address

    def start(self) -> None:
        if self._address.startswith("unix://"):
            path = self._address[len("unix://") :]
            self._listener = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            self._listener.bind(path)
        else:
            addr = self._address
            if addr.startswith("tcp://"):
                addr = addr[len("tcp://") :]
            host, _, port = addr.rpartition(":")
            self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            self._listener.bind((host or "127.0.0.1", int(port)))
            if int(port) == 0:
                h, p = self._listener.getsockname()
                self._address = f"tcp://{h}:{p}"
        self._listener.listen(8)
        t = threading.Thread(target=self._accept_loop, daemon=True)
        t.start()
        self._threads.append(t)

    def _accept_loop(self) -> None:
        assert self._listener is not None
        while not self._closed:
            try:
                conn, _ = self._listener.accept()
            except OSError:
                return
            t = threading.Thread(target=self._serve_conn, args=(conn,), daemon=True)
            t.start()
            self._threads.append(t)

    def _serve_conn(self, conn: socket.socket) -> None:
        buf = b""
        try:
            while not self._closed:
                chunk = conn.recv(65536)
                if not chunk:
                    return
                buf += chunk
                out = bytearray()
                while True:
                    try:
                        msg, consumed = abci.read_message(buf)
                    except ValueError:
                        break
                    buf = buf[consumed:]
                    out += self._handle(msg)
                if out:
                    conn.sendall(bytes(out))
        except OSError:
            return
        finally:
            conn.close()

    def _handle(self, msg: bytes) -> bytes:
        try:
            kind, payload = abci.decode_request(msg)
            req = abci.dec_request_payload(kind, payload)
            with self._app_mtx:
                resp_kind, resp = self._dispatch(kind, req)
        except Exception as e:  # noqa: BLE001 — exceptions go on the wire
            resp_kind, resp = "exception", str(e)
        framed = abci.write_message(
            abci.encode_response(resp_kind, abci.enc_response_payload(resp_kind, resp))
        )
        return framed

    def _dispatch(self, kind: str, req) -> Tuple[str, object]:
        app = self._app
        if kind == "echo":
            return "echo", req
        if kind == "flush":
            return "flush", None
        if kind == "info":
            return "info", app.info(req)
        if kind == "init_chain":
            return "init_chain", app.init_chain(req)
        if kind == "query":
            return "query", app.query(req)
        if kind == "begin_block":
            return "begin_block", app.begin_block(req)
        if kind == "check_tx":
            return "check_tx", app.check_tx(req)
        if kind == "deliver_tx":
            return "deliver_tx", app.deliver_tx(req)
        if kind == "end_block":
            return "end_block", app.end_block(req)
        if kind == "commit":
            return "commit", app.commit()
        if kind == "list_snapshots":
            return "list_snapshots", app.list_snapshots()
        if kind == "offer_snapshot":
            return "offer_snapshot", app.offer_snapshot(req)
        if kind == "load_snapshot_chunk":
            return "load_snapshot_chunk", app.load_snapshot_chunk(req)
        if kind == "apply_snapshot_chunk":
            return "apply_snapshot_chunk", app.apply_snapshot_chunk(req)
        raise ValueError(f"unknown request kind {kind}")

    def stop(self) -> None:
        self._closed = True
        if self._listener is not None:
            try:
                self._listener.close()
            except OSError:
                pass
