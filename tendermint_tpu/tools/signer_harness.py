"""Remote-signer conformance harness.

Reference parity: tools/tm-signer-harness/main.go + internal/test_harness.go
— a battery of acceptance tests any remote signer implementation (socket
or gRPC) must pass before being trusted with a validator key:

  1. PUBKEY:      the signer reports the expected public key
  2. SIGN_VOTE:   a prevote and a precommit come back correctly signed
  3. SIGN_PROPOSAL: a proposal comes back correctly signed
  4. DOUBLE_SIGN: signing a conflicting vote at the same HRS is refused
  5. HRS_REGRESSION: signing at a lower height/round/step is refused
  6. TS_REPEAT:   re-signing the identical vote returns the stored
                  signature (same-HRS timestamp rule)
"""

from __future__ import annotations

from dataclasses import dataclass, field, replace
from typing import List, Optional

from ..types import Vote
from ..types.block import BlockID, PartSetHeader
from ..types.proposal import Proposal
from ..types.vote import PRECOMMIT_TYPE, PREVOTE_TYPE
from ..wire.canonical import Timestamp


@dataclass
class HarnessResult:
    name: str
    ok: bool
    detail: str = ""


@dataclass
class HarnessReport:
    results: List[HarnessResult] = field(default_factory=list)

    @property
    def passed(self) -> bool:
        return all(r.ok for r in self.results)

    def add(self, name: str, ok: bool, detail: str = "") -> None:
        self.results.append(HarnessResult(name, ok, detail))


def _bid(tag: bytes) -> BlockID:
    h = tag * 32
    return BlockID(hash=h, part_set_header=PartSetHeader(total=1, hash=h))


def run_harness(
    signer,
    chain_id: str = "signer-harness",
    expected_pub_key=None,
    base_height: int = 1_000_000,
) -> HarnessReport:
    """Run the conformance battery against a PrivValidator-shaped signer.
    Uses a very high base height so a real validator state file is never
    poisoned for live heights."""
    rep = HarnessReport()
    h = base_height

    # 1. PUBKEY
    try:
        pk = signer.get_pub_key()
        if expected_pub_key is not None:
            rep.add(
                "PUBKEY",
                pk.bytes() == expected_pub_key.bytes(),
                "reported key differs from expected",
            )
        else:
            rep.add("PUBKEY", len(pk.bytes()) == 32)
    except Exception as e:  # noqa: BLE001
        rep.add("PUBKEY", False, str(e))
        return rep  # nothing else can run without the key

    # 2. SIGN_VOTE (prevote then precommit at the same height/round)
    signed_pre: Optional[Vote] = None
    for vtype, name in ((PREVOTE_TYPE, "SIGN_PREVOTE"), (PRECOMMIT_TYPE, "SIGN_PRECOMMIT")):
        v = Vote(
            type=vtype,
            height=h,
            round=0,
            block_id=_bid(b"\x51"),
            timestamp=Timestamp(seconds=1_700_000_000),
            validator_address=pk.address(),
            validator_index=0,
        )
        try:
            sv = signer.sign_vote(chain_id, v)
            ok = pk.verify_signature(sv.sign_bytes(chain_id), sv.signature)
            rep.add(name, bool(ok), "" if ok else "signature does not verify")
            if vtype == PRECOMMIT_TYPE:
                signed_pre = sv
        except Exception as e:  # noqa: BLE001
            rep.add(name, False, str(e))

    # 3. DOUBLE_SIGN: conflicting precommit at the already-signed HRS
    if signed_pre is not None:
        conflicting = replace(signed_pre, block_id=_bid(b"\x53"), signature=b"")
        try:
            signer.sign_vote(chain_id, conflicting)
            rep.add("DOUBLE_SIGN_REFUSED", False, "conflicting vote was signed")
        except Exception:  # noqa: BLE001 — refusal is the pass condition
            rep.add("DOUBLE_SIGN_REFUSED", True)

    # 4. TS_REPEAT: identical vote again -> stored signature returned
    # (same-HRS timestamp rule; must run before the proposal moves HRS)
    if signed_pre is not None:
        again = replace(signed_pre, signature=b"")
        try:
            sv2 = signer.sign_vote(chain_id, again)
            rep.add(
                "TS_REPEAT",
                sv2.signature == signed_pre.signature,
                "stored signature was not returned for the identical vote",
            )
        except Exception as e:  # noqa: BLE001
            rep.add("TS_REPEAT", False, str(e))

    # 5. SIGN_PROPOSAL (next height so HRS moves forward)
    try:
        p = Proposal(
            height=h + 1,
            round=0,
            pol_round=-1,
            block_id=_bid(b"\x52"),
            timestamp=Timestamp(seconds=1_700_000_100),
        )
        sp = signer.sign_proposal(chain_id, p)
        ok = pk.verify_signature(sp.sign_bytes(chain_id), sp.signature)
        rep.add("SIGN_PROPOSAL", bool(ok), "" if ok else "signature does not verify")
    except Exception as e:  # noqa: BLE001
        rep.add("SIGN_PROPOSAL", False, str(e))

    # 6. HRS_REGRESSION: height strictly below the last signed one
    low = Vote(
        type=PREVOTE_TYPE,
        height=h - 1,
        round=0,
        block_id=_bid(b"\x54"),
        timestamp=Timestamp(seconds=1_700_000_000),
        validator_address=pk.address(),
        validator_index=0,
    )
    try:
        signer.sign_vote(chain_id, low)
        rep.add("HRS_REGRESSION_REFUSED", False, "regressed height was signed")
    except Exception:  # noqa: BLE001
        rep.add("HRS_REGRESSION_REFUSED", True)

    return rep
