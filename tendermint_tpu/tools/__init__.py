"""Standalone tooling (tools/ in the reference tree)."""
