"""EventBus — typed event publication façade over libs.pubsub.

Reference parity: internal/eventbus/event_bus.go. Every block/tx/vote/
round-step event flows through here to RPC subscriptions and the indexer.
ABCI events emitted by the app are merged into the pubsub event map so
queries like `app.key='x' AND tm.event='Tx'` work.
"""

from __future__ import annotations

from typing import Dict, List, Optional

from ..libs.pubsub import Query, Server, Subscription
from ..libs.service import BaseService
from ..types import events as tme


def _merge_abci_events(base: Dict[str, List[str]], abci_events) -> None:
    """events.go: app events index as "<type>.<attr_key>"."""
    for ev in abci_events or []:
        if not ev.type:
            continue
        for attr in ev.attributes:
            if not attr.key:
                continue
            base.setdefault(f"{ev.type}.{attr.key}", []).append(attr.value)


class EventBus(BaseService):
    def __init__(self):
        super().__init__("EventBus")
        self._pubsub = Server()

    # -- subscriptions --------------------------------------------------

    def subscribe(self, subscriber: str, query: str, capacity: int = 100) -> Subscription:
        return self._pubsub.subscribe(subscriber, Query(query), capacity)

    def unsubscribe(self, subscriber: str, query: str) -> None:
        self._pubsub.unsubscribe(subscriber, Query(query))

    def unsubscribe_all(self, subscriber: str) -> None:
        self._pubsub.unsubscribe_all(subscriber)

    def num_clients(self) -> int:
        return self._pubsub.num_clients()

    def num_client_subscriptions(self, subscriber: str) -> int:
        return self._pubsub.num_client_subscriptions(subscriber)

    # -- publishers (event_bus.go:100-290) -------------------------------

    def _publish(self, event_type: str, data: object, extra: Optional[Dict[str, List[str]]] = None,
                 abci_events=None) -> None:
        events: Dict[str, List[str]] = {tme.EVENT_TYPE_KEY: [event_type]}
        if extra:
            for k, v in extra.items():
                events.setdefault(k, []).extend(v)
        _merge_abci_events(events, abci_events)
        self._pubsub.publish(data, events)

    def publish_new_block(self, block, block_id, abci_responses=None) -> None:
        abci_events = []
        if abci_responses is not None:
            from ..abci import types as abci

            bb = abci.dec_response_payload("begin_block", abci_responses.begin_block)
            eb = abci.dec_response_payload("end_block", abci_responses.end_block)
            abci_events = list(bb.events) + list(eb.events)
        self._publish(
            tme.EventNewBlock,
            {"block": block, "block_id": block_id},
            extra={tme.BLOCK_HEIGHT_KEY: [str(block.header.height)]},
            abci_events=abci_events,
        )

    def publish_new_block_header(self, header) -> None:
        self._publish(tme.EventNewBlockHeader, {"header": header})

    def publish_tx(self, height: int, index: int, tx: bytes, result_raw: bytes) -> None:
        from ..abci import types as abci
        from ..types.tx import tx_hash

        result = abci.dec_response_payload("deliver_tx", result_raw)
        self._publish(
            tme.EventTx,
            {"height": height, "index": index, "tx": tx, "result": result},
            extra={
                tme.TX_HASH_KEY: [tx_hash(tx).hex().upper()],
                tme.TX_HEIGHT_KEY: [str(height)],
            },
            abci_events=result.events,
        )

    def publish_validator_set_updates(self, updates) -> None:
        self._publish(tme.EventValidatorSetUpdates, {"validator_updates": updates})

    def publish_vote(self, vote) -> None:
        self._publish(tme.EventVote, {"vote": vote})

    def publish_new_evidence(self, evidence, height: int) -> None:
        self._publish(tme.EventNewEvidence, {"evidence": evidence, "height": height})

    def publish_new_round_step(self, rs) -> None:
        self._publish(tme.EventNewRoundStep, rs)

    def publish_new_round(self, rs) -> None:
        self._publish(tme.EventNewRound, rs)

    def publish_complete_proposal(self, rs) -> None:
        self._publish(tme.EventCompleteProposal, rs)

    def publish_polka(self, rs) -> None:
        self._publish(tme.EventPolka, rs)

    def publish_lock(self, rs) -> None:
        self._publish(tme.EventLock, rs)

    def publish_relock(self, rs) -> None:
        self._publish(tme.EventRelock, rs)

    def publish_valid_block(self, rs) -> None:
        self._publish(tme.EventValidBlock, rs)

    def publish_timeout_propose(self, rs) -> None:
        self._publish(tme.EventTimeoutPropose, rs)

    def publish_timeout_wait(self, rs) -> None:
        self._publish(tme.EventTimeoutWait, rs)
