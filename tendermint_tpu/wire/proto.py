"""Minimal deterministic proto3 encoder/decoder.

Matches gogoproto's generated marshalers (the reference's wire format,
e.g. proto/tendermint/types/canonical.pb.go:370-567):
  - fields emitted in ascending field-number order;
  - proto3 zero-value scalars omitted (0 / empty bytes / empty string);
  - *non-nullable* embedded messages (gogoproto.nullable=false, e.g.
    Timestamp in CanonicalVote, PartSetHeader in CanonicalBlockID) are
    ALWAYS emitted, even when empty — writers opt in via
    `write_message(..., always=True)`;
  - negative int32/int64 varints encode as 10-byte two's complement;
  - delimited framing is a uvarint length prefix
    (internal/libs/protoio/writer.go:54-80, MarshalDelimited :93).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple, Union

_U64_MASK = (1 << 64) - 1

# wire types
WT_VARINT = 0
WT_FIXED64 = 1
WT_BYTES = 2
WT_FIXED32 = 5


def encode_uvarint(v: int) -> bytes:
    if v < 0:
        raise ValueError("uvarint cannot be negative")
    out = bytearray()
    while v >= 0x80:
        out.append((v & 0x7F) | 0x80)
        v >>= 7
    out.append(v)
    return bytes(out)


def decode_uvarint(data: bytes, offset: int = 0) -> Tuple[int, int]:
    """Returns (value, next_offset)."""
    result = 0
    shift = 0
    while True:
        if offset >= len(data):
            raise ValueError("truncated uvarint")
        b = data[offset]
        offset += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, offset
        shift += 7
        if shift > 63:
            raise ValueError("uvarint overflow")


def _tag(field: int, wire_type: int) -> bytes:
    return encode_uvarint((field << 3) | wire_type)


class ProtoWriter:
    """Append-only message writer. Call write_* in ascending field order."""

    def __init__(self):
        self._buf = bytearray()

    def write_varint(self, field: int, value: int, always: bool = False) -> None:
        """int32/int64/uint64/enum/bool. Negative values are encoded as
        64-bit two's complement (proto3 int32/int64 semantics)."""
        if value == 0 and not always:
            return
        self._buf += _tag(field, WT_VARINT)
        self._buf += encode_uvarint(value & _U64_MASK)

    def write_sfixed64(self, field: int, value: int, always: bool = False) -> None:
        if value == 0 and not always:
            return
        self._buf += _tag(field, WT_FIXED64)
        self._buf += (value & _U64_MASK).to_bytes(8, "little")

    def write_fixed64(self, field: int, value: int, always: bool = False) -> None:
        self.write_sfixed64(field, value, always)

    def write_bytes(self, field: int, value: bytes, always: bool = False) -> None:
        if not value and not always:
            return
        self._buf += _tag(field, WT_BYTES)
        self._buf += encode_uvarint(len(value))
        self._buf += value

    def write_string(self, field: int, value: str, always: bool = False) -> None:
        self.write_bytes(field, value.encode("utf-8"), always)

    def write_message(self, field: int, encoded: Optional[bytes], always: bool = False) -> None:
        """Embedded message. None -> omitted (nullable); b"" with always=True
        -> emitted as zero-length (gogoproto non-nullable empty message)."""
        if encoded is None:
            return
        self._buf += _tag(field, WT_BYTES)
        self._buf += encode_uvarint(len(encoded))
        self._buf += encoded

    def bytes(self) -> bytes:
        return bytes(self._buf)

    def __len__(self) -> int:
        return len(self._buf)


FieldValue = Union[int, bytes]


def decode_message(data: bytes) -> Dict[int, List[Tuple[int, FieldValue]]]:
    """Parse a proto message into {field: [(wire_type, raw_value), ...]}.
    varint/fixed values come back as unsigned ints; bytes as bytes."""
    out: Dict[int, List[Tuple[int, FieldValue]]] = {}
    off = 0
    while off < len(data):
        key, off = decode_uvarint(data, off)
        field, wt = key >> 3, key & 7
        if field == 0:
            raise ValueError("field number 0 is invalid")
        if wt == WT_VARINT:
            val, off = decode_uvarint(data, off)
        elif wt == WT_FIXED64:
            if off + 8 > len(data):
                raise ValueError("truncated fixed64")
            val = int.from_bytes(data[off : off + 8], "little")
            off += 8
        elif wt == WT_BYTES:
            ln, off = decode_uvarint(data, off)
            if off + ln > len(data):
                raise ValueError("truncated bytes field")
            val = data[off : off + ln]
            off += ln
        elif wt == WT_FIXED32:
            if off + 4 > len(data):
                raise ValueError("truncated fixed32")
            val = int.from_bytes(data[off : off + 4], "little")
            off += 4
        else:
            raise ValueError(f"unsupported wire type {wt}")
        out.setdefault(field, []).append((wt, val))
    return out


def iter_fields(data: bytes):
    """Streaming variant of decode_message: yields (field, wire_type,
    value) in wire order without building the field dict or per-field
    lists. The columnar commit decode (types/block.py) walks each
    CommitSig record exactly once into numpy columns; at 10k signatures
    the dict/list allocations of decode_message were the dominant decode
    cost after object construction was removed."""
    off = 0
    ln_data = len(data)
    while off < ln_data:
        key, off = decode_uvarint(data, off)
        field, wt = key >> 3, key & 7
        if field == 0:
            raise ValueError("field number 0 is invalid")
        if wt == WT_VARINT:
            val, off = decode_uvarint(data, off)
        elif wt == WT_FIXED64:
            if off + 8 > ln_data:
                raise ValueError("truncated fixed64")
            val = int.from_bytes(data[off : off + 8], "little")
            off += 8
        elif wt == WT_BYTES:
            ln, off = decode_uvarint(data, off)
            if off + ln > ln_data:
                raise ValueError("truncated bytes field")
            val = data[off : off + ln]
            off += ln
        elif wt == WT_FIXED32:
            if off + 4 > ln_data:
                raise ValueError("truncated fixed32")
            val = int.from_bytes(data[off : off + 4], "little")
            off += 4
        else:
            raise ValueError(f"unsupported wire type {wt}")
        yield field, wt, val


def to_signed64(v: int) -> int:
    """Reinterpret an unsigned varint as int64."""
    return v - (1 << 64) if v >= (1 << 63) else v


def to_signed32(v: int) -> int:
    v &= 0xFFFFFFFF
    return v - (1 << 32) if v >= (1 << 31) else v


def field_bytes(
    fields: Dict[int, List[Tuple[int, FieldValue]]], num: int, default: bytes = b""
) -> bytes:
    vals = fields.get(num)
    if not vals:
        return default
    wt, val = vals[-1]
    if wt != WT_BYTES:
        raise ValueError(f"field {num}: expected bytes, got wire type {wt}")
    return val  # type: ignore[return-value]


def field_int(
    fields: Dict[int, List[Tuple[int, FieldValue]]], num: int, default: int = 0
) -> int:
    vals = fields.get(num)
    if not vals:
        return default
    wt, val = vals[-1]
    if wt == WT_BYTES:
        raise ValueError(f"field {num}: expected scalar, got length-delimited")
    return val  # type: ignore[return-value]


def field_repeated_bytes(
    fields: Dict[int, List[Tuple[int, FieldValue]]], num: int
) -> List[bytes]:
    """All values of a repeated length-delimited field; raises if any
    occurrence has a non-bytes wire type (adversarial input)."""
    out: List[bytes] = []
    for wt, val in fields.get(num, []):
        if wt != WT_BYTES:
            raise ValueError(f"repeated field {num}: expected bytes, got wire type {wt}")
        out.append(val)  # type: ignore[arg-type]
    return out


def marshal_delimited(encoded: bytes) -> bytes:
    """uvarint length prefix + message (protoio/writer.go:93-100)."""
    return encode_uvarint(len(encoded)) + encoded


def unmarshal_delimited(data: bytes) -> Tuple[bytes, int]:
    """Returns (message_bytes, total_consumed)."""
    ln, off = decode_uvarint(data, 0)
    if off + ln > len(data):
        raise ValueError("truncated delimited message")
    return data[off : off + ln], off + ln
