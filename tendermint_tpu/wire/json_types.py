"""JSON -> core-type decoding (the inverse of rpc/core.py's JSON shapes).

Reference parity: libs/json + rpc/client response decoding — RFC3339
times with nanosecond precision, hex-upper hashes, base64 keys and
signatures. Used by the MBT conformance driver (tests/vectors/mbt) and
the HTTP light-block provider.
"""

from __future__ import annotations

import base64
import calendar
import re

from ..crypto import ed25519
from ..types.block import (
    BlockID,
    Commit,
    CommitSig,
    Header,
    PartSetHeader,
    SignedHeader,
    Version,
)
from ..types.validator_set import Validator, ValidatorSet
from .canonical import Timestamp

_TIME_RE = re.compile(
    r"^(\d{4})-(\d{2})-(\d{2})T(\d{2}):(\d{2}):(\d{2})(?:\.(\d+))?Z$"
)


def parse_time(s: str) -> Timestamp:
    m = _TIME_RE.match(s)
    if not m:
        raise ValueError(f"bad RFC3339 time {s!r}")
    y, mo, d, h, mi, sec = (int(m.group(i)) for i in range(1, 7))
    frac = (m.group(7) or "").ljust(9, "0")
    secs = calendar.timegm((y, mo, d, h, mi, sec, 0, 0, 0))
    return Timestamp(seconds=secs, nanos=int(frac) if frac else 0)


def _hex(v) -> bytes:
    return bytes.fromhex(v) if v else b""


def parse_block_id(d) -> BlockID:
    if d is None:
        return BlockID()
    parts = d.get("parts") or d.get("part_set_header")
    psh = (
        PartSetHeader(total=int(parts["total"]), hash=_hex(parts["hash"]))
        if parts
        else PartSetHeader()
    )
    return BlockID(hash=_hex(d["hash"]), part_set_header=psh)


def parse_header(d) -> Header:
    return Header(
        version=Version(
            block=int(d["version"]["block"]), app=int(d["version"].get("app", 0))
        ),
        chain_id=d["chain_id"],
        height=int(d["height"]),
        time=parse_time(d["time"]),
        last_block_id=parse_block_id(d.get("last_block_id")),
        last_commit_hash=_hex(d.get("last_commit_hash")),
        data_hash=_hex(d.get("data_hash")),
        validators_hash=_hex(d["validators_hash"]),
        next_validators_hash=_hex(d["next_validators_hash"]),
        consensus_hash=_hex(d["consensus_hash"]),
        app_hash=_hex(d.get("app_hash")),
        last_results_hash=_hex(d.get("last_results_hash")),
        evidence_hash=_hex(d.get("evidence_hash")),
        proposer_address=_hex(d["proposer_address"]),
    )


def parse_commit(d) -> Commit:
    sigs = []
    for s in d["signatures"]:
        sigs.append(
            CommitSig(
                block_id_flag=int(s["block_id_flag"]),
                validator_address=_hex(s.get("validator_address")),
                timestamp=(
                    parse_time(s["timestamp"])
                    if s.get("timestamp")
                    else Timestamp.zero()
                ),
                signature=(
                    base64.b64decode(s["signature"]) if s.get("signature") else b""
                ),
            )
        )
    return Commit(
        height=int(d["height"]),
        round=int(d["round"]),
        block_id=parse_block_id(d["block_id"]),
        signatures=sigs,
    )


def parse_signed_header(d) -> SignedHeader:
    return SignedHeader(
        header=parse_header(d["header"]), commit=parse_commit(d["commit"])
    )


def parse_validator(v) -> Validator:
    pk = v["pub_key"]
    if pk.get("type") not in (None, "tendermint/PubKeyEd25519"):
        raise ValueError(f"unsupported pubkey type {pk.get('type')!r}")
    val = Validator.new(
        ed25519.PubKey(base64.b64decode(pk["value"])), int(v["voting_power"])
    )
    if v.get("proposer_priority") is not None:
        val.proposer_priority = int(v["proposer_priority"])
    if v.get("address"):
        want = _hex(v["address"])
        if val.address != want:
            raise ValueError("validator address does not match its pubkey")
    return val


def parse_validator_set(d) -> ValidatorSet:
    """Order-preserving (hash commits to the given order)."""
    vals = [parse_validator(v) for v in d["validators"]]
    vs = ValidatorSet(validators=vals)
    vs._update_total_voting_power()
    return vs


# ---------------------------------------------------------------------------
# core-type -> JSON encoding (ISSUE 11): the exact inverse of the parsers
# above, shape-identical to rpc/core.py's /commit and /validators results
# so /light_verify requests round-trip through one codec.
# ---------------------------------------------------------------------------


def time_to_json(ts: Timestamp) -> str:
    from ..types.genesis import _time_to_rfc3339

    return _time_to_rfc3339(ts)


def _hexs(b: bytes) -> str:
    return b.hex().upper()


def block_id_to_json(bid: BlockID) -> dict:
    return {
        "hash": _hexs(bid.hash),
        "parts": {
            "total": bid.part_set_header.total,
            "hash": _hexs(bid.part_set_header.hash),
        },
    }


def header_to_json(h: Header) -> dict:
    return {
        "version": {"block": str(h.version.block), "app": str(h.version.app)},
        "chain_id": h.chain_id,
        "height": str(h.height),
        "time": time_to_json(h.time),
        "last_block_id": block_id_to_json(h.last_block_id),
        "last_commit_hash": _hexs(h.last_commit_hash),
        "data_hash": _hexs(h.data_hash),
        "validators_hash": _hexs(h.validators_hash),
        "next_validators_hash": _hexs(h.next_validators_hash),
        "consensus_hash": _hexs(h.consensus_hash),
        "app_hash": _hexs(h.app_hash),
        "last_results_hash": _hexs(h.last_results_hash),
        "evidence_hash": _hexs(h.evidence_hash),
        "proposer_address": _hexs(h.proposer_address),
    }


def commit_to_json(c: Commit) -> dict:
    return {
        "height": str(c.height),
        "round": c.round,
        "block_id": block_id_to_json(c.block_id),
        "signatures": [
            {
                "block_id_flag": cs.block_id_flag,
                "validator_address": _hexs(cs.validator_address),
                "timestamp": time_to_json(cs.timestamp),
                "signature": (
                    base64.b64encode(cs.signature).decode()
                    if cs.signature
                    else None
                ),
            }
            for cs in c.signatures
        ],
    }


def signed_header_to_json(sh: SignedHeader) -> dict:
    return {
        "header": header_to_json(sh.header),
        "commit": commit_to_json(sh.commit),
    }


def validator_set_to_json(vs: ValidatorSet) -> dict:
    # parse_validator (above) accepts only ed25519 — refuse to emit a
    # foreign key under the ed25519 type tag (the bytes would round-trip
    # into a mismatched-address parse error at best)
    for v in vs.validators:
        if v.pub_key.type() != "ed25519":
            raise ValueError(
                f"validator pubkey type {v.pub_key.type()!r} has no JSON "
                f"wire form here (ed25519 only)"
            )
    return {
        "validators": [
            {
                "address": _hexs(v.address),
                "pub_key": {
                    "type": "tendermint/PubKeyEd25519",
                    "value": base64.b64encode(v.pub_key.bytes()).decode(),
                },
                "voting_power": str(v.voting_power),
                "proposer_priority": str(v.proposer_priority),
            }
            for v in vs.validators
        ]
    }
