"""Canonical sign-bytes construction.

Reference parity: types/canonical.go (CanonicalizeVote/Proposal/BlockID),
proto/tendermint/types/canonical.proto, and the generated marshalers in
canonical.pb.go:370-567. The resulting byte strings are what validators
ed25519-sign; they must match the reference bit-for-bit.

Encoded layout (gogoproto emission rules, see wire/proto.py docstring):
  CanonicalVote:     1 type(varint) 2 height(sfixed64) 3 round(sfixed64)
                     4 block_id(msg, nil-omitted) 5 timestamp(msg, ALWAYS)
                     6 chain_id(string)
  CanonicalProposal: 1 type 2 height 3 round 4 pol_round(varint)
                     5 block_id 6 timestamp(ALWAYS) 7 chain_id
  CanonicalBlockID:  1 hash(bytes) 2 part_set_header(msg, ALWAYS)
  CanonicalPartSetHeader: 1 total(varint) 2 hash(bytes)
  Timestamp:         1 seconds(varint int64) 2 nanos(varint int32)

The whole message is uvarint length-prefixed (types/vote.go:93-95,
protoio MarshalDelimited) — kept for hardware-signer compatibility.
"""

from __future__ import annotations

from typing import NamedTuple, Optional

from .proto import ProtoWriter, marshal_delimited

# SignedMsgType enum (proto/tendermint/types/types.pb.go:70-87)
SIGNED_MSG_TYPE_UNKNOWN = 0
SIGNED_MSG_TYPE_PREVOTE = 1
SIGNED_MSG_TYPE_PRECOMMIT = 2
SIGNED_MSG_TYPE_PROPOSAL = 32

# Go's zero time.Time (0001-01-01T00:00:00Z) as a proto Timestamp.
GO_ZERO_TIME_SECONDS = -62135596800


class Timestamp(NamedTuple):
    """google.protobuf.Timestamp value; Go zero time is the zero() value."""

    seconds: int = GO_ZERO_TIME_SECONDS
    nanos: int = 0

    @classmethod
    def zero(cls) -> "Timestamp":
        return cls(GO_ZERO_TIME_SECONDS, 0)

    def is_zero(self) -> bool:
        return self.seconds == GO_ZERO_TIME_SECONDS and self.nanos == 0


def encode_timestamp(ts: Timestamp) -> bytes:
    w = ProtoWriter()
    w.write_varint(1, ts.seconds)
    w.write_varint(2, ts.nanos)
    return w.bytes()


class CanonicalPartSetHeader(NamedTuple):
    total: int
    hash: bytes


class CanonicalBlockID(NamedTuple):
    hash: bytes
    part_set_header: CanonicalPartSetHeader


def encode_canonical_part_set_header(psh: CanonicalPartSetHeader) -> bytes:
    w = ProtoWriter()
    w.write_varint(1, psh.total)
    w.write_bytes(2, psh.hash)
    return w.bytes()


def encode_canonical_block_id(bid: CanonicalBlockID) -> bytes:
    w = ProtoWriter()
    w.write_bytes(1, bid.hash)
    # part_set_header is gogoproto non-nullable: always emitted
    w.write_message(2, encode_canonical_part_set_header(bid.part_set_header), always=True)
    return w.bytes()


def canonical_vote_sign_bytes(
    chain_id: str,
    msg_type: int,
    height: int,
    round_: int,
    block_id: Optional[CanonicalBlockID],
    timestamp: Timestamp,
) -> bytes:
    """VoteSignBytes (types/vote.go:84-101): delimited CanonicalVote.

    block_id must already be canonicalized: None iff the vote's BlockID is
    zero (types/canonical.go:18-34). Implemented via the template split so
    there is exactly one encoder for the cached and direct paths."""
    return compose_vote_sign_bytes(
        canonical_vote_template(chain_id, msg_type, height, round_, block_id),
        timestamp,
    )


def canonical_vote_template(
    chain_id: str,
    msg_type: int,
    height: int,
    round_: int,
    block_id: Optional[CanonicalBlockID],
) -> tuple:
    """Split the CanonicalVote encoding around its only per-signature field
    (the timestamp, field 5): (prefix = fields 1-4, suffix = field 6).
    compose_vote_sign_bytes(tpl, ts) == canonical_vote_sign_bytes(...) for
    every timestamp — a commit's 10k sign-bytes share one template
    (types/block.go:816-819 rebuilds the whole message per signature; the
    batch path here amortizes everything but the timestamp)."""
    w = ProtoWriter()
    w.write_varint(1, msg_type)
    w.write_sfixed64(2, height)
    w.write_sfixed64(3, round_)
    if block_id is not None:
        w.write_message(4, encode_canonical_block_id(block_id), always=True)
    prefix = w.bytes()
    w2 = ProtoWriter()
    w2.write_string(6, chain_id)
    return prefix, w2.bytes()


def compose_vote_sign_bytes(tpl: tuple, timestamp: Timestamp) -> bytes:
    prefix, suffix = tpl
    w = ProtoWriter()
    w.write_message(5, encode_timestamp(timestamp), always=True)
    return marshal_delimited(prefix + w.bytes() + suffix)


_U64 = (1 << 64) - 1


def _compose_one(prefix: bytes, suffix: bytes, ts: "Timestamp") -> bytes:
    """One record of the block composer's layout (scalar reference)."""
    from .proto import encode_uvarint

    tb = b""
    if ts.seconds:
        tb = b"\x08" + encode_uvarint(ts.seconds & _U64)
    if ts.nanos:
        tb += b"\x10" + encode_uvarint(ts.nanos & _U64)
    body = prefix + b"\x2a" + encode_uvarint(len(tb)) + tb + suffix
    return encode_uvarint(len(body)) + body


def _uvarint_len(v):
    """(n,) uint64 -> per-value uvarint byte length (numpy)."""
    import numpy as np

    length = np.ones(v.shape, dtype=np.int64)
    for k in range(1, 10):
        length += v >= np.uint64(1 << (7 * k))
    return length


def compose_vote_sign_bytes_block(tpl: tuple, timestamps) -> tuple:
    """Batch compose_vote_sign_bytes into ONE contiguous buffer: returns
    (buf, offsets) where buf[offsets[i]:offsets[i+1]] is the i-th vote's
    sign bytes — the EntryBlock msgs form (ops/entry_block.py), so the
    verify path never materializes per-signature PyBytes.

    Byte-identical to the per-call composer (differentially tested)."""
    import numpy as np

    prefix, suffix = tpl
    n = len(timestamps)
    if n and n < 64:
        offsets = np.zeros(n + 1, dtype=np.int64)
        chunks = [_compose_one(prefix, suffix, ts) for ts in timestamps]
        np.cumsum([len(c) for c in chunks], out=offsets[1:])
        return b"".join(chunks), offsets
    secs = np.fromiter(
        (ts.seconds for ts in timestamps), dtype=np.int64, count=n
    )
    nanos = np.fromiter(
        (ts.nanos for ts in timestamps), dtype=np.int64, count=n
    )
    return compose_vote_sign_bytes_cols(tpl, secs, nanos)


def compose_vote_sign_bytes_cols(
    tpl: tuple, secs_col, nanos_col, with_groups: bool = False
) -> tuple:
    """Column-input composer: (seconds (n,) int64, nanos (n,) int-like)
    arrays in, (buf, offsets) out — byte-identical to the per-call
    composer. The columnar commit path (ops/commit_prep.py) feeds the
    CommitBlock timestamp columns straight in, so no Timestamp objects
    exist anywhere between wire decode and the kernel.

    Records vary only in the two timestamp varints, so rows group by
    their (seconds-length, nanos-length) layout — a handful of groups per
    commit — and each group composes as one broadcast + vectorized varint
    fill instead of n ProtoWriter walks (~7x at 10k signatures). When
    every row shares one layout (the common case), the record matrix IS
    the output buffer — no scatter at all.

    with_groups=True appends a [(rows, (g, rec_len) uint8 array)] list so
    callers laying the same bytes into a second destination (the fused
    prep's SHA RAM blocks) can reuse the 2-D record matrices; the buffer
    then comes back as a 1-D uint8 ndarray (no bytes copy) instead of
    bytes."""
    import numpy as np

    prefix, suffix = tpl
    n = len(secs_col)
    offsets = np.zeros(n + 1, dtype=np.int64)
    if n == 0:
        return (b"", offsets, []) if with_groups else (b"", offsets)
    secs = np.ascontiguousarray(secs_col, dtype=np.int64).view(np.uint64)
    nanos = np.ascontiguousarray(nanos_col, dtype=np.int64).view(np.uint64)
    # per-row field layout: 0 length = field omitted (proto3 zero-skip)
    s_len = np.where(secs != 0, _uvarint_len(secs), 0)
    n_len = np.where(nanos != 0, _uvarint_len(nanos), 0)
    tn = (s_len != 0) * (1 + s_len) + (n_len != 0) * (1 + n_len)
    p_len, x_len = len(prefix), len(suffix)
    body_len = p_len + 2 + tn + x_len  # 0x2a + 1-byte uvarint(tn) + fields
    hdr_len = _uvarint_len(body_len.view(np.uint64))
    rec_len = hdr_len + body_len
    np.cumsum(rec_len, out=offsets[1:])
    pre_arr = np.frombuffer(prefix, dtype=np.uint8)
    suf_arr = np.frombuffer(suffix, dtype=np.uint8)

    def _fill_varint(dst, col, v, width):
        for j in range(width):
            b = (v >> np.uint64(7 * j)) & np.uint64(0x7F)
            if j < width - 1:
                b = b | np.uint64(0x80)
            dst[:, col + j] = b
        return col + width

    def _fill_group(rows):
        i0 = rows[0]
        sl, nl, hl = int(s_len[i0]), int(n_len[i0]), int(hdr_len[i0])
        rl, bl, t0 = int(rec_len[i0]), int(body_len[i0]), int(tn[i0])
        g = len(rows)
        # a commit's votes land within the same second (or two), so a
        # group's seconds column is usually ONE value: compose a single
        # template row, broadcast it, and fill only the varying varint
        # columns — one big write instead of ~15 per-column passes
        const_secs = g > 1 and sl and bool(
            (secs[rows] == secs[rows[0]]).all()
        )
        if const_secs:
            row = np.empty((1, rl), dtype=np.uint8)
            col = _fill_varint(row, 0, np.uint64(bl), hl)
            row[:, col : col + p_len] = pre_arr
            col += p_len
            row[:, col] = 0x2A
            row[:, col + 1] = t0
            col += 2
            row[:, col] = 0x08
            col = _fill_varint(row, col + 1, secs[rows[:1]], sl)
            n_col = col
            if nl:
                row[:, col] = 0x10
                col = _fill_varint(row, col + 1, nanos[rows[:1]], nl)
            row[:, col:] = suf_arr
            arr = np.empty((g, rl), dtype=np.uint8)
            arr[:] = row
            if nl:
                _fill_varint(arr, n_col + 1, nanos[rows], nl)
            return arr
        arr = np.empty((g, rl), dtype=np.uint8)
        col = _fill_varint(arr, 0, np.uint64(bl), hl)
        arr[:, col : col + p_len] = pre_arr
        col += p_len
        arr[:, col] = 0x2A
        arr[:, col + 1] = t0
        col += 2
        if sl:
            arr[:, col] = 0x08
            col = _fill_varint(arr, col + 1, secs[rows], sl)
        if nl:
            arr[:, col] = 0x10
            col = _fill_varint(arr, col + 1, nanos[rows], nl)
        arr[:, col:] = suf_arr
        return arr

    key = (s_len * 1024 + n_len * 16 + hdr_len).astype(np.int64)
    uniq = np.unique(key)
    groups = []
    if uniq.size == 1:
        rows = np.arange(n)
        arr = _fill_group(rows)
        if with_groups:
            groups.append((rows, arr))
            return arr.reshape(-1), offsets, groups
        return arr.tobytes(), offsets
    out = np.zeros(int(offsets[-1]), dtype=np.uint8)
    for k in uniq:
        rows = np.nonzero(key == k)[0]
        arr = _fill_group(rows)
        out[offsets[rows][:, None] + np.arange(arr.shape[1])] = arr
        groups.append((rows, arr))
    if with_groups:
        return out, offsets, groups
    return out.tobytes(), offsets


def canonical_proposal_sign_bytes(
    chain_id: str,
    height: int,
    round_: int,
    pol_round: int,
    block_id: Optional[CanonicalBlockID],
    timestamp: Timestamp,
) -> bytes:
    """ProposalSignBytes (types/proposal.go): delimited CanonicalProposal."""
    w = ProtoWriter()
    w.write_varint(1, SIGNED_MSG_TYPE_PROPOSAL)
    w.write_sfixed64(2, height)
    w.write_sfixed64(3, round_)
    w.write_varint(4, pol_round)
    if block_id is not None:
        w.write_message(5, encode_canonical_block_id(block_id), always=True)
    w.write_message(6, encode_timestamp(timestamp), always=True)
    w.write_string(7, chain_id)
    return marshal_delimited(w.bytes())
