"""Deterministic protobuf wire encoding.

Sign bytes are consensus-critical: every validator must produce the identical
byte string for the same vote, so this package hand-rolls proto3 encoding
with gogoproto's exact emission rules instead of relying on a generic
protobuf runtime. See wire/proto.py for the primitives and wire/canonical.py
for the canonical sign-bytes messages.
"""

from .proto import (  # noqa: F401
    ProtoWriter,
    decode_message,
    encode_uvarint,
    decode_uvarint,
    marshal_delimited,
    unmarshal_delimited,
)
