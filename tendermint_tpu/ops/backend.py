"""Host-side driver for the device verification engine.

Feeds fixed-shape, bucketed batches to the jitted ZIP-215 kernel and
implements the `crypto.BatchVerifier` interface so the engine plugs into
the dispatch seam (crypto/batch/batch.go:11-33 parity; see
tendermint_tpu.crypto.batch.use_device_engine).

Bucketing: XLA compiles one executable per shape, so batches are padded to
the next bucket size {128, 1024, 10240} (10240 covers the reference's
MaxVotesCount=10000, types/vote_set.go:18); larger inputs are chunked.
Padding lanes carry a throwaway-but-valid layout and are masked out.

The challenge scalar k = SHA512(R||A||M) mod L is computed host-side via
_challenges — the native batch helper (tm_native.ed25519_challenges,
OpenSSL SHA-512 + fold-based mod L in one C call per batch) when built,
else a hashlib loop. The per-sig Python loop it replaced measured ~50% of
end-to-end batch time on a loaded host. A device SHA-512 path
(ops.sha512 + prepare_batch_device_hash) exists for fixed-size
sign-bytes workloads.
"""

from __future__ import annotations

import functools
import hashlib
import os
import time
from typing import List, Tuple

import numpy as np

from ..crypto import BatchVerifier, PubKey
from ..crypto import ed25519 as _ed25519
from ..crypto._edwards import L
from ..libs import devcheck as _devcheck
from ..libs import metrics as _metrics
from ..observability import trace as _trace
from . import ed25519_verify
from .entry_block import EntryBlock, as_block

_span = _trace.span

_OPS = None


def _ops_m() -> "_metrics.OpsMetrics":
    """Process-wide ops metric set, cached to skip the registry lock on
    the per-batch hot path."""
    global _OPS
    if _OPS is None:
        _OPS = _metrics.ops_metrics()
    return _OPS


def _note_device_batch(n: int, bucket: int, prep_s: float = -1.0,
                       device_s: float = -1.0) -> None:
    """One dispatched device batch: counters + pad accounting (+ optional
    prep/device timing histograms when the caller measured them)."""
    m = _ops_m()
    b = str(bucket)
    m.batches.inc(bucket=b)
    m.sigs_verified.inc(n, path="device")
    if bucket > n:
        m.padded_lanes.inc(bucket - n)
    m.pad_waste_ratio.set(max(bucket - n, 0) / bucket if bucket else 0.0)
    if prep_s >= 0.0:
        m.host_prep_seconds.observe(prep_s, bucket=b)
    if device_s >= 0.0:
        m.device_seconds.observe(device_s, bucket=b)

BUCKETS = (128, 1024, 10240)

# Below this many signatures the per-call dispatch overhead beats the
# device win; use the host (OpenSSL) path. Mirrors the spirit of the
# reference's batchVerifyThreshold (types/validation.go:12) at device scale.
DEVICE_THRESHOLD = int(os.environ.get("TM_TPU_DEVICE_THRESHOLD", "64"))

# Messages up to this size hash on-device (R||A||M padded buffers);
# longer messages fall back to host hashlib for the challenge scalar.
# 192 covers canonical vote sign-bytes (~120B + 50-char chain ids).
# Defined in commit_prep (jax-free) so the types layer can size the fused
# prep's RAM columns without importing the device stack.
from .commit_prep import DEVICE_HASH_MAX_MSG  # noqa: E402

HOST_HASH = bool(int(os.environ.get("TM_TPU_HOST_HASH", "0")))

_L_BYTES = L.to_bytes(32, "little")


def _bucket_for(n: int) -> int:
    for b in BUCKETS:
        if n <= b:
            return b
    return BUCKETS[-1]


# The secp256k1 lane gets a finer bucket floor: with no RLC fusion the
# Strauss+GLV ladder's kernel time is ~linear per ROW (padding included),
# so a 10-signature commit on the 128 floor pays 12× its useful work —
# material on CPU backends where the ladder runs ~40 ms/row. One extra
# small shape in the compile cache buys it back.
SECP_BUCKETS = (16,) + BUCKETS


def _secp_bucket_for(n: int) -> int:
    for b in SECP_BUCKETS:
        if n <= b:
            return b
    return SECP_BUCKETS[-1]


def _pack_le_limbs(enc: np.ndarray) -> np.ndarray:
    """(B, 32) uint8 little-endian encodings -> (B, 20) int32 limbs of the
    low 255 bits (bit 255 — the sign bit — is excluded). Routes through the
    native packer (native/tm_native.cpp) when built."""
    from ..native import load as _load_native

    native = _load_native()
    n = enc.shape[0]
    if native is not None:
        raw = native.pack_le_limbs(np.ascontiguousarray(enc).tobytes(), n)
        return np.frombuffer(raw, dtype=np.int32).reshape(n, 20).copy()
    # vectorized word-shift extraction (mirrors the C packer): 4 uint64
    # words per row, 20 shifted 13-bit windows — ~6x the old
    # unpackbits-weights path, which built a (B, 20, 13) int32 transient
    w = np.ascontiguousarray(enc).view("<u8")  # (n, 4) LE words
    cols = [w[:, 0], w[:, 1], w[:, 2],
            w[:, 3] & np.uint64(0x7FFFFFFFFFFFFFFF)]  # bit 255 excluded
    out = np.empty((n, 20), dtype=np.int32)
    mask = np.uint64(0x1FFF)
    for limb in range(20):
        bit = limb * 13
        word, off = bit >> 6, bit & 63
        v = cols[word] >> np.uint64(off)
        if off > 64 - 13 and word < 3:
            v = v | (cols[word + 1] << np.uint64(64 - off))
        out[:, limb] = (v & mask).astype(np.int32)
    return out


def _bits_253(le32: np.ndarray) -> np.ndarray:
    """(B, 32) uint8 little-endian scalars (< 2^253) -> (253, B) int32 bits,
    transposed for the ladder's row indexing.

    Always the vectorized numpy path: the native pack_bits_le writes the
    transposed output column-wise (one lane's 253 bits stride the whole
    row axis) and measures 20 ms vs 1.8 ms here at a 10240 bucket — the
    rare case where C loses to numpy on access pattern alone."""
    n = le32.shape[0]
    # extract bits along the TRANSPOSED byte axis so the result lands
    # directly in ladder row order — no (B, 253) -> (253, B) strided
    # transpose copy (which dominated the old fallback at 10k lanes)
    tt = np.ascontiguousarray(le32.T)  # (32, B)
    bits = (tt[:, None, :] >> np.arange(8, dtype=np.uint8)[None, :, None]) & 1
    return bits.reshape(256, n)[:253].astype(np.int32)


_L_BE = np.frombuffer(L.to_bytes(32, "big"), dtype=np.uint8)


def _pack_rows(entries, bucket: int):
    """Bulk-pack a batch into padded (bucket, 32) pub/R/s arrays. For an
    EntryBlock the columns already exist — three slice-assigns, no joins
    or per-signature Python objects; tuple lists keep the two-join path
    (SURVEY.md §7 hard-part 3: host prep must not dominate the batch).

    Padding lanes: A = R = identity encoding (y=1), s = 0 — these verify
    trivially and keep the ladder numerically meaningful."""
    n = len(entries)
    pub = np.zeros((bucket, 32), dtype=np.uint8)
    r_enc = np.zeros((bucket, 32), dtype=np.uint8)
    s_enc = np.zeros((bucket, 32), dtype=np.uint8)
    if n:
        if isinstance(entries, EntryBlock):
            pub[:n] = entries.pub
            r_enc[:n] = entries.sig[:, :32]
            s_enc[:n] = entries.sig[:, 32:]
        else:
            # length check before the joins: a single wrong-length key
            # would otherwise silently shift every later lane after the
            # reshape
            if any(len(pk) != 32 or len(s) != 64 for pk, _, s in entries):
                raise ValueError("entries must be (pub32, msg, sig64) triples")
            pub[:n] = np.frombuffer(
                b"".join(pk for pk, _, _ in entries), dtype=np.uint8
            ).reshape(n, 32)
            sig = np.frombuffer(
                b"".join(s for _, _, s in entries), dtype=np.uint8
            ).reshape(n, 64)
            r_enc[:n] = sig[:, :32]
            s_enc[:n] = sig[:, 32:]
    pub[n:, 0] = 1
    r_enc[n:, 0] = 1
    return pub, r_enc, s_enc


def _challenges(r_enc: np.ndarray, pub: np.ndarray, msgs) -> bytes:
    """Batch challenge scalars k_i = SHA512(R_i||A_i||M_i) mod L, 32B LE
    each. Native C helper when built (one call for the whole batch — the
    per-sig Python loop measured ~50% of end-to-end batch time on a loaded
    host); hashlib fallback otherwise."""
    from ..native import load as _load_native

    native = _load_native()
    if native is not None and hasattr(native, "ed25519_challenges"):
        return native.ed25519_challenges(
            np.ascontiguousarray(r_enc).tobytes(),
            np.ascontiguousarray(pub).tobytes(),
            msgs,
        )
    # pure-Python fallback: one R||A prefix pre-join, then the hashlib +
    # bigint-mod floor per signature (CPython's 512-by-253-bit % beats a
    # vectorized numpy limb reduction here — measured 6.5 vs 19 ms/10k)
    n = len(msgs)
    ra = np.empty((n, 64), dtype=np.uint8)
    ra[:, :32] = r_enc[:n]
    ra[:, 32:] = pub[:n]
    ra_b = ra.tobytes()
    sha = hashlib.sha512
    return b"".join(
        (
            int.from_bytes(
                sha(ra_b[64 * i : 64 * i + 64] + m).digest(), "little"
            )
            % L
        ).to_bytes(32, "little")
        for i, m in enumerate(msgs)
    )


def _challenges_block(r_enc: np.ndarray, pub: np.ndarray,
                      block: EntryBlock) -> bytes:
    """Columnar _challenges: the whole batch's sign-bytes live in ONE
    buffer + offset table, so the native path is a single GIL-released
    call with no per-message Python objects; the hashlib fallback hashes
    zero-copy memoryview slices."""
    from ..native import load as _load_native

    native = _load_native()
    if native is not None and hasattr(native, "ed25519_challenges_buf"):
        buf, offs = block.msgs_contiguous()
        return native.ed25519_challenges_buf(
            np.ascontiguousarray(r_enc).tobytes(),
            np.ascontiguousarray(pub).tobytes(),
            buf,
            np.ascontiguousarray(offs).tobytes(),
        )
    # bytes slices (not memoryviews): hashlib's C fast path and the older
    # native sequence API both run measurably faster on real bytes
    buf, offs = block.msgs_contiguous()
    b = buf if isinstance(buf, bytes) else bytes(buf)
    o = offs.tolist()
    msgs = [b[o[i] : o[i + 1]] for i in range(len(block))]
    return _challenges(r_enc, pub, msgs)


def _challenges_any(r_enc: np.ndarray, pub: np.ndarray, entries) -> bytes:
    """Dispatch on the batch representation (EntryBlock vs tuple list)."""
    if isinstance(entries, EntryBlock):
        return _challenges_block(r_enc, pub, entries)
    return _challenges(r_enc, pub, [m for _, m, _ in entries])


def _s_below_l(s_enc: np.ndarray, n: int, bucket: int) -> np.ndarray:
    """Vectorized s < L check (RFC 8032 scalar range): big-endian
    lexicographic compare against L. Padding lanes pass (s = 0)."""
    s_ok = np.zeros((bucket,), dtype=bool)
    s_ok[n:] = True
    if n:
        s_be = s_enc[:n, ::-1]
        diff = s_be != _L_BE
        has_diff = diff.any(axis=1)
        first = diff.argmax(axis=1)
        rng = np.arange(n)
        s_ok[:n] = has_diff & (s_be[rng, first] < _L_BE[first])
    return s_ok


def prepare_batch(entries, bucket: int) -> tuple:
    """entries: EntryBlock or (pub32, msg, sig64) triples, len <= bucket.
    Returns the kernel argument tuple, padded to `bucket` lanes.

    EntryBlock + native module: the ENTIRE prep (row pack + SHA-512
    challenges + limb/bit pack + s<L) is ONE GIL-released C call
    (tm_native.ed25519_prep_fused) over the block's contiguous buffers —
    the per-commit GIL share this stage used to hold is what capped
    concurrent verify_commit throughput (PERF_r05). Columnar numpy and
    tuple-list fallbacks keep parity."""
    n = len(entries)
    t0 = time.perf_counter()
    with _span("ops.host_prep", n=n, bucket=bucket):
        if isinstance(entries, EntryBlock) and n:
            from ..native import load as _load_native

            native = _load_native()
            if native is not None and hasattr(native, "ed25519_prep_fused"):
                buf, offs = entries.msgs_contiguous()
                with _span("ops.prep_fused"):
                    pl, a_sign, rl, r_sign, sb, kb, sok = (
                        native.ed25519_prep_fused(
                            entries.pub.tobytes(),
                            entries.sig.tobytes(),
                            buf,
                            np.ascontiguousarray(offs).tobytes(),
                            bucket,
                        )
                    )
                args = (
                    np.frombuffer(pl, dtype=np.int32).reshape(bucket, 20),
                    np.frombuffer(a_sign, dtype=np.int32),
                    np.frombuffer(rl, dtype=np.int32).reshape(bucket, 20),
                    np.frombuffer(r_sign, dtype=np.int32),
                    np.frombuffer(sb, dtype=np.int32).reshape(253, bucket),
                    np.frombuffer(kb, dtype=np.int32).reshape(253, bucket),
                    np.frombuffer(sok, dtype=np.uint8).astype(bool),
                )
                _ops_m().host_prep_seconds.observe(
                    time.perf_counter() - t0, bucket=str(bucket)
                )
                return args
        with _span("ops.pack_rows"):
            pub, r_enc, s_enc = _pack_rows(entries, bucket)
        k_enc = np.zeros((bucket, 32), dtype=np.uint8)
        s_ok = _s_below_l(s_enc, n, bucket)
        if n:
            with _span("ops.challenges"):
                ks = _challenges_any(r_enc[:n], pub[:n], entries)
            k_enc[:n] = np.frombuffer(ks, dtype=np.uint8).reshape(n, 32)

        a_sign = (pub[:, 31] >> 7).astype(np.int32)
        r_sign = (r_enc[:, 31] >> 7).astype(np.int32)
        with _span("ops.limb_pack"):
            args = (
                _pack_le_limbs(pub),
                a_sign,
                _pack_le_limbs(r_enc),
                r_sign,
                _bits_253(s_enc),
                _bits_253(k_enc),
                s_ok,
            )
    _ops_m().host_prep_seconds.observe(
        time.perf_counter() - t0, bucket=str(bucket)
    )
    return args


def h2d_arg_bytes(args) -> int:
    """Host bytes a kernel-argument tuple ships to the device: numpy
    arrays transfer per call; jax Arrays (the epoch tables) are already
    device-resident and cost nothing per batch."""
    return sum(
        a.nbytes for a in args if isinstance(a, np.ndarray)
    )


def _pack_sig_rows(entries, bucket: int, ep):
    """Shared per-signature row prep for the epoch-cached paths: raw
    r/s rows (padding lanes identity/zero — the exact pattern _pack_rows
    gives the uncached kernels), host s<L flags, and the gather indices
    (padding lanes -> the table's identity row ep.vp - 1)."""
    n = len(entries)
    r_rows = np.zeros((bucket, 32), dtype=np.uint8)
    s_rows = np.zeros((bucket, 32), dtype=np.uint8)
    idx = np.full((bucket,), ep.vp - 1, dtype=np.int32)
    if n:
        r_rows[:n] = entries.sig[:, :32]
        s_rows[:n] = entries.sig[:, 32:]
        idx[:n] = entries.val_idx
    r_rows[n:, 0] = 1
    s_ok = _s_below_l(s_rows, n, bucket)
    return idx, r_rows, s_rows, s_ok


def cached_sig_args(entries: EntryBlock, bucket: int, ep) -> tuple:
    """The shared warm-epoch per-signature argument set: (idx, r_rows,
    s_rows, k_rows, s_ok (bucket,) bool) — gather indices, raw rows, and
    host SHA-512 challenges. Consumed by prepare_batch_cached (XLA) and
    pallas_verify.prepare_compact_cached; any padding or challenge-prep
    change lands in ONE place."""
    n = len(entries)
    idx, r_rows, s_rows, s_ok = _pack_sig_rows(entries, bucket, ep)
    k_rows = np.zeros((bucket, 32), dtype=np.uint8)
    if n:
        with _span("ops.challenges"):
            ks = _challenges_block(r_rows[:n], entries.pub, entries)
        k_rows[:n] = np.frombuffer(ks, dtype=np.uint8).reshape(n, 32)
    return idx, r_rows, s_rows, k_rows, s_ok


def prepare_batch_cached(entries: EntryBlock, bucket: int, ep) -> tuple:
    """Warm-epoch prep for jitted_verify_cached: NO pubkey-derived arrays
    and NO host limb/bit packing — the batch ships raw 32-byte rows
    (r/s/k) plus val_idx gather indices, and the device prologue unpacks
    (ed25519_verify.unpack_limbs_rows / bits253_rows). ~101 B/sig vs
    ~2.2 kB/sig for prepare_batch's unpacked arrays."""
    t0 = time.perf_counter()
    with _span("ops.host_prep", n=len(entries), bucket=bucket, cached=1):
        args = cached_sig_args(entries, bucket, ep)
    _ops_m().host_prep_seconds.observe(
        time.perf_counter() - t0, bucket=str(bucket)
    )
    return args


def prepare_batch_cached_device_hash(
    entries: EntryBlock, bucket: int, ep
) -> tuple:
    """Warm-epoch device-hash prep: per-signature R||A||M SHA blocks (the
    hash input — message data, shipped either way) + raw r/s rows +
    val_idx. Drops prepare_batch_device_hash's pubkey limb pack and the
    s-bit transpose entirely."""
    from . import sha512 as _sha

    n = len(entries)
    t0 = time.perf_counter()
    with _span("ops.host_prep", n=n, bucket=bucket, hash="device", cached=1):
        idx, r_rows, s_rows, s_ok = _pack_sig_rows(entries, bucket, ep)
        with _span("ops.sha_pad"):
            ram = None
            if entries.ram_hi is not None:
                ram = _sha.pad_ram_rows(
                    entries, bucket, 64 + DEVICE_HASH_MAX_MSG
                )
            if ram is None:
                ram = _sha.pad_ram_block(
                    entries, bucket, 64 + DEVICE_HASH_MAX_MSG
                )
            hi, lo, counts = ram
    args = (idx, r_rows, s_rows, hi, lo, counts, s_ok)
    _ops_m().host_prep_seconds.observe(
        time.perf_counter() - t0, bucket=str(bucket)
    )
    return args


@functools.lru_cache(maxsize=1)
def donate_enabled() -> bool:
    """Buffer donation default (ISSUE 7): ON for the TPU backend — donated
    launches let XLA recycle the batch input pages instead of growing the
    arena per launch — OFF elsewhere (CPU XLA ignores donation and warns
    per executable, so tier-1 runs opt in explicitly). TM_TPU_DONATE=1/0
    forces either way."""
    env = os.environ.get("TM_TPU_DONATE")
    if env is not None:
        return env != "0"
    import jax

    return jax.default_backend() == "tpu"


def cached_kernel(ep, device_hash: bool, donate: bool = False):
    """Kernel closure for a warm epoch: resolves the entry's device
    tables at CALL time — the caller is the pipeline's single
    dispatch-owner thread, so the one-time table upload happens on the
    only thread allowed to touch the relay. The tables ride as the two
    leading (never-donated) arguments; `donate` applies only to the
    per-batch args."""
    if device_hash:
        base = ed25519_verify.jitted_verify_cached_device_hash(donate)
    else:
        base = ed25519_verify.jitted_verify_cached(donate)

    def call(*args):
        tbl_limbs, tbl_sign = ep.xla_tables()
        return base(tbl_limbs, tbl_sign, *args)

    return call


# -- secp256k1 scheme lane (ISSUE 19) ---------------------------------------
#
# ECDSA has no RLC fusion and no pallas variant (follow-up work): the
# scheme rides the XLA per-signature kernel family only, with the same
# bucket ladder, donation contract, and warm-epoch gather split as
# ed25519. Host prep is python-int math (s^-1 mod n + GLV), so it runs on
# the prep pool like every other prep.


def _secp_items(entries) -> list:
    """EntryBlock (scheme secp256k1) or (pub33, msg, sig64) tuple list ->
    the item tuples ops/secp_verify.prepare_rows* consume."""
    if isinstance(entries, EntryBlock):
        mvs = entries.msg_views()
        return [
            (entries.pub_bytes(i), mvs[i], entries.sig[i].tobytes())
            for i in range(len(entries))
        ]
    return list(entries)


def prepare_batch_secp(entries, bucket: int) -> tuple:
    """Direct (uncached) secp256k1 prep: host decompression + GLV split
    -> the jitted_secp_verify arg arrays, padded to `bucket` with
    trivial-accept rows."""
    from . import secp_verify as _sv

    t0 = time.perf_counter()
    with _span("ops.host_prep", n=len(entries), bucket=bucket,
               scheme="secp256k1"):
        args = _sv.prepare_rows(_secp_items(entries), bucket)
    _ops_m().host_prep_seconds.observe(
        time.perf_counter() - t0, bucket=str(bucket)
    )
    return args


def prepare_batch_secp_cached(entries: EntryBlock, bucket: int, ep) -> tuple:
    """Warm-epoch secp prep: the committee's decompressed affine Q
    columns are device-resident (ep.secp_tables) — the batch ships gather
    indices + scalar data only."""
    from . import secp_verify as _sv

    t0 = time.perf_counter()
    with _span("ops.host_prep", n=len(entries), bucket=bucket,
               scheme="secp256k1", cached=1):
        args = _sv.prepare_rows_cached(
            _secp_items(entries), entries.val_idx, bucket, ep.vp - 1
        )
    _ops_m().host_prep_seconds.observe(
        time.perf_counter() - t0, bucket=str(bucket)
    )
    return args


def secp_kernel(donate: bool = False):
    from . import secp_verify as _sv

    return _sv.jitted_secp_verify(donate)


def secp_cached_kernel(ep, donate: bool = False):
    """Warm-epoch secp kernel closure: resolves the entry's device Q
    tables at CALL time on the dispatch-owner thread (the cached_kernel
    contract — tables are the leading never-donated arguments)."""
    from . import secp_verify as _sv

    base = _sv.jitted_secp_verify_cached(donate)

    def call(*args):
        qx, qy, q_ok = ep.secp_tables()
        return base(qx, qy, q_ok, *args)

    return call


def verify_batch_secp(entries) -> np.ndarray:
    """Run the secp256k1 device kernel over arbitrary batch size
    (EntryBlock with scheme secp256k1, or (pub33, msg, sig64) tuples);
    returns (n,) bool. Direct relay path — devcheck-exempt like
    verify_batch."""
    with _devcheck.exempt():
        from . import epoch_cache as _epoch
        from . import secp_verify as _sv

        ep = _epoch.lookup(entries)
        if ep is not None and ep.scheme != "secp256k1":
            ep = None
        out: List[np.ndarray] = []
        i = 0
        n_total = len(entries)
        while i < n_total:
            chunk = entries[i : i + BUCKETS[-1]]
            bucket = _secp_bucket_for(len(chunk))
            t0 = time.perf_counter()
            if ep is not None:
                args = prepare_batch_secp_cached(chunk, bucket, ep)
                kern = secp_cached_kernel(ep)
            else:
                args = prepare_batch_secp(chunk, bucket)
                kern = secp_kernel()
            t1 = time.perf_counter()
            with _span("ops.device_wait", bucket=bucket, scheme="secp256k1"):
                res = np.array(kern(*args))
            _note_device_batch(
                len(chunk), bucket, prep_s=t1 - t0,
                device_s=time.perf_counter() - t1,
            )
            out.append(res[: len(chunk)])
            i += len(chunk)
        return np.concatenate(out) if out else np.zeros((0,), dtype=bool)


# -- bls12381 aggregation lane (ISSUE 20) ------------------------------------
#
# One row is one aggregated COMMIT (a committee's worth of signatures
# collapsed into a single pairing check), so the bucket ladder is tiny:
# kernel time is ~linear in rows (2 Miller loops each) plus ONE fused
# final exponentiation amortized across the batch.

BLS_BUCKETS = (4, 16)

# Below this many concurrent aggregated commits the fused launch cannot
# amortize its final exponentiation; the pure-python oracle wins on
# latency and single commits verify synchronously
# (types/validation.py prepare_aggregated_commit).
BLS_DEVICE_THRESHOLD = int(os.environ.get("TM_TPU_BLS_DEVICE_THRESHOLD", "2"))


def _bls_bucket_for(n: int) -> int:
    for b in BLS_BUCKETS:
        if n <= b:
            return b
    return BLS_BUCKETS[-1]


def _bls_epoch(block):
    """AggBlock -> its EpochEntry or None. AggBlocks carry no val_idx
    (the signer bitmap IS the committee reference), so this bypasses
    epoch_cache.lookup()'s gather-index requirement and only guards the
    scheme."""
    from . import epoch_cache as _epoch

    key = getattr(block, "epoch_key", None)
    c = _epoch.cache()
    if key is None or c is None:
        return None
    ep = c.get(key)
    if ep is not None and ep.scheme != "bls12381":
        return None
    return ep


def _bls_bad_rows(pub48: np.ndarray) -> list:
    """Committee rows whose pubkey is unusable (malformed/identity/non-
    subgroup) — pubkey_status is memoized per key bytes, so this is a
    dict walk per batch after the first sight of an epoch."""
    from ..crypto import bls12381 as _bls

    return [
        i for i in range(pub48.shape[0])
        if _bls.pubkey_status(pub48[i].tobytes())[1] is not None
    ]


def prepare_batch_bls(block, bucket: int, vp: int, bad_rows=()) -> tuple:
    """Host prep for an AggBlock: Fiat-Shamir weights, G2 scalar muls and
    line-coefficient rows (ops/bls_verify.prepare_commits). Returns
    (masks, coeffs, ok, reasons); masks/coeffs are the device args, ok/
    reasons stay host-side for the verdict-code fold. Mesh pad rows
    (is_pad) are trailing by construction and prep as pad commits."""
    from . import bls_verify as _bv

    live = int(np.count_nonzero(~block.is_pad))
    if block.is_pad[:live].any():
        raise ValueError("AggBlock pad rows must be trailing")
    t0 = time.perf_counter()
    with _span("ops.host_prep", n=live, bucket=bucket, scheme="bls12381"):
        items = [
            (block.bits[i], block.msg(i), block.sig[i].tobytes())
            for i in range(live)
        ]
        masks, coeffs, ok, reasons = _bv.prepare_commits(
            items, bucket, vp, bad_rows=bad_rows
        )
    _ops_m().host_prep_seconds.observe(
        time.perf_counter() - t0, bucket=str(bucket)
    )
    return masks, coeffs, ok, reasons


def bls_kernel(block, ok, reasons, ep=None, donate: bool = False):
    """Launch closure for the aggregation lane: resolves the committee
    tables at CALL time (cached path: device residents owned by the
    epoch LRU; cold path: a host build from the block's pub48 snapshot),
    runs the two-launch verdict protocol (ops/bls_verify.run_verify) and
    returns the int32 verdict-code row as a HOST array — the protocol's
    branch point is a host reduce, so there is no device result left to
    read back."""
    from . import bls_verify as _bv

    def call(masks, coeffs):
        if ep is not None:
            tables = ep.bls_tables()
        else:
            tables = _bv.table_columns_g1(
                [r.tobytes() for r in block.pub48]
            )
        verdicts, cfail, apk_nz = _bv.run_verify(
            tables, masks, coeffs, ok, donate=donate
        )
        return _bv.verdict_codes(verdicts, cfail, apk_nz, reasons)

    return call


def verify_batch_bls_codes(block) -> np.ndarray:
    """Run the aggregation lane over an AggBlock; returns the (k,) int32
    verdict-code row (ops/bls_verify code constants). Direct relay path —
    devcheck-exempt like verify_batch."""
    with _devcheck.exempt():
        from . import bls_verify as _bv

        k = len(block)
        if k == 0:
            return np.zeros((0,), dtype=np.int32)
        ep = _bls_epoch(block)
        bad = _bls_bad_rows(block.pub48)
        vp = ep.vp if ep is not None else block.pub48.shape[0] + 1
        out: List[np.ndarray] = []
        i = 0
        while i < k:
            chunk = block[i : i + BLS_BUCKETS[-1]]
            bucket = _bls_bucket_for(len(chunk))
            t0 = time.perf_counter()
            masks, coeffs, ok, reasons = prepare_batch_bls(
                chunk, bucket, vp, bad_rows=bad
            )
            kern = bls_kernel(chunk, ok, reasons, ep=ep)
            t1 = time.perf_counter()
            with _span("ops.device_wait", bucket=bucket, scheme="bls12381"):
                # owning copy: np.asarray would alias the XLA buffer, and a
                # donated later launch could mutate the slice we hand out
                codes = np.array(kern(masks, coeffs))
            _note_device_batch(
                len(chunk), bucket, prep_s=t1 - t0,
                device_s=time.perf_counter() - t1,
            )
            out.append(codes[: len(chunk)])
            i += len(chunk)
        return np.concatenate(out)


def verify_batch_bls(block) -> np.ndarray:
    """Boolean face of the aggregation lane (one bool per COMMIT row)."""
    from . import bls_verify as _bv

    return verify_batch_bls_codes(block) == _bv.CODE_VALID


def prepare_batch_device_hash(entries, bucket: int) -> tuple:
    """Device-hash argument prep: no host SHA-512 — messages ship as padded
    R||A||M SHA blocks. EntryBlock input pads columnar (pad_ram_block);
    tuple lists build the per-message R||A||M bytes as before."""
    from . import sha512 as _sha

    n = len(entries)
    t0 = time.perf_counter()
    with _span("ops.host_prep", n=n, bucket=bucket, hash="device"):
        with _span("ops.pack_rows"):
            pub, r_enc, s_enc = _pack_rows(entries, bucket)
        s_ok = _s_below_l(s_enc, n, bucket)
        with _span("ops.sha_pad"):
            if isinstance(entries, EntryBlock):
                ram = None
                if entries.ram_hi is not None:
                    # fused commit prep already laid the R||A||M SHA
                    # blocks per row — pad rows, skip the byte scatter
                    ram = _sha.pad_ram_rows(
                        entries, bucket, 64 + DEVICE_HASH_MAX_MSG
                    )
                if ram is not None:
                    hi, lo, counts = ram
                else:
                    hi, lo, counts = _sha.pad_ram_block(
                        entries, bucket, 64 + DEVICE_HASH_MAX_MSG
                    )
            else:
                msgs = [sig[:32] + pk + msg for pk, msg, sig in entries]
                msgs += [b"\x01" + bytes(31) + b"\x01" + bytes(31)] * (
                    bucket - n
                )
                hi, lo, counts = _sha.pad_messages(
                    msgs, 64 + DEVICE_HASH_MAX_MSG
                )
        a_sign = (pub[:, 31] >> 7).astype(np.int32)
        r_sign = (r_enc[:, 31] >> 7).astype(np.int32)
        with _span("ops.limb_pack"):
            args = (
                _pack_le_limbs(pub),
                a_sign,
                _pack_le_limbs(r_enc),
                r_sign,
                _bits_253(s_enc),
                hi,
                lo,
                counts,
                s_ok,
            )
    _ops_m().host_prep_seconds.observe(
        time.perf_counter() - t0, bucket=str(bucket)
    )
    return args


@functools.lru_cache(maxsize=1)
def _use_pallas() -> bool:
    """Kernel selection: the 3-stage Pallas pipeline (ops.pallas_verify)
    on real TPU hardware — ~14x the XLA op-graph kernel there (measured
    round 3: per-op dispatch/HBM overhead dominates the op-graph path on
    the relay-attached device). On CPU backends the XLA kernel compiles
    natively while Pallas would interpret, so the op-graph path stays.
    TM_TPU_PALLAS=1/0 forces either way."""
    env = os.environ.get("TM_TPU_PALLAS")
    if env is not None:
        return env != "0"
    import jax

    return jax.default_backend() == "tpu"


def _pallas_bucket(n: int) -> int:
    from . import pallas_verify

    b = pallas_verify.BLOCK
    return max(b, min(((n + b - 1) // b) * b, BUCKETS[-1]))


def quantized_bucket(n: int) -> int:
    """Device bucket (in signatures) a batch of n will be padded to."""
    if _use_pallas() and _use_rlc():
        from . import pallas_rlc

        return pallas_rlc.plan_bucket(n)[0]
    return _bucket_for(n)


def max_coalesce() -> int:
    """Largest device batch the async pipeline may fuse concurrent jobs
    into. The RLC path raises it well past MaxVotesCount: the relay's
    flat per-transfer latency makes bigger batches strictly faster (see
    pallas_rlc.MAX_SIGS)."""
    if _use_pallas() and _use_rlc():
        from . import pallas_rlc

        return pallas_rlc.MAX_SIGS
    return BUCKETS[-1]


@functools.lru_cache(maxsize=1)
def _use_rlc() -> bool:
    """RLC fast-accept lane packing (ops.pallas_rlc): M signatures share
    one ladder per lane — ~1.45x the per-sig kernel on hardware (22.8 vs
    33 ms/10240). Default ON for the TPU pallas path; TM_TPU_RLC=1/0
    forces either way (tests force 1 on the CPU interpret backend)."""
    env = os.environ.get("TM_TPU_RLC")
    if env is not None:
        return env != "0"
    import jax

    return jax.default_backend() == "tpu"


def _max_msg_len(entries) -> int:
    """Longest message in a batch — O(1) columnar from an EntryBlock's
    offset table, a generator scan for tuple lists."""
    if isinstance(entries, EntryBlock):
        if not len(entries):
            return 0
        return int(np.diff(entries.offsets).max())
    return max((len(m) for _, m, _ in entries), default=0)


def verify_batch(entries) -> np.ndarray:
    """Run the device kernel over arbitrary batch size (EntryBlock or
    tuple list); returns (n,) bool.

    This is the SANCTIONED direct relay path (oversized batches past the
    pipeline's max bucket, standalone use, warmup) — under
    TM_TPU_DEVCHECK it runs in a devcheck.exempt() scope so the lazy
    epoch-table uploads it may trigger on the caller thread do not trip
    the relay-ownership assertion while a dispatcher owns the relay."""
    scheme = getattr(entries, "scheme", "ed25519")
    if scheme == "secp256k1":
        return verify_batch_secp(entries)
    if scheme == "bls12381":
        return verify_batch_bls(entries)
    with _devcheck.exempt():
        return _verify_batch_direct(entries)


def _verify_batch_direct(entries) -> np.ndarray:
    if _use_pallas():
        from . import pallas_verify

        interpret = False
        import jax

        if jax.default_backend() != "tpu":
            interpret = True  # forced-on under tests: tiny batches only
        if _use_rlc():
            from . import pallas_rlc

            n = len(entries)
            t0 = time.perf_counter()
            with _span("ops.device_rlc", n=n):
                res = pallas_rlc.verify_batch_rlc(entries, interpret=interpret)
            elapsed = time.perf_counter() - t0
            # verify_batch_rlc chunks internally at MAX_SIGS — account per
            # chunk so batches/padded_lanes match what actually dispatched;
            # elapsed (prep+device, coarse) is attributed to the first
            # chunk only so device_seconds is not multiply counted
            i = 0
            while i < n:
                c = min(n - i, pallas_rlc.MAX_SIGS)
                _note_device_batch(
                    c, pallas_rlc.plan_bucket(c)[0],
                    device_s=elapsed if i == 0 else -1.0,
                )
                i += c
            return res
        out = []
        i = 0
        while i < len(entries):
            chunk = entries[i : i + BUCKETS[-1]]
            bucket = _pallas_bucket(len(chunk))
            t0 = time.perf_counter()
            with _span("ops.host_prep", n=len(chunk), bucket=bucket):
                args = pallas_verify.prepare_compact(chunk, bucket)
            t1 = time.perf_counter()
            with _span("ops.device_wait", bucket=bucket):
                res = pallas_verify.verify_compact(*args, interpret=interpret)
            _note_device_batch(
                len(chunk), bucket, prep_s=t1 - t0,
                device_s=time.perf_counter() - t1,
            )
            out.append(res[: len(chunk)])
            i += len(chunk)
        return np.concatenate(out) if out else np.zeros((0,), dtype=bool)

    device_hash = not HOST_HASH and _max_msg_len(entries) <= DEVICE_HASH_MAX_MSG
    from . import epoch_cache as _epoch

    ep = _epoch.lookup(entries)
    out: List[np.ndarray] = []
    i = 0
    while i < len(entries):
        chunk = entries[i : i + BUCKETS[-1]]
        bucket = _bucket_for(len(chunk))
        # same donate flag as the pipeline's _prepare: the jitted-wrapper
        # caches key on it, so defaulting here would compile every bucket
        # twice (and de-warm warmup())
        donate = donate_enabled()
        if ep is not None:
            # warm epoch: committee gathers from the device-resident
            # table, per-sig rows ship raw and unpack on device
            kern = cached_kernel(ep, device_hash, donate)
            if device_hash:
                args = prepare_batch_cached_device_hash(chunk, bucket, ep)
            else:
                args = prepare_batch_cached(chunk, bucket, ep)
        elif device_hash:
            kern = ed25519_verify.jitted_verify_device_hash(donate)
            args = prepare_batch_device_hash(chunk, bucket)
        else:
            kern = ed25519_verify.jitted_verify(donate)
            args = prepare_batch(chunk, bucket)
        # dispatch vs wait split: jax dispatch returns before the device
        # finishes; the np.asarray blocks until the result materializes
        t0 = time.perf_counter()
        with _span("ops.device_dispatch", bucket=bucket):
            dev = kern(*args)
        with _span("ops.device_wait", bucket=bucket):
            # owned copy, not a view: under donation a later chunk's
            # launch recycles the output page and would mutate earlier
            # chunks' verdicts still sitting in `out` (the PR-7 bug
            # class, here across the chunks of ONE oversized batch)
            res = np.asarray(dev)[: len(chunk)].copy()
        _note_device_batch(
            len(chunk), bucket, device_s=time.perf_counter() - t0
        )
        out.append(res)
        i += len(chunk)
    return np.concatenate(out) if out else np.zeros((0,), dtype=bool)


class Ed25519DeviceBatchVerifier(BatchVerifier):
    """Accumulate-then-verify on the device engine.

    Length/type validation on add() mirrors curve25519-voi's BatchVerifier
    Add (crypto/ed25519/ed25519.go:203-217); verify() returns
    (all_valid, per_sig_valid) like BatchVerifier.Verify (:219-227).
    """

    def __init__(self, force_device: bool = False):
        self._entries: List[Tuple[bytes, bytes, bytes]] = []
        self._blocks: List[EntryBlock] = []
        self._force = force_device or bool(
            int(os.environ.get("TM_TPU_FORCE_DEVICE", "0"))
        )

    def add(self, key: PubKey, msg: bytes, sig: bytes) -> None:
        if not isinstance(key, _ed25519.PubKey):
            raise TypeError("pubkey is not ed25519")
        if len(sig) != _ed25519.SIGNATURE_SIZE:
            raise ValueError("invalid signature length")
        self._entries.append((key.bytes(), msg, sig))

    def add_entries(self, entries, lengths_checked: bool = False) -> None:
        """Bulk add(): one validation pass + one extend instead of a call
        frame per signature (the per-commit GIL time this saves directly
        raises concurrent verify_commit throughput). The per-key TYPE
        check always runs — only the proposer's key type is validated at
        verifier creation, and a mixed-key validator set must fail here
        exactly as per-entry add() does. lengths_checked=True skips only
        the signature-length scan for callers that already enforced it
        (validation.py checks lengths during selection)."""
        if any(not isinstance(k, _ed25519.PubKey) for k, _, _ in entries):
            raise TypeError("pubkey is not ed25519")
        if not lengths_checked and any(
            len(s) != _ed25519.SIGNATURE_SIZE for _, _, s in entries
        ):
            raise ValueError("invalid signature length")
        self._entries.extend((k.bytes(), m, s) for k, m, s in entries)

    def add_block(self, block: EntryBlock, keys=None) -> None:
        """Columnar bulk add: the block rides BY REFERENCE to the device
        prep — no per-signature tuples at any point. `keys` (optional
        iterable of the lanes' PubKey objects) runs the same per-key TYPE
        check as add()/add_entries; lengths are structural in the block's
        (n, 32)/(n, 64) shape."""
        if keys is not None and any(
            not isinstance(k, _ed25519.PubKey) for k in keys
        ):
            raise TypeError("pubkey is not ed25519")
        if len(block):
            # flush interleaved add() entries first so verify order (and
            # blame indices) match submission order
            if self._entries:
                self._blocks.append(EntryBlock.from_entries(self._entries))
                self._entries = []
            self._blocks.append(block)

    def _collect(self) -> EntryBlock:
        blocks = list(self._blocks)
        if self._entries:
            blocks.append(EntryBlock.from_entries(self._entries))
        return EntryBlock.concat(blocks)

    def verify(self) -> Tuple[bool, List[bool]]:
        n = len(self._entries) + sum(len(b) for b in self._blocks)
        if n == 0:
            return False, []
        if n < DEVICE_THRESHOLD and not self._force:
            m = _ops_m()
            m.host_fallback.inc()
            m.sigs_verified.inc(n, path="host")
            with _span("ops.verify_host", n=n):
                valid = [
                    _ed25519.verify_zip215_fast(pk, mg, s)
                    for pk, mg, s in self._collect().iter_entries()
                ]
            return all(valid), valid
        block = self._collect()
        # Default path is the shared async pipeline (VERDICT r3 item 1b):
        # one worker thread owns every device dispatch, so concurrent
        # commit verifies coalesce into full buckets and overlap host prep
        # + D2H with device compute instead of serializing RTTs.
        if n <= BUCKETS[-1]:
            from .pipeline import shared_verifier

            with _span("ops.pipeline_wait", n=n):
                res = shared_verifier().submit(block).result(timeout=600)
        else:
            res = verify_batch(block)
        res = np.asarray(res).astype(bool)
        # .all() and .tolist() both run in C — keeps the documented
        # (bool, List[bool]) interface without a 10k-iteration Python loop
        return bool(res.all()), res.tolist()


def warmup(bucket: int = BUCKETS[0]) -> None:
    """Pre-compile the kernel for a bucket (first XLA compile is slow)."""
    verify_batch([])  # no-op; keeps import light
    args = prepare_batch([], bucket)
    # the donate flag keys the jitted-wrapper cache — warm the variant
    # the pipeline will actually launch
    np.asarray(ed25519_verify.jitted_verify(donate_enabled())(*args))
