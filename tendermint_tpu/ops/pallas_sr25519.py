"""Device sr25519 (schnorrkel) verification — the ristretto lane.

Reference parity: crypto/sr25519/batch.go:13-19 (curve25519-voi's
schnorrkel batch verifier). Schnorr verification
    R == [s]B - [k]A,  k = merlin signing-transcript challenge
shares the joint double-scalar ladder with the ed25519 kernel
(ops.pallas_verify K2/K3 shapes); what differs is point DECODING
(ristretto255 DECODE instead of ZIP-215 edwards decompression) and the
final test (exact ristretto equality against R instead of cofactored
identity). The merlin challenges are host-side via the native C++
transcript (native/tm_native.cpp sr25519_challenges; pure-Python
fallback), s/k scalars feed the same shift-grouped digit layout.

Round-3 measured context: pure-Python sr25519 verify is ~10 ms/sig — the
mixed-curve BASELINE config #4 was host-bound; this lane moves the EC
math (2 scalar mults/sig) onto the device and the transcripts into C.

STATUS (round 4): production — compiles on the TPU in ~16s and matches
the host oracle at production buckets (block 512, bucket 2048 verified
on hardware); the round-3 Mosaic compile hang no longer reproduces. The
lane is ON by default; ops.mixed's first-use watchdog still time-boxes
the compile (TM_TPU_SR_COMPILE_TIMEOUT) and falls back to the native
host lane rather than wedge a caller.
"""

from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from . import fe_t, pallas_verify as pv
from ..crypto import _edwards

NL = fe_t.NLIMBS
P = _edwards.P
D = _edwards.D


def _ristretto_decode(s_limbs, ok_host):
    """ristretto255 DECODE on (20, B) limbs of s (host pre-checked:
    canonical s < p and even). Returns (ok (1,B), point)."""
    one = fe_t.limbs_from_int_t(1)
    d_col = fe_t.limbs_from_int_t(D)
    s = fe_t.carry(s_limbs)
    ss = fe_t.sq(s)
    u1 = fe_t.sub(one + jnp.zeros_like(s), ss)  # 1 - s^2
    u2 = fe_t.add(one + jnp.zeros_like(s), ss)  # 1 + s^2
    u2_sqr = fe_t.sq(u2)
    # v = -(D * u1^2) - u2^2
    v = fe_t.sub(fe_t.neg(fe_t.mul(d_col, fe_t.sq(u1))), u2_sqr)
    # invsqrt(v * u2^2): sqrt_ratio(1, x) gives r with x*r^2 == 1 when ok
    was_square, invsq = pv.sqrt_ratio(one + jnp.zeros_like(s), fe_t.mul(v, u2_sqr))
    den_x = fe_t.mul(invsq, u2)
    den_y = fe_t.mul(fe_t.mul(invsq, den_x), v)
    x = fe_t.mul(fe_t.add(s, s), den_x)
    # |x|: negate when odd
    x = fe_t.canon(x)
    x = jnp.where((x[0:1] & 1) != 0, fe_t.neg(x), x)
    y = fe_t.mul(u1, den_y)
    t = fe_t.mul(x, y)
    t_odd = (fe_t.canon(t)[0:1] & 1) != 0
    y_zero = fe_t.is_zero(y)
    ok = was_square & ~t_odd & ~y_zero & (ok_host != 0)
    z = jnp.broadcast_to(one, y.shape)
    return ok, (x, y, z, t)


def _k1r_decode_kernel(a_ref, r_ref, s_ref, k_ref, aok_ref, rok_ref,
                       coords_ref, ok_ref, sdig_ref, kdig_ref):
    """Ristretto decode of A and R (lane-folded) + scalar digit unpack.
    Output layout matches pallas_verify's K1 (32-row coordinate slots)."""
    a_enc = a_ref[:].astype(jnp.int32)
    r_enc = r_ref[:].astype(jnp.int32)
    sdig_ref[:] = pv._unpack_digits2_grouped(s_ref[:].astype(jnp.int32))
    kdig_ref[:] = pv._unpack_digits2_grouped(k_ref[:].astype(jnp.int32))

    a_y, _ = pv._unpack_limbs(a_enc)  # sign bit is rejected host-side
    r_y, _ = pv._unpack_limbs(r_enc)
    B = a_y.shape[-1]
    ok_ar, AR = _ristretto_decode(
        pv._cat([a_y, r_y]),
        pv._cat([aok_ref[0:1], rok_ref[0:1]]),
    )
    ok_ref[0:1] = ok_ar[:, :B].astype(jnp.int32)
    ok_ref[1:2] = ok_ar[:, B:].astype(jnp.int32)
    for c in range(4):
        coords_ref[c * 32 : c * 32 + NL] = AR[c][:, :B]
        coords_ref[(4 + c) * 32 : (4 + c) * 32 + NL] = AR[c][:, B:]


def _k3r_ladder_kernel(tbl_ref, sdig_ref, kdig_ref, coords_ref, ok_ref,
                       sok_ref, out_ref):
    """Joint ladder acc = [s]B + [k](-A), then EXACT ristretto equality
    against R: x1*y2 == y1*x2 or y1*y2 == x1*x2 (z cancels on both sides
    since R decodes with z=1 and both tests are cross-multiplied)."""
    B = sok_ref.shape[-1]
    zero = jnp.zeros((NL, B), dtype=jnp.int32)
    one = fe_t.limbs_from_int_t(1)
    ident = (zero, one + zero, one + zero, zero)

    def select(idx):
        out = [tbl_ref[c * 32 : c * 32 + NL] for c in range(4)]
        for e in range(1, 16):
            m = (idx == e)[None, :]
            for c in range(4):
                out[c] = jnp.where(
                    m, tbl_ref[(e * 4 + c) * 32 : (e * 4 + c) * 32 + NL], out[c]
                )
        return tuple(out)

    def body(i, acc):
        j = pv._digit_row(126 - i)
        # table entries are Niels-form since the shared K2 stores them
        # that way (pallas_verify._k2_table_kernel to_niels)
        acc = pv.point_double(pv.point_double(acc, need_t=False))
        return pv.point_add_niels(
            acc, select(sdig_ref[j] + 4 * kdig_ref[j]), need_t=False
        )

    acc = lax.fori_loop(0, 127, body, ident)
    rx = coords_ref[4 * 32 : 4 * 32 + NL]
    ry = coords_ref[5 * 32 : 5 * 32 + NL]
    rz = coords_ref[6 * 32 : 6 * 32 + NL]
    # acc == R (projective, ristretto equivalence class)
    eq1 = fe_t.is_zero(
        fe_t.sub(fe_t.mul(acc[0], ry), fe_t.mul(acc[1], rx))
    )
    eq2 = fe_t.is_zero(
        fe_t.sub(fe_t.mul(acc[1], ry), fe_t.mul(acc[0], rx))
    )
    del rz
    valid = (
        (ok_ref[0:1] != 0) & (ok_ref[1:2] != 0) & (sok_ref[0:1] != 0)
        & (eq1 | eq2)
    )
    out_ref[:] = valid.astype(jnp.int32)


@functools.lru_cache(maxsize=None)
def _jitted_sr25519_verify(n: int, block: int, interpret: bool):
    k2_block = min(block, 256)

    def mkspec(b):
        def spec(rows):
            return pl.BlockSpec((rows, b), lambda i: (0, i), memory_space=pltpu.VMEM)

        return spec

    spec = mkspec(block)
    spec2 = mkspec(k2_block)

    k1 = pl.pallas_call(
        _k1r_decode_kernel,
        grid=(n // block,),
        in_specs=[spec(32)] * 4 + [spec(1), spec(1)],
        out_specs=[spec(8 * 32), spec(2), spec(128), spec(128)],
        out_shape=[
            jax.ShapeDtypeStruct((8 * 32, n), jnp.int32),
            jax.ShapeDtypeStruct((2, n), jnp.int32),
            jax.ShapeDtypeStruct((128, n), jnp.int32),
            jax.ShapeDtypeStruct((128, n), jnp.int32),
        ],
        interpret=interpret,
    )
    k2 = pl.pallas_call(
        pv._k2_table_kernel,
        grid=(n // k2_block,),
        in_specs=[spec2(8 * 32)],
        out_specs=spec2(16 * 4 * 32),
        out_shape=jax.ShapeDtypeStruct((16 * 4 * 32, n), jnp.int32),
        interpret=interpret,
    )
    k3 = pl.pallas_call(
        _k3r_ladder_kernel,
        grid=(n // block,),
        in_specs=[spec(16 * 4 * 32), spec(128), spec(128), spec(8 * 32), spec(2), spec(1)],
        out_specs=spec(1),
        out_shape=jax.ShapeDtypeStruct((1, n), jnp.int32),
        interpret=interpret,
    )

    def pipeline(a_t, r_t, s_t, k_t, aok_t, rok_t, sok_t):
        coords, ok, sdig, kdig = k1(a_t, r_t, s_t, k_t, aok_t, rok_t)
        tbl = k2(coords)
        return k3(tbl, sdig, kdig, coords, ok, sok_t)

    return jax.jit(pipeline)


_P_BE = np.frombuffer(P.to_bytes(32, "big"), dtype=np.uint8)


def _canonical_even(enc: np.ndarray, n: int, bucket: int) -> np.ndarray:
    """(bucket, 32) LE field encodings -> host-side ristretto encoding
    admission: value < p AND even (ristretto rejects negative s)."""
    ok = np.zeros((bucket,), dtype=bool)
    ok[n:] = True  # padding (all-zero = identity encoding)
    if n:
        be = enc[:n, ::-1]
        diff = be != _P_BE
        has_diff = diff.any(axis=1)
        first = diff.argmax(axis=1)
        rng = np.arange(n)
        below_p = has_diff & (be[rng, first] < _P_BE[first])
        ok[:n] = below_p & ((enc[:n, 0] & 1) == 0)
    return ok


def prepare_sr25519(entries, bucket: int):
    """(pub32, msg, sig64) schnorrkel triples -> kernel args. Host work:
    v1-marker/s<L checks, canonical-encoding flags, merlin challenges
    (native C++, pure-Python fallback) reduced mod L."""
    from ..crypto._edwards import L
    from ..crypto.sr25519 import SIGNING_CTX, _signing_transcript
    from ..native import load as _load_native
    from .backend import _pack_rows, _s_below_l

    n = len(entries)
    marker_ok = np.zeros((bucket,), dtype=bool)
    marker_ok[n:] = True
    cleaned = []
    for i, (pk, msg, sig) in enumerate(entries):
        if len(sig) != 64 or len(pk) != 32:
            marker_ok[i] = False
            cleaned.append((bytes(32), msg, bytes(64)))
            continue
        sig = bytearray(sig)
        marker_ok[i] = bool(sig[63] & 0x80)
        sig[63] &= 0x7F
        cleaned.append((pk, msg, bytes(sig)))
    pub, r_enc, s_enc = _pack_rows(cleaned, bucket)
    # padding: _pack_rows pads with the EDWARDS identity encoding (0x01),
    # which is an odd (invalid) ristretto encoding — the ristretto
    # identity is the all-zero string
    pub[n:] = 0
    r_enc[n:] = 0
    s_ok = _s_below_l(s_enc, n, bucket) & marker_ok
    a_ok = _canonical_even(pub, n, bucket)
    r_ok = _canonical_even(r_enc, n, bucket)

    k_enc = np.zeros((bucket, 32), dtype=np.uint8)
    if n:
        native = _load_native()
        pubs = b"".join(pk for pk, _, _ in cleaned)
        rss = bytes(r_enc[:n].tobytes())
        msgs = [m for _, m, _ in cleaned]
        if native is not None:
            raw = native.sr25519_challenges(SIGNING_CTX, pubs, rss, msgs)
            digests = [raw[64 * i : 64 * (i + 1)] for i in range(n)]
        else:
            digests = []
            for (pk, msg, _), i in zip(cleaned, range(n)):
                t = _signing_transcript(msg)
                t.append_message(b"proto-name", b"Schnorr-sig")
                t.append_message(b"sign:pk", pk)
                t.append_message(b"sign:R", rss[32 * i : 32 * (i + 1)])
                digests.append(t.challenge_bytes(b"sign:c", 64))
        ks = b"".join(
            (int.from_bytes(d, "little") % L).to_bytes(32, "little") for d in digests
        )
        k_enc[:n] = np.frombuffer(ks, dtype=np.uint8).reshape(n, 32)

    return (
        np.ascontiguousarray(pub.T),
        np.ascontiguousarray(r_enc.T),
        np.ascontiguousarray(s_enc.T),
        np.ascontiguousarray(k_enc.T),
        np.ascontiguousarray(a_ok.astype(np.int32)[None, :]),
        np.ascontiguousarray(r_ok.astype(np.int32)[None, :]),
        np.ascontiguousarray(s_ok.astype(np.int32)[None, :]),
    )


def verify_sr25519_compact(*args, block: int = 0, interpret: bool = False):
    block = block or pv.BLOCK
    n = args[0].shape[-1]
    if n % block:
        raise ValueError(f"batch {n} not a multiple of block {block}")
    out = _jitted_sr25519_verify(n, block, interpret)(*args)
    return np.asarray(out)[0].astype(bool)
