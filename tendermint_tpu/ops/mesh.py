"""Mesh-aware dispatcher lane packing — multichip scale-out serving.

ISSUE 9 tentpole / ROADMAP §2: after PRs 4-7 the single-device path is
pipelined, epoch-cached and overlapped; the binding constraint is
device-count. `ops/sharded.py` proves the sharded kernels compile, but
nothing *feeds* a mesh with concurrent work — every queued commit still
serializes through one device's lanes. This module turns the pipeline's
coalescer into a **mesh dispatcher**: many commits in flight (many
chains / many heights — the millions-of-users shape) are bin-packed into
per-shard **lanes** of one `(n_lanes, lane_bucket)` superbatch per
launch, so one relay command carries every device's work for the step.

Packing model (committee-scale batching, arXiv 2302.00418):

    lane        one device shard's contiguous `lane_bucket` rows of the
                superbatch. A lane holds whole jobs (EntryBlocks) that
                share ONE epoch key — same-epoch blocks gather from the
                same device-resident table; mixed epochs land in
                DIFFERENT lanes, never mixed within one.
    pad rows    short lanes are completed with identity rows (A = R =
                the identity encoding, s = 0 — verify trivially under
                any challenge, exactly `_pack_rows`' padding lanes), so
                every lane is a full compiled shard.
    superbatch  lanes concatenated on the batch axis: `n_lanes *
                lane_bucket` rows, `n_lanes` rounded up to a power of
                two (compiled-shape discipline — shapes stay in
                {1,2,4,8,...} x BUCKETS). With `jax.shard_map` available
                the batch axis shards lane-per-device over the mesh
                (ops/sharded.mesh_valid_fn); otherwise the SAME
                superbatch launches through the plain jitted kernel —
                bit-identical verdicts, "simulated lanes" (the tier-1 /
                CPU face, and the warn-once fallback of ISSUE 9's first
                satellite).
    demux       per-job verdict spans are global row ranges
                (lane_idx * lane_bucket + offset) into the one verdict
                row — readback stays a single slice per job, blame
                indices unchanged.

The packing itself is pure host bookkeeping (numpy + EntryBlock — no
jax, no crypto), importable standalone the way ops/device_pool.py is;
`prepare_superbatch` is the only device-facing function and defers every
heavy import. Uploads and launches remain the property of the
pipeline's single dispatch-owner thread: this module builds plans and
argument tuples, the dispatcher transfers and launches them (the relay
single-owner invariant, tmlint relay-ownership + devcheck).

Knobs:
    TM_TPU_MESH              lane count: 0/unset = disabled (classic
                             single-lane dispatch), N = pack up to N
                             lanes per launch, "auto" = one lane per
                             visible jax device.
    TM_TPU_MESH_LANE_BUCKET  per-lane signature capacity cap (default:
                             the largest single-device bucket).
"""

from __future__ import annotations

import os
from typing import List, Optional, Tuple

import numpy as np

try:
    from .entry_block import AggBlock, EntryBlock, block_concat
except ImportError:  # pragma: no cover — standalone file load (crypto-less
    # containers exec this module by path for the jax-free packing tests;
    # entry_block is numpy-only and loads the same way)
    import importlib.util as _ilu
    import os as _os

    _eb_path = _os.path.join(
        _os.path.dirname(_os.path.abspath(__file__)), "entry_block.py"
    )
    _eb_spec = _ilu.spec_from_file_location(
        "_tm_tpu_entry_block_standalone", _eb_path
    )
    _eb = _ilu.module_from_spec(_eb_spec)
    _eb_spec.loader.exec_module(_eb)
    EntryBlock = _eb.EntryBlock
    AggBlock = _eb.AggBlock
    block_concat = _eb.block_concat

# single-device bucket ladder (ops/backend.BUCKETS, duplicated here so the
# packing layer stays importable without the device stack; backend asserts
# they agree at prepare_superbatch time)
_BUCKETS = (128, 1024, 10240)
# smallest lane bucket an operator may force via TM_TPU_MESH_LANE_BUCKET:
# the secp256k1 ladder's fine bucket floor (backend.SECP_BUCKETS) — its
# per-row kernel cost makes small lanes worthwhile, and the ed25519
# kernel handles any shape the packer emits
_LANE_BUCKET_FLOOR = 16

# BLS12-381 aggregation lanes (ISSUE 20) quantize to their OWN tiny
# ladder (backend.BLS_BUCKETS, asserted in sync at prep time): one row
# is one whole aggregated commit costing two Miller loops, so padding an
# agg lane out to `lane_bucket` per-signature rows would burn orders of
# magnitude more kernel time than the live work. Superbatch row offsets
# therefore accumulate per-lane widths instead of assuming a uniform
# lane stride.
_BLS_LANE_BUCKETS = (4, 16)


def _lane_width(n: int, scheme: str, lane_bucket: int) -> int:
    """Padded row count of one lane: `lane_bucket` for per-signature
    schemes, the smallest BLS bucket covering `n` commits (exact above
    the ladder top — the kernel jits per shape either way) for the
    aggregation lane."""
    if scheme != "bls12381":
        return lane_bucket
    for b in _BLS_LANE_BUCKETS:
        if n <= b:
            return b
    return n


def lanes_from_env() -> int:
    """TM_TPU_MESH -> lane count (0 = mesh dispatch disabled)."""
    env = os.environ.get("TM_TPU_MESH", "").strip().lower()
    if not env or env == "0":
        return 0
    if env == "auto":
        try:
            import jax

            return max(len(jax.devices()), 1)
        except Exception:  # noqa: BLE001 — no jax: mesh mode off
            return 0
    try:
        return max(int(env), 0)
    except ValueError:
        return 0


def lane_cap() -> int:
    """Max signatures one lane may hold (whole jobs only — submit()
    chunks oversized jobs at this bound in mesh mode). Clamped into the
    bucket ladder: a lane larger than the top bucket would let a lane
    outgrow every compiled shape."""
    env = os.environ.get("TM_TPU_MESH_LANE_BUCKET")
    if env:
        try:
            return min(max(int(env), _LANE_BUCKET_FLOOR), _BUCKETS[-1])
        except ValueError:
            pass
    return _BUCKETS[-1]


def _bucket_for(n: int) -> int:
    for b in _BUCKETS:
        if n <= b:
            return b
    return _BUCKETS[-1]


def _next_pow2(n: int) -> int:
    p = 1
    while p < n:
        p *= 2
    return p


def _pow2_floor(n: int) -> int:
    p = 1
    while p * 2 <= n:
        p *= 2
    return p


class Lane:
    """One shard's worth of packed jobs: single epoch key, single
    signature scheme (ISSUE 19 — a mixed-scheme commit's ed25519 and
    secp256k1 halves land in DIFFERENT lanes of the same superbatch),
    whole jobs, live rows <= the plan's lane_bucket."""

    __slots__ = ("key", "scheme", "jobs", "n")

    def __init__(self, key: Optional[bytes], scheme: str = "ed25519"):
        self.key = key
        self.scheme = scheme
        self.jobs: List = []  # objects with an `.entries` EntryBlock
        self.n = 0

    def add(self, job) -> None:
        self.jobs.append(job)
        self.n += len(job.entries)


class MeshPlan:
    """A packed superbatch: `lanes` live lanes (possibly fewer than
    `n_lanes` — the rest are pure identity padding), each padded to
    `lane_bucket` rows; `empty_jobs` resolve as zero-width spans without
    occupying a lane. `bucket` is the launch shape in signatures."""

    __slots__ = ("lanes", "lane_bucket", "n_lanes", "empty_jobs")

    def __init__(self, lanes: List[Lane], max_lanes: int,
                 lane_bucket: Optional[int] = None):
        self.lanes = lanes
        self.empty_jobs: List = []
        self.lane_bucket = lane_bucket or min(
            _bucket_for(max((l.n for l in lanes), default=1)), lane_cap()
        )
        # power-of-two lane count keeps the compiled-shape set small:
        # {1,2,4,...} x the bucket ladder — a non-pow2 TM_TPU_MESH is
        # floored (pack_jobs applies the same floor, so the plan always
        # has room for every lane it packed)
        self.n_lanes = min(
            _next_pow2(max(len(lanes), 1)),
            _pow2_floor(max(max_lanes, 1)),
        )

    @property
    def bucket(self) -> int:
        """Total superbatch rows. With only per-signature lanes this is
        `n_lanes * lane_bucket` (every lane strides uniformly); BLS
        aggregation lanes contribute their own quantized width
        (_lane_width) instead."""
        fill = self.n_lanes - len(self.lanes)
        s0 = self.schemes()[0] if fill else None
        total = fill * _lane_width(0, s0, self.lane_bucket) if fill else 0
        for l in self.lanes:
            total += _lane_width(l.n, l.scheme, self.lane_bucket)
        return total

    @property
    def live(self) -> int:
        return sum(l.n for l in self.lanes)

    @property
    def pad(self) -> int:
        return self.bucket - self.live

    def occupancy(self) -> float:
        """Mean live fraction across the superbatch's lanes (a pure-pad
        lane contributes 0)."""
        return self.live / self.bucket if self.bucket else 0.0

    def pad_ratio(self) -> float:
        return self.pad / self.bucket if self.bucket else 0.0

    def epoch_key(self) -> Optional[bytes]:
        """The superbatch's single epoch key, or None when lanes mix
        epochs (mixed packs ride the uncached prep — pubs ship with the
        batch, exactly EntryBlock.concat's mixed-key fallback)."""
        keys = {l.key for l in self.lanes}
        if len(keys) == 1:
            return next(iter(keys))
        return None

    def schemes(self) -> List[str]:
        """The plan's signature schemes in superblock segment order
        (ed25519 first — its pure-pad filler lanes extend the first
        segment)."""
        found = {l.scheme for l in self.lanes}
        return [s for s in ("ed25519", "secp256k1")
                if s in found or (s == "ed25519" and not found)] + sorted(
                    s for s in found if s not in ("ed25519", "secp256k1"))


def pack_jobs(jobs, max_lanes: int, cap: Optional[int] = None,
              ) -> Tuple[MeshPlan, List]:
    """First-fit bin-pack `jobs` (each with an `.entries` EntryBlock)
    into at most `max_lanes` single-epoch lanes of `cap` signatures.
    Jobs that fit nowhere are returned as held-over for the next
    superbatch (exactly the coalescer's bucket-overflow hold). A job
    larger than `cap` raises — submit() must chunk first.

    QoS ordering (ISSUE 13): jobs pack in (priority, seq) order — a
    CONSENSUS-class job claims its lane before any queued INGRESS
    superjob, so when the pack overflows into the hold list it is the
    lowest-priority latest arrivals that wait for the next superbatch.
    Jobs without the attributes (direct callers, older tests) default to
    the most urgent class in arrival order — the pre-QoS behavior."""
    cap = cap or lane_cap()
    # pow2 lane-count discipline (see MeshPlan): never pack more lanes
    # than the plan will have room for
    max_lanes = _pow2_floor(max(max_lanes, 1))
    lanes: List[Lane] = []
    held: List = []
    empty: List = []
    jobs = sorted(
        jobs,
        key=lambda j: (getattr(j, "priority", 0), getattr(j, "seq", 0)),
    )
    for job in jobs:
        n = len(job.entries)
        if n > cap:
            raise ValueError(
                f"job of {n} sigs exceeds the {cap}-sig lane capacity"
            )
        if n == 0:
            # empty submissions resolve as zero-width spans without
            # pinning a lane (an empty job's key must not demote a
            # same-warm-epoch pack to the uncached prep)
            empty.append(job)
            continue
        key = job.entries.epoch_key
        scheme = getattr(job.entries, "scheme", "ed25519")

        def _fits(l, n=n, key=key, scheme=scheme):
            # bucket-aware fit (the classic coalescer's peel rule, as a
            # pack-time predicate): fusing must not push the lane into a
            # BIGGER ladder bucket unless the fused total nearly fills
            # it — e.g. two 600-sig jobs stay separate 1024-bucket lanes
            # instead of one 1200-live lane quantized to 10240 rows.
            # Scheme-keyed (ISSUE 19): a lane holds ONE scheme — the
            # superblock concatenates per-scheme sub-blocks and the
            # launch runs each scheme's kernel over its own row range.
            if l.key != key or l.scheme != scheme or l.n + n > cap:
                return False
            b = _bucket_for(l.n + n)
            if b == _bucket_for(l.n):
                return True
            return b - (l.n + n) <= max(b // 8, 1024)

        lane = next((l for l in lanes if _fits(l)), None)
        if lane is None:
            if len(lanes) < max_lanes:
                lane = Lane(key, scheme)
                lanes.append(lane)
            else:
                held.append(job)
                continue
        lane.add(job)
    plan = MeshPlan(lanes, max_lanes)
    plan.empty_jobs = empty
    return plan, held


import functools as _functools
import hashlib as _hashlib


@_functools.lru_cache(maxsize=1)
def _secp_pad_row() -> Tuple[bytes, bytes]:
    """The secp256k1 padding lane's (pub33, sig64): a REAL lower-S ECDSA
    signature of the empty message under the generator as pubkey (d = 1,
    nonce k = 1 ⇒ r = Gx mod n, s = ±(e + r) mod n), so pad rows ride
    the normal prep/kernel path and verify deterministically True —
    exactly ed25519's identity-pad convention, no special-casing
    anywhere downstream. Self-contained integer math (standalone file
    loads must not need the crypto package)."""
    n_ord = 0xFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFEBAAEDCE6AF48A03BBFD25E8CD0364141
    gx = 0x79BE667EF9DCBBAC55A06295CE870B07029BFCDB2DCE28D959F2815B16F81798
    e = int.from_bytes(_hashlib.sha256(b"").digest(), "big") % n_ord
    r = gx % n_ord
    s = (e + r) % n_ord
    if s > n_ord // 2:
        s = n_ord - s
    pub = bytes([2]) + gx.to_bytes(32, "big")  # compress(G); Gy is even
    sig = r.to_bytes(32, "big") + s.to_bytes(32, "big")
    return pub, sig


def pad_block(n: int, ep=None, scheme: str = "ed25519") -> EntryBlock:
    """`n` padding rows as an EntryBlock. ed25519: A = R = the identity
    encoding (y = 1), s = 0, empty message — verifies trivially under
    any challenge scalar (the `_pack_rows` padding-lane construction).
    secp256k1: the fixed trivially-valid generator signature
    (_secp_pad_row). bls12381: committee-free AggBlock pad rows — the
    backend preps those from its fixed self-signed pad commit
    (bls_verify.PAD_MSG), and AggBlock.concat lets them adopt the lane's
    committee. With a warm epoch entry `ep`, rows carry the table's
    pad-row gather index (vp - 1) and the epoch key, so a cached
    superbatch's padding gathers the table's own pad row."""
    if scheme == "bls12381":
        return AggBlock.pad(n)
    pub = np.zeros((n, 32), dtype=np.uint8)
    sig = np.zeros((n, 64), dtype=np.uint8)
    pub_aux = None
    if scheme == "secp256k1":
        pad_pub, pad_sig = _secp_pad_row()
        pub_aux = np.full((n,), pad_pub[0], dtype=np.uint8)
        if n:
            pub[:] = np.frombuffer(pad_pub[1:], dtype=np.uint8)
            sig[:] = np.frombuffer(pad_sig, dtype=np.uint8)
    elif n:
        pub[:, 0] = 1
        sig[:, 0] = 1  # R = identity encoding; s stays 0
    offsets = np.zeros(n + 1, dtype=np.int64)
    val_idx = epoch_key = None
    if ep is not None:
        val_idx = np.full((n,), ep.vp - 1, dtype=np.int32)
        epoch_key = ep.key
    return EntryBlock(pub, sig, b"", offsets,
                      val_idx=val_idx, epoch_key=epoch_key,
                      scheme=scheme, pub_aux=pub_aux)


def _warm_entry(plan: MeshPlan):
    """The plan's epoch-cache entry iff every lane shares one WARM key
    (lazy import — the cache layer is jax-free but lives behind the ops
    package; standalone loads only exercise the packing half)."""
    key = plan.epoch_key()
    if key is None:
        return None
    try:
        from . import epoch_cache as _epoch
    except ImportError:  # pragma: no cover — standalone file load
        return None
    # lookup keys off the (epoch_key, val_idx) attrs; probe via a stub
    class _Probe:
        epoch_key = key
        val_idx = True

    return _epoch.lookup(_Probe())


class SchemeSuperBlock:
    """A mixed-scheme superbatch (ISSUE 19): EntryBlock.concat refuses
    cross-scheme merges, so the superblock holds one contiguous
    EntryBlock SEGMENT per scheme plus its global row offset. Demux
    spans index the fused verdict row exactly as for a plain superblock;
    prepare_superbatch preps each segment with its scheme's kernel and
    the launch fn concatenates the per-segment verdicts — ONE dispatch
    for the whole mixed commit.

    BLS12-381 aggregation lanes (ISSUE 20) appear as one segment PER
    LANE (an AggBlock is bound to one committee's pubkey table, so two
    agg lanes never merge), and their verdict rows are int32 codes —
    concatenating them with the boolean per-signature verdicts promotes
    the fused row to int32, which demux slicing is agnostic to."""

    __slots__ = ("parts", "_n")

    def __init__(self, parts: List[Tuple], n: int):
        self.parts = parts  # [(scheme, EntryBlock, row_offset), ...]
        self._n = n

    def __len__(self) -> int:
        return self._n

    @property
    def epoch_key(self):  # mixed segments never share one epoch table
        return None


def build_superblock(plan: MeshPlan) -> Tuple[object, List[Tuple]]:
    """Materialize the plan: exactly `plan.bucket` rows (live jobs +
    per-lane padding + pure-pad lanes) and the global demux spans
    [(job, row_offset, n), ...]. Column concat is one np.concatenate per
    column — no per-signature Python. Single-scheme plans return one
    EntryBlock; mixed-scheme plans return a SchemeSuperBlock whose
    segments group each scheme's lanes contiguously (pure-pad filler
    lanes extend the FIRST scheme's segment)."""
    ep = _warm_entry(plan)
    lb = plan.lane_bucket
    order = plan.schemes()
    # emit lanes grouped by scheme; filler pad lanes ride with the first
    # scheme's segment so every segment stays contiguous
    seq: List[Tuple] = []
    for s in order:
        seq.extend((l, s) for l in plan.lanes if l.scheme == s)
        if s == order[0]:
            seq.extend(
                (None, s) for _ in range(plan.n_lanes - len(plan.lanes))
            )
    # segments in seq order: per-signature lanes of one scheme merge into
    # one contiguous EntryBlock segment; every BLS lane stays its OWN
    # segment — agg lanes are keyed on epoch_key and two committees'
    # AggBlocks must never cross-concat (each lane gathers from its own
    # pubkey table)
    segs: List[Tuple] = []  # [(scheme, [blocks])]
    spans: List[Tuple] = []
    base = 0
    for lane, s in seq:
        w = _lane_width(lane.n if lane is not None else 0, s, lb)
        blocks: List = []
        off = 0
        if lane is not None:
            for job in lane.jobs:
                n = len(job.entries)
                spans.append((job, base + off, n))
                if n:
                    blocks.append(job.entries)
                off += n
        if off < w:
            blocks.append(pad_block(w - off, ep, s))
        if s != "bls12381" and segs and segs[-1][0] == s:
            segs[-1][1].extend(blocks)
        else:
            segs.append((s, blocks))
        base += w
    for job in plan.empty_jobs:
        spans.append((job, 0, 0))
    if len(segs) == 1 and segs[0][0] != "bls12381":
        return EntryBlock.concat(segs[0][1]), spans
    parts: List[Tuple] = []
    off = 0
    for s, blocks in segs:
        blk = block_concat(blocks)
        parts.append((s, blk, off))
        off += len(blk)
    return SchemeSuperBlock(parts, off), spans


# ---------------------------------------------------------------------------
# Device-facing half: superbatch prep + kernel selection. Runs on the
# pipeline's prep pool; the returned launch fn runs ONLY on the
# dispatch-owner thread (which also owns the transfer and any lazy
# epoch-table upload inside the cached closures).
# ---------------------------------------------------------------------------


def _prepare_mixed_superbatch(sb: SchemeSuperBlock, donate: bool,
                              bucket: int):
    """Prep a mixed-scheme superbatch: each scheme segment gets its own
    kernel + args, fused behind ONE launch fn that slices the flat arg
    tuple back per segment and concatenates the verdict rows in segment
    order — a single dispatch event for the whole commit. Per-segment
    epoch entries still engage the cached gather prep when a segment
    shares one warm key. XLA per-sig kernels only: the mixed face never
    routes through pallas or shard_map (follow-up, ROADMAP 3a)."""
    from . import backend as _backend
    from . import ed25519_verify as _kernel
    from . import epoch_cache as _epoch

    seg_fns: List[Tuple] = []
    flat_args: List = []
    for scheme, blk, _off in sb.parts:
        n = len(blk)
        if scheme == "bls12381":
            # aggregation lane (ISSUE 20): the whole segment is one
            # committee's AggBlock at its exact quantized width — no
            # lane_bucket padding (each pad row costs two Miller loops).
            # masks/coeffs ship to the device; ok/reasons ride the
            # closure for the host-side verdict-code fold.
            bep = _backend._bls_epoch(blk)
            vp = bep.vp if bep is not None else blk.pub48.shape[0] + 1
            masks, coeffs, ok, reasons = _backend.prepare_batch_bls(
                blk, n, vp, bad_rows=_backend._bls_bad_rows(blk.pub48)
            )
            args = (masks, coeffs)
            fn = _backend.bls_kernel(blk, ok, reasons, ep=bep,
                                     donate=donate)
            seg_fns.append((fn, len(flat_args), len(flat_args) + len(args)))
            flat_args.extend(args)
            continue
        ep = _epoch.lookup(blk)
        if scheme == "secp256k1":
            if ep is not None:
                args = _backend.prepare_batch_secp_cached(blk, n, ep)
                fn = _backend.secp_cached_kernel(ep, donate)
            else:
                args = _backend.prepare_batch_secp(blk, n)
                fn = _backend.secp_kernel(donate)
        else:
            device_hash = (
                not _backend.HOST_HASH
                and _backend._max_msg_len(blk) <= _backend.DEVICE_HASH_MAX_MSG
            )
            if ep is not None:
                if device_hash:
                    args = _backend.prepare_batch_cached_device_hash(
                        blk, n, ep
                    )
                else:
                    args = _backend.prepare_batch_cached(blk, n, ep)
                fn = _backend.cached_kernel(ep, device_hash, donate)
            elif device_hash:
                args = _backend.prepare_batch_device_hash(blk, n)
                fn = _kernel.jitted_verify_device_hash(donate)
            else:
                args = _backend.prepare_batch(blk, n)
                fn = _kernel.jitted_verify(donate)
        seg_fns.append((fn, len(flat_args), len(flat_args) + len(args)))
        flat_args.extend(args)

    def _launch(*flat):
        import jax.numpy as jnp

        outs = [fn(*flat[lo:hi]) for fn, lo, hi in seg_fns]
        return jnp.concatenate(outs)

    return _launch, tuple(flat_args), None, bucket, None


def prepare_superbatch(block: EntryBlock, plan: MeshPlan):
    """prep for a mesh superbatch. Same contract as the pipeline's
    `_prepare` plus transfer shardings:

        (launch_fn, args, None, bucket, shardings)

    `shardings` is a per-arg NamedSharding tuple when the superbatch
    launches through a real shard_map mesh (the dispatcher's
    `device_pool.transfer` places each array lane-per-device), or None
    on the single-device / simulated-lanes fallback.

    Kernel selection mirrors `_prepare`: pallas compact on the pallas
    backend (uncached — per-mesh coords tables are follow-up work, a
    warm pack ships pubs); otherwise the XLA family with the same
    device-hash choice `_prepare` makes (short messages hash on-chip)
    and the cached gather prep when the WHOLE pack shares one warm
    epoch. The RLC fast-accept kernel is per-lane-group incompatible
    with row demux and stays single-device (ops/pallas_rlc)."""
    from . import backend as _backend
    from . import sharded as _sharded

    assert _BUCKETS == _backend.BUCKETS, "bucket ladders diverged"
    assert _BLS_LANE_BUCKETS == _backend.BLS_BUCKETS, (
        "BLS bucket ladders diverged"
    )
    bucket = plan.bucket
    if len(block) != bucket:
        raise ValueError(
            f"superblock is {len(block)} rows, plan says {bucket}"
        )
    donate = _backend.donate_enabled()
    if isinstance(block, SchemeSuperBlock):
        return _prepare_mixed_superbatch(block, donate, bucket)
    ep = _warm_entry(plan) if block.epoch_key is not None else None
    if getattr(block, "scheme", "ed25519") == "secp256k1":
        # secp lane-group: the Strauss+GLV kernel (ops/secp_verify).
        # Plain jit only — no pallas/shard_map face yet (ROADMAP 3a);
        # the single-device XLA kernel still fuses all lanes into one
        # launch, which is what the mesh demux contract needs.
        if ep is not None and ep.scheme == "secp256k1":
            args = _backend.prepare_batch_secp_cached(block, bucket, ep)
            return (_backend.secp_cached_kernel(ep, donate), args, None,
                    bucket, None)
        args = _backend.prepare_batch_secp(block, bucket)
        return _backend.secp_kernel(donate), args, None, bucket, None
    use_mesh = plan.n_lanes > 1 and _sharded.mesh_ready(plan.n_lanes)
    if _backend._use_pallas():
        import jax

        from . import pallas_verify as _pv

        interpret = jax.default_backend() != "tpu"
        blk = _pv.pick_block(plan.lane_bucket)
        args = _pv.prepare_compact(block, bucket)
        if use_mesh:
            m = _sharded.dispatch_mesh(plan.n_lanes)
            fn = _sharded.mesh_pallas_valid_fn(
                m, bucket // plan.n_lanes, blk, interpret
            )
            shardings = _sharded.mesh_arg_shardings(m, "pallas", len(args))
            return fn, args, None, bucket, shardings
        fn = _pv._jitted_pallas_verify(bucket, blk, interpret, donate=donate)
        return fn, args, None, bucket, None
    device_hash = (
        not _backend.HOST_HASH
        and _backend._max_msg_len(block) <= _backend.DEVICE_HASH_MAX_MSG
    )
    if ep is not None:
        if device_hash:
            args = _backend.prepare_batch_cached_device_hash(
                block, bucket, ep
            )
            kind = "cached_device_hash"
        else:
            args = _backend.prepare_batch_cached(block, bucket, ep)
            kind = "cached"
        if use_mesh:
            m = _sharded.dispatch_mesh(plan.n_lanes)
            fn = _sharded.mesh_valid_fn_cached(m, ep, donate, device_hash)
            shardings = _sharded.mesh_arg_shardings(m, kind, len(args))
            return fn, args, None, bucket, shardings
        return (_backend.cached_kernel(ep, device_hash, donate), args,
                None, bucket, None)
    if device_hash:
        args = _backend.prepare_batch_device_hash(block, bucket)
        kind = "device_hash"
    else:
        args = _backend.prepare_batch(block, bucket)
        kind = "host_hash"
    if use_mesh:
        m = _sharded.dispatch_mesh(plan.n_lanes)
        fn = _sharded.mesh_valid_fn(m, donate, device_hash)
        shardings = _sharded.mesh_arg_shardings(m, kind, len(args))
        return fn, args, None, bucket, shardings
    from . import ed25519_verify as _kernel

    if device_hash:
        return (_kernel.jitted_verify_device_hash(donate), args, None,
                bucket, None)
    return _kernel.jitted_verify(donate), args, None, bucket, None
