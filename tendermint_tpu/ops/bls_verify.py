"""Batched BLS12-381 aggregated-commit verification kernel (JAX/XLA).

The aggregation lane (ISSUE 20 / ROADMAP item 3b). Each aggregated
commit carries ONE G2 signature + a signer bitmap over the committee;
verification is the pairing check

    e(apk, H(m)) == e(g1, sigma),   apk = sum of the signers' pubkeys.

Unlike ECDSA (see the ops/secp_verify.py note: every ECDSA signature
hides an independent modular inversion, so secp only parallelizes
per-signature), BLS *does* admit randomized-linear-combination fusion:
with Fiat-Shamir weights z_j the K per-commit checks fuse into

    prod_j [ e(apk_j, z_j H_j) * e(-g1, z_j sigma_j) ] == 1

— 2K Miller loops but a SINGLE final exponentiation. The weights ride
the G2 side and are applied on the HOST (z_j H_j, z_j sigma_j are
scalar-multiplied in crypto/bls12381 before line-coefficient prep), so
the device never does G2 arithmetic or scalar muls at all.

Device-side shape of the work:

- apk_j is a masked G1 point-sum over the epoch-cached decompressed
  pubkey columns — a log-depth tree of Renes-Costello-Batina complete
  additions (a = 0, b3 = 12), the per-row parallel analog of the secp
  Strauss ladder.
- The Miller loop is a 63-step lax.scan over HOST-prepared line
  coefficients (crypto/bls12381.g2_prepare): a UNIFORM [dbl, add]
  schedule where skipped adds carry (0, 0) coefficients whose "line"
  degenerates to a unit Fp2 scalar. The G1 point enters projectively:
  a line XI*yP + c w^3 - lam*xP w^5 evaluated at (X/Z, Y/Z) is
  (1/Z) * (XI*Y + c*Z w^3 - lam*X w^5), and the scalar (1/Z)^steps
  dies under the final exponentiation — NO device inversion anywhere.
- The final exponentiation is brute force, f^((p^12-1)/r), a lax.scan
  over the ~4313 exponent bits (square + select-multiply). No Fp12
  inversion, no Frobenius; the structured final exp is future work
  (ROADMAP item 3).
- Fp12 is the flat tower Fp2[w]/(w^6 - XI): elements are (..., 6, 2,
  36) limb tensors, multiplied schoolbook via ONE broadcast fe_bls.mul
  (144 limb convolutions batched in a single einsum) + a 0/1 k-index
  summation matrix + the XI fold.

Verdict protocol (two launches, second one rare): the kernel returns
RAW residues, not booleans — apk Z limbs, per-commit Miller products
f_j, and the final-exp residue of prod f_j. The host reduces those as
Python ints (fe_bls has no device canon; see its docstring). Happy
path: fused residue == 1 and every host lane bool holds -> all commits
accepted, ONE launch, ONE final exponentiation. Otherwise the f_j from
launch A feed a second, per-commit final-exp launch whose verdicts are
EXACT, not probabilistic: finalexp(f_j) = (check_j)^(z_j) in the prime-
order group mu_r, which is 1 iff check_j passes (z_j != 0 mod r). Blame
strings therefore pin bit-exact against the sequential reference.

Host prep never raises: malformed/identity/non-subgroup signatures and
bad committee pubkeys keep PAD-commit numerics with ok=False + a pinned
reason (types/validation.py owns the strings), so one bad commit cannot
poison the fused check for its batchmates.
"""

from __future__ import annotations

import functools
import hashlib

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from . import fe_bls as fe
from ..crypto import bls12381 as bls

P = bls.P
N_ATE = bls.N_ATE
B3 = 12  # 3*b for y^2 = x^3 + 4 (RCB formula constant)
NL = fe.NLIMBS

# Fiat-Shamir RLC weights are 128-bit (forced odd-nonzero); per-commit
# verdict exactness only needs z != 0 mod r.
Z_BITS = 128

# Curve constants in limb form — NUMPY, not jnp (trace-immunity; see the
# fe_bls constants note).
GX_L = np.asarray(fe.limbs_from_int(bls.GX))
GY_L = np.asarray(fe.limbs_from_int(bls.GY))
NEG_GY_L = np.asarray(fe.limbs_from_int(P - bls.GY))
ONE_L = np.asarray(fe.limbs_from_int(1))

# Fp12 one in limb-tensor form (6, 2, 36).
ONE12_L = np.zeros((6, 2, NL), dtype=np.int32)
ONE12_L[0, 0] = ONE_L

# Final-exponentiation exponent bits, MSB first (numpy constant).
FE_BITS = np.array([int(b) for b in bin(bls.FINAL_EXP)[2:]], dtype=np.int32)

# k-index summation matrices for the schoolbook Fp12 multiply:
# SUM_LO[i, j, k] = 1 iff i + j == k (k < 6); SUM_HI[i, j, m] = 1 iff
# i + j == m + 6 (the XI-folded columns).
_ii = np.arange(6)[:, None, None]
_jj = np.arange(6)[None, :, None]
SUM_LO = (_ii + _jj == np.arange(6)[None, None, :]).astype(np.int32)
SUM_HI = (_ii + _jj == np.arange(5)[None, None, :] + 6).astype(np.int32)

# Sparse variant: the line value occupies w-slots (0, 3, 5) only.
_SLOTS = np.array([0, 3, 5])
_jj3 = _SLOTS[None, :, None]
SUM_LO_S = (_ii[:, :1] + _jj3 == np.arange(6)[None, None, :]).astype(np.int32)
SUM_HI_S = (_ii[:, :1] + _jj3 == np.arange(5)[None, None, :] + 6).astype(
    np.int32
)


def point_add(p, q):
    """Complete projective G1 addition for y^2 = x^3 + b, a = 0 (RCB16
    Algorithm 7, b3 = 12) — valid for ALL inputs including the identity
    (0, 1, 0), so masked-out committee rows flow through the sum tree
    with no branches (same shape as secp_verify.point_add)."""
    x1, y1, z1 = p
    x2, y2, z2 = q
    t0 = fe.mul(x1, x2)
    t1 = fe.mul(y1, y2)
    t2 = fe.mul(z1, z2)
    t3 = fe.sub(fe.mul(fe.add(x1, y1), fe.add(x2, y2)), fe.add(t0, t1))
    t4 = fe.sub(fe.mul(fe.add(y1, z1), fe.add(y2, z2)), fe.add(t1, t2))
    t5 = fe.sub(fe.mul(fe.add(x1, z1), fe.add(x2, z2)), fe.add(t0, t2))
    t0_3 = fe.mul_small(t0, 3)
    t2_b = fe.mul_small(t2, B3)
    zs = fe.add(t1, t2_b)
    t1m = fe.sub(t1, t2_b)
    t5_b = fe.mul_small(t5, B3)
    x3 = fe.sub(fe.mul(t3, t1m), fe.mul(t4, t5_b))
    y3 = fe.add(fe.mul(t1m, zs), fe.mul(t5_b, t0_3))
    z3 = fe.add(fe.mul(zs, t4), fe.mul(t0_3, t3))
    return (x3, y3, z3)


# -- Fp12 (flat tower) limb-tensor arithmetic --------------------------------


def _pairwise(a, b):
    """All Fp component products of two Fp2-coefficient tensors via ONE
    broadcast fe.mul: a (..., A, 2, 36) x b (..., B, 2, 36) ->
    (..., A, B, 2&2 cross, 36) split into the d0/d1 Fp2 combine."""
    prod = fe.mul(
        a[..., :, None, :, None, :], b[..., None, :, None, :, :]
    )  # (..., A, B, 2, 2, 36)
    d0 = prod[..., 0, 0, :] - prod[..., 1, 1, :]  # re: a0b0 - a1b1
    d1 = prod[..., 0, 1, :] + prod[..., 1, 0, :]  # im: a0b1 + a1b0
    return d0, d1


def _assemble(d0, d1, sum_lo, sum_hi):
    """k-index summation + XI fold: (..., A, B, 36) products -> (..., 6,
    2, 36) reduced Fp12. Sums of <= 6 doubled-reduced limbs (~55k) plus
    the fold (~147k) sit inside carry()'s 1.7e8 domain."""
    lo0 = jnp.einsum("...ijl,ijk->...kl", d0, sum_lo,
                     preferred_element_type=jnp.int32)
    hi0 = jnp.einsum("...ijl,ijk->...kl", d0, sum_hi,
                     preferred_element_type=jnp.int32)
    lo1 = jnp.einsum("...ijl,ijk->...kl", d1, sum_lo,
                     preferred_element_type=jnp.int32)
    hi1 = jnp.einsum("...ijl,ijk->...kl", d1, sum_hi,
                     preferred_element_type=jnp.int32)
    pad = [(0, 0)] * (lo0.ndim - 2) + [(0, 1), (0, 0)]
    hi0 = jnp.pad(hi0, pad)
    hi1 = jnp.pad(hi1, pad)
    # XI * (h0 + h1 u) = (h0 - h1) + (h0 + h1) u
    out = jnp.stack([lo0 + hi0 - hi1, lo1 + hi0 + hi1], axis=-2)
    return fe.carry(out)


def f12_mul(a, b):
    """Full Fp12 multiply: (..., 6, 2, 36) x (..., 6, 2, 36)."""
    d0, d1 = _pairwise(a, b)
    return _assemble(d0, d1, SUM_LO, SUM_HI)


def f12_mul_sparse(a, l3):
    """Multiply by a sparse line value given as its (0, 3, 5) w-slots:
    l3 is (..., 3, 2, 36)."""
    d0, d1 = _pairwise(a, l3)
    return _assemble(d0, d1, SUM_LO_S, SUM_HI_S)


def f12_conj(f):
    """f^(p^6): w -> -w (negate odd w-coefficients)."""
    sign = np.array([1, -1, 1, -1, 1, -1], dtype=np.int32)
    return f * sign[:, None, None]


def _line_slots(lam, c, xl, yl, zl):
    """Line value w-slots (0, 3, 5) at the projective G1 point:
    lam, c (..., 2, 36) Fp2; xl, yl, zl (..., 36). Result (..., 3, 2, 36)
    = (XI*Y, c*Z, -lam*X) with XI*Y = (Y, Y)."""
    # batch the four Fp products (c0*Z, c1*Z, lam0*X, lam1*X) as one mul
    lhs = jnp.concatenate([c, lam], axis=-2)  # (..., 4, 36)
    rhs = jnp.stack([zl, zl, xl, xl], axis=-2)
    prod = fe.mul(lhs, rhs)  # (..., 4, 36)
    slot0 = jnp.stack([yl, yl], axis=-2)  # XI * Y
    slot3 = prod[..., 0:2, :]
    slot5 = -prod[..., 2:4, :]
    return jnp.stack([slot0, slot3, slot5], axis=-3)


def miller(coeffs, xl, yl, zl):
    """Miller loop over host-prepared line coefficients.

    coeffs: (..., N_ATE, 2, 2, 2, 36) — [step, dbl/add, lam/c, Fp2, limb]
    xl/yl/zl: (..., 36) projective G1 evaluation point.
    Returns the conjugated (negative-x) Miller value (..., 6, 2, 36).
    """
    batch = xl.shape[:-1]
    one = jnp.broadcast_to(ONE12_L, batch + (6, 2, NL))
    # scan over the step axis: move it to the front
    xs = jnp.moveaxis(coeffs, -5, 0)

    def body(f, step):
        f = f12_mul(f, f)
        for s in range(2):  # dbl line, then add line
            lam = step[..., s, 0, :, :]
            c = step[..., s, 1, :, :]
            f = f12_mul_sparse(f, _line_slots(lam, c, xl, yl, zl))
        return f, None

    f, _ = lax.scan(body, one, xs)
    return f12_conj(f)


def final_exp(f):
    """Brute-force final exponentiation f^((p^12-1)/r), batched over
    leading dims: scan over the exponent bits, square + select-multiply."""
    one = jnp.broadcast_to(ONE12_L, f.shape)

    def body(acc, bit):
        acc = f12_mul(acc, acc)
        m = jnp.where(bit != 0, f, one)
        return f12_mul(acc, m), None

    out, _ = lax.scan(body, one, FE_BITS)
    return out


# -- kernels ------------------------------------------------------------------


def verify_kernel(gx_tbl, gy_tbl, masks, coeffs):
    """Launch A: apk tree-sum + 2K Miller loops + ONE fused final exp.

    Args:
      gx_tbl, gy_tbl: (Vp, 36) int32 — decompressed affine committee
                      pubkey columns (epoch-cached; bad rows carry g1
                      and are killed host-side via the table ok lane)
      masks:          (K, Vp) bool — signer bitmaps (pad commits select
                      only the pad row)
      coeffs:         (K, 2, N_ATE, 2, 2, 2, 36) int32 — line
                      coefficients for the pairs (apk_j, z_j H_j) and
                      (-g1, z_j sigma_j)
    Returns (apk_z (K, 36), f (K, 6, 2, 36), fused_res (6, 2, 36)) —
    RAW residues; the host reduces them mod p (fe_bls int_from_limbs)
    and applies the lane booleans. No device canon, no device compare.
    """
    k = masks.shape[0]
    m = masks[..., None]
    xs = jnp.where(m, gx_tbl, 0)
    ys = jnp.where(m, gy_tbl, ONE_L)
    zs = jnp.where(m, ONE_L, 0)
    pt = (xs, ys, zs)  # (K, Vp, 36) coords; masked-out rows = identity
    n = pt[0].shape[-2]
    while n > 1:
        half = n // 2
        a = tuple(c[..., :half, :] for c in pt)
        b = tuple(c[..., half : 2 * half, :] for c in pt)
        s = point_add(a, b)
        if n % 2:
            s = tuple(
                jnp.concatenate([c, r[..., 2 * half :, :]], axis=-2)
                for c, r in zip(s, pt)
            )
        pt = s
        n = half + (n % 2)
    apk = tuple(c[..., 0, :] for c in pt)  # (K, 36) each

    # pair 0 evaluates at apk (projective), pair 1 at -g1 (affine, Z=1)
    zero = apk[0] - apk[0]
    xl = jnp.stack([apk[0], GX_L + zero], axis=-2)  # (K, 2, 36)
    yl = jnp.stack([apk[1], NEG_GY_L + zero], axis=-2)
    zl = jnp.stack([apk[2], ONE_L + zero], axis=-2)

    f_pairs = miller(coeffs, xl, yl, zl)  # (K, 2, 6, 2, 36)
    f = f12_mul(f_pairs[:, 0], f_pairs[:, 1])  # (K, 6, 2, 36)

    fused = f[0]
    for j in range(1, k):
        fused = f12_mul(fused, f[j])
    fused_res = final_exp(fused[None])[0]
    return apk[2], f, fused_res


def finalexp_kernel(f):
    """Launch B (rare): per-commit final exponentiations over the f_j
    returned by launch A — exact per-commit verdict residues."""
    return final_exp(f)


# Donation contract mirrors the other lanes: epoch-table args (0-1) are
# persistent device residents and are NEVER donated; per-batch masks and
# coefficients may be.


@functools.lru_cache(maxsize=None)
def jitted_bls_verify(donate: bool = False):
    if donate:
        return jax.jit(verify_kernel, donate_argnums=(2, 3))
    return jax.jit(verify_kernel)


@functools.lru_cache(maxsize=None)
def jitted_bls_finalexp(donate: bool = False):
    if donate:
        return jax.jit(finalexp_kernel, donate_argnums=(0,))
    return jax.jit(finalexp_kernel)


# -- host-side preparation ----------------------------------------------------


def table_columns_g1(pubs):
    """Decompress a committee's 48-byte pubkeys into epoch-table columns
    (gx (V+1, 36) int32, gy, g_ok (V+1,) bool). Bad pubkeys (malformed/
    identity/non-subgroup) carry g1 with g_ok False; row V is the
    padding lane (g1, ok). Mirrors secp_verify.table_columns."""
    xs, ys, oks = [], [], []
    for pub in pubs:
        pt, reason = bls.pubkey_status(bytes(pub))
        if reason is not None:
            xs.append(bls.GX)
            ys.append(bls.GY)
            oks.append(False)
        else:
            xs.append(pt[0])
            ys.append(pt[1])
            oks.append(True)
    xs.append(bls.GX)
    ys.append(bls.GY)
    oks.append(True)
    return (
        fe.field_to_limbs(xs),
        fe.field_to_limbs(ys),
        np.array(oks, dtype=bool),
    )


def _coeff_rows(q2) -> np.ndarray:
    """g2_prepare a (z-scaled) G2 point into the kernel's limb layout
    (N_ATE, 2, 2, 2, 36)."""
    rows = bls.g2_prepare(q2)
    flat = []
    for (lam_d, c_d), (lam_a, c_a) in rows:
        flat.extend((lam_d, c_d, lam_a, c_a))
    return fe.f2_rows(flat).reshape(N_ATE, 2, 2, 2, NL)


PAD_MSG = b"tm-tpu/bls-pad-commit"


@functools.lru_cache(maxsize=None)
def _pad_numerics():
    """Self-verifying pad commit: sk = 1 -> pk = g1 (the table pad row),
    sigma = H(pad_msg); z = 1. Pads never poison a batch and their
    residue is deterministically accepting (e(g1, H) == e(g1, H))."""
    h = bls.hash_to_g2(PAD_MSG)
    return _coeff_rows(h), _coeff_rows(h)  # (z*H, z*sigma) with z = 1


@functools.lru_cache(maxsize=4096)
def _prepared_pair(sig: bytes, msg: bytes, z: int):
    """(z*H(msg), z*sigma) line coefficients for one commit (memoized:
    retried commits and bench reps skip the G2 scalar muls)."""
    s, _ = bls.signature_status(sig)
    zh = bls.g2_mul(z, bls.hash_to_g2(msg))
    zs = bls.g2_mul(z % bls.R, s)
    return _coeff_rows(zh), _coeff_rows(zs)


def rlc_weights(items) -> list:
    """Deterministic Fiat-Shamir weights: each commit's z_j binds the
    WHOLE batch (all signatures, messages, bitmaps), so an adversary
    cannot steer a cancellation across the fused product."""
    ctx = hashlib.sha256()
    for bits, msg, sig in items:
        ctx.update(hashlib.sha256(
            np.asarray(bits, dtype=np.uint8).tobytes()
            + b"\x00" + bytes(msg) + b"\x00" + bytes(sig)
        ).digest())
    digest = ctx.digest()
    out = []
    for j in range(len(items)):
        zj = int.from_bytes(
            hashlib.sha256(digest + j.to_bytes(4, "big")).digest()[:16],
            "big",
        ) | 1
        out.append(zj)
    return out


def prepare_commits(items, size: int, vp: int, bad_rows=()):
    """Host prep for a batch of (signer_bits, msg, sig96) commits.

    items: [(bits (n_vals,) bool-array, msg bytes, sig bytes), ...]
    size:  padded K bucket; rows [len(items):size] are pad commits
    vp:    table row count (n_vals committee rows + 1 pad row)
    bad_rows: validator indices whose table pubkey failed decompression
              /subgroup (from table_columns_g1's ok lane) — commits
              touching one keep pad numerics with the pinned reason

    Returns (masks (size, vp) bool, coeffs (size, 2, N_ATE, 2, 2, 2, 36)
    int32, ok (size,) bool, reasons list[str|None]) — never raises:
    malformed rows become accepting pad lanes with ok False + reason
    (types/validation.py turns reasons into the pinned blame strings).
    """
    masks = np.zeros((size, vp), dtype=bool)
    masks[:, vp - 1] = True  # pad commits select only the pad row
    coeffs = np.empty((size, 2, N_ATE, 2, 2, 2, NL), dtype=np.int32)
    pad_a, pad_b = _pad_numerics()
    coeffs[:, 0] = pad_a
    coeffs[:, 1] = pad_b
    ok = np.ones(size, dtype=bool)
    reasons: list = [None] * size
    bad_rows = set(bad_rows)
    zs = rlc_weights(items)
    for i, (bits, msg, sig) in enumerate(items):
        bits = np.asarray(bits, dtype=bool)
        _, reason = bls.signature_status(bytes(sig))
        if reason is not None:
            ok[i] = False
            reasons[i] = f"sig:{reason}"
            continue
        hit = sorted(bad_rows.intersection(np.flatnonzero(bits)))
        if hit:
            ok[i] = False
            reasons[i] = f"pub:{hit[0]}"
            continue
        ca, cb = _prepared_pair(bytes(sig), bytes(msg), zs[i])
        coeffs[i, 0] = ca
        coeffs[i, 1] = cb
        masks[i, : len(bits)] = bits
        masks[i, vp - 1] = False
    return masks, coeffs, ok, reasons


def residue_int(limbs) -> list:
    """(6, 2, 36) limb tensor -> 12 canonical Fp ints (host reduce)."""
    a = np.asarray(limbs)
    return [
        fe.int_from_limbs(a[i, j]) % P for i in range(6) for j in range(2)
    ]


def residue_is_one(limbs) -> bool:
    r = residue_int(limbs)
    return r[0] == 1 and not any(r[1:])


def run_verify(tables, masks, coeffs, ok_host, donate: bool = False):
    """The two-launch verdict protocol over prepared arrays.

    tables: (gx, gy, g_ok) from table_columns_g1 (numpy or device
    residents). Returns (verdicts (K,) bool over the PREPARED size,
    crypto_failed (K,) bool — lanes whose pairing check itself failed,
    apk_nz (K,) bool — False where the masked point-sum landed on the
    identity, the "aggregate pubkey is the identity" blame lane).
    """
    gx, gy, g_ok = tables
    apk_z, f, fused = jitted_bls_verify(donate)(gx, gy, masks, coeffs)
    k = masks.shape[0]
    apk_nz = np.array(
        [fe.int_from_limbs(np.asarray(apk_z)[j]) % P != 0 for j in range(k)]
    )
    lane_ok = np.asarray(ok_host) & apk_nz
    if bool(np.all(lane_ok)) and residue_is_one(fused):
        return np.ones(k, dtype=bool) & lane_ok, np.zeros(k, dtype=bool), apk_nz
    # rare path: exact per-commit final exponentiations over launch A's
    # Miller products
    res = np.asarray(jitted_bls_finalexp(donate)(f))
    pair_ok = np.array([residue_is_one(res[j]) for j in range(k)])
    return lane_ok & pair_ok, lane_ok & ~pair_ok, apk_nz


# -- verdict-code transport ---------------------------------------------------
#
# The pipeline's conclude() closures are created BEFORE prep runs on the
# prep pool, so everything blame needs must ride the (n,) result row the
# dispatcher resolves. The BLS lane's row is therefore int32 CODES, not
# booleans (the mixed-scheme concatenate promotes its batchmates' bools
# to int32 harmlessly; ops/pipeline._resolve only booleanizes 2-D rows):

CODE_VALID = 1
CODE_PAIRING = 2       # pairing check failed (wrong aggregate signature)
CODE_APK_IDENTITY = 3  # masked pubkey sum is the identity
CODE_SIG = {"malformed": 4, "identity": 5, "subgroup": 6}
CODE_PUB_BASE = 16     # CODE_PUB_BASE + i: validator i's pubkey unusable

SIG_CODE_WORDS = {v: w for w, v in CODE_SIG.items()}


def verdict_codes(verdicts, crypto_failed, apk_nz, reasons) -> np.ndarray:
    """Fold run_verify outputs + prepare_commits reasons into the int32
    code row. Host-rejected lanes (reasons) win over device residues —
    they never reached a real pairing."""
    k = len(verdicts)
    codes = np.empty(k, dtype=np.int32)
    for j in range(k):
        r = reasons[j] if j < len(reasons) else None
        if r is not None:
            if r.startswith("sig:"):
                codes[j] = CODE_SIG[r[4:]]
            else:
                codes[j] = CODE_PUB_BASE + int(r[4:])
        elif not apk_nz[j]:
            codes[j] = CODE_APK_IDENTITY
        elif verdicts[j]:
            codes[j] = CODE_VALID
        else:
            codes[j] = CODE_PAIRING
    return codes
