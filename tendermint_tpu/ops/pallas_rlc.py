"""Per-lane RLC fast-accept verification — M signatures per kernel lane.

The per-signature kernel (ops.pallas_verify) spends ~70% of its ladder on
point doubles: every lane doubles its own accumulator 254 times to verify
ONE signature. This module amortizes those doubles over M signatures by
verifying a random-linear-combination equation per lane (the same
construction Go's crypto/ed25519 batch path uses across a whole batch —
crypto/ed25519/ed25519.go:192-227 — applied at lane granularity):

    lane g covers sigs j = 0..M-1 with coefficients c_0 = 1,
    c_j = z_j (random 128-bit, host CSPRNG, fresh per batch):

    acc = [S]B - sum_j [u_j]A_j - sum_{j>=1} [z_j]R_j
    accept iff [8]acc == [8]R_0          (cofactored, ZIP-215-compatible)

    S = (s_0 + sum z_j s_j) mod L,  u_0 = k_0,  u_j = (z_j k_j) mod L

Soundness: [8] of each per-sig residual e_j = [s_j]B - [k_j]A_j - R_j
lies in the prime-order subgroup, so if any [8]e_j != O the combination
[8]acc = sum c_j [8]e_j vanishes with probability <= 2^-125 over the
z_j. Valid batches ALWAYS accept ([8]e_j = O for all j implies
[8]acc = O identically — torsion components cancel under the cofactor
exactly as in per-sig ZIP-215). On lane reject the caller re-verifies
that lane's M signatures individually for blame (the reference's own
accept/reject asymmetry, types/validation.go:242-248); per-sig
accept/reject semantics are therefore preserved exactly, up to the
negligible false-accept probability every RLC batch verifier carries.

The ladder processes 2M scalars (1 + M full 253-bit, M-1 half 128-bit)
through M joint 16-entry Straus tables — 2 doubles + ~(M/2+1..M) adds
per iteration for M signatures, vs 2 doubles + 1 add per signature in
the per-sig kernel. At M=4 that is ~1.9x fewer field muls per signature
with the SAME per-block VMEM footprint (per-lane table bytes x4, lanes
/4). Layouts, point ops, and Mosaic constraints are shared with
ops.pallas_verify.
"""

from __future__ import annotations

import functools
import os

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from . import fe_t
from . import pallas_verify as pv
from ..crypto import _edwards

NL = fe_t.NLIMBS

# Signatures per lane. 2 scalars pair per joint table, so M tables serve
# 2M scalars; M=4 is the measured sweet spot (M=8 halves the remaining
# doubles but the z-lane adds start to dominate).
M = int(os.environ.get("TM_TPU_RLC_M", "4"))
if M not in (2, 4, 8):
    raise ValueError(f"TM_TPU_RLC_M={M} must be 2, 4 or 8")

# Lanes per kernel block (block covers BLOCK_LANES * M signatures). The
# per-block table is M x 16 entries x 4 coords — the same VMEM bytes as
# the per-sig kernel's 16-entry table at M x the lane count.
BLOCK_LANES = int(os.environ.get("TM_TPU_RLC_BLOCK", "128"))

# Max signatures per device batch. The relay-attached TPU pays a flat
# ~14 ms per host->device transfer regardless of size (measured round 5),
# so batches amortize it: 10240 sigs/batch tops out ~295k sigs/s while
# 81920 reaches ~460k (transfer included). The async pipeline coalesces
# concurrent commits up to this cap; HBM at 81920 is ~900 MB of
# intermediates on a 16 GB part.
#
# Validated at import (ADVICE r5): every bucket plan_bucket can select —
# the cap included — must divide into whole kernel blocks (M * BLOCK_LANES
# signatures each) or the truncated pallas grid would leave trailing
# lanes' verdicts uninitialized, and a cap below the smallest quantized
# bucket would make plan_bucket select ABOVE it.
MAX_SIGS = int(os.environ.get("TM_TPU_RLC_MAX_SIGS", "81920"))
if MAX_SIGS < 512 or MAX_SIGS % (M * BLOCK_LANES):
    raise ValueError(
        f"TM_TPU_RLC_MAX_SIGS={MAX_SIGS} must be >= 512 and a multiple of "
        f"M*BLOCK_LANES={M * BLOCK_LANES}"
    )

# Scalar q: 0 -> S, 1..M -> u_{q-1}, M+1..2M-1 -> z_{q-M}.
N_SCAL = 2 * M
# Table t pairs scalar lo=2t (low 2 bits of the entry index) with
# hi=2t+1. Tables whose BOTH scalars are z's (lo index > M) carry zero
# digits above bit 128 and are skipped in the top half of the ladder.
N_FULL_TABLES = M // 2 + 1


def _point_rows(p: int, c: int) -> slice:
    """Rows of coord c of point p in the coords ref (32-row slots)."""
    base = (p * 4 + c) * 32
    return slice(base, base + NL)


def _tbl_rows(t: int, e: int, c: int) -> slice:
    base = ((t * 16 + e) * 4 + c) * 32
    return slice(base, base + NL)


# -- K1: byte unpack + decompression of 2M points ---------------------------


def _k1_rlc_kernel(a_ref, r_ref, scal_ref, coords_ref, ok_ref, dig_ref):
    """Unpack 2M scalars' base-4 digits and jointly decompress the 2M
    points (A_0..A_{M-1}, R_0..R_{M-1}) of each lane's M signatures.

    coords: ((2M*4)*32, G) 32-row coordinate slots, A's then R's.
    ok:     (2M, G) decompression flags.
    dig:    (2M*128, G) shift-grouped digits, scalar-major."""
    for q in range(N_SCAL):
        enc = scal_ref[q * 32 : (q + 1) * 32].astype(jnp.int32)
        dig_ref[q * 128 : (q + 1) * 128] = pv._unpack_digits2_grouped(enc)

    ys = []
    signs = []
    for j in range(M):
        y, s = pv._unpack_limbs(a_ref[j * 32 : (j + 1) * 32].astype(jnp.int32))
        ys.append(y)
        signs.append(s)
    for j in range(M):
        y, s = pv._unpack_limbs(r_ref[j * 32 : (j + 1) * 32].astype(jnp.int32))
        ys.append(y)
        signs.append(s)
    G = ys[0].shape[-1]
    ok_all, pts = pv.decompress(pv._cat(ys), pv._cat(signs))
    for p in range(2 * M):
        ok_ref[p : p + 1] = ok_all[:, p * G : (p + 1) * G].astype(jnp.int32)
        for c in range(4):
            coords_ref[_point_rows(p, c)] = pts[c][:, p * G : (p + 1) * G]


def _k1_rlc_kernel_cached(ac_ref, aok_ref, r_ref, scal_ref, coords_ref,
                          ok_ref, dig_ref):
    """_k1_rlc_kernel for a WARM epoch: the M committee points per lane
    arrive pre-decompressed (gathered on device from the epoch cache's
    persistent coords table), so this variant decompresses M points (the
    R's) instead of 2M — K1 was ~half committee work by construction.

    ac: (M*4*32, B) int32 — slot-major A coords, point p coord c at rows
    (p*4 + c)*32; aok: (M, B) int32 per-slot decompression flags."""
    for q in range(N_SCAL):
        enc = scal_ref[q * 32 : (q + 1) * 32].astype(jnp.int32)
        dig_ref[q * 128 : (q + 1) * 128] = pv._unpack_digits2_grouped(enc)

    for p in range(M):
        ok_ref[p : p + 1] = aok_ref[p : p + 1]
        for c in range(4):
            coords_ref[_point_rows(p, c)] = ac_ref[
                (p * 4 + c) * 32 : (p * 4 + c) * 32 + NL
            ]

    ys = []
    signs = []
    for j in range(M):
        y, s = pv._unpack_limbs(r_ref[j * 32 : (j + 1) * 32].astype(jnp.int32))
        ys.append(y)
        signs.append(s)
    G = ys[0].shape[-1]
    ok_all, pts = pv.decompress(pv._cat(ys), pv._cat(signs))
    for j in range(M):
        p = M + j
        ok_ref[p : p + 1] = ok_all[:, j * G : (j + 1) * G].astype(jnp.int32)
        for c in range(4):
            coords_ref[_point_rows(p, c)] = pts[c][:, j * G : (j + 1) * G]


# -- K2: M joint Straus tables ----------------------------------------------


def _k2_rlc_kernel(coords_ref, tbl_ref):
    """Build the M 16-entry joint tables. Table t holds
    [lo]P_t + [hi]Q_t for digits lo, hi in 0..3 at entry lo + 4*hi, where
    (P_t, Q_t) are the points of scalars (2t, 2t+1): B for S, -A_j for
    u_j, -R_j for z_j. Same lane-folded dbl/tri/cross construction as
    pallas_verify._k2_table_kernel, folded across all M tables."""
    pts = []
    for p in range(2 * M):
        pt = tuple(coords_ref[_point_rows(p, c)] for c in range(4))
        pts.append(pv.point_neg(pt))
    G = pts[0][0].shape[-1]
    zero = jnp.zeros((NL, G), dtype=jnp.int32)
    one = fe_t.limbs_from_int_t(1)
    bx = fe_t.limbs_from_int_t(_edwards.BASE[0])
    by = fe_t.limbs_from_int_t(_edwards.BASE[1])
    bt = fe_t.limbs_from_int_t(_edwards.BASE[3])
    base = (bx + zero, by + zero, one + zero, bt + zero)
    ident = (zero, one + zero, one + zero, zero)

    def point_of(q):
        if q == 0:
            return base
        if q <= M:
            return pts[q - 1]  # -A_{q-1}
        return pts[M + (q - M)]  # -R_{q-M}

    P = [point_of(2 * t) for t in range(M)]
    Q = [point_of(2 * t + 1) for t in range(M)]
    # one fold for all 2M doubles, one for all 2M triples
    pair = pv._catp(P + Q)
    dbl = pv.point_double(pair)
    tri = pv.point_add(dbl, pair)
    rows = []  # rows[t] = [O, P, 2P, 3P]; cols[t] = [O, Q, 2Q, 3Q]
    cols = []
    for t in range(M):
        rows.append([ident, P[t], pv._slicep(dbl, t, G), pv._slicep(tri, t, G)])
        cols.append(
            [ident, Q[t], pv._slicep(dbl, M + t, G), pv._slicep(tri, M + t, G)]
        )
    # 9 cross entries per table, folded PER TABLE (a single M*9-wide fold
    # overruns scoped VMEM at 128 lanes: the (20, 20, 9*M*G) mul transient
    # alone is ~7 MB)
    crosses = [
        pv.point_add(
            pv._catp([rows[t][lo] for hi in (1, 2, 3) for lo in (1, 2, 3)]),
            pv._catp([cols[t][hi] for hi in (1, 2, 3) for lo in (1, 2, 3)]),
        )
        for t in range(M)
    ]
    entries = []  # (t, e, point)
    for t in range(M):
        for hi in range(4):
            for lo in range(4):
                if hi == 0:
                    pt = rows[t][lo]
                elif lo == 0:
                    pt = cols[t][hi]
                else:
                    pt = pv._slicep(crosses[t], (hi - 1) * 3 + (lo - 1), G)
                entries.append((t, lo + 4 * hi, pt))
    # Niels-form store, folded 8 entries at a time (keeps the (20,20,B)
    # mul transient within VMEM; see pallas_verify._k2_table_kernel)
    for half in range(len(entries) // 8):
        chunk = entries[half * 8 : half * 8 + 8]
        niels = pv.to_niels(pv._catp([pt for _, _, pt in chunk]))
        for j, (t, e, _) in enumerate(chunk):
            ent = pv._slicep(niels, j, G)
            for c in range(4):
                tbl_ref[_tbl_rows(t, e, c)] = ent[c]


# -- K3: the shared-doubles ladder ------------------------------------------


def _k3_rlc_kernel(tbl_ref, dig_ref, coords_ref, ok_ref, sok_ref, out_ref):
    """127-iteration ladder with 2 doubles + n_tables adds per iteration
    (vs 2 doubles + 1 add PER SIGNATURE in the per-sig kernel). The top
    63 iterations skip the all-z tables (digits structurally zero: z_j <
    2^128). Final test: [8]acc == [8]R_0 by doubles-only projective
    cross-multiplication, identical to pallas_verify._k3_ladder_kernel."""
    G = sok_ref.shape[-1]
    zero = jnp.zeros((NL, G), dtype=jnp.int32)
    one = fe_t.limbs_from_int_t(1)
    ident = (zero, one + zero, one + zero, zero)

    def select(t, idx):
        out = [tbl_ref[_tbl_rows(t, 0, c)] for c in range(4)]
        for e in range(1, 16):
            m = (idx == e)[None, :]
            for c in range(4):
                out[c] = jnp.where(m, tbl_ref[_tbl_rows(t, e, c)], out[c])
        return tuple(out)

    def make_body(n_tables):
        def body(i, acc):
            j = pv._digit_row(126 - i)
            acc = pv.point_double(pv.point_double(acc, need_t=False))
            for t in range(n_tables):
                idx = dig_ref[2 * t * 128 + j] + 4 * dig_ref[(2 * t + 1) * 128 + j]
                # intermediate adds feed the next add's t1*T2d term; only
                # the last add before the wrap-around doubles skips T
                acc = pv.point_add_niels(acc, select(t, idx), need_t=t + 1 < n_tables)
            return acc

        return body

    # positions 126..64: z digits are all zero — all-z tables skipped
    acc = lax.fori_loop(0, 63, make_body(N_FULL_TABLES), ident)
    acc = lax.fori_loop(63, 127, make_body(M), acc)

    # [8]acc == [8]R_0, doubles-only (complete for small-order inputs)
    R0 = tuple(coords_ref[_point_rows(M, c)] for c in range(4))
    acc8, r8 = acc, R0
    for _ in range(3):
        acc8 = pv.point_double(acc8, need_t=False)
        r8 = pv.point_double(r8, need_t=False)
    eq_x = fe_t.is_zero(
        fe_t.sub(fe_t.mul(acc8[0], r8[2]), fe_t.mul(r8[0], acc8[2]))
    )
    eq_y = fe_t.is_zero(
        fe_t.sub(fe_t.mul(acc8[1], r8[2]), fe_t.mul(r8[1], acc8[2]))
    )
    valid = eq_x & eq_y
    for p in range(2 * M):
        valid = valid & (ok_ref[p : p + 1] != 0)
    for j in range(M):
        valid = valid & (sok_ref[j : j + 1] != 0)
    out_ref[:] = valid.astype(jnp.int32)


# -- pipeline ----------------------------------------------------------------


# Quantized bucket ladder (in signatures): XLA compiles one executable
# per shape, and the coalescing pipeline would otherwise produce a fresh
# shape (and a ~25 s Mosaic compile) for every distinct batch total.
# Built as a sorted tuple filtered to <= MAX_SIGS (and to whole kernel
# blocks) so plan_bucket can never select above the cap or hand the
# jitted kernel a lane count that truncates its grid.
RLC_BUCKETS = tuple(
    sorted(
        b
        for b in {512, 2048, 10240, 20480, 40960, 81920, MAX_SIGS}
        if b <= MAX_SIGS and b % (M * BLOCK_LANES) == 0
    )
)
assert RLC_BUCKETS and RLC_BUCKETS[-1] == MAX_SIGS


def plan_bucket(n: int, block: int = 0) -> tuple:
    """(bucket_sigs, g_lanes, block) covering n signatures such that the
    lane count divides evenly into kernel blocks. EVERY caller that feeds
    _jitted_rlc_verify must size via this: a g not divisible by block
    would truncate the pallas grid and leave trailing lanes' verdicts
    uninitialized — read back as garbage 'valid' bits.

    Buckets quantize to RLC_BUCKETS (pow2 single-block below 512 sigs) so
    the compiled-shape set stays small under arbitrary coalesced sizes."""
    block = block or BLOCK_LANES
    lanes = max((n + M - 1) // M, 1)
    if block < BLOCK_LANES or lanes <= block:
        # explicit small blocks (tests) or tiny batches: pow2 single/multi
        # block, lane count padded to a multiple of the block
        block = min(block, 1 << (lanes - 1).bit_length())
        g = ((lanes + block - 1) // block) * block
        return g * M, g, block
    for b in RLC_BUCKETS:
        if n <= b:
            return b, b // M, block
    return RLC_BUCKETS[-1], RLC_BUCKETS[-1] // M, block


@functools.lru_cache(maxsize=None)
def _jitted_rlc_verify(g: int, block: int, interpret: bool,
                       vma: frozenset | None = None,
                       donate: bool = False):
    """g lanes (g*M signatures), block lanes per kernel invocation.
    donate=True donates the per-batch inputs (ISSUE 7; see
    ed25519_verify's donation note)."""
    if g % block:
        raise ValueError(
            f"lane count {g} not a multiple of block {block} (size buckets "
            "via plan_bucket — a truncated grid silently skips lanes)"
        )
    # Mosaic requires the minor block dim divisible by 128 (or the full
    # array dim); K2's working set at 128 lanes fits because its folds
    # are chunked (see _k2_rlc_kernel)
    k2_block = min(block, 128)

    def mkspec(b):
        def spec(rows):
            return pl.BlockSpec((rows, b), lambda i: (0, i), memory_space=pltpu.VMEM)

        return spec

    def out(rows):
        # positional-only when vma is unset: older jax releases predate
        # the vma kwarg, and an explicit vma=None still TypeErrors there
        if vma is None:
            return jax.ShapeDtypeStruct((rows, g), jnp.int32)
        return jax.ShapeDtypeStruct((rows, g), jnp.int32, vma=vma)

    spec = mkspec(block)
    spec2 = mkspec(k2_block)
    coords_rows = 2 * M * 4 * 32
    tbl_rows = M * 16 * 4 * 32
    dig_rows = N_SCAL * 128

    k1 = pl.pallas_call(
        _k1_rlc_kernel,
        grid=(g // block,),
        in_specs=[spec(M * 32), spec(M * 32), spec(N_SCAL * 32)],
        out_specs=[spec(coords_rows), spec(2 * M), spec(dig_rows)],
        out_shape=[out(coords_rows), out(2 * M), out(dig_rows)],
        interpret=interpret,
    )
    k2 = pl.pallas_call(
        _k2_rlc_kernel,
        grid=(g // k2_block,),
        in_specs=[spec2(coords_rows)],
        out_specs=spec2(tbl_rows),
        out_shape=out(tbl_rows),
        interpret=interpret,
    )
    k3 = pl.pallas_call(
        _k3_rlc_kernel,
        grid=(g // block,),
        in_specs=[spec(tbl_rows), spec(dig_rows), spec(coords_rows),
                  spec(2 * M), spec(M)],
        out_specs=spec(1),
        out_shape=out(1),
        interpret=interpret,
    )

    def pipeline(a_t, r_t, scal_t, sok_t):
        coords, ok, dig = k1(a_t, r_t, scal_t)
        tbl = k2(coords)
        return k3(tbl, dig, coords, ok, sok_t)

    if donate:
        return jax.jit(pipeline, donate_argnums=(0, 1, 2, 3))
    return jax.jit(pipeline)


@functools.lru_cache(maxsize=None)
def _jitted_rlc_verify_cached(g: int, block: int, vp: int, interpret: bool,
                              vma: frozenset | None = None,
                              donate: bool = False):
    """The epoch-cached RLC pipeline: gathers the committee's
    decompressed coords from the persistent (4*32, vp) device table,
    rearranges them (and the raw row-major per-sig inputs) into the
    slot-major kernel layout ON DEVICE, and runs K1-cached/K2/K3. The
    host ships only val_idx + raw rows — prepare_rlc's slot-major
    transposes (the bulk of its 31 ms at 10k sigs) become device work."""
    if g % block:
        raise ValueError(
            f"lane count {g} not a multiple of block {block} (size buckets "
            "via plan_bucket — a truncated grid silently skips lanes)"
        )
    k2_block = min(block, 128)

    def mkspec(b):
        def spec(rows):
            return pl.BlockSpec((rows, b), lambda i: (0, i), memory_space=pltpu.VMEM)

        return spec

    def out(rows):
        if vma is None:
            return jax.ShapeDtypeStruct((rows, g), jnp.int32)
        return jax.ShapeDtypeStruct((rows, g), jnp.int32, vma=vma)

    spec = mkspec(block)
    spec2 = mkspec(k2_block)
    coords_rows = 2 * M * 4 * 32
    acoords_rows = M * 4 * 32
    tbl_rows = M * 16 * 4 * 32
    dig_rows = N_SCAL * 128

    k1 = pl.pallas_call(
        _k1_rlc_kernel_cached,
        grid=(g // block,),
        in_specs=[spec(acoords_rows), spec(M), spec(M * 32),
                  spec(N_SCAL * 32)],
        out_specs=[spec(coords_rows), spec(2 * M), spec(dig_rows)],
        out_shape=[out(coords_rows), out(2 * M), out(dig_rows)],
        interpret=interpret,
    )
    k2 = pl.pallas_call(
        _k2_rlc_kernel,
        grid=(g // k2_block,),
        in_specs=[spec2(coords_rows)],
        out_specs=spec2(tbl_rows),
        out_shape=out(tbl_rows),
        interpret=interpret,
    )
    k3 = pl.pallas_call(
        _k3_rlc_kernel,
        grid=(g // block,),
        in_specs=[spec(tbl_rows), spec(dig_rows), spec(coords_rows),
                  spec(2 * M), spec(M)],
        out_specs=spec(1),
        out_shape=out(1),
        interpret=interpret,
    )

    def pipeline(coords_tbl, ok_tbl, idx, r_rows, scal_rows, sok_rows):
        # idx is signature-major (i = lane*M + slot); the reshapes below
        # land every array in the kernels' slot-major layout
        ac = (
            coords_tbl[:, idx]
            .reshape(4 * 32, g, M)
            .transpose(2, 0, 1)
            .reshape(acoords_rows, g)
        )
        aok = ok_tbl[:, idx].reshape(g, M).T
        r_t = r_rows.reshape(g, M, 32).transpose(1, 2, 0).reshape(M * 32, g)
        scal_t = scal_rows.transpose(1, 2, 0).reshape(N_SCAL * 32, g)
        sok_t = sok_rows.T
        coords, ok, dig = k1(ac, aok, r_t, scal_t)
        tbl = k2(coords)
        return k3(tbl, dig, coords, ok, sok_t)

    if donate:
        # persistent epoch tables (argnums 0-1) are never donated
        return jax.jit(pipeline, donate_argnums=(2, 3, 4, 5))
    return jax.jit(pipeline)


def rlc_cached_fn(ep, g: int, block: int, interpret: bool,
                  donate: bool = False):
    """Kernel closure for the warm-epoch RLC pipeline; coords tables
    resolve at CALL time on the dispatch-owner thread."""
    f = _jitted_rlc_verify_cached(g, block, ep.vp, interpret, donate=donate)

    def call(*args):
        coords_tbl, ok_tbl = ep.coords_tables()
        return f(coords_tbl, ok_tbl, *args)

    return call


# -- host prep ---------------------------------------------------------------


def _rlc_scalars_py(s_enc: bytes, k_enc: bytes, z_enc: bytes, m: int) -> bytes:
    """Pure-Python fallback for tm_native.ed25519_rlc_scalars."""
    L = _edwards.L
    n = len(s_enc) // 32
    g = n // m
    S = bytearray()
    U = bytearray()
    for lane in range(g):
        b = lane * m
        s0 = int.from_bytes(s_enc[32 * b : 32 * b + 32], "little") % L
        U += k_enc[32 * b : 32 * b + 32]
        for j in range(1, m):
            i = b + j
            z = int.from_bytes(z_enc[32 * i : 32 * i + 32], "little")
            s = int.from_bytes(s_enc[32 * i : 32 * i + 32], "little")
            k = int.from_bytes(k_enc[32 * i : 32 * i + 32], "little")
            s0 = (s0 + z * s) % L
            U += ((z * k) % L).to_bytes(32, "little")
        S += s0.to_bytes(32, "little")
    return bytes(S) + bytes(U)


def _seed_allowed() -> bool:
    """Security gate for TM_TPU_RLC_SEED (ADVICE r5): deterministic RLC
    coefficients turn the 2^-125 soundness bound into 'attacker picks the
    coefficients', so the seed is honored only where no production verify
    can run — a non-TPU (interpret) backend — or under the explicit
    TM_TPU_RLC_SEED_UNSAFE=1 test override. On a TPU backend without the
    override it is refused: warn once + ignore."""
    if os.environ.get("TM_TPU_RLC_SEED_UNSAFE") == "1":
        return True
    return jax.default_backend() != "tpu"


_seed_refused = False


def _gen_z(bucket: int) -> np.ndarray:
    """(bucket, 32) uint8 random 128-bit coefficients (top 16 bytes 0).
    Slot-0 entries are ignored by the scalar prep (coefficient 1).
    TM_TPU_RLC_SEED makes them deterministic for tests — subject to
    _seed_allowed; a production TPU backend always gets CSPRNG draws."""
    z = np.zeros((bucket, 32), dtype=np.uint8)
    seed = os.environ.get("TM_TPU_RLC_SEED")
    if seed is not None and not _seed_allowed():
        global _seed_refused
        if not _seed_refused:
            _seed_refused = True
            import warnings

            warnings.warn(
                "TM_TPU_RLC_SEED ignored on the TPU backend: predictable "
                "RLC coefficients would break batch soundness (set "
                "TM_TPU_RLC_SEED_UNSAFE=1 only in tests)",
                RuntimeWarning,
                stacklevel=2,
            )
        seed = None
    if seed is not None:
        z[:, :16] = np.random.RandomState(int(seed)).randint(
            0, 256, size=(bucket, 16), dtype=np.uint8
        )
    else:
        z[:, :16] = np.frombuffer(os.urandom(16 * bucket), dtype=np.uint8).reshape(
            bucket, 16
        )
    return z


def _rlc_host_scalars(entries, live: int, g_live: int):
    """Shared host scalar stage for both RLC preps: packs the live rows,
    draws the z coefficients, and computes the lane scalars. For an
    EntryBlock with the native module built, challenges + scalar mul-adds
    + s<L run as ONE GIL-released call over the block's contiguous
    buffers (tm_native.ed25519_rlc_prep); tuple lists and native-absent
    builds keep the split numpy/Python path with identical outputs.

    Returns (pub (live, 32), r_enc (live, 32), scal (g_live, N_SCAL, 32),
    s_ok (live,) bool)."""
    from .backend import _challenges_any, _pack_rows, _s_below_l
    from .entry_block import EntryBlock
    from ..native import load as _load_native

    n = len(entries)
    pub, r_enc, s_enc = _pack_rows(entries, live)
    z = _gen_z(live)

    native = _load_native()
    if (
        n
        and isinstance(entries, EntryBlock)
        and native is not None
        and hasattr(native, "ed25519_rlc_prep")
    ):
        buf, offs = entries.msgs_contiguous()
        k_raw, raw, sok_raw = native.ed25519_rlc_prep(
            entries.pub.tobytes(),
            entries.sig.tobytes(),
            buf,
            np.ascontiguousarray(offs).tobytes(),
            z.tobytes(),
            M,
            live,
        )
        s_ok = np.frombuffer(sok_raw, dtype=np.uint8).astype(bool)
    else:
        s_ok = _s_below_l(s_enc, n, live)
        k_enc = np.zeros((live, 32), dtype=np.uint8)
        if n:
            ks = _challenges_any(r_enc[:n], pub[:n], entries)
            k_enc[:n] = np.frombuffer(ks, dtype=np.uint8).reshape(n, 32)
        s_b, k_b, z_b = s_enc.tobytes(), k_enc.tobytes(), z.tobytes()
        if native is not None and hasattr(native, "ed25519_rlc_scalars"):
            raw = native.ed25519_rlc_scalars(s_b, k_b, z_b, M)
        else:
            raw = _rlc_scalars_py(s_b, k_b, z_b, M)
    S = np.frombuffer(raw[: 32 * g_live], dtype=np.uint8).reshape(g_live, 32)
    U = np.frombuffer(raw[32 * g_live :], dtype=np.uint8).reshape(g_live, M, 32)

    scal = np.zeros((g_live, N_SCAL, 32), dtype=np.uint8)
    scal[:, 0] = S
    scal[:, 1 : M + 1] = U
    scal[:, M + 1 :] = z.reshape(g_live, M, 32)[:, 1:]
    return pub, r_enc, scal, s_ok


def prepare_rlc(entries, bucket: int):
    """EntryBlock or (pub32, msg, sig64) triples -> RLC kernel args,
    padded to `bucket` signatures (bucket % M == 0, bucket // M lanes).
    Host work on top of the per-sig prep (pack + SHA-512 challenges +
    s<L): one 128x256-bit mod-L mul-add per signature (see
    _rlc_host_scalars), then the slot-major transposes the kernel layout
    needs — warm epochs skip those via prepare_rlc_cached."""
    n = len(entries)
    if bucket % M:
        raise ValueError(f"bucket {bucket} not a multiple of M={M}")
    g = bucket // M
    # All host work runs over the LIVE lanes only; padding lanes get
    # their constant pattern (identity-point A/R encodings, zero scalars,
    # s_ok true) via broadcast assigns. A coalesced total just past a
    # quantized bucket would otherwise pay the full bucket's packing and
    # transposes on the host.
    g_live = min((n + M - 1) // M, g)
    live = g_live * M
    pub, r_enc, scal, s_ok = _rlc_host_scalars(entries, live, g_live)

    def slotmajor(arr):  # (live, 32) -> (M*32, g_live)
        return np.ascontiguousarray(
            arr.reshape(g_live, M, 32).transpose(1, 2, 0).reshape(M * 32, g_live)
        )

    a_t = np.zeros((M * 32, g), dtype=np.uint8)
    r_t = np.zeros((M * 32, g), dtype=np.uint8)
    scal_t = np.zeros((N_SCAL * 32, g), dtype=np.uint8)
    sok_t = np.ones((M, g), dtype=np.int32)
    # padding lanes: identity encoding = byte 0 of each slot set to 1
    a_t[np.arange(M) * 32, g_live:] = 1
    r_t[np.arange(M) * 32, g_live:] = 1
    if g_live:
        a_t[:, :g_live] = slotmajor(pub)
        r_t[:, :g_live] = slotmajor(r_enc)
        scal_t[:, :g_live] = np.ascontiguousarray(
            scal.transpose(1, 2, 0).reshape(N_SCAL * 32, g_live)
        )
        sok_t[:, :g_live] = s_ok.reshape(g_live, M).T.astype(np.int32)
    return a_t, r_t, scal_t, sok_t


def prepare_rlc_cached(entries, bucket: int, ep):
    """Warm-epoch RLC prep: same host scalar stage as prepare_rlc, but
    the committee ships as val_idx gather indices (the kernel gathers the
    cached decompressed A coords on device) and every per-sig array ships
    ROW-major — the slot-major transposes happen on device in the jitted
    cached pipeline. entries must be an EntryBlock with val_idx set.

    Returns (idx (bucket,) int32, r_rows (bucket, 32) uint8,
    scal_rows (g, N_SCAL, 32) uint8, sok_rows (g, M) int32)."""
    n = len(entries)
    if bucket % M:
        raise ValueError(f"bucket {bucket} not a multiple of M={M}")
    g = bucket // M
    g_live = min((n + M - 1) // M, g)
    live = g_live * M
    _pub, r_enc, scal, s_ok = _rlc_host_scalars(entries, live, g_live)

    idx = np.full((bucket,), ep.vp - 1, dtype=np.int32)
    idx[:n] = entries.val_idx
    r_rows = np.zeros((bucket, 32), dtype=np.uint8)
    r_rows[:live] = r_enc
    r_rows[live:, 0] = 1  # padding lanes: identity encoding
    scal_rows = np.zeros((g, N_SCAL, 32), dtype=np.uint8)
    scal_rows[:g_live] = scal
    sok_rows = np.ones((g, M), dtype=np.int32)
    sok_rows[:g_live] = s_ok.reshape(g_live, M).astype(np.int32)
    return idx, r_rows, scal_rows, sok_rows


def verify_rlc_compact(a_t, r_t, scal_t, sok_t, block: int = 0,
                       interpret: bool = False) -> np.ndarray:
    """Run the RLC kernel; returns (g,) bool LANE validity (a lane is
    valid iff the RLC equation holds and every slot's flags pass)."""
    block = block or BLOCK_LANES
    g = a_t.shape[-1]
    if g % block:
        raise ValueError(f"lane count {g} not a multiple of block {block}")
    out = _jitted_rlc_verify(g, block, interpret)(a_t, r_t, scal_t, sok_t)
    return np.asarray(out)[0].astype(bool)


def expand_lanes(lane_valid: np.ndarray, entries) -> np.ndarray:
    """Lane verdicts -> per-signature verdicts (entries: EntryBlock or
    tuple list). Valid lanes accept all M slots; rejected lanes re-verify
    their live signatures individually on the host for blame
    (types/validation.go:242-248 asymmetry — rejects are the rare path,
    and M host verifies cost ~0.5 ms). The blame path is the ONLY place a
    per-signature tuple is materialized from an EntryBlock — M lanes at a
    time, never the whole batch."""
    from ..crypto import ed25519 as _ed25519
    from .entry_block import EntryBlock

    n = len(entries)
    per_sig = np.repeat(lane_valid, M)[:n].copy()
    if not lane_valid.all():
        is_block = isinstance(entries, EntryBlock)
        for lane in np.nonzero(~lane_valid)[0]:
            for i in range(lane * M, min((lane + 1) * M, n)):
                pk, msg, sig = entries.entry(i) if is_block else entries[i]
                per_sig[i] = _ed25519.verify_zip215_fast(pk, msg, sig)
    return per_sig


def verify_batch_rlc(entries, block: int = 0, interpret: bool = False) -> np.ndarray:
    """Arbitrary-size batch through the RLC fast-accept path; returns
    per-signature (n,) bool with exact per-sig ZIP-215 blame. Warm-epoch
    EntryBlocks route through the cached kernel (committee gathered from
    the device-resident table)."""
    from . import epoch_cache as _epoch

    ep = _epoch.lookup(entries)
    sigs_per_call = MAX_SIGS
    out = []
    i = 0
    while i < len(entries):
        chunk = entries[i : i + sigs_per_call]
        bucket, g, blk = plan_bucket(len(chunk), block)
        if ep is not None:
            args = prepare_rlc_cached(chunk, bucket, ep)
            dev = rlc_cached_fn(ep, g, blk, interpret)(*args)
            lane_valid = np.asarray(dev)[0].astype(bool)
        else:
            args = prepare_rlc(chunk, bucket)
            lane_valid = verify_rlc_compact(
                *args, block=blk, interpret=interpret
            )
        out.append(expand_lanes(lane_valid, chunk))
        i += len(chunk)
    return (
        np.concatenate(out) if out else np.zeros((0,), dtype=bool)
    )
