"""Batched secp256k1 ECDSA verification kernel (JAX/XLA, TPU-first).

The scheme-diversity lane (ISSUE 19 / ROADMAP item 3a): plain ECDSA can't
ride ed25519's randomized-linear-combination fusion (each signature hides
an independent modular inversion), but each signature's point equation

    R' = (e/s)·G + (r/s)·Q,   accept iff x(R') ≡ r (mod n)

is embarrassingly parallel across batch lanes — exactly the shape of
`ed25519_verify.verify_kernel`. Semantics are *per-signature* and match
the host oracle `crypto.secp256k1.PubKey.verify_signature` bit-for-bit
(including the reference's lower-S rejection, checked host-side like
ed25519's s < L).

Ladder shape: the host GLV-splits both scalars u1 = e/s and u2 = r/s
through the secp256k1 endomorphism (sc_secp), so the device runs a joint
4-scalar Strauss ladder — 130 iterations of (1 doubling + 1 add from a
16-entry per-lane subset-sum table of {±G, ±φG, ±Q, ±φQ}) — instead of
256 iterations over two full-width scalars. Point arithmetic uses the
Renes–Costello–Batina *complete* a=0 formulas (EuroCrypt 2016, Algs 7/9,
b3 = 3·7 = 21), so identity/equal/negated inputs need no branches and
all-zero scalar rows (host-rejected lanes) simply walk to the identity.

The final comparison is projective — x(R') ≡ r tests X ≡ r·Z without an
inversion — with a second candidate column r+n covering the x mod n
wraparound (possible because n < p < n + 2^129... strictly p - n < 2^129,
so at most one extra candidate and the host precomputes both).

Host-side prep (this module, `prepare_rows`): SHA-256 digests, one
batched s^-1 mod n (Montgomery trick), GLV decomposition, and pubkey
decompression (memoized; the epoch-cached path keeps decompressed Q
columns device-resident instead — ops/epoch_cache.py).
"""

from __future__ import annotations

import functools
import hashlib

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from . import fe_secp as fe
from . import sc_secp as sc
from ..crypto import _weierstrass as wst

N = sc.N
N_HALF = sc.N_HALF
P = fe.P

SCALAR_BITS = sc.SCALAR_BITS  # 130: GLV halves, one headroom bit
B3 = 21  # 3*b for y^2 = x^3 + 7 (the RCB formula constant)

# Curve constants in limb form — NUMPY, not jnp (trace-immunity; see the
# ed25519_verify constants note).
GX_L = np.asarray(fe.limbs_from_int(wst.GX))
GY_L = np.asarray(fe.limbs_from_int(wst.GY))
NEG_GY_L = np.asarray(fe.limbs_from_int(P - wst.GY))
PHI_GX_L = np.asarray(fe.limbs_from_int(sc.BETA * wst.GX % P))  # x(φG)
BETA_L = np.asarray(fe.limbs_from_int(sc.BETA))
ONE_L = np.asarray(fe.limbs_from_int(1))

# The endomorphism must actually act as [λ]: φ(G) = (β·Gx, Gy) = λ·G.
assert wst.scalar_mult(sc.LAMBDA, wst.G) == (sc.BETA * wst.GX % P, wst.GY)


def point_add(p, q):
    """Complete projective addition for y^2 = x^3 + b, a = 0 (RCB16
    Algorithm 7, b3 = 21): 12 muls + 3 small-constant muls, valid for ALL
    inputs including the identity (0, 1, 0) — no branches in the ladder."""
    x1, y1, z1 = p
    x2, y2, z2 = q
    t0 = fe.mul(x1, x2)
    t1 = fe.mul(y1, y2)
    t2 = fe.mul(z1, z2)
    t3 = fe.sub(fe.mul(fe.add(x1, y1), fe.add(x2, y2)), fe.add(t0, t1))
    t4 = fe.sub(fe.mul(fe.add(y1, z1), fe.add(y2, z2)), fe.add(t1, t2))
    t5 = fe.sub(fe.mul(fe.add(x1, z1), fe.add(x2, z2)), fe.add(t0, t2))
    t0_3 = fe.mul_small(t0, 3)  # 3·X1X2
    t2_b = fe.mul_small(t2, B3)  # 3b·Z1Z2
    zs = fe.add(t1, t2_b)  # Y1Y2 + 3bZ1Z2
    t1m = fe.sub(t1, t2_b)  # Y1Y2 - 3bZ1Z2
    t5_b = fe.mul_small(t5, B3)  # 3b·(X1Z2 + X2Z1)
    x3 = fe.sub(fe.mul(t3, t1m), fe.mul(t4, t5_b))
    y3 = fe.add(fe.mul(t1m, zs), fe.mul(t5_b, t0_3))
    z3 = fe.add(fe.mul(zs, t4), fe.mul(t0_3, t3))
    return (x3, y3, z3)


def point_double(p):
    """Complete projective doubling, a = 0 (RCB16 Algorithm 9)."""
    x, y, z = p
    t0 = fe.sq(y)
    y8 = fe.mul_small(t0, 8)  # 8Y^2
    t2 = fe.mul_small(fe.sq(z), B3)  # 3bZ^2
    x3 = fe.mul(t2, y8)  # 24bY^2Z^2
    y3 = fe.add(t0, t2)  # Y^2 + 3bZ^2
    z3 = fe.mul(fe.mul(y, z), y8)  # 8Y^3Z
    t0m = fe.sub(t0, fe.mul_small(t2, 3))  # Y^2 - 9bZ^2
    y3 = fe.add(x3, fe.mul(t0m, y3))
    x3 = fe.mul_small(fe.mul(t0m, fe.mul(x, y)), 2)
    return (x3, y3, z3)


def _stack_points(points, axis=0):
    """[(x,y,z), ...] -> one point whose coords carry a new stacked axis."""
    return tuple(
        jnp.stack([pt[c] for pt in points], axis=axis) for c in range(3)
    )


def _unstack_point(point, i):
    return tuple(c[i] for c in point)


def _select_point(table, idx):
    """table: point with (..., 16, 20) coords; idx: (...,) in [0, 16)."""
    out = []
    for c in table:
        picked = jnp.take_along_axis(c, idx[..., None, None], axis=-2)
        out.append(picked[..., 0, :])
    return tuple(out)


def scalar_digits(scalars):
    """(B, 4, 10) int32 13-bit scalar limbs -> (130, B) int32 joint table
    indices: digit = b1 + 2·b2 + 4·b3 + 8·b4, transposed for the ladder."""
    shifts = jnp.arange(fe.RADIX, dtype=scalars.dtype)
    bits = (scalars[:, :, :, None] >> shifts) & 1  # (B, 4, 10, 13)
    bits = bits.reshape(scalars.shape[0], 4, SCALAR_BITS)
    return (
        bits[:, 0] + 2 * bits[:, 1] + 4 * bits[:, 2] + 8 * bits[:, 3]
    ).T


def verify_kernel(qx, qy, scalars, signs, r1, r2, ok_host):
    """Batched per-signature ECDSA verification.

    Args (B = batch):
      qx, qy:   (B, 20) int32 — affine pubkey Q limbs (host-decompressed,
                canonical; rejected pubkeys carry G with ok_host False)
      scalars:  (B, 4, 10) int32 — |k| limbs of the GLV halves, order
                (u1_a, u1_b, u2_a, u2_b) for bases (G, φG, Q, φQ)
      signs:    (B, 4) int32 — 1 = negate that base point
      r1, r2:   (B, 20) int32 — the x-candidate limbs: r, and r+n when
                r+n < p (else r again — a harmless duplicate)
      ok_host:  (B,) bool — host-checked lengths/ranges/lower-S/decompress
    Returns: (B,) bool.
    """
    # Broadcast constants derived from an input (x + 0*input) so they keep
    # shard_map varying-manual-axes — same trick as ed25519_verify.
    zero_b = qx - qx
    one_b = ONE_L + zero_b
    ident = (zero_b, one_b, zero_b)

    gy_pos = GY_L + zero_b
    gy_neg = NEG_GY_L + zero_b
    qy_neg = fe.neg(qy)

    def pick_y(col, pos, neg_):
        return jnp.where((signs[:, col] == 1)[:, None], neg_, pos)

    b1 = (GX_L + zero_b, pick_y(0, gy_pos, gy_neg), one_b)
    b2 = (PHI_GX_L + zero_b, pick_y(1, gy_pos, gy_neg), one_b)
    b3p = (qx, pick_y(2, qy, qy_neg), one_b)
    b4 = (fe.mul(qx, BETA_L), pick_y(3, qy, qy_neg), one_b)

    # 16-entry subset-sum table, idx = b1 + 2b2 + 4b3 + 8b4, built with
    # three batched adds (3-lane + 1 + 7-lane) instead of 11 traces.
    s12 = point_add(
        _stack_points([b1, b3p, b3p]), _stack_points([b2, b1, b2])
    )
    t3 = _unstack_point(s12, 0)  # b1 + b2
    t5 = _unstack_point(s12, 1)  # b3 + b1
    t6 = _unstack_point(s12, 2)  # b3 + b2
    t7 = point_add(t3, b3p)  # b1 + b2 + b3
    low = [ident, b1, b2, t3, b3p, t5, t6, t7]
    hi = point_add(_stack_points(low[1:]), _stack_points([b4] * 7))
    entries = low + [b4] + [_unstack_point(hi, i) for i in range(7)]
    table = _stack_points(entries, axis=-2)  # coords (..., 16, 20)

    digits = scalar_digits(scalars)  # (130, B)

    def body(i, acc):
        d = lax.dynamic_index_in_dim(
            digits, SCALAR_BITS - 1 - i, 0, keepdims=False
        )
        acc = point_double(acc)
        return point_add(acc, _select_point(table, d))

    x, y, z = lax.fori_loop(0, SCALAR_BITS, body, ident)

    # Accept iff R' != O and x(R') ≡ r (mod n): projective compare against
    # both candidates (X ≡ cand·Z), no field inversion on device.
    nz = ~fe.is_zero(z)
    ok_x = fe.is_zero(fe.sub(x, fe.mul(r1, z))) | fe.is_zero(
        fe.sub(x, fe.mul(r2, z))
    )
    return ok_host & nz & ok_x


def verify_kernel_cached(
    qx_tbl, qy_tbl, q_ok_tbl, val_idx, scalars, signs, r1, r2, ok_host
):
    """verify_kernel with the committee's decompressed affine Q columns
    gathered from a device-resident epoch table (ops/epoch_cache.py):
    qx_tbl/qy_tbl (V, 20) int32, q_ok_tbl (V,) bool (False = the pubkey
    didn't decompress; its row carries G), val_idx (B,) int32."""
    qx = qx_tbl[val_idx]
    qy = qy_tbl[val_idx]
    ok = ok_host & q_ok_tbl[val_idx]
    return verify_kernel(qx, qy, scalars, signs, r1, r2, ok)


# -- host-side preparation ---------------------------------------------------


@functools.lru_cache(maxsize=65536)
def _decompress_memo(pub: bytes):
    return wst.decompress(pub)


def field_to_limbs(vals) -> np.ndarray:
    """Canonical field ints (< 2^256) -> (B, 20) int32 rows of 13-bit
    limbs, vectorized through a LE byte buffer like sc_secp.scalars_to_limbs."""
    if not len(vals):
        return np.zeros((0, fe.NLIMBS), dtype=np.int32)
    buf = b"".join(int(v).to_bytes(32, "little") for v in vals)
    w = np.frombuffer(buf, dtype="<u8").reshape(len(vals), 4)
    out = np.empty((len(vals), fe.NLIMBS), dtype=np.int32)
    for i in range(fe.NLIMBS):
        lo = fe.RADIX * i
        word, shift = lo >> 6, lo & 63
        v = w[:, word] >> np.uint64(shift)
        if shift + fe.RADIX > 64 and word + 1 < 4:
            v = v | (w[:, word + 1] << np.uint64(64 - shift))
        out[:, i] = (v & np.uint64(fe.MASK)).astype(np.int32)
    return out


def table_columns(pubs):
    """Decompress a committee's 33-byte pubkeys into epoch-table columns:
    (qx (V+1, 20) int32, qy, q_ok (V+1,) bool). Invalid pubkeys carry G
    with q_ok False; row V is the padding lane (G, ok)."""
    xs, ys, oks = [], [], []
    for pub in pubs:
        pt = _decompress_memo(bytes(pub)) if len(pub) == 33 else None
        if pt is None:
            xs.append(wst.GX)
            ys.append(wst.GY)
            oks.append(False)
        else:
            xs.append(pt[0])
            ys.append(pt[1])
            oks.append(True)
    xs.append(wst.GX)
    ys.append(wst.GY)
    oks.append(True)
    return (
        field_to_limbs(xs),
        field_to_limbs(ys),
        np.array(oks, dtype=bool),
    )


# A padding lane is a trivially-true row: u1 = 1, u2 = 0, Q = G, x-cand =
# Gx, so the ladder computes R' = G and the compare passes algebraically
# (matching ed25519's identity-pad convention: pads never poison a batch
# and their verdict is deterministic True).
_PAD_SCALARS = np.zeros((4, sc.SCALAR_LIMBS), dtype=np.int32)
_PAD_SCALARS[0, 0] = 1


def _empty_rows(size: int):
    qx = np.broadcast_to(GX_L, (size, fe.NLIMBS)).copy()
    qy = np.broadcast_to(GY_L, (size, fe.NLIMBS)).copy()
    scalars = np.broadcast_to(
        _PAD_SCALARS, (size, 4, sc.SCALAR_LIMBS)
    ).copy()
    signs = np.zeros((size, 4), dtype=np.int32)
    r1 = np.broadcast_to(GX_L, (size, fe.NLIMBS)).copy()
    r2 = r1.copy()
    ok = np.ones(size, dtype=bool)
    return qx, qy, scalars, signs, r1, r2, ok


def prepare_rows(items, size: int | None = None, with_tables: bool = False):
    """Host prep for a batch of (pub33, msg, sig64) -> kernel arg arrays.

    Rows [len(items):size] are trivial-accept padding lanes. Rejected rows
    (bad length / range / non-lower-S / failed decompress) keep padding
    numerics with ok_host False — the kernel's verdict gate.

    with_tables=False (the default) returns the direct-kernel args
    (qx, qy, scalars, signs, r1, r2, ok_host); with_tables=True returns
    (val_idx, scalars, signs, r1, r2, ok_host, (qx, qy, q_ok)) where the
    pubkey columns are deduplicated for the epoch-cached kernel.
    """
    n = len(items)
    size = n if size is None else size
    qx, qy, scalars, signs, r1, r2, ok = _empty_rows(size)

    pend = []  # (row, r, e, q) awaiting the batched inversion
    svals = []
    for i, (pub, msg, sig) in enumerate(items):
        ok[i] = False
        if len(sig) != 64:
            continue
        r = int.from_bytes(sig[:32], "big")
        s = int.from_bytes(sig[32:], "big")
        if r <= 0 or s <= 0 or r >= N or s > N_HALF:
            continue
        q = _decompress_memo(bytes(pub)) if len(pub) == 33 else None
        if q is None:
            continue
        e = int.from_bytes(hashlib.sha256(bytes(msg)).digest(), "big")
        pend.append((i, r, e, q))
        svals.append(s)

    winv = sc.inv_mod_n_many(svals)
    sc_rows, r1_i, r2_i, qx_i, qy_i, rows = [], [], [], [], [], []
    for (i, r, e, q), w in zip(pend, winv):
        u1 = e * w % N
        u2 = r * w % N
        m1, s1, m2, s2 = sc.glv_decompose(u1)
        m3, s3, m4, s4 = sc.glv_decompose(u2)
        signs[i] = (s1, s2, s3, s4)
        sc_rows.extend((m1, m2, m3, m4))
        r1_i.append(r)
        r2_i.append(r + N if r + N < P else r)
        qx_i.append(q[0])
        qy_i.append(q[1])
        rows.append(i)
        ok[i] = True

    if rows:
        idx = np.asarray(rows)
        scalars[idx] = sc.scalars_to_limbs(sc_rows).reshape(
            len(rows), 4, sc.SCALAR_LIMBS
        )
        r1[idx] = field_to_limbs(r1_i)
        r2[idx] = field_to_limbs(r2_i)
        qx[idx] = field_to_limbs(qx_i)
        qy[idx] = field_to_limbs(qy_i)
    return qx, qy, scalars, signs, r1, r2, ok


def prepare_rows_cached(items, val_idx, size: int, pad_idx: int):
    """Warm-epoch host prep (ops/epoch_cache.py secp_tables): the
    committee's decompressed Q columns stay device-resident, so the batch
    ships only gather indices + scalar data — no host decompression at
    all. Returns the verify_kernel_cached args after the tables:
    (val_idx (size,) int32, scalars, signs, r1, r2, ok_host). Rows
    [len(items):size] are trivial-accept pads gathering the table's pad
    row `pad_idx` (G, ok). A row whose pubkey failed decompression is
    killed by the TABLE's q_ok lane, matching prepare_rows' verdicts
    bit-for-bit."""
    n = len(items)
    _, _, scalars, signs, r1, r2, ok = _empty_rows(size)
    idx_col = np.full(size, pad_idx, dtype=np.int32)
    if n:
        idx_col[:n] = np.asarray(val_idx, dtype=np.int32)[:n]

    pend = []
    svals = []
    for i, (_pub, msg, sig) in enumerate(items):
        ok[i] = False
        if len(sig) != 64:
            continue
        r = int.from_bytes(sig[:32], "big")
        s = int.from_bytes(sig[32:], "big")
        if r <= 0 or s <= 0 or r >= N or s > N_HALF:
            continue
        e = int.from_bytes(hashlib.sha256(bytes(msg)).digest(), "big")
        pend.append((i, r, e))
        svals.append(s)

    winv = sc.inv_mod_n_many(svals)
    sc_rows, r1_i, r2_i, rows = [], [], [], []
    for (i, r, e), w in zip(pend, winv):
        u1 = e * w % N
        u2 = r * w % N
        m1, s1, m2, s2 = sc.glv_decompose(u1)
        m3, s3, m4, s4 = sc.glv_decompose(u2)
        signs[i] = (s1, s2, s3, s4)
        sc_rows.extend((m1, m2, m3, m4))
        r1_i.append(r)
        r2_i.append(r + N if r + N < P else r)
        rows.append(i)
        ok[i] = True

    if rows:
        idx = np.asarray(rows)
        scalars[idx] = sc.scalars_to_limbs(sc_rows).reshape(
            len(rows), 4, sc.SCALAR_LIMBS
        )
        r1[idx] = field_to_limbs(r1_i)
        r2[idx] = field_to_limbs(r2_i)
    return idx_col, scalars, signs, r1, r2, ok


def verify_rows(items, size: int | None = None) -> np.ndarray:
    """Convenience host driver: prepare + jitted kernel + np verdicts
    (the direct, non-epoch-cached path; mirrors backend.verify_batch's
    use of the ed25519 kernel)."""
    args = prepare_rows(items, size)
    return np.array(jitted_secp_verify()(*args))[: len(items)]


# Donation contract mirrors ed25519_verify: per-batch buffers may be
# donated; the epoch-table arguments of the cached kernel (argnums 0-2)
# are persistent device residents and are NEVER donated.


@functools.lru_cache(maxsize=None)
def jitted_secp_verify(donate: bool = False):
    if donate:
        return jax.jit(verify_kernel, donate_argnums=tuple(range(7)))
    return jax.jit(verify_kernel)


@functools.lru_cache(maxsize=None)
def jitted_secp_verify_cached(donate: bool = False):
    if donate:
        return jax.jit(verify_kernel_cached, donate_argnums=tuple(range(3, 9)))
    return jax.jit(verify_kernel_cached)
