"""Per-shape device input-buffer pool for the overlapped dispatcher.

ISSUE 7 tentpole piece (1): the dispatch-owner thread used to hand the
jitted kernel bare numpy arrays, so every launch implicitly minted fresh
device allocations for the batch inputs and the H2D copy serialized in
front of the kernel inside the launch call. The pool makes the input
buffers an explicit, bounded resource:

- a **slot** is one in-flight batch's set of device input buffers for one
  compiled layout (bucket + per-array shapes/dtypes). Acquiring a slot
  bounds how many batch input sets may be alive on the device at once
  (double/triple buffering, ``TM_TPU_POOL_DEPTH``); releasing it — after
  the batch resolves, or fails — recycles the allocation for the next
  batch.
- ``transfer()`` issues the actual ``jax.device_put`` of a prepared
  argument tuple. The dispatcher calls it for batch k+1 *before* blocking
  on the depth semaphore, so the copy rides behind kernel k's compute
  instead of serializing in front of its own launch.
- with buffer **donation** on (``ops/ed25519_verify.jitted_verify(donate
  =True)`` and friends), the transferred arrays are donated to XLA at
  launch — their pages return to the allocator the moment the kernel has
  consumed them, so the next slot's ``device_put`` reuses the same
  allocation instead of growing the arena. JAX has no host-writes-into-
  existing-device-buffer API; donation + a bounded slot set IS the
  recycled-allocation steady state.

Epoch tables (ops/epoch_cache.py) never pass through the pool: they are
persistent device residents resolved inside the kernel closures and are
explicitly excluded from every kernel's ``donate_argnums``.

``buffer_pool_hits``/``buffer_pool_misses`` (OpsMetrics): a hit recycles
a previously-minted slot, a miss mints a new one. A steady-state stream
over one bucket shows misses == pool depth (warmup) and hits thereafter.
NOTE what these observe: the HOST-side bounded-slot invariant (in-flight
input sets per layout, and that error paths return slots) — the page
recycling itself happens inside XLA under donation and is not visible
from Python. ``tools/prep_bench.py --overlap`` gates the slot bound plus
the dispatcher's span order; at the default ``pool_depth = depth + 1``
the acquire path never blocks (the launch semaphore is the tighter
bound) — blocking engages when TM_TPU_POOL_DEPTH is set below that,
which throttles the transfer stage itself.

Pure bookkeeping + lazy jax: importable without jax (the pool is built at
pipeline init, which already sits behind the device stack, but tests
exercise the accounting standalone).
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

try:
    from ..libs import devcheck as _devcheck
except ImportError:  # pragma: no cover — standalone file load (tests on
    # crypto-less containers exec this module by path, outside the
    # package); devcheck is stdlib+numpy so it loads the same way
    import importlib.util as _ilu
    import os as _os

    _dc_path = _os.path.join(
        _os.path.dirname(_os.path.abspath(__file__)),
        _os.pardir, "libs", "devcheck.py",
    )
    _dc_spec = _ilu.spec_from_file_location(
        "_tm_tpu_devcheck_standalone", _dc_path
    )
    _devcheck = _ilu.module_from_spec(_dc_spec)
    _dc_spec.loader.exec_module(_devcheck)

LayoutKey = Tuple


def layout_key(bucket: int, args) -> LayoutKey:
    """Compiled-layout key for a prepared argument tuple: the bucket plus
    every host array's (shape, dtype). Distinct preps (cached/uncached,
    host-hash/device-hash, RLC) of the same bucket get distinct keys —
    a slot only ever recycles buffers of identical layout."""
    return (bucket,) + tuple(
        (a.shape, a.dtype.str) for a in args if isinstance(a, np.ndarray)
    )


def transfer(args, shardings=None) -> tuple:
    """Issue the H2D copy of a prepared argument tuple: ``device_put``
    every host array (jax Arrays — none on current paths, but e.g. a
    pre-resolved table — pass through untouched). Returns the tuple with
    device arrays in place of numpy ones. The call returns once the
    copies are *enqueued*; completion ordering against the kernel's reads
    is the runtime's job.

    ``shardings`` (ISSUE 9: mesh superbatches) is an optional per-arg
    sequence of jax Shardings — each array's copy is placed
    lane-per-device across the dispatcher's mesh instead of on the
    default device, so batch k+1's distributed H2D rides behind mesh
    kernel k exactly like the single-device overlap path."""
    # devcheck relay assertion (ISSUE 8): transfers are relay touches —
    # once a dispatcher has claimed the relay, only it may issue them
    _devcheck.note_relay_touch("device_pool.transfer")
    import jax

    if shardings is None:
        return tuple(
            jax.device_put(a) if isinstance(a, np.ndarray) else a
            for a in args
        )
    if len(shardings) != len(args):
        raise ValueError(
            f"{len(args)} args but {len(shardings)} transfer shardings"
        )
    return tuple(
        jax.device_put(a, s) if isinstance(a, np.ndarray) else a
        for a, s in zip(args, shardings)
    )


class PoolSlot:
    """One in-flight batch's input-buffer set. ``arrays`` pins the
    transferred device arrays for the slot's flight (leak tests introspect
    it); release clears it so nothing outlives the batch."""

    __slots__ = ("key", "arrays")

    def __init__(self, key: LayoutKey):
        self.key = key
        self.arrays: Optional[tuple] = None


class DeviceBufferPool:
    """Bounded per-layout slot pool (thread-safe).

    ``acquire`` blocks while ``depth`` slots of the SAME layout are in
    flight — that is the transfer-side backpressure bound, one deeper
    than the launch semaphore so batch k+1's copy can start while the
    pipeline is otherwise full. ``abort`` (a callable) lets a shutting-
    down dispatcher bail out of the wait."""

    def __init__(self, depth: int = 3):
        self.depth = max(int(depth), 1)
        self._mtx = _devcheck.lock("pool.slots")
        self._cv = threading.Condition(self._mtx)
        self._free: Dict[LayoutKey, List[PoolSlot]] = {}
        self._minted: Dict[LayoutKey, int] = {}
        self._in_flight = 0

    def acquire(self, key: LayoutKey,
                abort: Optional[Callable[[], bool]] = None,
                _metrics=None) -> Optional[PoolSlot]:
        """A slot for `key`: recycled when one is free (hit), minted while
        under depth (miss), else blocks until a release. Returns None only
        when `abort()` goes true while waiting."""
        m = _metrics if _metrics is not None else _ops()
        with self._cv:
            while True:
                free = self._free.get(key)
                if free:
                    slot = free.pop()
                    self._in_flight += 1
                    if m is not None:
                        m.buffer_pool_hits.inc()
                    return slot
                if self._minted.get(key, 0) < self.depth:
                    self._minted[key] = self._minted.get(key, 0) + 1
                    self._in_flight += 1
                    if m is not None:
                        m.buffer_pool_misses.inc()
                    return PoolSlot(key)
                if abort is not None and abort():
                    return None
                self._cv.wait(timeout=0.1)

    def release(self, slot: Optional[PoolSlot]) -> None:
        """Return a slot (idempotence is the caller's job — the dispatcher
        nulls its reference on handoff). None is a no-op so error paths
        can release unconditionally."""
        if slot is None:
            return
        if _devcheck.enabled():
            # write-after-resolve canary: the slot's flight is over — all
            # previously delivered verdicts must still be byte-stable,
            # and the returned device buffers get poisoned where the
            # backend exposes writable host views
            _devcheck.on_slot_release(slot.arrays)
        slot.arrays = None
        with self._cv:
            self._in_flight -= 1
            self._free.setdefault(slot.key, []).append(slot)
            self._cv.notify()

    # -- introspection (leak tests, /status, the --overlap gate) ---------

    def in_flight(self) -> int:
        with self._mtx:
            return self._in_flight

    def stats(self) -> dict:
        with self._mtx:
            return {
                "depth": self.depth,
                "in_flight": self._in_flight,
                "layouts": len(self._minted),
                "minted": int(sum(self._minted.values())),
                "free": int(sum(len(v) for v in self._free.values())),
            }


_ops_cached = None


def _ops():
    global _ops_cached
    if _ops_cached is None:
        from ..libs import metrics as _metrics

        _ops_cached = _metrics.ops_metrics()
    return _ops_cached


class WindowedRatio:
    """Windowed num/den ratio pushed to a gauge, reset every ~`window`
    seconds (ISSUE 7 satellite: the dispatcher carried three inline
    copies of this accounting for `dispatch_busy_ratio`).

    wall=True: occupancy mode — the denominator is wall-clock elapsed
    since the window opened (busy seconds / elapsed). wall=False: the
    caller accumulates both terms (e.g. hidden transfer time / total
    transfer time). `tick()` is the idle heartbeat: it rolls the window
    so the gauge decays toward the current (quiet) window instead of
    sticking at the last busy value."""

    def __init__(self, gauge, window: float = 2.0, wall: bool = True):
        self._g = gauge
        self._window = window
        self._wall = wall
        self._start = time.perf_counter()
        self._num = 0.0
        self._den = 0.0

    def _publish(self, now: float) -> None:
        if self._wall:
            elapsed = now - self._start
            # occupancy needs a minimum measurement base: a sample
            # landing right after a roll would divide by near-zero
            # elapsed and clamp the gauge to 1.0 on an idle relay —
            # hold the previous value until the window has substance
            if elapsed >= min(self._window * 0.05, 0.05):
                self._g.set(min(self._num / elapsed, 1.0))
        elif self._den > 0:
            self._g.set(min(self._num / self._den, 1.0))

    def _roll(self, now: float) -> None:
        if now - self._start >= self._window:
            self._start, self._num, self._den = now, 0.0, 0.0

    def add(self, num: float, den: float = 0.0) -> None:
        now = time.perf_counter()
        # accumulate into the CURRENT window and publish before rolling:
        # a sample that closes a window genuinely spans it, and counting
        # it against the full elapsed window (then resetting) cannot
        # clamp the gauge to 1.0 the way crediting it to a zero-length
        # fresh window would. Stale pre-idle accumulators are not merged
        # in practice because the owner tick()s through idle stretches,
        # rolling the window long before the next sample lands.
        self._num += num
        self._den += den
        self._publish(now)
        self._roll(now)

    def tick(self) -> None:
        now = time.perf_counter()
        if now - self._start >= self._window:
            if not self._wall and self._den == 0:
                # ratio mode with an empty window: nothing flowed, so the
                # gauge decays to 0 (den==0 makes _publish a no-op)
                self._g.set(0.0)
            else:
                self._publish(now)
            self._start, self._num, self._den = now, 0.0, 0.0
