"""Batched ZIP-215 ed25519 verification kernel (JAX/XLA, TPU-first).

The device replacement for the reference's batch verifier
(crypto/ed25519/ed25519.go:192-227, curve25519-voi ZIP-215 config) and the
compute half of SURVEY.md §7 stage 1. Semantics are *per-signature*
cofactored verification — exactly the oracle in
tendermint_tpu.crypto._edwards.verify_zip215:

    accept iff  A, R decompress (non-canonical y allowed),
                0 <= s < L (checked host-side), and
                [8]([s]B - R - [k]A) == O,  k = SHA512(R||A||M) mod L.

Per-signature evaluation (vs the reference's random-linear-combination
batch) is the right shape for TPU: it is embarrassingly parallel over the
batch axis, needs no host-side randomness, and directly yields the per-sig
valid[] vector that types/validation.go:242-248 needs for blame assignment
— the reference has to re-verify one-by-one on batch failure to get it.

Control flow is branchless (complete twisted-Edwards formulas, masked
selects), shapes are static per bucket: everything jits to one XLA
computation with a 253-iteration fori_loop over the joint (Straus)
double-scalar ladder.
"""

from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from . import fe, sc, sha512 as _sha
from ..crypto import _edwards

# Curve constants in limb form (host-computed Python ints -> 20-limb arrays).
# Kept as NUMPY arrays, not jnp: a module-level jnp constant created while
# another function is being traced becomes a tracer and leaks (the r2 bench
# crash); numpy constants are trace-immune and jit folds them identically.
D_L = np.asarray(fe.limbs_from_int(_edwards.D))
D2_L = np.asarray(fe.limbs_from_int(_edwards.D2))
SQRT_M1_L = np.asarray(fe.limbs_from_int(_edwards.SQRT_M1))
BX_L = np.asarray(fe.limbs_from_int(_edwards.BASE[0]))
BY_L = np.asarray(fe.limbs_from_int(_edwards.BASE[1]))
BT_L = np.asarray(fe.limbs_from_int(_edwards.BASE[3]))

SCALAR_BITS = 253  # s, k < L < 2^253


def point_add(p, q):
    """Unified add-2008-hwcd-3 (a=-1): complete for all inputs including
    the identity — mirrors crypto/_edwards.point_add."""
    x1, y1, z1, t1 = p
    x2, y2, z2, t2 = q
    a = fe.mul(fe.sub(y1, x1), fe.sub(y2, x2))
    b = fe.mul(fe.add(y1, x1), fe.add(y2, x2))
    c = fe.mul(fe.mul(t1, D2_L), t2)
    zz = fe.mul(z1, z2)
    d = fe.add(zz, zz)
    e = fe.sub(b, a)
    f = fe.sub(d, c)
    g = fe.add(d, c)
    h = fe.add(b, a)
    return (fe.mul(e, f), fe.mul(g, h), fe.mul(f, g), fe.mul(e, h))


def point_double(p):
    """Dedicated dbl-2008-hwcd (a=-1) — mirrors crypto/_edwards.point_double."""
    x1, y1, z1, _ = p
    a = fe.sq(x1)
    b = fe.sq(y1)
    zz = fe.sq(z1)
    c = fe.add(zz, zz)
    e = fe.sub(fe.sub(fe.sq(fe.add(x1, y1)), a), b)
    g = fe.sub(b, a)  # (-a) + b
    f = fe.sub(g, c)
    h = fe.neg(fe.add(a, b))  # (-a) - b
    return (fe.mul(e, f), fe.mul(g, h), fe.mul(f, g), fe.mul(e, h))


def point_neg(p):
    x, y, z, t = p
    return (fe.neg(x), y, z, fe.neg(t))


def sqrt_ratio(u, v):
    """(ok, r) with v*r^2 == u when ok; p ≡ 5 (mod 8) exponentiation trick
    (RFC 8032 §5.1.3 step 3; crypto/_edwards._sqrt_ratio)."""
    v3 = fe.mul(fe.sq(v), v)
    v7 = fe.mul(fe.sq(v3), v)
    r = fe.mul(fe.mul(u, v3), fe.pow22523(fe.mul(u, v7)))
    check = fe.mul(v, fe.sq(r))
    ok_pos = fe.eq(check, u)
    ok_neg = fe.is_zero(fe.add(check, u))
    r = jnp.where(ok_pos[..., None], r, fe.mul(r, SQRT_M1_L))
    return ok_pos | ok_neg, r


def decompress(y_limbs, sign):
    """ZIP-215 decompression: y already reduced mod-range (low 255 bits of
    the encoding; values >= p are implicitly reduced by the field arithmetic
    — the non-canonical acceptance of crypto/_edwards.decompress)."""
    y = fe.carry(y_limbs)
    yy = fe.sq(y)
    u = fe.sub(yy, fe.ONE)
    v = fe.add(fe.mul(D_L, yy), fe.ONE)
    ok, x = sqrt_ratio(u, v)
    # Conditional negate to match the sign bit; "negative zero" decodes to
    # x = 0 (no step-4 rejection — ZIP-215 / curve25519-dalek behavior).
    x = fe.canon(x)
    flip = (x[..., 0] & 1) != sign
    x = jnp.where(flip[..., None], fe.neg(x), x)
    t = fe.mul(x, y)
    z = jnp.broadcast_to(fe.ONE, y.shape)
    return ok, (x, y, z, t)


def _broadcast_point(coords, shape):
    return tuple(jnp.broadcast_to(c, shape) for c in coords)


def _stack_points(points, axis=0):
    """[(x,y,z,t), ...] -> one point whose coords carry a new stacked axis."""
    return tuple(
        jnp.stack([pt[c] for pt in points], axis=axis) for c in range(4)
    )


def _unstack_point(point, i):
    return tuple(c[i] for c in point)


def _select_point(table, idx):
    """table: point with (..., 16, 20) coords; idx: (...,) in [0,16)."""
    out = []
    for c in table:
        picked = jnp.take_along_axis(c, idx[..., None, None], axis=-2)
        out.append(picked[..., 0, :])
    return tuple(out)


def _bits_to_digits2(bits_t):
    """(253, B) LSB-first bits -> (127, B) base-4 digits (bit 253 = 0)."""
    pad = jnp.zeros((1,) + bits_t.shape[1:], dtype=bits_t.dtype)
    padded = jnp.concatenate([bits_t, pad], axis=0)  # (254, B)
    pairs = padded.reshape(127, 2, *padded.shape[1:])
    return pairs[:, 0] + 2 * pairs[:, 1]


def verify_kernel(a_y, a_sign, r_y, r_sign, s_bits_t, k_bits_t, s_ok):
    """Batched cofactored verification.

    Joint 2-bit-window Straus ladder: 127 iterations of (2 doublings +
    one add from a 16-entry per-element table of s2*B + k2*(-A)) — ~20%
    fewer field multiplies than the 1-bit ladder at the cost of 11 table
    adds per batch element.

    Args (B = batch):
      a_y, r_y:       (B, 20) int32 — low-255-bit limbs of A / R encodings
      a_sign, r_sign: (B,)    int32 — encoding bit 255
      s_bits_t:       (253, B) int32 — bits of s, LSB-first (transposed so
                      the ladder indexes rows dynamically)
      k_bits_t:       (253, B) int32 — bits of k = SHA512(R||A||M) mod L
      s_ok:           (B,)    bool  — host-checked s < L
    Returns: (B,) bool.
    """
    # Decompress A and R in ONE batched call: the dominant subgraph
    # (sqrt_ratio -> pow22523, ~254 squarings) traces/compiles once and the
    # two decompressions run data-parallel on a stacked leading axis.
    ok_ar, AR = decompress(
        jnp.stack([a_y, r_y], axis=0), jnp.stack([a_sign, r_sign], axis=0)
    )
    ok_a, ok_r = ok_ar[0], ok_ar[1]
    A = _unstack_point(AR, 0)
    R = _unstack_point(AR, 1)
    negA = point_neg(A)
    negR = point_neg(R)

    # Derive broadcast constants from the inputs (x + 0*input) so they carry
    # the same varying-manual-axes as the batch under shard_map — a plain
    # jnp.broadcast_to constant would be "replicated" and reject as a
    # fori_loop carry there.
    zero_b = a_y - a_y
    base = (BX_L + zero_b, BY_L + zero_b, fe.ONE + zero_b, BT_L + zero_b)
    ident = (zero_b, fe.ONE + zero_b, fe.ONE + zero_b, zero_b)

    # 16-entry table: idx = s2 + 4*k2 -> [s2]B + [k2](-A). Built with three
    # batched point ops (vs 13 separate traces): one double for {2B, 2(-A)},
    # one add for {3B, 3(-A)}, one 9-lane add for the cross terms.
    pair = _stack_points([base, negA])
    dbl = point_double(pair)
    tri = point_add(dbl, pair)
    b_row = [ident, base, _unstack_point(dbl, 0), _unstack_point(tri, 0)]
    a_col = [ident, negA, _unstack_point(dbl, 1), _unstack_point(tri, 1)]
    cross = point_add(
        _stack_points([b_row[s2] for _ in range(1, 4) for s2 in range(1, 4)]),
        _stack_points([a_col[k2] for k2 in range(1, 4) for _ in range(1, 4)]),
    )
    entries = []
    for k2 in range(4):
        for s2 in range(4):
            if k2 == 0:
                entries.append(b_row[s2])
            elif s2 == 0:
                entries.append(a_col[k2])
            else:
                entries.append(_unstack_point(cross, (k2 - 1) * 3 + (s2 - 1)))
    table = _stack_points(entries, axis=-2)  # coords (..., 16, 20)

    s_digits = _bits_to_digits2(s_bits_t)  # (127, B)
    k_digits = _bits_to_digits2(k_bits_t)

    def body(i, acc):
        j = 126 - i
        s2 = lax.dynamic_index_in_dim(s_digits, j, 0, keepdims=False)
        k2 = lax.dynamic_index_in_dim(k_digits, j, 0, keepdims=False)
        acc = point_double(point_double(acc))
        addend = _select_point(table, s2 + 4 * k2)
        return point_add(acc, addend)

    acc = lax.fori_loop(0, 127, body, ident)
    acc = point_add(acc, negR)
    # Multiply by the cofactor 8 and test against the identity.
    acc = lax.fori_loop(0, 3, lambda _, p: point_double(p), acc)
    is_ident = fe.is_zero(acc[0]) & fe.is_zero(fe.sub(acc[1], acc[2]))
    return ok_a & ok_r & s_ok & is_ident


# -- on-device unpack + epoch-cached variants --------------------------------
#
# The cached kernels take the COMMITTEE as a persistent device table
# (uploaded once per epoch by ops/epoch_cache.py) plus per-signature gather
# indices, and the per-signature scalars/encodings as RAW 32-byte rows —
# limb and bit unpacking are trivial device work, while on the host they
# were the bulk of prepare_batch's wall time (PERF_r06 §3). Steady-state
# batches therefore ship ~101 B/sig instead of ~2.2 kB/sig on this path.


def unpack_limbs_rows(enc):
    """(B, 32) int32 LE bytes -> ((B, 20) int32 low-255-bit limbs, (B,)
    int32 sign). The device twin of backend._pack_le_limbs — same 13-bit
    windows, row-major; static per-limb byte arithmetic, no gathers."""
    sign = enc[:, 31] >> 7
    b31 = enc[:, 31] & 0x7F

    def byte(i):
        return b31 if i == 31 else enc[:, i]

    rows = []
    for i in range(fe.NLIMBS):
        lo_bit = fe.RADIX * i
        byte0 = lo_bit >> 3
        shift = lo_bit & 7
        v = byte(byte0)
        if byte0 + 1 < 32:
            v = v + (byte(byte0 + 1) << 8)
        if byte0 + 2 < 32 and shift + fe.RADIX > 16:
            v = v + (byte(byte0 + 2) << 16)
        rows.append((v >> shift) & fe.MASK)
    return jnp.stack(rows, axis=-1), sign


def bits253_rows(enc):
    """(B, 32) int32 LE scalar bytes (< 2^253) -> (253, B) int32 bits,
    LSB-first, transposed for the ladder — the device twin of
    backend._bits_253."""
    bits = (enc[:, :, None] >> jnp.arange(8, dtype=enc.dtype)) & 1
    return bits.reshape(enc.shape[0], 256).T[:253]


def verify_kernel_cached(
    a_tbl_limbs, a_tbl_sign, val_idx, r_enc, s_enc, k_enc, s_ok
):
    """verify_kernel with the committee gathered from a device-resident
    epoch table and per-sig limb/bit unpack on device.

    a_tbl_limbs (V, 20) int32 / a_tbl_sign (V,) int32: the epoch's pubkey
    rows (row V-1 = identity, the padding lane). val_idx (B,) int32 gather
    indices; r_enc/s_enc/k_enc (B, 32) uint8 raw rows."""
    a_y = a_tbl_limbs[val_idx]
    a_sign = a_tbl_sign[val_idx]
    r_y, r_sign = unpack_limbs_rows(r_enc.astype(jnp.int32))
    s_bits_t = bits253_rows(s_enc.astype(jnp.int32))
    k_bits_t = bits253_rows(k_enc.astype(jnp.int32))
    return verify_kernel(a_y, a_sign, r_y, r_sign, s_bits_t, k_bits_t, s_ok)


def verify_kernel_cached_device_hash(
    a_tbl_limbs, a_tbl_sign, val_idx, r_enc, s_enc,
    blocks_hi, blocks_lo, n_blocks, s_ok
):
    """verify_kernel_device_hash on the epoch-cached committee: k hashes
    on-chip from the shipped R||A||M blocks (per-signature message data),
    A limbs gather from the device table, r/s unpack on device."""
    digest = _sha.sha512_blocks(blocks_hi, blocks_lo, n_blocks)
    k_limbs = sc.mod_l_from_bits(sc.digest_to_le_bits(digest))
    k_bits_t = sc.limbs_to_bits(k_limbs, SCALAR_BITS)
    a_y = a_tbl_limbs[val_idx]
    a_sign = a_tbl_sign[val_idx]
    r_y, r_sign = unpack_limbs_rows(r_enc.astype(jnp.int32))
    s_bits_t = bits253_rows(s_enc.astype(jnp.int32))
    return verify_kernel(a_y, a_sign, r_y, r_sign, s_bits_t, k_bits_t, s_ok)


def verify_kernel_device_hash(
    a_y, a_sign, r_y, r_sign, s_bits_t, blocks_hi, blocks_lo, n_blocks, s_ok
):
    """Fully-device path: the challenge k = SHA512(R||A||M) mod L is
    computed on-chip (ops.sha512 + ops.sc) before the ladder — no host
    hashing in the hot loop (SURVEY.md §7 hard-part #2 resolved on
    device)."""
    digest = _sha.sha512_blocks(blocks_hi, blocks_lo, n_blocks)
    k_limbs = sc.mod_l_from_bits(sc.digest_to_le_bits(digest))
    k_bits_t = sc.limbs_to_bits(k_limbs, SCALAR_BITS)
    return verify_kernel(a_y, a_sign, r_y, r_sign, s_bits_t, k_bits_t, s_ok)


# Donation (ISSUE 7): with donate=True the jitted wrapper donates every
# PER-BATCH input buffer to XLA, so a launch consumes its inputs and their
# pages return to the allocator for the next batch's device_put — the
# "recycled device allocation" steady state the dispatcher's buffer pool
# (ops/device_pool.py) bounds. The epoch-table arguments of the cached
# kernels (argnums 0-1) are persistent device residents shared across
# batches and are NEVER donated — donating them would invalidate the
# cache entry after one launch.


@functools.lru_cache(maxsize=None)
def jitted_verify(donate: bool = False):
    if donate:
        return jax.jit(verify_kernel, donate_argnums=tuple(range(7)))
    return jax.jit(verify_kernel)


@functools.lru_cache(maxsize=None)
def jitted_verify_device_hash(donate: bool = False):
    if donate:
        return jax.jit(verify_kernel_device_hash,
                       donate_argnums=tuple(range(9)))
    return jax.jit(verify_kernel_device_hash)


@functools.lru_cache(maxsize=None)
def jitted_verify_cached(donate: bool = False):
    if donate:
        return jax.jit(verify_kernel_cached,
                       donate_argnums=tuple(range(2, 7)))
    return jax.jit(verify_kernel_cached)


@functools.lru_cache(maxsize=None)
def jitted_verify_cached_device_hash(donate: bool = False):
    if donate:
        return jax.jit(verify_kernel_cached_device_hash,
                       donate_argnums=tuple(range(2, 9)))
    return jax.jit(verify_kernel_cached_device_hash)
