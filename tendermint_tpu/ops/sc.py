"""Scalar arithmetic mod L = 2^252 + 27742317777372353535851937790883648493.

Device-side reduction of the 512-bit SHA-512 challenge digest to
k = digest mod L, producing the ladder's bit array. Uses bitwise Horner
(acc = 2*acc + bit, conditional subtract L) over the same 13-bit limb
machinery as fe.py — 512 cheap vector steps, negligible next to the EC
ladder.
"""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp
from jax import lax

from ..crypto._edwards import L
from . import fe

# numpy, not jnp: trace-immune if this module is first imported under a jit
# trace (the round-2 bench tracer-leak root cause).
L_LIMBS = np.asarray(fe.limbs_raw(L))


def _cond_sub_l(x):
    """x - L if x >= L else x (x < 2L, canonical-ish limbs)."""
    d = x - L_LIMBS
    out = []
    c = jnp.zeros_like(x[..., 0])
    for i in range(fe.NLIMBS):
        t = d[..., i] + c
        c = t >> fe.RADIX
        out.append(t & fe.MASK)
    t = jnp.stack(out, axis=-1)
    keep = (c < 0)[..., None]
    return jnp.where(keep, x, t)


def mod_l_from_bits(bits_t):
    """bits_t: (NBITS, B) int32, MSB-last indexing (bit i = weight 2^i).
    Returns k mod L as (B, 20) canonical limbs."""
    nbits = bits_t.shape[0]
    bsz = bits_t.shape[1]
    acc0 = jnp.zeros((bsz, fe.NLIMBS), dtype=jnp.int32)

    def body_fixed(i, acc):
        bit = lax.dynamic_index_in_dim(bits_t, nbits - 1 - i, 0, keepdims=False)
        doubled = acc + acc
        doubled = jnp.concatenate(
            [(doubled[..., :1] + bit[..., None]), doubled[..., 1:]], axis=-1
        )
        x = fe.carry(doubled)
        return _cond_sub_l(x)

    return lax.fori_loop(0, nbits, body_fixed, acc0)


def limbs_to_bits(limbs, nbits: int):
    """(B, 20) canonical limbs -> (nbits, B) int32 bit array (LSB-first)."""
    shifts = jnp.arange(fe.RADIX, dtype=jnp.int32)
    bits = (limbs[..., :, None] >> shifts) & 1  # (B, 20, 13)
    flat = bits.reshape(bits.shape[:-2] + (fe.NLIMBS * fe.RADIX,))
    return jnp.transpose(flat[..., :nbits])


def digest_to_le_bits(digest):
    """(B, 8, 2) uint32 SHA-512 digest words -> (512, B) int32 bits of the
    little-endian 512-bit integer (RFC 8032 scalar interpretation)."""
    hi = digest[..., 0]  # (B, 8) big-endian word halves
    lo = digest[..., 1]
    # bytes of each 64-bit word, big-endian: hi b0..b3, lo b0..b3
    parts = []
    for half in (hi, lo):
        for shift in (24, 16, 8, 0):
            parts.append(((half >> shift) & 0xFF).astype(jnp.int32))  # (B, 8)
    # parts[p][:, w] = byte (8*w + p); LE integer byte index = 8*w + p
    byte_mat = jnp.stack(parts, axis=-1)  # (B, 8, 8): [b, word, byte-in-word]
    bytes_flat = byte_mat.reshape(byte_mat.shape[0], 64)  # (B, 64) LE order
    shifts = jnp.arange(8, dtype=jnp.int32)
    bits = (bytes_flat[..., None] >> shifts) & 1  # (B, 64, 8) LSB-first
    return jnp.transpose(bits.reshape(bits.shape[0], 512))
