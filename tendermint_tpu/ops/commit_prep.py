"""Fused commit prep — CommitBlock columns to kernel-ready arrays.

PERF_r05 §3: after the EntryBlock representation landed, the remaining
GIL-held host work per 10k-signature verify_commit was the stage BEFORE
the EntryBlock existed — per-signature flag selection and voting-power
tally, per-lane sign-bytes handling, and the entry build — ~26 ms that
serialized concurrent commits. The fix is the round-6 data-structure
change: commits are columnar FROM DECODE (types/block.py fills a
CommitBlock once; CommitSig objects are lazy views), and this module
turns those columns + the validator set's cached pub/power columns into
a dispatch-ready EntryBlock in ONE call:

    selection      flag predicate over the (n,) uint8 flags column
    tally          voting-power sum vs the 2/3 threshold (with the
                   reference's early-stop semantics for the light path)
    sign bytes     canonical vote sign-bytes for every selected lane
                   composed into one contiguous buffer + offset table
    RAM blocks     the same bytes laid straight into the device-hash
                   kernel's padded SHA-512 R||A||M word layout
                   (EntryBlock ram_* columns), so the downstream batch
                   prep skips its scatter entirely
    gather         pub (m, 32) / sig (m, 64) rows fancy-indexed from the
                   cached columns

With the native module built the whole thing is one GIL-released C call
(tm_native.commit_prep_fused); the numpy fallback below is differentially
tested against it and against the object paths. RLC scalar prep stays in
the per-batch fused native call (tm_native.ed25519_rlc_prep): the random
z coefficients are drawn per DEVICE batch, and commits coalesce into
batches after this stage, so per-commit RLC scalars would pin the batch
composition before the coalescer has seen the traffic.
"""

from __future__ import annotations

import os
from typing import Optional, Tuple

import numpy as np

from .entry_block import CommitBlock, EntryBlock

# Messages up to this size hash on-device (single source of truth —
# ops.backend re-exports it). Importable without jax: the types layer
# reads RAM_MAX_LEN at verify time to size the fused prep's RAM columns.
DEVICE_HASH_MAX_MSG = int(os.environ.get("TM_TPU_DEVICE_HASH_MAX_MSG", "192"))
RAM_MAX_LEN = 64 + DEVICE_HASH_MAX_MSG

# BlockIDFlag values (types/block.py) — re-declared to keep this module
# importable without the types layer (which imports us for decode)
FLAG_ABSENT = 1
FLAG_COMMIT = 2
FLAG_NIL = 3

# mode bits shared with the native entry point
MODE_SELECT_COMMIT_ONLY = 1
MODE_COUNT_FOR_BLOCK = 2
MODE_EARLY_STOP = 4

# device-hash RAM layout: R(32) || A(32) || M padded into SHA-512 blocks
# (ops/sha512.pad_ram_block). 17 = 0x80 terminator + 16B length field
# floor of what one extra block must fit.
_RAM_HDR = 64


def ram_nblock(max_len: int) -> int:
    return (max_len + 17 + 127) // 128


def scatter_rows_by_length(buf: np.ndarray, col0: int, flat: np.ndarray,
                           offsets: np.ndarray, lens: np.ndarray) -> None:
    """Copy variable-length records flat[offsets[i]:offsets[i]+lens[i]]
    into buf[i, col0:col0+lens[i]] via grouped 2-D gathers by record
    length (a commit's sign bytes have a handful of distinct lengths) —
    ~2.5x cheaper than a flat row/col scatter at 10k messages. Shared by
    _fill_ram's no-groups fallback and sha512.pad_ram_block."""
    base = offsets[: len(lens)]
    for length in np.unique(lens):
        if length == 0:
            continue
        rows = np.flatnonzero(lens == length)
        src = base[rows][:, None] + np.arange(length)
        buf[rows[:, None], col0 + np.arange(length)[None, :]] = flat[src]


def select_and_tally(
    cblock: CommitBlock,
    power_col: np.ndarray,
    threshold: int,
    mode: int,
) -> Tuple[np.ndarray, int]:
    """Selection + voting-power tally over the flags column. Returns
    (sel_idx (m,) int64, tallied). Semantics mirror validation.go:152's
    loop exactly: early-stop keeps the lane that crosses the threshold,
    count-for-block tallies only COMMIT lanes while still selecting NIL
    lanes for verification."""
    flags = cblock.flags
    if mode & MODE_SELECT_COMMIT_ONLY:
        sel = np.flatnonzero(flags == FLAG_COMMIT).astype(np.int64)
    else:
        sel = np.flatnonzero(flags != FLAG_ABSENT).astype(np.int64)
    if sel.size == 0:
        return sel, 0
    if mode & MODE_EARLY_STOP:
        counted = power_col[sel]
        if mode & MODE_COUNT_FOR_BLOCK:
            counted = counted * (flags[sel] == FLAG_COMMIT)
        cum = np.cumsum(counted)
        k = int(np.searchsorted(cum, threshold, side="right"))
        if k < sel.size:
            return sel[: k + 1], int(cum[k])
        return sel, int(cum[-1])
    if mode & MODE_COUNT_FOR_BLOCK:
        tallied = int(power_col[flags == FLAG_COMMIT].sum())
    else:
        tallied = int(power_col[sel].sum())
    return sel, tallied


def _compose_selected(
    cblock: CommitBlock,
    sel: np.ndarray,
    prefix_commit: bytes,
    prefix_nil: bytes,
    suffix: bytes,
) -> Tuple[memoryview, np.ndarray, list]:
    """Sign bytes for the selected lanes, in selection order, as ONE
    (zero-copy buffer view, (m+1,) int64 offsets) pair, plus the per-group
    (rows, (g, rec_len) 2-D record array) list so _fill_ram can lay the
    same bytes into SHA blocks without re-gathering from the flat
    buffer. Lanes group by flag (at most two groups — COMMIT and NIL —
    per verify_commit selection); a mixed selection composes per group
    and merges by lane order."""
    from ..wire.canonical import compose_vote_sign_bytes_cols

    secs = cblock.ts_seconds[sel]
    nanos = cblock.ts_nanos[sel]
    flags = cblock.flags[sel]
    nil_rows = np.flatnonzero(flags == FLAG_NIL)
    m = sel.size
    if nil_rows.size == 0:
        flag_groups = [(None, prefix_commit, secs, nanos)]
    else:
        commit_rows = np.flatnonzero(flags != FLAG_NIL)
        flag_groups = [
            (commit_rows, prefix_commit, secs[commit_rows],
             nanos[commit_rows]),
            (nil_rows, prefix_nil, secs[nil_rows], nanos[nil_rows]),
        ]
    lens = np.zeros(m, dtype=np.int64)
    composed = []
    for rows, prefix, s, nn in flag_groups:
        buf, offs, rec_groups = compose_vote_sign_bytes_cols(
            (prefix, suffix), s, nn, with_groups=True
        )
        composed.append((rows, buf, offs, rec_groups))
        if rows is None:
            lens = np.diff(offs)
        else:
            lens[rows] = np.diff(offs)
    groups_out = []
    if len(composed) == 1 and composed[0][0] is None:
        _rows, buf, offsets, rec_groups = composed[0]
        groups_out.extend(rec_groups)
        return memoryview(buf), offsets, groups_out
    # merge the two group buffers back into lane order (grouped 2-D
    # copies by record length — a handful of distinct lengths)
    offsets = np.zeros(m + 1, dtype=np.int64)
    np.cumsum(lens, out=offsets[1:])
    out = np.empty(int(offsets[-1]), dtype=np.uint8)
    for rows, _buf, offs, rec_groups in composed:
        for g_rows, arr2d in rec_groups:
            global_rows = g_rows if rows is None else rows[g_rows]
            length = arr2d.shape[1]
            dst = offsets[:-1][global_rows][:, None] + np.arange(length)
            out[dst] = arr2d
            groups_out.append((global_rows, arr2d))
    return memoryview(out), offsets, groups_out


def _fill_ram(
    msgs_buf,
    offsets: np.ndarray,
    pub_rows: np.ndarray,
    sig_rows: np.ndarray,
    max_len: int,
    groups: Optional[list] = None,
) -> Optional[tuple]:
    """Per-row device-hash SHA blocks: R||A||M padded + length-closed,
    word-packed big-endian (ram_hi/ram_lo (m, nblock*16) uint32-valued +
    counts (m,) int32). `groups` are the composer's (rows, 2-D record
    array) pairs — the message bytes land via direct 2-D assignments
    instead of re-gathering from the flat buffer. The hi/lo outputs are
    big-endian VIEWS over the block buffer (no byteswap copy here); the
    single conversion happens when pad_ram_rows copies rows into the
    padded kernel arrays. Returns None when any message exceeds the
    layout — the generic prep then falls back to host hashing."""
    nblock = ram_nblock(max_len)
    m = pub_rows.shape[0]
    lens = np.diff(offsets)
    tot = lens + _RAM_HDR
    if m and int(tot.max()) > max_len:
        return None
    buf = np.zeros((m, nblock * 128), dtype=np.uint8)
    buf[:, :32] = sig_rows[:, :32]
    buf[:, 32:64] = pub_rows
    if groups is not None:
        for rows, arr2d in groups:
            buf[rows[:, None],
                _RAM_HDR + np.arange(arr2d.shape[1])[None, :]] = arr2d
    else:
        flat = np.frombuffer(msgs_buf, dtype=np.uint8)
        scatter_rows_by_length(buf, _RAM_HDR, flat, offsets, lens)
    rng = np.arange(m)
    buf[rng, tot] = 0x80
    blocks = (tot + 17 + 127) // 128
    bitlen = tot * 8
    base = blocks * 128 - 8
    # messages are < 8191 bytes, so only the low two length bytes are
    # ever nonzero — two scatters instead of eight
    buf[rng, base + 6] = (bitlen >> 8) & 0xFF
    buf[rng, base + 7] = bitlen & 0xFF
    # big-endian word split: each 8-byte group -> (hi, lo) uint32 views
    words = buf.view(">u4").reshape(m, nblock * 16, 2)
    return (
        words[:, :, 0],
        words[:, :, 1],
        blocks.astype(np.int32),
    )


def prep_commit_from(
    commit,
    vals,
    chain_id: str,
    threshold: int,
    mode: int,
    ram_max_len: int = RAM_MAX_LEN,
) -> Optional[Tuple[np.ndarray, int, Optional[EntryBlock]]]:
    """The shared fused-path entry for commit-level callers
    (types/validation and ops/pipeline): columnar-eligibility checks
    (CommitBlock present, all-ed25519 validator columns matching the
    commit size) + per-flag template fetch + prep_commit. Returns None
    when this commit/valset is not columnar-representable — callers fall
    back to the object path and its exact legacy errors."""
    cblock = commit.commit_block()
    if cblock is None:
        return None
    cols = vals.ed25519_columns()
    if cols is None or cols[0].shape[0] != cblock.n:
        return None
    tpl_c = commit.sign_bytes_template(chain_id, FLAG_COMMIT)
    tpl_n = commit.sign_bytes_template(chain_id, FLAG_NIL)
    sel, tallied, block = prep_commit(
        cblock,
        cols[0],
        cols[1],
        tpl_c[0],
        tpl_n[0],
        tpl_c[1],
        threshold,
        mode,
        ram_max_len,
    )
    if block is not None:
        # epoch-cache metadata: sel IS the valset row of each lane, and
        # the key is only attached for WARM epochs (ops/epoch_cache.py) —
        # downstream preps then ship gather indices instead of
        # pubkey-derived arrays. A disabled cache returns None and the
        # block is exactly what PR 4 produced.
        from . import epoch_cache as _epoch

        block.val_idx = sel.astype(np.int32)
        block.epoch_key = _epoch.note_valset(vals)
    return sel, tallied, block


def prep_commit(
    cblock: CommitBlock,
    pub_col: np.ndarray,
    power_col: np.ndarray,
    prefix_commit: bytes,
    prefix_nil: bytes,
    suffix: bytes,
    threshold: int,
    mode: int,
    ram_max_len: int = 0,
) -> Tuple[np.ndarray, int, Optional[EntryBlock]]:
    """The fused commit prep: returns (sel_idx, tallied, EntryBlock or
    None). The block is None exactly when tallied <= threshold — the
    caller raises ErrNotEnoughVotingPowerSigned without any sign-bytes
    work having happened, matching the object path's ordering.

    Native path: ONE GIL-released call does all five stages
    (tm_native.commit_prep_fused); numpy fallback below is differentially
    tested (tests/test_commit_block.py)."""
    from ..native import load as _load_native

    native = _load_native()
    if native is not None and hasattr(native, "commit_prep_fused"):
        res = native.commit_prep_fused(
            np.ascontiguousarray(cblock.flags),
            np.ascontiguousarray(cblock.sig),
            np.ascontiguousarray(cblock.ts_seconds),
            np.ascontiguousarray(cblock.ts_nanos),
            np.ascontiguousarray(pub_col),
            np.ascontiguousarray(power_col),
            prefix_commit,
            prefix_nil,
            suffix,
            threshold,
            mode,
            ram_max_len,
        )
        sel = np.frombuffer(res[0], dtype=np.int64)
        tallied = int(res[1])
        if len(res) == 2:
            return sel, tallied, None
        pub_b, sig_b, msgs, offs_b, ram_hi, ram_lo, counts = res[2:]
        m = sel.shape[0]
        ram = ram_hi is not None
        nblock = ram_nblock(ram_max_len) if ram else 0
        block = EntryBlock(
            np.frombuffer(pub_b, dtype=np.uint8).reshape(m, 32),
            np.frombuffer(sig_b, dtype=np.uint8).reshape(m, 64),
            msgs,
            np.frombuffer(offs_b, dtype=np.int64),
            ram_hi=np.frombuffer(ram_hi, dtype=np.uint32).reshape(
                m, nblock * 16
            )
            if ram
            else None,
            ram_lo=np.frombuffer(ram_lo, dtype=np.uint32).reshape(
                m, nblock * 16
            )
            if ram
            else None,
            ram_counts=np.frombuffer(counts, dtype=np.int32)
            if ram
            else None,
        )
        return sel, tallied, block
    return _prep_commit_numpy(
        cblock,
        pub_col,
        power_col,
        prefix_commit,
        prefix_nil,
        suffix,
        threshold,
        mode,
        ram_max_len,
    )


def _prep_commit_numpy(
    cblock: CommitBlock,
    pub_col: np.ndarray,
    power_col: np.ndarray,
    prefix_commit: bytes,
    prefix_nil: bytes,
    suffix: bytes,
    threshold: int,
    mode: int,
    ram_max_len: int,
) -> Tuple[np.ndarray, int, Optional[EntryBlock]]:
    """Vectorized fallback — identical outputs to the native call."""
    sel, tallied = select_and_tally(cblock, power_col, threshold, mode)
    if tallied <= threshold:
        return sel, tallied, None
    msgs_buf, offsets, groups = _compose_selected(
        cblock, sel, prefix_commit, prefix_nil, suffix
    )
    pub_rows = pub_col[sel]
    sig_rows = cblock.sig[sel]
    ram_hi = ram_lo = ram_counts = None
    if ram_max_len:
        ram = _fill_ram(msgs_buf, offsets, pub_rows, sig_rows,
                        ram_max_len, groups=groups)
        if ram is not None:
            ram_hi, ram_lo, ram_counts = ram
    block = EntryBlock(
        pub_rows,
        sig_rows,
        msgs_buf,
        offsets,
        ram_hi=ram_hi,
        ram_lo=ram_lo,
        ram_counts=ram_counts,
    )
    return sel, tallied, block
