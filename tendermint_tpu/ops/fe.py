"""Field arithmetic over GF(2^255 - 19) in 13-bit limbs, for TPU/XLA.

This is the arithmetic core of the device verification engine (SURVEY.md §7
stage 1; the kernel that replaces the reference's curve25519-voi batch
verifier, crypto/ed25519/ed25519.go:192-227).

Design notes — why 13-bit limbs in int32:
- TPU has no native 64-bit integer multiply. A field element is stored as
  20 int32 limbs of 13 bits (limb i holds bits [13*i, 13*i+13)), so a full
  20x20 schoolbook product accumulates at most 20 terms of < 2^26.01 each:
  20 * (2^13 + 8)^2 < 1.35e9 < 2^31 — no overflow, no carry-save needed
  inside the convolution.
- All ops are shape-polymorphic over leading batch dims: an element is an
  int32 array (..., 20). The batch dimension is the data-parallel axis the
  TPU VPU vectorizes over; 39-coefficient limb convolutions are expressed
  as a gather + contraction so XLA sees one fused dot per field-mul.
- Limbs are *signed*: subtraction produces small negative limbs which flow
  through arithmetic-shift carries correctly; values are only made
  canonical (in [0, p)) at comparison points via `canon`.

Invariants:
- "reduced" form (output of carry/add/sub/mul/sq): every limb in
  (-608, 2^13 + 608], |value| < 2^258 (so value + 8p > 0), value correct
  mod p. Safe as input to any op here: 20*(2^13+608)^2 < 2^31 keeps the
  mul convolution overflow-free.
- "canonical" form (output of canon): limbs in [0, 2^13), value in [0, p).
"""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp
from jax import lax

NLIMBS = 20
RADIX = 13
MASK = (1 << RADIX) - 1  # 8191

P = 2**255 - 19
# 2^260 mod p: the carry out of limb 19 wraps with this factor (2^5 * 19).
_TOP_WRAP = 608


def limbs_raw(v: int) -> np.ndarray:
    """Nonnegative int < 2^260 -> 20-limb int32 array, NO mod-p reduction."""
    out = np.zeros(NLIMBS, dtype=np.int32)
    for i in range(NLIMBS):
        out[i] = (v >> (RADIX * i)) & MASK
    return out


def limbs_from_int(v: int) -> np.ndarray:
    """Python int -> canonical (mod-p-reduced) 20-limb int32 array."""
    return limbs_raw(v % P)


def int_from_limbs(a) -> int:
    """Limb array (20,) -> Python int (host helper; no mod-p reduction)."""
    a = np.asarray(a, dtype=object)
    return int(sum(int(a[i]) << (RADIX * i) for i in range(NLIMBS)))


# Module constants stay NUMPY (never jnp): a jnp array materialized at import
# time *during an active trace* (lazy import under jit) leaks as a tracer;
# numpy constants are immune and jit constant-folds them the same way.
ZERO = np.zeros(NLIMBS, dtype=np.int32)
ONE = np.asarray(limbs_from_int(1))
P_LIMBS = np.asarray(limbs_raw(P))  # limbs of p itself (NOT reduced!)

# 8p in radix-13 limbs (fits: 8p < 2^258 < 2^260). Added before
# canonicalization so possibly-negative reduced values become positive.
P8_LIMBS = np.asarray(limbs_raw(8 * P))

# Convolution index/mask matrices: TOEP_IDX[k, i] = k - i (clipped),
# TOEP_MSK[k, i] = 1 iff 0 <= k - i < NLIMBS.
_k = np.arange(2 * NLIMBS - 1)[:, None]
_i = np.arange(NLIMBS)[None, :]
TOEP_IDX = np.clip(_k - _i, 0, NLIMBS - 1).astype(np.int32)
TOEP_MSK = (((_k - _i) >= 0) & ((_k - _i) < NLIMBS)).astype(np.int32)


def _carry_pass(x):
    """One parallel carry pass: every limb sheds its carry to the next limb
    simultaneously (the carry out of limb 19, weight 2^260, wraps to limb 0
    via 2^260 ≡ 608 mod p). One pass shrinks |limb| from < 2^31 to < 2^18.4;
    vectorized over the limb axis — no sequential dependency chain."""
    c = x >> RADIX  # arithmetic shift == floor division (signed-safe)
    r = x & MASK
    wrap = jnp.concatenate(
        [c[..., NLIMBS - 1 :] * _TOP_WRAP, c[..., : NLIMBS - 1]], axis=-1
    )
    return r + wrap


def carry(x):
    """Propagate carries: (..., 20) int32 with |limb| < 2^31 -> reduced form.

    Three parallel passes instead of a 20-step sequential chain: the carry
    magnitude contracts geometrically (2^31 -> 2^18.4 -> 2^15 -> 2^13+608),
    so three passes land every limb in (-608, 2^13 + 608] — "reduced" form
    (see module invariants; 20*(2^13+608)^2 = 1.55e9 < 2^31 keeps the next
    convolution overflow-free). Vectorized form compiles to ~1/5 the HLO of
    the unrolled chain and lets the VPU work the limb axis in parallel.
    """
    return _carry_pass(_carry_pass(_carry_pass(x)))


def add(a, b):
    return carry(a + b)


def sub(a, b):
    return carry(a - b)


def neg(a):
    return carry(-a)


def mul(a, b):
    """Field multiply: 39-coefficient limb convolution + fold + carry."""
    bt = jnp.take(b, TOEP_IDX, axis=-1) * TOEP_MSK  # (..., 39, 20)
    c39 = jnp.einsum(
        "...i,...ki->...k", a, bt, preferred_element_type=jnp.int32
    )
    lo = c39[..., :NLIMBS]
    hi = c39[..., NLIMBS:]  # coefficients k = 20..38
    # Split the high coefficients before scaling by 608 so products stay
    # within int32: hi = hi_hi * 2^13 + hi_lo.
    hi_lo = hi & MASK
    hi_hi = hi >> RADIX
    pad = [(0, 0)] * (c39.ndim - 1)
    # 608 * hi_lo lands at k-20 (positions 0..18); 608 * hi_hi at k-19 (1..19).
    r = (
        lo
        + _TOP_WRAP * jnp.pad(hi_lo, pad + [(0, 1)])
        + _TOP_WRAP * jnp.pad(hi_hi, pad + [(1, 0)])
    )
    return carry(r)


def sq(a):
    return mul(a, a)


def sqn(a, n: int):
    """n successive squarings; uses fori_loop so the trace stays small."""
    if n <= 4:
        for _ in range(n):
            a = sq(a)
        return a
    return lax.fori_loop(0, n, lambda _, v: sq(v), a)


def mul_small(a, c: int):
    """Multiply by a small constant (|c| * 2^13 must fit int32 headroom)."""
    return carry(a * c)


def pow22523(z):
    """z^((p-5)/8) = z^(2^252 - 3) — the sqrt-ratio exponent chain
    (standard ref10 addition chain: ~254 squarings, 12 multiplies)."""
    x2 = sq(z)  # z^2
    x9 = mul(z, sqn(x2, 2))  # z^9
    x11 = mul(x2, x9)  # z^11
    x31 = mul(x9, sq(x11))  # z^(2^5-1)
    xa = mul(sqn(x31, 5), x31)  # 2^10-1
    xb = mul(sqn(xa, 10), xa)  # 2^20-1
    xc = mul(sqn(xb, 20), xb)  # 2^40-1
    xd = mul(sqn(xc, 10), xa)  # 2^50-1
    xe = mul(sqn(xd, 50), xd)  # 2^100-1
    xf = mul(sqn(xe, 100), xe)  # 2^200-1
    xg = mul(sqn(xf, 50), xd)  # 2^250-1
    return mul(sqn(xg, 2), z)  # 2^252-3


def invert(z):
    """z^(p-2) = z^(2^255 - 21) (for compression/utilities; the verify
    kernel itself is inversion-free)."""
    t = pow22523(z)  # z^(2^252-3)
    # z^(p-2) = (z^(2^252-3))^8 * z^3  since 8*(2^252-3) + 3 = 2^255 - 21
    return mul(mul(sqn(t, 3), sq(z)), z)


def _fold255(x):
    """Fold bits >= 2^255 down (2^255 ≡ 19): requires limbs in [0, 2^13)+eps.
    Output: full carry chain re-run; value < 2^255 + small."""
    q = x[..., NLIMBS - 1] >> 8  # bits of weight >= 2^255
    parts = [x[..., i] for i in range(NLIMBS)]
    parts[NLIMBS - 1] = parts[NLIMBS - 1] & 0xFF
    parts[0] = parts[0] + 19 * q
    out = []
    c = jnp.zeros_like(parts[0])
    for i in range(NLIMBS):
        t = parts[i] + c
        c = t >> RADIX
        out.append(t & MASK)
    out[NLIMBS - 1] = out[NLIMBS - 1] + (c << RADIX)  # c is 0 here by bounds
    return jnp.stack(out, axis=-1)


def _cond_sub(x, const_limbs):
    """x - const if x >= const else x (both nonneg canonical-ish limbs)."""
    d = x - const_limbs
    out = []
    c = jnp.zeros_like(x[..., 0])
    for i in range(NLIMBS):
        t = d[..., i] + c
        c = t >> RADIX
        out.append(t & MASK)
    t = jnp.stack(out, axis=-1)
    keep = (c < 0)[..., None]  # borrow out -> x < const
    return jnp.where(keep, x, t)


def canon(x):
    """Fully canonicalize: reduced form -> limbs in [0, 2^13), value in [0, p)."""
    x = carry(x)
    x = carry(x + P8_LIMBS)  # make value strictly positive
    x = _fold255(x)
    x = _fold255(x)  # value now < 2^255 + eps < 2p
    x = _cond_sub(x, P_LIMBS)
    x = _cond_sub(x, P_LIMBS)
    return x


def is_zero(x):
    """(...,) bool: value ≡ 0 (mod p)."""
    return jnp.all(canon(x) == 0, axis=-1)


def eq(a, b):
    return is_zero(a - b)


def parity(x):
    """Canonical low bit (the RFC 8032 sign-of-x bit)."""
    return canon(x)[..., 0] & 1


def to_bytes_words(x):
    """Canonical value -> 8 little-endian uint32 words (..., 8) for output."""
    c = canon(x).astype(jnp.uint32)
    words = []
    for w in range(8):
        acc = jnp.zeros_like(c[..., 0])
        for i in range(NLIMBS):
            lo_bit = RADIX * i
            if lo_bit >= 32 * (w + 1) or lo_bit + RADIX <= 32 * w:
                continue
            sh = lo_bit - 32 * w
            if sh >= 0:
                acc = acc | (c[..., i] << sh)
            else:
                acc = acc | (c[..., i] >> (-sh))
        words.append(acc)
    return jnp.stack(words, axis=-1)
