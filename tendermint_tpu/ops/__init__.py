"""tendermint_tpu.ops — the device (TPU) compute engine.

JAX/XLA kernels replacing the reference's native-performance seams
(SURVEY.md §2: the batch signature-verification engine,
crypto/ed25519/ed25519.go:192-227) with TPU-first designs:

- fe:             GF(2^255-19) limb arithmetic (int32, 13-bit limbs)
- ed25519_verify: batched branchless ZIP-215 verification kernel
- backend:        bucketing host driver + BatchVerifier implementation
- sharded:        multi-chip sharding of verification over a jax Mesh

Importing this package installs the device batch-verifier factory into
crypto.batch (the reference's CreateBatchVerifier seam). The factory is
LAZY: `backend` (and with it jax) only loads on the first
create_batch_verifier call, so the numpy-only columnar modules
(entry_block, commit_prep) are importable from the wire/types layer —
commits decode straight into CommitBlock columns — without dragging the
device stack into every decode.
"""

from __future__ import annotations

from ..crypto import batch as _batch


def _device_verifier_factory():
    from .backend import Ed25519DeviceBatchVerifier

    return Ed25519DeviceBatchVerifier()


_batch.use_device_engine(_device_verifier_factory)

_LAZY = ("Ed25519DeviceBatchVerifier", "verify_batch", "warmup")


def __getattr__(name: str):
    if name in _LAZY:
        from . import backend

        return getattr(backend, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


def __dir__():
    return sorted(list(globals()) + list(_LAZY))
