"""tendermint_tpu.ops — the device (TPU) compute engine.

JAX/XLA kernels replacing the reference's native-performance seams
(SURVEY.md §2: the batch signature-verification engine,
crypto/ed25519/ed25519.go:192-227) with TPU-first designs:

- fe:             GF(2^255-19) limb arithmetic (int32, 13-bit limbs)
- ed25519_verify: batched branchless ZIP-215 verification kernel
- backend:        bucketing host driver + BatchVerifier implementation
- sharded:        multi-chip sharding of verification over a jax Mesh

Importing this package installs the device batch-verifier factory into
crypto.batch (the reference's CreateBatchVerifier seam).
"""

from __future__ import annotations

from .backend import Ed25519DeviceBatchVerifier, verify_batch, warmup  # noqa: F401
from ..crypto import batch as _batch

_batch.use_device_engine(Ed25519DeviceBatchVerifier)
