"""Limb-major field arithmetic over GF(2^255 - 19) for the Pallas kernel.

Same mathematics as ops.fe (13-bit limbs in int32, see the invariants
there), but transposed: a field element is (20, B) with the batch on the
LAST axis so the TPU's (sublane, lane) = (8, 128) vector registers tile the
batch across lanes. Written for Mosaic (Pallas-TPU):

- no gathers: the 39-coefficient limb convolution is one (20, 20, B)
  outer product plus 20 statically shifted row-pads — ~60 primitive ops
  per field-mul, which keeps the traced ladder body small enough for the
  Mosaic compiler while saturating the VPU;
- all shapes static; batch B is a compile-time block size.

Reference parity: the arithmetic mirrors crypto/_edwards (the ZIP-215
oracle); differential tests drive both from the same vectors.
"""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp
from jax import lax

NLIMBS = 20
RADIX = 13
MASK = (1 << RADIX) - 1
P = 2**255 - 19
_TOP_WRAP = 608  # 2^260 mod p = 2^5 * 19


def const_col(v: int):
    """Python int (< 2^260, canonical caller-side) -> (20, 1) jnp column
    built from SCALAR constants at trace time. Pallas kernels cannot
    capture array constants, so every in-kernel field constant goes
    through here (scalars inline into the jaxpr; arrays do not)."""
    rows = [
        jnp.full((1,), (v >> (RADIX * i)) & MASK, dtype=jnp.int32)
        for i in range(NLIMBS)
    ]
    return jnp.stack(rows, axis=0)


def limbs_from_int_t(v: int):
    """Python int -> canonical (20, 1) trace-time column."""
    return const_col(v % P)


def carry_pass(x):
    """One parallel carry pass over the leading limb axis."""
    c = x >> RADIX
    r = x & MASK
    wrap = jnp.concatenate([c[NLIMBS - 1 :] * _TOP_WRAP, c[: NLIMBS - 1]], axis=0)
    return r + wrap


def carry(x):
    return carry_pass(carry_pass(carry_pass(x)))


def add(a, b):
    """ONE carry pass suffices after add/sub of reduced operands: inputs
    have limbs in (-1216, 2^13 + 1216], sums in (-2432, 2^14 + 2432], and
    a single pass contracts back into (-1216, 2^13 + 1216] — closed under
    further add/sub and mul-safe (20 * (2^13 + 1216)^2 < 2^31). Cuts ~2/3
    of the VPU ops the 3-pass carry spent on every point-op add chain."""
    return carry_pass(a + b)


def sub(a, b):
    return carry_pass(a - b)


def neg(a):
    return carry_pass(-a)


def mul(a, b):
    """Field multiply, trace-compact: one (20, 20, B) outer product, then
    each row i lands at offset i via a static pad, summed into the 39
    convolution coefficients."""
    outer = a[:, None, :] * b[None, :, :]  # (20, 20, B)
    c39 = None
    for i in range(NLIMBS):
        row = jnp.pad(outer[i], ((i, NLIMBS - 1 - i), (0, 0)))  # (39, B)
        c39 = row if c39 is None else c39 + row
    return _wrap_fold(c39)


def _wrap_fold(c39):
    """39 convolution coefficients -> carried 20-limb element."""
    lo = c39[:NLIMBS]
    hi = c39[NLIMBS:]  # coefficients 20..38
    hi_lo = hi & MASK
    hi_hi = hi >> RADIX
    r = (
        lo
        + _TOP_WRAP * jnp.pad(hi_lo, ((0, 1), (0, 0)))
        + _TOP_WRAP * jnp.pad(hi_hi, ((1, 0), (0, 0)))
    )
    return carry(r)


def sq(a):
    """Squaring via convolution symmetry: c[k] = a_{k/2}^2 + 2·Σ_{i<j,
    i+j=k} a_i a_j — row i multiplies only limbs j >= i (the j > i terms
    pre-doubled), ~210 limb products vs mul's 400. Same bound analysis as
    mul: |2·a_i·a_j| ≤ 2·9408² and ≤ 20 terms per coefficient keeps every
    c39 entry < 2^31."""
    # plain elementwise double — NO carry: the trick needs u_j = 2*a_j
    # per-limb (a carried 2a has different limbs). |u_j| <= 2*9408 and
    # a_i*u_j <= 1.77e8; each c39 coefficient is the same Σ_{i+j=k} a_i a_j
    # value mul() produces, so the < 2^31 bound is unchanged.
    d = a + a
    c39 = None
    for i in range(NLIMBS):
        # row i: a_i * [a_i, 2a_{i+1}, ..., 2a_19] at offsets 2i..i+19
        # (i = 19 is the bare diagonal — Mosaic rejects 0-size slices)
        head = a[i : i + 1]
        tail = [d[i + 1 :]] if i + 1 < NLIMBS else []
        row = head * jnp.concatenate([head] + tail, axis=0)
        row = jnp.pad(row, ((2 * i, NLIMBS - 1 - i), (0, 0)))  # (39, B)
        c39 = row if c39 is None else c39 + row
    return _wrap_fold(c39)


def sqn(a, n: int):
    if n <= 4:
        for _ in range(n):
            a = sq(a)
        return a
    return lax.fori_loop(0, n, lambda _, v: sq(v), a, unroll=False)


def pow22523(z):
    """z^(2^252 - 3) — ref10 addition chain (see ops.fe.pow22523)."""
    x2 = sq(z)
    x9 = mul(z, sqn(x2, 2))
    x11 = mul(x2, x9)
    x31 = mul(x9, sq(x11))
    xa = mul(sqn(x31, 5), x31)
    xb = mul(sqn(xa, 10), xa)
    xc = mul(sqn(xb, 20), xb)
    xd = mul(sqn(xc, 10), xa)
    xe = mul(sqn(xd, 50), xd)
    xf = mul(sqn(xe, 100), xe)
    xg = mul(sqn(xf, 50), xd)
    return mul(sqn(xg, 2), z)


def _fold255(x):
    """Fold bits >= 2^255 (2^255 ≡ 19); input limbs near-canonical."""
    q = x[NLIMBS - 1] >> 8
    top = x[NLIMBS - 1] & 0xFF
    body = jnp.concatenate([(x[0] + 19 * q)[None], x[1 : NLIMBS - 1], top[None]], axis=0)
    # sequential small-carry chain (bounded: one pass suffices after carry)
    return carry(body)


def _cond_sub(x, const_col):
    """x - const if x >= const (canonical-ish nonneg limbs)."""
    d = x - const_col
    # sequential borrow propagation across 20 limbs (static unroll)
    rows = []
    c = jnp.zeros_like(x[0])
    for i in range(NLIMBS):
        t = d[i] + c
        c = t >> RADIX
        rows.append(t & MASK)
    t = jnp.stack(rows, axis=0)
    keep = (c < 0)[None, :]
    return jnp.where(keep, x, t)


def canon(x):
    p_col = const_col(P)
    x = carry(x)
    x = carry(x + const_col(8 * P))
    x = _fold255(x)
    x = _fold255(x)
    x = _cond_sub(x, p_col)
    x = _cond_sub(x, p_col)
    return x


def is_zero(x):
    """(1, B) bool: value ≡ 0 (mod p). Kept 2D — 1D vectors force Mosaic
    into unsupported gather lowerings at concat/slice sites."""
    return jnp.all(canon(x) == 0, axis=0, keepdims=True)


def eq(a, b):
    return is_zero(a - b)
