"""Single-kernel batched ZIP-215 verification (Pallas / Mosaic, TPU).

The whole verification — point decompression, 16-entry Straus table, the
127-iteration joint double-scalar ladder, cofactor-8 clearing and the
identity test — runs as ONE Pallas kernel per batch block, entirely in
VMEM. Rationale (measured on the target device, round 3): the XLA op-graph
kernel pays an HBM round-trip (and relay dispatch overhead) per fused op,
capping it near ~15k sigs/s; fusing the ladder into one kernel removes
every intermediate HBM touch.

Inputs are the COMPACT wire encodings (batch-minor uint8: 32 B/sig for
each of A, R, S, k ≈ 129 B/sig total vs ~1.6 kB/sig for the unpacked
int32 arrays) — limb and base-4-digit unpacking happens in-kernel, which
matters because host→device transfer on the relay-attached TPU is part of
every commit's critical path.

Semantics are identical to ops.ed25519_verify / crypto._edwards
(per-signature cofactored ZIP-215, crypto/ed25519/ed25519.go:26-31 parity):
  accept iff A, R decompress (non-canonical y allowed), s < L (host-checked
  flag), and [8]([s]B - [k]A) == [8]R — evaluated as a doubles-only
  projective cross-multiplication (complete for small-order inputs) —
  with k = SHA512(R||A||M) mod L computed host-side: the native batch
  helper is ~17 ms/batch, fully hidden behind the 33 ms device pass by
  the async pipeline, and shipping k costs 32 B/sig vs ~256 B/sig for
  on-device hashing (PERF_r04.md).

Table entries are stored in Niels form (Y+X, Y-X, Z, T*2d) and the
ladder carries no T (doubles never read it; see point_double/
point_add_niels need_t).
"""

from __future__ import annotations

import functools
import os

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from . import fe_t
from ..crypto import _edwards

# Curve constants are materialized per-trace from Python ints via
# fe_t.limbs_from_int_t (Pallas kernels cannot capture array constants);
# XLA/Mosaic CSEs the repeated scalar stacks.
def D_T():
    return fe_t.limbs_from_int_t(_edwards.D)


def D2_T():
    return fe_t.limbs_from_int_t(_edwards.D2)


def SQRT_M1_T():
    return fe_t.limbs_from_int_t(_edwards.SQRT_M1)


NL = fe_t.NLIMBS

# Default lanes per kernel block: table (4 coords x 16 x 20 x B x 4B) plus
# digit scratch must fit VMEM (~16 MB) with headroom. Env-tunable for
# block-size sweeps on real hardware; must divide every bucket size or
# grid=(n // block,) would silently leave the tail lanes unverified.
BLOCK = int(os.environ.get("TM_TPU_PALLAS_BLOCK", "512"))
if BLOCK <= 0 or 10240 % BLOCK:
    raise ValueError(
        f"TM_TPU_PALLAS_BLOCK={BLOCK} must be a positive divisor of 10240"
    )


def pick_block(n: int) -> int:
    """Largest kernel block size dividing an n-lane (per-shard) batch —
    the one candidate ladder shared by every sharded/mesh call site, so
    the grid shape for a given per-shard size can never drift between
    paths."""
    for cand in (BLOCK, 256, 128, 64, 32, 16, 8):
        if n % cand == 0:
            return cand
    return n


# -- point ops (limb-major; mirrors ops.ed25519_verify) ---------------------


def point_add(p, q):
    x1, y1, z1, t1 = p
    x2, y2, z2, t2 = q
    a = fe_t.mul(fe_t.sub(y1, x1), fe_t.sub(y2, x2))
    b = fe_t.mul(fe_t.add(y1, x1), fe_t.add(y2, x2))
    c = fe_t.mul(fe_t.mul(t1, D2_T()), t2)
    zz = fe_t.mul(z1, z2)
    d = fe_t.add(zz, zz)
    e = fe_t.sub(b, a)
    f = fe_t.sub(d, c)
    g = fe_t.add(d, c)
    h = fe_t.add(b, a)
    return (fe_t.mul(e, f), fe_t.mul(g, h), fe_t.mul(f, g), fe_t.mul(e, h))


def point_double(p, need_t: bool = True):
    """Doubling never READS t; with need_t=False it also skips producing
    it (the e*h mul) — valid whenever the consumer is another double or a
    select, which covers the first double of every ladder iteration."""
    x1, y1, z1 = p[0], p[1], p[2]
    a = fe_t.sq(x1)
    b = fe_t.sq(y1)
    zz = fe_t.sq(z1)
    c = fe_t.add(zz, zz)
    e = fe_t.sub(fe_t.sub(fe_t.sq(fe_t.add(x1, y1)), a), b)
    g = fe_t.sub(b, a)
    f = fe_t.sub(g, c)
    h = fe_t.neg(fe_t.add(a, b))
    t = fe_t.mul(e, h) if need_t else jnp.zeros_like(x1)
    return (fe_t.mul(e, f), fe_t.mul(g, h), fe_t.mul(f, g), t)


def point_neg(p):
    x, y, z, t = p
    return (fe_t.neg(x), y, z, fe_t.neg(t))


def to_niels(p):
    """Projective (X, Y, Z, T) -> cached/Niels form (Y+X, Y-X, Z, T*2d).
    Table entries are stored this way so the ladder's add costs 8 field
    muls instead of 9 and skips two per-iteration carry passes."""
    x, y, z, t = p
    return (fe_t.add(y, x), fe_t.sub(y, x), z, fe_t.mul(t, D2_T()))


def point_add_niels(p, q, need_t: bool = True):
    """acc (extended projective) + table entry (Niels form). With
    need_t=False the e*h mul is skipped — sound when the consumer chain
    never reads T (doubles and the cross-multiplied equality test)."""
    x1, y1, z1, t1 = p
    yplusx2, yminusx2, z2, t2d2 = q
    a = fe_t.mul(fe_t.sub(y1, x1), yminusx2)
    b = fe_t.mul(fe_t.add(y1, x1), yplusx2)
    c = fe_t.mul(t1, t2d2)
    zz = fe_t.mul(z1, z2)
    d = fe_t.add(zz, zz)
    e = fe_t.sub(b, a)
    f = fe_t.sub(d, c)
    g = fe_t.add(d, c)
    h = fe_t.add(b, a)
    t = fe_t.mul(e, h) if need_t else jnp.zeros_like(x1)
    return (fe_t.mul(e, f), fe_t.mul(g, h), fe_t.mul(f, g), t)


def sqrt_ratio(u, v):
    v3 = fe_t.mul(fe_t.sq(v), v)
    v7 = fe_t.mul(fe_t.sq(v3), v)
    r = fe_t.mul(fe_t.mul(u, v3), fe_t.pow22523(fe_t.mul(u, v7)))
    check = fe_t.mul(v, fe_t.sq(r))
    ok_pos = fe_t.eq(check, u)
    ok_neg = fe_t.is_zero(fe_t.add(check, u))
    r = jnp.where(ok_pos, r, fe_t.mul(r, SQRT_M1_T()))
    return ok_pos | ok_neg, r


def decompress(y_limbs, sign):
    """ZIP-215 decompression; y_limbs (20, B), sign (1, B). All flag
    vectors stay 2D (1, B) — see fe_t.is_zero."""
    one = fe_t.limbs_from_int_t(1)
    y = fe_t.carry(y_limbs)
    yy = fe_t.sq(y)
    u = fe_t.sub(yy, one)
    v = fe_t.add(fe_t.mul(D_T(), yy), one)
    ok, x = sqrt_ratio(u, v)
    x = fe_t.canon(x)
    flip = (x[0:1] & 1) != sign
    x = jnp.where(flip, fe_t.neg(x), x)
    t = fe_t.mul(x, y)
    z = jnp.broadcast_to(one, y.shape)
    return ok, (x, y, z, t)


# -- in-kernel unpacking ----------------------------------------------------


def _unpack_limbs(enc32):
    """(32, B) int32 bytes (LE encoding) -> ((20, B) low-255-bit limbs,
    (B,) sign). Static per-limb byte-window arithmetic — no gathers."""
    b = enc32
    sign = b[31:32] >> 7  # (1, B)
    b31 = b[31] & 0x7F
    rows = []
    for i in range(NL):
        lo_bit = fe_t.RADIX * i
        byte0 = lo_bit >> 3
        shift = lo_bit & 7
        v = b[byte0] if byte0 != 31 else b31
        if byte0 + 1 < 32:
            nxt = b[byte0 + 1] if byte0 + 1 != 31 else b31
            v = v + (nxt << 8)
        if byte0 + 2 < 32 and shift + fe_t.RADIX > 16:
            nxt2 = b[byte0 + 2] if byte0 + 2 != 31 else b31
            v = v + (nxt2 << 16)
        rows.append((v >> shift) & fe_t.MASK)
    return jnp.stack(rows, axis=0), sign


def _unpack_digits2_grouped(enc32):
    """(32, B) int32 scalar bytes (LE, < 2^253) -> (128, B) base-4 digits
    in SHIFT-GROUPED layout: digit t (= bits [2t, 2t+2), both always in
    byte t>>2) is stored at row (t & 3) * 32 + (t >> 2). Grouping by
    in-byte shift keeps the unpack to four (32, B) block writes — the
    interleaved (32, 4, B) -> (128, B) reshape lowers to a 3D gather,
    which Mosaic rejects."""
    b = enc32  # (32, B)
    return jnp.concatenate([(b >> s) & 3 for s in (0, 2, 4, 6)], axis=0)


def _digit_row(t):
    """Row of digit t in the shift-grouped layout (works on traced t)."""
    return (t & 3) * 32 + (t >> 2)


# -- the kernel -------------------------------------------------------------


def _cat(parts):
    return jnp.concatenate(parts, axis=-1)


def _catp(points):
    """Concatenate points along the lane axis."""
    return tuple(_cat([p[c] for p in points]) for c in range(4))


def _slicep(point, i, b):
    return tuple(c[..., i * b : (i + 1) * b] for c in point)


def _k1_decompress_kernel(a_ref, r_ref, s_ref, k_ref, coords_ref, ok_ref, sdig_ref, kdig_ref):
    """K1: byte unpack + joint (lane-folded) decompression of A and R.

    Outputs: coords (160, B) = [Ax Ay Az At Rx Ry Rz Rt] x 20 limb rows,
    ok (2, B), and the base-4 scalar digits for s and k (128, B) each."""
    a_enc = a_ref[:].astype(jnp.int32)
    r_enc = r_ref[:].astype(jnp.int32)
    sdig_ref[:] = _unpack_digits2_grouped(s_ref[:].astype(jnp.int32))
    kdig_ref[:] = _unpack_digits2_grouped(k_ref[:].astype(jnp.int32))

    a_y, a_sign = _unpack_limbs(a_enc)
    r_y, r_sign = _unpack_limbs(r_enc)
    B = a_y.shape[-1]
    ok_ar, AR = decompress(_cat([a_y, r_y]), _cat([a_sign, r_sign]))
    ok_ref[0:1] = ok_ar[:, :B].astype(jnp.int32)
    ok_ref[1:2] = ok_ar[:, B:].astype(jnp.int32)
    # 32-row-aligned coordinate slots: Mosaic aborts on refs sliced at
    # offsets that are not multiples of the 8-row sublane tile, and 20-row
    # slots put 3 of every 4 coords off-tile (measured round 3)
    for c in range(4):
        coords_ref[c * 32 : c * 32 + NL] = AR[c][:, :B]
        coords_ref[(4 + c) * 32 : (4 + c) * 32 + NL] = AR[c][:, B:]


def _k1_decompress_kernel_cached(
    ac_ref, aok_ref, r_ref, s_ref, k_ref, coords_ref, ok_ref, sdig_ref,
    kdig_ref
):
    """K1 for a WARM epoch: the committee's decompressed coordinates
    arrive as an input (gathered on device from the epoch cache's
    persistent table — ops/epoch_cache.py coords_tables), so this variant
    decompresses HALF the points of _k1_decompress_kernel: R only.

    ac: (4*32, B) int32 A coords in the 32-row slot layout; aok (1, B)."""
    r_enc = r_ref[:].astype(jnp.int32)
    sdig_ref[:] = _unpack_digits2_grouped(s_ref[:].astype(jnp.int32))
    kdig_ref[:] = _unpack_digits2_grouped(k_ref[:].astype(jnp.int32))

    r_y, r_sign = _unpack_limbs(r_enc)
    ok_r, R = decompress(r_y, r_sign)
    ok_ref[0:1] = aok_ref[0:1]
    ok_ref[1:2] = ok_r.astype(jnp.int32)
    for c in range(4):
        coords_ref[c * 32 : c * 32 + NL] = ac_ref[c * 32 : c * 32 + NL]
        coords_ref[(4 + c) * 32 : (4 + c) * 32 + NL] = R[c]


def _k2_table_kernel(coords_ref, tbl_ref):
    """K2: 16-entry Straus table [s2]B + [k2](-A) built with three
    lane-folded point ops; entry e coord c lands at rows
    [(e*4 + c)*20, (e*4 + c + 1)*20)."""
    A = tuple(coords_ref[c * 32 : c * 32 + NL] for c in range(4))
    negA = point_neg(A)
    B = A[0].shape[-1]
    zero = jnp.zeros((NL, B), dtype=jnp.int32)
    one = fe_t.limbs_from_int_t(1)
    bx = fe_t.limbs_from_int_t(_edwards.BASE[0])
    by = fe_t.limbs_from_int_t(_edwards.BASE[1])
    bt = fe_t.limbs_from_int_t(_edwards.BASE[3])
    base = (bx + zero, by + zero, one + zero, bt + zero)
    ident = (zero, one + zero, one + zero, zero)
    pair = _catp([base, negA])
    dbl = point_double(pair)
    tri = point_add(dbl, pair)
    b_row = [ident, base, _slicep(dbl, 0, B), _slicep(tri, 0, B)]
    a_col = [ident, negA, _slicep(dbl, 1, B), _slicep(tri, 1, B)]
    cross = point_add(
        _catp([b_row[s2] for k2 in range(1, 4) for s2 in range(1, 4)]),
        _catp([a_col[k2] for k2 in range(1, 4) for s2 in range(1, 4)]),
    )
    entries = []
    for k2 in range(4):
        for s2 in range(4):
            if k2 == 0:
                entries.append(b_row[s2])
            elif s2 == 0:
                entries.append(a_col[k2])
            else:
                entries.append(_slicep(cross, (k2 - 1) * 3 + (s2 - 1), B))
    # store in Niels form (Y+X, Y-X, Z, T*2d): one 8-lane-folded to_niels
    # per half keeps the (20, 20, lanes) mul transient within VMEM
    for half in range(2):
        niels = to_niels(_catp(entries[half * 8 : half * 8 + 8]))
        for j in range(8):
            e = half * 8 + j
            ent = _slicep(niels, j, B)
            for c in range(4):
                tbl_ref[(e * 4 + c) * 32 : (e * 4 + c) * 32 + NL] = ent[c]


def _k3_ladder_kernel(tbl_ref, sdig_ref, kdig_ref, coords_ref, ok_ref, sok_ref, out_ref):
    """K3: the 127-iteration joint ladder. The table is an input ref —
    Mosaic aborts when point-op RESULTS cross into a fori_loop as live
    values (measured round 3), but ref reads inside the body are fine, so
    the 16-way select re-reads table rows each iteration (VMEM-resident)."""
    B = sok_ref.shape[-1]
    zero = jnp.zeros((NL, B), dtype=jnp.int32)
    one = fe_t.limbs_from_int_t(1)
    ident = (zero, one + zero, one + zero, zero)

    def select(idx):
        out = [tbl_ref[c * 32 : c * 32 + NL] for c in range(4)]
        for e in range(1, 16):
            m = (idx == e)[None, :]
            for c in range(4):
                out[c] = jnp.where(
                    m, tbl_ref[(e * 4 + c) * 32 : (e * 4 + c) * 32 + NL], out[c]
                )
        return tuple(out)

    def body(i, acc):
        j = _digit_row(126 - i)
        # inner double & the add skip their T output (never read); only
        # the outer double's T feeds the Niels add's t1*T2d term
        acc = point_double(point_double(acc, need_t=False))
        return point_add_niels(
            acc, select(sdig_ref[j] + 4 * kdig_ref[j]), need_t=False
        )

    acc = lax.fori_loop(0, 127, body, ident)
    # [8]([s]B - [k]A - R) == O  <=>  [8]acc == [8]R, checked by projective
    # cross-multiplication — doubles-only (complete for all inputs, incl.
    # the small-order/mixed ZIP-215 edge points) and T-free end to end.
    R = tuple(coords_ref[(4 + c) * 32 : (4 + c) * 32 + NL] for c in range(4))
    acc8 = acc
    r8 = R
    for _ in range(3):
        acc8 = point_double(acc8, need_t=False)
        r8 = point_double(r8, need_t=False)
    eq_x = fe_t.is_zero(
        fe_t.sub(fe_t.mul(acc8[0], r8[2]), fe_t.mul(r8[0], acc8[2]))
    )
    eq_y = fe_t.is_zero(
        fe_t.sub(fe_t.mul(acc8[1], r8[2]), fe_t.mul(r8[1], acc8[2]))
    )
    valid = (
        (ok_ref[0:1] != 0)
        & (ok_ref[1:2] != 0)
        & (sok_ref[0:1] != 0)
        & eq_x
        & eq_y
    )
    out_ref[:] = valid.astype(jnp.int32)


@functools.lru_cache(maxsize=None)
def _jitted_pallas_verify(n: int, block: int, interpret: bool,
                          vma: frozenset | None = None,
                          donate: bool = False):
    """Three chained pallas_calls (single-kernel fusion SIGABRTs Mosaic;
    see the kernel docstrings). Intermediates live in HBM between kernels
    — ~3 MB/block, negligible next to the in-kernel work. K2's block is
    capped at 256 lanes: its double-buffered (2048, B) table output plus
    the 9B-lane cross-add working set exceeds VMEM at 512.

    vma: varying-mesh-axes annotation for the kernel outputs — required
    when the pipeline runs inside a checked shard_map (ops.sharded), where
    every output must declare which mesh axes it varies over.

    donate: donate the per-batch input buffers to XLA so launches recycle
    their pages (ISSUE 7; see ed25519_verify's donation note)."""
    k2_block = min(block, 256)

    def mkspec(b):
        def spec(rows):
            return pl.BlockSpec((rows, b), lambda i: (0, i), memory_space=pltpu.VMEM)

        return spec

    def out(rows):
        # positional-only when vma is unset: older jax releases (this
        # container's CPU image among them) predate the vma kwarg, and an
        # explicit vma=None still TypeErrors there
        if vma is None:
            return jax.ShapeDtypeStruct((rows, n), jnp.int32)
        return jax.ShapeDtypeStruct((rows, n), jnp.int32, vma=vma)

    spec = mkspec(block)
    spec2 = mkspec(k2_block)

    k1 = pl.pallas_call(
        _k1_decompress_kernel,
        grid=(n // block,),
        in_specs=[spec(32)] * 4,
        out_specs=[spec(8 * 32), spec(2), spec(128), spec(128)],
        out_shape=[out(8 * 32), out(2), out(128), out(128)],
        interpret=interpret,
    )
    k2 = pl.pallas_call(
        _k2_table_kernel,
        grid=(n // k2_block,),
        in_specs=[spec2(8 * 32)],
        out_specs=spec2(16 * 4 * 32),
        out_shape=out(16 * 4 * 32),
        interpret=interpret,
    )
    k3 = pl.pallas_call(
        _k3_ladder_kernel,
        grid=(n // block,),
        in_specs=[spec(16 * 4 * 32), spec(128), spec(128), spec(8 * 32), spec(2), spec(1)],
        out_specs=spec(1),
        out_shape=out(1),
        interpret=interpret,
    )

    def pipeline(a_t, r_t, s_t, k_t, sok_t):
        coords, ok, sdig, kdig = k1(a_t, r_t, s_t, k_t)
        tbl = k2(coords)
        return k3(tbl, sdig, kdig, coords, ok, sok_t)

    if donate:
        return jax.jit(pipeline, donate_argnums=(0, 1, 2, 3, 4))
    return jax.jit(pipeline)


@functools.lru_cache(maxsize=None)
def _jitted_pallas_verify_cached(n: int, block: int, vp: int,
                                 interpret: bool,
                                 vma: frozenset | None = None,
                                 donate: bool = False):
    """The epoch-cached 3-kernel pipeline: the jitted program GATHERS the
    committee's decompressed coordinates from the persistent device table
    ((4*32, vp) int32 + (1, vp) ok) and transposes the raw per-sig rows
    on device — host prep ships row-major bytes only. K2/K3 are shared
    with the uncached pipeline; only K1 changes (R-only decompression)."""
    k2_block = min(block, 256)

    def mkspec(b):
        def spec(rows):
            return pl.BlockSpec((rows, b), lambda i: (0, i), memory_space=pltpu.VMEM)

        return spec

    def out(rows):
        if vma is None:
            return jax.ShapeDtypeStruct((rows, n), jnp.int32)
        return jax.ShapeDtypeStruct((rows, n), jnp.int32, vma=vma)

    spec = mkspec(block)
    spec2 = mkspec(k2_block)

    k1 = pl.pallas_call(
        _k1_decompress_kernel_cached,
        grid=(n // block,),
        in_specs=[spec(4 * 32), spec(1), spec(32), spec(32), spec(32)],
        out_specs=[spec(8 * 32), spec(2), spec(128), spec(128)],
        out_shape=[out(8 * 32), out(2), out(128), out(128)],
        interpret=interpret,
    )
    k2 = pl.pallas_call(
        _k2_table_kernel,
        grid=(n // k2_block,),
        in_specs=[spec2(8 * 32)],
        out_specs=spec2(16 * 4 * 32),
        out_shape=out(16 * 4 * 32),
        interpret=interpret,
    )
    k3 = pl.pallas_call(
        _k3_ladder_kernel,
        grid=(n // block,),
        in_specs=[spec(16 * 4 * 32), spec(128), spec(128), spec(8 * 32), spec(2), spec(1)],
        out_specs=spec(1),
        out_shape=out(1),
        interpret=interpret,
    )

    def pipeline(coords_tbl, ok_tbl, idx, r_rows, s_rows, k_rows, sok_t):
        ac = coords_tbl[:, idx]          # (4*32, n) device gather
        aok = ok_tbl[:, idx]             # (1, n)
        r_t = r_rows.T                   # device-side transposes: trivial
        s_t = s_rows.T                   # on-chip, ~31 ms on host at 10k
        k_t = k_rows.T
        coords, ok, sdig, kdig = k1(ac, aok, r_t, s_t, k_t)
        tbl = k2(coords)
        return k3(tbl, sdig, kdig, coords, ok, sok_t)

    if donate:
        # the persistent coords/ok epoch tables (argnums 0-1) are shared
        # across batches — never donated
        return jax.jit(pipeline, donate_argnums=(2, 3, 4, 5, 6))
    return jax.jit(pipeline)


def prepare_compact_cached(entries, bucket: int, ep):
    """Warm-epoch compact prep: ships val_idx + raw row-major r/s/k (the
    jitted pipeline transposes on device) — no pubkey bytes, no host
    transposes. entries must be an EntryBlock with val_idx set. Same
    argument build as the XLA path (backend.cached_sig_args); only the
    s_ok shaping differs (the kernel wants a (1, N) int32 row)."""
    from .backend import cached_sig_args

    idx, r_rows, s_rows, k_rows, s_ok = cached_sig_args(entries, bucket, ep)
    return (
        idx,
        r_rows,
        s_rows,
        k_rows,
        np.ascontiguousarray(s_ok.astype(np.int32)[None, :]),
    )


def cached_compact_fn(ep, n: int, block: int, interpret: bool,
                      donate: bool = False):
    """Kernel closure for the warm-epoch compact pipeline; the epoch's
    coords tables resolve at CALL time (dispatch-owner thread — the only
    thread allowed to issue the one-time upload)."""
    f = _jitted_pallas_verify_cached(n, block, ep.vp, interpret,
                                     donate=donate)

    def call(*args):
        coords_tbl, ok_tbl = ep.coords_tables()
        return f(coords_tbl, ok_tbl, *args)

    return call


def verify_compact_cached(args, ep, block: int = 0,
                          interpret: bool = False):
    """Run the cached kernel over prepare_compact_cached args; returns
    (N,) bool."""
    block = block or BLOCK
    n = args[1].shape[0]
    if n % block:
        raise ValueError(f"batch {n} not a multiple of block {block}")
    out = cached_compact_fn(ep, n, block, interpret)(*args)
    return np.asarray(out)[0].astype(bool)


def verify_compact(a_t, r_t, s_t, k_t, s_ok_t, block: int = 0, interpret: bool = False):
    """Run the kernel. Args are batch-minor:
    a_t/r_t/s_t/k_t (32, N) uint8, s_ok_t (1, N) int32; N % block == 0.
    block=0 means the module default (BLOCK, read at call time so tests
    can shrink it). Returns (N,) bool.
    """
    block = block or BLOCK
    n = a_t.shape[-1]
    if n % block:
        raise ValueError(f"batch {n} not a multiple of block {block}")
    out = _jitted_pallas_verify(n, block, interpret)(a_t, r_t, s_t, k_t, s_ok_t)
    return np.asarray(out)[0].astype(bool)


def prepare_compact(entries, bucket: int):
    """EntryBlock or (pub32, msg, sig64) triples -> compact batch-minor
    kernel args. Host work: one SHA-512 per sig for k (native batch helper
    when built — a single GIL-released call over the block's contiguous
    msgs buffer — else hashlib), s<L check, two transposes. Padding lanes
    verify trivially (A=R=identity, s=k=0)."""
    from .backend import _challenges_any, _pack_rows, _s_below_l

    n = len(entries)
    pub, r_enc, s_enc = _pack_rows(entries, bucket)  # (bucket, 32) uint8 each
    s_ok = _s_below_l(s_enc, n, bucket)
    k_enc = np.zeros((bucket, 32), dtype=np.uint8)
    if n:
        ks = _challenges_any(r_enc[:n], pub[:n], entries)
        k_enc[:n] = np.frombuffer(ks, dtype=np.uint8).reshape(n, 32)
    return (
        np.ascontiguousarray(pub.T),
        np.ascontiguousarray(r_enc.T),
        np.ascontiguousarray(s_enc.T),
        np.ascontiguousarray(k_enc.T),
        np.ascontiguousarray(s_ok.astype(np.int32)[None, :]),
    )
