"""Async device verification pipeline — overlap host prep with device work.

SURVEY.md §7 hard-part 4 and the reference's pipelined sync shape
(internal/blocksync/pool.go:127 parallel requesters feeding a sequential
verify/apply loop): verification batches are submitted to a single worker
thread that dispatches the jitted kernel asynchronously (JAX dispatch
returns before the device finishes) and only blocks on a result when the
pipeline is `depth` batches deep — so batch N's host prep (sign-bytes
construction, limb packing) runs while batch N-1 executes on device, and
the device never waits on the host between batches.

Consumers:
- blocksync reactor: speculative pre-verification of the next block's
  commit while the current block runs through ABCI apply.
- light client header sync: verify_headers_pipelined — BASELINE config #5
  (pipelined 1k-header verify).
"""

from __future__ import annotations

import queue
import threading
import time
from collections import deque
from concurrent.futures import Future
from typing import List, Optional, Sequence, Tuple

import numpy as np

from ..observability import trace as _trace
from ..types.validation import ErrNotEnoughVotingPowerSigned
from . import backend as _backend
from . import ed25519_verify as _kernel
from .entry_block import EntryBlock, as_block

_span = _trace.span


class _Job:
    __slots__ = ("entries", "future")

    def __init__(self, entries: EntryBlock):
        self.entries = entries
        self.future: Future = Future()


class AsyncBatchVerifier:
    """Double-buffered pipeline over the device engine.

    submit(entries) returns a Future resolving to the (n,) bool validity
    array; entries may be an EntryBlock (handed downstream BY REFERENCE —
    the zero-copy commit path) or a (pub, msg, sig) tuple list (converted
    once at this boundary). One worker thread owns all device dispatches;
    `depth` in-flight batches bound device memory (2 = classic double
    buffering).
    """

    def __init__(self, depth: int = 3):
        self._depth = max(depth, 1)
        self._q: "queue.Queue[_Job]" = queue.Queue()
        self._stopped = threading.Event()
        # wake signal for the worker: set on submit() and on prep-future
        # completion so the worker can sleep instead of polling the job
        # queue at 2 ms while preps are in flight (ADVICE r5)
        self._wake = threading.Event()
        self._thread = threading.Thread(target=self._worker, daemon=True)
        self._thread.start()

    def submit(self, entries) -> Future:
        if self._stopped.is_set():
            raise RuntimeError("verifier is closed")
        job = _Job(as_block(entries))
        self._q.put(job)
        self._wake.set()
        _backend._ops_m().pipeline_queue_depth.set(self._q.qsize())
        return job.future

    def close(self) -> None:
        self._stopped.set()
        self._wake.set()
        self._thread.join(timeout=5)

    # -- worker ----------------------------------------------------------

    @staticmethod
    def _prepare(entries):
        """Host prep only (runs on the prep pool — CPU-heavy, largely
        GIL-releasing: native SHA-512 challenges, numpy packing).

        Returns (kernel_fn, args, rlc_entries, bucket): rlc_entries is
        None for the per-signature kernels; for the RLC fast-accept kernel
        it is the entry list _resolve needs to expand lane verdicts to
        per-sig verdicts (and re-verify rejected lanes for blame). bucket
        is the padded device batch size (signature lanes) for metric
        labels."""
        if _backend._use_pallas():
            import jax

            from . import pallas_verify

            interpret = jax.default_backend() != "tpu"
            if _backend._use_rlc():
                from . import pallas_rlc

                bucket, g, block = pallas_rlc.plan_bucket(len(entries))
                t0 = time.perf_counter()
                with _span("pipeline.prep", n=len(entries), bucket=bucket):
                    args = pallas_rlc.prepare_rlc(entries, bucket)
                _backend._note_device_batch(
                    len(entries), bucket, prep_s=time.perf_counter() - t0
                )
                f = pallas_rlc._jitted_rlc_verify(g, block, interpret)
                return f, args, entries, bucket
            bucket = _backend._pallas_bucket(len(entries))
            t0 = time.perf_counter()
            with _span("pipeline.prep", n=len(entries), bucket=bucket):
                args = pallas_verify.prepare_compact(entries, bucket)
            _backend._note_device_batch(
                len(entries), bucket, prep_s=time.perf_counter() - t0
            )
            f = pallas_verify._jitted_pallas_verify(
                bucket, min(pallas_verify.BLOCK, bucket), interpret
            )
            return f, args, None, bucket
        device_hash = (
            not _backend.HOST_HASH
            and _backend._max_msg_len(entries) <= _backend.DEVICE_HASH_MAX_MSG
        )
        bucket = _backend._bucket_for(len(entries))
        # prep timing histograms are recorded inside prepare_batch*;
        # only the dispatch counters are noted here
        with _span("pipeline.prep", n=len(entries), bucket=bucket):
            if device_hash:
                args = _backend.prepare_batch_device_hash(entries, bucket)
                kern = _kernel.jitted_verify_device_hash()
            else:
                args = _backend.prepare_batch(entries, bucket)
                kern = _kernel.jitted_verify()
        _backend._note_device_batch(len(entries), bucket)
        return kern, args, None, bucket

    def _dispatch(self, entries):
        """Synchronous prep + async device dispatch (kept for callers and
        tests that bypass the worker's prep pool)."""
        f, args, rlc_entries, _bucket = self._prepare(entries)
        return f(*args), rlc_entries

    @staticmethod
    def _resolve(spans, dev, rlc_entries=None, t_dispatch: float = 0.0,
                 bucket: int = 0) -> None:
        try:
            with _span("pipeline.device_wait"):
                arr = np.asarray(dev)
            if t_dispatch:
                # dispatch-to-materialized: the device+transfer time this
                # batch actually cost the pipeline
                _backend._ops_m().device_seconds.observe(
                    time.perf_counter() - t_dispatch,
                    bucket=str(bucket or arr.shape[-1]),
                )
            if arr.ndim == 2:  # pallas output is (1, N) / (1, lanes)
                arr = arr[0].astype(bool)
            if rlc_entries is not None:
                from . import pallas_rlc

                arr = pallas_rlc.expand_lanes(arr, rlc_entries)
        except Exception as e:  # noqa: BLE001
            for job, _, _ in spans:
                job.future.set_exception(e)
            return
        for job, off, n in spans:
            job.future.set_result(arr[off : off + n])

    def _worker(self) -> None:
        """Coalescing pipeline: many small commits (e.g. 128-signature
        headers during header sync) fuse into ONE device batch up to the
        max bucket — per-dispatch latency on the relay-attached TPU is
        tens of ms, so per-commit dispatches would cap throughput at
        ~1/latency regardless of batch size.

        Host prep runs on a small thread pool so batch N+1's packing/
        hashing overlaps batch N's prep AND the device kernel: with the
        RLC kernel at ~23 ms/batch and prep at ~35 ms, a single
        prep-then-dispatch thread was prep-bound at ~39 ms/batch
        (measured 257k sigs/s); overlapped prep restores the kernel-bound
        rate. Device dispatch itself stays on this one worker thread."""
        from concurrent.futures import ThreadPoolExecutor

        prep_pool = ThreadPoolExecutor(3, thread_name_prefix="verify-prep")
        preps: deque = deque()  # (spans, prep_future)
        pending: deque = deque()  # (spans, device_value, rlc_entries)
        hold: Optional[_Job] = None
        max_b = _backend.max_coalesce()
        wake = self._wake
        try:
            while not (
                self._stopped.is_set() and self._q.empty()
                and not preps and not pending and hold is None
            ):
                jobs = []
                total = 0
                job = hold
                hold = None
                if job is None:
                    try:
                        job = self._q.get_nowait()
                    except queue.Empty:
                        job = None
                    # actionable without a new job: a finished head prep
                    # (dispatch), pending beyond depth (forced resolve),
                    # or pending with no preps (the drain-to-idle resolve
                    # branch below, which blocks on the device)
                    actionable = (
                        (preps and preps[0][1].done())
                        or len(pending) > self._depth
                        or (pending and not preps)
                    )
                    if job is None and not actionable:
                        # Nothing actionable: sleep until a submission or
                        # the head prep's done-callback sets the wake
                        # event (no 2 ms busy-poll while preps are in
                        # flight — ADVICE r5). Recheck after clear() so a
                        # set() racing the clear is never lost.
                        wake.clear()
                        if (
                            self._q.empty()
                            and not (preps and preps[0][1].done())
                            and not self._stopped.is_set()
                        ):
                            wake.wait(0.2)
                        try:
                            job = self._q.get_nowait()
                        except queue.Empty:
                            job = None
                if job is not None:
                    jobs.append(job)
                    total = len(job.entries)
                    # coalescing window: while the device pipeline is busy
                    # a short linger costs nothing (the dispatch would
                    # queue anyway) and fuses straggler jobs into bigger
                    # batches — the relay pays a flat ~14 ms per transfer,
                    # so fewer, larger batches are strictly faster
                    deadline = (
                        time.monotonic() + 0.008 if (pending or preps) else 0.0
                    )
                    while total < max_b:
                        try:
                            nxt = self._q.get_nowait()
                        except queue.Empty:
                            wait = deadline - time.monotonic()
                            if wait <= 0:
                                break
                            try:
                                nxt = self._q.get(timeout=wait)
                            except queue.Empty:
                                break
                        if total + len(nxt.entries) > max_b:
                            hold = nxt
                            break
                        jobs.append(nxt)
                        total += len(nxt.entries)
                    # bucket-fit: kernel buckets are quantized, so a total
                    # just past a bucket pays that bucket's FULL padding in
                    # device time and host prep — peel trailing jobs back
                    # while doing so lands the batch in a smaller bucket
                    # with less waste
                    while len(jobs) > 1 and hold is None:
                        b = _backend.quantized_bucket(total)
                        if b - total <= max(b // 8, 1024):
                            break
                        shorter = _backend.quantized_bucket(
                            total - len(jobs[-1].entries)
                        )
                        if shorter >= b:
                            break
                        hold = jobs.pop()
                        total -= len(hold.entries)
                if jobs:
                    _backend._ops_m().pipeline_coalesced_jobs.observe(len(jobs))
                    if total > max_b:
                        # single oversized job: chunked synchronous fallback
                        for j in jobs:
                            try:
                                j.future.set_result(
                                    _backend.verify_batch(j.entries)
                                )
                            except Exception as e:  # noqa: BLE001
                                j.future.set_exception(e)
                    else:
                        spans = []
                        off = 0
                        for j in jobs:
                            spans.append((j, off, len(j.entries)))
                            off += len(j.entries)
                        # columnar coalescing: one concatenate per column
                        # instead of a per-signature list-extend
                        entries = EntryBlock.concat([j.entries for j in jobs])
                        fut = prep_pool.submit(self._prepare, entries)
                        fut.add_done_callback(lambda _f: wake.set())
                        preps.append((spans, fut))
                # dispatch every finished prep in FIFO order; if the device
                # would otherwise go idle (nothing pending), wait for the
                # head prep instead of spinning
                while preps and (
                    preps[0][1].done() or (not pending and not jobs)
                ):
                    spans, fut = preps.popleft()
                    try:
                        f, args, rlc_entries, bucket = fut.result()
                        with _span("pipeline.dispatch", bucket=bucket):
                            dev = f(*args)
                        # start the device->host copy NOW: a blocking fetch
                        # through the relay costs a full ~65ms RTT, but an
                        # async copy rides behind the compute, so the later
                        # np.asarray in _resolve returns in microseconds
                        # (measured: sustained 152k -> 286k sigs/s)
                        try:
                            dev.copy_to_host_async()
                        except AttributeError:
                            pass
                        pending.append(
                            (spans, dev, rlc_entries, time.perf_counter(),
                             bucket)
                        )
                    except Exception as e:  # noqa: BLE001
                        for j, _, _ in spans:
                            j.future.set_exception(e)
                while len(pending) > self._depth:
                    self._resolve(*pending.popleft())
                if not jobs and not preps and pending:
                    self._resolve(*pending.popleft())
                # refresh the backlog gauges every iteration — including
                # the drain-to-idle one, so they read 0 when idle instead
                # of going stale at the last busy value
                m = _backend._ops_m()
                m.pipeline_inflight.set(len(pending))
                m.pipeline_queue_depth.set(self._q.qsize())
        finally:
            prep_pool.shutdown(wait=False)


_shared: Optional[AsyncBatchVerifier] = None
_shared_mtx = threading.Lock()


def shared_verifier() -> AsyncBatchVerifier:
    """Process-wide pipeline instance (device submission is serialized
    through one thread regardless of how many reactors use it)."""
    global _shared
    with _shared_mtx:
        if _shared is None:
            _shared = AsyncBatchVerifier()
        return _shared


# ---------------------------------------------------------------------------
# Commit-level helpers: host-side entry construction mirrors
# types/validation.go:152 verifyCommitBatch, device path per signature.
# ---------------------------------------------------------------------------


def commit_entries(
    chain_id: str, vals, commit, voting_power_needed: int
) -> Tuple[EntryBlock, int]:
    """Build the columnar EntryBlock for a commit's for-block signatures
    (index lookup, early-stop past 2/3 like validation.go:152 with
    countAllSignatures=false). Returns (block, tallied_power). Raises on
    structural problems (bad counts, short power).

    The sign bytes come back as ONE contiguous buffer + offset table
    (Commit.vote_sign_bytes_block) and ride by reference all the way to
    the kernel prep — no per-signature PyBytes or tuples. Callers that
    need tuples can block.to_entries()."""
    idxs = []
    tallied = 0
    for idx, cs in enumerate(commit.signatures):
        if not cs.for_block():
            continue
        idxs.append(idx)
        tallied += vals.validators[idx].voting_power
        if tallied > voting_power_needed:
            break
    if tallied <= voting_power_needed:
        raise ErrNotEnoughVotingPowerSigned(got=tallied, needed=voting_power_needed)
    sigs = commit.signatures
    if any(len(sigs[i].signature) != 64 for i in idxs):
        raise ValueError("invalid signature length")
    buf, offsets = commit.vote_sign_bytes_block(chain_id, idxs)
    n = len(idxs)
    pub_b = b"".join(vals.validators[i].pub_key.bytes() for i in idxs)
    if len(pub_b) != 32 * n:
        # a wrong-size key (e.g. secp256k1 in an ed25519 set) must surface
        # as the error the per-entry path raised, not a reshape failure
        raise TypeError("pubkey is not ed25519")
    pub = np.frombuffer(pub_b, dtype=np.uint8).reshape(n, 32)
    sig = np.frombuffer(
        b"".join(sigs[i].signature for i in idxs), dtype=np.uint8
    ).reshape(n, 64)
    return EntryBlock(pub, sig, buf, offsets), tallied


def verify_commits_pipelined(
    chain_id: str,
    jobs: Sequence[Tuple[object, object, int, object]],
    verifier: Optional[AsyncBatchVerifier] = None,
) -> List[Optional[str]]:
    """jobs: (vals, block_id, height, commit) per header. All host prep
    and device batches flow through the pipeline; returns one entry per
    job — None on success or an error string.

    The per-job semantics match verify_commit_light (types/validation.go
    :59): basic val/commit binding, then +2/3 of `vals` must have signed
    `block_id` at `height` with valid signatures.
    """
    from ..types.validation import _verify_basic_vals_and_commit

    v = verifier or shared_verifier()
    errors: List[Optional[str]] = [None] * len(jobs)

    # The whole job list is known upfront, so entries are packed into
    # FULL max-bucket device batches here instead of relying on the
    # worker's opportunistic coalescing: per-job submission races the
    # worker's queue drain, and on a relay-attached TPU each undersized
    # dispatch pays ~100 ms — measured 3-4x slower for 1k-header syncs.
    # A job's signatures may straddle two batches; verdicts re-aggregate
    # per job below. NOTE this intentionally layers over the worker's own
    # span machinery (_worker packs STREAMED submissions; this packs a
    # KNOWN-size job list) — each full chunk passes through the worker
    # 1:1, so the worker's spans are trivial for this path.
    max_b = _backend.BUCKETS[-1]
    futures: List[Future] = []
    job_spans: List[list] = [[] for _ in jobs]  # (future_idx, off, n)
    cur: list = []  # EntryBlocks (or zero-copy slices of them)
    cur_n = 0
    cur_spans: list = []  # (job_idx, off_in_batch, n)

    def _flush() -> None:
        nonlocal cur, cur_n, cur_spans
        if not cur:
            return
        fi = len(futures)
        futures.append(v.submit(EntryBlock.concat(cur)))
        for job_i, off, n in cur_spans:
            job_spans[job_i].append((fi, off, n))
        cur, cur_n, cur_spans = [], 0, []

    for i, (vals, block_id, height, commit) in enumerate(jobs):
        try:
            _verify_basic_vals_and_commit(vals, commit, height, block_id)
            needed = vals.total_voting_power() * 2 // 3
            entries, _ = commit_entries(chain_id, vals, commit, needed)
        except (ValueError, RuntimeError) as e:
            errors[i] = str(e)
            continue
        pos = 0
        while pos < len(entries):
            take = min(len(entries) - pos, max_b - cur_n)
            cur_spans.append((i, cur_n, take))
            # a job straddling two device batches rides as a zero-copy
            # slice of its block — no per-signature re-packing
            cur.append(entries[pos : pos + take])
            cur_n += take
            pos += take
            if cur_n >= max_b:
                _flush()
    _flush()

    results: List[object] = []
    for fut in futures:
        try:
            results.append(np.asarray(fut.result(timeout=300)))
        except Exception as e:  # noqa: BLE001
            results.append(e)
    for i in range(len(jobs)):
        if errors[i] is not None:
            continue
        pos_in_job = 0
        for fi, off, n in job_spans[i]:
            r = results[fi]
            if isinstance(r, Exception):
                errors[i] = str(r)
                break
            # _resolve already normalized pallas output to a 1-D array
            seg = np.asarray(r[off : off + n]).astype(bool)
            if not seg.all():
                # report the signature index WITHIN this job's entries
                # (validation.go:242-248 blame assignment), not the lane
                # of the packed multi-job device batch
                bad = pos_in_job + int(np.argmin(seg))
                errors[i] = f"wrong signature (entry {bad})"
                break
            pos_in_job += n
    return errors


def verify_headers_pipelined(
    chain_id: str,
    trusted_header,
    headers: Sequence[Tuple[object, object]],
) -> None:
    """Pipelined ADJACENT header-chain verification (BASELINE config #5:
    light/verifier.go VerifyAdjacent's checks over a fetched range, with
    all commit signature batches overlapped on the device).

    headers: ordered [(signed_header, validator_set), ...] starting at
    trusted_header.height + 1, strictly adjacent. Raises ValueError on the
    first failure (host continuity checks first — they are cheap — then
    the pipelined signature verdicts in order)."""
    from ..types.block import BlockID

    prev = trusted_header
    jobs = []
    for sh, vals in headers:
        if sh.header.height != prev.header.height + 1:
            raise ValueError(
                f"headers must be adjacent: {sh.header.height} after {prev.header.height}"
            )
        sh.validate_basic(chain_id)
        if sh.header.validators_hash != vals.hash():
            raise ValueError(
                f"header {sh.header.height} validators_hash does not match supplied set"
            )
        if sh.header.validators_hash != prev.header.next_validators_hash:
            raise ValueError(
                f"header {sh.header.height} validators_hash breaks continuity"
            )
        jobs.append(
            (
                vals,
                BlockID(
                    hash=sh.commit.block_id.hash,
                    part_set_header=sh.commit.block_id.part_set_header,
                ),
                sh.header.height,
                sh.commit,
            )
        )
        prev = sh
    errors = verify_commits_pipelined(chain_id, jobs)
    for (sh, _), err in zip(headers, errors):
        if err is not None:
            raise ValueError(f"header {sh.header.height}: {err}")
