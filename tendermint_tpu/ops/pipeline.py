"""Async device verification pipeline — overlap host prep with device work.

SURVEY.md §7 hard-part 4 and the reference's pipelined sync shape
(internal/blocksync/pool.go:127 parallel requesters feeding a sequential
verify/apply loop): verification batches are submitted to a single worker
thread that dispatches the jitted kernel asynchronously (JAX dispatch
returns before the device finishes) and only blocks on a result when the
pipeline is `depth` batches deep — so batch N's host prep (sign-bytes
construction, limb packing) runs while batch N-1 executes on device, and
the device never waits on the host between batches.

Consumers:
- blocksync reactor: speculative pre-verification of the next block's
  commit while the current block runs through ABCI apply.
- light client header sync: verify_headers_pipelined — BASELINE config #5
  (pipelined 1k-header verify).
"""

from __future__ import annotations

import functools
import heapq
import itertools
import logging
import os
import queue
import threading
import time
from concurrent.futures import Future
from concurrent.futures import TimeoutError as _FutTimeout
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..libs import devcheck as _devcheck
from ..observability import trace as _trace
from ..types.validation import ErrNotEnoughVotingPowerSigned
from . import backend as _backend
from . import device_pool as _dpool
from . import ed25519_verify as _kernel
from . import mesh as _mesh
from .entry_block import EntryBlock, as_block, block_concat

_span = _trace.span

_log = logging.getLogger("tendermint_tpu.ops.pipeline")


@functools.lru_cache(maxsize=1)
def _d2h_async_supported() -> bool:
    """One-time capability probe (ISSUE 7 satellite): do this backend's
    device arrays support copy_to_host_async()? Probed once at engine
    init and logged — the old code wrapped every batch's call in a bare
    `except AttributeError: pass`, so a missing capability silently cost
    a full relay RTT per batch with nothing in the logs."""
    import jax

    try:
        arr = jax.device_put(np.zeros(1, dtype=np.uint8))
        supported = callable(getattr(arr, "copy_to_host_async", None))
    except Exception as e:  # noqa: BLE001 — probe must never kill init
        _log.warning("copy_to_host_async capability probe failed: %r", e)
        return False
    if supported:
        _log.debug("device arrays support copy_to_host_async; verdict "
                   "readback overlaps compute")
    else:
        _log.warning(
            "device arrays lack copy_to_host_async(); verdict readback "
            "will block on materialization (one extra relay RTT per batch)"
        )
    return supported


class _Readback:
    """Structured async verdict readback (ISSUE 7 tentpole piece 4): the
    launched device result plus its D2H copy, started at construction
    when the backend supports it — so the copy rides behind the batches
    still computing. The resolver drains it via wait(); the depth
    semaphore keeps bounding launched-but-unresolved batches exactly as
    before."""

    __slots__ = ("dev",)

    def __init__(self, dev, start_async: bool):
        self.dev = dev
        # a launch closure may hand back a host array (the BLS lane's
        # two-launch protocol reduces residues host-side and returns the
        # verdict-code row as numpy) — nothing left to copy back then
        if start_async and hasattr(dev, "copy_to_host_async"):
            dev.copy_to_host_async()

    def wait(self) -> np.ndarray:
        # _resolve applies the owndata guard (copies before delivery);
        # wait() itself hands back the raw materialization
        return np.asarray(self.dev)  # tmlint: disable=donation-aliasing — consumer copies


_alias_scratch: dict = {}


def _alias_view(arr: np.ndarray) -> np.ndarray:
    """TM_TPU_INJECT_LINTBUG=alias (test seam, ISSUE 8): re-introduce the
    PR-7 readback-aliasing bug DETERMINISTICALLY on any backend — the
    verdict is delivered as a view of one per-shape scratch buffer that
    the next batch's resolve overwrites, exactly the recycled-donated-
    page mechanics devcheck's write-after-resolve canary must catch."""
    key = (arr.shape, str(arr.dtype))
    buf = _alias_scratch.get(key)
    if buf is None:
        buf = _alias_scratch[key] = np.empty_like(arr)
    np.copyto(buf, arr)
    return buf[:]  # non-owning view of the shared scratch


# QoS priority classes (ISSUE 13/14): the dispatcher is multi-tenant —
# consensus commit batches share it with blocksync replay ranges and
# mempool CheckTx superbatches. Lower value = more urgent. A pending
# CONSENSUS batch overtakes every queued REPLAY range and INGRESS
# superbatch (never an in-flight launch), so neither a rejoining node's
# catch-up flood nor a tx flood can push commit verification to the back
# of the line. REPLAY sits above INGRESS: catch-up is a node-liveness
# workload, user-tx ingress is best-effort.
PRIORITY_CONSENSUS = 0
PRIORITY_REPLAY = 1
PRIORITY_INGRESS = 2

# lane label per priority class — the queue_wait_seconds histogram and
# lane_counts() speak the same vocabulary (ISSUE 16)
_LANE_NAMES = {
    PRIORITY_CONSENSUS: "consensus",
    PRIORITY_REPLAY: "replay",
    PRIORITY_INGRESS: "ingress",
}


class _PriorityQueue:
    """Priority-ordered hand-off queue (ISSUE 13): items pop in
    (priority, arrival) order — arrival sequence preserves FIFO within a
    class, so this degrades to the old plain Queue when every producer
    uses one priority. Reordering happens strictly while an item is
    QUEUED: once the consumer picks a batch up (an in-flight transfer or
    launch) it is never revoked. The None close sentinel is delivered
    only after the heap drains, preserving the plain-Queue shutdown
    contract. `on_bypass(n)` — called outside the internal lock — reports
    how many queued lower-priority items a new arrival overtook: the
    preemption-visibility hook feeding `checktx_preemptions`."""

    def __init__(self, on_bypass=None):
        self._heap: list = []
        self._ctr = itertools.count()
        self._cv = threading.Condition(threading.Lock())
        self._closed = False
        self.on_bypass = on_bypass

    def put(self, item, priority: int = 0) -> None:
        if item is None:
            with self._cv:
                self._closed = True
                self._cv.notify_all()
            return
        with self._cv:
            bypassed = sum(1 for p, _, _ in self._heap if p > priority)
            heapq.heappush(self._heap, (priority, next(self._ctr), item))
            self._cv.notify()
        if bypassed and self.on_bypass is not None:
            try:
                self.on_bypass(bypassed)
            except Exception:  # noqa: BLE001 — observability never fatal
                pass

    def get(self, timeout: Optional[float] = None):
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cv:
            while not self._heap:
                if self._closed:
                    return None
                if deadline is None:
                    self._cv.wait()
                    continue
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise queue.Empty
                self._cv.wait(remaining)
            return heapq.heappop(self._heap)[2]

    def get_nowait(self):
        with self._cv:
            if not self._heap:
                raise queue.Empty
            return heapq.heappop(self._heap)[2]

    def empty(self) -> bool:
        with self._cv:
            return not self._heap

    def qsize(self) -> int:
        with self._cv:
            return len(self._heap)

    def best_priority(self) -> Optional[int]:
        """Priority of the most-urgent queued item (None when empty) —
        the dispatcher's preemption probe while parked on the depth
        semaphore with a lower-urgency batch in hand."""
        with self._cv:
            return self._heap[0][0] if self._heap else None


class DispatchError(RuntimeError):
    """A batch failed on the dispatch-owner thread (host prep, epoch-table
    upload, or kernel launch). Carries the epoch/bucket context of the
    failing batch (bucket 0 when the failure precedes bucket planning) so
    a caller holding many futures can attribute the failure; the original
    exception rides as __cause__. The dispatcher itself survives — only
    the poisoned batch's futures fail."""

    def __init__(self, msg: str, *, bucket: int = 0,
                 epoch_key: Optional[bytes] = None):
        ek = epoch_key.hex()[:16] if epoch_key else None
        super().__init__(
            f"{msg} (bucket={bucket}, epoch={ek or 'uncached'})"
        )
        self.bucket = bucket
        self.epoch_key = epoch_key


class _Job:
    __slots__ = ("entries", "future", "flow", "flow_owned",
                 "priority", "seq")

    def __init__(self, entries: EntryBlock,
                 priority: int = PRIORITY_CONSENSUS, seq: int = 0):
        self.entries = entries
        self.future: Future = Future()
        # flow correlation id (ISSUE 10): allocated at submit() when the
        # tracer is live, threaded through the coalesced batch so the
        # dispatch/verdict instants chain back to the submitting caller.
        # flow_owned=False (ISSUE 11) marks a CONTINUED caller flow (the
        # light service's RPC-arrival → verdict chain): the verdict
        # instant then steps ("t") instead of finishing ("f") so the
        # caller owns the chain's terminal event.
        self.flow: Optional[int] = None
        self.flow_owned = True
        # QoS class + submission sequence (ISSUE 13): seq keeps ordering
        # FIFO within a class and lets the mesh packer count how many
        # earlier-arrived INGRESS jobs a CONSENSUS job overtook
        self.priority = priority
        self.seq = seq


class AsyncBatchVerifier:
    """Coalescing pipeline over the device engine with a SINGLE
    dispatch-owner thread.

    submit(entries) returns a Future resolving to the (n,) bool validity
    array; entries may be an EntryBlock (handed downstream BY REFERENCE —
    the zero-copy commit path) or a (pub, msg, sig) tuple list (converted
    once at this boundary).

    Thread layout (PERF_r05 §2: the relay is one serial command channel —
    transfers neither overlap execution nor tolerate concurrency, so
    exactly ONE thread may touch it, and it must never block on anything
    but the relay itself):

      coalescer   drains submit()s, fuses jobs into device batches,
                  farms host prep out to a small pool
      dispatcher  the ONLY thread that launches kernels / issues device
                  transfers; pulls prepared args FIFO off a queue, so
                  callers and prep threads never convoy on the relay
      resolver    blocks on device results (np.asarray) and completes
                  futures — device waits never delay the next launch

    `depth` bounds launched-but-unresolved batches (device memory;
    2 = classic double buffering) via a semaphore between dispatcher and
    resolver. `pool_depth` (default depth + 1, env TM_TPU_POOL_DEPTH)
    bounds transferred-but-unresolved input-buffer sets per compiled
    layout (ops/device_pool.py) — one deeper than the launch bound so
    batch k+1's H2D copy can issue while the pipeline is full.

    `mesh_lanes` >= 1 (default: TM_TPU_MESH, see ops/mesh.py) switches
    the coalescer into MESH-DISPATCHER mode (ISSUE 9): queued jobs are
    bin-packed into per-shard lanes of one (lanes x lane_bucket)
    superbatch per launch — same-epoch jobs share a lane, short lanes
    pad with identity rows, verdicts demux per job on readback. The
    dispatcher/resolver stages are UNCHANGED: a superbatch transfers,
    launches (sharded over the mesh when jax.shard_map + devices allow,
    simulated lanes otherwise) and reads back through the same
    single-owner overlap machinery as a single-device batch."""

    def __init__(self, depth: int = 3, pool_depth: Optional[int] = None,
                 mesh_lanes: Optional[int] = None):
        self._depth = max(depth, 1)
        self._mesh_lanes = (
            _mesh.lanes_from_env() if mesh_lanes is None
            else max(int(mesh_lanes), 0)
        )
        if pool_depth is None:
            pool_depth = int(
                os.environ.get("TM_TPU_POOL_DEPTH", self._depth + 1)
            )
        self._pool = _dpool.DeviceBufferPool(pool_depth)
        self._d2h_async = _d2h_async_supported()
        # job intake is priority-ordered too (ISSUE 13): a commit
        # submitted behind a backlog of queued ingress windows reaches
        # the coalescer first instead of waiting out the whole backlog
        self._q = _PriorityQueue()
        # QoS preemption visibility (ISSUE 13): total lower-priority
        # batches bypassed while queued, plus caller hooks (the mempool
        # ingress accumulator feeds MempoolMetrics.checktx_preemptions)
        self.preempted_total = 0
        self._preempt_mtx = threading.Lock()
        self._preempt_hooks: List = []
        # per-lane intake accounting (ISSUE 15): the CONSENSUS class is
        # now multi-producer — commit batches AND live-vote ingress
        # windows share it — so lane counters are the only way /status
        # can show votes actually cross-coalescing through the QoS lanes
        self._lane_mtx = threading.Lock()
        self._lane_submitted = {
            PRIORITY_CONSENSUS: 0, PRIORITY_REPLAY: 0, PRIORITY_INGRESS: 0,
        }
        # declared-origin attribution (ISSUE 18): fleet-server submits
        # carry each remote client's lane name; same mutex as lane counts
        self._origin_submitted: Dict[str, int] = {}
        # (spans, prep_future, t_enqueue, priority) | None sentinel —
        # priority-ordered so a pending consensus batch overtakes queued
        # ingress superbatches (never an in-flight launch)
        self._dispatch_q = _PriorityQueue(on_bypass=self._note_preempt)
        # resolve order is priority-ordered too: with batches of both
        # classes in flight, the consensus verdict materializes first
        # instead of queuing behind ingress readbacks
        self._resolve_q = _PriorityQueue()
        self._job_seq = itertools.count()
        self._stopped = threading.Event()
        self._sem = threading.Semaphore(self._depth)
        # QoS reserved lane (ISSUE 13): INGRESS batches may occupy at
        # most depth-1 of the launch slots, so a consensus commit never
        # queues behind a device pipeline filled wall-to-wall with tx
        # superbatches — its depth wait is ~0 instead of a full readback.
        # Degenerate depth=1 disables the reservation (guarded at use).
        self._ing_sem = threading.Semaphore(max(self._depth - 1, 1))
        self._mtx = _devcheck.lock("pipeline.inflight")
        self._inflight = 0
        # thread idents that ever launched a kernel — asserted single-
        # element by tests/test_commit_block.py::TestDispatchOwnerThread
        # (the relay-ownership invariant)
        self.dispatch_thread_idents: set = set()
        self._thread = threading.Thread(
            target=self._worker_mesh if self._mesh_lanes else self._worker,
            daemon=True, name="verify-coalesce",
        )
        self._dispatch_thread = threading.Thread(
            target=self._dispatcher, daemon=True, name="verify-dispatch"
        )
        self._resolve_thread = threading.Thread(
            target=self._resolver, daemon=True, name="verify-resolve"
        )
        self._thread.start()
        self._dispatch_thread.start()
        self._resolve_thread.start()

    def add_preempt_hook(self, fn) -> None:
        """Register fn(n_bypassed) — called whenever queued lower-priority
        batches are overtaken by a higher-priority arrival (dispatch-queue
        bypass or mesh-pack reorder)."""
        self._preempt_hooks.append(fn)

    def _note_preempt(self, n: int) -> None:
        with self._preempt_mtx:
            self.preempted_total += n
        for fn in list(self._preempt_hooks):
            try:
                fn(n)
            except Exception:  # noqa: BLE001 — observability never fatal
                pass

    def submit(self, entries, flow: Optional[int] = None,
               priority: int = PRIORITY_CONSENSUS,
               origin: Optional[str] = None) -> Future:
        """`origin` names WHO submitted (ISSUE 18: the fleet server
        passes each client's wire-declared lane) — pure attribution for
        origin_counts(); scheduling ignores it."""
        if self._stopped.is_set():
            raise RuntimeError("verifier is closed")
        block = as_block(entries)
        max_b = _backend.max_coalesce()
        if self._mesh_lanes:
            # mesh mode packs WHOLE jobs into lanes — chunk oversized
            # submissions at the lane capacity so every chunk fits one
            max_b = min(max_b, _mesh.lane_cap())
        if len(block) > max_b:
            return self._submit_chunked(block, max_b, flow, priority,
                                        origin=origin)
        job = _Job(block, priority=int(priority),
                   seq=next(self._job_seq))
        if _trace.TRACER.enabled:
            if flow is not None:
                # continue the CALLER's flow (ISSUE 11: the light
                # service chains RPC arrival → epoch-group → mesh_pack →
                # verdict through the pipeline); the caller emits the
                # finish, so this submit and the verdict both step
                job.flow = int(flow)
                job.flow_owned = False
                _trace.TRACER.flow_point(
                    "pipeline.submit", job.flow, "t", n=len(block)
                )
            else:
                job.flow = _trace.next_flow()
                _trace.TRACER.flow_point(
                    "pipeline.submit", job.flow, "s", n=len(block)
                )
        with self._lane_mtx:
            self._lane_submitted[
                min(job.priority, PRIORITY_INGRESS)
            ] = self._lane_submitted.get(
                min(job.priority, PRIORITY_INGRESS), 0
            ) + 1
            if origin is not None:
                self._origin_submitted[origin] = (
                    self._origin_submitted.get(origin, 0) + 1
                )
        self._q.put(job, priority=job.priority)
        _backend._ops_m().pipeline_queue_depth.set(self._q.qsize())
        return job.future

    def lane_counts(self) -> dict:
        """Jobs accepted per QoS class since start — keys 'consensus'
        (commit batches + live-vote windows), 'replay', 'ingress'."""
        with self._lane_mtx:
            return {
                "consensus": self._lane_submitted[PRIORITY_CONSENSUS],
                "replay": self._lane_submitted[PRIORITY_REPLAY],
                "ingress": self._lane_submitted[PRIORITY_INGRESS],
            }

    def origin_counts(self) -> dict:
        """Jobs accepted per declared origin (ISSUE 18: fleet clients'
        lane names). Empty until someone submits with origin=."""
        with self._lane_mtx:
            return dict(self._origin_submitted)

    def _submit_chunked(self, block: EntryBlock, max_b: int,
                        flow: Optional[int] = None,
                        priority: int = PRIORITY_CONSENSUS,
                        origin: Optional[str] = None) -> Future:
        """An oversized job rides as zero-copy slices through the normal
        queue (the dispatcher stays the only device-touching thread; the
        old path ran a chunked synchronous fallback on the worker) and
        re-aggregates into one future."""
        futs: List[Future] = []
        i = 0
        while i < len(block):
            futs.append(
                self.submit(block[i : i + max_b], flow=flow,
                            priority=priority, origin=origin)
            )
            i += max_b
        agg: Future = Future()
        done_lock = threading.Lock()

        def _combine(_f) -> None:
            with done_lock:
                if agg.done() or not all(f.done() for f in futs):
                    return
                try:
                    parts = [np.asarray(f.result()) for f in futs]
                except Exception as e:  # noqa: BLE001
                    agg.set_exception(e)
                    return
                agg.set_result(np.concatenate(parts))

        for f in futs:
            f.add_done_callback(_combine)
        return agg

    def close(self) -> None:
        self._stopped.set()
        self._thread.join(timeout=5)
        self._dispatch_thread.join(timeout=5)
        self._resolve_thread.join(timeout=5)
        # retire this verifier's relay claim (no-op set op when devcheck
        # never armed) — stale idents would outlaw later direct use and
        # can be recycled by the OS onto unrelated threads
        _devcheck.unclaim_relay(self.dispatch_thread_idents)
        if _devcheck.enabled():
            _devcheck.canary_sweep("pipeline.close")
            # scoped to EXITED threads: the pipeline's own joined threads
            # can only have leaks left, while an unrelated live thread
            # (consensus mid-verify_dispatch, or a dispatch thread that
            # outlived join's timeout on a stalled device call) is
            # legitimately mid-span and must not false-positive
            _devcheck.span_check("pipeline.close", only_exited=True)

    # -- worker ----------------------------------------------------------

    @staticmethod
    def _prepare(entries):
        """Host prep only (runs on the prep pool — CPU-heavy, largely
        GIL-releasing: native SHA-512 challenges, numpy packing).

        Returns (kernel_fn, args, rlc_entries, bucket): rlc_entries is
        None for the per-signature kernels; for the RLC fast-accept kernel
        it is the entry list _resolve needs to expand lane verdicts to
        per-sig verdicts (and re-verify rejected lanes for blame). bucket
        is the padded device batch size (signature lanes) for metric
        labels."""
        from . import epoch_cache as _epoch

        # warm-epoch fast path: the committee is device-resident (keyed
        # by ValidatorSet.hash()) — prep ships only per-signature data
        # and the kernels gather cached A columns on device
        ep = _epoch.lookup(entries)
        # donation (ISSUE 7): launches consume their per-batch inputs so
        # XLA recycles the pages; epoch tables stay exempt in every
        # kernel's donate_argnums
        donate = _backend.donate_enabled()
        if getattr(entries, "scheme", "ed25519") == "bls12381":
            # aggregation lane (ISSUE 20): one row = one whole commit.
            # `ep` above is None by construction (AggBlocks carry no
            # gather indices); the lane keys its epoch on the bitmap's
            # committee directly.
            ep = _backend._bls_epoch(entries)
            bucket = _backend._bls_bucket_for(len(entries))
            vp = ep.vp if ep is not None else entries.pub48.shape[0] + 1
            with _span("pipeline.prep", n=len(entries), bucket=bucket,
                       cached=int(ep is not None), scheme="bls12381"):
                masks, coeffs, ok, reasons = _backend.prepare_batch_bls(
                    entries, bucket, vp,
                    bad_rows=_backend._bls_bad_rows(entries.pub48),
                )
                kern = _backend.bls_kernel(
                    entries, ok, reasons, ep=ep, donate=donate
                )
            _backend._note_device_batch(len(entries), bucket)
            return kern, (masks, coeffs), None, bucket
        if getattr(entries, "scheme", "ed25519") == "secp256k1":
            # scheme lane (ISSUE 19): the Strauss+GLV ECDSA kernel.
            # Plain XLA jit only — no pallas/RLC face for secp yet
            # (ROADMAP 3a); `ep` is already scheme-guarded by
            # epoch_cache.lookup so a warm secp committee gathers its
            # decompressed affine Q columns on device.
            bucket = _backend._secp_bucket_for(len(entries))
            with _span("pipeline.prep", n=len(entries), bucket=bucket,
                       cached=int(ep is not None), scheme="secp256k1"):
                if ep is not None:
                    args = _backend.prepare_batch_secp_cached(
                        entries, bucket, ep
                    )
                    kern = _backend.secp_cached_kernel(ep, donate)
                else:
                    args = _backend.prepare_batch_secp(entries, bucket)
                    kern = _backend.secp_kernel(donate)
            _backend._note_device_batch(len(entries), bucket)
            return kern, args, None, bucket
        if _backend._use_pallas():
            import jax

            from . import pallas_verify

            interpret = jax.default_backend() != "tpu"
            if _backend._use_rlc():
                from . import pallas_rlc

                bucket, g, block = pallas_rlc.plan_bucket(len(entries))
                t0 = time.perf_counter()
                with _span("pipeline.prep", n=len(entries), bucket=bucket,
                           cached=int(ep is not None)):
                    if ep is not None:
                        args = pallas_rlc.prepare_rlc_cached(
                            entries, bucket, ep
                        )
                        f = pallas_rlc.rlc_cached_fn(
                            ep, g, block, interpret, donate
                        )
                    else:
                        args = pallas_rlc.prepare_rlc(entries, bucket)
                        f = pallas_rlc._jitted_rlc_verify(
                            g, block, interpret, donate=donate
                        )
                _backend._note_device_batch(
                    len(entries), bucket, prep_s=time.perf_counter() - t0
                )
                return f, args, entries, bucket
            bucket = _backend._pallas_bucket(len(entries))
            blk = min(pallas_verify.BLOCK, bucket)
            t0 = time.perf_counter()
            with _span("pipeline.prep", n=len(entries), bucket=bucket,
                       cached=int(ep is not None)):
                if ep is not None:
                    args = pallas_verify.prepare_compact_cached(
                        entries, bucket, ep
                    )
                    f = pallas_verify.cached_compact_fn(
                        ep, bucket, blk, interpret, donate
                    )
                else:
                    args = pallas_verify.prepare_compact(entries, bucket)
                    f = pallas_verify._jitted_pallas_verify(
                        bucket, blk, interpret, donate=donate
                    )
            _backend._note_device_batch(
                len(entries), bucket, prep_s=time.perf_counter() - t0
            )
            return f, args, None, bucket
        device_hash = (
            not _backend.HOST_HASH
            and _backend._max_msg_len(entries) <= _backend.DEVICE_HASH_MAX_MSG
        )
        bucket = _backend._bucket_for(len(entries))
        # prep timing histograms are recorded inside prepare_batch*;
        # only the dispatch counters are noted here
        with _span("pipeline.prep", n=len(entries), bucket=bucket,
                   cached=int(ep is not None)):
            if ep is not None:
                kern = _backend.cached_kernel(ep, device_hash, donate)
                if device_hash:
                    args = _backend.prepare_batch_cached_device_hash(
                        entries, bucket, ep
                    )
                else:
                    args = _backend.prepare_batch_cached(entries, bucket, ep)
            elif device_hash:
                args = _backend.prepare_batch_device_hash(entries, bucket)
                kern = _kernel.jitted_verify_device_hash(donate)
            else:
                args = _backend.prepare_batch(entries, bucket)
                kern = _kernel.jitted_verify(donate)
        _backend._note_device_batch(len(entries), bucket)
        return kern, args, None, bucket

    @classmethod
    def _prepare_timed(cls, entries):
        """_prepare plus its own completion timestamp — returned IN the
        future's value so the dispatcher's queue-wait measurement cannot
        race the done-callback machinery."""
        return cls._prepare(entries), time.perf_counter()

    @staticmethod
    def _prepare_mesh(block, plan):
        """Host prep for a mesh superbatch (ISSUE 9): delegate to
        ops/mesh.prepare_superbatch — same return contract as _prepare
        plus the per-arg transfer shardings (None on simulated lanes).
        Pad accounting uses the plan's LIVE count so pad_waste metrics
        see the identity rows the packer added."""
        with _span("pipeline.prep", n=plan.live, bucket=plan.bucket,
                   lanes=plan.n_lanes,
                   cached=int(getattr(block, "epoch_key", None) is not None),
                   schemes=len(plan.schemes())):
            res = _mesh.prepare_superbatch(block, plan)
        # prep timing histograms are recorded inside prepare_batch*; the
        # dispatch counters note the LIVE rows against the full bucket
        _backend._note_device_batch(plan.live, plan.bucket)
        return res

    @classmethod
    def _prepare_mesh_timed(cls, block, plan):
        return cls._prepare_mesh(block, plan), time.perf_counter()

    @staticmethod
    def _resolve(spans, dev, rlc_entries=None, t_dispatch: float = 0.0,
                 bucket: int = 0) -> None:
        try:
            with _span("pipeline.device_wait"):
                # dev is a _Readback from the dispatcher (async D2H copy
                # already in flight) or a bare device array from direct
                # callers — both materialize here
                arr = dev.wait() if isinstance(dev, _Readback) else np.asarray(dev)
            if not arr.flags.owndata:
                # np.asarray of a device array is a zero-copy VIEW of the
                # XLA output buffer on the CPU backend. Under donation the
                # output aliases a donated input page, and once the jax
                # handles drop that page is recycled and overwritten by a
                # later batch — mutating verdicts already delivered to
                # callers. Futures must resolve to host-OWNED memory; the
                # verdict row is ≤ bucket bytes, so the copy is free.
                arr = np.array(arr, copy=True)
            if t_dispatch:
                # dispatch-to-materialized: the device+transfer time this
                # batch actually cost the pipeline
                _backend._ops_m().device_seconds.observe(
                    time.perf_counter() - t_dispatch,
                    bucket=str(bucket or arr.shape[-1]),
                )
            if arr.ndim == 2:  # pallas output is (1, N) / (1, lanes)
                arr = arr[0].astype(bool)
            if rlc_entries is not None:
                from . import pallas_rlc

                arr = pallas_rlc.expand_lanes(arr, rlc_entries)
            if _devcheck.inject_lintbug("alias"):
                # AFTER the 2-D/RLC reductions (they mint fresh owned
                # arrays that would neutralize the seam): the DELIVERED
                # verdict becomes the recycled-scratch view
                arr = _alias_view(arr)
            if _devcheck.enabled():
                # canary: earlier batches' delivered verdicts must still
                # be byte-stable now; this batch's verdict row registers
                # for the NEXT sweep (resolve / slot release / close)
                _devcheck.canary_sweep("pipeline.resolve")
                _devcheck.canary_register(
                    arr, tag=f"bucket={bucket or arr.shape[-1]}"
                )
        except Exception as e:  # noqa: BLE001
            for job, _, _ in spans:
                job.future.set_exception(e)
            return
        # verdict delivery is pure numpy slicing: one view per job out of
        # the batch verdict array — no per-entry Python anywhere between
        # the device result and the caller's future
        for job, off, n in spans:
            job.future.set_result(arr[off : off + n])
        if _trace.TRACER.enabled:
            for job, _off, n in spans:
                if getattr(job, "flow", None) is not None:
                    _trace.TRACER.flow_point(
                        "pipeline.verdict", job.flow,
                        "f" if getattr(job, "flow_owned", True) else "t",
                        n=n,
                    )

    def _worker(self) -> None:
        """Coalescer: many small commits (e.g. 128-signature headers
        during header sync) fuse into ONE device batch up to the max
        bucket — per-dispatch latency on the relay-attached TPU is tens
        of ms, so per-commit dispatches would cap throughput at
        ~1/latency regardless of batch size.

        Host prep runs on a small thread pool so batch N+1's packing/
        hashing overlaps batch N's prep AND the device kernel; prepared
        batches are handed to the dispatch-owner thread in FIFO order via
        the dispatch queue. This thread never touches the device."""
        from concurrent.futures import ThreadPoolExecutor

        prep_pool = ThreadPoolExecutor(3, thread_name_prefix="verify-prep")
        hold: Optional[_Job] = None
        max_b = _backend.max_coalesce()
        # QoS fuse cap (ISSUE 13): INGRESS-class rounds fuse only up to
        # this many entries. Every non-preemptible stage a fused batch
        # passes through — host prep, readback post-processing — scales
        # with batch size, so an unbounded ingress fuse turns into
        # head-of-line latency for the consensus class even with every
        # queue priority-ordered. Consensus rounds keep the full bucket.
        ing_cap = int(os.environ.get("TM_TPU_INGRESS_FUSE", "1024"))
        # REPLAY fuses to the full bucket by default (ISSUE 14): range
        # batching IS the catch-up win, and the preemption points below
        # bound the head-of-line cost for consensus either way.
        rep_cap = int(os.environ.get("TM_TPU_REPLAY_FUSE", str(max_b)))
        m = _backend._ops_m()
        try:
            while True:
                job = hold
                hold = None
                if job is None:
                    try:
                        job = self._q.get(timeout=0.05)
                    except queue.Empty:
                        if self._stopped.is_set() and self._q.empty():
                            break
                        continue
                jobs = [job]
                total = len(job.entries)
                # epoch-key gate: only jobs sharing a (non-None) epoch
                # key fuse — a mixed-key concat would drop the gather
                # indices and push the whole fused batch onto the
                # uncached prep (EntryBlock.concat's fallback). A
                # differing-key job is held for the NEXT batch, exactly
                # like a bucket-overflow job.
                key0 = job.entries.epoch_key
                # scheme gate (ISSUE 19): cross-scheme concat RAISES in
                # EntryBlock.concat (rows would hit the wrong kernel) —
                # a differing-scheme job is held like a differing key
                scheme0 = getattr(job.entries, "scheme", "ed25519")
                # coalescing window: while the device pipeline is busy a
                # short linger costs nothing (the dispatch would queue
                # anyway) and fuses straggler jobs into bigger batches —
                # the relay pays a flat ~14 ms per transfer, so fewer,
                # larger batches are strictly faster
                busy = self._inflight > 0 or self._dispatch_q.qsize() > 0
                deadline = time.monotonic() + 0.008 if busy else 0.0
                if job.priority <= PRIORITY_CONSENSUS:
                    limit = max_b
                elif job.priority <= PRIORITY_REPLAY:
                    limit = min(max_b, rep_cap)
                else:
                    limit = min(max_b, ing_cap)
                while total < limit:
                    try:
                        nxt = self._q.get_nowait()
                    except queue.Empty:
                        wait = deadline - time.monotonic()
                        if wait <= 0:
                            break
                        try:
                            nxt = self._q.get(timeout=wait)
                        except queue.Empty:
                            break
                    if (
                        total + len(nxt.entries) > limit
                        or nxt.entries.epoch_key != key0
                        or getattr(nxt.entries, "scheme", "ed25519")
                        != scheme0
                    ):
                        hold = nxt
                        break
                    jobs.append(nxt)
                    total += len(nxt.entries)
                # bucket-fit: kernel buckets are quantized, so a total
                # just past a bucket pays that bucket's FULL padding in
                # device time and host prep — peel trailing jobs back
                # while doing so lands the batch in a smaller bucket
                # with less waste
                while len(jobs) > 1 and hold is None:
                    b = _backend.quantized_bucket(total)
                    if b - total <= max(b // 8, 1024):
                        break
                    shorter = _backend.quantized_bucket(
                        total - len(jobs[-1].entries)
                    )
                    if shorter >= b:
                        break
                    hold = jobs.pop()
                    total -= len(hold.entries)
                m.pipeline_coalesced_jobs.observe(len(jobs))
                spans = []
                off = 0
                for j in jobs:
                    spans.append((j, off, len(j.entries)))
                    off += len(j.entries)
                # columnar coalescing: one concatenate per column instead
                # of a per-signature list-extend; a single-job dispatch
                # passes its block through BY IDENTITY (zero copies).
                # block_concat dispatches on block type — the scheme gate
                # above keeps a window homogeneous (AggBlocks carry
                # scheme "bls12381"), so agg commits coalesce with agg
                # commits only.
                entries = (
                    jobs[0].entries
                    if len(jobs) == 1
                    else block_concat([j.entries for j in jobs])
                )
                # a fused batch inherits the most urgent class of its
                # jobs: a consensus job fused with ingress stragglers
                # lifts the whole batch rather than riding behind it
                pri = min(j.priority for j in jobs)
                if pri <= PRIORITY_CONSENSUS:
                    # consensus prep runs INLINE: the prep pool is a FIFO,
                    # so a commit's (small) prep submitted behind queued
                    # ingress-superbatch preps would wait out every one of
                    # them — the same inversion the priority queues fix,
                    # one layer down. Inline prep hands the dispatcher an
                    # already-resolved future; overlap with the in-flight
                    # kernel is preserved (this thread isn't the
                    # dispatcher), only drain-ahead is given up, and a
                    # consensus round is small enough not to miss it.
                    fut = Future()
                    try:
                        fut.set_result(self._prepare_timed(entries))
                    except BaseException as e:  # noqa: BLE001
                        fut.set_exception(e)
                else:
                    fut = prep_pool.submit(self._prepare_timed, entries)
                self._dispatch_q.put(
                    (spans, fut, time.perf_counter(), pri), priority=pri
                )
                m.dispatch_queue_depth.set(self._dispatch_q.qsize())
                m.pipeline_queue_depth.set(self._q.qsize())
        finally:
            self._dispatch_q.put(None)
            prep_pool.shutdown(wait=False)

    def _worker_mesh(self) -> None:
        """Mesh-dispatcher coalescer (ISSUE 9 tentpole): drain queued
        jobs up to the full mesh capacity (lanes x lane capacity), then
        bin-pack them into single-epoch lanes of ONE superbatch launch
        (ops/mesh.pack_jobs). Unlike the single-lane worker there is no
        epoch-key gate on draining — differing epochs land in different
        LANES of the same launch instead of serializing into separate
        launches. Jobs that fit no lane are held for the next superbatch
        (the bucket-overflow hold, generalized). This thread never
        touches the device; the dispatcher/resolver stages downstream
        are shared with the single-lane mode unchanged."""
        from concurrent.futures import ThreadPoolExecutor

        prep_pool = ThreadPoolExecutor(3, thread_name_prefix="verify-prep")
        held: List[_Job] = []
        max_lanes = self._mesh_lanes
        m = _backend._ops_m()
        try:
            while True:
                jobs = held
                held = []
                if not jobs:
                    try:
                        jobs = [self._q.get(timeout=0.05)]
                    except queue.Empty:
                        if self._stopped.is_set() and self._q.empty():
                            break
                        continue
                # cap re-read per superbatch: submit() reads it per call,
                # so a knob change mid-run must not strand a job that was
                # legal when it was accepted
                cap = _mesh.lane_cap()
                total = sum(len(j.entries) for j in jobs)
                budget = max_lanes * cap
                # same coalescing-window rationale as _worker: while the
                # pipeline is busy a short linger fuses stragglers into
                # fuller lanes for free
                busy = self._inflight > 0 or self._dispatch_q.qsize() > 0
                deadline = time.monotonic() + 0.008 if busy else 0.0
                while total < budget:
                    try:
                        nxt = self._q.get_nowait()
                    except queue.Empty:
                        wait = deadline - time.monotonic()
                        if wait <= 0:
                            break
                        try:
                            nxt = self._q.get(timeout=wait)
                        except queue.Empty:
                            break
                    jobs.append(nxt)
                    total += len(nxt.entries)
                # Coalescer survival invariant (the dispatcher's PR-6
                # rule extended to the new packing stage): a poisoned
                # pack fails ONLY the drained jobs' futures — the worker
                # thread itself never dies on a batch's account.
                # QoS reorder (ISSUE 13): pack order is (priority, seq)
                # order, so a CONSENSUS commit drained in the same window
                # as queued INGRESS superjobs packs — and launches — ahead
                # of every one of them. `preempted` counts the ingress
                # jobs that arrived earlier but were ordered behind (or
                # pushed to the hold list by) this window's consensus
                # work; an already-launched superbatch is never revoked.
                jobs.sort(key=lambda j: (j.priority, j.seq))
                min_pri = min(j.priority for j in jobs)
                hi_seq = max(
                    j.seq for j in jobs if j.priority == min_pri
                )
                preempted = sum(
                    1 for j in jobs
                    if j.priority > min_pri and j.seq < hi_seq
                )
                try:
                    plan, held = _mesh.pack_jobs(jobs, max_lanes, cap)
                    if not plan.lanes:
                        # nothing live: empty submissions resolve right
                        # here, no launch
                        for j in plan.empty_jobs:
                            if not j.future.done():
                                j.future.set_result(
                                    np.zeros(0, dtype=bool)
                                )
                        continue
                    m.pipeline_coalesced_jobs.observe(
                        sum(len(l.jobs) for l in plan.lanes)
                    )
                    with _span("pipeline.mesh_pack", lanes=plan.n_lanes,
                               lane_bucket=plan.lane_bucket,
                               live=plan.live, pad=plan.pad,
                               preempted=preempted):
                        block, spans = _mesh.build_superblock(plan)
                    if preempted:
                        self._note_preempt(preempted)
                    m.mesh_lane_occupancy.set(plan.occupancy())
                    m.mesh_pad_waste_ratio.set(plan.pad_ratio())
                    fut = prep_pool.submit(
                        self._prepare_mesh_timed, block, plan
                    )
                except Exception as e:  # noqa: BLE001 — pack isolation
                    self._fail_spans(
                        [(j, 0, len(j.entries)) for j in jobs],
                        self._wrap_dispatch_err(
                            "mesh pack failed", e, 0,
                            [(j, 0, 0) for j in jobs],
                        ),
                    )
                    held = []
                    continue
                self._dispatch_q.put(
                    (spans, fut, time.perf_counter(), min_pri),
                    priority=min_pri,
                )
                m.dispatch_queue_depth.set(self._dispatch_q.qsize())
                m.pipeline_queue_depth.set(self._q.qsize())
        finally:
            self._dispatch_q.put(None)
            prep_pool.shutdown(wait=False)

    def _dispatcher(self) -> None:
        """The dispatch-owner: the ONLY thread that touches the relay —
        it issues the host->device transfers AND launches the kernels,
        interleaved as two stages of one loop (ISSUE 7 tentpole): batch
        k+1's `device_put` is issued BEFORE blocking on the depth
        semaphore, so its H2D copy rides behind kernel k's compute
        instead of serializing in front of its own launch. Timeline at
        steady state:

            transfer k+1  ||  kernel k  ||  readback k-1 (resolver)

        Prepared batches arrive FIFO; `pipeline.transfer` records the
        copy issue (with hidden=1 when a kernel was in flight — the
        transfer_overlap_ratio source) and `pipeline.queue_wait` now
        records PURE depth backpressure (transferred-to-launched), so
        span_summary separates wait from relay time (`pipeline.dispatch`).
        The buffer pool bounds transferred-but-unresolved input sets and
        counts recycled vs minted slots."""
        m = _backend._ops_m()
        # occupancy/overlap are WINDOWED (reset every ~2s): a cumulative-
        # since-start average would read near zero forever after a long
        # idle stretch, hiding relay saturation from /status
        busy = _dpool.WindowedRatio(m.dispatch_busy_ratio, wall=True)
        overlap = _dpool.WindowedRatio(m.transfer_overlap_ratio, wall=False)
        while True:
            try:
                item = self._dispatch_q.get(timeout=2.0)
            except queue.Empty:
                # idle tick: decay both windows so the gauges read ~0
                # when no traffic flows instead of sticking at the last
                # busy/overlap value
                busy.tick()
                overlap.tick()
                continue
            if item is None:
                self._resolve_q.put(None)
                break
            if item[0] == "xfered":
                # a batch this loop already transferred, then requeued to
                # let a higher-priority arrival overtake it at the depth
                # block (ISSUE 13) — its pool slot and device buffers
                # carry over; it re-enters directly at the launch stage
                (_tag, spans, f, dev_args, rlc_entries, bucket,
                 xslot, t_enq, pri, t_xfer_done) = item
                fut = None
            else:
                spans, fut, t_enq = item[:3]
                pri = item[3] if len(item) > 3 else PRIORITY_CONSENSUS
                xslot = None
                t_xfer_done = 0.0
            # Dispatcher survival invariant: NOTHING a single batch does —
            # prep failure, metrics accounting, the transfer, epoch-table
            # upload inside the kernel closure, the launch itself — may
            # kill or wedge this thread. A poisoned batch fails ONLY its
            # own futures (wrapped in DispatchError with epoch/bucket
            # context) and the loop moves to the next item with the depth
            # semaphore AND its pool slot intact (sem_held/slot track
            # both so even the last-resort handler leaks neither).
            sem_held = False
            ing_held = False
            slot = xslot
            if fut is not None:
                bucket = 0
            try:
                m.dispatch_queue_depth.set(self._dispatch_q.qsize())
                if fut is not None:
                    # QoS preemption point A (ISSUE 13): the PREP wait.
                    # Host prep of a fused ingress superbatch can run tens
                    # of ms; nothing device-side is held yet, so when a
                    # higher-priority batch queues up behind this wait the
                    # untouched item requeues as-is and the urgent one is
                    # served first.
                    requeued = False
                    prep_err = None
                    while True:
                        try:
                            prep, t_ready = fut.result(timeout=0.002)
                            break
                        except _FutTimeout:
                            best = self._dispatch_q.best_priority()
                            if best is not None and best < pri:
                                self._dispatch_q.put(
                                    (spans, fut, t_enq, pri), priority=pri
                                )
                                self._note_preempt(1)
                                requeued = True
                                break
                        except Exception as e:  # noqa: BLE001 — prep
                            prep_err = e
                            break
                    if requeued:
                        continue
                    if prep_err is not None:
                        self._fail_spans(spans, self._wrap_dispatch_err(
                            "batch prep failed", prep_err, 0, spans))
                        continue
                    # mesh preps append per-arg transfer shardings as
                    # a 5th element (lane-per-device placement);
                    # classic preps stay 4-tuples
                    shardings = prep[4] if len(prep) > 4 else None
                    f, args, rlc_entries, bucket = prep[:4]
                    try:
                        # transfer accounting: host bytes this launch
                        # ships, averaged over the commits fused into it —
                        # the gauge a warm epoch cache visibly shrinks
                        # (/status, PERF_r07)
                        m.h2d_bytes_per_commit.set(
                            _backend.h2d_arg_bytes(args) / max(len(spans), 1)
                        )
                    except Exception:  # noqa: BLE001 — never fatal
                        pass
                    self.dispatch_thread_idents.add(threading.get_ident())
                    # devcheck relay ownership (ISSUE 8): this thread
                    # claims the relay; any transfer/upload from another
                    # thread now asserts (no-op when TM_TPU_DEVCHECK off)
                    _devcheck.claim_relay("verify-dispatch")
                    # -- stage 1: transfer (before the depth block) ------
                    try:
                        slot = self._pool.acquire(
                            _dpool.layout_key(bucket, args),
                            abort=self._stopped.is_set,
                        )
                        hidden = self._inflight > 0
                        t_x0 = time.perf_counter()
                        # positional call when unsharded: test doubles
                        # (and any older transfer impl) keep their
                        # (args)-only signature working
                        if shardings is None:
                            dev_args = _dpool.transfer(args)
                        else:
                            dev_args = _dpool.transfer(
                                args, shardings=shardings
                            )
                        t_x1 = time.perf_counter()
                        if slot is not None:
                            slot.arrays = dev_args
                        if _trace.TRACER.enabled:
                            _trace.TRACER.record(
                                "pipeline.transfer", t_x0, t_x1,
                                {"bucket": bucket, "hidden": int(hidden)},
                            )
                        overlap.add(
                            t_x1 - t_x0 if hidden else 0.0, t_x1 - t_x0
                        )
                        busy.add(t_x1 - t_x0)
                    except Exception as e:  # noqa: BLE001
                        self._pool.release(slot)
                        slot = None
                        self._fail_spans(spans, self._wrap_dispatch_err(
                            "batch transfer failed", e, bucket, spans))
                        continue
                    # -- stage 2: launch (behind the depth semaphore) ----
                    t_xfer_done = time.perf_counter()
                    t_enq = max(t_enq, t_ready)
                # QoS preemption point B (ISSUE 13): while parked here with
                # a lower-urgency batch in hand, a queued higher-priority
                # batch may overtake — this batch requeues WITH its
                # transferred state (pool slot + device buffers), so the
                # consensus commit's wait shrinks to in-flight launches
                # only, never the whole transferred backlog. An in-flight
                # launch is never revoked. INGRESS batches additionally
                # pass through the reserved-lane semaphore first, leaving
                # one launch slot the tx flood can never fill.
                requeued = False
                if pri > PRIORITY_CONSENSUS and self._depth > 1:
                    # test seam (ISSUE 16, gated like the alias/owner
                    # seams): with the "starve" lintbug armed the
                    # reserved-lane semaphore is broken for ingress —
                    # its acquire never succeeds, so tx batches park
                    # here forever while consensus/replay keep
                    # overtaking. The soak harness must catch this via
                    # its ingress-admission SLO, not by luck.
                    starved = (pri >= PRIORITY_INGRESS
                               and _devcheck.inject_lintbug("starve"))
                    while starved or not self._ing_sem.acquire(timeout=0.002):
                        if starved:
                            time.sleep(0.002)
                        if self._stopped.is_set():
                            # shutdown while parked: fail the batch and
                            # return its slot instead of wedging close()
                            self._pool.release(slot)
                            slot = None
                            self._fail_spans(
                                spans, self._wrap_dispatch_err(
                                    "pipeline stopped while queued",
                                    RuntimeError("shutdown"), bucket, spans))
                            requeued = True
                            break
                        best = self._dispatch_q.best_priority()
                        if best is not None and best < pri:
                            self._dispatch_q.put(
                                ("xfered", spans, f, dev_args, rlc_entries,
                                 bucket, slot, t_enq, pri, t_xfer_done),
                                priority=pri,
                            )
                            slot = None  # rode along with the item
                            self._note_preempt(1)
                            requeued = True
                            break
                    ing_held = not requeued
                if not requeued:
                    while not self._sem.acquire(timeout=0.002):
                        best = self._dispatch_q.best_priority()
                        if best is not None and best < pri:
                            self._dispatch_q.put(
                                ("xfered", spans, f, dev_args, rlc_entries,
                                 bucket, slot, t_enq, pri, t_xfer_done),
                                priority=pri,
                            )
                            slot = None  # ownership rode along
                            if ing_held:
                                self._ing_sem.release()
                                ing_held = False
                            self._note_preempt(1)
                            requeued = True
                            break
                if requeued:
                    continue
                sem_held = True
                t0 = time.perf_counter()
                # per-QoS-lane queue wait (ISSUE 16): the scrapeable
                # counterpart of the queue_wait span — ingress starvation
                # shows up here as a fat ingress tail, visible to /status
                # and the soak sampler without tracing enabled
                m.queue_wait_seconds.observe(
                    max(t0 - max(t_enq, t_xfer_done), 0.0),
                    lane=_LANE_NAMES.get(min(pri, PRIORITY_INGRESS),
                                         "ingress"),
                )
                if _trace.TRACER.enabled:
                    _trace.TRACER.record(
                        "pipeline.queue_wait",
                        max(t_enq, t_xfer_done), t0,
                        {"bucket": bucket},
                    )
                try:
                    with _span("pipeline.dispatch", bucket=bucket):
                        dev = f(*dev_args)
                    if _trace.TRACER.enabled:
                        # one launch serves many coalesced jobs: step each
                        # job's flow through the dispatch instant so every
                        # chain passes through this batch's slice
                        for _j, _, _ in spans:
                            if getattr(_j, "flow", None) is not None:
                                _trace.TRACER.flow_point(
                                    "pipeline.dispatch.flow", _j.flow, "t",
                                    bucket=bucket,
                                )
                    # start the device->host copy NOW: a blocking fetch
                    # through the relay costs a full RTT (~65 ms, PERF_r05),
                    # but an async copy rides behind the compute so the
                    # later wait() in _resolve finds the bytes already
                    # host-side. Capability probed ONCE at init
                    # (_d2h_async_supported) — no silent per-batch except.
                    rb = _Readback(dev, self._d2h_async)
                except Exception as e:  # noqa: BLE001
                    # epoch-table upload (lazy, inside the cached-kernel
                    # closure) or the launch itself blew up: release the
                    # depth slot + buffer slot and fail this batch alone
                    self._sem.release()
                    sem_held = False
                    if ing_held:
                        self._ing_sem.release()
                        ing_held = False
                    self._pool.release(slot)
                    slot = None
                    self._fail_spans(spans, self._wrap_dispatch_err(
                        "kernel dispatch failed", e, bucket, spans))
                    continue
                with self._mtx:
                    self._inflight += 1
                    m.pipeline_inflight.set(self._inflight)
                now = time.perf_counter()
                busy.add(now - t0)
                self._resolve_q.put(
                    (spans, rb, rlc_entries, now, bucket, slot, ing_held),
                    priority=pri,
                )
                sem_held = False  # resolver now owns the release
                ing_held = False  # (both semaphores and the pool slot)
                slot = None
            except Exception as e:  # noqa: BLE001 — last-resort isolation
                if sem_held:
                    self._sem.release()
                if ing_held:
                    self._ing_sem.release()
                self._pool.release(slot)
                self._fail_spans(spans, self._wrap_dispatch_err(
                    "dispatch bookkeeping failed", e, bucket, spans))

    @staticmethod
    def _wrap_dispatch_err(msg, e, bucket, spans) -> "DispatchError":
        err = DispatchError(
            f"{msg}: {e!r}",
            bucket=bucket,
            epoch_key=getattr(spans[0][0].entries, "epoch_key", None)
            if spans else None,
        )
        err.__cause__ = e
        return err

    @staticmethod
    def _fail_spans(spans, err: BaseException) -> None:
        for j, _, _ in spans:
            if not j.future.done():
                j.future.set_exception(err)

    def _resolver(self) -> None:
        """Completes futures: blocks on device materialization so neither
        the coalescer nor the dispatch-owner ever waits on a result. Also
        returns each batch's buffer-pool slot — the input buffers' flight
        ends when the verdicts are read back (or the batch fails)."""
        m = _backend._ops_m()
        while True:
            item = self._resolve_q.get()
            if item is None:
                break
            spans, rb, rlc_entries, t_dispatch, bucket, slot = item[:6]
            ing_held = item[6] if len(item) > 6 else False
            if _devcheck.inject_lintbug("owner"):
                # test seam (ISSUE 8): touch the relay from the resolver
                # thread — devcheck's ownership assertion must fire
                try:
                    _dpool.transfer((np.zeros(1, dtype=np.uint8),))
                except _devcheck.DevcheckViolation:
                    pass  # recorded; the injected run continues
            try:
                self._resolve(spans, rb, rlc_entries, t_dispatch, bucket)
            finally:
                self._pool.release(slot)
                with self._mtx:
                    self._inflight -= 1
                    m.pipeline_inflight.set(self._inflight)
                self._sem.release()
                if ing_held:
                    self._ing_sem.release()


_shared: Optional[AsyncBatchVerifier] = None
_shared_mtx = threading.Lock()


def shared_verifier() -> AsyncBatchVerifier:
    """Process-wide pipeline instance (device submission is serialized
    through one thread regardless of how many reactors use it)."""
    global _shared
    with _shared_mtx:
        if _shared is None:
            _shared = AsyncBatchVerifier()
        return _shared


# ---------------------------------------------------------------------------
# Commit-level helpers: host-side entry construction mirrors
# types/validation.go:152 verifyCommitBatch, device path per signature.
# ---------------------------------------------------------------------------


def commit_entries(
    chain_id: str, vals, commit, voting_power_needed: int
) -> Tuple[EntryBlock, int]:
    """Build the columnar EntryBlock for a commit's for-block signatures
    (index lookup, early-stop past 2/3 like validation.go:152 with
    countAllSignatures=false). Returns (block, tallied_power). Raises on
    structural problems (bad counts, short power).

    The sign bytes come back as ONE contiguous buffer + offset table
    (Commit.vote_sign_bytes_block) and ride by reference all the way to
    the kernel prep — no per-signature PyBytes or tuples. Callers that
    need tuples can block.to_entries().

    Columnar commits (CommitBlock from wire decode, or built+cached on
    first use) with all-ed25519 validator columns take the FUSED path:
    selection, tally, sign-bytes, gather, and the device-hash RAM blocks
    in one call (native GIL-released when built)."""
    from . import commit_prep as _cp

    with _span("pipeline.commit_prep_fused", n=len(commit.signatures)):
        fused = _cp.prep_commit_from(
            commit,
            vals,
            chain_id,
            voting_power_needed,
            _cp.MODE_SELECT_COMMIT_ONLY | _cp.MODE_EARLY_STOP,
        )
    if fused is not None:
        sel, tallied, blk = fused
        if blk is None:
            raise ErrNotEnoughVotingPowerSigned(
                got=tallied, needed=voting_power_needed
            )
        return blk, tallied
    return commit_entries_legacy(chain_id, vals, commit, voting_power_needed)


def commit_entries_legacy(
    chain_id: str, vals, commit, voting_power_needed: int
) -> Tuple[EntryBlock, int]:
    """The PR-2 columnar path, object-walking selection + per-stage
    composition: the fallback for non-columnar commits/valsets, and the
    pinned baseline the fused path is gated against (tools/prep_bench.py
    --fused, tests/test_gil_budget.py)."""
    idxs = []
    tallied = 0
    for idx, cs in enumerate(commit.signatures):
        if not cs.for_block():
            continue
        idxs.append(idx)
        tallied += vals.validators[idx].voting_power
        if tallied > voting_power_needed:
            break
    if tallied <= voting_power_needed:
        raise ErrNotEnoughVotingPowerSigned(got=tallied, needed=voting_power_needed)
    sigs = commit.signatures
    if any(len(sigs[i].signature) != 64 for i in idxs):
        raise ValueError("invalid signature length")
    buf, offsets = commit.vote_sign_bytes_block(chain_id, idxs)
    n = len(idxs)
    idx_arr = np.asarray(idxs, dtype=np.int32)
    cols = vals.ed25519_columns()
    epoch_key = None
    scheme = "ed25519"
    pub_aux = None
    if cols is not None:
        # columnar valset, non-columnar commit: gather the cached pub
        # rows instead of re-joining pub_key.bytes() per commit (the
        # column build + key-type proof already ran once per epoch), and
        # carry the epoch metadata so warm epochs skip shipping pubs
        pub = cols[0][idx_arr]
        from . import epoch_cache as _epoch

        epoch_key = _epoch.note_valset(vals)
    elif (scols := vals.secp256k1_columns()) is not None:
        # all-secp256k1 committee (ISSUE 19): gather the 33-byte SEC1
        # rows and route the block through the scheme lane — the prefix
        # column splits off so downstream columns stay 32-wide
        raw = scols[0][idx_arr]
        from . import epoch_cache as _epoch

        pub_aux = np.ascontiguousarray(raw[:, 0])
        pub = np.ascontiguousarray(raw[:, 1:])
        scheme = "secp256k1"
        epoch_key = _epoch.note_valset(vals)
    else:
        pub_b = b"".join(vals.validators[i].pub_key.bytes() for i in idxs)
        if len(pub_b) != 32 * n:
            # a wrong-size key (e.g. secp256k1 in an ed25519 set) must
            # surface as the error the per-entry path raised, not a
            # reshape failure
            raise TypeError("pubkey is not ed25519")
        pub = np.frombuffer(pub_b, dtype=np.uint8).reshape(n, 32)
    sig = np.frombuffer(
        b"".join(sigs[i].signature for i in idxs), dtype=np.uint8
    ).reshape(n, 64)
    return EntryBlock(pub, sig, buf, offsets,
                      val_idx=idx_arr, epoch_key=epoch_key,
                      scheme=scheme, pub_aux=pub_aux), tallied


def verify_commits_pipelined(
    chain_id: str,
    jobs: Sequence[Tuple[object, object, int, object]],
    verifier: Optional[AsyncBatchVerifier] = None,
) -> List[Optional[str]]:
    """jobs: (vals, block_id, height, commit) per header. All host prep
    and device batches flow through the pipeline; returns one entry per
    job — None on success or an error string.

    The per-job semantics match verify_commit_light (types/validation.go
    :59): basic val/commit binding, then +2/3 of `vals` must have signed
    `block_id` at `height` with valid signatures.
    """
    from ..types.validation import _verify_basic_vals_and_commit

    v = verifier or shared_verifier()
    errors: List[Optional[str]] = [None] * len(jobs)

    # The whole job list is known upfront, so entries are packed into
    # FULL max-bucket device batches here instead of relying on the
    # worker's opportunistic coalescing: per-job submission races the
    # worker's queue drain, and on a relay-attached TPU each undersized
    # dispatch pays ~100 ms — measured 3-4x slower for 1k-header syncs.
    # A job's signatures may straddle two batches; verdicts re-aggregate
    # per job below. NOTE this intentionally layers over the worker's own
    # span machinery (_worker packs STREAMED submissions; this packs a
    # KNOWN-size job list) — each full chunk passes through the worker
    # 1:1, so the worker's spans are trivial for this path.
    max_b = _backend.BUCKETS[-1]
    futures: List[Future] = []
    job_spans: List[list] = [[] for _ in jobs]  # (future_idx, off, n)
    cur: list = []  # EntryBlocks (or zero-copy slices of them)
    cur_n = 0
    cur_spans: list = []  # (job_idx, off_in_batch, n)

    def _flush() -> None:
        nonlocal cur, cur_n, cur_spans
        if not cur:
            return
        fi = len(futures)
        futures.append(v.submit(EntryBlock.concat(cur)))
        for job_i, off, n in cur_spans:
            job_spans[job_i].append((fi, off, n))
        cur, cur_n, cur_spans = [], 0, []

    for i, (vals, block_id, height, commit) in enumerate(jobs):
        try:
            _verify_basic_vals_and_commit(vals, commit, height, block_id)
            needed = vals.total_voting_power() * 2 // 3
            entries, _ = commit_entries(chain_id, vals, commit, needed)
        except (ValueError, RuntimeError) as e:
            errors[i] = str(e)
            continue
        # scheme gate (ISSUE 19): a batch concats only same-scheme
        # blocks (EntryBlock.concat raises across schemes) — flush the
        # running batch before a job that switches scheme
        scheme_i = getattr(entries, "scheme", "ed25519")
        if cur and getattr(cur[0], "scheme", "ed25519") != scheme_i:
            _flush()
        pos = 0
        while pos < len(entries):
            take = min(len(entries) - pos, max_b - cur_n)
            cur_spans.append((i, cur_n, take))
            # a job straddling two device batches rides as a zero-copy
            # slice of its block — no per-signature re-packing
            cur.append(entries[pos : pos + take])
            cur_n += take
            pos += take
            if cur_n >= max_b:
                _flush()
    _flush()

    results: List[object] = []
    for fut in futures:
        try:
            results.append(np.asarray(fut.result(timeout=300)))
        except Exception as e:  # noqa: BLE001
            results.append(e)
    for i in range(len(jobs)):
        if errors[i] is not None:
            continue
        pos_in_job = 0
        for fi, off, n in job_spans[i]:
            r = results[fi]
            if isinstance(r, Exception):
                errors[i] = str(r)
                break
            # _resolve already normalized pallas output to a 1-D array
            seg = np.asarray(r[off : off + n]).astype(bool)
            if not seg.all():
                # report the signature index WITHIN this job's entries
                # (validation.go:242-248 blame assignment), not the lane
                # of the packed multi-job device batch
                bad = pos_in_job + int(np.argmin(seg))
                errors[i] = f"wrong signature (entry {bad})"
                break
            pos_in_job += n
    return errors


def verify_headers_pipelined(
    chain_id: str,
    trusted_header,
    headers: Sequence[Tuple[object, object]],
) -> None:
    """Pipelined ADJACENT header-chain verification (BASELINE config #5:
    light/verifier.go VerifyAdjacent's checks over a fetched range, with
    all commit signature batches overlapped on the device).

    headers: ordered [(signed_header, validator_set), ...] starting at
    trusted_header.height + 1, strictly adjacent. Raises ValueError on the
    first failure (host continuity checks first — they are cheap — then
    the pipelined signature verdicts in order)."""
    from ..types.block import BlockID

    prev = trusted_header
    jobs = []
    for sh, vals in headers:
        if sh.header.height != prev.header.height + 1:
            raise ValueError(
                f"headers must be adjacent: {sh.header.height} after {prev.header.height}"
            )
        sh.validate_basic(chain_id)
        if sh.header.validators_hash != vals.hash():
            raise ValueError(
                f"header {sh.header.height} validators_hash does not match supplied set"
            )
        if sh.header.validators_hash != prev.header.next_validators_hash:
            raise ValueError(
                f"header {sh.header.height} validators_hash breaks continuity"
            )
        jobs.append(
            (
                vals,
                BlockID(
                    hash=sh.commit.block_id.hash,
                    part_set_header=sh.commit.block_id.part_set_header,
                ),
                sh.header.height,
                sh.commit,
            )
        )
        prev = sh
    errors = verify_commits_pipelined(chain_id, jobs)
    for (sh, _), err in zip(headers, errors):
        if err is not None:
            raise ValueError(f"header {sh.header.height}: {err}")
