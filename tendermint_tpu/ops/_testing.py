"""Shared test scaffolding for the overlapped dispatcher (ISSUE 7).

Used by BOTH tests/test_overlap.py and the `tools/prep_bench.py
--overlap` tier-1 gate: they pin the same dispatcher loop structure
(transfer k+1 issued before batch k resolves) against the same mock, so
the mock lives in one place instead of drifting as two copies.

Not imported by any production path.
"""

from __future__ import annotations

import time


class SlowReadback:
    """Proxy device result whose materialization costs `delay` seconds —
    the resolver blocks on __array__ exactly like a relay-attached TPU's
    D2H wait; async-copy capability passes through to the real result."""

    def __init__(self, dev, delay: float):
        self._dev = dev
        self._delay = delay

    def copy_to_host_async(self):
        fn = getattr(self._dev, "copy_to_host_async", None)
        if fn is not None:
            fn()

    def __array__(self, dtype=None):
        import numpy as np

        time.sleep(self._delay)
        a = np.asarray(self._dev)
        # a device-array stand-in must return the raw (possibly
        # non-owning) materialization — the resolver's owndata guard is
        # exactly what the overlap tests exercise
        return a.astype(dtype) if dtype is not None else a  # tmlint: disable=donation-aliasing — mock mimics device semantics


def slow_prepare(real_prepare, delay: float):
    """Wrap AsyncBatchVerifier._prepare so every kernel result rides a
    SlowReadback — the kernel itself (and its donation/transfer path)
    runs unchanged; only the readback is slowed."""

    def prep(entries):
        f, args, rlc, bucket = real_prepare(entries)
        return (lambda *xs: SlowReadback(f(*xs), delay)), args, rlc, bucket

    return prep


def slow_mesh_prepare(real_prepare, delay: float):
    """Mesh-mode twin of slow_prepare: wrap AsyncBatchVerifier's
    `_prepare_mesh` so every superbatch's kernel result rides a
    SlowReadback — the REAL packing, prep, transfer shardings and kernel
    run unchanged; only the readback is slowed (the `tools/prep_bench.py
    --mesh` gate's relay-RTT proxy)."""

    def prep(block, plan):
        res = real_prepare(block, plan)
        f, args, rlc, bucket = res[:4]
        return (
            (lambda *xs: SlowReadback(f(*xs), delay)), args, rlc, bucket,
        ) + tuple(res[4:])

    return prep


def mock_mesh_prepare(real_prepare, rtt_s: float):
    """Fully-mocked mesh DEVICE for `bench.py multichip`'s simulated-lane
    curve: the real lane packing, host prep and H2D transfer run
    unchanged, but the launch returns an all-accept verdict row behind a
    fixed relay RTT instead of running the kernel — modeling an L-device
    mesh (per-lane compute parallel across devices, one relay command
    per superbatch) on a box with one physical device. The curve then
    measures exactly what the mesh dispatcher adds: signatures packed
    per relay command vs the dispatcher's own serial host costs."""
    import numpy as np

    def prep(block, plan):
        res = real_prepare(block, plan)
        _f, args, rlc, bucket = res[:4]

        def launch(*_xs):
            return SlowReadback(np.ones((bucket,), dtype=bool), rtt_s)

        return (launch, args, rlc, bucket) + tuple(res[4:])

    return prep


def mock_light_prepare(real_prepare, rtt_s: float):
    """Mocked-relay DEVICE for `bench.py light` and the
    `tools/prep_bench.py --light` throughput figure: the real host prep
    (sign-bytes, epoch grouping, coalescing, packing) and the H2D
    transfer run unchanged, but the launch returns an all-accept verdict
    row behind a fixed relay RTT instead of running the kernel — the
    mock_mesh_prepare philosophy applied to the classic single-lane
    `_prepare`. What the light-service curve then measures is exactly
    what the service adds over per-request dispatch: cross-request
    epoch-grouped coalescing (headers per relay command) and
    request-level dedup, not kernel speed."""
    import numpy as np

    def prep(entries):
        _f, args, rlc, bucket = real_prepare(entries)

        def launch(*_xs):
            return SlowReadback(np.ones((bucket,), dtype=bool), rtt_s)

        return launch, args, rlc, bucket

    return prep


class DeadlineReadback:
    """Proxy device result that materializes at an absolute deadline —
    `rtt_s` after LAUNCH, not after the resolver gets around to it. The
    SlowReadback mock charges its delay inside __array__, which
    serializes the resolver at one RTT per batch; a real device's compute
    proceeds while the host pipelines, so concurrent launches' readbacks
    mature in parallel. bench.py mempool uses this so the mocked relay
    models per-launch LATENCY (each batch's verdict is unavailable for a
    full RTT) without inventing a serial resolver bottleneck no real
    backend has."""

    def __init__(self, verdict, deadline: float):
        self._verdict = verdict
        self._deadline = deadline

    def copy_to_host_async(self):
        pass

    def __array__(self, dtype=None):
        import numpy as np

        now = time.perf_counter()
        if now < self._deadline:
            time.sleep(self._deadline - now)
        a = np.asarray(self._verdict)
        return a.astype(dtype) if dtype is not None else a  # tmlint: disable=donation-aliasing — mock mimics device semantics


def mock_mempool_prepare(real_prepare, rtt_s: float):
    """Mocked-relay DEVICE for `bench.py mempool` (ISSUE 13): the real
    ingress accumulation, EntryBlock packing, host prep and H2D transfer
    run unchanged, but the launch returns an all-accept verdict row that
    matures `rtt_s` after launch (DeadlineReadback) instead of running
    the kernel. Both bench columns — the windowed accumulator and the
    per-tx baseline — pay this same relay latency per LAUNCH, so the
    ratio measures exactly what device-batched CheckTx adds: signatures
    fused per relay command."""
    import numpy as np

    def prep(entries):
        _f, args, rlc, bucket = real_prepare(entries)

        def launch(*_xs):
            return DeadlineReadback(
                np.ones((bucket,), dtype=bool),
                time.perf_counter() + rtt_s,
            )

        return launch, args, rlc, bucket

    return prep


def mock_vote_prepare(real_prepare, rtt_s: float):
    """Mocked-relay DEVICE for `bench.py votes` and the
    `tools/prep_bench.py --votes` gate (ISSUE 15): the real vote-ingress
    windowing, EntryBlock packing, host prep and H2D transfer run
    unchanged, but the launch returns an all-accept verdict row that
    matures `rtt_s` after launch (DeadlineReadback) instead of running
    the kernel. Both bench columns — the windowed accumulator and the
    per-vote baseline — pay this same relay latency per LAUNCH, so the
    ratio measures exactly what device-batched AddVote adds: live-vote
    signatures fused per relay command."""
    import numpy as np

    def prep(entries):
        _f, args, rlc, bucket = real_prepare(entries)

        def launch(*_xs):
            return DeadlineReadback(
                np.ones((bucket,), dtype=bool),
                time.perf_counter() + rtt_s,
            )

        return launch, args, rlc, bucket

    return prep


def drain_pool(pool, timeout: float = 5.0) -> None:
    """Wait for every in-flight slot to return. The resolver completes a
    batch's futures BEFORE releasing its pool slot, so a caller waking
    from future.result() can observe in_flight briefly nonzero — tests
    and the --overlap gate drain here before asserting leak-freedom."""
    deadline = time.time() + timeout
    while pool.in_flight() and time.time() < deadline:
        time.sleep(0.01)
