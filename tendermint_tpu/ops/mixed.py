"""Mixed-curve batch verification (BASELINE config #4).

Reference parity: crypto/batch/batch.go:11-33 — batch verifiers exist for
ed25519 and sr25519; secp256k1 never batches (batch.go:26-33). Here the
two batchable curves each get a DEVICE lane (ops.pallas_verify /
ops.pallas_sr25519) and secp256k1 falls back to per-signature host
verification (OpenSSL ECDSA), mirroring the reference's split.

verify_mixed() partitions one heterogeneous batch by key type, dispatches
all lanes, and reassembles per-signature verdicts in input order.
"""

from __future__ import annotations

import os
from typing import List, Sequence, Tuple

import numpy as np

from ..crypto import PubKey
from ..crypto import ed25519 as _ed
from ..crypto import secp256k1 as _secp
from ..crypto import sr25519 as _sr
from . import backend as _backend

# Below this many sr25519 signatures the device round-trip loses to the
# (pure-Python, ~10 ms/sig) host path only for very small counts; the
# device wins early because host schnorr math is so slow.
SR_DEVICE_THRESHOLD = int(os.environ.get("TM_TPU_SR_DEVICE_THRESHOLD", "8"))


# First device call (the Mosaic compile) is time-boxed: a pathologically
# slow or hung remote compile must not wedge the caller — on timeout the
# process permanently falls back to the host path for sr25519.
_sr_device_state = {"ok": None}  # None = untried, True/False decided


def _sr_compile_timeout() -> float:
    """Read at call time so bench.py can tighten the budget after this
    module is already imported."""
    return float(os.environ.get("TM_TPU_SR_COMPILE_TIMEOUT", "300"))


def _host_sr_batch(entries) -> np.ndarray:
    return np.asarray(_sr.verify_batch(list(entries)), dtype=bool)


def _sr_device_enabled() -> bool:
    """The sr25519 DEVICE lane is opt-in (TM_TPU_SR_DEVICE=1): its Mosaic
    compile has been observed to hang the shared remote compile helper,
    which poisons the relay for every subsequent process on the host (see
    ops/pallas_sr25519 STATUS). The kernels are differentially validated;
    flip the default once the toolchain compiles them."""
    return os.environ.get("TM_TPU_SR_DEVICE", "0") == "1"


def _verify_sr25519_batch(entries: List[Tuple[bytes, bytes, bytes]]) -> np.ndarray:
    if (
        len(entries) < SR_DEVICE_THRESHOLD
        or not _sr_device_enabled()
        or not _backend._use_pallas()
        or _sr_device_state["ok"] is False
    ):
        return _host_sr_batch(entries)
    import jax

    from . import pallas_sr25519 as ps

    interpret = jax.default_backend() != "tpu"

    def run_chunks() -> np.ndarray:
        out = []
        i = 0
        while i < len(entries):
            chunk = entries[i : i + _backend.BUCKETS[-1]]
            bucket = _backend._pallas_bucket(len(chunk))
            args = ps.prepare_sr25519(chunk, bucket)
            res = ps.verify_sr25519_compact(*args, interpret=interpret)
            out.append(res[: len(chunk)])
            i += len(chunk)
        return np.concatenate(out)

    if _sr_device_state["ok"]:
        return run_chunks()

    # first use: compile under a watchdog
    import threading

    holder: dict = {}

    def attempt():
        try:
            holder["res"] = run_chunks()
        except Exception as e:  # noqa: BLE001
            holder["err"] = e

    t = threading.Thread(target=attempt, daemon=True)
    t.start()
    t.join(_sr_compile_timeout())
    if "res" in holder:
        _sr_device_state["ok"] = True
        return holder["res"]
    _sr_device_state["ok"] = False  # hung or failed: host from now on
    return _host_sr_batch(entries)


def verify_mixed(
    entries: Sequence[Tuple[PubKey, bytes, bytes]],
) -> List[bool]:
    """entries: (PubKey, msg, sig) with heterogeneous key types. Returns
    per-entry validity in input order; ed25519 and sr25519 ride their
    device lanes, secp256k1 verifies per-signature on the host."""
    lanes = {"ed25519": [], "sr25519": [], "secp256k1": [], "other": []}
    order = []
    for i, (pk, msg, sig) in enumerate(entries):
        kind = pk.type() if pk.type() in lanes else "other"
        order.append((kind, len(lanes[kind])))
        lanes[kind].append((pk, msg, sig))

    results = {}
    if lanes["ed25519"]:
        results["ed25519"] = _backend.verify_batch(
            [(pk.bytes(), m, s) for pk, m, s in lanes["ed25519"]]
        )
    if lanes["sr25519"]:
        results["sr25519"] = _verify_sr25519_batch(
            [(pk.bytes(), m, s) for pk, m, s in lanes["sr25519"]]
        )
    if lanes["secp256k1"]:
        results["secp256k1"] = np.asarray(
            [pk.verify_signature(m, s) for pk, m, s in lanes["secp256k1"]],
            dtype=bool,
        )
    if lanes["other"]:
        results["other"] = np.asarray(
            [pk.verify_signature(m, s) for pk, m, s in lanes["other"]],
            dtype=bool,
        )
    return [bool(results[kind][j]) for kind, j in order]


class Sr25519DeviceBatchVerifier:
    """crypto.BatchVerifier for sr25519 on the device ristretto lane
    (crypto/sr25519/batch.go parity)."""

    def __init__(self):
        self._entries: List[Tuple[bytes, bytes, bytes]] = []

    def add(self, key, msg: bytes, sig: bytes) -> None:
        if key.type() != _sr.KEY_TYPE:
            raise TypeError("pubkey is not sr25519")
        if len(sig) != _sr.SIGNATURE_SIZE:
            raise ValueError("invalid signature length")
        self._entries.append((key.bytes(), msg, sig))

    def verify(self) -> Tuple[bool, List[bool]]:
        if not self._entries:
            return False, []
        res = _verify_sr25519_batch(self._entries)
        valid = [bool(v) for v in res]
        return all(valid), valid
