"""Mixed-curve batch verification (BASELINE config #4).

Reference parity: crypto/batch/batch.go:11-33 — batch verifiers exist for
ed25519 and sr25519; secp256k1 never batches (batch.go:26-33). Here every
curve gets a DEVICE lane: ed25519 and sr25519 as before
(ops.pallas_verify / ops.pallas_sr25519), and since ISSUE 19 secp256k1
batches through the Strauss+GLV ECDSA kernel (ops.secp_verify) — the
reference's "no secp batching" is a verifier-interface fact, not a
verdict change, so the device lane stays bit-identical to per-signature
verification. The per-signature host loop survives as the
small-batch / TM_TPU_SECP_DEVICE=0 fallback, thread-pooled because each
OpenSSL ECDSA_verify releases the GIL.

verify_mixed() partitions one heterogeneous batch by key type, dispatches
all lanes, and reassembles per-signature verdicts in input order.
"""

from __future__ import annotations

import os
from typing import List, Sequence, Tuple

import numpy as np

from ..crypto import PubKey
from ..crypto import ed25519 as _ed
from ..crypto import secp256k1 as _secp
from ..crypto import sr25519 as _sr
from . import backend as _backend

# Below this many sr25519 signatures the device round-trip loses to the
# (pure-Python, ~10 ms/sig) host path only for very small counts; the
# device wins early because host schnorr math is so slow.
SR_DEVICE_THRESHOLD = int(os.environ.get("TM_TPU_SR_DEVICE_THRESHOLD", "8"))

# secp256k1 scheme lane (ISSUE 19): below this many signatures the
# device round-trip loses to the host's native ECDSA_verify loop
SECP_DEVICE_THRESHOLD = int(
    os.environ.get("TM_TPU_SECP_DEVICE_THRESHOLD", "8")
)
# host-fallback pool: ECDSA_verify releases the GIL, so the per-sig loop
# threads near-linearly; small batches stay single-threaded (pool spawn
# costs more than it saves)
SECP_HOST_POOL_MIN = int(os.environ.get("TM_TPU_SECP_HOST_POOL_MIN", "32"))


def _secp_device_enabled() -> bool:
    return os.environ.get("TM_TPU_SECP_DEVICE", "1") == "1"


def _secp_host_workers() -> int:
    w = os.environ.get("TM_TPU_SECP_HOST_WORKERS")
    if w is not None:
        return max(1, int(w))
    return max(1, min(8, (os.cpu_count() or 1)))


def _host_secp_batch(lane: Sequence[Tuple[PubKey, bytes, bytes]]) -> np.ndarray:
    """Per-signature host verification, thread-pooled (satellite of
    ISSUE 19): each native ECDSA_verify drops the GIL so N workers give
    ~N×; under TM_TPU_PUREPY_CRYPTO the math is pure Python and the pool
    is skipped (threads would just interleave GIL-held bignum ops)."""
    n = len(lane)
    workers = _secp_host_workers()
    if n < SECP_HOST_POOL_MIN or workers < 2 or _secp.is_pure_python():
        return np.array(
            [pk.verify_signature(m, s) for pk, m, s in lane], dtype=bool
        )
    from concurrent.futures import ThreadPoolExecutor

    with ThreadPoolExecutor(max_workers=workers) as pool:
        return np.fromiter(
            pool.map(
                lambda e: e[0].verify_signature(e[1], e[2]),
                lane,
                chunksize=max(1, n // (workers * 4)),
            ),
            dtype=bool,
            count=n,
        )


def _verify_secp_batch(lane: Sequence[Tuple[PubKey, bytes, bytes]]) -> np.ndarray:
    """The secp lane: batched device kernel when enabled and worth the
    round-trip, the (pooled) host loop otherwise. Device and host agree
    bit-for-bit on verdicts (tests/test_secp_lane.py pins this)."""
    if len(lane) >= SECP_DEVICE_THRESHOLD and _secp_device_enabled():
        entries_b = [(pk.bytes(), m, s) for pk, m, s in lane]
        return np.array(_backend.verify_batch_secp(entries_b), dtype=bool)
    return _host_secp_batch(lane)


# First device call (the Mosaic compile) is time-boxed: a pathologically
# slow or hung remote compile must not wedge the caller — on timeout the
# process permanently falls back to the host path for sr25519.
_sr_device_state = {"ok": None}  # None = untried, True/False decided


def _sr_compile_timeout() -> float:
    """Read at call time so bench.py can tighten the budget after this
    module is already imported."""
    return float(os.environ.get("TM_TPU_SR_COMPILE_TIMEOUT", "300"))


# host-fallback pool for sr25519 (satellite of ISSUE 20, mirroring the
# secp pool): the native schnorrkel batch call computes outside the GIL,
# so splitting a big batch across workers scales ~linearly; the
# pure-Python fallback is GIL-held bignum math and stays single-threaded
SR_HOST_POOL_MIN = int(os.environ.get("TM_TPU_SR_HOST_POOL_MIN", "32"))


def _sr_host_workers() -> int:
    w = os.environ.get("TM_TPU_SR_HOST_WORKERS")
    if w is not None:
        return max(1, int(w))
    return max(1, min(8, (os.cpu_count() or 1)))


def _sr_native_batch_available() -> bool:
    from ..native import load as _load_native

    native = _load_native()
    return native is not None and hasattr(native, "sr25519_verify_batch")


def _host_sr_batch(entries) -> np.ndarray:
    """Host sr25519 verdicts, thread-pooled over native batch chunks.
    Small batches (or the pure-Python fallback, where threads would only
    interleave GIL-held math) run the single verify_batch call."""
    entries = list(entries)
    n = len(entries)
    workers = _sr_host_workers()
    if (
        n < SR_HOST_POOL_MIN
        or workers < 2
        or not _sr_native_batch_available()
    ):
        return np.array(_sr.verify_batch(entries), dtype=bool)
    from concurrent.futures import ThreadPoolExecutor

    step = -(-n // workers)
    chunks = [entries[i:i + step] for i in range(0, n, step)]
    with ThreadPoolExecutor(max_workers=len(chunks)) as pool:
        parts = list(pool.map(_sr.verify_batch, chunks))
    return np.concatenate([np.asarray(p, dtype=bool) for p in parts])


def _sr_device_enabled() -> bool:
    """sr25519 device lane: ON by default since round 4 — the round-3
    Mosaic compile hang no longer reproduces (verified on hardware:
    compiles in ~16s, correct at production buckets vs the host oracle).
    The first-use watchdog below still guards against a hung remote
    compile; TM_TPU_SR_DEVICE=0 forces the native host lane."""
    return os.environ.get("TM_TPU_SR_DEVICE", "1") == "1"


def _verify_sr25519_batch(entries: List[Tuple[bytes, bytes, bytes]]) -> np.ndarray:
    if (
        len(entries) < SR_DEVICE_THRESHOLD
        or not _sr_device_enabled()
        or not _backend._use_pallas()
        or _sr_device_state["ok"] is False
    ):
        return _host_sr_batch(entries)
    import jax

    from . import pallas_sr25519 as ps

    interpret = jax.default_backend() != "tpu"

    def run_chunks() -> np.ndarray:
        out = []
        i = 0
        while i < len(entries):
            chunk = entries[i : i + _backend.BUCKETS[-1]]
            bucket = _backend._pallas_bucket(len(chunk))
            args = ps.prepare_sr25519(chunk, bucket)
            res = ps.verify_sr25519_compact(*args, interpret=interpret)
            out.append(res[: len(chunk)])
            i += len(chunk)
        return np.concatenate(out)

    if _sr_device_state["ok"]:
        return run_chunks()

    # first use: compile under a watchdog
    import threading

    holder: dict = {}

    def attempt():
        try:
            holder["res"] = run_chunks()
        except Exception as e:  # noqa: BLE001
            holder["err"] = e

    t = threading.Thread(target=attempt, daemon=True)
    t.start()
    t.join(_sr_compile_timeout())
    if "res" in holder:
        _sr_device_state["ok"] = True
        return holder["res"]
    _sr_device_state["ok"] = False  # hung or failed: host from now on
    return _host_sr_batch(entries)


def verify_mixed(
    entries: Sequence[Tuple[PubKey, bytes, bytes]],
) -> List[bool]:
    """entries: (PubKey, msg, sig) with heterogeneous key types. Returns
    per-entry validity in input order; ed25519 and sr25519 ride their
    device lanes, secp256k1 verifies per-signature on the host."""
    lanes = {"ed25519": [], "sr25519": [], "secp256k1": [], "other": []}
    order = []
    for i, (pk, msg, sig) in enumerate(entries):
        kind = pk.type() if pk.type() in lanes else "other"
        order.append((kind, len(lanes[kind])))
        lanes[kind].append((pk, msg, sig))

    # Lanes run CONCURRENTLY: the ed25519 batch rides the shared async
    # pipeline (a future), the sr25519 and secp256k1 device batches
    # dispatch on helper threads, and any host loops fill the main
    # thread while the device works — the mixed batch costs max(lanes),
    # not sum(lanes).
    results = {}
    ed_future = None
    sr_thread = None
    sr_holder: dict = {}
    secp_thread = None
    secp_holder: dict = {}
    if lanes["ed25519"]:
        ed_entries = [(pk.bytes(), m, s) for pk, m, s in lanes["ed25519"]]
        if len(ed_entries) <= _backend.BUCKETS[-1]:
            from .pipeline import shared_verifier

            ed_future = shared_verifier().submit(ed_entries)
        else:
            results["ed25519"] = _backend.verify_batch(ed_entries)
    if lanes["sr25519"]:
        import threading

        sr_entries = [(pk.bytes(), m, s) for pk, m, s in lanes["sr25519"]]

        def _sr_run():
            try:
                sr_holder["res"] = _verify_sr25519_batch(sr_entries)
            except Exception as e:  # noqa: BLE001
                sr_holder["err"] = e

        sr_thread = threading.Thread(target=_sr_run, daemon=True)
        sr_thread.start()
    if lanes["secp256k1"]:
        import threading

        secp_lane = lanes["secp256k1"]

        def _secp_run():
            try:
                secp_holder["res"] = _verify_secp_batch(secp_lane)
            except Exception as e:  # noqa: BLE001
                secp_holder["err"] = e

        secp_thread = threading.Thread(target=_secp_run, daemon=True)
        secp_thread.start()
    if lanes["other"]:
        results["other"] = np.asarray(
            [pk.verify_signature(m, s) for pk, m, s in lanes["other"]],
            dtype=bool,
        )
    if ed_future is not None:
        results["ed25519"] = np.asarray(ed_future.result(timeout=600))
    if sr_thread is not None:
        sr_thread.join(timeout=600)
        if sr_thread.is_alive():
            raise TimeoutError("sr25519 device lane did not finish in 600s")
        if "err" in sr_holder:
            raise sr_holder["err"]
        results["sr25519"] = sr_holder["res"]
    if secp_thread is not None:
        secp_thread.join(timeout=600)
        if secp_thread.is_alive():
            raise TimeoutError("secp256k1 lane did not finish in 600s")
        if "err" in secp_holder:
            raise secp_holder["err"]
        results["secp256k1"] = secp_holder["res"]
    return [bool(results[kind][j]) for kind, j in order]


class Sr25519DeviceBatchVerifier:
    """crypto.BatchVerifier for sr25519 on the device ristretto lane
    (crypto/sr25519/batch.go parity)."""

    def __init__(self):
        self._entries: List[Tuple[bytes, bytes, bytes]] = []

    def add(self, key, msg: bytes, sig: bytes) -> None:
        if key.type() != _sr.KEY_TYPE:
            raise TypeError("pubkey is not sr25519")
        if len(sig) != _sr.SIGNATURE_SIZE:
            raise ValueError("invalid signature length")
        self._entries.append((key.bytes(), msg, sig))

    def verify(self) -> Tuple[bool, List[bool]]:
        if not self._entries:
            return False, []
        res = _verify_sr25519_batch(self._entries)
        valid = [bool(v) for v in res]
        return all(valid), valid


class Secp256k1DeviceBatchVerifier:
    """crypto.BatchVerifier shape over the secp256k1 scheme lane.

    NOT returned by crypto/batch.create_batch_verifier — that stays None
    for reference parity (batch.go:26-33), and _verify_commit_batch's
    ed25519-shaped add_block path must never see 33-byte keys. Callers
    that want batched secp opt in explicitly (ops.mixed, bench, tests);
    commits route through prepare_commit_batch / the mesh instead."""

    def __init__(self):
        self._entries: List[Tuple[PubKey, bytes, bytes]] = []

    def add(self, key, msg: bytes, sig: bytes) -> None:
        if key.type() != _secp.KEY_TYPE:
            raise TypeError("pubkey is not secp256k1")
        if len(sig) != _secp.SIGNATURE_LENGTH:
            raise ValueError("invalid signature length")
        self._entries.append((key, msg, sig))

    def verify(self) -> Tuple[bool, List[bool]]:
        if not self._entries:
            return False, []
        res = _verify_secp_batch(self._entries)
        valid = [bool(v) for v in res]
        return all(valid), valid
