"""Columnar signature-batch representation — the zero-copy commit prep.

PERF_r05: the RLC kernel sustains ~476k sigs/s but end-to-end
types.verify_commit peaked at 143k because the host path between
verify_commit and the kernel was built from per-signature Python objects:
a (pub32, msg, sig64) tuple per lane, PyBytes sign-bytes, and b"".join
re-copies in every prep stage — all GIL-held, so under concurrent commits
the orchestration language (not the device) was the binding constraint.

An EntryBlock carries one commit's (or one coalesced device batch's)
signatures as contiguous columnar buffers built ONCE and handed by
reference:

    pub     (n, 32) uint8   public keys, row per signature
    sig     (n, 64) uint8   signatures (R || s)
    msgs    bytes/memoryview  all sign-bytes concatenated
    offsets (n+1,) int64    msgs[offsets[i]:offsets[i+1]] is message i

Downstream consumers (ops.backend prepare_batch*, ops.pallas_verify
prepare_compact, ops.pallas_rlc prepare_rlc, the async pipeline's
coalescer) slice these arrays directly: no per-signature Python objects
are created between commit selection and the kernel argument arrays, and
batch concatenation is np.concatenate instead of list-extend. The
tuple-list API everywhere remains a thin shim over `as_block`.
"""

from __future__ import annotations

from typing import Iterator, List, Sequence, Tuple, Union

import numpy as np

Entry = Tuple[bytes, bytes, bytes]

_EMPTY_OFFSETS = np.zeros(1, dtype=np.int64)


class EntryBlock:
    """Columnar (pub, msg, sig) batch; see module docstring.

    Optional `ram_*` columns carry each row's R||A||M message already
    padded into SHA-512 blocks and packed into the device-hash kernel's
    big-endian 32-bit word layout (ops/sha512.pad_ram_block output, but
    per ROW instead of per padded bucket): ram_hi/ram_lo (n, W) uint32
    with W = nblock*16, ram_counts (n,) int32 blocks-used. The fused
    commit prep (ops/commit_prep.py) fills them while composing the sign
    bytes — the bytes are in cache anyway — so prepare_batch_device_hash
    skips its big scatter and just pads rows. They ride through concat
    and slicing like every other column; blocks without them (tuple-list
    conversions, mixed sources) simply fall back to the generic pad."""

    __slots__ = ("pub", "sig", "msgs", "offsets",
                 "ram_hi", "ram_lo", "ram_counts",
                 "val_idx", "epoch_key", "scheme", "pub_aux")

    def __init__(self, pub: np.ndarray, sig: np.ndarray,
                 msgs: Union[bytes, memoryview], offsets: np.ndarray,
                 ram_hi: "np.ndarray" = None, ram_lo: "np.ndarray" = None,
                 ram_counts: "np.ndarray" = None,
                 val_idx: "np.ndarray" = None, epoch_key: bytes = None,
                 scheme: str = "ed25519", pub_aux: "np.ndarray" = None):
        n = pub.shape[0]
        if pub.shape != (n, 32) or sig.shape != (n, 64):
            raise ValueError("pub must be (n, 32) and sig (n, 64) uint8")
        if offsets.shape != (n + 1,):
            raise ValueError("offsets must be (n+1,)")
        # monotonicity is load-bearing: downstream native code derives
        # per-message lengths as offsets[i+1]-offsets[i] in GIL-released
        # C, where a negative difference wraps to a huge size_t
        if n and bool((np.diff(offsets) < 0).any()):
            raise ValueError("offsets must be non-decreasing")
        self.pub = pub
        self.sig = sig
        self.msgs = msgs
        self.offsets = offsets
        if ram_hi is not None:
            if (
                ram_lo is None or ram_counts is None
                or ram_hi.shape != ram_lo.shape or ram_hi.shape[0] != n
                or ram_counts.shape != (n,)
            ):
                raise ValueError("ram columns must be (n, W) hi/lo + (n,) counts")
        self.ram_hi = ram_hi
        self.ram_lo = ram_lo
        self.ram_counts = ram_counts
        # Epoch-cache metadata (ops/epoch_cache.py): val_idx (n,) int32 —
        # each lane's row in its validator set's cached device pub table;
        # epoch_key — the ValidatorSet.hash() the table is keyed by. When
        # set, warm-epoch preps ship val_idx instead of pubkey-derived
        # arrays and the kernels gather A on device.
        if val_idx is not None and val_idx.shape != (n,):
            raise ValueError("val_idx must be (n,)")
        self.val_idx = val_idx
        self.epoch_key = epoch_key
        # Scheme tag (ISSUE 19): every row of a block shares ONE signature
        # scheme — the mesh packer keys lanes on it and the kernel prep
        # branches on it. `pub_aux` carries the per-row byte a scheme's
        # wire key needs beyond the (n, 32) column: for secp256k1 the SEC1
        # compression prefix (pub = prefix || X, so pub holds X). ed25519
        # blocks keep pub_aux None.
        self.scheme = scheme
        if pub_aux is not None and pub_aux.shape != (n,):
            raise ValueError("pub_aux must be (n,)")
        self.pub_aux = pub_aux

    # -- construction -------------------------------------------------------

    @classmethod
    def empty(cls, scheme: str = "ed25519") -> "EntryBlock":
        return cls(
            np.zeros((0, 32), dtype=np.uint8),
            np.zeros((0, 64), dtype=np.uint8),
            b"",
            _EMPTY_OFFSETS,
            scheme=scheme,
            pub_aux=(
                np.zeros(0, dtype=np.uint8) if scheme != "ed25519" else None
            ),
        )

    @classmethod
    def from_entries(cls, entries: Sequence[Entry],
                     scheme: str = "ed25519") -> "EntryBlock":
        """Tuple-list shim: one validation pass + two joins, the same cost
        the old per-batch _pack_rows paid — conversion happens once at the
        API boundary instead of in every downstream stage. Non-ed25519
        schemes declare themselves: secp256k1 entries carry 33-byte SEC1
        keys, split here into the prefix column (pub_aux) + X (pub)."""
        n = len(entries)
        if n == 0:
            return cls.empty(scheme)
        klen = 33 if scheme == "secp256k1" else 32
        if any(len(pk) != klen or len(s) != 64 for pk, _, s in entries):
            raise ValueError(
                f"entries must be (pub{klen}, msg, sig64) triples"
            )
        raw = np.frombuffer(
            b"".join(pk for pk, _, _ in entries), dtype=np.uint8
        ).reshape(n, klen)
        pub_aux = None
        if klen == 33:
            pub_aux = np.ascontiguousarray(raw[:, 0])
            pub = np.ascontiguousarray(raw[:, 1:])
        else:
            pub = raw
        sig = np.frombuffer(
            b"".join(s for _, _, s in entries), dtype=np.uint8
        ).reshape(n, 64)
        lens = np.fromiter((len(m) for _, m, _ in entries), dtype=np.int64,
                           count=n)
        offsets = np.zeros(n + 1, dtype=np.int64)
        np.cumsum(lens, out=offsets[1:])
        msgs = b"".join(m for _, m, _ in entries)
        return cls(pub, sig, msgs, offsets, scheme=scheme, pub_aux=pub_aux)

    # -- shape --------------------------------------------------------------

    @property
    def n(self) -> int:
        return self.pub.shape[0]

    def __len__(self) -> int:
        return self.pub.shape[0]

    def msg_nbytes(self) -> int:
        return int(self.offsets[-1] - self.offsets[0])

    # -- access -------------------------------------------------------------

    def msg(self, i: int) -> bytes:
        o = self.offsets
        return bytes(memoryview(self.msgs)[int(o[i]) : int(o[i + 1])])

    def pub_bytes(self, i: int) -> bytes:
        """Row i's full wire-format key (prefix byte re-attached for
        schemes that split one into pub_aux)."""
        if self.pub_aux is not None:
            return bytes([int(self.pub_aux[i])]) + self.pub[i].tobytes()
        return self.pub[i].tobytes()

    def entry(self, i: int) -> Entry:
        """Materialize ONE (pub, msg, sig64) tuple — the blame path's
        per-lane re-verify, not a bulk conversion. The pub element is the
        scheme's wire key (32 bytes ed25519, 33 bytes secp256k1)."""
        return self.pub_bytes(i), self.msg(i), self.sig[i].tobytes()

    def iter_entries(self) -> Iterator[Entry]:  # tmlint: fallback — tuple-compat shim, blame/debug path only
        for i in range(self.n):
            yield self.entry(i)

    def to_entries(self) -> List[Entry]:
        return list(self.iter_entries())

    def msg_views(self) -> List[memoryview]:
        """Per-message zero-copy views (hashlib and the native sequence
        APIs both accept memoryview)."""
        mv = memoryview(self.msgs)
        o = self.offsets
        return [mv[int(o[i]) : int(o[i + 1])] for i in range(self.n)]

    def msgs_contiguous(self) -> Tuple[Union[bytes, memoryview], np.ndarray]:
        """(buffer, offsets) with the buffer trimmed to exactly the message
        window and offsets rebased to start at 0 — the form the native
        *_buf calls consume."""
        base = int(self.offsets[0])
        end = int(self.offsets[-1])
        buf = self.msgs
        if base != 0 or end != len(buf):
            buf = memoryview(buf)[base:end]
        if base == 0:
            return buf, self.offsets
        return buf, self.offsets - base

    def __getitem__(self, key: slice) -> "EntryBlock":
        """Zero-copy sub-block (numpy views + a rebased offset window) —
        how a coalesced job straddles two device batches without
        rebuilding per-signature objects."""
        if not isinstance(key, slice):
            raise TypeError("EntryBlock indexing takes a slice")
        start, stop, step = key.indices(self.n)
        if step != 1:
            raise ValueError("EntryBlock slices must be contiguous")
        o = self.offsets
        base = int(o[start])
        mv = memoryview(self.msgs)[base : int(o[stop])]
        ram = self.ram_hi is not None
        return EntryBlock(
            self.pub[start:stop],
            self.sig[start:stop],
            mv,
            o[start : stop + 1] - base,
            ram_hi=self.ram_hi[start:stop] if ram else None,
            ram_lo=self.ram_lo[start:stop] if ram else None,
            ram_counts=self.ram_counts[start:stop] if ram else None,
            val_idx=(
                self.val_idx[start:stop] if self.val_idx is not None else None
            ),
            epoch_key=self.epoch_key,
            scheme=self.scheme,
            pub_aux=(
                self.pub_aux[start:stop] if self.pub_aux is not None else None
            ),
        )

    # -- combination --------------------------------------------------------

    @staticmethod
    def concat(blocks: Sequence["EntryBlock"]) -> "EntryBlock":
        """One np.concatenate per column + one msgs join — the coalescing
        pipeline's replacement for per-signature list.extend. A single
        non-empty block passes through BY IDENTITY (no copies at all —
        the common one-commit dispatch)."""
        blocks = [b for b in blocks if len(b)]
        if not blocks:
            return EntryBlock.empty()
        if len(blocks) == 1:
            return blocks[0]
        # scheme discipline (ISSUE 19): unlike epoch_key (which degrades a
        # mixed merge to the uncached prep), a cross-scheme concat has no
        # meaning — the rows would hit the wrong kernel. The mesh packer
        # keys lanes per scheme precisely so this never fires in the
        # dispatch path; a caller-driven mix is a bug, not a fallback.
        scheme = blocks[0].scheme
        if any(b.scheme != scheme for b in blocks):
            raise ValueError("cannot concat mixed-scheme EntryBlocks")
        pub = np.concatenate([b.pub for b in blocks])
        sig = np.concatenate([b.sig for b in blocks])
        msgs = b"".join(b.msgs_contiguous()[0] for b in blocks)
        offsets = np.zeros(len(pub) + 1, dtype=np.int64)
        pos = 0
        base = 0
        for b in blocks:
            buf, o = b.msgs_contiguous()
            offsets[pos + 1 : pos + len(b) + 1] = o[1:] + base
            pos += len(b)
            base += int(o[-1])
        ram_hi = ram_lo = ram_counts = None
        if all(b.ram_hi is not None for b in blocks) and len(
            {b.ram_hi.shape[1] for b in blocks}
        ) == 1:
            ram_hi = np.concatenate([b.ram_hi for b in blocks])
            ram_lo = np.concatenate([b.ram_lo for b in blocks])
            ram_counts = np.concatenate([b.ram_counts for b in blocks])
        # epoch metadata survives only a SAME-epoch merge: gather indices
        # are rows of one valset's device table, so a mixed-key concat
        # (the coalescer's mixed-valset fallback) drops to the uncached
        # prep instead of gathering from the wrong table
        val_idx = epoch_key = None
        if (
            blocks[0].epoch_key is not None
            and all(b.epoch_key == blocks[0].epoch_key for b in blocks)
            and all(b.val_idx is not None for b in blocks)
        ):
            epoch_key = blocks[0].epoch_key
            val_idx = np.concatenate([b.val_idx for b in blocks])
        pub_aux = None
        if all(b.pub_aux is not None for b in blocks):
            pub_aux = np.concatenate([b.pub_aux for b in blocks])
        return EntryBlock(pub, sig, msgs, offsets,
                          ram_hi=ram_hi, ram_lo=ram_lo,
                          ram_counts=ram_counts,
                          val_idx=val_idx, epoch_key=epoch_key,
                          scheme=scheme, pub_aux=pub_aux)


class AggBlock:
    """Columnar AGGREGATED-commit batch — the BLS12-381 lane's analogue
    of EntryBlock (ISSUE 20). One row is one whole commit, not one
    signature:

        sig     (k, 96) uint8   aggregated G2 signatures (compressed)
        bits    (k, v)  bool    signer bitmap rows over ONE committee
        msgs    bytes           all sign-bytes concatenated (one per row)
        offsets (k+1,)  int64   msgs[offsets[i]:offsets[i+1]] is row i
        pub48   (v, 48) uint8   the committee's compressed G1 pubkeys —
                                a host snapshot carried so a cold/evicted
                                epoch can still build kernel tables
        is_pad  (k,)    bool    mesh padding rows (verdicts discarded)

    Unlike EntryBlock there is no val_idx column: the bitmap IS the
    committee reference, so `epoch_key` (ValidatorSet.hash()) is ALWAYS
    set — the mesh packer keys lanes on it, which is what guarantees two
    different committees' bitmaps never share a device launch. Pad
    blocks are committee-free (bits width 0) and adopt the committee of
    whatever non-pad block they are concatenated with."""

    __slots__ = ("sig", "bits", "msgs", "offsets", "pub48", "is_pad",
                 "epoch_key", "scheme", "val_idx")

    def __init__(self, sig: np.ndarray, bits: np.ndarray,
                 msgs: Union[bytes, memoryview], offsets: np.ndarray,
                 pub48: np.ndarray, epoch_key: bytes,
                 is_pad: "np.ndarray" = None):
        k = sig.shape[0]
        if sig.shape != (k, 96):
            raise ValueError("sig must be (k, 96) uint8")
        if bits.ndim != 2 or bits.shape[0] != k:
            raise ValueError("bits must be (k, v) bool")
        if offsets.shape != (k + 1,):
            raise ValueError("offsets must be (k+1,)")
        if k and bool((np.diff(offsets) < 0).any()):
            raise ValueError("offsets must be non-decreasing")
        if pub48.shape != (bits.shape[1], 48):
            raise ValueError("pub48 must be (v, 48) matching bits width")
        self.sig = sig
        self.bits = bits
        self.msgs = msgs
        self.offsets = offsets
        self.pub48 = pub48
        self.epoch_key = epoch_key
        if is_pad is None:
            is_pad = np.zeros(k, dtype=bool)
        elif is_pad.shape != (k,):
            raise ValueError("is_pad must be (k,)")
        self.is_pad = is_pad
        self.scheme = "bls12381"
        self.val_idx = None  # epoch_cache.lookup() bypass: bitmap-indexed

    # -- construction -------------------------------------------------------

    @classmethod
    def from_commits(cls, commits, pub48: np.ndarray,
                     epoch_key: bytes) -> "AggBlock":
        """[(bits_bool_row, sign_bytes, sig96), ...] over one committee."""
        k = len(commits)
        v = pub48.shape[0]
        if k == 0:
            return cls(np.zeros((0, 96), dtype=np.uint8),
                       np.zeros((0, v), dtype=bool), b"", _EMPTY_OFFSETS,
                       pub48, epoch_key)
        sig = np.frombuffer(
            b"".join(s for _, _, s in commits), dtype=np.uint8
        ).reshape(k, 96)
        bits = np.stack([np.asarray(b, dtype=bool) for b, _, _ in commits])
        lens = np.fromiter((len(m) for _, m, _ in commits), dtype=np.int64,
                           count=k)
        offsets = np.zeros(k + 1, dtype=np.int64)
        np.cumsum(lens, out=offsets[1:])
        msgs = b"".join(m for _, m, _ in commits)
        return cls(sig, bits, msgs, offsets, pub48, epoch_key)

    @classmethod
    def pad(cls, n: int) -> "AggBlock":
        """Committee-free padding rows (bits width 0; the backend preps
        pads from its fixed self-signed pad commit, not from the bitmap).
        epoch_key None: mesh pad blocks are built per lane AFTER packing,
        so they concat-adopt the lane's key/committee."""
        return cls(
            np.zeros((n, 96), dtype=np.uint8),
            np.zeros((n, 0), dtype=bool),
            b"",
            np.zeros(n + 1, dtype=np.int64),
            np.zeros((0, 48), dtype=np.uint8),
            None,
            is_pad=np.ones(n, dtype=bool),
        )

    # -- shape / access -----------------------------------------------------

    @property
    def n(self) -> int:
        return self.sig.shape[0]

    def __len__(self) -> int:
        return self.sig.shape[0]

    def msg_nbytes(self) -> int:
        return int(self.offsets[-1] - self.offsets[0])

    def msg(self, i: int) -> bytes:
        o = self.offsets
        return bytes(memoryview(self.msgs)[int(o[i]) : int(o[i + 1])])

    def msgs_contiguous(self):
        base = int(self.offsets[0])
        end = int(self.offsets[-1])
        buf = self.msgs
        if base != 0 or end != len(buf):
            buf = memoryview(buf)[base:end]
        if base == 0:
            return buf, self.offsets
        return buf, self.offsets - base

    def __getitem__(self, key: slice) -> "AggBlock":
        if not isinstance(key, slice):
            raise TypeError("AggBlock indexing takes a slice")
        start, stop, step = key.indices(self.n)
        if step != 1:
            raise ValueError("AggBlock slices must be contiguous")
        o = self.offsets
        base = int(o[start])
        mv = memoryview(self.msgs)[base : int(o[stop])]
        return AggBlock(
            self.sig[start:stop],
            self.bits[start:stop],
            mv,
            o[start : stop + 1] - base,
            self.pub48,
            self.epoch_key,
            is_pad=self.is_pad[start:stop],
        )

    # -- combination --------------------------------------------------------

    @staticmethod
    def concat(blocks: Sequence["AggBlock"]) -> "AggBlock":
        """Same one-concatenate-per-column discipline as EntryBlock. The
        committee comes from the non-pad blocks, which must AGREE (the
        mesh keys agg lanes on epoch_key, so a mixed-committee concat is
        a caller bug); width-0 pad blocks adopt it."""
        blocks = [b for b in blocks if len(b)]
        if not blocks:
            raise ValueError("cannot concat zero aggregated rows")
        if len(blocks) == 1:
            return blocks[0]
        live = [b for b in blocks if b.epoch_key is not None]
        if live:
            epoch_key = live[0].epoch_key
            pub48 = live[0].pub48
            if any(b.epoch_key != epoch_key for b in live):
                raise ValueError("cannot concat mixed-committee AggBlocks")
        else:  # all-pad merge keeps the committee-free form
            epoch_key = None
            pub48 = blocks[0].pub48
        v = pub48.shape[0]
        bits = np.zeros((sum(len(b) for b in blocks), v), dtype=bool)
        pos = 0
        for b in blocks:
            if b.bits.shape[1]:
                bits[pos : pos + len(b)] = b.bits
            pos += len(b)
        sig = np.concatenate([b.sig for b in blocks])
        is_pad = np.concatenate([b.is_pad for b in blocks])
        msgs = b"".join(b.msgs_contiguous()[0] for b in blocks)
        offsets = np.zeros(len(sig) + 1, dtype=np.int64)
        pos = 0
        base = 0
        for b in blocks:
            _, o = b.msgs_contiguous()
            offsets[pos + 1 : pos + len(b) + 1] = o[1:] + base
            pos += len(b)
            base += int(o[-1])
        return AggBlock(sig, bits, msgs, offsets, pub48, epoch_key,
                        is_pad=is_pad)


def block_concat(blocks):
    """Type-dispatched concat for the mesh/pipeline coalescers: a lane is
    homogeneous (EntryBlocks or AggBlocks, never both — scheme-keyed
    packing), but the CALLER is generic over lanes."""
    blocks = list(blocks)
    if blocks and isinstance(blocks[0], AggBlock):
        return AggBlock.concat(blocks)
    return EntryBlock.concat(blocks)


class CommitBlock:
    """Columnar commit-signature representation — populated ONCE at wire
    decode (types/block.py Commit.decode) so the verify hot path never
    walks per-signature CommitSig objects. The CommitSig objects the
    `commit.signatures` API exposes are LAZY VIEWS over these columns
    (types/block.py CommitSigs), not the source of truth:

        flags      (n,)    uint8   BlockIDFlag per signature
        val_idx    (n,)    int32   validator index (signature order)
        sig        (n, 64) uint8   signatures; absent lanes all-zero
        ts_seconds (n,)    int64   vote timestamp seconds
        ts_nanos   (n,)    int32   vote timestamp nanos
        addr       (n, 20) uint8   validator addresses; absent lanes zero

    Construction invariant (enforced by the builders in types/block.py):
    every lane matches the canonical CommitSig shape — absent lanes have
    no address/signature and the Go zero timestamp, non-absent lanes
    carry a 20-byte address and exactly 64 signature bytes, and flags are
    one of {ABSENT, COMMIT, NIL}. A commit violating that decodes to
    plain CommitSig objects instead (no CommitBlock), so the object path
    keeps raising exactly the errors it always raised."""

    __slots__ = ("flags", "val_idx", "sig", "ts_seconds", "ts_nanos", "addr")

    def __init__(self, flags: np.ndarray, val_idx: np.ndarray,
                 sig: np.ndarray, ts_seconds: np.ndarray,
                 ts_nanos: np.ndarray, addr: np.ndarray):
        n = flags.shape[0]
        if (
            sig.shape != (n, 64) or addr.shape != (n, 20)
            or val_idx.shape != (n,) or ts_seconds.shape != (n,)
            or ts_nanos.shape != (n,)
        ):
            raise ValueError("CommitBlock column shapes disagree")
        self.flags = flags
        self.val_idx = val_idx
        self.sig = sig
        self.ts_seconds = ts_seconds
        self.ts_nanos = ts_nanos
        self.addr = addr

    @property
    def n(self) -> int:
        return self.flags.shape[0]

    def __len__(self) -> int:
        return self.flags.shape[0]


EntriesLike = Union[EntryBlock, Sequence[Entry]]


def as_block(entries: EntriesLike) -> EntryBlock:
    """Normalize the public tuple-list API onto the columnar form."""
    if isinstance(entries, (EntryBlock, AggBlock)):
        return entries
    return EntryBlock.from_entries(list(entries))
