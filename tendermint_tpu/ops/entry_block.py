"""Columnar signature-batch representation — the zero-copy commit prep.

PERF_r05: the RLC kernel sustains ~476k sigs/s but end-to-end
types.verify_commit peaked at 143k because the host path between
verify_commit and the kernel was built from per-signature Python objects:
a (pub32, msg, sig64) tuple per lane, PyBytes sign-bytes, and b"".join
re-copies in every prep stage — all GIL-held, so under concurrent commits
the orchestration language (not the device) was the binding constraint.

An EntryBlock carries one commit's (or one coalesced device batch's)
signatures as contiguous columnar buffers built ONCE and handed by
reference:

    pub     (n, 32) uint8   public keys, row per signature
    sig     (n, 64) uint8   signatures (R || s)
    msgs    bytes/memoryview  all sign-bytes concatenated
    offsets (n+1,) int64    msgs[offsets[i]:offsets[i+1]] is message i

Downstream consumers (ops.backend prepare_batch*, ops.pallas_verify
prepare_compact, ops.pallas_rlc prepare_rlc, the async pipeline's
coalescer) slice these arrays directly: no per-signature Python objects
are created between commit selection and the kernel argument arrays, and
batch concatenation is np.concatenate instead of list-extend. The
tuple-list API everywhere remains a thin shim over `as_block`.
"""

from __future__ import annotations

from typing import Iterator, List, Sequence, Tuple, Union

import numpy as np

Entry = Tuple[bytes, bytes, bytes]

_EMPTY_OFFSETS = np.zeros(1, dtype=np.int64)


class EntryBlock:
    """Columnar (pub, msg, sig) batch; see module docstring."""

    __slots__ = ("pub", "sig", "msgs", "offsets")

    def __init__(self, pub: np.ndarray, sig: np.ndarray,
                 msgs: Union[bytes, memoryview], offsets: np.ndarray):
        n = pub.shape[0]
        if pub.shape != (n, 32) or sig.shape != (n, 64):
            raise ValueError("pub must be (n, 32) and sig (n, 64) uint8")
        if offsets.shape != (n + 1,):
            raise ValueError("offsets must be (n+1,)")
        # monotonicity is load-bearing: downstream native code derives
        # per-message lengths as offsets[i+1]-offsets[i] in GIL-released
        # C, where a negative difference wraps to a huge size_t
        if n and bool((np.diff(offsets) < 0).any()):
            raise ValueError("offsets must be non-decreasing")
        self.pub = pub
        self.sig = sig
        self.msgs = msgs
        self.offsets = offsets

    # -- construction -------------------------------------------------------

    @classmethod
    def empty(cls) -> "EntryBlock":
        return cls(
            np.zeros((0, 32), dtype=np.uint8),
            np.zeros((0, 64), dtype=np.uint8),
            b"",
            _EMPTY_OFFSETS,
        )

    @classmethod
    def from_entries(cls, entries: Sequence[Entry]) -> "EntryBlock":
        """Tuple-list shim: one validation pass + two joins, the same cost
        the old per-batch _pack_rows paid — conversion happens once at the
        API boundary instead of in every downstream stage."""
        n = len(entries)
        if n == 0:
            return cls.empty()
        if any(len(pk) != 32 or len(s) != 64 for pk, _, s in entries):
            raise ValueError("entries must be (pub32, msg, sig64) triples")
        pub = np.frombuffer(
            b"".join(pk for pk, _, _ in entries), dtype=np.uint8
        ).reshape(n, 32)
        sig = np.frombuffer(
            b"".join(s for _, _, s in entries), dtype=np.uint8
        ).reshape(n, 64)
        lens = np.fromiter((len(m) for _, m, _ in entries), dtype=np.int64,
                           count=n)
        offsets = np.zeros(n + 1, dtype=np.int64)
        np.cumsum(lens, out=offsets[1:])
        msgs = b"".join(m for _, m, _ in entries)
        return cls(pub, sig, msgs, offsets)

    # -- shape --------------------------------------------------------------

    @property
    def n(self) -> int:
        return self.pub.shape[0]

    def __len__(self) -> int:
        return self.pub.shape[0]

    def msg_nbytes(self) -> int:
        return int(self.offsets[-1] - self.offsets[0])

    # -- access -------------------------------------------------------------

    def msg(self, i: int) -> bytes:
        o = self.offsets
        return bytes(memoryview(self.msgs)[int(o[i]) : int(o[i + 1])])

    def entry(self, i: int) -> Entry:
        """Materialize ONE (pub32, msg, sig64) tuple — the blame path's
        per-lane re-verify, not a bulk conversion."""
        return self.pub[i].tobytes(), self.msg(i), self.sig[i].tobytes()

    def iter_entries(self) -> Iterator[Entry]:
        for i in range(self.n):
            yield self.entry(i)

    def to_entries(self) -> List[Entry]:
        return list(self.iter_entries())

    def msg_views(self) -> List[memoryview]:
        """Per-message zero-copy views (hashlib and the native sequence
        APIs both accept memoryview)."""
        mv = memoryview(self.msgs)
        o = self.offsets
        return [mv[int(o[i]) : int(o[i + 1])] for i in range(self.n)]

    def msgs_contiguous(self) -> Tuple[Union[bytes, memoryview], np.ndarray]:
        """(buffer, offsets) with the buffer trimmed to exactly the message
        window and offsets rebased to start at 0 — the form the native
        *_buf calls consume."""
        base = int(self.offsets[0])
        end = int(self.offsets[-1])
        buf = self.msgs
        if base != 0 or end != len(buf):
            buf = memoryview(buf)[base:end]
        if base == 0:
            return buf, self.offsets
        return buf, self.offsets - base

    def __getitem__(self, key: slice) -> "EntryBlock":
        """Zero-copy sub-block (numpy views + a rebased offset window) —
        how a coalesced job straddles two device batches without
        rebuilding per-signature objects."""
        if not isinstance(key, slice):
            raise TypeError("EntryBlock indexing takes a slice")
        start, stop, step = key.indices(self.n)
        if step != 1:
            raise ValueError("EntryBlock slices must be contiguous")
        o = self.offsets
        base = int(o[start])
        mv = memoryview(self.msgs)[base : int(o[stop])]
        return EntryBlock(
            self.pub[start:stop],
            self.sig[start:stop],
            mv,
            o[start : stop + 1] - base,
        )

    # -- combination --------------------------------------------------------

    @staticmethod
    def concat(blocks: Sequence["EntryBlock"]) -> "EntryBlock":
        """One np.concatenate per column + one msgs join — the coalescing
        pipeline's replacement for per-signature list.extend."""
        blocks = [b for b in blocks if len(b)]
        if not blocks:
            return EntryBlock.empty()
        if len(blocks) == 1:
            return blocks[0]
        pub = np.concatenate([b.pub for b in blocks])
        sig = np.concatenate([b.sig for b in blocks])
        msgs = b"".join(b.msgs_contiguous()[0] for b in blocks)
        offsets = np.zeros(len(pub) + 1, dtype=np.int64)
        pos = 0
        base = 0
        for b in blocks:
            buf, o = b.msgs_contiguous()
            offsets[pos + 1 : pos + len(b) + 1] = o[1:] + base
            pos += len(b)
            base += int(o[-1])
        return EntryBlock(pub, sig, msgs, offsets)


EntriesLike = Union[EntryBlock, Sequence[Entry]]


def as_block(entries: EntriesLike) -> EntryBlock:
    """Normalize the public tuple-list API onto the columnar form."""
    if isinstance(entries, EntryBlock):
        return entries
    return EntryBlock.from_entries(list(entries))
