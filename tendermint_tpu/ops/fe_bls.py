"""Field arithmetic over the BLS12-381 base prime (381 bits) in 11-bit
limbs, for TPU/XLA.

Same conventions as fe.py / fe_secp.py (ISSUE 20: the BLS12-381 lane of
the device verification engine): an element is an int32 array (..., 36)
of limbs, shape-polymorphic over leading batch dims, signed limbs with
lazy canonicalization. The prime's SHAPE forces two departures:

- The radix drops to 11 (not 13, and not the 26 the issue sketch named:
  a 26-bit-limb convolution would need 52-bit products — int32 einsums
  top out at 13-bit limbs even for sparse primes). p is GENERIC — no
  sparse 2^k +- tiny form — so the carry-time top wrap adds the FULL
  limb vector of W = 2^396 mod p (every limb up to 2047) once per unit
  of top carry. The resting reduced form is therefore |limb| <~ 4200,
  and the convolution bound is NLIMBS * (2*4608)^2 for products of
  doubled limbs — at radix 13/30 limbs that is 8.6e9 (overflow); at
  radix 11/36 limbs it is 36 * 4608^2 = 7.6e8, comfortably int32.
  (4608 is the documented reduced bound, derived below with margin.)
- Reduction after a multiply cannot fold through two or three sparse
  wrap constants: the high convolution coefficients fold through a
  precomputed (36, 36) matrix FOLD[k-36] = limbs(2^(11k) mod p) in one
  einsum.

Capacity is 36*11 = 396 bits, 15 bits above p. That headroom is what
makes the generic wrap converge fast: W = 2^396 mod p < p < 2^381, so
W's limb 35 is ZERO and limb 34 is < 2^7 — a carry out of the top limb
never feeds the top limb back, and the secondary feed (limb 34) is
small, so three parallel passes reach the resting state from any
|limb| < 1.7e8 (bound notes inline).

Invariants:
- "reduced" form (output of carry/add/sub/mul/sq): |limb| <= 4608.
  Worst case seen in practice is ~4200 (2047 residue + one W wrap +
  small shift carry); 4608 is the documented contract with margin, and
  it is what the convolution bound above assumes.
- "canonical" form: limbs in [0, 2^11), value in [0, p). There is NO
  device-side canon: the verify kernel (ops/bls_verify.py) is built so
  nothing on device ever needs a canonical value — projective G1 sums,
  unit-factor-tolerant line evaluations, and final-exponentiation
  residues that the HOST reduces as Python ints. int_from_limbs + % p
  on host is the canonicalizer.
"""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp
from jax import lax

from ..crypto.bls12381 import P

NLIMBS = 36
RADIX = 11
MASK = (1 << RADIX) - 1  # 2047

# Top wrap: 2^396 mod p, a full generic limb vector. W < p < 2^381 means
# limb 35 = 0 and limb 34 < 2^7 (bits 374..380 only) — the contraction
# anchors of the carry analysis.
_W_INT = (1 << (RADIX * NLIMBS)) % P


def limbs_raw(v: int) -> np.ndarray:
    """Nonnegative int < 2^396 -> 36-limb int32 array, NO mod-p reduction."""
    out = np.zeros(NLIMBS, dtype=np.int32)
    for i in range(NLIMBS):
        out[i] = (v >> (RADIX * i)) & MASK
    return out


def limbs_from_int(v: int) -> np.ndarray:
    """Python int -> canonical (mod-p-reduced) 36-limb int32 array."""
    return limbs_raw(v % P)


def int_from_limbs(a) -> int:
    """Limb array (36,) -> Python int (host helper; no mod-p reduction)."""
    a = np.asarray(a, dtype=object)
    return int(sum(int(a[i]) << (RADIX * i) for i in range(NLIMBS)))


# Module constants stay NUMPY (never jnp): a jnp array materialized at
# import time *during an active trace* (lazy import under jit) leaks as a
# tracer; numpy constants are immune (see fe.py).
ZERO = np.zeros(NLIMBS, dtype=np.int32)
ONE = np.asarray(limbs_from_int(1))
W_LIMBS = np.asarray(limbs_raw(_W_INT))
assert W_LIMBS[35] == 0 and W_LIMBS[34] < 128

# Convolution index/mask matrices (fe_secp idiom): 71 output columns.
_k = np.arange(2 * NLIMBS - 1)[:, None]
_i = np.arange(NLIMBS)[None, :]
TOEP_IDX = np.clip(_k - _i, 0, NLIMBS - 1).astype(np.int32)
TOEP_MSK = (((_k - _i) >= 0) & ((_k - _i) < NLIMBS)).astype(np.int32)

# Reduction matrix for the high convolution coefficients: coefficient k
# (36 <= k <= 72) of a wide array has weight 2^(11k); FOLD[k-36] is that
# weight mod p in limbs. Entries <= 2047, so a 37-term fold of ~2250-
# bounded coefficients stays below 37*2250*2047 ~ 1.7e8 (int32-safe).
FOLD = np.stack(
    [limbs_raw(pow(2, RADIX * k, P)) for k in range(NLIMBS, 2 * NLIMBS + 1)]
).astype(np.int32)


def _carry_pass(x):
    """One parallel carry pass: every limb sheds its carry to the next
    limb; the carry out of limb 35 (weight 2^396) wraps through the FULL
    W vector. Contraction: W[35] = 0 means the next pass's top carry
    comes only from limb 34's content (W[34] < 2^7 plus the shifted
    carry), so from |limb| <= 1.7e8 the top carry goes ~8e4 -> ~40 -> 1
    across three passes and every limb lands within |2047 + W[i] + c|
    <= 4608 (the reduced contract)."""
    c = x >> RADIX  # arithmetic shift == floor division (signed-safe)
    r = x & MASK
    top = c[..., NLIMBS - 1 :]
    shift = jnp.concatenate(
        [jnp.zeros_like(top), c[..., : NLIMBS - 1]], axis=-1
    )
    return r + top * W_LIMBS + shift


def carry(x):
    """Propagate carries: (..., 36) int32 with |limb| < 1.7e8 -> reduced
    form. Three passes (bound walk in _carry_pass). The first pass's
    wrap product is the int32 ceiling: (1.7e8 >> 11) * 2047 < 1.7e8."""
    return _carry_pass(_carry_pass(_carry_pass(x)))


def carry2(x):
    """Two-pass carry for small inputs (|limb| < 2^17: sums/differences
    of a few reduced values, mul_small by <= 24). Pass 1 leaves limbs
    <= 2047 + (2^6)*2047 + 2^6; pass 2's top carry is 1 (W[35] = 0) and
    lands the resting bound."""
    return _carry_pass(_carry_pass(x))


def add(a, b):
    return carry2(a + b)


def sub(a, b):
    return carry2(a - b)


def neg(a):
    return carry2(-a)


def _wide_pass(x):
    """One carry pass over a widened coefficient array with NO top wrap
    (callers size the array so the top coefficient's carry is zero)."""
    c = x >> RADIX
    r = x & MASK
    shift = jnp.concatenate([jnp.zeros_like(c[..., :1]), c[..., :-1]], axis=-1)
    return r + shift


def mul(a, b):
    """Field multiply: 71-coefficient limb convolution, two wide passes,
    one matrix fold, one 3-pass carry.

    Bounds: conv coefficients < 36 * 4608^2 = 7.7e8 (reduced inputs).
    Width 73 holds the worst reduced product: a reduced VALUE reaches
    4608/2047 * 2^396 ~ 2^397.2, so products need 2^794.4 < 2^803.
    Two wide passes shrink coefficients to ~2250; the fold adds
    <= 37*2250*2047 < 1.7e8 onto the low 36, which is exactly carry()'s
    documented domain."""
    bt = jnp.take(b, TOEP_IDX, axis=-1) * TOEP_MSK  # (..., 71, 36)
    c71 = jnp.einsum(
        "...i,...ki->...k", a, bt, preferred_element_type=jnp.int32
    )
    pad = [(0, 0)] * (c71.ndim - 1)
    x = _wide_pass(_wide_pass(jnp.pad(c71, pad + [(0, 2)])))  # width 73
    lo = x[..., :NLIMBS]
    hi = x[..., NLIMBS:]
    return carry(
        lo + jnp.einsum("...h,hl->...l", hi, FOLD,
                        preferred_element_type=jnp.int32)
    )


def sq(a):
    return mul(a, a)


def sqn(a, n: int):
    """n successive squarings; fori_loop above n=4 keeps the trace small."""
    if n <= 4:
        for _ in range(n):
            a = sq(a)
        return a
    return lax.fori_loop(0, n, lambda _, v: sq(v), a)


def mul_small(a, c: int):
    """Multiply by a small constant (|c| <= 24 with reduced input keeps
    limbs under 2^17, carry2's domain)."""
    return carry2(a * c)


def field_to_limbs(vals) -> np.ndarray:
    """Canonical field ints (< 2^384) -> (B, 36) int32 limb rows,
    vectorized through a padded LE byte buffer (secp field_to_limbs
    idiom; 56-byte rows so limb 35's bit window indexes cleanly)."""
    vals = list(vals)
    if not vals:
        return np.zeros((0, NLIMBS), dtype=np.int32)
    buf = b"".join(int(v).to_bytes(56, "little") for v in vals)
    w = np.frombuffer(buf, dtype="<u8").reshape(len(vals), 7)
    out = np.empty((len(vals), NLIMBS), dtype=np.int32)
    for i in range(NLIMBS):
        lo = RADIX * i
        word, shift = lo >> 6, lo & 63
        v = w[:, word] >> np.uint64(shift)
        if shift + RADIX > 64 and word + 1 < 7:
            v = v | (w[:, word + 1] << np.uint64(64 - shift))
        out[:, i] = (v & np.uint64(MASK)).astype(np.int32)
    return out


def f2_rows(vals) -> np.ndarray:
    """[(c0, c1), ...] Fp2 ints -> (B, 2, 36) int32 limb rows."""
    flat = [c for pair in vals for c in pair]
    return field_to_limbs(flat).reshape(-1, 2, NLIMBS)
