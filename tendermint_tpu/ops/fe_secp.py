"""Field arithmetic over GF(2^256 - 2^32 - 977) — secp256k1 — in 13-bit
limbs, for TPU/XLA.

Same limb conventions as `fe.py` (ISSUE 19: the secp256k1 lane of the
device verification engine): an element is an int32 array (..., 20) of
13-bit limbs, shape-polymorphic over leading batch dims, signed limbs
with lazy canonicalization. The differences from GF(2^255 - 19) are all
consequences of the prime's shape:

- The top wrap is NOT a single small constant. 2^260 mod p =
  2^4 * (2^32 + 977) = 2^36 + 15632, which in radix-13 limbs is
  (7440, 1, 1024) at limbs (0, 1, 2). Every carry out of limb 19
  distributes over three low limbs instead of one.
- The wrap coefficient 7440 is ~12x ed25519's 608, so the single-stage
  fold `fe.mul` uses (hi split 13-bit, scale, add to lo) would overflow
  int32: hi_hi * 7440 alone reaches ~1.7e9 on top of a ~1.8e9 lo term.
  `mul` here instead carries the 39-coefficient convolution in place
  first (no wrap, widths 41 -> coefficients < 2^13.01), then folds the
  top coefficients through the (7440, 1, 1024) pattern twice. The extra
  carry passes are element-wise shifts; the einsum still dominates.
- Canonicalization folds at the 2^256 boundary (mid-limb-19: bit 9),
  since 2^256 ≡ 2^32 + 977 gives a two-term sparse fold (977 at limb 0,
  64 at limb 2).

Invariants (re-derived for this prime; see the bound notes inline):
- "reduced" form (output of carry/add/sub/mul/sq): limb 0 in
  (-15632, 15632], limb 1 in (-8223, 8223], limb 2 in (-9246, 9246],
  limbs 3..19 in (-8198, 8198]. Safe as input to any op here: the worst
  convolution coefficient is bounded by 2*15632*9252 + 18*9252^2
  < 1.84e9 < 2^31.
- "canonical" form (output of canon): limbs in [0, 2^13), value in [0, p).
"""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp
from jax import lax

NLIMBS = 20
RADIX = 13
MASK = (1 << RADIX) - 1  # 8191

P = 2**256 - 2**32 - 977

# 2^260 mod p = 2^36 + 15632, distributed over limbs 0..2.
_WRAP0 = 7440  # 15632 & 8191
_WRAP1 = 1  # 15632 >> 13
_WRAP2 = 1024  # 2^36 = 2^(13*2 + 10)

# 2^256 mod p = 2^32 + 977: the canon-time fold constants. 2^32 sits at
# bit 6 of limb 2 (32 = 13*2 + 6).
_FOLD0 = 977
_FOLD2 = 64


def limbs_raw(v: int) -> np.ndarray:
    """Nonnegative int < 2^260 -> 20-limb int32 array, NO mod-p reduction."""
    out = np.zeros(NLIMBS, dtype=np.int32)
    for i in range(NLIMBS):
        out[i] = (v >> (RADIX * i)) & MASK
    return out


def limbs_from_int(v: int) -> np.ndarray:
    """Python int -> canonical (mod-p-reduced) 20-limb int32 array."""
    return limbs_raw(v % P)


def int_from_limbs(a) -> int:
    """Limb array (20,) -> Python int (host helper; no mod-p reduction)."""
    a = np.asarray(a, dtype=object)
    return int(sum(int(a[i]) << (RADIX * i) for i in range(NLIMBS)))


# Module constants stay NUMPY (never jnp): a jnp array materialized at import
# time *during an active trace* (lazy import under jit) leaks as a tracer;
# numpy constants are immune and jit constant-folds them the same way.
ZERO = np.zeros(NLIMBS, dtype=np.int32)
ONE = np.asarray(limbs_from_int(1))
P_LIMBS = np.asarray(limbs_raw(P))  # limbs of p itself (NOT reduced!)

# 16p in radix-13 limbs (fits: 16p < 2^260). Added before canonicalization
# so possibly-negative reduced values become positive: a reduced value is
# > -2^253 (the masked residues are nonnegative; only the ~30-bounded
# carries of the final pass contribute negatively), and 16p > 2^259.9.
P16_LIMBS = np.asarray(limbs_raw(16 * P))

# Convolution index/mask matrices: TOEP_IDX[k, i] = k - i (clipped),
# TOEP_MSK[k, i] = 1 iff 0 <= k - i < NLIMBS.
_k = np.arange(2 * NLIMBS - 1)[:, None]
_i = np.arange(NLIMBS)[None, :]
TOEP_IDX = np.clip(_k - _i, 0, NLIMBS - 1).astype(np.int32)
TOEP_MSK = (((_k - _i) >= 0) & ((_k - _i) < NLIMBS)).astype(np.int32)


def _carry_pass(x):
    """One parallel carry pass: every limb sheds its carry to the next limb
    simultaneously; the carry out of limb 19 (weight 2^260) wraps into
    limbs 0..2 with the (7440, 1, 1024) pattern. Three passes land every
    limb within the reduced-form bounds above: starting from |limb| < 2^31,
    the carries contract 2^18 -> ~2.4e5 -> ~30 -> ~1, and the resting
    state keeps limb 0 below 8191 + 1*7440 and limb 2 below
    8191 + 30 + 1024."""
    c = x >> RADIX  # arithmetic shift == floor division (signed-safe)
    r = x & MASK
    top = c[..., NLIMBS - 1 :]
    wrap = jnp.concatenate(
        [top * _WRAP0, top * _WRAP1, top * _WRAP2,
         jnp.zeros_like(c[..., : NLIMBS - 3])],
        axis=-1,
    )
    shift = jnp.concatenate(
        [jnp.zeros_like(top), c[..., : NLIMBS - 1]], axis=-1
    )
    return r + wrap + shift


def carry(x):
    """Propagate carries: (..., 20) int32 with |limb| < 2^31 -> reduced form.

    Three parallel passes, like fe.carry; the secp wrap feeds three limbs
    per pass but the contraction argument is the same (bounds in the
    _carry_pass docstring)."""
    return _carry_pass(_carry_pass(_carry_pass(x)))


def add(a, b):
    return carry(a + b)


def sub(a, b):
    return carry(a - b)


def neg(a):
    return carry(-a)


def _wide_pass(x):
    """One carry pass over a widened coefficient array with NO top wrap:
    the top coefficient simply accumulates (callers size the array so the
    value fits)."""
    c = x >> RADIX
    r = x & MASK
    shift = jnp.concatenate([jnp.zeros_like(c[..., :1]), c[..., :-1]], axis=-1)
    return r + shift


def _fold_top(x, width_out: int):
    """Fold coefficients >= 20 of a carried wide array through
    2^260 ≡ 2^36 + 15632: coefficient k contributes (7440, 1, 1024) at
    positions (k-20, k-19, k-18). Requires |coeff| <~ 2^13.01 (post
    _wide_pass x2), so every product stays below ~8230 * 7440 < 7e7."""
    lo = x[..., :NLIMBS]
    hi = x[..., NLIMBS:]
    nhi = hi.shape[-1]
    pad = [(0, 0)] * (x.ndim - 1)

    def _at(v, off):
        # place hi coefficient j's contribution at position j + off, in a
        # width_out array
        return jnp.pad(v, pad + [(off, width_out - nhi - off)])

    out = jnp.pad(lo, pad + [(0, width_out - NLIMBS)])
    out = out + _WRAP0 * _at(hi, 0) + _WRAP1 * _at(hi, 1) + _WRAP2 * _at(hi, 2)
    return out


def mul(a, b):
    """Field multiply: 39-coefficient limb convolution, in-place wide
    carry, then two fold-and-carry rounds through the 2^260 wrap.

    Bounds: conv coefficients < 1.84e9 (reduced-form inputs). Two wide
    passes over width 41 (p^2 < 2^512 < 2^13*41) shrink them below 8225.
    Fold A lands positions 20..40 into a width-23 array with |coeff|
    < 8225 * (7440 + 1 + 1024) + 8225 < 7e7; two more wide passes over
    width 25 (the folded value is < 2^313) shrink again, and fold B
    (positions 20..24, no spill past limb 6) leaves |limb| < 7e7 for the
    final 3-pass carry into reduced form."""
    bt = jnp.take(b, TOEP_IDX, axis=-1) * TOEP_MSK  # (..., 39, 20)
    c39 = jnp.einsum(
        "...i,...ki->...k", a, bt, preferred_element_type=jnp.int32
    )
    pad = [(0, 0)] * (c39.ndim - 1)
    x = jnp.pad(c39, pad + [(0, 2)])  # width 41
    x = _wide_pass(_wide_pass(x))
    x = _fold_top(x, 25)  # width 25 ( > 2^313 capacity)
    x = _wide_pass(_wide_pass(x))
    x = _fold_top(x, NLIMBS)  # positions 20..24 -> limbs 0..6
    return carry(x)


def sq(a):
    return mul(a, a)


def sqn(a, n: int):
    """n successive squarings; uses fori_loop so the trace stays small."""
    if n <= 4:
        for _ in range(n):
            a = sq(a)
        return a
    return lax.fori_loop(0, n, lambda _, v: sq(v), a)


def mul_small(a, c: int):
    """Multiply by a small constant (|c| * 15632 must fit int32 headroom)."""
    return carry(a * c)


def invert(z):
    """z^(p-2) (host-side utility / completeness; the verify kernel itself
    compares projectively and never inverts). p - 2 =
    2^256 - 2^32 - 979; chain: build z^(2^223-1) like the standard
    libsecp256k1 ladder, then stitch the sparse low word."""
    # p - 2 = 0xFFFF...FFFE FFFFFC2D: 223 ones, then bits of 0xFFFFFC2D
    x1 = z
    x2 = mul(sqn(x1, 1), x1)  # 2 ones
    x3 = mul(sqn(x2, 1), x1)  # 3 ones
    x6 = mul(sqn(x3, 3), x3)
    x9 = mul(sqn(x6, 3), x3)
    x11 = mul(sqn(x9, 2), x2)
    x22 = mul(sqn(x11, 11), x11)
    x44 = mul(sqn(x22, 22), x22)
    x88 = mul(sqn(x44, 44), x44)
    x176 = mul(sqn(x88, 88), x88)
    x220 = mul(sqn(x176, 44), x44)
    x223 = mul(sqn(x220, 3), x3)
    # tail: (x223 << 23) | 0x2D... follow the exponent bits of 0xFFFFFC2D
    t = sqn(x223, 23)
    t = mul(t, x22)  # low 23 bits of p-2 are 0b111_1100_0010_1101 padded:
    t = sqn(t, 5)  # 0xFFFFFC2D = ...111111111111111111111100_00101101
    t = mul(t, x1)
    t = sqn(t, 3)
    t = mul(t, x2)
    t = sqn(t, 2)
    return mul(t, x1)


def _fold256(x):
    """Fold bits >= 2^256 down (2^256 ≡ 2^32 + 977): sequential carry
    chain, extract q = bits >= 256 from limb 19 (bit 9 up), re-add
    q*977 at limb 0 and q*64 at limb 2, re-chain. Requires a nonnegative
    value < ~2^262; output limbs in [0, 2^13), value < 2^256 + q*2^33."""
    parts = [x[..., i] for i in range(NLIMBS)]
    out = []
    c = jnp.zeros_like(parts[0])
    for i in range(NLIMBS):
        t = parts[i] + c
        c = t >> RADIX
        out.append(t & MASK)
    top = out[NLIMBS - 1] + (c << RADIX)  # exact bits 247.. of the value
    q = top >> 9  # bits >= 2^256
    out[NLIMBS - 1] = top & 0x1FF
    out[0] = out[0] + q * _FOLD0
    out[2] = out[2] + q * _FOLD2
    res = []
    c = jnp.zeros_like(out[0])
    for i in range(NLIMBS):
        t = out[i] + c
        c = t >> RADIX
        res.append(t & MASK)
    res[NLIMBS - 1] = res[NLIMBS - 1] + (c << RADIX)  # c is 0 by bounds
    return jnp.stack(res, axis=-1)


def _cond_sub(x, const_limbs):
    """x - const if x >= const else x (both nonneg canonical-ish limbs)."""
    d = x - const_limbs
    out = []
    c = jnp.zeros_like(x[..., 0])
    for i in range(NLIMBS):
        t = d[..., i] + c
        c = t >> RADIX
        out.append(t & MASK)
    t = jnp.stack(out, axis=-1)
    keep = (c < 0)[..., None]  # borrow out -> x < const
    return jnp.where(keep, x, t)


def canon(x):
    """Fully canonicalize: reduced form -> limbs in [0, 2^13), value in
    [0, p). The +16p makes the value strictly positive (reduced values
    are > -2^253; 16p > 2^259.9) without leaving the 20-limb range
    (16p + |value| < 2^262, within _fold256's domain)."""
    x = carry(x)
    x = x + P16_LIMBS
    x = _fold256(x)
    x = _fold256(x)  # value now < 2^256 + eps < 2p
    x = _cond_sub(x, P_LIMBS)
    x = _cond_sub(x, P_LIMBS)
    return x


def is_zero(x):
    """(...,) bool: value ≡ 0 (mod p)."""
    return jnp.all(canon(x) == 0, axis=-1)


def eq(a, b):
    return is_zero(a - b)


def parity(x):
    """Canonical low bit (the SEC1 compressed-point sign bit)."""
    return canon(x)[..., 0] & 1
