"""Batched SHA-512 on device (SURVEY.md §7 hard-part #2).

The ed25519 challenge hash k = SHA512(R || A || M) runs on 32-bit lanes:
each 64-bit word is an (hi, lo) uint32 pair; rotations and the Σ/σ
schedules decompose into 32-bit shifts with cross-word carries; additions
ripple the carry via an unsigned compare. Messages are host-padded into
fixed NBLOCK buffers; a per-message block count selects the right digest
state from the scanned per-block states (branchless variable length).

Matches hashlib.sha512 bit-for-bit (differentially tested).
"""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp
from jax import lax

# Round constants (FIPS 180-4) as (hi, lo) uint32 pairs.
_K = [
    0x428A2F98D728AE22, 0x7137449123EF65CD, 0xB5C0FBCFEC4D3B2F, 0xE9B5DBA58189DBBC,
    0x3956C25BF348B538, 0x59F111F1B605D019, 0x923F82A4AF194F9B, 0xAB1C5ED5DA6D8118,
    0xD807AA98A3030242, 0x12835B0145706FBE, 0x243185BE4EE4B28C, 0x550C7DC3D5FFB4E2,
    0x72BE5D74F27B896F, 0x80DEB1FE3B1696B1, 0x9BDC06A725C71235, 0xC19BF174CF692694,
    0xE49B69C19EF14AD2, 0xEFBE4786384F25E3, 0x0FC19DC68B8CD5B5, 0x240CA1CC77AC9C65,
    0x2DE92C6F592B0275, 0x4A7484AA6EA6E483, 0x5CB0A9DCBD41FBD4, 0x76F988DA831153B5,
    0x983E5152EE66DFAB, 0xA831C66D2DB43210, 0xB00327C898FB213F, 0xBF597FC7BEEF0EE4,
    0xC6E00BF33DA88FC2, 0xD5A79147930AA725, 0x06CA6351E003826F, 0x142929670A0E6E70,
    0x27B70A8546D22FFC, 0x2E1B21385C26C926, 0x4D2C6DFC5AC42AED, 0x53380D139D95B3DF,
    0x650A73548BAF63DE, 0x766A0ABB3C77B2A8, 0x81C2C92E47EDAEE6, 0x92722C851482353B,
    0xA2BFE8A14CF10364, 0xA81A664BBC423001, 0xC24B8B70D0F89791, 0xC76C51A30654BE30,
    0xD192E819D6EF5218, 0xD69906245565A910, 0xF40E35855771202A, 0x106AA07032BBD1B8,
    0x19A4C116B8D2D0C8, 0x1E376C085141AB53, 0x2748774CDF8EEB99, 0x34B0BCB5E19B48A8,
    0x391C0CB3C5C95A63, 0x4ED8AA4AE3418ACB, 0x5B9CCA4F7763E373, 0x682E6FF3D6B2B8A3,
    0x748F82EE5DEFB2FC, 0x78A5636F43172F60, 0x84C87814A1F0AB72, 0x8CC702081A6439EC,
    0x90BEFFFA23631E28, 0xA4506CEBDE82BDE9, 0xBEF9A3F7B2C67915, 0xC67178F2E372532B,
    0xCA273ECEEA26619C, 0xD186B8C721C0C207, 0xEADA7DD6CDE0EB1E, 0xF57D4F7FEE6ED178,
    0x06F067AA72176FBA, 0x0A637DC5A2C898A6, 0x113F9804BEF90DAE, 0x1B710B35131C471B,
    0x28DB77F523047D84, 0x32CAAB7B40C72493, 0x3C9EBE0A15C9BEBC, 0x431D67C49C100D4C,
    0x4CC5D4BECB3E42B6, 0x597F299CFC657E2A, 0x5FCB6FAB3AD6FAEC, 0x6C44198C4A475817,
]
# numpy, not jnp: trace-immune under lazy import (see ops/fe.py note).
_K_HI = np.asarray([(k >> 32) & 0xFFFFFFFF for k in _K], dtype=np.uint32)
_K_LO = np.asarray([k & 0xFFFFFFFF for k in _K], dtype=np.uint32)

_IV = [
    0x6A09E667F3BCC908, 0xBB67AE8584CAA73B, 0x3C6EF372FE94F82B, 0xA54FF53A5F1D36F1,
    0x510E527FADE682D1, 0x9B05688C2B3E6C1F, 0x1F83D9ABFB41BD6B, 0x5BE0CD19137E2179,
]


def _add64(ah, al, bh, bl):
    lo = al + bl
    carry = (lo < al).astype(jnp.uint32)
    hi = ah + bh + carry
    return hi, lo


def _ror64(h, l, n: int):
    n %= 64
    if n == 0:
        return h, l
    if n < 32:
        nh = (h >> n) | (l << (32 - n))
        nl = (l >> n) | (h << (32 - n))
        return nh, nl
    if n == 32:
        return l, h
    m = n - 32
    nh = (l >> m) | (h << (32 - m))
    nl = (h >> m) | (l << (32 - m))
    return nh, nl


def _shr64(h, l, n: int):
    if n < 32:
        return h >> n, (l >> n) | (h << (32 - n))
    return jnp.zeros_like(h), h >> (n - 32)


def _big_sigma0(h, l):
    a = _ror64(h, l, 28)
    b = _ror64(h, l, 34)
    c = _ror64(h, l, 39)
    return a[0] ^ b[0] ^ c[0], a[1] ^ b[1] ^ c[1]


def _big_sigma1(h, l):
    a = _ror64(h, l, 14)
    b = _ror64(h, l, 18)
    c = _ror64(h, l, 41)
    return a[0] ^ b[0] ^ c[0], a[1] ^ b[1] ^ c[1]


def _small_sigma0(h, l):
    a = _ror64(h, l, 1)
    b = _ror64(h, l, 8)
    c = _shr64(h, l, 7)
    return a[0] ^ b[0] ^ c[0], a[1] ^ b[1] ^ c[1]


def _small_sigma1(h, l):
    a = _ror64(h, l, 19)
    b = _ror64(h, l, 61)
    c = _shr64(h, l, 6)
    return a[0] ^ b[0] ^ c[0], a[1] ^ b[1] ^ c[1]


def _compress_block(state, block_hi, block_lo):
    """One SHA-512 compression: state (8,2)x(B,), block (B,16) hi/lo."""
    # message schedule as a rolling 16-word window inside a fori_loop
    w_hi = block_hi  # (B, 16)
    w_lo = block_lo

    hs = [state[i][0] for i in range(8)]
    ls = [state[i][1] for i in range(8)]
    a_h, b_h, c_h, d_h, e_h, f_h, g_h, h_h = hs
    a_l, b_l, c_l, d_l, e_l, f_l, g_l, h_l = ls

    def round_body(t, carry):
        (a_h, a_l, b_h, b_l, c_h, c_l, d_h, d_l,
         e_h, e_l, f_h, f_l, g_h, g_l, h_h, h_l, w_hi, w_lo) = carry
        idx = t % 16
        wt_h = lax.dynamic_index_in_dim(w_hi, idx, 1, keepdims=False)
        wt_l = lax.dynamic_index_in_dim(w_lo, idx, 1, keepdims=False)

        s1 = _big_sigma1(e_h, e_l)
        ch_h = (e_h & f_h) ^ (~e_h & g_h)
        ch_l = (e_l & f_l) ^ (~e_l & g_l)
        # jnp.asarray inside the trace: the constant is created in the same
        # trace that consumes it (numpy module constants are trace-immune,
        # but numpy can't be indexed by the tracer t directly).
        kt_h = jnp.asarray(_K_HI)[t]
        kt_l = jnp.asarray(_K_LO)[t]
        t1 = _add64(h_h, h_l, *s1)
        t1 = _add64(*t1, ch_h, ch_l)
        t1 = _add64(*t1, jnp.broadcast_to(kt_h, h_h.shape), jnp.broadcast_to(kt_l, h_l.shape))
        t1 = _add64(*t1, wt_h, wt_l)
        s0 = _big_sigma0(a_h, a_l)
        maj_h = (a_h & b_h) ^ (a_h & c_h) ^ (b_h & c_h)
        maj_l = (a_l & b_l) ^ (a_l & c_l) ^ (b_l & c_l)
        t2 = _add64(*s0, maj_h, maj_l)

        new_e = _add64(d_h, d_l, *t1)
        new_a = _add64(*t1, *t2)

        # schedule update for w[t+16]: uses w[t], w[t+1], w[t+9], w[t+14]
        w1_h = lax.dynamic_index_in_dim(w_hi, (t + 1) % 16, 1, keepdims=False)
        w1_l = lax.dynamic_index_in_dim(w_lo, (t + 1) % 16, 1, keepdims=False)
        w9_h = lax.dynamic_index_in_dim(w_hi, (t + 9) % 16, 1, keepdims=False)
        w9_l = lax.dynamic_index_in_dim(w_lo, (t + 9) % 16, 1, keepdims=False)
        w14_h = lax.dynamic_index_in_dim(w_hi, (t + 14) % 16, 1, keepdims=False)
        w14_l = lax.dynamic_index_in_dim(w_lo, (t + 14) % 16, 1, keepdims=False)
        nw = _add64(wt_h, wt_l, *_small_sigma0(w1_h, w1_l))
        nw = _add64(*nw, w9_h, w9_l)
        nw = _add64(*nw, *_small_sigma1(w14_h, w14_l))
        w_hi = lax.dynamic_update_index_in_dim(w_hi, nw[0], idx, 1)
        w_lo = lax.dynamic_update_index_in_dim(w_lo, nw[1], idx, 1)

        return (new_a[0], new_a[1], a_h, a_l, b_h, b_l, c_h, c_l,
                new_e[0], new_e[1], e_h, e_l, f_h, f_l, g_h, g_l, w_hi, w_lo)

    carry = (a_h, a_l, b_h, b_l, c_h, c_l, d_h, d_l,
             e_h, e_l, f_h, f_l, g_h, g_l, h_h, h_l, w_hi, w_lo)
    carry = lax.fori_loop(0, 80, round_body, carry)
    out_vals = carry[:16]
    new_state = []
    for i in range(8):
        nh, nl = _add64(hs[i], ls[i], out_vals[2 * i], out_vals[2 * i + 1])
        new_state.append((nh, nl))
    return new_state


def sha512_blocks(blocks_hi, blocks_lo, n_blocks):
    """Batched SHA-512 over pre-padded messages.

    blocks_hi/lo: (B, NBLOCK, 16) uint32 big-endian word halves.
    n_blocks:     (B,) int32 actual block count per message (>= 1).
    Returns (B, 8, 2) uint32 digest words (hi, lo).
    """
    bsz = blocks_hi.shape[0]
    nblock = blocks_hi.shape[1]
    state = [
        (
            jnp.full((bsz,), (iv >> 32) & 0xFFFFFFFF, dtype=jnp.uint32),
            jnp.full((bsz,), iv & 0xFFFFFFFF, dtype=jnp.uint32),
        )
        for iv in _IV
    ]
    digest_h = jnp.stack([s[0] for s in state], axis=1)  # (B, 8)
    digest_l = jnp.stack([s[1] for s in state], axis=1)

    for blk in range(nblock):
        new_state = _compress_block(
            [(digest_h[:, i], digest_l[:, i]) for i in range(8)],
            blocks_hi[:, blk],
            blocks_lo[:, blk],
        )
        nh = jnp.stack([s[0] for s in new_state], axis=1)
        nl = jnp.stack([s[1] for s in new_state], axis=1)
        # only advance the state for messages that still have blocks left
        active = (n_blocks > blk)[:, None]
        digest_h = jnp.where(active, nh, digest_h)
        digest_l = jnp.where(active, nl, digest_l)

    return jnp.stack([digest_h, digest_l], axis=-1)  # (B, 8, 2)


def pad_messages(msgs, max_len: int):
    """Host-side padding: list of bytes -> (B, NBLOCK, 16) uint32 hi/lo +
    (B,) block counts. max_len bounds the unpadded message length.

    Fully vectorized (one join + scatter) — no per-message Python work, so
    host prep stays a small fraction of end-to-end batch time at 10k sigs
    (SURVEY.md §7 hard-part 3/4)."""
    from .commit_prep import ram_nblock

    nblock = ram_nblock(max_len)
    bsz = len(msgs)
    buf = np.zeros((bsz, nblock * 128), dtype=np.uint8)
    lens = np.fromiter((len(m) for m in msgs), dtype=np.int64, count=bsz)
    if bsz and lens.max(initial=0) > max_len:
        bad = int(lens.max())
        raise ValueError(f"message too long: {bad} > {max_len}")
    blocks = (lens + 17 + 127) // 128
    counts = blocks.astype(np.int32)
    if bsz:
        flat = np.frombuffer(b"".join(msgs), dtype=np.uint8)
        rows = np.repeat(np.arange(bsz), lens)
        offs = np.concatenate(([0], np.cumsum(lens)[:-1]))
        cols = np.arange(lens.sum()) - np.repeat(offs, lens)
        buf[rows, cols] = flat
        rng = np.arange(bsz)
        buf[rng, lens] = 0x80
        bitlen = lens * 8
        base = blocks * 128 - 8
        for j in range(8):
            buf[rng, base + j] = (bitlen >> (8 * (7 - j))) & 0xFF
    return _buf_to_words(buf, bsz, nblock) + (counts,)


def _buf_to_words(buf: np.ndarray, bsz: int, nblock: int):
    """(bsz, nblock*128) uint8 -> big-endian (hi, lo) uint32 word arrays.
    One big-endian view + two strided copies instead of eight shift-or
    passes (~6x at a 10240-row bucket)."""
    words = np.ascontiguousarray(buf).view(">u4").reshape(bsz, nblock, 16, 2)
    return (
        words[..., 0].astype(np.uint32),
        words[..., 1].astype(np.uint32),
    )


def pad_ram_block(block, bucket: int, max_len: int):
    """Columnar device-hash prep: an EntryBlock's R||A||M messages padded
    straight into SHA blocks — (bucket, NBLOCK, 16) uint32 hi/lo + (bucket,)
    block counts, with NO per-message bytes objects (the tuple-list path
    builds sig[:32]+pk+msg per signature; here R and A land as two column
    assigns and the msgs buffer scatters once). Padding lanes carry the
    identity pattern (b"\\x01" + 31 zeros, twice)."""
    from .commit_prep import ram_nblock

    nblock = ram_nblock(max_len)
    n = len(block)
    lens = np.full(bucket, 64, dtype=np.int64)
    buf = np.zeros((bucket, nblock * 128), dtype=np.uint8)
    if n:
        mbuf, offs = block.msgs_contiguous()
        offs = np.asarray(offs)
        mlens = np.diff(offs)
        lens[:n] = 64 + mlens
        if lens.max() > max_len:
            raise ValueError(f"message too long: {int(lens.max())} > {max_len}")
        buf[:n, :32] = block.sig[:, :32]
        buf[:n, 32:64] = block.pub
        total = int(mlens.sum())
        if total:
            from .commit_prep import scatter_rows_by_length

            flat = np.frombuffer(mbuf, dtype=np.uint8, count=total)
            scatter_rows_by_length(buf, 64, flat, offs, mlens)
    buf[n:, 0] = 1
    buf[n:, 32] = 1
    blocks = (lens + 17 + 127) // 128
    rng = np.arange(bucket)
    buf[rng, lens] = 0x80
    bitlen = lens * 8
    base = blocks * 128 - 8
    for j in range(8):
        buf[rng, base + j] = (bitlen >> (8 * (7 - j))) & 0xFF
    return _buf_to_words(buf, bucket, nblock) + (blocks.astype(np.int32),)


_PAD_ROW_CACHE: dict = {}


def _pad_row(max_len: int):
    """The padding lane's (1, nblock, 16) hi/lo words + count — produced
    by pad_ram_block itself on an empty block so the row passthrough is
    bit-identical to the generic path, cached per layout."""
    row = _PAD_ROW_CACHE.get(max_len)
    if row is None:
        from .entry_block import EntryBlock

        row = pad_ram_block(EntryBlock.empty(), 1, max_len)
        _PAD_ROW_CACHE[max_len] = row
    return row


def pad_ram_rows(block, bucket: int, max_len: int):
    """Device-hash prep from PRECOMPUTED per-row ram columns (EntryBlock
    ram_hi/ram_lo/ram_counts, filled by the fused commit prep while the
    sign bytes were still in cache): two row copies + padding-lane fill —
    no byte scatter, no word packing. Returns None when the block's ram
    layout does not match this max_len (caller falls back to
    pad_ram_block)."""
    from .commit_prep import ram_nblock

    nblock = ram_nblock(max_len)
    n = len(block)
    if block.ram_hi is None or block.ram_hi.shape[1] != nblock * 16:
        return None
    hi = np.empty((bucket, nblock, 16), dtype=np.uint32)
    lo = np.empty((bucket, nblock, 16), dtype=np.uint32)
    counts = np.empty((bucket,), dtype=np.int32)
    # reshape the DEST, not the source: ram columns may be strided
    # big-endian views over the fused prep's block buffer, and this
    # assignment is the single pass that byteswaps + compacts them
    hi.reshape(bucket, nblock * 16)[:n] = block.ram_hi
    lo.reshape(bucket, nblock * 16)[:n] = block.ram_lo
    counts[:n] = block.ram_counts
    if bucket > n:
        pad_hi, pad_lo, pad_counts = _pad_row(max_len)
        hi[n:] = pad_hi[0]
        lo[n:] = pad_lo[0]
        counts[n:] = pad_counts[0]
    return hi, lo, counts


def digest_to_bytes(digest) -> np.ndarray:
    """(B, 8, 2) uint32 -> (B, 64) uint8 big-endian digests (host)."""
    d = np.asarray(digest)
    bsz = d.shape[0]
    out = np.zeros((bsz, 64), dtype=np.uint8)
    for w in range(8):
        for half, col in ((0, 0), (1, 4)):
            v = d[:, w, half]
            out[:, 8 * w + col + 0] = (v >> 24) & 0xFF
            out[:, 8 * w + col + 1] = (v >> 16) & 0xFF
            out[:, 8 * w + col + 2] = (v >> 8) & 0xFF
            out[:, 8 * w + col + 3] = v & 0xFF
    return out
