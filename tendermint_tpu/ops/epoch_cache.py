"""Device-resident validator-set epoch cache.

PERF_r06 §3: after PR 4 the per-batch host cost is dominated by data that
never changes between heights — the validator pubkey columns are re-packed
into limbs/bits on the host, re-shipped over the relay, and re-decompressed
in kernel K1 for EVERY batch, even though the signer set is stable across
consecutive heights (committee-based consensus amortizes exactly this way;
arxiv 2302.00418) and the light-client loop re-verifies the SAME valset
across a whole trust period (arxiv 2010.07031).

This module keys on `ValidatorSet.hash()` — already cached on the set and
invalidated (with `ed25519_columns`) by `_update_with_change_set`, so a
membership or power change yields a NEW key and the stale entry ages out
of the LRU. On first sight of a valset the cache registers its pubkey
column; from the SECOND commit on, batches carry only per-signature data
(sig rows, sign-bytes/RAM blocks, `val_idx` gather indices) and the
kernels gather the committee from persistent device arrays:

    xla_tables()    (vp, 20) int32 limb rows + (vp,) sign bits — the
                    per-sig XLA kernel gathers A rows on device
                    (ops/ed25519_verify.verify_kernel_cached)
    coords_tables() (4*32, vp) int32 decompressed extended coordinates in
                    the pallas 32-row slot layout + (1, vp) ok flags —
                    K1 then decompresses M points (R only) instead of 2M
                    (ops/pallas_verify, ops/pallas_rlc cached kernels)

Table rows are padded to a power of two (identity-point rows) so the
compiled-shape set stays small under arbitrary valset sizes; gather index
`vp - 1` is the padding lane's identity row.

Upload discipline: the device arrays are materialized LAZILY, on first
use by the kernel closure — which runs on the pipeline's single
dispatch-owner thread (PERF_r05: exactly one thread may touch the relay).
A COLD epoch therefore verifies through the uncached path (no epoch key
attached); only warm epochs ride the cached kernels. That keeps the first
commit's latency unchanged and makes cold-vs-warm H2D accounting exact
(tools/prep_bench.py --transfer).

Enablement: TM_TPU_EPOCH_CACHE=N sets the LRU depth (0 disables). Unset,
the cache is on (depth 8) for the TPU backend and off elsewhere — CPU/XLA
test runs opt in explicitly so they do not compile extra kernel shapes.
Importable without jax (the types layer notes epochs at verify time).
"""

from __future__ import annotations

import functools
import os
import threading
from collections import OrderedDict
from typing import Optional, Tuple

import numpy as np

from ..libs import devcheck as _devcheck
from ..observability import trace as _trace

_span = _trace.span

DEFAULT_DEPTH = 8

_IDENT_ENC = np.zeros(32, dtype=np.uint8)
_IDENT_ENC[0] = 1  # y = 1: the identity point's wire encoding


def _next_pow2(n: int) -> int:
    p = 1
    while p < n:
        p *= 2
    return p


@functools.lru_cache(maxsize=1)
def _secp_pad_pub() -> np.ndarray:
    """The secp256k1 padding row's pubkey: the compressed generator
    (jax-free — pure-python curve constants only)."""
    from ..crypto import _weierstrass as _wst

    return np.frombuffer(_wst.compress(_wst.G), dtype=np.uint8)


@functools.lru_cache(maxsize=1)
def _bls_pad_pub() -> np.ndarray:
    """The bls12381 padding row's pubkey: the compressed G1 generator —
    the pad lane's self-signed pad commit verifies under sk=1
    (ops/bls_verify.PAD_MSG)."""
    from ..crypto import bls12381 as _bls

    return np.frombuffer(_bls.g1_compress(_bls.G1_GEN), dtype=np.uint8)


def _pad_row_for(scheme: str) -> np.ndarray:
    if scheme == "ed25519":
        return _IDENT_ENC
    if scheme == "bls12381":
        return _bls_pad_pub()
    return _secp_pad_pub()


class EpochEntry:
    """One validator set's device-resident pubkey tables.

    `pub_rows` is the (vp, 32) HOST snapshot — padded with identity rows —
    from which every device layout derives; layouts materialize lazily
    (and upload exactly once) under the entry lock.

    Donation exemption (ISSUE 7): these device arrays persist across
    batches, so every cached kernel's donate_argnums EXCLUDES the table
    arguments — a donated launch consumes only its per-batch buffers.
    Uploads are span-traced (`pipeline.table_upload`) so the overlapped
    dispatcher's transfer accounting can attribute the one-time cold-
    epoch cost separately from steady-state H2D."""

    __slots__ = ("key", "n_vals", "vp", "pub_rows", "scheme", "_mtx",
                 "_dev")

    def __init__(self, key: bytes, pub_col: np.ndarray,
                 scheme: str = "ed25519"):
        v = pub_col.shape[0]
        # pad to a power of two (min 16) so the compiled-shape set stays
        # small: the kernels' shapes are keyed by vp, not the raw size
        vp = max(_next_pow2(v + 1), 16)
        rows = np.empty((vp, pub_col.shape[1]), dtype=np.uint8)
        rows[:v] = pub_col
        # padding rows: the scheme's trivial gather target — ed25519's
        # identity encoding, secp256k1's compressed generator (the secp
        # pad lane verifies a fixed signature under G; ops/mesh.py
        # _secp_pad_row), or bls12381's compressed G1 generator (the agg
        # pad commit is self-signed under sk=1; ops/bls_verify)
        rows[v:] = _pad_row_for(scheme)
        self.key = key
        self.n_vals = v
        self.vp = vp
        self.pub_rows = rows
        self.scheme = scheme
        self._mtx = _devcheck.lock("epoch.entry")
        self._dev: dict = {}

    # -- device layouts (device_put ONCE per layout, lock-protected) -----

    def xla_tables(self) -> Tuple:
        """((vp, 20) int32 limbs, (vp,) int32 sign) on device — gathered
        per batch by verify_kernel_cached. Limbs are packed on the host by
        the SAME _pack_le_limbs the uncached prep uses, so cached vs
        uncached kernel inputs are bit-identical by construction."""
        with self._mtx:
            t = self._dev.get("xla")
            if t is None:
                # relay touch: table uploads run on the dispatch-owner
                # thread (lazy, inside the kernel closure) — assert it
                _devcheck.note_relay_touch("epoch_cache.xla_tables")
                import jax

                from .backend import _pack_le_limbs

                limbs = _pack_le_limbs(self.pub_rows)
                sign = (self.pub_rows[:, 31] >> 7).astype(np.int32)
                with _span("pipeline.table_upload", layout="xla",
                           vp=self.vp):
                    t = (jax.device_put(limbs), jax.device_put(sign))
                self._dev["xla"] = t
            return t

    def coords_tables(self) -> Tuple:
        """((4*32, vp) int32 decompressed extended coords in the pallas
        32-row slot layout, (1, vp) int32 ok flags) on device. Decompression
        runs ON DEVICE, once per epoch, via the same traced field routines
        the kernels use (ops/pallas_verify._unpack_limbs / decompress) —
        K1's cached variants then skip the committee half of their
        decompression entirely."""
        with self._mtx:
            t = self._dev.get("coords")
            if t is None:
                _devcheck.note_relay_touch("epoch_cache.coords_tables")
                import jax

                with _span("pipeline.table_upload", layout="coords",
                           vp=self.vp):
                    coords, ok = _coords_fn()(
                        np.ascontiguousarray(self.pub_rows.T)
                    )
                    # block until materialized so the first cached
                    # dispatch is not racing the table build
                    coords.block_until_ready()
                t = (coords, ok)
                self._dev["coords"] = t
            return t

    def sharded_xla_tables(self, mesh) -> Tuple:
        """The xla_tables layout REPLICATED over a jax device mesh
        (ISSUE 9 (b)): one resident copy per device, keyed inside this
        entry's layout dict by the mesh's device ids — so the epoch LRU
        owns the mesh replicas' lifetime exactly as it owns the
        single-device layouts (eviction drops them all), replacing the
        old module-level side cache in ops/sharded.py. Limbs are packed
        by the SAME _pack_le_limbs as the uncached prep, so mesh-cached
        vs single-device kernel inputs stay bit-identical."""
        key = ("xla_sharded", tuple(d.id for d in mesh.devices.flat))
        with self._mtx:
            t = self._dev.get(key)
            if t is None:
                # relay touch: replication is an upload fanned across the
                # mesh — dispatch-owner thread only, like every layout
                _devcheck.note_relay_touch("epoch_cache.sharded_tables")
                import jax
                from jax.sharding import NamedSharding
                from jax.sharding import PartitionSpec as _P

                from .backend import _pack_le_limbs

                limbs = _pack_le_limbs(self.pub_rows)
                sign = (self.pub_rows[:, 31] >> 7).astype(np.int32)
                repl = NamedSharding(mesh, _P())
                with _span("pipeline.table_upload", layout="xla_sharded",
                           vp=self.vp):
                    t = (jax.device_put(limbs, repl),
                         jax.device_put(sign, repl))
                self._dev[key] = t
            return t

    def secp_tables(self) -> Tuple:
        """((vp, 20) int32 qx limbs, (vp, 20) int32 qy limbs, (vp,) bool
        ok) on device — the committee's DECOMPRESSED affine Q columns for
        the cached secp256k1 kernel (ops/secp_verify.verify_kernel_cached).
        Decompression (the per-key square root) runs once per epoch on
        the host (ops/secp_verify.table_columns, memoized per key); rows
        whose pubkey fails to decompress carry G with ok False, and every
        padding row is (G, True) — the pad lane's trivial-accept base."""
        with self._mtx:
            t = self._dev.get("secp")
            if t is None:
                _devcheck.note_relay_touch("epoch_cache.secp_tables")
                import jax

                from . import secp_verify as _sv

                # table_columns appends ONE pad row itself; feed it the
                # first vp-1 rows (live keys + compressed-G padding) so
                # the device shape lands exactly on vp
                qx, qy, ok = _sv.table_columns(
                    [r.tobytes() for r in self.pub_rows[: self.vp - 1]]
                )
                with _span("pipeline.table_upload", layout="secp",
                           vp=self.vp):
                    t = (jax.device_put(qx), jax.device_put(qy),
                         jax.device_put(ok))
                self._dev["secp"] = t
            return t

    def bls_tables(self) -> Tuple:
        """((vp, 36) int32 gx limbs, (vp, 36) int32 gy limbs, (vp,) bool
        ok) on device — the committee's DECOMPRESSED affine G1 columns
        for the aggregation kernel's masked point-sum
        (ops/bls_verify.verify_kernel). Decompression (one Fp square root
        per key) runs once per epoch on the host; rows that fail to
        decompress or sit outside the G1 subgroup carry the generator
        with ok False, and every padding row is (G1, True) — the pad
        commit's sk=1 base."""
        with self._mtx:
            t = self._dev.get("bls")
            if t is None:
                _devcheck.note_relay_touch("epoch_cache.bls_tables")
                import jax

                from . import bls_verify as _bv

                # table_columns_g1 appends ONE pad row itself; feed it
                # the first vp-1 rows so the device shape lands on vp
                gx, gy, ok = _bv.table_columns_g1(
                    [r.tobytes() for r in self.pub_rows[: self.vp - 1]]
                )
                with _span("pipeline.table_upload", layout="bls",
                           vp=self.vp):
                    t = (jax.device_put(gx), jax.device_put(gy),
                         jax.device_put(ok))
                self._dev["bls"] = t
            return t

    def nbytes_host(self) -> int:
        """Host bytes a FULL table upload ships (every layout the kernels
        consume) — the cold-epoch H2D cost the --transfer gate accounts."""
        if self.scheme == "secp256k1":
            # qx + qy limb tables + ok flags
            return self.vp * (2 * 20 * 4 + 1)
        if self.scheme == "bls12381":
            # gx + gy 36-limb tables + ok flags
            return self.vp * (2 * 36 * 4 + 1)
        # xla limbs+sign, pallas coords+ok
        return self.vp * (20 * 4 + 4) + self.vp * (4 * 32 * 4 + 4)


@functools.lru_cache(maxsize=1)
def _coords_fn():
    import jax
    import jax.numpy as jnp

    from . import pallas_verify as pv

    def build(a_t):  # (32, vp) uint8
        y, sign = pv._unpack_limbs(a_t.astype(jnp.int32))
        ok, pt = pv.decompress(y, sign)
        vp = a_t.shape[-1]
        pad = jnp.zeros((32 - pv.NL, vp), dtype=jnp.int32)
        coords = jnp.concatenate(
            [jnp.concatenate([pt[c], pad], axis=0) for c in range(4)], axis=0
        )
        return coords, ok.astype(jnp.int32)

    return jax.jit(build)


class EpochCache:
    """LRU over recent validator-set epochs (thread-safe)."""

    def __init__(self, depth: int):
        self.depth = depth
        self._mtx = _devcheck.lock("epoch.lru")
        self._entries: "OrderedDict[bytes, EpochEntry]" = OrderedDict()

    def __len__(self) -> int:
        with self._mtx:
            return len(self._entries)

    def get(self, key: bytes) -> Optional[EpochEntry]:
        with self._mtx:
            e = self._entries.get(key)
            if e is not None:
                self._entries.move_to_end(key)
            return e

    def note(self, key: bytes, pub_col: np.ndarray,
             scheme: str = "ed25519") -> Optional[EpochEntry]:
        """Warm lookup-or-register. Returns the entry when the epoch is
        WARM (seen before — counted as a hit); a cold epoch registers and
        returns None so the first commit rides the uncached path and the
        table upload never sits in a cold commit's critical path."""
        m = _ops()
        with self._mtx:
            e = self._entries.get(key)
            if e is not None:
                self._entries.move_to_end(key)
                m.epoch_cache_hits.inc()
                return e
            m.epoch_cache_misses.inc()
            self._entries[key] = EpochEntry(key, pub_col, scheme)
            while len(self._entries) > self.depth:
                self._entries.popitem(last=False)
                m.epoch_cache_evictions.inc()
        return None

    def clear(self) -> None:
        with self._mtx:
            self._entries.clear()


_ops_cached = None


def _ops():
    global _ops_cached
    if _ops_cached is None:
        from ..libs import metrics as _metrics

        _ops_cached = _metrics.ops_metrics()
    return _ops_cached


_cache: Optional[EpochCache] = None
_cache_mtx = _devcheck.lock("epoch.cache")


def _depth_from_env() -> int:
    env = os.environ.get("TM_TPU_EPOCH_CACHE")
    if env is not None:
        try:
            return max(int(env), 0)
        except ValueError:
            return 0
    # default: on for the TPU backend only — CPU/XLA runs opt in so test
    # suites do not compile cached-kernel shapes they never asked for
    try:
        import jax

        return DEFAULT_DEPTH if jax.default_backend() == "tpu" else 0
    except Exception:  # noqa: BLE001  (no jax in this process)
        return 0


def cache() -> Optional[EpochCache]:
    """The process-wide cache, or None when disabled. Depth is read once;
    tests use reset(depth=...) to reconfigure."""
    global _cache
    with _cache_mtx:
        if _cache is None:
            _cache = EpochCache(_depth_from_env())
        return _cache if _cache.depth > 0 else None


def reset(depth: Optional[int] = None) -> None:
    """Drop every entry (and optionally reconfigure the depth) — test
    seam; production invalidation is the hash() keying itself."""
    global _cache
    with _cache_mtx:
        _cache = EpochCache(_depth_from_env() if depth is None else depth)


def note_valset(vals) -> Optional[bytes]:
    """Register/refresh `vals` in the cache; returns the epoch key iff the
    epoch is WARM and cacheable (single-scheme columns: all-ed25519 or
    all-secp256k1 — ISSUE 19). The key rides on the EntryBlock
    (`epoch_key`) so the prep stage can find the entry."""
    c = cache()
    if c is None:
        return None
    cols = vals.ed25519_columns()
    scheme = "ed25519"
    if cols is None:
        cols = vals.secp256k1_columns()
        scheme = "secp256k1"
    if cols is None:
        cols = vals.bls12381_columns()
        scheme = "bls12381"
    if cols is None:
        return None
    key = vals.hash()
    return key if c.note(key, cols[0], scheme) is not None else None


def stats() -> dict:
    """Snapshot of the cache state + the process-wide hit/miss/eviction
    counters (cumulative — callers diff two snapshots to attribute
    movement to a workload). Importable and callable without jax; the
    simnet harness embeds the delta in its run report so churn scenarios
    can assert the cache actually cycled cold→warm→evict."""
    m = _ops()
    c = cache()
    return {
        "enabled": c is not None,
        "depth": c.depth if c is not None else 0,
        "entries": len(c) if c is not None else 0,
        "hits": m.epoch_cache_hits.total(),
        "misses": m.epoch_cache_misses.total(),
        "evictions": m.epoch_cache_evictions.total(),
    }


def lookup(entries) -> Optional[EpochEntry]:
    """EntryBlock -> its epoch entry, or None (no key, evicted, or cache
    disabled). Evicted-between-submit-and-prep degrades to the uncached
    path — never an error."""
    key = getattr(entries, "epoch_key", None)
    if key is None or getattr(entries, "val_idx", None) is None:
        return None
    c = cache()
    if c is None:
        return None
    e = c.get(key)
    if e is not None and e.scheme != getattr(entries, "scheme", "ed25519"):
        # hash collision across schemes can't happen for one valset (a
        # set has one scheme), but a stale/mismatched key must degrade to
        # the uncached path, never feed the wrong kernel's tables
        return None
    return e
