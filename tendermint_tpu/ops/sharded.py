"""Multi-chip sharding of commit verification over a jax device Mesh.

SURVEY.md §7 stage 8 / §2 parallelism table: the reference's only
data-parallel compute — signature batching (types/validation.go:152,
crypto/ed25519/ed25519.go:192) — scales across chips here by sharding the
batch axis over an ICI mesh. The voting-power tally that VerifyCommit
folds over signatures (types/validation.go:152-260) becomes a `psum`
collective, so a 10k-validator commit verifies as: shard signatures,
verify locally (embarrassingly parallel ladder), all-reduce the tallied
power and the all-valid bit over ICI.

This module is the framework's "full training step over a mesh": the
shape the driver's `dryrun_multichip` exercises.
"""

from __future__ import annotations

from typing import List, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..observability import trace as _trace
from . import backend as _backend
from . import ed25519_verify as _kernel

_span = _trace.span

AXIS = "dp"


def make_mesh(n_devices: int | None = None) -> Mesh:
    devs = jax.devices()
    n = n_devices or len(devs)
    if len(devs) < n:
        raise RuntimeError(
            f"need {n} devices, have {len(devs)} "
            "(set XLA_FLAGS=--xla_force_host_platform_device_count=N on CPU)"
        )
    return Mesh(np.asarray(devs[:n]), (AXIS,))


def _commit_step(a_y, a_sign, r_y, r_sign, s_bits_t, k_bits_t, s_ok, power, live):
    """Per-shard body: verify local signatures, then all-reduce the tally.

    power: (B, 4) int32 — voting power split into base-2^16 lanes (see
    split_power) so 63-bit totals survive int32-only TPU lanes.
    """
    valid = _kernel.verify_kernel(a_y, a_sign, r_y, r_sign, s_bits_t, k_bits_t, s_ok)
    ok = valid & live
    # Tally voting power of valid signatures in 4 base-2^16 int32 lanes:
    # power < MaxTotalVotingPower = 2^60 (types/validator_set.go:25), so
    # each lane < 2^16 and a 10240-row lane sum < 2^30 — no overflow.
    lanes = jnp.sum(jnp.where(ok[..., None], power, 0), axis=0)
    lanes = jax.lax.psum(lanes, AXIS)
    all_valid = jax.lax.psum(jnp.sum(jnp.where(live & ~valid, 1, 0)), AXIS) == 0
    return valid, lanes, all_valid


def sharded_commit_verifier(mesh: Mesh):
    """Build the jitted, mesh-sharded commit verification step."""
    batch_sharded = NamedSharding(mesh, P(AXIS))
    bits_sharded = NamedSharding(mesh, P(None, AXIS))  # (253, B)
    replicated = NamedSharding(mesh, P())

    from jax import shard_map

    fn = shard_map(
        _commit_step,
        mesh=mesh,
        in_specs=(
            P(AXIS), P(AXIS), P(AXIS), P(AXIS),
            P(None, AXIS), P(None, AXIS), P(AXIS), P(AXIS), P(AXIS),
        ),
        out_specs=(P(AXIS), P(), P()),
    )
    return jax.jit(fn), (batch_sharded, bits_sharded, replicated)


POWER_LANES = 4
POWER_BASE = 1 << 16


def split_power(powers: np.ndarray) -> np.ndarray:
    """(B,) voting powers (< 2^60 = MaxTotalVotingPower cap) -> (B, 4)
    int32 base-2^16 lanes."""
    p = np.asarray(powers, dtype=np.int64)
    if (p < 0).any() or (p >= 1 << 62).any():
        raise ValueError("voting power out of range")
    lanes = [(p >> (16 * i)) & 0xFFFF for i in range(POWER_LANES)]
    return np.stack(lanes, axis=1).astype(np.int32)


def join_power(lanes) -> int:
    return sum(int(v) << (16 * i) for i, v in enumerate(np.asarray(lanes)))


def verify_commit_sharded(
    entries: List[Tuple[bytes, bytes, bytes]],
    powers: List[int],
    mesh: Mesh,
    bucket: int | None = None,
) -> Tuple[np.ndarray, int, bool]:
    """Verify a commit's signatures across the mesh and tally voting power.

    Returns (valid[n], tallied_power_of_valid, all_valid). The device
    equivalent of types/validation.go:152 verifyCommitBatch's accumulation,
    with the per-sig valid[] the blame path (:242-248) needs.

    A warm-epoch EntryBlock (val_idx + epoch_key resident in the cache)
    dispatches to the cached variant: the committee reads from each
    shard's replicated table instead of riding the batch transfer.
    """
    from . import epoch_cache as _epoch

    if _epoch.lookup(entries) is not None:
        return verify_commit_sharded_cached(entries, powers, mesh,
                                            bucket=bucket)
    n = len(entries)
    nd = np.prod(mesh.devices.shape)
    bucket = bucket or _backend._bucket_for(max(n, int(nd)))
    if bucket % nd:
        bucket += int(nd) - bucket % int(nd)
    with _span("sharded.host_prep", n=n, bucket=bucket):
        args = _backend.prepare_batch(entries, bucket)
        live = np.zeros((bucket,), dtype=bool)
        live[:n] = True
        pw = np.zeros((bucket, POWER_LANES), dtype=np.int32)
        pw[:n] = split_power(np.asarray(powers[:n]))
    fn, _ = _jitted_for(mesh)
    with _span("sharded.device", n=n, bucket=bucket):
        valid, lanes, all_valid = fn(*args, pw, live)
        # np.array, not asarray: on the CPU backend the latter is a
        # zero-copy view of the XLA output buffer, and with donation on
        # a later launch can recycle that page under the caller's slice
        valid = np.array(valid)
    return (
        valid[:n],
        join_power(lanes),
        bool(np.asarray(all_valid)),
    )


_mesh_cache: dict = {}


def _jitted_for(mesh: Mesh):
    key = (tuple(d.id for d in mesh.devices.flat),)
    if key not in _mesh_cache:
        _mesh_cache[key] = sharded_commit_verifier(mesh)
    return _mesh_cache[key]


# ---------------------------------------------------------------------------
# Epoch-cached sharding: the valset's device tables (ops/epoch_cache.py)
# REPLICATED across the mesh — every shard gathers its local lanes'
# committee rows from its own resident copy, so a warm epoch ships only
# per-signature data to every chip (the multi-chip face of the PR-7
# epoch cache). Table replication happens once per (epoch, mesh).
# ---------------------------------------------------------------------------

_shard_tbl_cache: dict = {}


def epoch_tables_sharded(ep, mesh: Mesh):
    """The epoch's XLA limb/sign tables placed with a REPLICATED
    NamedSharding over `mesh` — per-shard residency, uploaded once per
    (epoch key, mesh). Returns (limbs (vp, 20), sign (vp,)) jax Arrays."""
    from . import backend as _b

    key = (ep.key, tuple(d.id for d in mesh.devices.flat))
    t = _shard_tbl_cache.get(key)
    if t is None:
        limbs = _b._pack_le_limbs(ep.pub_rows)
        sign = (ep.pub_rows[:, 31] >> 7).astype(np.int32)
        repl = NamedSharding(mesh, P())
        t = (jax.device_put(limbs, repl), jax.device_put(sign, repl))
        _shard_tbl_cache[key] = t
        # bound growth: tables are small, but meshes*epochs churn in tests
        while len(_shard_tbl_cache) > 16:
            _shard_tbl_cache.pop(next(iter(_shard_tbl_cache)))
    return t


def _commit_step_cached(tbl_limbs, tbl_sign, idx, r_enc, s_enc, k_enc,
                        s_ok, power, live):
    """Per-shard body of the epoch-cached commit step: gather this
    shard's committee rows from the replicated table, unpack the raw
    per-sig rows on device, verify, then the same psum tally as
    _commit_step."""
    valid = _kernel.verify_kernel_cached(
        tbl_limbs, tbl_sign, idx, r_enc, s_enc, k_enc, s_ok
    )
    ok = valid & live
    lanes = jnp.sum(jnp.where(ok[..., None], power, 0), axis=0)
    lanes = jax.lax.psum(lanes, AXIS)
    all_valid = jax.lax.psum(jnp.sum(jnp.where(live & ~valid, 1, 0)), AXIS) == 0
    return valid, lanes, all_valid


def sharded_commit_verifier_cached(mesh: Mesh, donate: bool = False):
    """Jitted mesh-sharded commit verification over a device-resident
    epoch table: tables replicated (P(None, ...)), per-signature inputs
    sharded on the batch axis.

    donate=True donates ONLY the per-signature batch args (argnums 2+,
    fresh host arrays every call) — the replicated epoch tables (argnums
    0-1) live in _shard_tbl_cache across calls and donating them would
    invalidate every later call's table reference (ISSUE 7: the
    donation-safety rule under the replicated-table path)."""
    from jax import shard_map

    fn = shard_map(
        _commit_step_cached,
        mesh=mesh,
        in_specs=(
            P(None, None), P(None),               # replicated epoch table
            P(AXIS), P(AXIS), P(AXIS), P(AXIS),   # idx, r, s, k
            P(AXIS), P(AXIS), P(AXIS),            # s_ok, power, live
        ),
        out_specs=(P(AXIS), P(), P()),
    )
    if donate:
        return jax.jit(fn, donate_argnums=tuple(range(2, 9)))
    return jax.jit(fn)


def verify_commit_sharded_cached(
    block,
    powers: List[int],
    mesh: Mesh,
    bucket: int | None = None,
) -> Tuple[np.ndarray, int, bool]:
    """verify_commit_sharded for a WARM epoch: `block` is an EntryBlock
    carrying val_idx/epoch_key (ops/entry_block.py) whose valset is in
    the epoch cache. Ships raw per-sig rows + gather indices; each shard
    reads the committee from its replicated table copy. Falls back to
    verify_commit_sharded when the epoch is not resident."""
    from . import epoch_cache as _epoch

    ep = _epoch.lookup(block)
    if ep is None:
        return verify_commit_sharded(block, powers, mesh, bucket=bucket)
    n = len(block)
    nd = int(np.prod(mesh.devices.shape))
    bucket = bucket or _backend._bucket_for(max(n, nd))
    if bucket % nd:
        bucket += nd - bucket % nd
    with _span("sharded.host_prep", n=n, bucket=bucket, cached=1):
        args = _backend.prepare_batch_cached(block, bucket, ep)
        live = np.zeros((bucket,), dtype=bool)
        live[:n] = True
        pw = np.zeros((bucket, POWER_LANES), dtype=np.int32)
        pw[:n] = split_power(np.asarray(powers[:n]))
    tbl = epoch_tables_sharded(ep, mesh)
    donate = _backend.donate_enabled()
    key = ("cached", tuple(d.id for d in mesh.devices.flat), donate)
    if key not in _mesh_cache:
        _mesh_cache[key] = sharded_commit_verifier_cached(mesh, donate)
    with _span("sharded.device", n=n, bucket=bucket, cached=1):
        valid, lanes, all_valid = _mesh_cache[key](*tbl, *args, pw, live)
        # np.array, not asarray: on the CPU backend the latter is a
        # zero-copy view of the XLA output buffer, and with donation on
        # a later launch can recycle that page under the caller's slice
        valid = np.array(valid)
    return (
        valid[:n],
        join_power(lanes),
        bool(np.asarray(all_valid)),
    )


# ---------------------------------------------------------------------------
# Production-kernel sharding: the compact Pallas pipeline under shard_map
# (VERDICT r3 item 4 — shard the kernel VerifyCommit actually runs, not the
# op-graph fallback). Batch-minor compact args shard on their LAST axis;
# the voting-power tally and all-valid bit ride psum collectives over ICI.
# ---------------------------------------------------------------------------


def sharded_pallas_verifier(mesh: Mesh, n_per_shard: int, block: int,
                            interpret: bool):
    from jax import shard_map

    from . import pallas_verify as _pv

    # Compiled path: declare the kernel outputs varying over the dp axis
    # so shard_map's invariant checking (check_vma, the default) stays ON.
    # Interpret path: call positionally without vma — an explicit vma=None
    # kwarg would create a distinct lru_cache entry and re-trace the same
    # pipeline other call sites already compiled.
    if interpret:
        kern = _pv._jitted_pallas_verify(n_per_shard, block, interpret)
    else:
        kern = _pv._jitted_pallas_verify(
            n_per_shard, block, interpret, vma=frozenset({AXIS})
        )

    def _step(a_t, r_t, s_t, k_t, sok_t, power, live):
        valid = kern(a_t, r_t, s_t, k_t, sok_t)[0].astype(bool)
        ok = valid & live
        lanes = jnp.sum(jnp.where(ok[..., None], power, 0), axis=0)
        lanes = jax.lax.psum(lanes, AXIS)
        all_valid = jax.lax.psum(jnp.sum(jnp.where(live & ~valid, 1, 0)), AXIS) == 0
        return valid, lanes, all_valid

    fn = shard_map(
        _step,
        mesh=mesh,
        in_specs=(
            P(None, AXIS), P(None, AXIS), P(None, AXIS), P(None, AXIS),
            P(None, AXIS), P(AXIS), P(AXIS),
        ),
        out_specs=(P(AXIS), P(), P()),
        # The production (Mosaic/TPU) path runs with vma checking ON —
        # the kernel outputs declare vma={dp} above and a 1-device TPU
        # mesh compiles+runs checked (verified on hardware, round 5).
        # interpret mode only: jax's pallas HLO interpreter mixes varying
        # and unvarying operands in its own grid-index lowering and fails
        # with "shift_right_arithmetic requires varying manual axes to
        # match ... pass check_vma=False" — a documented jax workaround,
        # not a property of this kernel.
        check_vma=not interpret,
    )
    return jax.jit(fn)


def verify_commit_sharded_pallas(
    entries: List[Tuple[bytes, bytes, bytes]],
    powers: List[int],
    mesh: Mesh,
    bucket: int | None = None,
) -> Tuple[np.ndarray, int, bool]:
    """verify_commit_sharded on the production Pallas kernel: compact
    wire-format inputs, batch axis sharded across the mesh, psum tally.
    Non-TPU backends run the kernel in interpret mode (the same traced
    program Mosaic compiles on TPU)."""
    from . import pallas_verify as _pv

    n = len(entries)
    nd = int(np.prod(mesh.devices.shape))
    bucket = bucket or max(nd * 8, _bucket_pow2(n, nd))
    if bucket % nd:
        bucket += nd - bucket % nd
    per_shard = bucket // nd
    block = per_shard
    for cand in (_pv.BLOCK, 256, 128, 64, 32, 16, 8):
        if per_shard % cand == 0:
            block = cand
            break
    interpret = jax.default_backend() != "tpu"
    with _span("sharded.host_prep", n=n, bucket=bucket):
        a_t, r_t, s_t, k_t, sok_t = _pv.prepare_compact(entries, bucket)
        live = np.zeros((bucket,), dtype=bool)
        live[:n] = True
        pw = np.zeros((bucket, POWER_LANES), dtype=np.int32)
        pw[:n] = split_power(np.asarray(powers[:n]))
    key = ("pallas", tuple(d.id for d in mesh.devices.flat), per_shard, block,
           interpret)
    if key not in _mesh_cache:
        _mesh_cache[key] = sharded_pallas_verifier(mesh, per_shard, block,
                                                   interpret)
    with _span("sharded.device", n=n, bucket=bucket):
        valid, lanes, all_valid = _mesh_cache[key](
            a_t, r_t, s_t, k_t, sok_t, pw, live
        )
        # np.array, not asarray: on the CPU backend the latter is a
        # zero-copy view of the XLA output buffer, and with donation on
        # a later launch can recycle that page under the caller's slice
        valid = np.array(valid)
    return (
        valid[:n],
        join_power(lanes),
        bool(np.asarray(all_valid)),
    )


def _bucket_pow2(n: int, nd: int) -> int:
    b = nd
    while b < n:
        b *= 2
    return b


# ---------------------------------------------------------------------------
# Flagship-kernel sharding: the RLC fast-accept pipeline (ops.pallas_rlc —
# the engine VerifyCommit dispatches on TPU since round 5) under shard_map.
# The LANE axis shards over the mesh; the psum tally sums voting power of
# signatures in accepted lanes; rejected lanes re-verify on the host for
# blame exactly like the single-chip path (expand_lanes semantics).
# ---------------------------------------------------------------------------


def sharded_rlc_verifier(mesh: Mesh, g_per_shard: int, block: int,
                         interpret: bool):
    from jax import shard_map

    from . import pallas_rlc as _pr

    if interpret:
        kern = _pr._jitted_rlc_verify(g_per_shard, block, interpret)
    else:
        kern = _pr._jitted_rlc_verify(
            g_per_shard, block, interpret, vma=frozenset({AXIS})
        )
    m = _pr.M

    def _step(a_t, r_t, scal_t, sok_t, power, live):
        lane_valid = kern(a_t, r_t, scal_t, sok_t)[0].astype(bool)
        sig_valid = jnp.repeat(lane_valid, m)  # fast-accept: lane -> sigs
        ok = sig_valid & live
        lanes = jnp.sum(jnp.where(ok[..., None], power, 0), axis=0)
        lanes = jax.lax.psum(lanes, AXIS)
        all_valid = (
            jax.lax.psum(jnp.sum(jnp.where(live & ~sig_valid, 1, 0)), AXIS) == 0
        )
        return lane_valid, lanes, all_valid

    fn = shard_map(
        _step,
        mesh=mesh,
        in_specs=(
            P(None, AXIS), P(None, AXIS), P(None, AXIS), P(None, AXIS),
            P(AXIS), P(AXIS),
        ),
        out_specs=(P(AXIS), P(), P()),
        # same rationale as sharded_pallas_verifier above
        check_vma=not interpret,
    )
    return jax.jit(fn)


def verify_commit_sharded_rlc(
    entries: List[Tuple[bytes, bytes, bytes]],
    powers: List[int],
    mesh: Mesh,
) -> Tuple[np.ndarray, int, bool]:
    """verify_commit_sharded on the FLAGSHIP (RLC fast-accept) kernel:
    lanes shard across the mesh, accepted-lane voting power rides a psum,
    rejected lanes fall back to host per-sig verification for blame (and
    their valid signatures' power is added back on the host — identical
    accept/tally semantics to the single-chip RLC path). The batch size
    is derived from the mesh (per-shard lane count is pow2) — unlike the
    siblings there is no bucket parameter to pin."""
    from . import pallas_rlc as _pr

    n = len(entries)
    nd = int(np.prod(mesh.devices.shape))
    m = _pr.M
    lanes_needed = max((n + m - 1) // m, 1)
    # per-shard lane count: pow2, >= 1, such that total lanes covers n
    g_shard = 1
    while g_shard * nd < lanes_needed:
        g_shard *= 2
    block = min(g_shard, 128)  # pow2 g_shard: block always divides
    g = g_shard * nd
    bucket = g * m

    with _span("sharded.host_prep", n=n, bucket=bucket):
        a_t, r_t, scal_t, sok_t = _pr.prepare_rlc(entries, bucket)
        live = np.zeros((bucket,), dtype=bool)
        live[:n] = True
        pw = np.zeros((bucket, POWER_LANES), dtype=np.int32)
        pw[:n] = split_power(np.asarray(powers[:n]))
    interpret = jax.default_backend() != "tpu"
    key = ("rlc", tuple(d.id for d in mesh.devices.flat), g_shard, block,
           interpret)
    if key not in _mesh_cache:
        _mesh_cache[key] = sharded_rlc_verifier(mesh, g_shard, block, interpret)
    with _span("sharded.device", n=n, bucket=bucket):
        lane_valid, lanes_pw, all_valid = _mesh_cache[key](
            a_t, r_t, scal_t, sok_t, pw, live
        )
        lane_valid = np.asarray(lane_valid)
    tallied = join_power(lanes_pw)
    # lane verdicts -> per-sig verdicts + host re-verify of rejected
    # lanes (shared with the single-chip path), then add the rescued
    # signatures' power back into the device tally
    per_sig = _pr.expand_lanes(lane_valid, entries)
    rescued = per_sig & ~np.repeat(lane_valid, m)[:n]
    tallied += sum(int(powers[i]) for i in np.nonzero(rescued)[0])
    return per_sig, tallied, bool(per_sig.all()) if n else bool(all_valid)
