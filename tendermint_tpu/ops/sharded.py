"""Multi-chip sharding of commit verification over a jax device Mesh.

SURVEY.md §7 stage 8 / §2 parallelism table: the reference's only
data-parallel compute — signature batching (types/validation.go:152,
crypto/ed25519/ed25519.go:192) — scales across chips here by sharding the
batch axis over an ICI mesh. The voting-power tally that VerifyCommit
folds over signatures (types/validation.go:152-260) becomes a `psum`
collective, so a 10k-validator commit verifies as: shard signatures,
verify locally (embarrassingly parallel ladder), all-reduce the tallied
power and the all-valid bit over ICI.

This module is the framework's "full training step over a mesh": the
shape the driver's `dryrun_multichip` exercises.
"""

from __future__ import annotations

import functools
import logging
from typing import List, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..observability import trace as _trace
from . import backend as _backend
from . import ed25519_verify as _kernel

_span = _trace.span

_log = logging.getLogger("tendermint_tpu.ops.sharded")

AXIS = "dp"


@functools.lru_cache(maxsize=1)
def shard_map_available() -> bool:
    """ONE-TIME capability probe (ISSUE 9 satellite): does this jax ship
    `jax.shard_map`? Older versions (e.g. 0.4.37 in some containers)
    don't, and the sharded builders used to re-raise the ImportError on
    EVERY warm block that auto-dispatched here — the probe result is
    cached so the fallback decision costs one boolean test per batch."""
    try:
        from jax import shard_map  # noqa: F401

        return True
    except ImportError:
        return False


_fallback_warned: set = set()


def _warn_fallback(where: str) -> None:
    """Warn ONCE per entry point when the sharded path degrades to
    single-device dispatch (jax.shard_map unavailable, or fewer devices
    than requested lanes) — not once per batch."""
    if where in _fallback_warned:
        return
    _fallback_warned.add(where)
    _log.warning(
        "%s: jax.shard_map unavailable on this jax version — falling "
        "back to single-device dispatch of the same superbatch "
        "(bit-identical verdicts, no mesh parallelism). Logged once.",
        where,
    )


def _host_tally(valid: np.ndarray, pw: np.ndarray, live: np.ndarray,
                n: int) -> Tuple[np.ndarray, int, bool]:
    """The psum tally's host equivalent for the single-device fallback:
    sum the base-2^16 power lanes of valid live rows, fold, and compute
    the all-valid bit. `valid` must already be an owned bool array."""
    ok = valid & live
    lanes = pw[ok].sum(axis=0, dtype=np.int64)
    all_valid = not bool((live & ~valid).any())
    return valid[:n], join_power(lanes), all_valid


def make_mesh(n_devices: int | None = None) -> Mesh:
    devs = jax.devices()
    n = n_devices or len(devs)
    if len(devs) < n:
        raise RuntimeError(
            f"need {n} devices, have {len(devs)} "
            "(set XLA_FLAGS=--xla_force_host_platform_device_count=N on CPU)"
        )
    return Mesh(np.asarray(devs[:n]), (AXIS,))


def _commit_step(a_y, a_sign, r_y, r_sign, s_bits_t, k_bits_t, s_ok, power, live):
    """Per-shard body: verify local signatures, then all-reduce the tally.

    power: (B, 4) int32 — voting power split into base-2^16 lanes (see
    split_power) so 63-bit totals survive int32-only TPU lanes.
    """
    valid = _kernel.verify_kernel(a_y, a_sign, r_y, r_sign, s_bits_t, k_bits_t, s_ok)
    ok = valid & live
    # Tally voting power of valid signatures in 4 base-2^16 int32 lanes:
    # power < MaxTotalVotingPower = 2^60 (types/validator_set.go:25), so
    # each lane < 2^16 and a 10240-row lane sum < 2^30 — no overflow.
    lanes = jnp.sum(jnp.where(ok[..., None], power, 0), axis=0)
    lanes = jax.lax.psum(lanes, AXIS)
    all_valid = jax.lax.psum(jnp.sum(jnp.where(live & ~valid, 1, 0)), AXIS) == 0
    return valid, lanes, all_valid


def sharded_commit_verifier(mesh: Mesh):
    """Build the jitted, mesh-sharded commit verification step."""
    batch_sharded = NamedSharding(mesh, P(AXIS))
    bits_sharded = NamedSharding(mesh, P(None, AXIS))  # (253, B)
    replicated = NamedSharding(mesh, P())

    from jax import shard_map

    fn = shard_map(
        _commit_step,
        mesh=mesh,
        in_specs=(
            P(AXIS), P(AXIS), P(AXIS), P(AXIS),
            P(None, AXIS), P(None, AXIS), P(AXIS), P(AXIS), P(AXIS),
        ),
        out_specs=(P(AXIS), P(), P()),
    )
    return jax.jit(fn), (batch_sharded, bits_sharded, replicated)


POWER_LANES = 4
POWER_BASE = 1 << 16


def split_power(powers: np.ndarray) -> np.ndarray:
    """(B,) voting powers (< 2^60 = MaxTotalVotingPower cap) -> (B, 4)
    int32 base-2^16 lanes."""
    p = np.asarray(powers, dtype=np.int64)
    if (p < 0).any() or (p >= 1 << 62).any():
        raise ValueError("voting power out of range")
    lanes = [(p >> (16 * i)) & 0xFFFF for i in range(POWER_LANES)]
    return np.stack(lanes, axis=1).astype(np.int32)


def join_power(lanes) -> int:
    return sum(int(v) << (16 * i) for i, v in enumerate(np.asarray(lanes)))


def verify_commit_sharded(
    entries: List[Tuple[bytes, bytes, bytes]],
    powers: List[int],
    mesh: Mesh,
    bucket: int | None = None,
) -> Tuple[np.ndarray, int, bool]:
    """Verify a commit's signatures across the mesh and tally voting power.

    Returns (valid[n], tallied_power_of_valid, all_valid). The device
    equivalent of types/validation.go:152 verifyCommitBatch's accumulation,
    with the per-sig valid[] the blame path (:242-248) needs.

    A warm-epoch EntryBlock (val_idx + epoch_key resident in the cache)
    dispatches to the cached variant: the committee reads from each
    shard's replicated table instead of riding the batch transfer.
    """
    from . import epoch_cache as _epoch

    if _epoch.lookup(entries) is not None:
        return verify_commit_sharded_cached(entries, powers, mesh,
                                            bucket=bucket)
    n = len(entries)
    nd = np.prod(mesh.devices.shape)
    bucket = bucket or _backend._bucket_for(max(n, int(nd)))
    if bucket % nd:
        bucket += int(nd) - bucket % int(nd)
    with _span("sharded.host_prep", n=n, bucket=bucket):
        args = _backend.prepare_batch(entries, bucket)
        live = np.zeros((bucket,), dtype=bool)
        live[:n] = True
        pw = np.zeros((bucket, POWER_LANES), dtype=np.int32)
        pw[:n] = split_power(np.asarray(powers[:n]))
    if not shard_map_available():
        # warn-once fallback (ISSUE 9 satellite): same kernel math over
        # the same padded batch on one device, tally folded on the host
        _warn_fallback("verify_commit_sharded")
        with _span("sharded.device", n=n, bucket=bucket, fallback=1):
            kern = _kernel.jitted_verify(_backend.donate_enabled())
            valid = np.array(kern(*args)).astype(bool)
        return _host_tally(valid, pw, live, n)
    fn, _ = _jitted_for(mesh)
    with _span("sharded.device", n=n, bucket=bucket):
        valid, lanes, all_valid = fn(*args, pw, live)
        # np.array, not asarray: on the CPU backend the latter is a
        # zero-copy view of the XLA output buffer, and with donation on
        # a later launch can recycle that page under the caller's slice
        valid = np.array(valid)
    return (
        valid[:n],
        join_power(lanes),
        bool(np.asarray(all_valid)),
    )


_mesh_cache: dict = {}


def _jitted_for(mesh: Mesh):
    key = (tuple(d.id for d in mesh.devices.flat),)
    if key not in _mesh_cache:
        _mesh_cache[key] = sharded_commit_verifier(mesh)
    return _mesh_cache[key]


# ---------------------------------------------------------------------------
# Epoch-cached sharding: the valset's device tables (ops/epoch_cache.py)
# REPLICATED across the mesh — every shard gathers its local lanes'
# committee rows from its own resident copy, so a warm epoch ships only
# per-signature data to every chip (the multi-chip face of the PR-7
# epoch cache). Table replication happens once per (epoch, mesh).
# ---------------------------------------------------------------------------


def epoch_tables_sharded(ep, mesh: Mesh):
    """The epoch's XLA limb/sign tables placed with a REPLICATED
    NamedSharding over `mesh` — per-shard residency, uploaded once per
    (epoch key, mesh). Returns (limbs (vp, 20), sign (vp,)) jax Arrays.

    ISSUE 9 (b): mesh-keyed tables live INSIDE the epoch's cache entry
    (EpochEntry._dev, keyed ("xla_sharded", device ids)) instead of a
    module-level side table, so the PR-5 LRU owns their lifetime — an
    evicted epoch drops its mesh replicas with its single-device
    layouts, and the upload runs under the entry lock on the dispatch-
    owner thread (devcheck note_relay_touch covers it)."""
    return ep.sharded_xla_tables(mesh)


def _commit_step_cached(tbl_limbs, tbl_sign, idx, r_enc, s_enc, k_enc,
                        s_ok, power, live):
    """Per-shard body of the epoch-cached commit step: gather this
    shard's committee rows from the replicated table, unpack the raw
    per-sig rows on device, verify, then the same psum tally as
    _commit_step."""
    valid = _kernel.verify_kernel_cached(
        tbl_limbs, tbl_sign, idx, r_enc, s_enc, k_enc, s_ok
    )
    ok = valid & live
    lanes = jnp.sum(jnp.where(ok[..., None], power, 0), axis=0)
    lanes = jax.lax.psum(lanes, AXIS)
    all_valid = jax.lax.psum(jnp.sum(jnp.where(live & ~valid, 1, 0)), AXIS) == 0
    return valid, lanes, all_valid


def sharded_commit_verifier_cached(mesh: Mesh, donate: bool = False):
    """Jitted mesh-sharded commit verification over a device-resident
    epoch table: tables replicated (P(None, ...)), per-signature inputs
    sharded on the batch axis.

    donate=True donates ONLY the per-signature batch args (argnums 2+,
    fresh host arrays every call) — the replicated epoch tables (argnums
    0-1) live in the epoch entry's mesh-keyed cache across calls and donating them would
    invalidate every later call's table reference (ISSUE 7: the
    donation-safety rule under the replicated-table path)."""
    from jax import shard_map

    fn = shard_map(
        _commit_step_cached,
        mesh=mesh,
        in_specs=(
            P(None, None), P(None),               # replicated epoch table
            P(AXIS), P(AXIS), P(AXIS), P(AXIS),   # idx, r, s, k
            P(AXIS), P(AXIS), P(AXIS),            # s_ok, power, live
        ),
        out_specs=(P(AXIS), P(), P()),
    )
    if donate:
        return jax.jit(fn, donate_argnums=tuple(range(2, 9)))
    return jax.jit(fn)


def verify_commit_sharded_cached(
    block,
    powers: List[int],
    mesh: Mesh,
    bucket: int | None = None,
) -> Tuple[np.ndarray, int, bool]:
    """verify_commit_sharded for a WARM epoch: `block` is an EntryBlock
    carrying val_idx/epoch_key (ops/entry_block.py) whose valset is in
    the epoch cache. Ships raw per-sig rows + gather indices; each shard
    reads the committee from its replicated table copy. Falls back to
    verify_commit_sharded when the epoch is not resident."""
    from . import epoch_cache as _epoch

    ep = _epoch.lookup(block)
    if ep is None:
        return verify_commit_sharded(block, powers, mesh, bucket=bucket)
    n = len(block)
    nd = int(np.prod(mesh.devices.shape))
    bucket = bucket or _backend._bucket_for(max(n, nd))
    if bucket % nd:
        bucket += nd - bucket % nd
    with _span("sharded.host_prep", n=n, bucket=bucket, cached=1):
        args = _backend.prepare_batch_cached(block, bucket, ep)
        live = np.zeros((bucket,), dtype=bool)
        live[:n] = True
        pw = np.zeros((bucket, POWER_LANES), dtype=np.int32)
        pw[:n] = split_power(np.asarray(powers[:n]))
    donate = _backend.donate_enabled()
    if not shard_map_available():
        # warn-once fallback (ISSUE 9 satellite): the warm block still
        # rides the CACHED kernel (single-device table, device gather +
        # unpack), host tally — previously every warm auto-dispatch
        # re-raised the shard_map ImportError
        _warn_fallback("verify_commit_sharded_cached")
        with _span("sharded.device", n=n, bucket=bucket, cached=1,
                   fallback=1):
            kern = _backend.cached_kernel(ep, False, donate)
            valid = np.array(kern(*args)).astype(bool)
        return _host_tally(valid, pw, live, n)
    tbl = epoch_tables_sharded(ep, mesh)
    key = ("cached", tuple(d.id for d in mesh.devices.flat), donate)
    if key not in _mesh_cache:
        _mesh_cache[key] = sharded_commit_verifier_cached(mesh, donate)
    with _span("sharded.device", n=n, bucket=bucket, cached=1):
        valid, lanes, all_valid = _mesh_cache[key](*tbl, *args, pw, live)
        # np.array, not asarray: on the CPU backend the latter is a
        # zero-copy view of the XLA output buffer, and with donation on
        # a later launch can recycle that page under the caller's slice
        valid = np.array(valid)
    return (
        valid[:n],
        join_power(lanes),
        bool(np.asarray(all_valid)),
    )


# ---------------------------------------------------------------------------
# Production-kernel sharding: the compact Pallas pipeline under shard_map
# (VERDICT r3 item 4 — shard the kernel VerifyCommit actually runs, not the
# op-graph fallback). Batch-minor compact args shard on their LAST axis;
# the voting-power tally and all-valid bit ride psum collectives over ICI.
# ---------------------------------------------------------------------------


def sharded_pallas_verifier(mesh: Mesh, n_per_shard: int, block: int,
                            interpret: bool):
    from jax import shard_map

    from . import pallas_verify as _pv

    # Compiled path: declare the kernel outputs varying over the dp axis
    # so shard_map's invariant checking (check_vma, the default) stays ON.
    # Interpret path: call positionally without vma — an explicit vma=None
    # kwarg would create a distinct lru_cache entry and re-trace the same
    # pipeline other call sites already compiled.
    if interpret:
        kern = _pv._jitted_pallas_verify(n_per_shard, block, interpret)
    else:
        kern = _pv._jitted_pallas_verify(
            n_per_shard, block, interpret, vma=frozenset({AXIS})
        )

    def _step(a_t, r_t, s_t, k_t, sok_t, power, live):
        valid = kern(a_t, r_t, s_t, k_t, sok_t)[0].astype(bool)
        ok = valid & live
        lanes = jnp.sum(jnp.where(ok[..., None], power, 0), axis=0)
        lanes = jax.lax.psum(lanes, AXIS)
        all_valid = jax.lax.psum(jnp.sum(jnp.where(live & ~valid, 1, 0)), AXIS) == 0
        return valid, lanes, all_valid

    fn = shard_map(
        _step,
        mesh=mesh,
        in_specs=(
            P(None, AXIS), P(None, AXIS), P(None, AXIS), P(None, AXIS),
            P(None, AXIS), P(AXIS), P(AXIS),
        ),
        out_specs=(P(AXIS), P(), P()),
        # The production (Mosaic/TPU) path runs with vma checking ON —
        # the kernel outputs declare vma={dp} above and a 1-device TPU
        # mesh compiles+runs checked (verified on hardware, round 5).
        # interpret mode only: jax's pallas HLO interpreter mixes varying
        # and unvarying operands in its own grid-index lowering and fails
        # with "shift_right_arithmetic requires varying manual axes to
        # match ... pass check_vma=False" — a documented jax workaround,
        # not a property of this kernel.
        check_vma=not interpret,
    )
    return jax.jit(fn)


def verify_commit_sharded_pallas(
    entries: List[Tuple[bytes, bytes, bytes]],
    powers: List[int],
    mesh: Mesh,
    bucket: int | None = None,
) -> Tuple[np.ndarray, int, bool]:
    """verify_commit_sharded on the production Pallas kernel: compact
    wire-format inputs, batch axis sharded across the mesh, psum tally.
    Non-TPU backends run the kernel in interpret mode (the same traced
    program Mosaic compiles on TPU)."""
    from . import pallas_verify as _pv

    n = len(entries)
    nd = int(np.prod(mesh.devices.shape))
    bucket = bucket or max(nd * 8, _bucket_pow2(n, nd))
    if bucket % nd:
        bucket += nd - bucket % nd
    per_shard = bucket // nd
    block = _pv.pick_block(per_shard)
    interpret = jax.default_backend() != "tpu"
    with _span("sharded.host_prep", n=n, bucket=bucket):
        a_t, r_t, s_t, k_t, sok_t = _pv.prepare_compact(entries, bucket)
        live = np.zeros((bucket,), dtype=bool)
        live[:n] = True
        pw = np.zeros((bucket, POWER_LANES), dtype=np.int32)
        pw[:n] = split_power(np.asarray(powers[:n]))
    if not shard_map_available():
        _warn_fallback("verify_commit_sharded_pallas")
        with _span("sharded.device", n=n, bucket=bucket, fallback=1):
            kern = _pv._jitted_pallas_verify(bucket, block, interpret)
            valid = np.array(kern(a_t, r_t, s_t, k_t, sok_t))[0].astype(bool)
        return _host_tally(valid, pw, live, n)
    key = ("pallas", tuple(d.id for d in mesh.devices.flat), per_shard, block,
           interpret)
    if key not in _mesh_cache:
        _mesh_cache[key] = sharded_pallas_verifier(mesh, per_shard, block,
                                                   interpret)
    with _span("sharded.device", n=n, bucket=bucket):
        valid, lanes, all_valid = _mesh_cache[key](
            a_t, r_t, s_t, k_t, sok_t, pw, live
        )
        # np.array, not asarray: on the CPU backend the latter is a
        # zero-copy view of the XLA output buffer, and with donation on
        # a later launch can recycle that page under the caller's slice
        valid = np.array(valid)
    return (
        valid[:n],
        join_power(lanes),
        bool(np.asarray(all_valid)),
    )


def _bucket_pow2(n: int, nd: int) -> int:
    b = nd
    while b < n:
        b *= 2
    return b


# ---------------------------------------------------------------------------
# Flagship-kernel sharding: the RLC fast-accept pipeline (ops.pallas_rlc —
# the engine VerifyCommit dispatches on TPU since round 5) under shard_map.
# The LANE axis shards over the mesh; the psum tally sums voting power of
# signatures in accepted lanes; rejected lanes re-verify on the host for
# blame exactly like the single-chip path (expand_lanes semantics).
# ---------------------------------------------------------------------------


def sharded_rlc_verifier(mesh: Mesh, g_per_shard: int, block: int,
                         interpret: bool):
    from jax import shard_map

    from . import pallas_rlc as _pr

    if interpret:
        kern = _pr._jitted_rlc_verify(g_per_shard, block, interpret)
    else:
        kern = _pr._jitted_rlc_verify(
            g_per_shard, block, interpret, vma=frozenset({AXIS})
        )
    m = _pr.M

    def _step(a_t, r_t, scal_t, sok_t, power, live):
        lane_valid = kern(a_t, r_t, scal_t, sok_t)[0].astype(bool)
        sig_valid = jnp.repeat(lane_valid, m)  # fast-accept: lane -> sigs
        ok = sig_valid & live
        lanes = jnp.sum(jnp.where(ok[..., None], power, 0), axis=0)
        lanes = jax.lax.psum(lanes, AXIS)
        all_valid = (
            jax.lax.psum(jnp.sum(jnp.where(live & ~sig_valid, 1, 0)), AXIS) == 0
        )
        return lane_valid, lanes, all_valid

    fn = shard_map(
        _step,
        mesh=mesh,
        in_specs=(
            P(None, AXIS), P(None, AXIS), P(None, AXIS), P(None, AXIS),
            P(AXIS), P(AXIS),
        ),
        out_specs=(P(AXIS), P(), P()),
        # same rationale as sharded_pallas_verifier above
        check_vma=not interpret,
    )
    return jax.jit(fn)


def verify_commit_sharded_rlc(
    entries: List[Tuple[bytes, bytes, bytes]],
    powers: List[int],
    mesh: Mesh,
) -> Tuple[np.ndarray, int, bool]:
    """verify_commit_sharded on the FLAGSHIP (RLC fast-accept) kernel:
    lanes shard across the mesh, accepted-lane voting power rides a psum,
    rejected lanes fall back to host per-sig verification for blame (and
    their valid signatures' power is added back on the host — identical
    accept/tally semantics to the single-chip RLC path). The batch size
    is derived from the mesh (per-shard lane count is pow2) — unlike the
    siblings there is no bucket parameter to pin."""
    from . import pallas_rlc as _pr

    n = len(entries)
    nd = int(np.prod(mesh.devices.shape))
    m = _pr.M
    lanes_needed = max((n + m - 1) // m, 1)
    # per-shard lane count: pow2, >= 1, such that total lanes covers n
    g_shard = 1
    while g_shard * nd < lanes_needed:
        g_shard *= 2
    block = min(g_shard, 128)  # pow2 g_shard: block always divides
    g = g_shard * nd
    bucket = g * m

    with _span("sharded.host_prep", n=n, bucket=bucket):
        a_t, r_t, scal_t, sok_t = _pr.prepare_rlc(entries, bucket)
        live = np.zeros((bucket,), dtype=bool)
        live[:n] = True
        pw = np.zeros((bucket, POWER_LANES), dtype=np.int32)
        pw[:n] = split_power(np.asarray(powers[:n]))
    interpret = jax.default_backend() != "tpu"
    if not shard_map_available():
        _warn_fallback("verify_commit_sharded_rlc")
        with _span("sharded.device", n=n, bucket=bucket, fallback=1):
            kern = _pr._jitted_rlc_verify(g, block, interpret)
            lane_valid = np.array(
                kern(a_t, r_t, scal_t, sok_t)
            )[0].astype(bool)
        sig_valid = np.repeat(lane_valid, m)
        tallied = join_power(
            pw[sig_valid & live].sum(axis=0, dtype=np.int64)
        )
        all_valid = not bool((live & ~sig_valid).any())
    else:
        key = ("rlc", tuple(d.id for d in mesh.devices.flat), g_shard, block,
               interpret)
        if key not in _mesh_cache:
            _mesh_cache[key] = sharded_rlc_verifier(mesh, g_shard, block,
                                                    interpret)
        with _span("sharded.device", n=n, bucket=bucket):
            lane_valid, lanes_pw, all_valid = _mesh_cache[key](
                a_t, r_t, scal_t, sok_t, pw, live
            )
            lane_valid = np.asarray(lane_valid)
        tallied = join_power(lanes_pw)
    # lane verdicts -> per-sig verdicts + host re-verify of rejected
    # lanes (shared with the single-chip path), then add the rescued
    # signatures' power back into the device tally
    per_sig = _pr.expand_lanes(lane_valid, entries)
    rescued = per_sig & ~np.repeat(lane_valid, m)[:n]
    tallied += sum(int(powers[i]) for i in np.nonzero(rescued)[0])
    return per_sig, tallied, bool(per_sig.all()) if n else bool(all_valid)


# ---------------------------------------------------------------------------
# Mesh-dispatcher kernels (ISSUE 9 tentpole): valid-bits-only variants of
# the sharded verifiers for the pipeline's lane-packed superbatches. The
# dispatcher needs the per-row verdict vector and nothing else — blame and
# tallies are demuxed per job on the host — so these skip the psum
# collectives entirely: each device verifies its lane(s), the output
# shards back along the batch axis. Built once per (mesh, variant) in
# _mesh_cache; called ONLY from the dispatch-owner thread.
# ---------------------------------------------------------------------------


def mesh_ready(n_lanes: int) -> bool:
    """Can a real shard_map mesh serve `n_lanes` lanes? False degrades
    the mesh dispatcher to simulated lanes (same superbatch, plain
    kernel, warn-once) — the tier-1/CPU face."""
    if not shard_map_available():
        _warn_fallback("mesh_dispatch")
        return False
    if len(jax.devices()) < n_lanes:
        if "mesh_dispatch_devices" not in _fallback_warned:
            _fallback_warned.add("mesh_dispatch_devices")
            _log.warning(
                "mesh dispatcher asked for %d lanes but only %d devices "
                "are visible — running simulated lanes on one device. "
                "Logged once.", n_lanes, len(jax.devices()),
            )
        return False
    return True


_dispatch_meshes: dict = {}


def dispatch_mesh(n_lanes: int) -> Mesh:
    """The dispatcher's mesh over the first `n_lanes` devices (cached —
    Mesh construction is cheap but the _mesh_cache keys off device ids,
    so reusing the object keeps the jit caches warm)."""
    m = _dispatch_meshes.get(n_lanes)
    if m is None:
        m = _dispatch_meshes[n_lanes] = make_mesh(n_lanes)
    return m


def mesh_valid_fn(mesh: Mesh, donate: bool = False,
                  device_hash: bool = False):
    """Jitted shard_map of the bare per-sig verify kernel: uncached args
    sharded lane-per-device, (B,) bool verdicts out. `device_hash` picks
    the on-chip-SHA kernel (R||A||M block rows ship instead of host
    challenges — the same selection the classic `_prepare` makes)."""
    key = ("mesh_valid", tuple(d.id for d in mesh.devices.flat), donate,
           device_hash)
    if key not in _mesh_cache:
        from jax import shard_map

        if device_hash:
            body = _kernel.verify_kernel_device_hash
            # a_limbs/sign, r_limbs/sign, s_bits, hi, lo, counts, s_ok —
            # the SHA block rows are (B, NBLOCK, 16): batch axis leads
            specs = (P(AXIS), P(AXIS), P(AXIS), P(AXIS), P(None, AXIS),
                     P(AXIS), P(AXIS), P(AXIS), P(AXIS))
            n_args = 9
        else:
            body = _kernel.verify_kernel
            specs = (P(AXIS), P(AXIS), P(AXIS), P(AXIS),
                     P(None, AXIS), P(None, AXIS), P(AXIS))
            n_args = 7
        fn = shard_map(body, mesh=mesh, in_specs=specs, out_specs=P(AXIS))
        _mesh_cache[key] = (
            jax.jit(fn, donate_argnums=tuple(range(n_args))) if donate
            else jax.jit(fn)
        )
    return _mesh_cache[key]


def mesh_valid_fn_cached(mesh: Mesh, ep, donate: bool = False,
                         device_hash: bool = False):
    """Cached-epoch mesh kernel closure: each shard gathers committee
    rows from its replicated table copy (epoch_tables_sharded — resident
    per device, owned by the epoch LRU) and unpacks the raw per-sig rows
    on device. The table resolves at CALL time, on the dispatch-owner
    thread, exactly like backend.cached_kernel."""
    key = ("mesh_valid_cached",
           tuple(d.id for d in mesh.devices.flat), donate, device_hash)
    if key not in _mesh_cache:
        from jax import shard_map

        if device_hash:
            body = _kernel.verify_kernel_cached_device_hash
            # idx, r, s, hi (B, NB, 16), lo, counts, s_ok
            specs = (P(None, None), P(None),
                     P(AXIS), P(AXIS), P(AXIS),
                     P(AXIS), P(AXIS), P(AXIS), P(AXIS))
            n_args = 9
        else:
            body = _kernel.verify_kernel_cached
            specs = (P(None, None), P(None),              # tables
                     P(AXIS), P(AXIS), P(AXIS), P(AXIS),  # idx, r, s, k
                     P(AXIS))                             # s_ok
            n_args = 7
        fn = shard_map(body, mesh=mesh, in_specs=specs, out_specs=P(AXIS))
        _mesh_cache[key] = (
            jax.jit(fn, donate_argnums=tuple(range(2, n_args))) if donate
            else jax.jit(fn)
        )
    base = _mesh_cache[key]

    def call(*args):
        tbl_limbs, tbl_sign = epoch_tables_sharded(ep, mesh)
        return base(tbl_limbs, tbl_sign, *args)

    return call


def mesh_pallas_valid_fn(mesh: Mesh, n_per_shard: int, block: int,
                         interpret: bool):
    """Compact-pallas mesh kernel, valid bits only: batch-minor args
    shard on their LAST axis (one lane per device), verdict row out."""
    key = ("mesh_pallas_valid", tuple(d.id for d in mesh.devices.flat),
           n_per_shard, block, interpret)
    if key not in _mesh_cache:
        from jax import shard_map

        from . import pallas_verify as _pv

        if interpret:
            kern = _pv._jitted_pallas_verify(n_per_shard, block, interpret)
        else:
            kern = _pv._jitted_pallas_verify(
                n_per_shard, block, interpret, vma=frozenset({AXIS})
            )

        def _step(a_t, r_t, s_t, k_t, sok_t):
            return kern(a_t, r_t, s_t, k_t, sok_t)[0].astype(bool)

        fn = shard_map(
            _step,
            mesh=mesh,
            in_specs=(
                P(None, AXIS), P(None, AXIS), P(None, AXIS),
                P(None, AXIS), P(None, AXIS),
            ),
            out_specs=P(AXIS),
            # same vma rationale as sharded_pallas_verifier above
            check_vma=not interpret,
        )
        _mesh_cache[key] = jax.jit(fn)
    return _mesh_cache[key]


_MESH_SPECS = {
    # host-hash uncached: limbs/sign/bits/s_ok (backend.prepare_batch)
    "host_hash": (P(AXIS), P(AXIS), P(AXIS), P(AXIS),
                  P(None, AXIS), P(None, AXIS), P(AXIS)),
    # device-hash uncached: limbs/sign/s_bits + (B, NB, 16) SHA rows
    "device_hash": (P(AXIS), P(AXIS), P(AXIS), P(AXIS), P(None, AXIS),
                    P(AXIS), P(AXIS), P(AXIS), P(AXIS)),
    # warm-epoch gather args: idx + raw r/s/k rows + s_ok
    "cached": (P(AXIS), P(AXIS), P(AXIS), P(AXIS), P(AXIS)),
    # warm-epoch device-hash: idx + raw r/s + SHA rows + s_ok
    "cached_device_hash": (P(AXIS),) * 7,
    # compact pallas: batch-minor, shard the last axis
    "pallas": (P(None, AXIS),) * 5,
}


def mesh_arg_shardings(mesh: Mesh, kind: str, n_args: int):
    """Per-arg NamedShardings for device_pool.transfer — batch k+1's H2D
    copies land lane-per-device (overlapping the mesh kernel k exactly
    like the single-device overlap path; ISSUE 9 tentpole piece c)."""
    specs = _MESH_SPECS[kind]
    if len(specs) != n_args:
        raise ValueError(
            f"{kind} superbatch has {n_args} args, specs cover {len(specs)}"
        )
    return tuple(NamedSharding(mesh, p) for p in specs)
