"""One ingress fabric — the shared windowed-accumulator engine (ISSUE 17).

PRs 11/12/14/15 put every serving workload on the device pipeline, and
each grew its own near-identical windowed accumulator: light
single-flight, mempool batch/window, replay range fuse, vote
micro-windows — four flush threads, four fallback paths, four
poisoned-window isolation schemes. This module is the consolidation:
ONE engine that owns the window open/flush lifecycle (one scheduler
thread, one completer thread for the whole process), EntryBlock
assembly and submission to the shared AsyncBatchVerifier at each lane's
QoS priority, poisoned-window isolation with retryability, the
fallback-to-host contract, and per-lane labeled metrics. A workload
keeps only a `LaneSpec` — window policy, priority tier, host-stage
check and verdict-apply callbacks — and the engine does the rest.

Windows are ADAPTIVE and SLO-AWARE (`AdaptiveWindow`): under flood a
lane's window deepens (more amortization per relay command — the
2302.00418 batch economics applied at admission); when traffic thins it
shrinks below its base so a lone request is not taxed the full window;
and a lane's p99 latency budget bounds the effective window so the
flush fires BEFORE the budget is exhausted (deadline-aware flush).
Explicitly-configured lanes (constructor args, every existing bench and
test call site) keep fixed windows unless TM_TPU_INGRESS_ADAPTIVE=1 —
determinism by default where determinism was promised.

Threading contracts the engine preserves from the per-lane era:

* Scheduler flushes stage under the engine mutex, RELEASE it, then
  submit — verifier submission never happens under a lock (the tmlint
  lock-discipline shape).
* A lane may ask for completer-thread delivery (`use_completer`): its
  verdict delivery and host verification run on the engine's completer
  thread, never the pipeline resolver. The mempool needs this —
  consensus holds the mempool lock across update()→recheck while
  waiting on PIPELINE futures (resolved by the resolver, which never
  takes that lock), so completion work that takes the mempool lock must
  live on a different thread. The completer only ever takes workload
  locks that their owners release without waiting on the completer —
  verdict futures are resolved here, pipeline futures never are.
* A lane with `use_completer=False` (votes) delivers straight from the
  resolver done-callback: its apply callback is enqueue-only by
  contract.
* Stepped lanes (simnet) are never touched by the scheduler: nothing
  flushes until `flush_pending()` — flush points stay a pure function
  of message arrival, so cluster runs stay replay-exact.

Error policy, per window (the four schemes, now one):

* pre-submit failure (EntryBlock build or verifier.submit raised):
  `submit_error_to_host=True` lanes host-verify the window instead
  (votes — the host path is always available); others deliver the
  error to exactly that window's items (mempool — futures raise).
* post-submit DispatchError: poisons ONLY its own window — the items
  are handed back with the error, the lane and every later window keep
  flowing.
* post-submit remote death (ISSUE 18): an error marked
  `fallback_to_host` (a fleet verifier's FleetUnavailable) host-verifies
  the window through `host_fn` instead of poisoning — zero lost items
  while the remote backend rejoins.

Knobs (lane-keyed, replacing the per-workload sprawl — old names are
honored with a DeprecationWarning): TM_TPU_INGRESS_<LANE>_BATCH,
TM_TPU_INGRESS_<LANE>_WINDOW_MS, TM_TPU_INGRESS_<LANE>_BUDGET_MS,
TM_TPU_INGRESS_<LANE>_ADAPTIVE, and the global TM_TPU_INGRESS_ADAPTIVE.

This module imports neither jax nor the pipeline at module level: the
controller and engine mechanics are testable in a jax-free interpreter
(tests/test_ingress_fabric.py), and lanes resolve their verifier
lazily exactly like the accumulators they replaced.
"""

from __future__ import annotations

import os
import queue
import threading
import time
import warnings
from concurrent.futures import Future
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

# QoS tiers — mirror ops/pipeline.py (asserted equal by the test suite;
# duplicated so the engine stays importable without numpy/jax).
PRIORITY_CONSENSUS = 0
PRIORITY_REPLAY = 1
PRIORITY_INGRESS = 2

# flush causes fed to the controller
CAUSE_FULL = "full"          # a window hit the batch target
CAUSE_TIMER = "timer"        # the base window elapsed
CAUSE_DEADLINE = "deadline"  # the SLO budget bound the window
CAUSE_MANUAL = "manual"      # flush_now()
CAUSE_STEPPED = "stepped"    # flush_pending() in stepped mode
CAUSE_CLOSE = "close"        # final drain on lane close

# Per-lane defaults: base batch/window (the pre-fabric knob defaults,
# unchanged) and the p99 budget the deadline-aware flush respects.
# Consensus votes carry the paper's 5 ms hot-path budget; the others
# are configurable via TM_TPU_INGRESS_<LANE>_BUDGET_MS.
LANE_DEFAULTS: Dict[str, Dict[str, float]] = {
    "mempool": {"batch": 256, "window_ms": 4.0, "budget_ms": 25.0},
    "votes": {"batch": 128, "window_ms": 2.0, "budget_ms": 5.0},
    "light": {"batch": 64, "window_ms": 0.0, "budget_ms": 20.0},
    "replay": {"batch": 512, "window_ms": 0.0, "budget_ms": 0.0},
}

_warned_legacy: set = set()


def _warn_legacy(old: str, new: str) -> None:
    if old in _warned_legacy:
        return
    _warned_legacy.add(old)
    warnings.warn(
        f"{old} is deprecated; use {new} (lane-keyed ingress knobs)",
        DeprecationWarning, stacklevel=3,
    )


def env_setting(new: str, old: Optional[str] = None) -> Optional[str]:
    """Read a lane-keyed TM_TPU_INGRESS_* env knob, honoring its legacy
    per-workload name with a one-time DeprecationWarning."""
    v = os.environ.get(new)
    if v is not None:
        return v
    if old is not None:
        v = os.environ.get(old)
        if v is not None:
            _warn_legacy(old, new)
            return v
    return None


@dataclass
class LaneConfig:
    """Resolved knobs for one lane (see resolve_lane_config)."""

    batch: int
    window_ms: float
    budget_ms: Optional[float]
    adaptive: bool


def resolve_lane_config(
    lane: str,
    batch: Optional[int] = None,
    window_ms: Optional[float] = None,
    budget_ms: Optional[float] = None,
    adaptive: Optional[bool] = None,
    legacy_batch: Optional[str] = None,
    legacy_window: Optional[str] = None,
) -> LaneConfig:
    """Resolve one lane's knobs: explicit args > TM_TPU_INGRESS_<LANE>_*
    > legacy env names (DeprecationWarning) > LANE_DEFAULTS.

    Adaptivity defaults ON only when both batch and window came from
    env/defaults: a caller that pinned them (every bench column, every
    parity test, the soak harness) promised determinism and keeps it.
    TM_TPU_INGRESS_<LANE>_ADAPTIVE / TM_TPU_INGRESS_ADAPTIVE override
    either way."""
    d = LANE_DEFAULTS.get(lane, {"batch": 256, "window_ms": 4.0,
                                 "budget_ms": 0.0})
    up = lane.upper()
    explicit = batch is not None or window_ms is not None
    if batch is None:
        v = env_setting(f"TM_TPU_INGRESS_{up}_BATCH", legacy_batch)
        batch = int(v) if v is not None else int(d["batch"])
    if window_ms is None:
        v = env_setting(f"TM_TPU_INGRESS_{up}_WINDOW_MS", legacy_window)
        window_ms = float(v) if v is not None else float(d["window_ms"])
    if adaptive is None:
        v = env_setting(f"TM_TPU_INGRESS_{up}_ADAPTIVE") or env_setting(
            "TM_TPU_INGRESS_ADAPTIVE")
        adaptive = (v == "1") if v is not None else not explicit
    if budget_ms is None:
        v = env_setting(f"TM_TPU_INGRESS_{up}_BUDGET_MS")
        if v is not None:
            budget_ms = float(v)
        else:
            # the default SLO budget engages only with adaptivity: a
            # caller that pinned batch/window (benches, parity tests)
            # gets EXACTLY the flush timing it pinned
            budget_ms = float(d.get("budget_ms") or 0.0) if adaptive else 0.0
    return LaneConfig(batch=max(int(batch), 1),
                      window_ms=max(float(window_ms), 0.0),
                      budget_ms=(float(budget_ms) or None),
                      adaptive=bool(adaptive))


class AdaptiveWindow:
    """SLO-aware window controller — pure state machine, no clocks.

    Feeds: `on_flush(depth, cause)` per flush cycle and
    `note_service(ms)` per completed device window. Outputs:
    `batch_target()` (current size trigger) and `effective_window_ms()`
    (current time trigger). Policy:

    * deepen under flood — a FULL flush at the current target grows the
      window ×1.5 and the target ×2 (throughput: more signatures per
      relay command), up to 8× the configured base;
    * shrink when idle — SHRINK_PATIENCE consecutive timer flushes each
      carrying ≤¼ of the target halve both, down to ¼ window / base
      batch (latency: a lone request is not taxed a flood-depth window;
      the patience is hysteresis — one jitter-thinned flush mid-flood
      must not collapse a window the next burst will need);
    * deadline-aware — the effective window never exceeds
      `budget_ms - 2×(service-time EWMA)`: the flush fires early enough
      that submit + device service still fit the lane's p99 budget.

    `adaptive=False` freezes the base batch/window (existing call sites
    that pinned their knobs) but keeps the deadline bound when a budget
    is set — SLO awareness is not optional, adaptivity is.
    """

    GROW_WINDOW = 1.5
    GROW_BATCH = 2
    SHRINK = 0.5
    IDLE_FRACTION = 0.25
    SHRINK_PATIENCE = 2   # consecutive idle flushes before shrinking
    SPAN = 8.0            # max window / base window (and batch cap ×8)
    ALPHA = 0.3           # service-time EWMA weight
    SAFETY = 2.0          # budget headroom in service-time multiples

    def __init__(self, batch: int, window_ms: float,
                 budget_ms: Optional[float] = None,
                 adaptive: bool = True):
        self.base_batch = max(int(batch), 1)
        self.base_window_ms = max(float(window_ms), 0.0)
        self.budget_ms = float(budget_ms) if budget_ms else None
        self.adaptive = bool(adaptive)
        self.min_window_ms = self.base_window_ms / 4.0
        self.max_window_ms = self.base_window_ms * self.SPAN
        self.batch_cap = int(self.base_batch * self.SPAN)
        self.batch = self.base_batch
        self.window_ms = self.base_window_ms
        self.service_ewma_ms = 0.0
        self.deadline_bound = False   # last effective window was budget-clamped
        self.grows = 0
        self.shrinks = 0
        self.deadline_flushes = 0
        self._idle_streak = 0

    def batch_target(self) -> int:
        return self.batch

    def effective_window_ms(self) -> float:
        """The live time trigger: the adaptive window, clamped so flush +
        expected device service still fit inside the lane's budget."""
        w = self.window_ms
        if self.budget_ms is not None:
            lim = self.budget_ms - self.SAFETY * self.service_ewma_ms
            lim = max(lim, self.min_window_ms)
            if lim < w:
                self.deadline_bound = True
                return lim
        self.deadline_bound = False
        return w

    def note_service(self, ms: float) -> None:
        if ms < 0.0:
            return
        if self.service_ewma_ms == 0.0:
            self.service_ewma_ms = ms
        else:
            self.service_ewma_ms += self.ALPHA * (ms - self.service_ewma_ms)

    def on_flush(self, depth: int, cause: str) -> None:
        if cause == CAUSE_DEADLINE:
            self.deadline_flushes += 1
        if not self.adaptive or cause in (CAUSE_MANUAL, CAUSE_STEPPED,
                                          CAUSE_CLOSE):
            return
        if cause == CAUSE_FULL and depth >= self.batch:
            self._idle_streak = 0
            grew = False
            if self.batch < self.batch_cap:
                self.batch = min(self.batch * self.GROW_BATCH,
                                 self.batch_cap)
                grew = True
            if self.window_ms < self.max_window_ms:
                self.window_ms = min(self.window_ms * self.GROW_WINDOW,
                                     self.max_window_ms)
                grew = True
            if grew:
                self.grows += 1
        elif cause in (CAUSE_TIMER, CAUSE_DEADLINE):
            if depth <= max(self.batch * self.IDLE_FRACTION, 1.0):
                self._idle_streak += 1
                if self._idle_streak < self.SHRINK_PATIENCE:
                    return
                shrank = False
                if self.batch > self.base_batch:
                    self.batch = max(int(self.batch * self.SHRINK),
                                     self.base_batch)
                    shrank = True
                if self.window_ms > self.min_window_ms:
                    self.window_ms = max(self.window_ms * self.SHRINK,
                                         self.min_window_ms)
                    shrank = True
                if shrank:
                    self.shrinks += 1
            else:
                self._idle_streak = 0


@dataclass
class LaneSpec:
    """Everything lane-specific the engine needs — a workload IS this
    spec plus its host-stage check and verdict-apply callbacks.

    deliver(items, verdicts, err) receives the window's IngressItems in
    submission order; verdicts is None iff err is set. It runs on the
    completer thread when `use_completer`, else on the flusher/resolver
    thread — and must be enqueue-only in the latter case."""

    name: str                                  # metric label + registry key
    priority: int = PRIORITY_INGRESS
    batch: int = 256
    window_ms: float = 4.0
    budget_ms: Optional[float] = None
    adaptive: bool = False
    stepped: bool = False
    full_by_window: bool = False   # size trigger per keyed window (votes)
                                   # vs total lane depth (mempool)
    device_threshold: int = 0      # windows below this host-verify
                                   # (unless TM_TPU_FORCE_DEVICE=1)
    use_completer: bool = False    # deliver + host_fn on completer thread
    submit_error_to_host: bool = False  # pre-submit failure → host verify
    closed_msg: str = "ingress lane is closed"
    # None → ops.pipeline.shared_verifier(). Anything submit()-shaped
    # plugs in here — including fleet.client.FleetClient, which routes
    # the lane's flushed windows over the wire to a remote device fleet
    # (ISSUE 18). A remote verifier signals post-submit death by failing
    # futures with an error whose `fallback_to_host` attr is true: such
    # windows host-verify via host_fn (counted remote_fallbacks) instead
    # of poisoning. Pre-submit raises ride submit_error_to_host as ever.
    verifier: Any = None
    # callbacks (None where a lane has no use for the seam)
    entries_fn: Optional[Callable[[Any], Tuple[bytes, bytes, bytes]]] = None
    route_fn: Optional[Callable[[Any], bool]] = None   # True → device lane
    attach_fn: Optional[Callable[[Any, Any, List[Any]], None]] = None
    flow_fn: Optional[Callable[[Any], Optional[int]]] = None
    trace_fn: Optional[Callable[[List[Any], int], None]] = None
    host_fn: Optional[Callable[[List[Any]], Sequence[bool]]] = None
    deliver: Optional[Callable[
        [List["IngressItem"], Optional[Sequence[bool]],
         Optional[BaseException]], None]] = None
    observer: Any = None           # legacy metric mirror (duck-typed)


class IngressItem:
    """One queued submission riding a window."""

    __slots__ = ("item", "future", "t_enq")

    def __init__(self, item: Any, t_enq: float, want_future: bool = False):
        self.item = item
        self.future: Optional[Future] = Future() if want_future else None
        self.t_enq = t_enq


def _observe(obs: Any, method: str, *args) -> None:
    """Call an optional legacy-metric mirror — observability never fatal."""
    if obs is None:
        return
    fn = getattr(obs, method, None)
    if fn is None:
        return
    try:
        fn(*args)
    except Exception:  # noqa: BLE001
        pass


class Lane:
    """One registered workload on the engine. Created via
    IngressEngine.register(spec); all mutable window state is guarded by
    the ENGINE mutex (one scheduler means one lock suffices)."""

    def __init__(self, engine: "IngressEngine", spec: LaneSpec):
        self.engine = engine
        self.spec = spec
        self.ctrl = AdaptiveWindow(spec.batch, spec.window_ms,
                                   budget_ms=spec.budget_ms,
                                   adaptive=spec.adaptive)
        self._v = spec.verifier
        self._v_hooked = False
        # window state — engine-mutex guarded
        self._windows: Dict[Any, List[IngressItem]] = {}
        self._inwindow: set = set()
        self._depth = 0
        self._t_first = 0.0
        self._force = False            # flush_now / window<=0 / full
        self._manual = False           # the force came from flush_now
        self._inflight = 0             # submitted, verdict not delivered
        self._host_inflight = 0        # parked on the completer queue
        self._closed = False
        # counters (read via stats(); labeled metrics mirror them)
        self.batches = 0
        self.sigs = 0
        self.host_lane_sigs = 0        # route_fn-directed host items
        self.window_dups = 0
        self.sync_fallbacks = 0
        self.preempted = 0
        self.dispatch_errors = 0
        self.remote_fallbacks = 0      # remote verifier died post-submit
        self.blocks = 0                # whole-block passthrough submits
        self._wait_ms_sum = 0.0
        self._flush_t0: Dict[int, float] = {}   # inflight window → t_submit

    # -- wiring -----------------------------------------------------------

    def _verifier(self):
        if self._v is None:
            from . import pipeline as _pl

            self._v = _pl.shared_verifier()
        if not self._v_hooked:
            self._v_hooked = True
            hook = getattr(self._v, "add_preempt_hook", None)
            if hook is not None:
                hook(self._note_preempt)
        return self._v

    def _note_preempt(self, n: int) -> None:
        self.preempted += n
        self.engine._m_preempt(self.spec.name, n)
        _observe(self.spec.observer, "preempt", n)

    # -- submission -------------------------------------------------------

    def submit(self, item: Any, key: Any = None,
               dedup_key: Any = None, t_enq: Optional[float] = None,
               want_future: bool = False) -> Optional[Future]:
        """Queue one item into the window keyed by `key`. Returns a
        per-item Future when `want_future` (resolved by deliver());
        returns None on an in-window duplicate drop."""
        if self._closed:
            raise RuntimeError(self.spec.closed_msg)
        it = IngressItem(item, t_enq or time.perf_counter(), want_future)
        eng = self.engine
        with eng._mtx:
            if dedup_key is not None:
                if dedup_key in self._inwindow:
                    self.window_dups += 1
                    return None
                self._inwindow.add(dedup_key)
            win = self._windows.get(key)
            if win is None:
                win = self._windows[key] = []
            if not self._depth:
                self._t_first = it.t_enq
            win.append(it)
            self._depth += 1
            depth = self._depth
            size = len(win) if self.spec.full_by_window else depth
            full = (size >= self.ctrl.batch_target()
                    or self.ctrl.effective_window_ms() <= 0.0)
            if full and not self.spec.stepped:
                self._force = True
        eng._m_depth(self.spec.name, depth)
        _observe(self.spec.observer, "depth", depth)
        if not self.spec.stepped:
            eng._kick()
        return it.future

    def submit_block(self, block, flow: Optional[int] = None,
                     priority: Optional[int] = None,
                     count: bool = True):
        """Whole-block passthrough (light header stages, mempool recheck,
        replay fused ranges): submit straight to the lane's verifier at
        its QoS tier, count it, return the PIPELINE future — resolved on
        the resolver thread, safe to wait on while holding workload
        locks that deliver() would need. `count=False` keeps the block
        out of the lane's batches/sigs counters (mempool recheck, whose
        legacy stats never counted recheck traffic)."""
        if priority is None:
            priority = self.spec.priority
        if priority == PRIORITY_CONSENSUS:
            # CONSENSUS is the pipeline's default tier — omit the kwarg
            # so narrow duck-typed verifiers (submit(entries, flow=None))
            # keep working
            fut = self._verifier().submit(block, flow=flow)
        else:
            fut = self._verifier().submit(block, flow=flow,
                                          priority=priority)
        n = len(block)
        if count:
            with self.engine._mtx:
                self.blocks += 1
                self.sigs += n
        self.engine._m_block(self.spec.name, n)
        return fut

    def flush_now(self) -> None:
        if self.spec.stepped:
            self.flush_pending()
            return
        with self.engine._mtx:
            self._force = True
            self._manual = True
        self.engine._kick()

    def flush_pending(self) -> bool:
        """Stepped-mode flush point: host-verify every open window in
        submission order and apply inline on the CALLER's thread.
        Returns True when anything flushed."""
        taken = self._take()
        if not taken:
            return False
        for _key, items in taken:
            self._note_flush(items)
            self._host(items, fallback=True)
        self.ctrl.on_flush(sum(len(i) for _, i in taken), CAUSE_STEPPED)
        return True

    # -- flush machinery (engine-driven) ----------------------------------

    def _take(self) -> List[Tuple[Any, List[IngressItem]]]:
        with self.engine._mtx:
            taken = list(self._windows.items())
            self._windows = {}
            self._inwindow.clear()
            self._depth = 0
            self._t_first = 0.0
            self._force = False
            self._manual = False
        return taken

    def _classify_locked(self, now: float) -> Optional[str]:
        """Under the engine mutex: is this lane due, and why? None when
        not due; the scheduler flushes due lanes after releasing."""
        if self._closed or self.spec.stepped or not self._depth:
            return None
        if self._force:
            if self._manual:
                return CAUSE_MANUAL
            return CAUSE_FULL
        w_ms = self.ctrl.effective_window_ms()
        if now - self._t_first >= w_ms / 1e3:
            return CAUSE_DEADLINE if self.ctrl.deadline_bound else CAUSE_TIMER
        return None

    def _deadline_locked(self) -> Optional[float]:
        if self._closed or self.spec.stepped or not self._depth:
            return None
        if self._force:
            return 0.0
        return self._t_first + self.ctrl.effective_window_ms() / 1e3

    def _note_flush(self, items: List[IngressItem]) -> None:
        now = time.perf_counter()
        wait_ms = max(
            (now - min((it.t_enq or now) for it in items)) * 1e3, 0.0)
        with self.engine._mtx:
            self.batches += 1
            self.sigs += len(items)
            self._wait_ms_sum += wait_ms
        self.engine._m_flush(self.spec.name, len(items), wait_ms)
        _observe(self.spec.observer, "flush", len(items), wait_ms)

    def _flush(self, cause: str) -> None:
        """Take and dispatch every open window. Runs on the scheduler
        thread (or the closing thread for the final drain) with NO lock
        held — staging happened in _take()."""
        taken = self._take()
        if not taken:
            return
        total = 0
        for key, items in taken:
            total += len(items)
            self._note_flush(items)
            if self.spec.route_fn is not None:
                dev = [it for it in items if self.spec.route_fn(it.item)]
                host = [it for it in items
                        if not self.spec.route_fn(it.item)]
            else:
                dev, host = items, []
            if host:
                with self.engine._mtx:
                    self.host_lane_sigs += len(host)
                self.engine._m_host_lane(self.spec.name, len(host))
                self._host(host, fallback=False)
            if dev:
                self._flush_device(key, dev)
        self.ctrl.on_flush(total, cause)
        self.engine._m_window(self.spec.name, self.ctrl)
        _observe(self.spec.observer, "depth", 0)

    def _flush_device(self, key: Any, items: List[IngressItem]) -> None:
        spec = self.spec
        force = os.environ.get("TM_TPU_FORCE_DEVICE", "0") == "1"
        if len(items) < spec.device_threshold and not force:
            self._host(items, fallback=True)
            return
        t0 = time.perf_counter()
        try:
            from .entry_block import EntryBlock

            block = EntryBlock.from_entries(
                [spec.entries_fn(it.item) for it in items])
            if spec.attach_fn is not None:
                spec.attach_fn(block, key, [it.item for it in items])
            flow = None
            if spec.flow_fn is not None:
                flow = next((f for f in (spec.flow_fn(it.item)
                                         for it in items)
                             if f is not None), None)
            if flow is not None and spec.trace_fn is not None:
                spec.trace_fn([it.item for it in items], flow)
            with self.engine._mtx:
                self._inflight += 1
            fut = self._verifier().submit(block, flow=flow,
                                          priority=spec.priority)
        except Exception as e:  # noqa: BLE001 — window isolation:
            # engine absent/closed or a build failure hits exactly this
            # window; only post-submit DispatchErrors poison futures
            with self.engine._mtx:
                self._inflight = max(self._inflight - 1, 0)
            if spec.submit_error_to_host:
                self._host(items, fallback=True)
            else:
                self._deliver(items, None, e)
            return
        self._flush_t0[id(fut)] = t0
        if spec.use_completer:
            # done-callback runs on the pipeline resolver: ONLY enqueue —
            # the completer owns any work that may take workload locks
            fut.add_done_callback(
                lambda f, b=items: self.engine._cq_put(
                    ("device", self, b, f)))
        else:
            fut.add_done_callback(
                lambda f, b=items: self._complete_device(b, f,
                                                         dec_first=True))

    def _complete_device(self, items: List[IngressItem], fut,
                         dec_first: bool = False) -> None:
        if dec_first:
            with self.engine._mtx:
                self._inflight = max(self._inflight - 1, 0)
        t0 = self._flush_t0.pop(id(fut), None)
        if t0 is not None:
            self.ctrl.note_service((time.perf_counter() - t0) * 1e3)
        err = fut.exception()
        if err is not None:
            # graceful degradation (ISSUE 18): a remote verifier that
            # died AFTER submit marks its error fallback_to_host (duck-
            # typed — fleet.client.FleetUnavailable; ingress never
            # imports fleet). The window host-verifies instead of
            # poisoning: zero lost items, and the lane keeps flowing
            # while the client rejoins.
            if (getattr(err, "fallback_to_host", False)
                    and self.spec.host_fn is not None):
                with self.engine._mtx:
                    self.remote_fallbacks += 1
                self.engine._m_remote_fallback(self.spec.name)
                _observe(self.spec.observer, "remote_fallback")
                try:
                    # fallback=False: remote_fallbacks is the counter
                    # here, not sync_fallbacks (disjoint taxonomies)
                    self._host(items, fallback=False)
                    return
                except Exception as e:  # noqa: BLE001 — fallback failed
                    self._count_dispatch_error()
                    self._deliver(items, None, e)
                    return
            # poisoned window: exactly these items fail; the lane and
            # every later window keep flowing (items left the dedup set
            # at stage time, so a retry re-enters cleanly)
            self._count_dispatch_error()
            self._deliver(items, None, err)
            return
        try:
            verdicts = [bool(v) for v in fut.result()]
            self._deliver(items, verdicts, None)
        except Exception as e:  # noqa: BLE001 — a delivery failure is
            # handed back like a dispatch failure, never swallowed
            self._count_dispatch_error()
            self._deliver(items, None, e)

    def _count_dispatch_error(self) -> None:
        with self.engine._mtx:
            self.dispatch_errors += 1
        self.engine._m_dispatch_error(self.spec.name)
        _observe(self.spec.observer, "dispatch_error")

    def _host(self, items: List[IngressItem], fallback: bool) -> None:
        """Host-verify one window — inline, or parked on the completer
        queue for use_completer lanes. `fallback` distinguishes the sync
        fallback (sub-threshold / stepped / engine absent) from
        route_fn-directed host-lane traffic."""
        if fallback:
            with self.engine._mtx:
                self.sync_fallbacks += 1
            self.engine._m_sync_fallback(self.spec.name)
            _observe(self.spec.observer, "sync_fallback")
        if self.spec.use_completer:
            with self.engine._mtx:
                self._host_inflight += 1
            self.engine._cq_put(("host", self, items, None))
        else:
            self._run_host(items)

    def _run_host(self, items: List[IngressItem]) -> None:
        verdicts = self.spec.host_fn([it.item for it in items])
        self._deliver(items, verdicts, None)

    def _deliver(self, items: List[IngressItem],
                 verdicts: Optional[Sequence[bool]],
                 err: Optional[BaseException]) -> None:
        if self.spec.deliver is not None:
            self.spec.deliver(items, verdicts, err)

    # -- lifecycle / introspection ----------------------------------------

    def stats(self) -> dict:
        with self.engine._mtx:
            depth = self._depth
        return {
            "queue_depth": depth,
            "batches": self.batches,
            "sigs": self.sigs,
            "host_lane_sigs": self.host_lane_sigs,
            "window_dups": self.window_dups,
            "sync_fallbacks": self.sync_fallbacks,
            "batch_wait_ms_avg": (
                self._wait_ms_sum / self.batches if self.batches else 0.0
            ),
            "preemptions": self.preempted,
            "dispatch_errors": self.dispatch_errors,
            "remote_fallbacks": self.remote_fallbacks,
            "blocks": self.blocks,
            "max_batch": self.ctrl.batch_target(),
            "window_ms": self.ctrl.window_ms,
            "budget_ms": self.ctrl.budget_ms or 0.0,
            "adaptive": self.ctrl.adaptive,
            "stepped": self.spec.stepped,
            "window_grows": self.ctrl.grows,
            "window_shrinks": self.ctrl.shrinks,
            "deadline_flushes": self.ctrl.deadline_flushes,
        }

    def close(self, timeout: float = 10.0) -> None:
        """Drain and retire the lane: flush open windows on the calling
        thread, then wait for every in-flight verdict to deliver. The
        engine (shared, process-wide) keeps running for other lanes."""
        with self.engine._mtx:
            if self._closed:
                return
            self._closed = True
        self._flush(CAUSE_CLOSE)
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            with self.engine._mtx:
                if self._inflight == 0 and self._host_inflight == 0:
                    break
            time.sleep(0.005)
        self.engine._unregister(self)


class IngressEngine:
    """The fabric: ONE flush scheduler and ONE completer thread serving
    every registered lane (threads start lazily, on first need). Lanes
    may carry different verifiers — tests and multi-node sims register
    private-verifier lanes on the same engine."""

    def __init__(self):
        self._mtx = threading.Lock()
        self._lanes: List[Lane] = []
        self._wake = threading.Event()
        self._cq: "queue.Queue" = queue.Queue()
        self._sched: Optional[threading.Thread] = None
        self._cthread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._metrics = None

    # -- registration -----------------------------------------------------

    def register(self, spec: LaneSpec) -> Lane:
        lane = Lane(self, spec)
        with self._mtx:
            self._lanes.append(lane)
        if not spec.stepped:
            self._ensure_scheduler()
        if spec.use_completer:
            self._ensure_completer()
        self._m_window(spec.name, lane.ctrl)
        return lane

    def _unregister(self, lane: Lane) -> None:
        with self._mtx:
            if lane in self._lanes:
                self._lanes.remove(lane)

    # -- threads ----------------------------------------------------------

    def _ensure_scheduler(self) -> None:
        with self._mtx:
            if self._sched is None or not self._sched.is_alive():
                self._sched = threading.Thread(
                    target=self._scheduler, daemon=True,
                    name="ingress-fabric-flush")
                self._sched.start()

    def _ensure_completer(self) -> None:
        with self._mtx:
            if self._cthread is None or not self._cthread.is_alive():
                self._cthread = threading.Thread(
                    target=self._completer, daemon=True,
                    name="ingress-fabric-complete")
                self._cthread.start()

    def _kick(self) -> None:
        self._wake.set()

    def _cq_put(self, item) -> None:
        self._ensure_completer()
        self._cq.put(item)

    def _scheduler(self) -> None:
        while not self._stop.is_set():
            due: List[Tuple[Lane, str]] = []
            nxt: Optional[float] = None
            with self._mtx:
                lanes = list(self._lanes)
            now = time.perf_counter()
            with self._mtx:
                for lane in lanes:
                    cause = lane._classify_locked(now)
                    if cause is not None:
                        due.append((lane, cause))
                        continue
                    dl = lane._deadline_locked()
                    if dl is not None:
                        nxt = dl if nxt is None else min(nxt, dl)
            for lane, cause in due:
                try:
                    lane._flush(cause)
                except Exception:  # noqa: BLE001 — a lane's flush bug
                    # must not stall the other lanes' scheduler
                    pass
            if due:
                continue
            if nxt is None:
                self._wake.wait(0.05)
            else:
                self._wake.wait(min(max(nxt - now, 0.0), 0.05))
            self._wake.clear()

    def _completer(self) -> None:
        while True:
            item = self._cq.get()
            if item is None:
                break
            kind, lane, items, fut = item
            try:
                if kind == "device":
                    lane._complete_device(items, fut)
                else:
                    lane._run_host(items)
            except Exception:  # noqa: BLE001 — one lane's completion
                # bug must not kill the shared completer
                pass
            finally:
                with self._mtx:
                    if kind == "device":
                        lane._inflight = max(lane._inflight - 1, 0)
                    else:
                        lane._host_inflight = max(
                            lane._host_inflight - 1, 0)

    def close(self, timeout: float = 10.0) -> None:
        """Stop the engine threads (used by tests owning a private
        engine; the process-wide shared engine is never closed)."""
        self._stop.set()
        self._wake.set()
        if self._sched is not None:
            self._sched.join(timeout=timeout)
        self._cq.put(None)
        if self._cthread is not None:
            self._cthread.join(timeout=timeout)

    # -- labeled metrics (satellite 1) ------------------------------------

    def _m(self):
        if self._metrics is None:
            try:
                from ..libs import metrics as _m

                self._metrics = _m.ingress_metrics()
            except Exception:  # noqa: BLE001 — observability never fatal
                return None
        return self._metrics

    def _m_depth(self, lane: str, depth: int) -> None:
        m = self._m()
        if m is not None:
            try:
                m.queue_depth.set(depth, lane=lane)
            except Exception:  # noqa: BLE001
                pass

    def _m_flush(self, lane: str, n: int, wait_ms: float) -> None:
        m = self._m()
        if m is not None:
            try:
                m.batches.inc(1, lane=lane)
                m.sigs.inc(n, lane=lane)
                m.batch_wait_ms.observe(wait_ms, lane=lane)
                m.queue_depth.set(0, lane=lane)
            except Exception:  # noqa: BLE001
                pass

    def _m_host_lane(self, lane: str, n: int) -> None:
        m = self._m()
        if m is not None:
            try:
                m.host_lane_sigs.inc(n, lane=lane)
            except Exception:  # noqa: BLE001
                pass

    def _m_sync_fallback(self, lane: str) -> None:
        m = self._m()
        if m is not None:
            try:
                m.sync_fallbacks.inc(1, lane=lane)
            except Exception:  # noqa: BLE001
                pass

    def _m_remote_fallback(self, lane: str) -> None:
        m = self._m()
        if m is not None:
            try:
                m.remote_fallbacks.inc(1, lane=lane)
            except Exception:  # noqa: BLE001
                pass

    def _m_dispatch_error(self, lane: str) -> None:
        m = self._m()
        if m is not None:
            try:
                m.dispatch_errors.inc(1, lane=lane)
            except Exception:  # noqa: BLE001
                pass

    def _m_preempt(self, lane: str, n: int) -> None:
        m = self._m()
        if m is not None:
            try:
                m.preemptions.inc(n, lane=lane)
            except Exception:  # noqa: BLE001
                pass

    def _m_block(self, lane: str, n: int) -> None:
        m = self._m()
        if m is not None:
            try:
                m.blocks.inc(1, lane=lane)
                m.sigs.inc(n, lane=lane)
            except Exception:  # noqa: BLE001
                pass

    def _m_window(self, lane: str, ctrl: AdaptiveWindow) -> None:
        m = self._m()
        if m is not None:
            try:
                m.window_ms.set(ctrl.window_ms, lane=lane)
                m.batch_target.set(ctrl.batch_target(), lane=lane)
                m.deadline_flushes.inc(0, lane=lane)
            except Exception:  # noqa: BLE001
                pass

    # -- introspection ----------------------------------------------------

    def lanes(self) -> List[Lane]:
        with self._mtx:
            return list(self._lanes)

    def stats(self) -> Dict[str, dict]:
        out: Dict[str, dict] = {}
        for lane in self.lanes():
            out[lane.spec.name] = lane.stats()
        return out


class BlockFuser:
    """The replay range fuse, engine-owned: pack per-height EntryBlocks
    into lane submissions of at most `cap` signatures. add() concludes a
    chunk when the next block would overflow; flush() concludes the
    tail. Each concluded chunk is ONE verifier command; `on_chunk(fut,
    parts)` receives the pipeline future plus (tag, offset, length)
    per packed block so the caller can slice verdicts back out."""

    def __init__(self, lane: Lane, cap: int,
                 on_chunk: Callable[[Any, List[Tuple[Any, int, int]]], None],
                 flow: Optional[int] = None):
        self.lane = lane
        self.cap = max(int(cap), 1)
        self.on_chunk = on_chunk
        self.flow = flow
        self._blocks: List[Any] = []
        self._parts: List[Tuple[Any, int, int]] = []
        self._n = 0

    def add(self, tag: Any, block) -> None:
        n = len(block)
        if self._n and self._n + n > self.cap:
            self.flush()
        self._blocks.append(block)
        self._parts.append((tag, self._n, n))
        self._n += n

    def flush(self) -> None:
        if not self._blocks:
            return
        from .entry_block import EntryBlock

        fused = (self._blocks[0] if len(self._blocks) == 1
                 else EntryBlock.concat(self._blocks))
        parts = self._parts
        self._blocks, self._parts, self._n = [], [], 0
        fut = self.lane.submit_block(fused, flow=self.flow)
        self.on_chunk(fut, parts)


# ---------------------------------------------------------------------------
# process-wide engine
# ---------------------------------------------------------------------------

_shared_mtx = threading.Lock()
_shared: Optional[IngressEngine] = None


def shared_engine() -> IngressEngine:
    """THE process-wide fabric — every lane in the process shares its
    one scheduler and one completer (multi-node sims included: lanes
    carry their own verifiers, the threads are common infrastructure)."""
    global _shared
    with _shared_mtx:
        if _shared is None:
            _shared = IngressEngine()
        return _shared
