"""Scalar arithmetic mod n (the secp256k1 group order) + GLV decomposition.

The ECDSA lane's scalar layer (ISSUE 19), mirroring `sc.py`'s place in the
ed25519 stack — with one structural difference: ECDSA's scalar work
(s^-1 mod n, u1 = e/s, u2 = r/s) is a handful of 256-bit bigint ops per
signature and does NOT sit inside the device hot loop, so this module is
host-side math: Python-int modular arithmetic (batched inversion via the
Montgomery product trick), the GLV endomorphism split that halves the
device ladder length, and the numpy packing that ships the split scalars
to the kernel as 13-bit limb rows.

GLV: secp256k1 has the efficient endomorphism phi(x, y) = (beta*x, y) =
[lambda]P (beta^3 = 1 mod p, lambda^3 = 1 mod n). Any scalar u splits as
u = u_a + u_b*lambda (mod n) with |u_a|, |u_b| < 2^129, so the kernel's
joint ladder runs 130 iterations over four ~half-width scalars instead of
256 over two full-width ones. Constants are the standard lattice basis
(libsecp256k1 scalar_impl.h); the lattice membership identities are
asserted at import."""

from __future__ import annotations

import numpy as np

from ..crypto.secp256k1 import _N as N

N_HALF = N // 2  # lower-S bound: valid signatures have s <= N_HALF

# The endomorphism pair: beta (mod p) acts on x; lambda (mod n) acts on
# the scalar. phi(P) = (beta*x, y) = [lambda]P for all P on the curve.
LAMBDA = 0x5363AD4CC05C30E0A5261C028812645A122E22EA20816678DF02967C1B23BD72
BETA = 0x7AE96A2B657C07106E64479EAC3434E99CF0497512F58995C1396C28719501EE

# Lattice basis vectors v1 = (A1, -B1), v2 = (A2, B2) of
# {(x, y) : x + y*lambda ≡ 0 (mod n)} — libsecp256k1's g1/g2 basis.
A1 = 0x3086D221A7D46BCDE86C90E49284EB15
B1 = 0xE4437ED6010E88286F547FA90ABFE4C3  # = -v1.y (stored positive)
A2 = 0x114CA50F7A8E2F3F657C1108D9D44CFD8
B2 = A1

# Lattice membership: both basis vectors must annihilate lambda mod n —
# the entire split correctness rests on these two congruences.
assert (A1 - B1 * LAMBDA) % N == 0
assert (A2 + B2 * LAMBDA) % N == 0

SCALAR_BITS = 130  # split magnitudes are < 2^129; one bit of headroom
SCALAR_LIMBS = 10  # ceil(130 / 13)
_RADIX = 13
_MASK = (1 << _RADIX) - 1


def glv_split(u: int) -> tuple[int, int]:
    """u in [0, n) -> (k1, k2) SIGNED ints with u ≡ k1 + k2*lambda (mod n)
    and |k1|, |k2| < 2^129 (round-to-nearest Babai on the basis above)."""
    c1 = (B2 * u + (N >> 1)) // N
    c2 = (B1 * u + (N >> 1)) // N
    k1 = u - c1 * A1 - c2 * A2
    k2 = c1 * B1 - c2 * B2
    return k1, k2


def glv_decompose(u: int) -> tuple[int, int, int, int]:
    """u -> (|k1|, sign1, |k2|, sign2); signs are 0/1 (1 = negate the
    base point on device)."""
    k1, k2 = glv_split(u)
    s1, s2 = int(k1 < 0), int(k2 < 0)
    m1, m2 = abs(k1), abs(k2)
    if m1 >> SCALAR_BITS or m2 >> SCALAR_BITS:  # pragma: no cover
        raise AssertionError("GLV split exceeded 130 bits")
    return m1, s1, m2, s2


def inv_mod_n_many(vals: list[int]) -> list[int]:
    """Batched modular inverses mod n (one pow + 3 mulmods per element via
    the Montgomery product trick). Zero entries pass through as 0 — the
    caller has already marked those rows invalid."""
    idx = [i for i, v in enumerate(vals) if v]
    out = [0] * len(vals)
    if not idx:
        return out
    prefix = []
    acc = 1
    for i in idx:
        prefix.append(acc)
        acc = acc * vals[i] % N
    inv = pow(acc, -1, N)
    for j in reversed(range(len(idx))):
        i = idx[j]
        out[i] = prefix[j] * inv % N
        inv = inv * vals[i] % N
    return out


def scalars_to_limbs(vals: list[int]) -> np.ndarray:
    """Nonnegative ints < 2^130 -> (B, 10) int32 rows of 13-bit limbs
    (LSB-first), the kernel's scalar wire format. Vectorized through a
    24-byte-per-row LE buffer -> 3 uint64 words -> 10 shifted windows."""
    if not vals:
        return np.zeros((0, SCALAR_LIMBS), dtype=np.int32)
    buf = b"".join(v.to_bytes(24, "little") for v in vals)
    w = np.frombuffer(buf, dtype="<u8").reshape(len(vals), 3)
    out = np.empty((len(vals), SCALAR_LIMBS), dtype=np.int32)
    for i in range(SCALAR_LIMBS):
        lo = _RADIX * i
        word, shift = lo >> 6, lo & 63
        v = w[:, word] >> np.uint64(shift)
        if shift + _RADIX > 64 and word + 1 < 3:
            v = v | (w[:, word + 1] << np.uint64(64 - shift))
        out[:, i] = (v & np.uint64(_MASK)).astype(np.int32)
    return out
