"""JSON-RPC HTTP client.

Reference parity: rpc/client/http — the Client interface's method surface
over HTTP JSON-RPC (plus the local in-process client, rpc/client/local).
"""

from __future__ import annotations

import base64
import json
import urllib.request
from typing import Optional

from .core import Environment, RPCError


class HTTPClient:
    def __init__(self, base_url: str):
        if not base_url.startswith("http"):
            base_url = "http://" + base_url.replace("tcp://", "")
        self._url = base_url.rstrip("/")
        self._id = 0

    def call(self, method: str, **params):
        self._id += 1
        body = json.dumps(
            {"jsonrpc": "2.0", "id": self._id, "method": method, "params": params}
        ).encode()
        req = urllib.request.Request(
            self._url, data=body, headers={"Content-Type": "application/json"}
        )
        with urllib.request.urlopen(req, timeout=30) as resp:
            obj = json.loads(resp.read())
        if "error" in obj:
            e = obj["error"]
            raise RPCError(e.get("code", -1), e.get("message", ""), e.get("data", ""))
        return obj["result"]

    # -- convenience methods (rpc/client/interface.go) --------------------

    def status(self):
        return self.call("status")

    def health(self):
        return self.call("health")

    def net_info(self):
        return self.call("net_info")

    def genesis(self):
        return self.call("genesis")

    def abci_info(self):
        return self.call("abci_info")

    def abci_query(self, path: str, data: bytes, height: int = 0, prove: bool = False):
        return self.call(
            "abci_query", path=path, data=data.hex(), height=height, prove=prove
        )

    def block(self, height: Optional[int] = None):
        return self.call("block", height=height) if height else self.call("block")

    def block_results(self, height: Optional[int] = None):
        return self.call("block_results", height=height) if height else self.call("block_results")

    def commit(self, height: Optional[int] = None):
        return self.call("commit", height=height) if height else self.call("commit")

    def validators(self, height: Optional[int] = None):
        return self.call("validators", height=height) if height else self.call("validators")

    def broadcast_tx_sync(self, tx: bytes):
        return self.call("broadcast_tx_sync", tx=base64.b64encode(tx).decode())

    def broadcast_tx_commit(self, tx: bytes):
        return self.call("broadcast_tx_commit", tx=base64.b64encode(tx).decode())

    def tx(self, tx_hash: bytes, prove: bool = False):
        return self.call("tx", hash=tx_hash.hex(), prove=prove)

    def unconfirmed_txs(self, limit: int = 30):
        return self.call("unconfirmed_txs", limit=limit)


class LocalRPCClient:
    """rpc/client/local — direct Environment calls in-process."""

    def __init__(self, env: Environment):
        self._env = env

    def __getattr__(self, name):
        return getattr(self._env, name)
