"""JSON-RPC HTTP client.

Reference parity: rpc/client/http — the Client interface's method surface
over HTTP JSON-RPC (plus the local in-process client, rpc/client/local).
"""

from __future__ import annotations

import base64
import json
import urllib.request
from typing import Optional

from .core import Environment, RPCError


class HTTPClient:
    """JSON-RPC over http:// or https://. For https, `ca_file` pins a CA
    bundle (self-signed server certs in tests/private deployments);
    `insecure=True` skips verification entirely (curl -k equivalent)."""

    def __init__(self, base_url: str, ca_file: str = "", insecure: bool = False):
        if not base_url.startswith("http"):
            base_url = "http://" + base_url.replace("tcp://", "")
        self._url = base_url.rstrip("/")
        self._id = 0
        self._ctx = None
        if self._url.startswith("https"):
            import ssl

            if insecure:
                self._ctx = ssl._create_unverified_context()
            else:
                self._ctx = ssl.create_default_context(
                    cafile=ca_file or None
                )

    def call(self, method: str, **params):
        self._id += 1
        body = json.dumps(
            {"jsonrpc": "2.0", "id": self._id, "method": method, "params": params}
        ).encode()
        req = urllib.request.Request(
            self._url, data=body, headers={"Content-Type": "application/json"}
        )
        with urllib.request.urlopen(req, timeout=30, context=self._ctx) as resp:
            obj = json.loads(resp.read())
        if "error" in obj:
            e = obj["error"]
            raise RPCError(e.get("code", -1), e.get("message", ""), e.get("data", ""))
        return obj["result"]

    # -- convenience methods (rpc/client/interface.go) --------------------

    def status(self):
        return self.call("status")

    def health(self):
        return self.call("health")

    def net_info(self):
        return self.call("net_info")

    def genesis(self):
        return self.call("genesis")

    def abci_info(self):
        return self.call("abci_info")

    def abci_query(self, path: str, data: bytes, height: int = 0, prove: bool = False):
        return self.call(
            "abci_query", path=path, data=data.hex(), height=height, prove=prove
        )

    def block(self, height: Optional[int] = None):
        return self.call("block", height=height) if height else self.call("block")

    def block_results(self, height: Optional[int] = None):
        return self.call("block_results", height=height) if height else self.call("block_results")

    def commit(self, height: Optional[int] = None):
        return self.call("commit", height=height) if height else self.call("commit")

    def validators(self, height: Optional[int] = None):
        return self.call("validators", height=height) if height else self.call("validators")

    def broadcast_tx_sync(self, tx: bytes):
        return self.call("broadcast_tx_sync", tx=base64.b64encode(tx).decode())

    def broadcast_tx_commit(self, tx: bytes):
        return self.call("broadcast_tx_commit", tx=base64.b64encode(tx).decode())

    def tx(self, tx_hash: bytes, prove: bool = False):
        return self.call("tx", hash=tx_hash.hex(), prove=prove)

    def unconfirmed_txs(self, limit: int = 30):
        return self.call("unconfirmed_txs", limit=limit)


class LocalRPCClient:
    """rpc/client/local — direct Environment calls in-process (local.go:1:
    the client apps embed when they run in the same process as the node)."""

    def __init__(self, env: Environment):
        self._env = env

    def __getattr__(self, name):
        return getattr(self._env, name)


class Call:
    """rpc/client/mock/client.go Call: one canned response (or error) for
    a method, optionally matched against specific args; also the record
    type the recorder keeps."""

    def __init__(self, name: str, args=None, response=None, error=None):
        self.name = name
        self.args = args
        self.response = response
        self.error = error

    def get_response(self, args):
        """mock/client.go GetResponse: error-only -> raise; response-only
        -> return; both set -> response iff args match, else error."""
        if self.response is None:
            if self.error is not None:
                raise self.error
            raise RuntimeError("mock call has no response or error")
        if self.error is None:
            return self.response
        if self.args == args:
            return self.response
        raise self.error


class MockClient:
    """rpc/client/mock — canned per-method responses + call recording.

    Configure with `mock.expect(Call("status", response={...}))`; every
    RPC method then resolves against the canned table, and `mock.calls`
    records (name, args, response_or_error) like mock/client.go's
    recorder. Unconfigured methods fall through to `base` (e.g. a
    LocalRPCClient) when one is given, else raise."""

    def __init__(self, base=None):
        self._canned = {}
        self._base = base
        self.calls: list = []

    def expect(self, call: Call) -> "MockClient":
        self._canned[call.name] = call
        return self

    def _invoke(self, name, **params):
        if name in self._canned:
            try:
                resp = self._canned[name].get_response(params or None)
            except Exception as e:
                self.calls.append(Call(name, params or None, error=e))
                raise
            self.calls.append(Call(name, params or None, response=resp))
            return resp
        if self._base is not None:
            fn = getattr(self._base, name)
            try:
                resp = fn(**params) if params else fn()
            except Exception as e:
                self.calls.append(Call(name, params or None, error=e))
                raise
            self.calls.append(Call(name, params or None, response=resp))
            return resp
        raise NotImplementedError(f"mock client: no expectation for {name!r}")

    def __getattr__(self, name):
        if name.startswith("_"):
            raise AttributeError(name)

        def method(**params):
            return self._invoke(name, **params)

        return method


class WSClient:
    """Websocket JSON-RPC client with event subscriptions — the client
    half of rpc/jsonrpc/client/ws_client.go + rpc/client/http Subscribe:
    one connection carries request/response calls AND pushed subscription
    events (demuxed by id: calls echo the integer id, event pushes carry
    the server's "<query>#event" string id)."""

    def __init__(self, addr: str, timeout: float = 10.0,
                 ca_file: str = "", insecure: bool = False):
        import os
        import socket as _s
        import threading

        from urllib.parse import urlsplit

        if "//" not in addr:
            addr = "//" + addr
        addr = addr.replace("tcp://", "http://").replace("wss://", "https://")
        addr = addr.replace("ws://", "http://")
        parts = urlsplit(addr, scheme="http")
        host = parts.hostname or "127.0.0.1"
        port = parts.port or 26657
        self._sock = _s.create_connection((host, port), timeout=timeout)
        if parts.scheme == "https":  # wss: TLS under the websocket frames
            import ssl

            if insecure:
                ctx = ssl._create_unverified_context()
            else:
                ctx = ssl.create_default_context(cafile=ca_file or None)
            self._sock = ctx.wrap_socket(self._sock, server_hostname=host)
        key = base64.b64encode(os.urandom(16)).decode()
        self._sock.sendall(
            (
                f"GET /websocket HTTP/1.1\r\nHost: {host}\r\n"
                "Upgrade: websocket\r\nConnection: Upgrade\r\n"
                f"Sec-WebSocket-Key: {key}\r\nSec-WebSocket-Version: 13\r\n\r\n"
            ).encode()
        )
        buf = b""
        while b"\r\n\r\n" not in buf:
            chunk = self._sock.recv(4096)
            if not chunk:
                raise ConnectionError("websocket handshake failed")
            buf += chunk
        headers, _, leftover = buf.partition(b"\r\n\r\n")
        if b"101" not in headers.split(b"\r\n", 1)[0]:
            raise ConnectionError(f"websocket upgrade refused: {headers[:80]!r}")
        # the handshake timeout must not govern the frame stream: an idle
        # subscription would otherwise kill the reader after `timeout`s
        self._sock.settimeout(None)
        # frame bytes the server pipelined behind the 101 must not be lost
        self._rfile = _LeftoverReader(leftover, self._sock.makefile("rb"))
        self._next_id = 0
        self._mtx = threading.Lock()
        self._write_mtx = threading.Lock()
        self._responses: dict = {}
        self._abandoned: set = set()
        self._resp_cv = threading.Condition(self._mtx)
        import queue as _q

        self._events: "_q.Queue[dict]" = _q.Queue()
        self._closed = threading.Event()
        self._reader = threading.Thread(target=self._read_loop, daemon=True)
        self._reader.start()

    # -- framing ---------------------------------------------------------

    def _send_frame(self, opcode: int, payload: bytes) -> None:
        import os

        from .websocket import encode_frame

        data = encode_frame(opcode, payload, mask=os.urandom(4))
        with self._write_mtx:  # reader PONGs race application calls
            self._sock.sendall(data)

    def _read_loop(self) -> None:
        from .websocket import OP_CLOSE, OP_PING, OP_PONG, OP_TEXT, read_frame

        try:
            while not self._closed.is_set():
                try:
                    frame = read_frame(self._rfile)
                except Exception:  # noqa: BLE001 — truncated frame/EOF
                    break
                if frame is None:
                    break
                opcode, payload = frame
                if opcode == OP_CLOSE:
                    break
                if opcode == OP_PING:
                    try:
                        self._send_frame(OP_PONG, payload)
                    except OSError:
                        break
                    continue
                if opcode != OP_TEXT:
                    continue
                try:
                    msg = json.loads(payload)
                except ValueError:
                    continue
                mid = msg.get("id")
                if isinstance(mid, str) and mid.endswith("#event"):
                    self._events.put(msg.get("result", {}))
                else:
                    with self._resp_cv:
                        if mid in self._abandoned:
                            self._abandoned.discard(mid)  # late reply: drop
                        else:
                            self._responses[mid] = msg
                            self._resp_cv.notify_all()
        finally:
            self._closed.set()
            with self._resp_cv:
                self._resp_cv.notify_all()

    # -- JSON-RPC --------------------------------------------------------

    def call(self, method: str, params: Optional[dict] = None, timeout: float = 30.0):
        import time as _t

        from .websocket import OP_TEXT

        with self._mtx:
            self._next_id += 1
            rid = self._next_id
        self._send_frame(
            OP_TEXT,
            json.dumps(
                {"jsonrpc": "2.0", "id": rid, "method": method, "params": params or {}}
            ).encode(),
        )
        deadline = _t.monotonic() + timeout
        with self._resp_cv:
            while rid not in self._responses:
                if self._closed.is_set():
                    raise ConnectionError("websocket closed")
                remaining = deadline - _t.monotonic()
                if remaining <= 0:
                    self._abandoned.add(rid)  # drop the late reply
                    raise TimeoutError(f"no response to {method} within {timeout}s")
                self._resp_cv.wait(timeout=min(remaining, 0.5))
            msg = self._responses.pop(rid)
        err = msg.get("error")
        if err:
            raise RPCError(
                err.get("code", -1), err.get("message", ""), err.get("data", "")
            )
        return msg.get("result")

    # -- subscriptions (rpc/client/http Subscribe) -----------------------

    def subscribe(self, query: str, timeout: float = 30.0) -> None:
        self.call("subscribe", {"query": query}, timeout=timeout)

    def unsubscribe(self, query: str, timeout: float = 30.0) -> None:
        self.call("unsubscribe", {"query": query}, timeout=timeout)

    def unsubscribe_all(self, timeout: float = 30.0) -> None:
        self.call("unsubscribe_all", {}, timeout=timeout)

    def next_event(self, timeout: float = 30.0) -> dict:
        """Next pushed subscription event: {"query", "data", "events"}."""
        import queue as _q

        try:
            return self._events.get(timeout=timeout)
        except _q.Empty:
            raise TimeoutError(f"no event within {timeout}s") from None

    def close(self) -> None:
        self._closed.set()
        try:
            self._sock.close()
        except OSError:
            pass


class _LeftoverReader:
    """File-like serving buffered bytes before the underlying stream —
    frame data the server pipelined behind the handshake response."""

    def __init__(self, leftover: bytes, rfile):
        self._buf = leftover
        self._rfile = rfile

    def read(self, n: int) -> bytes:
        out = b""
        if self._buf:
            out, self._buf = self._buf[:n], self._buf[n:]
            n -= len(out)
        if n > 0:
            out += self._rfile.read(n)
        return out
