"""RPC core — the method environment backing the JSON-RPC API.

Reference parity: internal/rpc/core/ — the Environment with its method
table (routes.go:12-50): status, abci_query, broadcast_tx_{sync,async,
commit}, block*, validators, consensus state/params, tx lookups, net
info, health, evidence. JSON result shapes follow the reference's
camel-free snake_case conventions (hashes hex-upper, bytes base64).
"""

from __future__ import annotations

import base64
import json
import time
from typing import Any, Dict, List, Optional

from ..abci import types as abci
from ..types.tx import tx_hash as _tx_hash


def _b64(b: bytes) -> str:
    return base64.b64encode(b).decode()


def _hex(b: bytes) -> str:
    return b.hex().upper()


def _ts_str(ts) -> str:
    from ..types.genesis import _time_to_rfc3339

    return _time_to_rfc3339(ts)


def _header_json(h) -> dict:
    return {
        "version": {"block": str(h.version.block), "app": str(h.version.app)},
        "chain_id": h.chain_id,
        "height": str(h.height),
        "time": _ts_str(h.time),
        "last_block_id": _block_id_json(h.last_block_id),
        "last_commit_hash": _hex(h.last_commit_hash),
        "data_hash": _hex(h.data_hash),
        "validators_hash": _hex(h.validators_hash),
        "next_validators_hash": _hex(h.next_validators_hash),
        "consensus_hash": _hex(h.consensus_hash),
        "app_hash": _hex(h.app_hash),
        "last_results_hash": _hex(h.last_results_hash),
        "evidence_hash": _hex(h.evidence_hash),
        "proposer_address": _hex(h.proposer_address),
    }


def _block_id_json(bid) -> dict:
    return {
        "hash": _hex(bid.hash),
        "parts": {
            "total": bid.part_set_header.total,
            "hash": _hex(bid.part_set_header.hash),
        },
    }


def _commit_json(c) -> dict:
    return {
        "height": str(c.height),
        "round": c.round,
        "block_id": _block_id_json(c.block_id),
        "signatures": [
            {
                "block_id_flag": cs.block_id_flag,
                "validator_address": _hex(cs.validator_address),
                "timestamp": _ts_str(cs.timestamp),
                "signature": _b64(cs.signature) if cs.signature else None,
            }
            for cs in c.signatures
        ],
    }


def _evidence_json(raw: bytes) -> dict:
    """One committed evidence item (oneof wire form -> typed JSON)."""
    from ..types.evidence import DuplicateVoteEvidence, decode_evidence

    try:
        ev = decode_evidence(raw)
    except (ValueError, KeyError):
        return {"type": "unknown", "value": _b64(raw)}
    if isinstance(ev, DuplicateVoteEvidence):
        return {
            "type": "tendermint/DuplicateVoteEvidence",
            "value": {
                "total_voting_power": str(ev.total_voting_power),
                "validator_power": str(ev.validator_power),
                "height": str(ev.height()),
                "vote_a": {"validator_address": _hex(ev.vote_a.validator_address)},
                "vote_b": {"validator_address": _hex(ev.vote_b.validator_address)},
            },
        }
    return {
        "type": "tendermint/LightClientAttackEvidence",
        "value": {
            "common_height": str(ev.common_height),
            "total_voting_power": str(ev.total_voting_power),
        },
    }


def _block_json(b) -> dict:
    return {
        "header": _header_json(b.header),
        "data": {"txs": [_b64(tx) for tx in b.data.txs]},
        "evidence": {"evidence": [_evidence_json(raw) for raw in b.evidence]},
        "last_commit": _commit_json(b.last_commit) if b.last_commit else None,
    }


class RPCError(Exception):
    def __init__(self, code: int, message: str, data: str = ""):
        super().__init__(message)
        self.code = code
        self.message = message
        self.data = data


class Environment:
    """internal/rpc/core/env.go Environment."""

    def __init__(self, node):
        self._node = node

    # -- info (core/status.go, net.go, abci.go) --------------------------

    def status(self) -> dict:
        node = self._node
        bs = node.block_store
        latest_height = bs.height()
        latest_meta = bs.load_block_meta(latest_height) if latest_height else None
        pv_addr = b""
        pub = None
        if node.consensus._priv_validator_pub_key is not None:
            pub = node.consensus._priv_validator_pub_key
            pv_addr = pub.address()
        return {
            "node_info": {
                "id": node.node_id,
                "listen_addr": node.config.p2p.laddr,
                "network": node.genesis.chain_id,
                "moniker": node.config.base.moniker,
                "version": "tendermint-tpu/0.1.0",
            },
            "sync_info": {
                "latest_block_hash": _hex(latest_meta.block_id.hash) if latest_meta else "",
                "latest_app_hash": _hex(node.consensus.committed_state.app_hash),
                "latest_block_height": str(latest_height),
                "latest_block_time": _ts_str(latest_meta.header.time) if latest_meta else "",
                "earliest_block_height": str(bs.base()),
                "catching_up": False,
            },
            "validator_info": {
                "address": _hex(pv_addr),
                "pub_key": (
                    {"type": "tendermint/PubKeyEd25519", "value": _b64(pub.bytes())}
                    if pub
                    else None
                ),
                "voting_power": str(self._own_voting_power()),
            },
            # beyond the reference: live device verify-engine stats (the
            # north-star hot path) — counters only, no jax import, so a
            # /status poll stays cheap even mid-verification
            "verify_engine": self._verify_engine_stats(),
            # ISSUE 13: device-batched CheckTx back-pressure — queue depth,
            # window wait, preemptions. Same cheap-counters-only discipline.
            "mempool_ingress": self._mempool_ingress_stats(),
            # ISSUE 14: catch-up replay — speculation hit/miss/discard and
            # range-batched replay counters. Same cheap-counters-only rule.
            "blocksync": self._blocksync_stats(),
            # ISSUE 15: live-vote ingress — window batching, memo hits,
            # fallbacks, and the QoS lane intake split proving votes ride
            # the consensus lane. Same cheap-counters-only rule.
            "vote_ingress": self._vote_ingress_stats(),
            # ISSUE 18: the verification fleet — client connection state,
            # RTT EWMA, fallback/rejoin counters, and server accepted-
            # frame/per-lane counts. Same cheap-counters-only rule; reads
            # only libs.metrics (never imports fleet, never dials).
            "fleet": self._fleet_stats(),
        }

    def _mempool_ingress_stats(self) -> dict:
        try:
            mp = getattr(self._node, "mempool", None)
            if mp is not None and hasattr(mp, "ingress_stats"):
                return mp.ingress_stats()
            from ..mempool.ingress import ingress_stats

            return ingress_stats()
        except Exception as e:  # noqa: BLE001 — /status must not 500
            return {"enabled": False, "error": str(e)}

    @staticmethod
    def _vote_ingress_stats() -> dict:
        try:
            from ..consensus.vote_ingress import vote_ingress_stats

            stats = vote_ingress_stats()
            # lane split only when a pipeline already exists — /status
            # must never be the thing that spins the engine up
            from ..ops import pipeline as _pl

            if _pl._shared is not None:
                stats["pipeline_lanes"] = _pl._shared.lane_counts()
            return stats
        except Exception as e:  # noqa: BLE001 — /status must not 500
            return {"enabled": False, "error": str(e)}

    @staticmethod
    def _fleet_stats() -> dict:
        try:
            from ..libs.metrics import fleet_stats

            stats = fleet_stats()
            # origin split only when a pipeline already exists — same
            # no-spin-up rule as _vote_ingress_stats
            from ..ops import pipeline as _pl

            if _pl._shared is not None and hasattr(_pl._shared,
                                                   "origin_counts"):
                stats["server"]["origin_counts"] = (
                    _pl._shared.origin_counts()
                )
            return stats
        except Exception as e:  # noqa: BLE001 — /status must not 500
            return {"enabled": False, "error": str(e)}

    @staticmethod
    def _blocksync_stats() -> dict:
        try:
            from ..libs.metrics import blocksync_stats

            return blocksync_stats()
        except Exception as e:  # noqa: BLE001 — /status must not 500
            return {"error": str(e)}

    @staticmethod
    def _verify_engine_stats() -> dict:
        from ..libs.metrics import ops_stats
        from ..observability import trace as _trace

        stats = ops_stats()
        stats["tracing"] = _trace.TRACER.enabled
        stats["trace_spans_recorded"] = _trace.TRACER.recorded_total
        # ISSUE 16: the per-lane intake split next to the per-lane
        # queue-wait histogram summary (queue_wait_by_lane, from
        # ops_stats) — a scrape now sees ingress starvation directly.
        # Same no-spin-up rule as _vote_ingress_stats.
        from ..ops import pipeline as _pl

        if _pl._shared is not None:
            stats["lane_counts"] = _pl._shared.lane_counts()
        return stats

    def _own_voting_power(self) -> int:
        cs = self._node.consensus
        if cs._priv_validator_pub_key is None:
            return 0
        state = cs.committed_state
        _, val = state.validators.get_by_address(cs._priv_validator_pub_key.address())
        return val.voting_power if val else 0

    def health(self) -> dict:
        return {}

    def thread_dump(self) -> dict:
        """The goroutine-dump equivalent (the reference's debug command
        captures pprof goroutine profiles): every live thread's stack,
        for `debug kill` captures and hang diagnosis — a stuck verify
        path shows up here without attaching a debugger."""
        import sys as _sys
        import threading as _threading
        import traceback as _traceback

        names = {t.ident: t.name for t in _threading.enumerate()}
        threads = []
        for ident, frame in sorted(_sys._current_frames().items()):
            threads.append(
                {
                    "id": ident,
                    "name": names.get(ident, "?"),
                    "stack": _traceback.format_stack(frame),
                }
            )
        return {"n_threads": len(threads), "threads": threads}

    def dump_trace(self, summary: bool = False) -> dict:
        """Live span-trace introspection (num_unconfirmed_txs-style
        read-only endpoint): the tracer ring buffer as Chrome-trace JSON
        (load the `trace` value in chrome://tracing / Perfetto), plus a
        per-span p50/p95/p99 summary. `summary=true` omits the raw events
        for a cheap poll."""
        from ..observability import trace as _trace

        out = {
            "enabled": _trace.TRACER.enabled,
            "capacity": _trace.TRACER.capacity,
            "recorded_total": _trace.TRACER.recorded_total,
            "summary": _trace.TRACER.summary(),
        }
        # GET params arrive as strings — accept the usual truthy spellings
        if str(summary).lower() not in ("true", "1", "yes", "on"):
            out["trace"] = _trace.TRACER.export_chrome()
        return out

    def height_timeline(self, height: int = 0) -> dict:
        """Per-height consensus latency attribution (ISSUE 10): the
        HeightTimeline record for `height` (latest when omitted) from the
        node's last-K ring — phase timestamps, per-phase durations and the
        round count, turning "why was h=37 slow" into a lookup."""
        cs = self._node.consensus
        # ONE snapshot serves the lookup, the error message and the
        # retained-range summary — a commit landing mid-handler cannot
        # make them disagree
        ring = list(cs.height_timelines)
        if not ring:
            raise RPCError(-32603, "no height timelines recorded yet")
        h = int(height) if height else 0
        tl = next((t for t in ring if t.height == h), None) if h else ring[-1]
        if tl is None:
            raise RPCError(
                -32603,
                f"height {h} not in the retained timeline ring "
                f"({ring[0].height}..{ring[-1].height})",
            )
        return {
            "height": str(tl.height),
            "timeline": tl.to_dict(),
            "retained": {
                "count": len(ring),
                "min_height": str(ring[0].height),
                "max_height": str(ring[-1].height),
            },
        }

    def net_info(self) -> dict:
        router = self._node.router
        peers = router.connected() if router else []
        return {
            "listening": router is not None,
            "listeners": [self._node.config.p2p.laddr],
            "n_peers": str(len(peers)),
            "peers": [{"node_id": p} for p in peers],
        }

    def genesis(self) -> dict:
        return {"genesis": json.loads(self._node.genesis.to_json())}

    def genesis_chunked(self, chunk: int = 0) -> dict:
        """env.GenesisChunked (routes.go:25): the genesis doc split into
        base64 chunks for large-genesis chains. Chunks are computed once
        and cached — this endpoint exists for very large documents."""
        chunks = getattr(self, "_genesis_chunks", None)
        if chunks is None:
            data = self._node.genesis.to_json().encode()
            size = 16 * 1024 * 1024  # internal/rpc/core/net.go genesisChunkSize
            chunks = [data[i : i + size] for i in range(0, len(data), size)] or [b""]
            self._genesis_chunks = chunks
        chunk = int(chunk)
        if not 0 <= chunk < len(chunks):
            raise RPCError(
                -32603,
                f"there are {len(chunks)} chunks, but requested {chunk}",
            )
        return {
            "chunk": str(chunk),
            "total": str(len(chunks)),
            "data": _b64(chunks[chunk]),
        }

    def remove_tx(self, txkey: str) -> dict:
        """env.RemoveTx (routes.go:31): drop a tx from the mempool by key."""
        import base64 as _base64

        key = _base64.b64decode(txkey)
        if not self._node.mempool.remove_tx_by_key(key):
            raise RPCError(-32603, "transaction not found in the mempool")
        return {}

    def unsafe_flush_mempool(self) -> dict:
        """env.UnsafeFlushMempool (routes.go:56-60, unsafe route)."""
        self._node.mempool.flush()
        return {}

    def abci_info(self) -> dict:
        res = self._node.proxy_app.info(abci.RequestInfo())
        return {
            "response": {
                "data": res.data,
                "version": res.version,
                "app_version": str(res.app_version),
                "last_block_height": str(res.last_block_height),
                "last_block_app_hash": _b64(res.last_block_app_hash),
            }
        }

    def abci_query(self, path: str = "", data: str = "", height: int = 0, prove: bool = False) -> dict:
        res = self._node.proxy_app.query(
            abci.RequestQuery(
                data=bytes.fromhex(data) if data else b"",
                path=path,
                height=int(height),
                prove=bool(prove),
            )
        )
        return {
            "response": {
                "code": res.code,
                "log": res.log,
                "info": res.info,
                "index": str(res.index),
                "key": _b64(res.key),
                "value": _b64(res.value),
                "height": str(res.height),
                "codespace": res.codespace,
            }
        }

    # -- blocks (core/blocks.go) -----------------------------------------

    def block(self, height: Optional[int] = None) -> dict:
        bs = self._node.block_store
        h = int(height) if height else bs.height()
        meta = bs.load_block_meta(h)
        blk = bs.load_block(h)
        if meta is None or blk is None:
            raise RPCError(-32603, f"block at height {h} not found")
        return {"block_id": _block_id_json(meta.block_id), "block": _block_json(blk)}

    def block_by_hash(self, hash: str) -> dict:
        bs = self._node.block_store
        blk = bs.load_block_by_hash(bytes.fromhex(hash))
        if blk is None:
            raise RPCError(-32603, f"block with hash {hash} not found")
        return self.block(blk.header.height)

    def blockchain(self, min_height: int = 1, max_height: int = 0) -> dict:
        bs = self._node.block_store
        max_h = int(max_height) or bs.height()
        min_h = max(int(min_height), bs.base())
        max_h = min(max_h, bs.height())
        metas = []
        for h in range(max_h, max(min_h, max_h - 20) - 1, -1):
            m = bs.load_block_meta(h)
            if m:
                metas.append(
                    {
                        "block_id": _block_id_json(m.block_id),
                        "block_size": str(m.block_size),
                        "header": _header_json(m.header),
                        "num_txs": str(m.num_txs),
                    }
                )
        return {"last_height": str(bs.height()), "block_metas": metas}

    def commit(self, height: Optional[int] = None) -> dict:
        bs = self._node.block_store
        h = int(height) if height else bs.height()
        meta = bs.load_block_meta(h)
        if meta is None:
            raise RPCError(-32603, f"commit at height {h} not found")
        if h < bs.height():
            c = bs.load_block_commit(h)
            canonical = True
        else:
            c = bs.load_seen_commit()
            canonical = False
        return {
            "signed_header": {"header": _header_json(meta.header), "commit": _commit_json(c)},
            "canonical": canonical,
        }

    def block_results(self, height: Optional[int] = None) -> dict:
        h = int(height) if height else self._node.block_store.height()
        responses = self._node.state_store.load_abci_responses(h)
        if responses is None:
            raise RPCError(-32603, f"no results for height {h}")
        dtxs = [
            abci.dec_response_payload("deliver_tx", raw) for raw in responses.deliver_txs
        ]
        eb = abci.dec_response_payload("end_block", responses.end_block)
        return {
            "height": str(h),
            "txs_results": [
                {"code": r.code, "data": _b64(r.data), "log": r.log, "gas_wanted": str(r.gas_wanted), "gas_used": str(r.gas_used)}
                for r in dtxs
            ],
            "validator_updates": [
                {"power": str(v.power)} for v in eb.validator_updates
            ],
        }

    def validators(self, height: Optional[int] = None, page: int = 1, per_page: int = 30) -> dict:
        h = int(height) if height else self._node.block_store.height() or 1
        try:
            vals = self._node.state_store.load_validators(h)
        except KeyError as e:
            raise RPCError(-32603, str(e)) from e
        page, per_page = int(page), int(per_page)
        start = (page - 1) * per_page
        sel = vals.validators[start : start + per_page]
        return {
            "block_height": str(h),
            "validators": [
                {
                    "address": _hex(v.address),
                    "pub_key": {"type": "tendermint/PubKeyEd25519", "value": _b64(v.pub_key.bytes())},
                    "voting_power": str(v.voting_power),
                    "proposer_priority": str(v.proposer_priority),
                }
                for v in sel
            ],
            "count": str(len(sel)),
            "total": str(vals.size()),
        }

    def consensus_params(self, height: Optional[int] = None) -> dict:
        h = int(height) if height else self._node.block_store.height() or 1
        try:
            params = self._node.state_store.load_consensus_params(h)
        except KeyError:
            params = self._node.consensus.committed_state.consensus_params
        return {
            "block_height": str(h),
            "consensus_params": {
                "block": {
                    "max_bytes": str(params.block.max_bytes),
                    "max_gas": str(params.block.max_gas),
                },
                "evidence": {
                    "max_age_num_blocks": str(params.evidence.max_age_num_blocks),
                    "max_age_duration": str(params.evidence.max_age_duration_ns),
                    "max_bytes": str(params.evidence.max_bytes),
                },
                "validator": {"pub_key_types": list(params.validator.pub_key_types)},
            },
        }

    def consensus_state(self) -> dict:
        rs = self._node.consensus.rs
        return {"round_state": rs.round_state_event()}

    def dump_consensus_state(self) -> dict:
        rs = self._node.consensus.rs
        return {
            "round_state": {
                **rs.round_state_event(),
                "start_time": rs.start_time,
                "locked_round": rs.locked_round,
                "valid_round": rs.valid_round,
            },
            "peers": [{"node_id": p} for p in (self._node.router.connected() if self._node.router else [])],
        }

    # -- txs (core/mempool.go, tx.go) ------------------------------------

    def broadcast_tx_sync(self, tx: str) -> dict:
        raw = base64.b64decode(tx)
        reactor = self._node.mempool_reactor
        try:
            if reactor is not None:
                res = reactor.check_tx_and_broadcast(raw)
            else:
                res = self._node.mempool.check_tx(raw)
        except ValueError as e:
            raise RPCError(-32603, str(e)) from e
        return {
            "code": res.code,
            "data": _b64(res.data),
            "log": res.log,
            "codespace": res.codespace,
            "hash": _hex(_tx_hash(raw)),
        }

    def broadcast_tx_async(self, tx: str) -> dict:
        return self.broadcast_tx_sync(tx)

    def broadcast_tx_commit(self, tx: str, timeout: float = 10.0) -> dict:
        """core/mempool.go BroadcastTxCommit: wait for the tx to land."""
        raw = base64.b64decode(tx)
        check = self.broadcast_tx_sync(tx)
        if check["code"] != 0:
            return {"check_tx": check, "deliver_tx": None, "height": "0", "hash": check["hash"]}
        want = _tx_hash(raw)
        bs = self._node.block_store
        deadline = time.time() + timeout
        while time.time() < deadline:
            for h in range(max(bs.base(), 1), bs.height() + 1):
                blk = bs.load_block(h)
                if blk is None:
                    continue
                for i, btx in enumerate(blk.data.txs):
                    if _tx_hash(btx) == want:
                        responses = self._node.state_store.load_abci_responses(h)
                        dres = (
                            abci.dec_response_payload("deliver_tx", responses.deliver_txs[i])
                            if responses and i < len(responses.deliver_txs)
                            else None
                        )
                        return {
                            "check_tx": check,
                            "deliver_tx": {"code": dres.code if dres else 0},
                            "height": str(h),
                            "hash": check["hash"],
                        }
            time.sleep(0.05)
        raise RPCError(-32603, "timed out waiting for tx to be included in a block")

    def tx(self, hash: str, prove: bool = False) -> dict:
        want = bytes.fromhex(hash) if isinstance(hash, str) else hash
        bs = self._node.block_store
        for h in range(max(bs.base(), 1), bs.height() + 1):
            blk = bs.load_block(h)
            if blk is None:
                continue
            for i, btx in enumerate(blk.data.txs):
                if _tx_hash(btx) == want:
                    out = {
                        "hash": _hex(want),
                        "height": str(h),
                        "index": i,
                        "tx": _b64(btx),
                    }
                    if prove:
                        from ..types.tx import tx_proof

                        proof = tx_proof(blk.data.txs, i)
                        out["proof"] = {
                            "root_hash": _hex(proof.root_hash),
                            "data": _b64(proof.data),
                            "proof": {
                                "total": str(proof.proof.total),
                                "index": str(proof.proof.index),
                                "leaf_hash": _b64(proof.proof.leaf_hash),
                                "aunts": [_b64(a) for a in proof.proof.aunts],
                            },
                        }
                    return out
        raise RPCError(-32603, f"tx {hash} not found")

    def num_unconfirmed_txs(self) -> dict:
        mp = self._node.mempool
        return {
            "n_txs": str(mp.size()),
            "total": str(mp.size()),
            "total_bytes": str(mp.size_bytes()),
        }

    def unconfirmed_txs(self, limit: int = 30) -> dict:
        txs = self._node.mempool.reap_max_txs(int(limit))
        return {
            "n_txs": str(len(txs)),
            "total": str(self._node.mempool.size()),
            "total_bytes": str(self._node.mempool.size_bytes()),
            "txs": [_b64(t) for t in txs],
        }

    def check_tx(self, tx: str) -> dict:
        raw = base64.b64decode(tx)
        res = self._node.proxy_app.check_tx(abci.RequestCheckTx(tx=raw))
        return {"code": res.code, "log": res.log, "gas_wanted": str(res.gas_wanted)}

    def broadcast_evidence(self, evidence: str) -> dict:
        from ..types.evidence import decode_evidence

        ev = decode_evidence(base64.b64decode(evidence))
        self._node.evidence_pool.add_evidence(ev)
        return {"hash": _hex(ev.hash())}

    # -- indexed search (core/tx.go TxSearch, blocks.go BlockSearch) ------

    def tx_search(self, query: str, prove: bool = False, page: int = 1, per_page: int = 30) -> dict:
        sink = getattr(self._node, "tx_index_sink", None)
        if sink is None:
            raise RPCError(-32603, "transaction indexing is disabled")
        page, per_page = int(page), int(per_page)
        hits = sink.search_txs(query, limit=page * per_page + per_page)
        total = len(hits)
        sel = hits[(page - 1) * per_page : page * per_page]
        return {
            "txs": [
                {
                    "hash": _hex(_tx_hash(bytes.fromhex(rec["tx"]))),
                    "height": str(rec["height"]),
                    "index": rec["index"],
                    "tx_result": {"code": rec["code"], "log": rec["log"]},
                    "tx": _b64(bytes.fromhex(rec["tx"])),
                }
                for rec in sel
            ],
            "total_count": str(total),
        }

    def block_search(self, query: str, page: int = 1, per_page: int = 30) -> dict:
        sink = getattr(self._node, "tx_index_sink", None)
        if sink is None:
            raise RPCError(-32603, "block indexing is disabled")
        page, per_page = int(page), int(per_page)
        heights = sink.search_blocks(query, limit=page * per_page + per_page)
        sel = heights[(page - 1) * per_page : page * per_page]
        blocks = []
        for h in sel:
            try:
                blocks.append(self.block(h))
            except RPCError:
                continue
        return {"blocks": blocks, "total_count": str(len(heights))}

    # -- light-client verification service (ISSUE 11) ---------------------

    def _light_service(self):
        """Lazy per-environment LightVerifyService bound to the node's
        shared device pipeline — requests are self-contained (headers +
        valsets ride in the call), so the node's own stores are not
        consulted."""
        svc = getattr(self, "_light_svc", None)
        if svc is None:
            from ..light.service import LightVerifyService

            svc = self._light_svc = LightVerifyService()
        return svc

    def light_verify(self, requests=None, timeout: float = 60.0,
                     stream: bool = False):
        """Batched light-client header verification: many (trusted,
        untrusted) pairs verified through the shared device pipeline —
        sig work grouped by valset epoch and coalesced ACROSS requests,
        non-sig checks bit-identical to light/verifier.py. Verdicts are
        listed in COMPLETION order (each carries its request `index`);
        `stream=true` returns them as chunked NDJSON lines as device
        batches resolve instead of one JSON body."""
        from ..light import service as _lsvc

        if isinstance(requests, str):
            try:
                requests = json.loads(requests)
            except json.JSONDecodeError as e:
                raise RPCError(-32602, f"requests is not JSON: {e}") from e
        if not isinstance(requests, list) or not requests:
            raise RPCError(-32602, "requests must be a non-empty list")
        try:
            reqs = [_lsvc.request_from_json(d) for d in requests]
        except (KeyError, ValueError, TypeError) as e:
            raise RPCError(-32602, f"bad light_verify request: {e}") from e
        svc = self._light_service()
        batch = svc.submit_many(reqs)
        timeout = float(timeout)
        # GET params arrive as strings — accept the usual truthy spellings
        if str(stream).lower() in ("true", "1", "yes", "on"):
            def gen():
                # a deadline expiry must still terminate the chunked
                # stream cleanly (error line + terminator), never escape
                # mid-response after the 200 headers went out
                try:
                    for v in batch.stream(timeout=timeout):
                        yield v
                except TimeoutError as e:
                    yield {"done": False, "error": str(e),
                           "total": len(batch), "stats": svc.stats()}
                    return
                yield {
                    "done": True,
                    "total": len(batch),
                    "stats": svc.stats(),
                }

            return gen()
        try:
            verdicts = list(batch.stream(timeout=timeout))
        except TimeoutError as e:
            raise RPCError(-32603, str(e)) from e
        return {
            "verdicts": verdicts,
            "total": str(len(verdicts)),
            "ok_count": str(sum(1 for v in verdicts if v["ok"])),
            "stats": svc.stats(),
        }

    # -- subscriptions (events.go; served over the websocket endpoint) ----

    def _subscribe(self, subscriber: str, query: str):
        return self._node.event_bus.subscribe(subscriber, query, capacity=200)

    def _unsubscribe(self, subscriber: str, query: str) -> None:
        self._node.event_bus.unsubscribe(subscriber, query)

    def _unsubscribe_all(self, subscriber: str) -> None:
        self._node.event_bus.unsubscribe_all(subscriber)


# Method table (routes.go:12-50)
ROUTES = [
    "status", "health", "net_info", "genesis", "genesis_chunked",
    "abci_info", "abci_query",
    "block", "block_by_hash", "blockchain", "commit", "block_results",
    "validators", "consensus_params", "consensus_state", "dump_consensus_state",
    "broadcast_tx_sync", "broadcast_tx_async", "broadcast_tx_commit",
    "tx", "tx_search", "block_search", "num_unconfirmed_txs",
    "unconfirmed_txs", "check_tx", "remove_tx", "broadcast_evidence",
    "dump_trace", "height_timeline", "light_verify",
]

# routes.go:56-60 AddUnsafe — mounted only when rpc.unsafe is configured.
# thread_dump exposes every thread's stack (paths, code layout): operator
# tooling only, like the reference's separately-gated pprof listener.
UNSAFE_ROUTES = ["unsafe_flush_mempool", "thread_dump"]
