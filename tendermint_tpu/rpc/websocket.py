"""WebSocket event subscriptions (RFC 6455 server side).

Reference parity: rpc/jsonrpc/server/ws_handler.go + core/events.go —
clients connect to /websocket, send JSON-RPC subscribe/unsubscribe with an
event query, and receive event messages as JSON-RPC notifications keyed by
the subscription query. Stdlib-only frame implementation (no extensions,
no fragmentation of outgoing frames).
"""

from __future__ import annotations

import base64
import hashlib
import json
import queue as _q
import struct
import threading
from typing import Optional

_WS_GUID = "258EAFA5-E914-47DA-95CA-C5AB0DC85B11"

OP_TEXT = 0x1
OP_CLOSE = 0x8
OP_PING = 0x9
OP_PONG = 0xA


def accept_key(client_key: str) -> str:
    return base64.b64encode(
        hashlib.sha1((client_key + _WS_GUID).encode()).digest()
    ).decode()


def encode_frame(opcode: int, payload: bytes, mask: bytes = b"") -> bytes:
    """One frame. Servers send unmasked; clients pass a 4-byte mask
    (RFC 6455 §5.3 requires client frames to be masked)."""
    mask_bit = 0x80 if mask else 0
    head = bytes([0x80 | opcode])
    n = len(payload)
    if n < 126:
        head += bytes([mask_bit | n])
    elif n < 65536:
        head += bytes([mask_bit | 126]) + struct.pack(">H", n)
    else:
        head += bytes([mask_bit | 127]) + struct.pack(">Q", n)
    if mask:
        payload = bytes(b ^ mask[i % 4] for i, b in enumerate(payload))
        return head + mask + payload
    return head + payload


def read_frame(rfile) -> Optional[tuple]:
    """Returns (opcode, payload) or None on EOF."""
    head = rfile.read(2)
    if len(head) < 2:
        return None
    opcode = head[0] & 0x0F
    masked = head[1] & 0x80
    n = head[1] & 0x7F
    if n == 126:
        n = struct.unpack(">H", rfile.read(2))[0]
    elif n == 127:
        n = struct.unpack(">Q", rfile.read(8))[0]
    mask = rfile.read(4) if masked else b""
    payload = rfile.read(n)
    if masked:
        payload = bytes(b ^ mask[i % 4] for i, b in enumerate(payload))
    return opcode, payload


def handle_websocket(handler, env) -> None:
    """Upgrade an http.server request to a websocket session and serve
    subscribe/unsubscribe until the client goes away."""
    key = handler.headers.get("Sec-WebSocket-Key", "")
    handler.send_response(101, "Switching Protocols")
    handler.send_header("Upgrade", "websocket")
    handler.send_header("Connection", "Upgrade")
    handler.send_header("Sec-WebSocket-Accept", accept_key(key))
    handler.end_headers()

    subscriber = f"ws-{id(handler)}"
    write_mtx = threading.Lock()
    stop = threading.Event()

    def send_json(obj) -> None:
        data = json.dumps(obj).encode()
        with write_mtx:
            handler.wfile.write(encode_frame(OP_TEXT, data))
            handler.wfile.flush()

    def pump(sub, query: str) -> None:
        while not stop.is_set() and not sub.canceled.is_set():
            try:
                msg = sub.next(timeout=0.5)
            except _q.Empty:
                continue
            try:
                send_json(
                    {
                        "jsonrpc": "2.0",
                        "id": f"{query}#event",
                        "result": {
                            "query": query,
                            "data": _serialize_event(msg),
                            "events": msg.events,
                        },
                    }
                )
            except OSError:
                return

    pumps = []
    try:
        while not stop.is_set():
            frame = read_frame(handler.rfile)
            if frame is None:
                break
            opcode, payload = frame
            if opcode == OP_CLOSE:
                break
            if opcode == OP_PING:
                with write_mtx:
                    handler.wfile.write(encode_frame(OP_PONG, payload))
                continue
            if opcode != OP_TEXT:
                continue
            try:
                req = json.loads(payload)
            except ValueError:
                continue
            method = req.get("method", "")
            params = req.get("params") or {}
            rid = req.get("id")
            try:
                if method == "subscribe":
                    query = params.get("query", "")
                    sub = env._subscribe(subscriber, query)
                    t = threading.Thread(target=pump, args=(sub, query), daemon=True)
                    t.start()
                    pumps.append(t)
                    send_json({"jsonrpc": "2.0", "id": rid, "result": {}})
                elif method == "unsubscribe":
                    env._unsubscribe(subscriber, params.get("query", ""))
                    send_json({"jsonrpc": "2.0", "id": rid, "result": {}})
                elif method == "unsubscribe_all":
                    env._unsubscribe_all(subscriber)
                    send_json({"jsonrpc": "2.0", "id": rid, "result": {}})
                else:
                    # any regular RPC method also works over the socket —
                    # through the SAME route gate as HTTP dispatch (route
                    # restriction + unsafe-route config must not be
                    # bypassable by upgrading to a websocket)
                    gate = getattr(handler, "_route_allowed", None)
                    fn = getattr(env, method, None)
                    if gate is not None and not gate(method):
                        fn = None
                    if fn is None or method.startswith("_"):
                        send_json(
                            {
                                "jsonrpc": "2.0",
                                "id": rid,
                                "error": {"code": -32601, "message": f"Method not found: {method}"},
                            }
                        )
                    else:
                        send_json({"jsonrpc": "2.0", "id": rid, "result": fn(**params)})
            except Exception as e:  # noqa: BLE001
                try:
                    send_json(
                        {
                            "jsonrpc": "2.0",
                            "id": rid,
                            "error": {"code": -32603, "message": str(e)},
                        }
                    )
                except OSError:
                    break
    finally:
        stop.set()
        try:
            env._unsubscribe_all(subscriber)
        except KeyError:
            pass


def _serialize_event(msg) -> dict:
    """Best-effort JSON form of eventbus payloads (events.go result_data)."""
    d = msg.data
    if isinstance(d, dict):
        out = {}
        for k, v in d.items():
            if hasattr(v, "header"):
                out[k] = {"height": v.header.height}
            elif isinstance(v, (int, str)):
                out[k] = v
            elif isinstance(v, bytes):
                out[k] = base64.b64encode(v).decode()
            else:
                out[k] = str(v)
        return out
    return {"value": str(d)}
