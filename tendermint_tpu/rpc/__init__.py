"""tendermint_tpu.rpc — JSON-RPC API (reference rpc/ + internal/rpc/core, L11)."""

from .client import Call, HTTPClient, LocalRPCClient, MockClient  # noqa: F401
from .core import Environment, ROUTES, RPCError  # noqa: F401
from .server import RPCServer  # noqa: F401
