"""JSON-RPC 2.0 server over HTTP (POST body + GET URI params).

Reference parity: rpc/jsonrpc/server/ — http_json_handler.go (POST
JSON-RPC), uri handler (GET /method?param=value), and the event
subscription endpoint. Runs on stdlib ThreadingHTTPServer.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional
from urllib.parse import parse_qsl, urlparse

from .core import Environment, ROUTES, UNSAFE_ROUTES, RPCError


def _rpc_response(id_, result=None, error: Optional[RPCError] = None) -> bytes:
    obj = {"jsonrpc": "2.0", "id": id_}
    if error is not None:
        obj["error"] = {"code": error.code, "message": error.message, "data": error.data}
    else:
        obj["result"] = result
    return json.dumps(obj).encode()


class _Handler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"
    env: Environment = None  # class attr set by server factory
    route_filter = None  # optional frozenset restricting served routes

    def log_message(self, fmt, *args):  # noqa: A003 — silence default logging
        pass

    def _send(self, code: int, body: bytes, content_type: str = "application/json"):
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _route_allowed(self, method: str) -> bool:
        """Single route gate for HTTP, URI, and websocket dispatch:
        restricted servers (inspect) serve only their table; unsafe
        routes mount only when configured (routes.go:56-60)."""
        if self.route_filter is not None and method not in self.route_filter:
            return False
        if method in ROUTES:
            return True
        if method in UNSAFE_ROUTES:
            cfg = getattr(getattr(self.env, "_node", None), "config", None)
            return bool(cfg and cfg.rpc.unsafe)
        return False

    def _call(self, method: str, params: dict, id_):
        """Returns the response BYTES, or a generator when the method
        streams (the /light_verify verdict stream) — callers send the
        latter through _send_stream as chunked NDJSON."""
        if not self._route_allowed(method):
            return _rpc_response(
                id_, error=RPCError(-32601, f"Method not found: {method}")
            )
        fn = getattr(self.env, method, None)
        if fn is None:
            return _rpc_response(
                id_, error=RPCError(-32601, f"Method not implemented: {method}")
            )
        try:
            result = fn(**params) if params else fn()
            if hasattr(result, "__next__"):
                return result  # streaming method: items, not one body
            return _rpc_response(id_, result=result)
        except RPCError as e:
            return _rpc_response(id_, error=e)
        except TypeError as e:
            return _rpc_response(id_, error=RPCError(-32602, f"Invalid params: {e}"))
        except Exception as e:  # noqa: BLE001 — internal error on the wire
            return _rpc_response(id_, error=RPCError(-32603, f"Internal error: {e}"))

    def _call_bytes(self, method: str, params: dict, id_) -> bytes:
        """Batch JSON-RPC slots cannot stream: a streaming result inside
        a batch collapses to an error response instead of corrupting the
        batch body. The generator is NOT drained — the work behind it
        was already submitted and resolves (and memoizes) on its own;
        draining would only park this handler thread until the batch's
        deadline."""
        resp = self._call(method, params, id_)
        if isinstance(resp, bytes):
            return resp
        resp.close()
        return _rpc_response(
            id_, error=RPCError(
                -32600, "streaming methods are not supported in a batch"
            )
        )

    def _send_stream(self, gen) -> None:
        """Chunked NDJSON (application/x-ndjson): one JSON object per
        line, flushed as each item resolves — the streaming half of
        /light_verify (verdicts arrive as device batches complete)."""
        self.send_response(200)
        self.send_header("Content-Type", "application/x-ndjson")
        self.send_header("Transfer-Encoding", "chunked")
        self.end_headers()
        try:
            try:
                for item in gen:
                    line = json.dumps(item).encode() + b"\n"
                    self.wfile.write(b"%x\r\n" % len(line) + line + b"\r\n")
                    self.wfile.flush()
            except Exception as e:  # noqa: BLE001 — headers already sent:
                # the only honest move left is an error line + terminator
                line = json.dumps(
                    {"done": False, "error": f"stream failed: {e}"}
                ).encode() + b"\n"
                self.wfile.write(b"%x\r\n" % len(line) + line + b"\r\n")
            self.wfile.write(b"0\r\n\r\n")
        except (BrokenPipeError, ConnectionResetError):
            pass  # client went away mid-stream; verdicts already resolved

    def _respond(self, resp) -> None:
        if isinstance(resp, bytes):
            self._send(200, resp)
        else:
            self._send_stream(resp)

    def do_POST(self):  # noqa: N802
        try:
            length = int(self.headers.get("Content-Length", 0))
            body = self.rfile.read(length)
            req = json.loads(body)
        except (ValueError, KeyError):
            self._send(400, _rpc_response(None, error=RPCError(-32700, "Parse error")))
            return
        if isinstance(req, list):
            out = [
                json.loads(
                    self._call_bytes(r.get("method", ""), r.get("params") or {}, r.get("id"))
                )
                if isinstance(r, dict)
                else json.loads(_rpc_response(None, error=RPCError(-32600, "Invalid Request")))
                for r in req
            ]
            self._send(200, json.dumps(out).encode())
            return
        if not isinstance(req, dict):
            self._send(400, _rpc_response(None, error=RPCError(-32600, "Invalid Request")))
            return
        params = req.get("params")
        if not isinstance(params, dict):
            params = {}
        method = req.get("method", "")
        if not isinstance(method, str):
            method = ""
        self._respond(self._call(method, params, req.get("id")))

    def do_GET(self):  # noqa: N802
        parsed = urlparse(self.path)
        method = parsed.path.strip("/")
        if method == "websocket" and "websocket" in (
            self.headers.get("Upgrade", "").lower()
        ):
            from .websocket import handle_websocket

            handle_websocket(self, self.env)
            return
        if method == "":
            # route listing like the reference's index page (restricted
            # servers advertise only what they serve)
            methods = [r for r in ROUTES if self._route_allowed(r)]
            body = json.dumps({"available_methods": methods}).encode()
            self._send(200, body)
            return
        params = {}
        for k, v in parse_qsl(parsed.query):
            v = v.strip('"')
            params[k] = v
        self._respond(self._call(method, params, -1))


class RPCServer:
    def __init__(
        self,
        laddr: str,
        env: Environment,
        tls_cert_file: str = "",
        tls_key_file: str = "",
        routes=None,
    ):
        addr = laddr
        for prefix in ("tcp://", "http://", "https://"):
            if addr.startswith(prefix):
                addr = addr[len(prefix):]
        host, _, port = addr.rpartition(":")
        handler = type(
            "BoundHandler",
            (_Handler,),
            {"env": env,
             "route_filter": frozenset(routes) if routes is not None else None},
        )
        self._httpd = ThreadingHTTPServer((host or "127.0.0.1", int(port)), handler)
        if bool(tls_cert_file) != bool(tls_key_file):
            raise ValueError(
                "TLS requires BOTH tls_cert_file and tls_key_file; refusing "
                "to silently serve plaintext on a half-configured listener"
            )
        self.tls = bool(tls_cert_file and tls_key_file)
        if self.tls:
            # ServeTLS (rpc/jsonrpc/server/http_server.go:113): same
            # handler tree over TLS; WS upgrades ride the same listener.
            import ssl

            ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
            ctx.load_cert_chain(tls_cert_file, tls_key_file)
            self._httpd.socket = ctx.wrap_socket(
                self._httpd.socket, server_side=True
            )
        self._thread: Optional[threading.Thread] = None

    @property
    def listen_addr(self) -> str:
        h, p = self._httpd.server_address[:2]
        return f"{h}:{p}"

    def start(self) -> None:
        self._thread = threading.Thread(target=self._httpd.serve_forever, daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
