"""Event indexing — tx and block event indexes with query support.

Reference parity: internal/state/indexer/ — the IndexerService consuming
eventbus Tx/NewBlock subscriptions, the kv sink (sink/kv) keying events
as "<type>.<attr>=<value>" -> heights/tx hashes, the null sink, and the
query execution backing /tx_search and /block_search.
"""

from __future__ import annotations

import json
import struct
import threading
from typing import Dict, List, Optional, Tuple

from ..db import DB, MemDB
from ..libs.pubsub import Query
from ..types import events as tme
from ..types.tx import tx_hash


class Sink:
    """indexer.EventSink interface."""

    def index_tx(self, height: int, index: int, tx: bytes, result, events: Dict[str, List[str]]) -> None: ...

    def index_block(self, height: int, events: Dict[str, List[str]]) -> None: ...


class NullSink(Sink):
    def index_tx(self, *a, **k) -> None:
        pass

    def index_block(self, *a, **k) -> None:
        pass


class KVSink(Sink):
    """sink/kv: hash -> tx record; event-kv -> matches."""

    def __init__(self, db: Optional[DB] = None):
        self._db = db or MemDB()
        self._mtx = threading.Lock()

    # -- writes ---------------------------------------------------------

    def index_tx(self, height, index, tx, result, events) -> None:
        h = tx_hash(tx)
        record = {
            "height": height,
            "index": index,
            "tx": tx.hex(),
            "code": getattr(result, "code", 0),
            "log": getattr(result, "log", ""),
            "events": events,
        }
        with self._mtx:
            self._db.set(b"tx/" + h, json.dumps(record).encode())
            for key, values in events.items():
                for v in values:
                    self._db.set(
                        b"txevt/" + _kv(key, v) + b"/" + struct.pack(">qi", height, index),
                        h,
                    )

    def index_block(self, height, events) -> None:
        # the implicit height key every block gets (state/indexer/block/kv:
        # block.height is always queryable)
        events = dict(events)
        events.setdefault("block.height", [str(height)])
        with self._mtx:
            self._db.set(b"blk/" + struct.pack(">q", height), json.dumps(events).encode())
            for key, values in events.items():
                for v in values:
                    self._db.set(
                        b"blkevt/" + _kv(key, v) + b"/" + struct.pack(">q", height), b"\x01"
                    )

    # -- reads ----------------------------------------------------------

    def get_tx(self, h: bytes) -> Optional[dict]:
        raw = self._db.get(b"tx/" + h)
        return json.loads(raw) if raw is not None else None

    def search_txs(self, query: str, limit: int = 100) -> List[dict]:
        """tx_search: AND of =-conditions over indexed events; height
        conditions are applied as a post-filter."""
        q = Query(query)
        candidate_hashes: Optional[set] = None
        post_conditions = []
        for key, op, val in q.conditions:
            # index fast path only for string-typed equality: the kv index
            # stores raw value strings, and typed numeric/time equality
            # must coerce ("4" matches a stored "4.0") in the post-filter
            if op == "=" and isinstance(val, str) and key not in ("tx.height",):
                hashes = {
                    v
                    for _, v in self._db.iterator(
                        b"txevt/" + _kv(key, val) + b"/",
                        b"txevt/" + _kv(key, val) + b"0",
                    )
                }
                candidate_hashes = (
                    hashes if candidate_hashes is None else candidate_hashes & hashes
                )
            else:
                post_conditions.append((key, op, val))
        out = []
        if candidate_hashes is None:
            # scan all txs
            records = [
                json.loads(v) for _, v in self._db.iterator(b"tx/", b"tx0")
            ]
        else:
            records = [r for h in candidate_hashes if (r := self.get_tx(h)) is not None]
        for rec in records:
            events = dict(rec.get("events", {}))
            events.setdefault("tx.height", [str(rec["height"])])
            if Query.match_conditions(events, post_conditions):
                out.append(rec)
        # sort BEFORE applying the limit: records iterate in db/hash-set
        # order, so an early break would return an arbitrary page instead
        # of the first `limit` by (height, index)
        out.sort(key=lambda r: (r["height"], r["index"]))
        return out[:limit]

    def search_blocks(self, query: str, limit: int = 100) -> List[int]:
        q = Query(query)
        candidate: Optional[set] = None
        post_conditions = []
        for key, op, val in q.conditions:
            if op == "=" and isinstance(val, str):
                hs = {
                    struct.unpack(">q", k[-8:])[0]
                    for k, _ in self._db.iterator(
                        b"blkevt/" + _kv(key, val) + b"/",
                        b"blkevt/" + _kv(key, val) + b"0",
                    )
                }
                candidate = hs if candidate is None else candidate & hs
            else:
                post_conditions.append((key, op, val))
        if candidate is None:
            candidate = {
                struct.unpack(">q", k[len(b"blk/"):])[0]
                for k, _ in self._db.iterator(b"blk/", b"blk0")
            }
        if post_conditions:
            kept = set()
            for h in candidate:
                raw = self._db.get(b"blk/" + struct.pack(">q", h))
                events = json.loads(raw) if raw is not None else {}
                if Query.match_conditions(events, post_conditions):
                    kept.add(h)
            candidate = kept
        return sorted(candidate)[:limit]


def _kv(key: str, value: str) -> bytes:
    return key.encode() + b"=" + value.encode()


class IndexerService:
    """indexer/service.go: subscribes to the eventbus and feeds sinks."""

    def __init__(self, sinks: List[Sink], event_bus):
        self._sinks = sinks
        self._bus = event_bus
        self._stopped = threading.Event()
        self._threads: List[threading.Thread] = []

    def start(self) -> None:
        tx_sub = self._bus.subscribe("indexer", tme.query_for_event(tme.EventTx), capacity=1000)
        blk_sub = self._bus.subscribe(
            "indexer-blk", tme.query_for_event(tme.EventNewBlock), capacity=1000
        )
        for sub, fn in ((tx_sub, self._on_tx), (blk_sub, self._on_block)):
            t = threading.Thread(target=self._pump, args=(sub, fn), daemon=True)
            t.start()
            self._threads.append(t)

    def stop(self) -> None:
        self._stopped.set()
        try:
            self._bus.unsubscribe_all("indexer")
            self._bus.unsubscribe_all("indexer-blk")
        except KeyError:
            pass

    def _pump(self, sub, fn) -> None:
        import queue as _q

        while not self._stopped.is_set():
            try:
                msg = sub.next(timeout=0.5)
            except _q.Empty:
                continue
            fn(msg)

    def _on_tx(self, msg) -> None:
        d = msg.data
        for sink in self._sinks:
            sink.index_tx(d["height"], d["index"], d["tx"], d["result"], msg.events)

    def _on_block(self, msg) -> None:
        d = msg.data
        for sink in self._sinks:
            sink.index_block(d["block"].header.height, msg.events)
