"""SQL event sink — the psql sink re-designed over DB-API.

Reference parity: internal/state/indexer/sink/psql/ (psql.go + schema.sql)
— blocks / tx_results / events / attributes tables with the
`event_attributes` convenience view semantics. Instead of binding to one
driver, this sink takes ANY DB-API 2.0 connection factory: `psycopg2`
against a real PostgreSQL in production, stdlib `sqlite3` in tests and
single-node deployments (the schema below is written in the dialect
subset both accept).
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List

from . import Sink

_SCHEMA = [
    """CREATE TABLE IF NOT EXISTS blocks (
        rowid      INTEGER PRIMARY KEY,
        height     BIGINT NOT NULL,
        chain_id   VARCHAR NOT NULL,
        created_at VARCHAR NOT NULL,
        UNIQUE (height, chain_id)
    )""",
    """CREATE TABLE IF NOT EXISTS tx_results (
        rowid      INTEGER PRIMARY KEY,
        block_id   BIGINT NOT NULL REFERENCES blocks(rowid),
        tx_index   INTEGER NOT NULL,
        created_at VARCHAR NOT NULL,
        tx_hash    VARCHAR NOT NULL,
        tx_result  BLOB NOT NULL,
        UNIQUE (block_id, tx_index)
    )""",
    """CREATE TABLE IF NOT EXISTS events (
        rowid    INTEGER PRIMARY KEY,
        block_id BIGINT NOT NULL REFERENCES blocks(rowid),
        tx_id    BIGINT NULL REFERENCES tx_results(rowid),
        type     VARCHAR NOT NULL
    )""",
    """CREATE TABLE IF NOT EXISTS attributes (
        event_id  BIGINT NOT NULL REFERENCES events(rowid),
        key       VARCHAR NOT NULL,
        composite_key VARCHAR NOT NULL,
        value     VARCHAR NULL
    )""",
    "CREATE INDEX IF NOT EXISTS idx_blocks_height_chain ON blocks(height, chain_id)",
    "CREATE INDEX IF NOT EXISTS idx_attributes_composite ON attributes(composite_key, value)",
]


def _utc() -> str:
    return time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())


class SQLSink(Sink):
    """psql.EventSink analog over a DB-API connection."""

    def __init__(self, connect: Callable, chain_id: str):
        self._conn = connect() if callable(connect) else connect
        self._chain_id = chain_id
        self._mtx = threading.Lock()
        cur = self._conn.cursor()
        for stmt in _SCHEMA:
            cur.execute(stmt)
        self._conn.commit()

    # -- helpers ---------------------------------------------------------

    def _block_rowid(self, cur, height: int) -> int:
        cur.execute(
            "SELECT rowid FROM blocks WHERE height = ? AND chain_id = ?",
            (height, self._chain_id),
        )
        row = cur.fetchone()
        if row:
            return row[0]
        cur.execute(
            "INSERT INTO blocks (height, chain_id, created_at) VALUES (?, ?, ?)",
            (height, self._chain_id, _utc()),
        )
        return cur.lastrowid

    def _insert_events(self, cur, block_id: int, tx_id, events: Dict[str, List[str]]):
        """events come pre-flattened as {"type.attr": [values]} (the
        eventbus composite-key form); split back into type/key rows like
        psql.go insertEvents."""
        for composite, values in events.items():
            etype, _, key = composite.partition(".")
            cur.execute(
                "INSERT INTO events (block_id, tx_id, type) VALUES (?, ?, ?)",
                (block_id, tx_id, etype),
            )
            event_id = cur.lastrowid
            for v in values:
                cur.execute(
                    "INSERT INTO attributes (event_id, key, composite_key, value)"
                    " VALUES (?, ?, ?, ?)",
                    (event_id, key, composite, v),
                )

    # -- Sink interface ---------------------------------------------------

    def index_block(self, height: int, events: Dict[str, List[str]]) -> None:
        with self._mtx:
            cur = self._conn.cursor()
            block_id = self._block_rowid(cur, height)
            self._insert_events(cur, block_id, None, events)
            self._conn.commit()

    def index_tx(self, height: int, index: int, tx: bytes, result, events) -> None:
        from ..types.tx import tx_hash

        with self._mtx:
            cur = self._conn.cursor()
            block_id = self._block_rowid(cur, height)
            cur.execute(
                "SELECT rowid FROM tx_results WHERE block_id = ? AND tx_index = ?",
                (block_id, index),
            )
            if cur.fetchone():
                self._conn.commit()
                return
            cur.execute(
                "INSERT INTO tx_results (block_id, tx_index, created_at, tx_hash,"
                " tx_result) VALUES (?, ?, ?, ?, ?)",
                (block_id, index, _utc(), tx_hash(tx).hex().upper(), tx),
            )
            tx_id = cur.lastrowid
            self._insert_events(cur, block_id, tx_id, events)
            self._conn.commit()

    # -- queries (psql has none server-side; these aid tests/tools) -------

    def tx_count(self) -> int:
        with self._mtx:
            cur = self._conn.cursor()
            cur.execute("SELECT COUNT(*) FROM tx_results")
            return cur.fetchone()[0]

    def find_tx_hashes_by_event(self, composite_key: str, value: str) -> List[str]:
        with self._mtx:
            cur = self._conn.cursor()
            cur.execute(
                "SELECT DISTINCT t.tx_hash FROM tx_results t"
                " JOIN events e ON e.tx_id = t.rowid"
                " JOIN attributes a ON a.event_id = e.rowid"
                " WHERE a.composite_key = ? AND a.value = ?",
                (composite_key, value),
            )
            return [r[0] for r in cur.fetchall()]

    def close(self) -> None:
        with self._mtx:
            self._conn.close()
