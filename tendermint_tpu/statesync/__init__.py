"""State sync — bootstrap a fresh node from an application snapshot.

Reference parity: internal/statesync/ — the discovery/offer/chunk protocol
(syncer.go:178 SyncAny, offerSnapshot:384, applyChunks:420, verifyApp:567),
chunk queue (chunks.go), and the light-client-backed StateProvider
(stateprovider.go:33) that supplies trusted AppHash/Commit/State; the
p2p dispatcher (dispatcher.go) serves light blocks over a dedicated
channel.

Channels (reactor.go): snapshot 0x60, chunk 0x61, light-block 0x62.
Wire oneofs:
  snapshot ch: 1 snapshots_request{} | 2 snapshots_response{1 height,
               2 format, 3 chunks, 4 hash, 5 metadata}
  chunk ch:    1 chunk_request{1 height, 2 format, 3 index}
               | 2 chunk_response{1 height, 2 format, 3 index, 4 chunk, 5 missing}
  light ch:    1 light_block_request{1 height} | 2 light_block_response{1 lb}
"""

from __future__ import annotations

import queue
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from ..abci import types as abci
from ..light import verifier as light_verifier
from ..light.provider import LightBlock
from ..p2p.conn.mconnection import ChannelDescriptor
from ..p2p.router import Router
from ..state import State
from ..types import Commit, Header, SignedHeader, ValidatorSet
from ..types.params import ConsensusParams
from ..types.block import BlockID
from ..types.validation import verify_commit_light
from ..version import BLOCK_PROTOCOL
from ..wire.canonical import Timestamp
from ..wire.proto import ProtoWriter, decode_message, field_bytes, field_int, to_signed64

SNAPSHOT_CHANNEL = 0x60
CHUNK_CHANNEL = 0x61
LIGHT_BLOCK_CHANNEL = 0x62
PARAMS_CHANNEL = 0x63  # reactor.go ParamsChannel

# stateprovider.go:21-27: the light client behind the state provider uses
# the node's trusting period; this default mirrors config's 14-day window.
DEFAULT_TRUSTING_PERIOD = 14 * 24 * 3600.0
MAX_CLOCK_DRIFT = 10.0


def _now_ts() -> Timestamp:
    t = time.time()
    return Timestamp(seconds=int(t), nanos=int((t % 1.0) * 1e9))

SNAPSHOT_DESC = ChannelDescriptor(id=SNAPSHOT_CHANNEL, priority=5)
CHUNK_DESC = ChannelDescriptor(
    id=CHUNK_CHANNEL, priority=3, recv_message_capacity=16 * 1024 * 1024
)
LIGHT_BLOCK_DESC = ChannelDescriptor(
    id=LIGHT_BLOCK_CHANNEL, priority=5, recv_message_capacity=8 * 1024 * 1024
)
PARAMS_DESC = ChannelDescriptor(id=PARAMS_CHANNEL, priority=2)

ALL_STATESYNC_DESCS = [SNAPSHOT_DESC, CHUNK_DESC, LIGHT_BLOCK_DESC, PARAMS_DESC]


class SyncError(RuntimeError):
    pass


class RetrySnapshot(Exception):
    """syncer.go errRetrySnapshot: the app asked to restart restoration
    of the SAME snapshot (transient failure) — not a snapshot rejection."""


def _enc(kind: int, fields: Optional[dict] = None) -> bytes:
    inner = ProtoWriter()
    for num, val in sorted((fields or {}).items()):
        if isinstance(val, bytes):
            inner.write_bytes(num, val)
        else:
            inner.write_varint(num, val)
    w = ProtoWriter()
    w.write_message(kind, inner.bytes(), always=True)
    return w.bytes()


def _encode_light_block(lb: LightBlock) -> bytes:
    sh = ProtoWriter()
    sh.write_message(1, lb.signed_header.header.encode(), always=True)
    sh.write_message(2, lb.signed_header.commit.encode(), always=True)
    w = ProtoWriter()
    w.write_message(1, sh.bytes(), always=True)
    w.write_message(2, lb.validators.encode(), always=True)
    return w.bytes()


def _decode_light_block(raw: bytes) -> LightBlock:
    f = decode_message(raw)
    sh = decode_message(field_bytes(f, 1))
    return LightBlock(
        signed_header=SignedHeader(
            header=Header.decode(field_bytes(sh, 1)),
            commit=Commit.decode(field_bytes(sh, 2)),
        ),
        validators=ValidatorSet.decode(field_bytes(f, 2)),
    )


@dataclass
class _SnapshotInfo:
    height: int
    format: int
    chunks: int
    hash: bytes
    metadata: bytes
    peers: List[str] = field(default_factory=list)

    def key(self) -> tuple:
        return (self.height, self.format, self.hash)


class StateSyncReactor:
    """internal/statesync/reactor.go + syncer.go (server + client roles)."""

    def __init__(
        self,
        router: Router,
        query_conn,  # ABCI query/snapshot connection
        state_store,
        block_store,
        chain_id: str,
        serving: bool = True,
    ):
        self._router = router
        self._conn = query_conn
        self._state_store = state_store
        self._block_store = block_store
        self._chain_id = chain_id
        self._serving = serving
        self._snap_ch = router.open_channel(SNAPSHOT_DESC)
        self._chunk_ch = router.open_channel(CHUNK_DESC)
        self._lb_ch = router.open_channel(LIGHT_BLOCK_DESC)
        self._params_ch = router.open_channel(PARAMS_DESC)
        self._stopped = threading.Event()
        self._snapshots: Dict[tuple, _SnapshotInfo] = {}
        # (height, format, index) -> (chunk bytes, sender peer id)
        self._chunks: Dict[Tuple[int, int, int], Tuple[bytes, str]] = {}
        self._banned_senders: set = set()
        self._light_blocks: Dict[int, LightBlock] = {}
        self._params: Dict[int, ConsensusParams] = {}
        self._mtx = threading.Lock()

    def start(self) -> None:
        for ch, handler in (
            (self._snap_ch, self._handle_snapshot_msg),
            (self._chunk_ch, self._handle_chunk_msg),
            (self._lb_ch, self._handle_light_block_msg),
            (self._params_ch, self._handle_params_msg),
        ):
            t = threading.Thread(target=self._process, args=(ch, handler), daemon=True)
            t.start()

    def stop(self) -> None:
        self._stopped.set()

    def _process(self, ch, handler) -> None:
        while not self._stopped.is_set():
            try:
                env = ch.receive(timeout=0.5)
            except queue.Empty:
                continue
            try:
                handler(env)
            except (ValueError, KeyError):
                continue

    # -- server side ------------------------------------------------------

    RECENT_SNAPSHOTS = 10  # reactor.go recentSnapshots

    def _handle_snapshot_msg(self, env) -> None:
        f = decode_message(env.message)
        if 1 in f and self._serving:  # snapshots_request
            res = self._conn.list_snapshots()
            # NEWEST first, capped (reactor.go recentSnapshots): apps with
            # bounded retention prune old snapshots, so advertising
            # oldest-first steers the syncer toward soon-to-vanish ones
            advertised = sorted(
                res.snapshots, key=lambda s: (-s.height, s.format)
            )[: self.RECENT_SNAPSHOTS]
            for s in advertised:
                self._snap_ch.send(
                    env.from_id,
                    _enc(2, {1: s.height, 2: s.format, 3: s.chunks, 4: s.hash, 5: s.metadata}),
                )
        elif 2 in f:  # snapshots_response
            r = decode_message(field_bytes(f, 2))
            info = _SnapshotInfo(
                height=field_int(r, 1),
                format=field_int(r, 2),
                chunks=field_int(r, 3),
                hash=field_bytes(r, 4),
                metadata=field_bytes(r, 5),
            )
            with self._mtx:
                existing = self._snapshots.setdefault(info.key(), info)
                if env.from_id not in existing.peers:
                    existing.peers.append(env.from_id)

    def _handle_chunk_msg(self, env) -> None:
        f = decode_message(env.message)
        if 1 in f and self._serving:  # chunk_request
            r = decode_message(field_bytes(f, 1))
            height, fmt = field_int(r, 1), field_int(r, 2)
            res = self._conn.load_snapshot_chunk(
                abci.RequestLoadSnapshotChunk(
                    height=height, format=fmt, chunk=field_int(r, 3)
                )
            )
            # missing means "I no longer have this snapshot" (reactor.go:
            # resp.Chunk == nil), NOT "the chunk is zero-length" — a
            # legitimately empty chunk from a still-advertised snapshot
            # must be served as data or the slot can never be filled
            missing = 1 if not res.chunk else 0
            if missing:
                try:
                    have = self._conn.list_snapshots().snapshots
                    if any(s.height == height and s.format == fmt for s in have):
                        missing = 0
                except Exception:  # noqa: BLE001 — keep the missing verdict
                    pass
            self._chunk_ch.send(
                env.from_id,
                _enc(2, {
                    1: height, 2: fmt, 3: field_int(r, 3),
                    4: res.chunk, 5: missing,
                }),
            )
        elif 2 in f:  # chunk_response
            r = decode_message(field_bytes(f, 2))
            key = (field_int(r, 1), field_int(r, 2), field_int(r, 3))
            with self._mtx:
                # keep the sender: the app can blame it (reject_senders).
                # Banned senders are ignored, and a cached chunk is never
                # overwritten (chunks.go Add: first writer wins) — a
                # malicious re-send must not clobber an honest peer's data
                if env.from_id in self._banned_senders or key in self._chunks:
                    return
                if field_int(r, 5):
                    return  # missing=1: the peer pruned this snapshot
                self._chunks[key] = (field_bytes(r, 4), env.from_id)

    def _handle_light_block_msg(self, env) -> None:
        f = decode_message(env.message)
        if 1 in f and self._serving:  # light_block_request
            r = decode_message(field_bytes(f, 1))
            height = to_signed64(field_int(r, 1))
            lb = self._load_local_light_block(height)
            if lb is not None:
                self._lb_ch.send(env.from_id, _enc(2, {1: _encode_light_block(lb)}))
        elif 2 in f:  # light_block_response
            r = decode_message(field_bytes(f, 2))
            lb = _decode_light_block(field_bytes(r, 1))
            with self._mtx:
                self._light_blocks[lb.height] = lb

    def _load_local_light_block(self, height: int) -> Optional[LightBlock]:
        meta = self._block_store.load_block_meta(height)
        commit = self._block_store.load_block_commit(height)
        if meta is None or commit is None:
            return None
        try:
            vals = self._state_store.load_validators(height)
        except KeyError:
            return None
        return LightBlock(
            signed_header=SignedHeader(header=meta.header, commit=commit),
            validators=vals,
        )

    # -- client side: the sync (syncer.go:178 SyncAny) ---------------------

    def _handle_params_msg(self, env) -> None:
        """reactor.go:?? params channel: 1 request{1 height} ->
        2 response{1 height, 2 params}; served from the state store."""
        f = decode_message(env.message)
        if 1 in f and self._serving and self._state_store is not None:
            req = decode_message(field_bytes(f, 1))
            height = to_signed64(field_int(req, 1))
            try:
                params = self._state_store.load_consensus_params(height)
            except KeyError:
                return
            self._params_ch.send(
                env.from_id, _enc(2, {1: height, 2: params.encode()})
            )
        elif 2 in f:
            res = decode_message(field_bytes(f, 2))
            height = to_signed64(field_int(res, 1))
            with self._mtx:
                self._params[height] = ConsensusParams.decode(field_bytes(res, 2))

    def _fetch_params(self, height: int, timeout: float = 10.0) -> Optional[ConsensusParams]:
        """syncer.go params fetch at the snapshot height (replacing the
        round-2 genesis-params shortcut)."""
        deadline = time.time() + timeout
        while time.time() < deadline:
            with self._mtx:
                p = self._params.get(height)
            if p is not None:
                return p
            self._params_ch.broadcast(_enc(1, {1: height}))
            time.sleep(0.2)
        return None

    def backfill(self, state: State) -> int:
        """reactor.go:504 backfill: after a snapshot restore, walk the
        chain BACKWARDS from the snapshot height over the evidence window
        (max_age_num_blocks / max_age_duration), hash-link-verifying each
        header, and persist headers+commits+validator sets so historical
        evidence can be verified. Returns the number of blocks stored."""
        ev = state.consensus_params.evidence
        stop_height = max(
            state.initial_height, state.last_block_height - ev.max_age_num_blocks
        )
        stop_time_ns = (
            state.last_block_time.seconds * 10**9
            + state.last_block_time.nanos
            - ev.max_age_duration_ns
        )
        current = self._load_local_light_block(state.last_block_height)
        if current is None:
            return 0
        stored = 0
        for h in range(state.last_block_height - 1, stop_height - 1, -1):
            t_ns = (
                current.signed_header.header.time.seconds * 10**9
                + current.signed_header.header.time.nanos
            )
            if t_ns < stop_time_ns:
                break  # time window exhausted (range() bounds the heights)
            try:
                lb = self._fetch_light_block(h)
            except SyncError:
                break
            # hash-linkage: the verified child must point at this header
            if current.signed_header.header.last_block_id.hash != lb.hash():
                raise SyncError(f"backfill: hash mismatch at height {h}")
            if lb.signed_header.header.validators_hash != lb.validators.hash():
                raise SyncError(f"backfill: validator hash mismatch at {h}")
            # the commit must actually commit THIS header with +2/3 of its
            # validator set (reactor.go backfill verifies light blocks; a
            # byzantine peer could otherwise attach garbage commits to the
            # genuine hash-linked header)
            try:
                lb.signed_header.validate_basic(self._chain_id)
                verify_commit_light(
                    self._chain_id,
                    lb.validators,
                    lb.signed_header.commit.block_id,
                    h,
                    lb.signed_header.commit,
                )
            except ValueError as e:
                raise SyncError(f"backfill: bad commit at height {h}: {e}") from e
            self._block_store.save_signed_header(
                lb.signed_header, current.signed_header.header.last_block_id
            )
            self._state_store.save_validators_at(h, lb.validators)
            stored += 1
            current = lb
        return stored

    def _fetch_light_block(self, height: int, timeout: float = 10.0) -> LightBlock:
        deadline = time.time() + timeout
        while time.time() < deadline:
            with self._mtx:
                lb = self._light_blocks.get(height)
            if lb is not None:
                return lb
            self._lb_ch.broadcast(_enc(1, {1: height}))
            time.sleep(0.2)
        raise SyncError(f"no light block at height {height}")

    def sync_any(
        self,
        genesis_state: State,
        trust_height: int,
        trust_hash: bytes,
        discovery_time: float = 5.0,
        chunk_timeout: float = 15.0,
    ) -> Tuple[State, Commit]:
        """Discover a snapshot, restore it, verify the app, and build the
        post-sync State with light-client-verified trust."""
        # 1. verify the root of trust (light/client.go
        # initializeWithTrustOptions: hash match, vals bound to the header,
        # commit verified by those vals over this exact header).
        root = self._fetch_light_block(trust_height)
        if root.hash() != trust_hash:
            raise SyncError(
                f"trust hash mismatch at height {trust_height}: "
                f"got {root.hash().hex()}, want {trust_hash.hex()}"
            )
        root.signed_header.validate_basic(self._chain_id)
        if root.validators.hash() != root.signed_header.header.validators_hash:
            raise SyncError("trusted root validators do not match header")
        verify_commit_light(
            self._chain_id, root.validators, root.signed_header.commit.block_id,
            trust_height, root.signed_header.commit,
        )
        trusted: Dict[int, LightBlock] = {trust_height: root}

        # 2. discover snapshots
        # Multiple discovery rounds with a FRESH snapshot list each time
        # (syncer.go re-discovers as peers advertise): serving apps retain
        # only their newest snapshots, so a fast-moving chain can prune a
        # snapshot between our discovery and the chunk fetch — stale
        # candidates must not doom the whole sync.
        discovered_any = False
        failed: set = set()  # (height, format, hash) keys that already failed
        for _round in range(3):
            with self._mtx:
                self._snapshots.clear()
            # wait the FULL discovery window (syncer.go waits
            # discoveryTime): grabbing the first response would bias
            # toward whatever snapshot message lands first, not the best
            deadline = time.time() + discovery_time
            while time.time() < deadline:
                self._snap_ch.broadcast(_enc(1))
                time.sleep(min(0.2, max(deadline - time.time(), 0.01)))
            with self._mtx:
                candidates = sorted(
                    self._snapshots.values(), key=lambda s: (-s.height, s.format)
                )
            discovered_any = discovered_any or bool(candidates)
            fresh = [c for c in candidates if c.key() not in failed]
            if not fresh:
                break  # only known-bad snapshots left: re-trying won't help
            for snap in fresh:
                for _attempt in range(3):
                    try:
                        return self._sync_one(
                            genesis_state, snap, chunk_timeout, trusted
                        )
                    except RetrySnapshot:
                        # syncer.go errRetrySnapshot: restart restoration
                        # of this same snapshot (not a rejection)
                        continue
                    except SyncError:
                        failed.add(snap.key())
                        break
                    finally:
                        # chunkQueue teardown: drop this snapshot's cached
                        # chunks whether the attempt succeeded or not
                        with self._mtx:
                            for k in [
                                k
                                for k in self._chunks
                                if k[0] == snap.height and k[1] == snap.format
                            ]:
                                del self._chunks[k]
                else:
                    failed.add(snap.key())
        if not discovered_any:
            raise SyncError("no snapshots discovered")
        raise SyncError("all discovered snapshots failed")

    def _verified_light_block(
        self,
        height: int,
        trusted: Dict[int, LightBlock],
        trusting_period: float = DEFAULT_TRUSTING_PERIOD,
    ) -> LightBlock:
        """Fetch a light block and verify it through the light-client chain
        of trust rooted at the operator-provided trust hash — NOT against
        its own peer-supplied validator set (stateprovider.go:33: every
        header the state provider returns flows through light.Client
        verification; skipping verification with bisection is
        light/client.go:639 verifySkipping)."""
        if height in trusted:
            return trusted[height]
        lower = [h for h in trusted if h < height]
        if not lower:
            raise SyncError(
                f"height {height} is below the trusted root "
                f"{min(trusted)} — cannot establish trust"
            )
        cur = trusted[max(lower)]
        now = _now_ts()
        pending = [height]
        fetched: Dict[int, LightBlock] = {}  # unverified fetch cache: each
        # bisection retry would otherwise re-fetch the same block (10s
        # network round-trip each)
        while pending:
            h = pending[-1]
            if h in trusted:
                cur = trusted[h]
                pending.pop()
                continue
            lb = fetched.get(h)
            if lb is None:
                lb = fetched[h] = self._fetch_light_block(h)
            try:
                light_verifier.verify(
                    cur.signed_header, cur.validators,
                    lb.signed_header, lb.validators,
                    trusting_period, now, MAX_CLOCK_DRIFT,
                    light_verifier.DEFAULT_TRUST_LEVEL,
                )
            except light_verifier.ErrNotEnoughTrust:
                # bisect: pivot 9/16 of the way up (client.go:44-45)
                pivot = cur.height + (h - cur.height) * 9 // 16
                if pivot <= cur.height or pivot >= h:
                    raise SyncError(f"cannot bisect between {cur.height} and {h}")
                pending.append(pivot)
                continue
            except ValueError as e:
                raise SyncError(
                    f"light block at height {h} failed verification: {e}"
                ) from e
            trusted[h] = lb
            cur = lb
            pending.pop()
        return trusted[height]

    def _sync_one(
        self,
        genesis_state: State,
        snap: _SnapshotInfo,
        chunk_timeout: float,
        trusted: Dict[int, LightBlock],
    ):
        # Both headers verified through the chain of trust from the root —
        # the trusted app hash comes from the header at snapshot height + 1.
        snap_block = self._verified_light_block(snap.height, trusted)
        header_next = self._verified_light_block(snap.height + 1, trusted)
        trusted_app_hash = header_next.signed_header.header.app_hash
        if header_next.signed_header.header.last_block_id.hash != snap_block.hash():
            raise SyncError("light block chain linkage broken")

        # 3. offer to the app (syncer.go:384)
        res = self._conn.offer_snapshot(
            abci.RequestOfferSnapshot(
                snapshot=abci.Snapshot(
                    height=snap.height, format=snap.format, chunks=snap.chunks,
                    hash=snap.hash, metadata=snap.metadata,
                ),
                app_hash=trusted_app_hash,
            )
        )
        if res.result != abci.OFFER_SNAPSHOT_ACCEPT:
            raise SyncError(f"snapshot rejected by app: {res.result}")

        # 4. fetch + apply chunks (chunks.go + syncer.go:420-470). The app
        # steers recovery: RETRY re-applies the same chunk (refetched),
        # refetch_chunks re-fetches earlier chunks it discarded,
        # reject_senders bans their sources, RETRY_SNAPSHOT/REJECT abort
        # this candidate (sync_any moves to the next snapshot).
        pending = set(range(snap.chunks))  # chunkQueue: lowest unreturned next
        retries = 0
        max_retries = 4 * max(snap.chunks, 1)
        while pending:
            index = min(pending)
            pending.discard(index)
            chunk, sender = self._fetch_chunk(snap, index, chunk_timeout)
            ares = self._conn.apply_snapshot_chunk(
                abci.RequestApplySnapshotChunk(
                    index=index, chunk=chunk, sender=sender
                )
            )
            # chunks.Discard: drop the cached bytes so they are refetched
            for r_idx in ares.refetch_chunks:
                with self._mtx:
                    self._chunks.pop((snap.height, snap.format, r_idx), None)
                pending.add(r_idx)
                retries += 1
                if retries > max_retries:
                    raise SyncError("refetch limit exceeded")
            # snapshots.RejectPeer + chunks.DiscardSender: ban the sender
            # and drop any cached chunks it supplied
            if ares.reject_senders:
                rejected = set(ares.reject_senders)
                with self._mtx:
                    self._banned_senders.update(rejected)
                    for key in [
                        k
                        for k, (_, snd) in self._chunks.items()
                        if snd in rejected
                    ]:
                        del self._chunks[key]
            if ares.result == abci.APPLY_SNAPSHOT_CHUNK_ACCEPT:
                # chunkQueue discards a chunk once applied — a multi-GB
                # snapshot must not pin every chunk in RAM
                with self._mtx:
                    self._chunks.pop((snap.height, snap.format, index), None)
            elif ares.result == abci.APPLY_SNAPSHOT_CHUNK_RETRY:
                # chunks.Retry: re-apply the SAME cached bytes (no refetch)
                retries += 1
                if retries > max_retries:
                    raise SyncError(f"chunk {index}: retry limit exceeded")
                pending.add(index)
            elif ares.result == abci.APPLY_SNAPSHOT_CHUNK_RETRY_SNAPSHOT:
                raise RetrySnapshot(f"app requested retry at chunk {index}")
            else:
                raise SyncError(f"chunk {index} rejected: {ares.result}")

        # 5. verify the app took the snapshot (syncer.go:565 verifyApp)
        info = self._conn.info(abci.RequestInfo())
        if info.last_block_app_hash != trusted_app_hash:
            raise SyncError(
                f"appHash verification failed: expected {trusted_app_hash.hex()}, "
                f"got {info.last_block_app_hash.hex()}"
            )
        if info.last_block_height != snap.height:
            raise SyncError("app reported unexpected last block height")

        # 6. build State (stateprovider.go State()) — validator sets come
        # from chain-of-trust-verified light blocks only.
        next_vals = header_next.validators
        try:
            nn_vals = self._verified_light_block(snap.height + 2, trusted).validators
        except SyncError:
            nn_vals = next_vals
        # consensus params at the snapshot height from the params channel
        # (reactor.go params fetch); genesis params only as a last resort
        params = self._fetch_params(snap.height, timeout=5.0)
        if params is not None:
            params_height = snap.height
        else:
            params = genesis_state.consensus_params
            params_height = genesis_state.initial_height
        state = State(
            version=genesis_state.version,
            chain_id=self._chain_id,
            initial_height=genesis_state.initial_height,
            last_block_height=snap.height,
            last_block_id=header_next.signed_header.header.last_block_id,
            last_block_time=snap_block.signed_header.header.time,
            validators=next_vals.copy(),
            next_validators=nn_vals.copy(),
            last_validators=snap_block.validators.copy(),
            last_height_validators_changed=snap.height + 1,
            consensus_params=params,
            last_height_consensus_params_changed=params_height,
            last_results_hash=header_next.signed_header.header.last_results_hash,
            app_hash=trusted_app_hash,
        )
        # bootstrap the stores (node.go statesync completion)
        self._state_store.bootstrap(state)
        self._block_store.save_signed_header(
            snap_block.signed_header,
            header_next.signed_header.header.last_block_id,
        )
        return state, snap_block.signed_header.commit

    def _fetch_chunk(
        self, snap: _SnapshotInfo, index: int, timeout: float
    ) -> tuple:
        """-> (chunk_bytes, sender_id). Senders the app rejected
        (banned_senders) are never asked again (syncer.go applyChunks
        RejectSenders)."""
        key = (snap.height, snap.format, index)
        deadline = time.time() + timeout
        while time.time() < deadline:
            with self._mtx:
                entry = self._chunks.get(key)
                banned = set(self._banned_senders)
                if entry is not None and entry[1] in banned:
                    # poisoned source; drop under the SAME lock so a
                    # fresh chunk landing in between is never discarded
                    del self._chunks[key]
                    entry = None
            if entry is not None:
                return entry
            peers = [p for p in (snap.peers or [""]) if p not in banned]
            if snap.peers and not peers:
                # every known source of this snapshot has been banned via
                # RejectSenders — replies from them are dropped on receipt
                # (_handle_chunk_msg), so waiting out the timeout can never
                # succeed; fail the restore attempt now (syncer.go
                # applyChunks errNoSnapshotSources spirit)
                raise SyncError(
                    f"no usable sources for chunk {index}: all "
                    f"{len(snap.peers)} snapshot peers are banned"
                )
            for peer in peers or [""]:
                msg = _enc(1, {1: snap.height, 2: snap.format, 3: index})
                if peer:
                    self._chunk_ch.send(peer, msg)
                else:
                    self._chunk_ch.broadcast(msg)
            time.sleep(0.2)
        raise SyncError(f"timed out fetching chunk {index}")
