"""Randomized testnet-manifest generator.

Reference parity: test/e2e/generator/generate.go — the nightly sweep
generates manifests over a Cartesian product of global options (topology,
initial height) with per-node randomized choices (mode, start height,
perturbations, misbehavior) drawn from weighted distributions. This build
keeps the same shape but emits `e2e.Manifest` objects the in-process
`Testnet` runner consumes directly (the docker/ABCI-transport/database
axes collapse: one process, memdb, builtin app).
"""

from __future__ import annotations

import random
from typing import Dict, List

from . import Manifest, NodeManifest


class weighted_choice(Dict[str, int]):
    """generate.go weightedChoice: pick a key with probability
    proportional to its integer weight."""

    def choose(self, r: random.Random):
        total = sum(self.values())
        x = r.randrange(total)
        for k, w in sorted(self.items()):
            x -= w
            if x < 0:
                return k
        raise AssertionError("unreachable")


class uniform_choice(list):
    """generate.go uniformChoice."""

    def choose(self, r: random.Random):
        return r.choice(self)


class prob_set_choice(Dict[str, float]):
    """generate.go probSetChoice: include each key independently with its
    probability."""

    def choose(self, r: random.Random) -> List[str]:
        return [k for k, p in sorted(self.items()) if r.random() <= p]


class _perturbation_choice(prob_set_choice):
    """Perturbation draw: kill and restart are mutually exclusive
    (restart implies a kill; a node with both would be rebuilt by
    perturb() and end up running while every downstream liveness check
    assumes it dead)."""

    def choose(self, r: random.Random) -> List[str]:
        picks = super().choose(r)
        if "kill" in picks and "restart" in picks:
            picks.remove("kill")
        return picks


TOPOLOGIES = uniform_choice(["single", "quad", "large"])
INITIAL_HEIGHTS = uniform_choice([1, 1000])
NODE_POWERS = uniform_choice([10, 50, 100])
PERTURBATIONS = _perturbation_choice(
    {"disconnect": 0.1, "restart": 0.1, "kill": 0.05}
)
MISBEHAVIORS = weighted_choice({"": 90, "double-prevote": 10})
START_AT_PROB = 0.2  # late joiner exercising blocksync catch-up


def generate(r: random.Random, min_size: int = 1, max_size: int = 0) -> List[Manifest]:
    """Generate one manifest per topology x initial-height combination
    (generate.go Generate), filtered to [min_size, max_size)."""
    manifests = []
    for topology in TOPOLOGIES:
        for initial_height in INITIAL_HEIGHTS:
            m = _generate_testnet(r, topology, initial_height)
            if len(m.nodes) < min_size:
                continue
            if max_size and len(m.nodes) >= max_size:
                continue
            manifests.append(m)
    return manifests


def _generate_testnet(r: random.Random, topology: str, initial_height: int) -> Manifest:
    if topology == "single":
        n_validators, n_fulls = 1, 0
    elif topology == "quad":
        n_validators, n_fulls = 4, 0
    else:  # large: 5-8 validators, 1-2 full nodes (scaled-down
        # generate.go "large": in-process threads, not 32 containers)
        n_validators, n_fulls = 5 + r.randrange(4), 1 + r.randrange(2)

    manifest = Manifest(
        chain_id=f"gen-{topology}-{initial_height}",
        initial_height=initial_height,
        load_tx_count=10,
        wait_blocks=4,
        nodes=[],
    )
    misbehave_used = False
    for i in range(n_validators):
        misbehave = ""
        # at most one equivocator, never in a 1- or 2-validator net (it
        # would halt: >1/3 byzantine power)
        if n_validators >= 4 and not misbehave_used:
            misbehave = MISBEHAVIORS.choose(r)
            misbehave_used = bool(misbehave)
        manifest.nodes.append(
            NodeManifest(
                name=f"validator{i:02d}",
                mode="validator",
                # the equivocator gets the minimum power so its share
                # stays below 1/3 regardless of the other draws (>=4
                # validators of >=10 each bounds it at 10/40 = 25%)
                power=min(NODE_POWERS) if misbehave else NODE_POWERS.choose(r),
                perturb=[] if misbehave else PERTURBATIONS.choose(r),
                misbehave=misbehave,
            )
        )
    for i in range(n_fulls):
        start_at = 0
        if r.random() <= START_AT_PROB:
            # join once the chain has blocks to sync (generate.go derives
            # startAt from initialHeight the same way)
            start_at = initial_height + 2
        manifest.nodes.append(
            NodeManifest(
                name=f"full{i:02d}",
                mode="full",
                start_at=start_at,
                # a late joiner is not running when perturb() fires, so
                # perturbing it would start it early and break start_at
                perturb=[] if start_at else PERTURBATIONS.choose(r),
            )
        )
    # a net that loses >1/3 of its voting power to kill perturbations
    # cannot reach the 2/3 quorum and halts; strip kills (highest power
    # first) until the surviving power clears the threshold
    vals = [n for n in manifest.nodes if n.mode == "validator"]
    total = sum(n.power for n in vals)
    for n in sorted(vals, key=lambda n: -n.power):
        alive = sum(v.power for v in vals if "kill" not in v.perturb)
        if alive * 3 > total * 2:
            break
        if "kill" in n.perturb:
            n.perturb = [p for p in n.perturb if p != "kill"]
    return manifest
