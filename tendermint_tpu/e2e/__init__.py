"""End-to-end test harness — manifest-driven in-process testnets.

Reference parity: test/e2e/ — the runner pipeline (runner/main.go:45-130):
setup → start → tx load → perturbations (kill/restart/disconnect) → wait →
invariant tests (RPC-only, black-box) → benchmark. Manifests describe
heterogeneous networks (validator/full nodes, sync modes); the reference
uses docker-compose, this build runs nodes in-process (threads) which is
the same seam its reactor tests use (SURVEY.md §4).
"""

from __future__ import annotations

import random
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from ..abci import PersistentKVStoreApplication
from ..config import Config, ConsensusConfig
from ..crypto import ed25519
from ..node import Node, make_node
from ..p2p import NodeKey, PeerAddress
from ..privval import FilePV
from ..rpc import HTTPClient
from ..types import Timestamp
from ..types.genesis import GenesisDoc, GenesisValidator


@dataclass
class NodeManifest:
    """test/e2e/pkg/manifest.go Node."""

    name: str
    mode: str = "validator"  # validator | full
    power: int = 10
    start_at: int = 0  # join later (block height)
    perturb: List[str] = field(default_factory=list)  # kill | restart | disconnect
    misbehave: str = ""  # "double-prevote" -> equivocate (runner misbehaviors)


@dataclass
class Manifest:
    """test/e2e/pkg/manifest.go Manifest (condensed)."""

    chain_id: str = "e2e-chain"
    nodes: List[NodeManifest] = field(default_factory=list)
    initial_height: int = 1
    load_tx_count: int = 20
    wait_blocks: int = 4


@dataclass
class _RunningNode:
    manifest: NodeManifest
    node: Node
    sk: object
    node_key: NodeKey
    rpc: Optional[HTTPClient] = None


class Testnet:
    """runner/main.go — orchestrates an in-process testnet."""

    def __init__(self, manifest: Manifest, consensus_config: Optional[ConsensusConfig] = None):
        self.manifest = manifest
        self._ccfg = consensus_config or ConsensusConfig(
            timeout_propose_ms=400, timeout_propose_delta_ms=100,
            timeout_prevote_ms=200, timeout_prevote_delta_ms=100,
            timeout_precommit_ms=200, timeout_precommit_delta_ms=100,
            timeout_commit_ms=100, skip_timeout_commit=False,
        )
        self.nodes: Dict[str, _RunningNode] = {}
        self._genesis_json: str = ""

    # -- setup (runner: Setup) -------------------------------------------

    def setup(self) -> None:
        validators = [m for m in self.manifest.nodes if m.mode == "validator"]
        sks = {
            m.name: ed25519.gen_priv_key((m.name * 32).encode()[:32])
            for m in self.manifest.nodes
        }
        doc = GenesisDoc(
            chain_id=self.manifest.chain_id,
            genesis_time=Timestamp(seconds=1_700_000_000),
            initial_height=self.manifest.initial_height,
            validators=[
                GenesisValidator(address=b"", pub_key=sks[m.name].pub_key(), power=m.power)
                for m in validators
            ],
        )
        self._genesis_json = doc.to_json()
        for i, m in enumerate(self.manifest.nodes):
            self._build_node(i, m, sks[m.name])
        # full mesh of persistent peers
        for name, rn in self.nodes.items():
            entries = []
            for other, orn in self.nodes.items():
                if other != name and orn.node.router is not None and rn.node.router is not None:
                    rn.node.router._pm.add_address(
                        PeerAddress(
                            orn.node_key.node_id,
                            orn.node.router._transport.listen_addr,
                        ),
                        persistent=True,
                    )
                    entries.append(
                        f"{orn.node_key.node_id}@{orn.node.router._transport.listen_addr}"
                    )
            # full nodes record the mesh in config too so
            # _should_block_sync routes them through the real
            # blocksync->consensus handoff (late joiners catch up over
            # the blocksync channel, not consensus gossip); validators
            # skip it to start consensus at genesis without the
            # caught-up wait
            if rn.manifest.mode != "validator":
                rn.node.config.p2p.persistent_peers = ",".join(entries)

    def _build_node(self, i: int, m: NodeManifest, sk) -> None:
        cfg = Config()
        cfg.base.home = ""
        cfg.base.db_backend = "memdb"
        cfg.base.moniker = m.name
        cfg.consensus = self._ccfg
        cfg.p2p.laddr = "tcp://127.0.0.1:0"
        cfg.rpc.laddr = "tcp://127.0.0.1:0"
        node = make_node(
            cfg,
            app=PersistentKVStoreApplication(),
            genesis=GenesisDoc.from_json(self._genesis_json),
            priv_validator=FilePV(sk) if m.mode == "validator" else None,
            node_key=NodeKey.generate((f"nk-{m.name}" * 8).encode()[:32]),
            with_rpc=True,
        )
        self.nodes[m.name] = _RunningNode(manifest=m, node=node, sk=sk, node_key=node.node_key)
        if m.misbehave == "double-prevote" and m.mode == "validator":
            self._install_equivocation(self.nodes[m.name])

    def _install_equivocation(self, rn: "_RunningNode") -> None:
        """Manifest misbehavior (runner's double-sign injection): the node
        prevotes the real proposal AND a fabricated block every round; the
        conflicting vote gossips out and must come back as committed
        DuplicateVoteEvidence (checked by check_evidence_committed)."""
        from ..types import Vote
        from ..types.block import BlockID, PartSetHeader
        from ..types.vote import PREVOTE_TYPE

        cs = rn.node.consensus
        orig = cs._do_prevote
        chain_id = self.manifest.chain_id

        def equivocating_prevote(cs_self, height, round_):
            orig(height, round_)
            addr = cs_self._priv_validator_pub_key.address()
            idx, _ = cs_self.rs.validators.get_by_address(addr)
            bid = BlockID(
                hash=b"\x66" * 32,
                part_set_header=PartSetHeader(total=1, hash=b"\x66" * 32),
            )
            evil = Vote(
                type=PREVOTE_TYPE,
                height=cs_self.rs.height,
                round=cs_self.rs.round,
                block_id=bid,
                timestamp=cs_self._vote_time(),
                validator_address=addr,
                validator_index=idx,
            )
            sig = cs_self._priv_validator._priv_key.sign(evil.sign_bytes(chain_id))
            evil = Vote(**{**evil.__dict__, "signature": sig})
            # hand the conflicting vote to every peer (gossip shortcut)
            for other in self.nodes.values():
                if other.node.consensus is not cs_self:
                    other.node.consensus.add_vote_msg(evil, peer_id="byz")

        cs.do_prevote_override = equivocating_prevote

    # -- run (runner: Start/Load/Perturb/Wait) ----------------------------

    def start(self) -> None:
        """Start every node with start_at == 0; late joiners (start_at > 0,
        runner/start.go wait-then-start) are launched by start_late_joiners
        once the network reaches their height and catch up via blocksync."""
        for rn in self.nodes.values():
            if rn.manifest.start_at == 0:
                rn.node.start()
                rn.rpc = HTTPClient(rn.node.rpc_server.listen_addr)

    def start_late_joiners(self, timeout: float = 60.0) -> None:
        pending = [rn for rn in self.nodes.values() if rn.rpc is None]
        for rn in sorted(pending, key=lambda r: r.manifest.start_at):
            # wait on any node that is actually running (the first pick may
            # have been killed by a prior perturb()); with none running the
            # joiner starts immediately and produces/syncs on its own
            gate = next(
                (
                    o
                    for o in self.nodes.values()
                    if o.rpc is not None and "kill" not in o.manifest.perturb
                ),
                None,
            )
            if gate is not None and rn.manifest.start_at > 0:
                gate.node.wait_for_height(rn.manifest.start_at, timeout=timeout)
            rn.node.start()
            rn.rpc = HTTPClient(rn.node.rpc_server.listen_addr)

    def load_transactions(self) -> List[bytes]:
        """runner/load.go: submit load via RPC round-robin."""
        txs = []
        rns = [rn for rn in self.nodes.values() if rn.rpc is not None]
        for i in range(self.manifest.load_tx_count):
            tx = f"load-{i}=v{i}".encode()
            rn = rns[i % len(rns)]
            rn.rpc.broadcast_tx_sync(tx)
            txs.append(tx)
        return txs

    def perturb(self) -> None:
        """runner/perturb.go: apply manifest perturbations."""
        for rn in list(self.nodes.values()):
            for kind in rn.manifest.perturb:
                if kind == "disconnect":
                    # sever all connections; peer manager will redial
                    for nid in rn.node.router.connected():
                        rn.node.router.disconnect_peer(nid)
                elif kind == "kill":
                    rn.node.stop()
                elif kind == "restart":
                    rn.node.stop()
                    time.sleep(0.3)
                    self._build_node(0, rn.manifest, rn.sk)
                    new_rn = self.nodes[rn.manifest.name]
                    for other, orn in self.nodes.items():
                        if other != rn.manifest.name:
                            new_rn.node.router._pm.add_address(
                                PeerAddress(
                                    orn.node_key.node_id,
                                    orn.node.router._transport.listen_addr,
                                ),
                                persistent=True,
                            )
                    new_rn.node.start()
                    new_rn.rpc = HTTPClient(new_rn.node.rpc_server.listen_addr)

    def wait_for_height(self, height: int, timeout: float = 120.0) -> None:
        deadline = time.time() + timeout
        for rn in self._live():
            remaining = max(deadline - time.time(), 0.1)
            rn.node.wait_for_height(height, timeout=remaining)

    def stop(self) -> None:
        for rn in self.nodes.values():
            try:
                rn.node.stop()
            except Exception:  # noqa: BLE001
                pass

    def _live(self):
        return [
            rn
            for rn in self.nodes.values()
            if rn.rpc is not None and "kill" not in rn.manifest.perturb
        ]

    # -- invariants (test/e2e/tests, RPC-only black box) -------------------

    def check_invariants(self) -> None:
        live = self._live()
        heights = {}
        for rn in live:
            st = rn.rpc.status()
            heights[rn.manifest.name] = int(st["sync_info"]["latest_block_height"])
        min_h = min(heights.values())
        # block_test.go: all nodes agree on every height up to min
        reference_hashes = {}
        first = self.manifest.initial_height
        for h in range(first, min_h + 1):
            for rn in live:
                blk = rn.rpc.block(h)
                bh = blk["block_id"]["hash"]
                if h in reference_hashes:
                    assert reference_hashes[h] == bh, (
                        f"height {h}: {rn.manifest.name} disagrees"
                    )
                else:
                    reference_hashes[h] = bh
        # validator_test.go: validator sets consistent
        vals0 = live[0].rpc.validators(first)
        for rn in live[1:]:
            assert rn.rpc.validators(first) == vals0

    def check_evidence_committed(self, timeout: float = 30.0) -> dict:
        """evidence_test.go: with a misbehaving node in the manifest, some
        committed block must carry DuplicateVoteEvidence naming it."""
        import time as _t

        assert any(m.misbehave for m in self.manifest.nodes), "no misbehavior configured"
        honest = next(rn for rn in self._live() if not rn.manifest.misbehave)
        deadline = _t.time() + timeout
        scanned = 0  # evidence can't appear retroactively in old heights
        while _t.time() < deadline:
            tip = int(honest.rpc.status()["sync_info"]["latest_block_height"])
            for h in range(scanned + 1, tip + 1):
                blk = honest.rpc.block(h)
                ev = blk["block"].get("evidence", {}).get("evidence") or []
                if ev:
                    return {"height": h, "evidence": ev}
            scanned = tip
            _t.sleep(0.3)
        raise AssertionError("no evidence committed within timeout")

    def rotate_validator_power(self, name: str, power: int) -> None:
        """Submit the kvstore validator-update tx (persistent_kvstore.go
        "val:<b64 pubkey>!<power>") for node `name` via RPC."""
        import base64 as _b64

        rn = self.nodes[name]
        pub = rn.sk.pub_key().bytes()
        tx = b"val:" + _b64.b64encode(pub) + b"!" + str(power).encode()
        self._live()[0].rpc.broadcast_tx_sync(tx)

    def check_validator_rotation(self, name: str, power: int, timeout: float = 30.0) -> None:
        """After rotate_validator_power, every live node's validator set
        reflects the new power."""
        import time as _t

        rn = self.nodes[name]
        addr = rn.sk.pub_key().address().hex().upper()
        deadline = _t.time() + timeout
        while _t.time() < deadline:
            tip = int(rn.rpc.status()["sync_info"]["latest_block_height"])
            vals = rn.rpc.validators(tip)
            for v in vals["validators"]:
                if v["address"] == addr and int(v["voting_power"]) == power:
                    return
            _t.sleep(0.3)
        raise AssertionError(f"validator {name} never rotated to power {power}")

    def benchmark(self) -> dict:
        """runner/benchmark.go:15-67: block interval stats."""
        rn = self._live()[0]
        st = rn.rpc.status()
        last = int(st["sync_info"]["latest_block_height"])
        times = []
        for h in range(self.manifest.initial_height, last + 1):
            blk = rn.rpc.block(h)
            t = blk["block"]["header"]["time"]
            times.append(t)
        from tendermint_tpu.types.genesis import _time_from_rfc3339

        secs = [
            _time_from_rfc3339(t).seconds + _time_from_rfc3339(t).nanos / 1e9
            for t in times
        ]
        intervals = [b - a for a, b in zip(secs, secs[1:])] or [0.0]
        return {
            "blocks": last,
            "avg_interval": sum(intervals) / len(intervals),
            "min_interval": min(intervals),
            "max_interval": max(intervals),
        }
