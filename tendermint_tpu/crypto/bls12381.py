"""BLS12-381 minimal-pubkey signatures (pure Python).

The scheme-diversity aggregation lane (ISSUE 20 / ROADMAP item 3b):
pubkeys live in G1 (48-byte compressed), signatures in G2 (96 bytes), so
an aggregated commit ships ONE signature + a signer bitmap and verifies
with a single pairing check

    e(apk, H(m)) == e(g1, sigma),   apk = sum of the signers' pubkeys

("Performance of EdDSA and BLS Signatures in Committee-Based Consensus",
arxiv 2302.00418). This module is the reference oracle: the device lane
(ops/bls_verify.py) is differential-tested against it bit-for-bit, and it
is the small-batch / purepy fallback exactly like crypto._weierstrass is
for secp256k1.

No external library — the container has no BLS wheel, and the tier-1
suite runs TM_TPU_PUREPY_CRYPTO anyway. Everything here is int/tuple
arithmetic:

  - Fp is plain ints mod P; Fp2 = Fp[u]/(u^2+1) as (c0, c1) tuples.
  - Curve points are AFFINE tuples, None = infinity. Scalar muls pay a
    field inversion per step (~5us via pow(x, P-2, P)) — milliseconds
    per op, which is the right trade for an oracle.
  - Compression is the ZCash format (bit7 compressed, bit6 infinity,
    bit5 lexicographically-larger y; G2 serializes c1 || c0).
  - hash-to-G2 is try-and-increment + cofactor clearing by h_eff
    (RFC 9380 8.8.2), NOT the SSWU ciphersuite: interop parity is only
    against this repo's own device lane, and try-and-increment keeps the
    oracle dependency-free. The DST is correspondingly custom.
  - The pairing uses the flat tower Fp12 = Fp2[w]/(w^6 - XI), XI = 1+u
    (no Fp6 intermediate — mirrors what the device kernel evaluates),
    and a BRUTE-FORCE final exponentiation f^((P^12-1)//R). No Fp12
    inversion or Frobenius anywhere; the structured final exp is an
    optimization the device lane can pick up later (ROADMAP item 3).

Line-coefficient prep (`g2_prepare`) is shared with the device kernel:
the host runs the ate loop over the G2 input once, emitting a UNIFORM
(63 steps x [dbl, add]) schedule of (lambda, c) Fp2 pairs — non-add
steps carry (0, 0), whose "line" degenerates to the Fp2 scalar XI*yP
that the final exponentiation kills. The device then only evaluates and
accumulates; oracle and kernel walk the same coefficients.
"""

from __future__ import annotations

import functools
import hashlib
import os

from . import PrivKey as _PrivKey, PubKey as _PubKey, address_hash, register_key_type

KEY_TYPE = "bls12381"
PUB_KEY_SIZE = 48
PRIV_KEY_SIZE = 32
SIGNATURE_LENGTH = 96

PUB_KEY_NAME = "tendermint/PubKeyBls12381"
PRIV_KEY_NAME = "tendermint/PrivKeyBls12381"

# Base field prime (381 bits) and the prime subgroup order r (255 bits).
P = 0x1A0111EA397FE69A4B1BA7B6434BACD764774B84F38512BF6730D2A0F6B0F6241EABFFFEB153FFFFB9FEFFFFFFFFAAAB
R = 0x73EDA753299D7D483339D80809A1D80553BDA402FFFE5BFEFFFFFFFF00000001

# The BLS parameter x (negative): p = (x-1)^2 (x^4 - x^2 + 1)/3 + x.
X_ABS = 0xD201000000010000
X_NEG = True  # x < 0: the ate Miller value is conjugated at the end

# E: y^2 = x^3 + 4 over Fp; E': y^2 = x^3 + 4*XI over Fp2, XI = 1 + u
# (M-twist). B3 = 3*b = 12 is the RCB complete-formula constant the
# device G1 adder uses.
B = 4
B3 = 12

GX = 0x17F1D3A73197D7942695638C4FA9AC0FC3688C4F9774B905A14E3A3F171BAC586C55E83FF97A1AEFFB3AF00ADB22C6BB
GY = 0x08B3F481E3AAA0F1A09E30ED741D8AE4FCF5E095D5D00AF600DB18CB2C04B3EDD03CC744A2888AE40CAA232946C5E7E1
G1_GEN = (GX, GY)

G2_GEN = (
    (
        0x024AA2B2F08F0A91260805272DC51051C6E47AD4FA403B02B4510B647AE3D1770BAC0326A805BBEFD48056C8C121BDB8,
        0x13E02B6052719F607DACD3A088274F65596BD0D09920B61AB5DA61BBDC7F5049334CF11213945D57E5AC7D055D042B7E,
    ),
    (
        0x0CE5D527727D6E118CC9CDC6DA2E351AADFD9BAA8CBDD3A76D429A695160D12C923AC9CC3BACA289E193548608B82801,
        0x0606C4A02EA734CC32ACD2B02BC28B99CB3E287E85A763AF267492AB572E99AB3F370D275CEC1DA1AAA9075FF05F79BE,
    ),
)

# G2 cofactor-clearing exponent h_eff (RFC 9380 8.8.2).
H_EFF = 0xBC69F08F2EE75B3584C6A0EA91B352888E2A8E9145AD7689986FF031508FFE1329C2F178731DB956D82BF015D1212B02EC0EC69D7477C1AE954CBC06689F6A359894C0ADEBBF6B4E8020005AAA95551

# Custom domain separator — see the module docstring on hash-to-G2.
DST = b"TM_TPU_BLS12381G2_HAI_POP_"

# Full final-exponentiation exponent (brute force; ~4313 bits).
FINAL_EXP = (P**12 - 1) // R

_INV2 = pow(2, P - 2, P)
_SQRT_EXP = (P + 1) // 4  # p = 3 mod 4

# ---- Fp2 = Fp[u]/(u^2 + 1) --------------------------------------------------

F2_ZERO = (0, 0)
F2_ONE = (1, 0)
XI = (1, 1)  # 1 + u


def f2_add(a, b):
    return ((a[0] + b[0]) % P, (a[1] + b[1]) % P)


def f2_sub(a, b):
    return ((a[0] - b[0]) % P, (a[1] - b[1]) % P)


def f2_neg(a):
    return (-a[0] % P, -a[1] % P)


def f2_mul(a, b):
    t0 = a[0] * b[0]
    t1 = a[1] * b[1]
    t2 = (a[0] + a[1]) * (b[0] + b[1])
    return ((t0 - t1) % P, (t2 - t0 - t1) % P)


def f2_sqr(a):
    return f2_mul(a, a)


def f2_scalar(a, k):
    return (a[0] * k % P, a[1] * k % P)


def f2_inv(a):
    norm = (a[0] * a[0] + a[1] * a[1]) % P
    ni = pow(norm, P - 2, P)
    return (a[0] * ni % P, -a[1] * ni % P)


def f2_mul_xi(a):
    """(1+u)*(c0 + c1 u) = (c0 - c1) + (c0 + c1) u."""
    return ((a[0] - a[1]) % P, (a[0] + a[1]) % P)


def fp_sqrt(a):
    """Square root in Fp (p = 3 mod 4), or None."""
    s = pow(a, _SQRT_EXP, P)
    return s if s * s % P == a % P else None


def f2_sqrt(a):
    """Square root in Fp2 via the norm method, or None."""
    a0, a1 = a[0] % P, a[1] % P
    if a1 == 0:
        s = fp_sqrt(a0)
        if s is not None:
            return (s, 0)
        # -1 is a non-residue (p = 3 mod 4): -a0 must be square, and
        # (y*u)^2 = -y^2 = a0.
        s = fp_sqrt(-a0 % P)
        return None if s is None else (0, s)
    s = fp_sqrt((a0 * a0 + a1 * a1) % P)
    if s is None:
        return None
    for cand in (s, -s % P):
        t = (a0 + cand) * _INV2 % P
        x0 = fp_sqrt(t)
        if x0 is None or x0 == 0:
            continue
        x1 = a1 * pow(2 * x0, P - 2, P) % P
        if (x0 * x0 - x1 * x1) % P == a0 and 2 * x0 * x1 % P == a1:
            return (x0, x1)
    return None


# ---- curve arithmetic (affine, None = infinity) -----------------------------


def g1_add(p1, p2):
    if p1 is None:
        return p2
    if p2 is None:
        return p1
    x1, y1 = p1
    x2, y2 = p2
    if x1 == x2:
        if (y1 + y2) % P == 0:
            return None
        lam = 3 * x1 * x1 * pow(2 * y1, P - 2, P) % P
    else:
        lam = (y2 - y1) * pow(x2 - x1, P - 2, P) % P
    x3 = (lam * lam - x1 - x2) % P
    return (x3, (lam * (x1 - x3) - y1) % P)


def g1_neg(p1):
    return None if p1 is None else (p1[0], -p1[1] % P)


def g1_mul(k, p1):
    # No k % R reduction: the subgroup checks multiply by R itself, and
    # reducing first would turn them into `[0]P is None` — vacuously
    # true for EVERY on-curve point (reduction is only sound once p1 is
    # already known to have order R).
    acc = None
    for bit in bin(k)[2:]:
        acc = g1_add(acc, acc)
        if bit == "1":
            acc = g1_add(acc, p1)
    return acc


def g2_add(p1, p2):
    if p1 is None:
        return p2
    if p2 is None:
        return p1
    x1, y1 = p1
    x2, y2 = p2
    if x1 == x2:
        if f2_add(y1, y2) == F2_ZERO:
            return None
        lam = f2_mul(f2_scalar(f2_sqr(x1), 3), f2_inv(f2_scalar(y1, 2)))
    else:
        lam = f2_mul(f2_sub(y2, y1), f2_inv(f2_sub(x2, x1)))
    x3 = f2_sub(f2_sub(f2_sqr(lam), x1), x2)
    return (x3, f2_sub(f2_mul(lam, f2_sub(x1, x3)), y1))


def g2_neg(p1):
    return None if p1 is None else (p1[0], f2_neg(p1[1]))


def g2_mul(k, p1):
    acc = None
    for bit in bin(k)[2:]:
        acc = g2_add(acc, acc)
        if bit == "1":
            acc = g2_add(acc, p1)
    return acc


def g1_on_curve(p1):
    if p1 is None:
        return True
    x, y = p1
    return y * y % P == (x * x * x + B) % P


def g2_on_curve(p1):
    if p1 is None:
        return True
    x, y = p1
    return f2_sqr(y) == f2_add(f2_mul(x, f2_sqr(x)), f2_scalar(XI, B))


def g1_in_subgroup(p1):
    return g1_on_curve(p1) and g1_mul(R, p1) is None


def g2_in_subgroup(p1):
    return g2_on_curve(p1) and g2_mul(R, p1) is None


# ---- serialization (ZCash compressed format) --------------------------------

_HALF = (P - 1) // 2


def _fp_larger(y):
    return y > _HALF


def _f2_larger(y):
    return _fp_larger(y[1]) if y[1] else _fp_larger(y[0])


def g1_compress(p1) -> bytes:
    if p1 is None:
        return bytes([0xC0]) + bytes(47)
    buf = bytearray(p1[0].to_bytes(48, "big"))
    buf[0] |= 0x80 | (0x20 if _fp_larger(p1[1]) else 0)
    return bytes(buf)


def g1_decompress(data: bytes):
    """48-byte compressed G1 -> affine point / None (infinity), or the
    string "bad" on any malformed encoding (so callers can pin blame
    without exceptions)."""
    if len(data) != PUB_KEY_SIZE or not data[0] & 0x80:
        return "bad"
    if data[0] & 0x40:
        if data[0] != 0xC0 or any(data[1:]):
            return "bad"
        return None
    x = int.from_bytes(bytes([data[0] & 0x1F]) + data[1:], "big")
    if x >= P:
        return "bad"
    y = fp_sqrt((x * x * x + B) % P)
    if y is None:
        return "bad"
    if _fp_larger(y) != bool(data[0] & 0x20):
        y = -y % P
    return (x, y)


def g2_compress(p1) -> bytes:
    if p1 is None:
        return bytes([0xC0]) + bytes(95)
    x, y = p1
    buf = bytearray(x[1].to_bytes(48, "big") + x[0].to_bytes(48, "big"))
    buf[0] |= 0x80 | (0x20 if _f2_larger(y) else 0)
    return bytes(buf)


def g2_decompress(data: bytes):
    """96-byte compressed G2 -> affine point / None / "bad" (see
    g1_decompress)."""
    if len(data) != SIGNATURE_LENGTH or not data[0] & 0x80:
        return "bad"
    if data[0] & 0x40:
        if data[0] != 0xC0 or any(data[1:]):
            return "bad"
        return None
    x1 = int.from_bytes(bytes([data[0] & 0x1F]) + data[1:48], "big")
    x0 = int.from_bytes(data[48:], "big")
    if x0 >= P or x1 >= P:
        return "bad"
    x = (x0, x1)
    y = f2_sqrt(f2_add(f2_mul(x, f2_sqr(x)), f2_scalar(XI, B)))
    if y is None:
        return "bad"
    if _f2_larger(y) != bool(data[0] & 0x20):
        y = f2_neg(y)
    return (x, y)


# ---- hash to G2 (try-and-increment + cofactor clearing) ---------------------


def _hash_fp(tag: int, ctr: int, msg: bytes) -> int:
    pre = DST + bytes([tag]) + ctr.to_bytes(4, "big")
    h = hashlib.sha256(pre + b"\x00" + msg).digest()
    h += hashlib.sha256(pre + b"\x01" + msg).digest()
    return int.from_bytes(h, "big") % P


@functools.lru_cache(maxsize=4096)
def hash_to_g2(msg: bytes):
    """Deterministic msg -> G2 subgroup point (never None for real
    inputs: a failure probability of ~2^-255 per candidate)."""
    ctr = 0
    while True:
        x = (_hash_fp(0, ctr, msg), _hash_fp(1, ctr, msg))
        y = f2_sqrt(f2_add(f2_mul(x, f2_sqr(x)), f2_scalar(XI, B)))
        ctr += 1
        if y is None:
            continue
        sign = hashlib.sha256(DST + b"\x02" + msg).digest()[0] & 1
        if _f2_larger(y) != bool(sign):
            y = f2_neg(y)
        q = g2_mul(H_EFF, (x, y))
        if q is not None:
            return q


# ---- pairing ----------------------------------------------------------------
#
# Ate loop schedule: 63 uniform steps, MSB-first over bits 62..0 of |x|
# (bit 63 seeds T = Q). Every step doubles; steps whose bit is set also
# add. The stored row is ((lam_dbl, c_dbl), (lam_add, c_add)) with
# c = lam*x_T - y_T, and (0, 0) for the skipped add — the line then
# degenerates to the unit Fp2 scalar XI*yP (killed by the final exp), so
# oracle and device share one flag-free schedule.

ATE_BITS = tuple((X_ABS >> i) & 1 for i in range(62, -1, -1))
N_ATE = len(ATE_BITS)  # 63


def g2_prepare(q):
    """Ate-loop line coefficients for a G2 point: N_ATE rows of
    ((lam, c)_dbl, (lam, c)_add), each an Fp2 pair."""
    rows = []
    t = q
    for bit in ATE_BITS:
        xt, yt = t
        lam_d = f2_mul(f2_scalar(f2_sqr(xt), 3), f2_inv(f2_scalar(yt, 2)))
        c_d = f2_sub(f2_mul(lam_d, xt), yt)
        t = _g2_add_with_slope(t, t, lam_d)
        if bit:
            xt, yt = t
            lam_a = f2_mul(f2_sub(yt, q[1]), f2_inv(f2_sub(xt, q[0])))
            c_a = f2_sub(f2_mul(lam_a, xt), yt)
            t = _g2_add_with_slope(t, q, lam_a)
        else:
            lam_a, c_a = F2_ZERO, F2_ZERO
        rows.append(((lam_d, c_d), (lam_a, c_a)))
    return rows


def _g2_add_with_slope(p1, p2, lam):
    x3 = f2_sub(f2_sub(f2_sqr(lam), p1[0]), p2[0])
    return (x3, f2_sub(f2_mul(lam, f2_sub(p1[0], x3)), p1[1]))


# Fp12 = Fp2[w]/(w^6 - XI), flat: tuples of 6 Fp2 coefficients. The
# untwist is x = x'*w^4/XI, y = y'*w^3/XI, so a line with twist-side
# slope lam through T evaluated at P = (xP, yP) in G1, scaled by the
# final-exp-killed XI, is   XI*yP + c*w^3 - lam*xP*w^5.

FP12_ONE = (F2_ONE, F2_ZERO, F2_ZERO, F2_ZERO, F2_ZERO, F2_ZERO)


def fp12_mul(a, b):
    acc = [F2_ZERO] * 11
    for i in range(6):
        ai = a[i]
        if ai == F2_ZERO:
            continue
        for j in range(6):
            acc[i + j] = f2_add(acc[i + j], f2_mul(ai, b[j]))
    return tuple(
        f2_add(acc[k], f2_mul_xi(acc[k + 6])) if k < 5 else acc[k]
        for k in range(6)
    )


def fp12_conj(a):
    """a^(p^6): w -> -w (negate odd coefficients)."""
    return (a[0], f2_neg(a[1]), a[2], f2_neg(a[3]), a[4], f2_neg(a[5]))


def line_eval(lam, c, xp, yp):
    """The (sparse) Fp12 line value at the G1 point (xp, yp)."""
    return (
        f2_scalar(XI, yp),
        F2_ZERO,
        F2_ZERO,
        c,
        F2_ZERO,
        f2_scalar(lam, -xp % P),
    )


def miller(coeffs, p1):
    """Miller loop: evaluate prepared line coefficients at the G1 point.
    Conjugated at the end for the negative BLS parameter (conj differs
    from the true inverse by an element the final exp kills)."""
    xp, yp = p1
    f = FP12_ONE
    for (lam_d, c_d), (lam_a, c_a) in coeffs:
        f = fp12_mul(f, f)
        f = fp12_mul(f, line_eval(lam_d, c_d, xp, yp))
        f = fp12_mul(f, line_eval(lam_a, c_a, xp, yp))
    return fp12_conj(f) if X_NEG else f


def final_exp(f):
    """f^((p^12-1)/r) by square-and-multiply (see module docstring)."""
    acc = FP12_ONE
    for bit in bin(FINAL_EXP)[2:]:
        acc = fp12_mul(acc, acc)
        if bit == "1":
            acc = fp12_mul(acc, f)
    return acc


def pairing_product_is_one(pairs) -> bool:
    """prod e(P_i, Q_i) == 1 for affine pairs [(G1 pt, G2 pt), ...]:
    one Miller loop per pair, ONE shared final exponentiation."""
    f = FP12_ONE
    for p1, q2 in pairs:
        f = fp12_mul(f, miller(g2_prepare(q2), p1))
    return final_exp(f) == FP12_ONE


# ---- signatures -------------------------------------------------------------


@functools.lru_cache(maxsize=65536)
def pubkey_status(pub: bytes):
    """(point, reason): reason is None for a usable pubkey, else the
    pinned blame suffix ("malformed" / "identity" / "subgroup"). Memoized
    — the per-epoch subgroup check amortizes to zero on the hot path."""
    pt = g1_decompress(bytes(pub))
    if pt == "bad":
        return None, "malformed"
    if pt is None:
        return None, "identity"
    if not g1_mul(R, pt) is None:
        return None, "subgroup"
    return pt, None


@functools.lru_cache(maxsize=4096)
def signature_status(sig: bytes):
    """(point, reason) for a 96-byte aggregate signature (same protocol
    as pubkey_status)."""
    pt = g2_decompress(bytes(sig))
    if pt == "bad":
        return None, "malformed"
    if pt is None:
        return None, "identity"
    if not g2_mul(R, pt) is None:
        return None, "subgroup"
    return pt, None


def verify(pub: bytes, msg: bytes, sig: bytes) -> bool:
    pk, reason = pubkey_status(bytes(pub))
    if reason is not None:
        return False
    s, reason = signature_status(bytes(sig))
    if reason is not None:
        return False
    return pairing_product_is_one(
        [(pk, hash_to_g2(bytes(msg))), (g1_neg(G1_GEN), s)]
    )


def aggregate(sigs) -> bytes:
    """Aggregate signatures (sum in G2). Raises on malformed input —
    aggregation is a proposer-side op, not a verify path."""
    acc = None
    for sig in sigs:
        pt = g2_decompress(bytes(sig))
        if pt == "bad":
            raise ValueError("malformed bls12381 signature")
        acc = g2_add(acc, pt)
    return g2_compress(acc)


def aggregate_pubkeys(pubs):
    """Affine apk over decompressed pubkeys, or (None, reason-index)."""
    acc = None
    for i, pub in enumerate(pubs):
        pt, reason = pubkey_status(bytes(pub))
        if reason is not None:
            return None, i
        acc = g1_add(acc, pt)
    return acc, None


def fast_aggregate_verify(pubs, msg: bytes, sig: bytes) -> bool:
    """All signers signed the SAME message: one pairing check against
    the aggregate pubkey. False (never raises) on any malformed input,
    identity/subgroup violations, or an infinity apk."""
    s, reason = signature_status(bytes(sig))
    if reason is not None:
        return False
    apk, bad = aggregate_pubkeys(pubs)
    if bad is not None or apk is None:
        return False
    return pairing_product_is_one(
        [(apk, hash_to_g2(bytes(msg))), (g1_neg(G1_GEN), s)]
    )


# Host-side batch helper: thread-pooled like the secp host loop
# (ops/mixed.py) for API parity, but NOTE the oracle is GIL-held Python
# bignum math, so the pool only helps under free-threaded builds — the
# device lane is the real batch path and single commits stay cheap.
BLS_HOST_POOL_MIN = int(os.environ.get("TM_TPU_BLS_HOST_POOL_MIN", "4"))


def _bls_host_workers() -> int:
    w = os.environ.get("TM_TPU_BLS_HOST_WORKERS")
    if w:
        return max(1, int(w))
    return min(4, os.cpu_count() or 1)


def fast_aggregate_verify_batch(items):
    """[(pubs, msg, sig), ...] -> list of bools via the pool policy."""
    items = list(items)
    workers = _bls_host_workers()
    if len(items) < BLS_HOST_POOL_MIN or workers <= 1:
        return [fast_aggregate_verify(*it) for it in items]
    from concurrent.futures import ThreadPoolExecutor

    with ThreadPoolExecutor(max_workers=workers) as pool:
        return list(pool.map(lambda it: fast_aggregate_verify(*it), items))


class PubKey(_PubKey):
    __slots__ = ("_bytes",)

    def __init__(self, data: bytes):
        if len(data) != PUB_KEY_SIZE:
            raise ValueError(f"bls12381 pubkey must be {PUB_KEY_SIZE} bytes")
        self._bytes = bytes(data)

    def address(self) -> bytes:
        return address_hash(self._bytes)

    def bytes(self) -> bytes:
        return self._bytes

    def verify_signature(self, msg: bytes, sig: bytes) -> bool:
        if len(sig) != SIGNATURE_LENGTH:
            return False
        return verify(self._bytes, msg, sig)

    def type(self) -> str:
        return KEY_TYPE


class PrivKey(_PrivKey):
    __slots__ = ("_bytes", "_d")

    def __init__(self, data: bytes):
        if len(data) != PRIV_KEY_SIZE:
            raise ValueError(f"bls12381 privkey must be {PRIV_KEY_SIZE} bytes")
        self._bytes = bytes(data)
        self._d = int.from_bytes(data, "big") % R
        if self._d == 0:
            raise ValueError("invalid bls12381 scalar")

    def sign(self, msg: bytes) -> bytes:
        return g2_compress(g2_mul(self._d, hash_to_g2(bytes(msg))))

    def pub_key(self) -> PubKey:
        return PubKey(g1_compress(g1_mul(self._d, G1_GEN)))

    def bytes(self) -> bytes:
        return self._bytes

    def type(self) -> str:
        return KEY_TYPE


def gen_priv_key() -> PrivKey:
    while True:
        cand = os.urandom(PRIV_KEY_SIZE)
        if int.from_bytes(cand, "big") % R:
            return PrivKey(cand)


register_key_type(KEY_TYPE, PubKey, PUB_KEY_SIZE)

# Generator sanity (cheap; the subgroup checks below are a few ms and
# gate the whole lane's correctness, so they run once per process).
assert g1_on_curve(G1_GEN) and g2_on_curve(G2_GEN)
