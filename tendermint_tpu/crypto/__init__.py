"""Crypto abstractions: keys, signatures, batch verification.

Reference parity: crypto/crypto.go:22-54 — PubKey / PrivKey / BatchVerifier
interfaces, Address = SHA256(pubkey)[:20]. Implementations register
themselves in KEY_TYPES so proto codecs and JSON can round-trip key types
(the reference does this with libs/json type registry + crypto/encoding).
"""

from __future__ import annotations

import abc
import os
from typing import Dict, List, Tuple, Type

from . import tmhash

ADDRESS_SIZE = tmhash.TRUNCATED_SIZE


def address_hash(data: bytes) -> bytes:
    """Address of raw key bytes: first 20 bytes of SHA-256 (crypto/crypto.go:8-20)."""
    return tmhash.sum_truncated(data)


class PubKey(abc.ABC):
    @abc.abstractmethod
    def address(self) -> bytes: ...

    @abc.abstractmethod
    def bytes(self) -> bytes: ...

    @abc.abstractmethod
    def verify_signature(self, msg: bytes, sig: bytes) -> bool: ...

    @abc.abstractmethod
    def type(self) -> str: ...

    def __eq__(self, other) -> bool:
        return (
            isinstance(other, PubKey)
            and self.type() == other.type()
            and self.bytes() == other.bytes()
        )

    def __hash__(self):
        return hash((self.type(), self.bytes()))

    def __repr__(self):
        return f"PubKey{self.type().capitalize()}{{{self.bytes().hex().upper()}}}"


class PrivKey(abc.ABC):
    @abc.abstractmethod
    def sign(self, msg: bytes) -> bytes: ...

    @abc.abstractmethod
    def pub_key(self) -> PubKey: ...

    @abc.abstractmethod
    def bytes(self) -> bytes: ...

    @abc.abstractmethod
    def type(self) -> str: ...


class BatchVerifier(abc.ABC):
    """Accumulate (pubkey, msg, sig) triples, verify all at once.

    Reference parity: crypto/crypto.go:44-54. verify() returns
    (all_valid, per_entry_validity) like curve25519-voi's BatchVerifier.
    """

    @abc.abstractmethod
    def add(self, key: PubKey, msg: bytes, sig: bytes) -> None: ...

    @abc.abstractmethod
    def verify(self) -> Tuple[bool, List[bool]]: ...


# key-type registry: type name -> (PubKey class, pubkey byte size)
KEY_TYPES: Dict[str, Tuple[Type[PubKey], int]] = {}


def register_key_type(name: str, pubkey_cls: Type[PubKey], size: int) -> None:
    KEY_TYPES[name] = (pubkey_cls, size)


def c_reader_random(n: int) -> bytes:
    """Cryptographic randomness (crypto/random.go CReader)."""
    return os.urandom(n)
