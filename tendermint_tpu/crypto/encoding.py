"""PubKey ⇄ proto conversion.

Reference parity: crypto/encoding/codec.go (PubKeyToProto/PubKeyFromProto)
and proto/tendermint/crypto/keys.proto — PublicKey is a oneof:
  1 ed25519 (bytes) | 2 secp256k1 (bytes) | 3 sr25519 (bytes)
"""

from __future__ import annotations

from ..wire.proto import ProtoWriter, decode_message, field_bytes
from . import PubKey
from . import ed25519 as _ed25519
from . import secp256k1 as _secp256k1
from . import sr25519 as _sr25519
from . import bls12381 as _bls12381

_FIELD_ED25519 = 1
_FIELD_SECP256K1 = 2
_FIELD_SR25519 = 3
# local extension (ISSUE 20): upstream keys.proto stops at sr25519; the
# aggregation lane's min-pubkey BLS keys ride the next oneof slot
_FIELD_BLS12381 = 4


def pubkey_to_proto(pk: PubKey) -> bytes:
    """Encode a PubKey as a tendermint.crypto.PublicKey message."""
    w = ProtoWriter()
    t = pk.type()
    if t == _ed25519.KEY_TYPE:
        w.write_bytes(_FIELD_ED25519, pk.bytes(), always=True)
    elif t == _secp256k1.KEY_TYPE:
        w.write_bytes(_FIELD_SECP256K1, pk.bytes(), always=True)
    elif t == _sr25519.KEY_TYPE:
        w.write_bytes(_FIELD_SR25519, pk.bytes(), always=True)
    elif t == _bls12381.KEY_TYPE:
        w.write_bytes(_FIELD_BLS12381, pk.bytes(), always=True)
    else:
        raise ValueError(f"unsupported key type {t}")
    return w.bytes()


def pubkey_from_proto(data: bytes) -> PubKey:
    fields = decode_message(data)
    if _FIELD_ED25519 in fields:
        return _ed25519.PubKey(field_bytes(fields, _FIELD_ED25519))
    if _FIELD_SECP256K1 in fields:
        return _secp256k1.PubKey(field_bytes(fields, _FIELD_SECP256K1))
    if _FIELD_SR25519 in fields:
        return _sr25519.PubKey(field_bytes(fields, _FIELD_SR25519))
    if _FIELD_BLS12381 in fields:
        return _bls12381.PubKey(field_bytes(fields, _FIELD_BLS12381))
    raise ValueError("unknown or empty PublicKey oneof")
