"""Batch-verifier dispatch — the seam where the TPU engine plugs in.

Reference parity: crypto/batch/batch.go:11-33 — CreateBatchVerifier /
SupportsBatchVerifier keyed on pubkey type; ed25519 and sr25519 batch,
secp256k1 does not.

The default ed25519 batch verifier here is the device-backed one from
tendermint_tpu.ops (JAX: TPU when available, CPU otherwise). Its semantics
are *per-signature* cofactored ZIP-215 verification evaluated in a single
fixed-shape vmapped kernel — deterministic, and exactly equal to the
reference's single-verify semantics (the reference's random-linear-
combination batch accepts the same set except with negligible probability;
on failure it too falls back to per-signature checks, ed25519.go:225-227).
"""

from __future__ import annotations

from typing import List, Optional, Tuple

from . import BatchVerifier, PubKey
from . import ed25519 as _ed25519
from . import _edwards


class Ed25519HostBatchVerifier(BatchVerifier):
    """Host-only fallback: per-signature ZIP-215 via the OpenSSL fast path."""

    def __init__(self):
        self._entries: List[Tuple[bytes, bytes, bytes]] = []

    def add(self, key: PubKey, msg: bytes, sig: bytes) -> None:
        if not isinstance(key, _ed25519.PubKey):
            raise TypeError("pubkey is not ed25519")
        if len(sig) != _ed25519.SIGNATURE_SIZE:
            raise ValueError("invalid signature length")
        self._entries.append((key.bytes(), msg, sig))

    def add_entries(self, entries, lengths_checked: bool = False) -> None:
        """Bulk add() — one pass. The key-type check always runs (a mixed
        validator set must fail like per-entry add); lengths_checked=True
        skips only the length scan for callers that already did it."""
        if any(not isinstance(k, _ed25519.PubKey) for k, _, _ in entries):
            raise TypeError("pubkey is not ed25519")
        if not lengths_checked and any(
            len(s) != _ed25519.SIGNATURE_SIZE for _, _, s in entries
        ):
            raise ValueError("invalid signature length")
        self._entries.extend((k.bytes(), m, s) for k, m, s in entries)

    def add_block(self, block, keys=None) -> None:
        """Columnar bulk add (ops.entry_block.EntryBlock). The host
        verifier is the no-device fallback, so the block is expanded to
        tuples here; the device verifier keeps it by reference. `keys`
        runs the same per-key TYPE check as add()/add_entries; lengths
        are structural in the block's (n, 32)/(n, 64) shape."""
        if keys is not None and any(
            not isinstance(k, _ed25519.PubKey) for k in keys
        ):
            raise TypeError("pubkey is not ed25519")
        self._entries.extend(block.iter_entries())

    def verify(self) -> Tuple[bool, List[bool]]:
        # Random-linear-combination batch first when the native module is
        # built (one Pippenger MSM — crypto/ed25519/ed25519.go:219-227
        # semantics), falling back to per-signature checks for blame
        # assignment exactly like the reference (:225-227).
        n = len(self._entries)
        if n >= 16:
            from ..native import load as _load_native

            native = _load_native()
            if native is not None and hasattr(native, "ed25519_batch_verify"):
                ok = native.ed25519_batch_verify(
                    b"".join(p for p, _, _ in self._entries),
                    b"".join(s for _, _, s in self._entries),
                    [m for _, m, _ in self._entries],
                )
                if ok:
                    return True, [True] * n
        valid = [
            _ed25519.verify_zip215_fast(pub, msg, sig) for pub, msg, sig in self._entries
        ]
        return all(valid) and len(valid) > 0, valid


_device_verifier_factory = None


def use_device_engine(factory) -> None:
    """Install the device (TPU) batch-verifier factory. Called by
    tendermint_tpu.ops on import; kept injectable for tests."""
    global _device_verifier_factory
    _device_verifier_factory = factory


def create_batch_verifier(pk: PubKey) -> Optional[BatchVerifier]:
    """crypto/batch/batch.go:11-24. Returns None if unsupported."""
    if pk.type() == _ed25519.KEY_TYPE:
        if _device_verifier_factory is not None:
            return _device_verifier_factory()
        return Ed25519HostBatchVerifier()
    from . import sr25519 as _sr25519

    if pk.type() == _sr25519.KEY_TYPE:
        from ..ops.mixed import Sr25519DeviceBatchVerifier

        return Sr25519DeviceBatchVerifier()
    # secp256k1 has no batch VERIFIER (batch.go:26-33) and must stay
    # None here: _verify_commit_batch's add_block path is ed25519-shaped
    # and would choke on 33-byte keys. Batched secp verification exists
    # anyway (ISSUE 19) — it routes through the scheme lanes instead:
    # types/validation.prepare_commit_batch (all-secp committees),
    # prepare_commit_scheme_split + the mesh packer (mixed committees),
    # and ops.mixed.Secp256k1DeviceBatchVerifier for explicit opt-in.
    return None


def supports_batch_verifier(pk: Optional[PubKey]) -> bool:
    """crypto/batch/batch.go:26-33."""
    if pk is None:
        return False
    from . import sr25519 as _sr25519

    return pk.type() in (_ed25519.KEY_TYPE, _sr25519.KEY_TYPE)
