"""ristretto255 group on edwards25519 — sr25519's curve group.

Pure-Python oracle (like _edwards for ed25519): decode/encode per the
ristretto255 spec (draft-irtf-cfrg-ristretto255), arithmetic reuses the
extended-coordinate point ops from _edwards.
"""

from __future__ import annotations

from typing import Optional, Tuple

from . import _edwards as E

P = E.P
D = E.D
SQRT_M1 = E.SQRT_M1

# 1/sqrt(a - d) with a = -1 (ristretto encode constant)
INVSQRT_A_MINUS_D = 0

Point = Tuple[int, int, int, int]


def _is_negative(x: int) -> bool:
    return (x % P) & 1 == 1


def _invsqrt(u: int) -> Tuple[bool, int]:
    """(was_square, 1/sqrt(u)); for u=0 returns (True, 0)."""
    if u % P == 0:
        return True, 0
    r = E._sqrt_ratio(1, u)
    if r is not None:
        return True, r % P
    # not a square: sqrt(i/u)
    r = E._sqrt_ratio(SQRT_M1, u)
    return False, (r % P) if r is not None else 0


def _compute_constants():
    global INVSQRT_A_MINUS_D
    a = P - 1
    _, inv = _invsqrt((a - D) % P)
    INVSQRT_A_MINUS_D = inv


_compute_constants()


def decode(b: bytes) -> Optional[Point]:
    """ristretto255 DECODE."""
    if len(b) != 32:
        return None
    s = int.from_bytes(b, "little")
    if s >= P or _is_negative(s):
        return None
    ss = s * s % P
    u1 = (1 - ss) % P
    u2 = (1 + ss) % P
    u2_sqr = u2 * u2 % P
    v = (-(D * u1 % P * u1) - u2_sqr) % P
    ok, invsq = _invsqrt(v * u2_sqr % P)
    den_x = invsq * u2 % P
    den_y = invsq * den_x % P * v % P
    x = (s + s) % P * den_x % P
    if _is_negative(x):
        x = P - x
    y = u1 * den_y % P
    t = x * y % P
    if not ok or _is_negative(t) or y == 0:
        return None
    return (x, y, 1, t)


def encode(pt: Point) -> bytes:
    """ristretto255 ENCODE."""
    x0, y0, z0, t0 = pt
    u1 = (z0 + y0) * (z0 - y0) % P
    u2 = x0 * y0 % P
    _, invsq = _invsqrt(u1 * u2 % P * u2 % P)
    den1 = invsq * u1 % P
    den2 = invsq * u2 % P
    z_inv = den1 * den2 % P * t0 % P
    if _is_negative(t0 * z_inv % P):
        x = y0 * SQRT_M1 % P
        y = x0 * SQRT_M1 % P
        den_inv = den1 * INVSQRT_A_MINUS_D % P
    else:
        x = x0
        y = y0
        den_inv = den2
    if _is_negative(x * z_inv % P):
        y = (P - y) % P
    s = den_inv * ((z0 - y) % P) % P
    if _is_negative(s):
        s = P - s
    return s.to_bytes(32, "little")


def equals(a: Point, b: Point) -> bool:
    """Ristretto equality: x1 y2 == y1 x2 or y1 y2 == x1 x2."""
    x1, y1, _, _ = a
    x2, y2, _, _ = b
    return (x1 * y2 - y1 * x2) % P == 0 or (y1 * y2 - x1 * x2) % P == 0


BASE: Point = E.BASE
IDENTITY: Point = E.IDENTITY
add = E.point_add
neg = E.point_neg
scalar_mult = E.scalar_mult
L = E.L
