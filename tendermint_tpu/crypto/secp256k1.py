"""secp256k1 ECDSA keys.

Reference parity: crypto/secp256k1/secp256k1.go and secp256k1_nocgo.go —
  - PubKey is 33-byte compressed SEC1; Address = RIPEMD160(SHA256(pub)) (:141-153)
  - Sign: ECDSA over SHA256(msg), 64-byte R||S, lower-S form (nocgo:20-32)
  - VerifySignature rejects non-lower-S signatures (nocgo:34-54)
  - No batch VERIFIER (crypto/batch/batch.go:26-33): create_batch_verifier
    stays None for parity. Device batching exists anyway since ISSUE 19 —
    it routes through the scheme lanes (ops/secp_verify via
    prepare_commit_batch / ops.mixed), not the verifier interface, and is
    bit-identical to per-signature verification.

Backed by the `cryptography` OpenSSL binding when present; under
TM_TPU_PUREPY_CRYPTO=1 a container without the wheel runs the pure-Python
_weierstrass implementation instead (byte-identical signatures — both paths
are RFC 6979 deterministic with lower-S normalization). Lower-S and the
64-byte wire format are handled here either way.
"""

from __future__ import annotations

import hashlib
import os

try:  # OpenSSL fast path (see crypto/ed25519 for the gating rationale).
    from cryptography.hazmat.primitives.asymmetric import ec
    from cryptography.hazmat.primitives.asymmetric.utils import (
        Prehashed,
        decode_dss_signature,
        encode_dss_signature,
    )
    from cryptography.exceptions import InvalidSignature
    from cryptography.hazmat.primitives import hashes, serialization

    _HAVE_OPENSSL = True
except ModuleNotFoundError:
    if not os.environ.get("TM_TPU_PUREPY_CRYPTO"):
        raise
    _HAVE_OPENSSL = False

from . import PrivKey as _PrivKey, PubKey as _PubKey, register_key_type
from . import _weierstrass

KEY_TYPE = "secp256k1"
PUB_KEY_SIZE = 33
PRIV_KEY_SIZE = 32
SIGNATURE_LENGTH = 64

PUB_KEY_NAME = "tendermint/PubKeySecp256k1"
PRIV_KEY_NAME = "tendermint/PrivKeySecp256k1"

# Curve order of secp256k1.
_N = 0xFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFEBAAEDCE6AF48A03BBFD25E8CD0364141
_CURVE = ec.SECP256K1() if _HAVE_OPENSSL else None


def is_pure_python() -> bool:
    """True when the OpenSSL binding is absent (TM_TPU_PUREPY_CRYPTO
    fallback): per-signature verification is GIL-held Python bignum math,
    so callers skip thread pools and prefer the device lane."""
    return not _HAVE_OPENSSL


class PubKey(_PubKey):
    __slots__ = ("_bytes",)

    def __init__(self, data: bytes):
        if len(data) != PUB_KEY_SIZE:
            raise ValueError(f"secp256k1 pubkey must be {PUB_KEY_SIZE} bytes")
        self._bytes = bytes(data)

    def address(self) -> bytes:
        sha = hashlib.sha256(self._bytes).digest()
        return hashlib.new("ripemd160", sha).digest()

    def bytes(self) -> bytes:
        return self._bytes

    def verify_signature(self, msg: bytes, sig: bytes) -> bool:
        if len(sig) != SIGNATURE_LENGTH:
            return False
        r = int.from_bytes(sig[:32], "big")
        s = int.from_bytes(sig[32:], "big")
        if r <= 0 or s <= 0 or r >= _N:
            return False
        if s > _N // 2:  # reject non-lower-S (nocgo:35,41-44)
            return False
        digest = hashlib.sha256(msg).digest()
        if not _HAVE_OPENSSL:
            return _weierstrass.verify_digest(
                _weierstrass.decompress(self._bytes), digest, r, s
            )
        try:
            pub = ec.EllipticCurvePublicKey.from_encoded_point(_CURVE, self._bytes)
            pub.verify(
                encode_dss_signature(r, s),
                digest,
                ec.ECDSA(Prehashed(hashes.SHA256())),
            )
            return True
        except (InvalidSignature, ValueError):
            return False

    def type(self) -> str:
        return KEY_TYPE


class PrivKey(_PrivKey):
    __slots__ = ("_bytes", "_d", "_sk")

    def __init__(self, data: bytes):
        if len(data) != PRIV_KEY_SIZE:
            raise ValueError(f"secp256k1 privkey must be {PRIV_KEY_SIZE} bytes")
        self._bytes = bytes(data)
        self._d = int.from_bytes(data, "big")
        if not (0 < self._d < _N):
            raise ValueError("invalid secp256k1 scalar")
        self._sk = ec.derive_private_key(self._d, _CURVE) if _HAVE_OPENSSL else None

    def sign(self, msg: bytes) -> bytes:
        # RFC 6979 deterministic nonces, matching btcec (nocgo:20-32): same
        # (key, msg) must always yield the same signature bytes — on both
        # the OpenSSL and the pure-Python path.
        digest = hashlib.sha256(msg).digest()
        if self._sk is not None:
            der = self._sk.sign(
                digest,
                ec.ECDSA(Prehashed(hashes.SHA256()), deterministic_signing=True),
            )
            r, s = decode_dss_signature(der)
        else:
            r, s = _weierstrass.sign_digest(self._d, digest)
        if s > _N // 2:  # normalize to lower-S
            s = _N - s
        return r.to_bytes(32, "big") + s.to_bytes(32, "big")

    def pub_key(self) -> PubKey:
        if self._sk is not None:
            pub = self._sk.public_key().public_bytes(
                serialization.Encoding.X962,
                serialization.PublicFormat.CompressedPoint,
            )
            return PubKey(pub)
        return PubKey(
            _weierstrass.compress(_weierstrass.scalar_mult(self._d, _weierstrass.G))
        )

    def bytes(self) -> bytes:
        return self._bytes

    def type(self) -> str:
        return KEY_TYPE


def gen_priv_key() -> PrivKey:
    while True:
        cand = os.urandom(PRIV_KEY_SIZE)
        d = int.from_bytes(cand, "big")
        if 0 < d < _N:
            return PrivKey(cand)


register_key_type(KEY_TYPE, PubKey, PUB_KEY_SIZE)
