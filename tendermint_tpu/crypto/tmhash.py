"""SHA-256 hashing, full and 20-byte truncated.

Reference parity: crypto/tmhash/hash.go — Sum (32B) and SumTruncated (20B,
used for addresses: crypto/crypto.go:8-20).
"""

import hashlib

SIZE = 32
TRUNCATED_SIZE = 20
BLOCK_SIZE = 64


def sum_sha256(data: bytes) -> bytes:
    return hashlib.sha256(data).digest()


def sum_truncated(data: bytes) -> bytes:
    return hashlib.sha256(data).digest()[:TRUNCATED_SIZE]
