"""Merlin transcripts on STROBE-128 on Keccak-f[1600].

Supports the sr25519 (schnorrkel) signature scheme the reference gets from
curve25519-voi (crypto/sr25519/). Pure Python: keccak_f is the standard
24-round permutation; Strobe128 follows merlin's strobe.rs subset
(meta_ad/ad/prf/key); Transcript implements merlin's framing
(dom-sep + label + LE32 length).
"""

from __future__ import annotations

import os
from typing import List

# -- Keccak-f[1600] ---------------------------------------------------------

_ROUND_CONSTANTS = [
    0x0000000000000001, 0x0000000000008082, 0x800000000000808A, 0x8000000080008000,
    0x000000000000808B, 0x0000000080000001, 0x8000000080008081, 0x8000000000008009,
    0x000000000000008A, 0x0000000000000088, 0x0000000080008009, 0x000000008000000A,
    0x000000008000808B, 0x800000000000008B, 0x8000000000008089, 0x8000000000008003,
    0x8000000000008002, 0x8000000000000080, 0x000000000000800A, 0x800000008000000A,
    0x8000000080008081, 0x8000000000008080, 0x0000000080000001, 0x8000000080008008,
]

_ROTC = [
    [0, 36, 3, 41, 18],
    [1, 44, 10, 45, 2],
    [62, 6, 43, 15, 61],
    [28, 55, 25, 21, 56],
    [27, 20, 39, 8, 14],
]

_MASK64 = (1 << 64) - 1


def _rotl(v: int, n: int) -> int:
    n %= 64
    return ((v << n) | (v >> (64 - n))) & _MASK64


def keccak_f1600(state: bytearray) -> None:
    """In-place permutation of a 200-byte state."""
    lanes = [
        [int.from_bytes(state[8 * (x + 5 * y) : 8 * (x + 5 * y) + 8], "little") for y in range(5)]
        for x in range(5)
    ]
    for rc in _ROUND_CONSTANTS:
        # θ
        c = [lanes[x][0] ^ lanes[x][1] ^ lanes[x][2] ^ lanes[x][3] ^ lanes[x][4] for x in range(5)]
        d = [c[(x - 1) % 5] ^ _rotl(c[(x + 1) % 5], 1) for x in range(5)]
        for x in range(5):
            for y in range(5):
                lanes[x][y] ^= d[x]
        # ρ and π
        b = [[0] * 5 for _ in range(5)]
        for x in range(5):
            for y in range(5):
                b[y][(2 * x + 3 * y) % 5] = _rotl(lanes[x][y], _ROTC[x][y])
        # χ
        for x in range(5):
            for y in range(5):
                lanes[x][y] = b[x][y] ^ ((~b[(x + 1) % 5][y]) & b[(x + 2) % 5][y] & _MASK64)
        # ι
        lanes[0][0] ^= rc
    for x in range(5):
        for y in range(5):
            state[8 * (x + 5 * y) : 8 * (x + 5 * y) + 8] = lanes[x][y].to_bytes(8, "little")


# -- STROBE-128 (merlin's subset) -------------------------------------------

_R = 166  # strobe128 rate
FLAG_I = 1
FLAG_A = 1 << 1
FLAG_C = 1 << 2
FLAG_T = 1 << 3
FLAG_M = 1 << 4
FLAG_K = 1 << 5


class Strobe128:
    def __init__(self, protocol_label: bytes):
        st = bytearray(200)
        st[0:6] = bytes([1, _R + 2, 1, 0, 1, 12 * 8])
        st[6:18] = b"STROBEv1.0.2"
        keccak_f1600(st)
        self.state = st
        self.pos = 0
        self.pos_begin = 0
        self.cur_flags = 0
        self.meta_ad(protocol_label, False)

    def clone(self) -> "Strobe128":
        c = Strobe128.__new__(Strobe128)
        c.state = bytearray(self.state)
        c.pos = self.pos
        c.pos_begin = self.pos_begin
        c.cur_flags = self.cur_flags
        return c

    def _run_f(self) -> None:
        self.state[self.pos] ^= self.pos_begin
        self.state[self.pos + 1] ^= 0x04
        self.state[_R + 1] ^= 0x80
        keccak_f1600(self.state)
        self.pos = 0
        self.pos_begin = 0

    def _absorb(self, data: bytes) -> None:
        for b in data:
            self.state[self.pos] ^= b
            self.pos += 1
            if self.pos == _R:
                self._run_f()

    def _overwrite(self, data: bytes) -> None:
        for b in data:
            self.state[self.pos] = b
            self.pos += 1
            if self.pos == _R:
                self._run_f()

    def _squeeze(self, n: int) -> bytes:
        out = bytearray(n)
        for i in range(n):
            out[i] = self.state[self.pos]
            self.state[self.pos] = 0
            self.pos += 1
            if self.pos == _R:
                self._run_f()
        return bytes(out)

    def _begin_op(self, flags: int, more: bool) -> None:
        if more:
            if self.cur_flags != flags:
                raise ValueError("flag mismatch on more=True")
            return
        if flags & FLAG_T:
            raise ValueError("transport not supported")
        old_begin = self.pos_begin
        self.pos_begin = self.pos + 1
        self.cur_flags = flags
        self._absorb(bytes([old_begin, flags]))
        force_f = bool(flags & (FLAG_C | FLAG_K))
        if force_f and self.pos != 0:
            self._run_f()

    def meta_ad(self, data: bytes, more: bool) -> None:
        self._begin_op(FLAG_M | FLAG_A, more)
        self._absorb(data)

    def ad(self, data: bytes, more: bool) -> None:
        self._begin_op(FLAG_A, more)
        self._absorb(data)

    def prf(self, n: int, more: bool = False) -> bytes:
        self._begin_op(FLAG_I | FLAG_A | FLAG_C, more)
        return self._squeeze(n)

    def key(self, data: bytes, more: bool = False) -> None:
        self._begin_op(FLAG_A | FLAG_C, more)
        self._overwrite(data)


# -- Merlin transcript -------------------------------------------------------


def _le32(n: int) -> bytes:
    return n.to_bytes(4, "little")


class Transcript:
    def __init__(self, label: bytes):
        self._strobe = Strobe128(b"Merlin v1.0")
        self.append_message(b"dom-sep", label)

    def clone(self) -> "Transcript":
        t = Transcript.__new__(Transcript)
        t._strobe = self._strobe.clone()
        return t

    def append_message(self, label: bytes, message: bytes) -> None:
        self._strobe.meta_ad(label, False)
        self._strobe.meta_ad(_le32(len(message)), True)
        self._strobe.ad(message, False)

    def append_u64(self, label: bytes, n: int) -> None:
        self.append_message(label, n.to_bytes(8, "little"))

    def challenge_bytes(self, label: bytes, n: int) -> bytes:
        self._strobe.meta_ad(label, False)
        self._strobe.meta_ad(_le32(n), True)
        return self._strobe.prf(n)

    def witness_bytes(self, label: bytes, nonce_seeds: List[bytes], n: int) -> bytes:
        """TranscriptRngBuilder: rekey with witness then external rng."""
        s = self._strobe.clone()
        for seed in nonce_seeds:
            s.meta_ad(label, False)
            s.meta_ad(_le32(len(seed)), True)
            s.key(seed, False)
        s.meta_ad(b"rng", False)
        s.key(os.urandom(32), False)
        s.meta_ad(_le32(n), False)
        return s.prf(n)
