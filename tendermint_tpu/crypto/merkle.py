"""RFC-6962-style Merkle trees and proofs.

Reference parity: crypto/merkle/tree.go (HashFromByteSlices, leaf/inner
prefixes 0x00/0x01, split at largest power of two < n) and
crypto/merkle/proof.go (Proof with index/total/leaf_hash/aunts,
ProofOperator chains for multi-store proofs).
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

from .tmhash import SIZE as HASH_SIZE, sum_sha256 as _sha256

LEAF_PREFIX = b"\x00"
INNER_PREFIX = b"\x01"

MAX_AUNTS = 100  # proof.go: maxAunts


def leaf_hash(leaf: bytes) -> bytes:
    return _sha256(LEAF_PREFIX + leaf)


def inner_hash(left: bytes, right: bytes) -> bytes:
    return _sha256(INNER_PREFIX + left + right)


def split_point(length: int) -> int:
    """Largest power of 2 strictly less than length (tree.go:92-103)."""
    if length < 1:
        raise ValueError("length must be >= 1")
    bit_len = (length - 1).bit_length()
    k = 1 << (bit_len - 1) if bit_len > 0 else 1
    if k == length:
        k >>= 1
    return max(k, 1) if length > 1 else 0


def hash_from_byte_slices(items: Sequence[bytes]) -> bytes:
    """Merkle root of the list (tree.go:11-29). Empty list hashes to
    SHA256(""). Large inputs route through the native C++ engine when
    available (native/tm_native.cpp merkle_root)."""
    n = len(items)
    if n == 0:
        return _sha256(b"")
    if n >= 16:
        from ..native import load as _load_native

        native = _load_native()
        if native is not None:
            return native.merkle_root(list(items))
    if n == 1:
        return leaf_hash(items[0])
    k = split_point(n)
    left = hash_from_byte_slices(items[:k])
    right = hash_from_byte_slices(items[k:])
    return inner_hash(left, right)


class Proof:
    """Merkle inclusion proof (crypto/merkle/proof.go:23-35)."""

    __slots__ = ("total", "index", "leaf_hash", "aunts")

    def __init__(self, total: int, index: int, leaf_hash_: bytes, aunts: List[bytes]):
        self.total = total
        self.index = index
        self.leaf_hash = leaf_hash_
        self.aunts = aunts

    def validate_basic(self) -> None:
        """Stateless sanity checks on an untrusted proof (proof.go:95-116)."""
        if self.total < 0:
            raise ValueError("proof total must be positive")
        if self.index < 0:
            raise ValueError("proof index cannot be negative")
        if len(self.leaf_hash) != HASH_SIZE:
            raise ValueError(
                f"expected leaf_hash size to be {HASH_SIZE}, got {len(self.leaf_hash)}"
            )
        if len(self.aunts) > MAX_AUNTS:
            raise ValueError(f"expected no more than {MAX_AUNTS} aunts")
        for i, aunt in enumerate(self.aunts):
            if len(aunt) != HASH_SIZE:
                raise ValueError(f"expected aunt #{i} size to be {HASH_SIZE}")

    def verify(self, root_hash: bytes, leaf: bytes) -> None:
        """Raise ValueError unless this proves `leaf` at index under root
        (proof.go:59-79)."""
        self.validate_basic()
        lh = leaf_hash(leaf)
        if lh != self.leaf_hash:
            raise ValueError("invalid leaf hash")
        computed = self.compute_root_hash()
        if computed != root_hash:
            raise ValueError("invalid root hash")

    def compute_root_hash(self) -> Optional[bytes]:
        return _compute_hash_from_aunts(self.index, self.total, self.leaf_hash, self.aunts)

    def encode(self) -> bytes:
        """tendermint.crypto.Proof wire form (proto/tendermint/crypto/
        proof.pb.go): 1 total 2 index 3 leaf_hash 4 aunts(repeated)."""
        from ..wire.proto import ProtoWriter

        w = ProtoWriter()
        w.write_varint(1, self.total)
        w.write_varint(2, self.index)
        w.write_bytes(3, self.leaf_hash)
        for aunt in self.aunts:
            w.write_bytes(4, aunt, always=True)
        return w.bytes()

    @classmethod
    def decode(cls, data: bytes) -> "Proof":
        from ..wire.proto import (
            decode_message,
            field_bytes,
            field_int,
            field_repeated_bytes,
            to_signed64,
        )

        f = decode_message(data)
        return cls(
            total=to_signed64(field_int(f, 1)),
            index=to_signed64(field_int(f, 2)),
            leaf_hash_=field_bytes(f, 3),
            aunts=field_repeated_bytes(f, 4),
        )

    def __eq__(self, other) -> bool:
        return (
            isinstance(other, Proof)
            and self.total == other.total
            and self.index == other.index
            and self.leaf_hash == other.leaf_hash
            and self.aunts == other.aunts
        )


def _compute_hash_from_aunts(
    index: int, total: int, leaf: bytes, aunts: List[bytes]
) -> Optional[bytes]:
    """proof.go:137-168."""
    if index >= total or index < 0 or total <= 0:
        return None
    if total == 1:
        if aunts:
            return None
        return leaf
    if not aunts:
        return None
    k = split_point(total)
    if index < k:
        left = _compute_hash_from_aunts(index, k, leaf, aunts[:-1])
        if left is None:
            return None
        return inner_hash(left, aunts[-1])
    right = _compute_hash_from_aunts(index - k, total - k, leaf, aunts[:-1])
    if right is None:
        return None
    return inner_hash(aunts[-1], right)


def proofs_from_byte_slices(items: Sequence[bytes]) -> Tuple[bytes, List[Proof]]:
    """Root hash + one proof per item (proof.go:87-103)."""
    trails, root = _trails_from_byte_slices(list(items))
    root_hash = root.hash
    proofs = []
    for i, trail in enumerate(trails):
        proofs.append(Proof(len(items), i, trail.hash, trail.flatten_aunts()))
    return root_hash, proofs


class _ProofNode:
    __slots__ = ("hash", "parent", "left", "right")

    def __init__(self, h: bytes):
        self.hash = h
        self.parent = None
        self.left = None  # left sibling (aunt) node
        self.right = None  # right sibling (aunt) node

    def flatten_aunts(self) -> List[bytes]:
        aunts: List[bytes] = []
        node = self
        while node is not None:
            if node.left is not None:
                aunts.append(node.left.hash)
            elif node.right is not None:
                aunts.append(node.right.hash)
            node = node.parent
        return aunts


def _trails_from_byte_slices(items: List[bytes]) -> Tuple[List[_ProofNode], _ProofNode]:
    n = len(items)
    if n == 0:
        return [], _ProofNode(_sha256(b""))
    if n == 1:
        trail = _ProofNode(leaf_hash(items[0]))
        return [trail], trail
    k = split_point(n)
    lefts, left_root = _trails_from_byte_slices(items[:k])
    rights, right_root = _trails_from_byte_slices(items[k:])
    root = _ProofNode(inner_hash(left_root.hash, right_root.hash))
    left_root.parent = root
    left_root.right = right_root
    right_root.parent = root
    right_root.left = left_root
    return lefts + rights, root
